"""Per-executable XLA cost/memory accounting + the device peak table
(ISSUE 15, round 19).

Two jobs, both grounded in what the COMPILER says rather than what we
typed by hand:

1. **Device peak table** — every roofline in ``bench.py`` used to
   divide by hard-coded ``197e12`` / ``819e9`` (TPU v5e bf16 FLOP/s and
   HBM B/s) no matter what hardware actually ran, so MFU/HBM fractions
   silently lied on anything that wasn't a v5e.  :func:`device_peaks`
   resolves the live backend's ``device_kind`` against
   :data:`PEAK_TABLE` (public spec-sheet numbers, provenance in the
   table) and falls back to the **documented nominal v5e entry** on CPU
   and unknown kinds — flagged ``nominal=True`` so consumers (and the
   bench summary) can tell a real ceiling from a reference one.  Lint
   rule JX017 keeps new hand-typed peaks out of roofline/bench paths;
   this module is the one sanctioned home for the literals.

2. **Cost/memory harvest** — :func:`harvest_compiled` pulls
   ``compiled.cost_analysis()`` (flops, bytes accessed) and
   ``compiled.memory_analysis()`` (argument/output/temp HBM) off an XLA
   executable; :func:`analyze_jitted` does the AOT
   ``lower(...).compile()`` dance for a jitted callable.  Availability
   is per-backend and per-version: every probe is guarded, failures are
   COUNTED (``costs.unavailable{what=...}``), never raised, and the row
   says what it could and couldn't get.  Rows land in :data:`_ROWS`
   (scrapeable via gauges ``xla.flops{executable=}`` /
   ``xla.bytes_accessed{executable=}`` / ``xla.peak_bytes{executable=}``)
   and ``bench.py`` appends them to the perfwatch history store, so a
   compile that doubles HBM traffic fails the history gate even when
   wall-clock noise hides it.

:func:`memory_watermarks` additionally samples
``device.memory_stats()`` into ``hbm.peak_bytes{device=}`` /
``hbm.bytes_in_use{device=}`` gauges (TPU backends report them; CPU
returns None — counted, skipped).

Hot-path rule (PR 9): nothing here runs per step.  Harvest happens at
bind/bench time (AOT lowering executes nothing and syncs nothing);
watermark sampling reads host-side allocator stats.  The module
imports neither jax nor numpy at module scope — jax is lazy so
import-light obs consumers stay import-light.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

from cup3d_tpu.obs import metrics as _metrics


@dataclass(frozen=True)
class DevicePeaks:
    """One device kind's advertised ceilings (the roofline denominators).

    ``nominal`` marks a reference entry (CPU / unknown kinds): the
    numbers are the documented v5e ceilings so trend lines stay
    comparable across backends, NOT a claim about the local machine.
    """

    kind: str
    bf16_flops: float        # dense bf16 peak, FLOP/s per chip
    hbm_bytes_per_s: float   # HBM bandwidth, B/s per chip
    nominal: bool = False
    note: str = ""

    def as_dict(self) -> dict:
        return {"kind": self.kind, "bf16_flops": self.bf16_flops,
                "hbm_bytes_per_s": self.hbm_bytes_per_s,
                "nominal": self.nominal, "note": self.note}


#: public spec-sheet peaks per ``device_kind`` substring (cloud.google
#: .com/tpu/docs/system-architecture-tpu-vm, v4/v5e/v5p/v6e pages).
#: Matching is normalized-substring (``"TPU v5 lite"`` -> v5e): order
#: matters, most specific first.
PEAK_TABLE = (
    DevicePeaks("TPU v6e", 918e12, 1640e9,
                note="Trillium: 918 TFLOP/s bf16, 1640 GB/s HBM"),
    DevicePeaks("TPU v5p", 459e12, 2765e9,
                note="459 TFLOP/s bf16, 2765 GB/s HBM"),
    DevicePeaks("TPU v5e", 197e12, 819e9,
                note="v5 lite: 197 TFLOP/s bf16, 819 GB/s HBM"),
    DevicePeaks("TPU v4", 275e12, 1228e9,
                note="275 TFLOP/s bf16, 1228 GB/s HBM"),
)

#: the documented fallback: rooflines on CPU (and unknown kinds) are
#: reported against the v5e ceilings so the history trajectory stays
#: one series, with ``nominal=True`` recording that the ceiling is a
#: reference, not the local hardware.
NOMINAL_FALLBACK = DevicePeaks(
    "nominal-v5e", 197e12, 819e9, nominal=True,
    note="reference ceiling (v5e numbers): backend has no entry in "
         "PEAK_TABLE — MFU/HBM fractions are vs this documented "
         "reference, not the local machine",
)

_KIND_ALIASES = {
    "tpu v5 lite": "TPU v5e",
    "tpu v5litepod": "TPU v5e",
    "tpu v6 lite": "TPU v6e",
}


def peaks_for_kind(kind: str) -> DevicePeaks:
    """Resolve a ``device_kind`` string against :data:`PEAK_TABLE`
    (normalized substring match, v5-lite aliases folded in); unknown
    kinds get :data:`NOMINAL_FALLBACK`."""
    norm = str(kind).strip().lower()
    norm = _KIND_ALIASES.get(norm, norm).lower()
    for peaks in PEAK_TABLE:
        if peaks.kind.lower() in norm or norm in peaks.kind.lower():
            return peaks
    return NOMINAL_FALLBACK


def device_peaks(device=None) -> DevicePeaks:
    """The live backend's peaks (``jax.devices()[0]`` unless a device
    is passed).  Never raises: a jax-less / backend-less environment is
    counted and returns the nominal fallback."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        return peaks_for_kind(device.device_kind)
    except Exception:
        _metrics.counter("costs.unavailable", what="device_kind").inc()
        return NOMINAL_FALLBACK


# -- per-executable harvest --------------------------------------------------

#: name -> harvested row; append-only per process (re-harvest of the
#: same name overwrites — the newest compile wins)
_ROWS: Dict[str, dict] = {}


def _cost_analysis(compiled) -> Optional[dict]:
    """``compiled.cost_analysis()`` normalized to one flat dict (older
    jax returns a one-element list); None when the backend can't."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        _metrics.counter("costs.unavailable", what="cost_analysis").inc()
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        _metrics.counter("costs.unavailable", what="cost_analysis").inc()
        return None
    return ca


def _memory_analysis(compiled) -> Optional[object]:
    try:
        return compiled.memory_analysis()
    except Exception:
        _metrics.counter("costs.unavailable",
                         what="memory_analysis").inc()
        return None


def harvest_compiled(name: str, compiled) -> dict:
    """Harvest one XLA executable's compiler-counted cost/memory row.

    Always returns a row; the ``available`` sub-dict says which halves
    the backend actually produced.  ``peak_bytes`` is the static HBM
    footprint bound argument+output+temp (XLA's CompiledMemoryStats);
    live allocator watermarks come from :func:`memory_watermarks`."""
    row = {"name": str(name), "flops": None, "bytes_accessed": None,
           "argument_bytes": None, "output_bytes": None,
           "temp_bytes": None, "alias_bytes": None,
           "generated_code_bytes": None, "peak_bytes": None,
           "available": {"cost": False, "memory": False}}
    ca = _cost_analysis(compiled)
    if ca is not None:
        row["available"]["cost"] = True
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        row["flops"] = float(flops) if flops is not None else None
        row["bytes_accessed"] = (
            float(nbytes) if nbytes is not None else None)
    ma = _memory_analysis(compiled)
    if ma is not None:
        try:
            arg = int(ma.argument_size_in_bytes)
            out = int(ma.output_size_in_bytes)
            tmp = int(ma.temp_size_in_bytes)
            row.update(
                argument_bytes=arg, output_bytes=out, temp_bytes=tmp,
                alias_bytes=int(ma.alias_size_in_bytes),
                generated_code_bytes=int(ma.generated_code_size_in_bytes),
                peak_bytes=arg + out + tmp,
            )
            row["available"]["memory"] = True
        except Exception:
            _metrics.counter("costs.unavailable",
                             what="memory_analysis").inc()
    _ROWS[row["name"]] = row
    if row["flops"] is not None:
        _metrics.gauge("xla.flops", executable=name).set(row["flops"])
    if row["bytes_accessed"] is not None:
        _metrics.gauge("xla.bytes_accessed",
                       executable=name).set(row["bytes_accessed"])
    if row["peak_bytes"] is not None:
        _metrics.gauge("xla.peak_bytes",
                       executable=name).set(float(row["peak_bytes"]))
    _metrics.counter("costs.harvests").inc()
    return row


def analyze_jitted(name: str, jitted, *args, **kwargs) -> Optional[dict]:
    """AOT-lower and compile ``jitted`` on ``args`` and harvest the
    executable's cost row.  Off the hot path by design: lowering
    executes nothing (no device sync, no donation — safe on functions
    with ``donate_argnums``), compiling costs one compile.  Returns
    None (counted) when the backend can't lower/compile here.

    Round 21 fix: a store-backed executable (aot/store.py) already
    holds — or knows how to load — its compiled object; harvesting
    through ``ensure_compiled`` reuses it instead of paying a
    duplicate lower+compile of a twin."""
    ensure = getattr(jitted, "ensure_compiled", None)
    if ensure is not None:
        try:
            compiled = ensure(*args, **kwargs)
        except Exception:
            compiled = None
        if compiled is not None:
            return harvest_compiled(name, compiled)
        # fallback state: harvest the plain jitted twin below
        jitted = getattr(jitted, "jitted", jitted)
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        _metrics.counter("costs.unavailable", what="lower").inc()
        return None
    return harvest_compiled(name, compiled)


def rows() -> Dict[str, dict]:
    """Every harvested row this process, by executable name (copies)."""
    return {k: dict(v, available=dict(v["available"]))
            for k, v in _ROWS.items()}


def enabled() -> bool:
    """``CUP3D_COSTS=1`` arms the bind-point harvest in
    ``parallel/forest.py`` (one extra AOT compile per bound
    executable); bench/tests call :func:`analyze_jitted` explicitly."""
    return os.environ.get("CUP3D_COSTS", "0") not in ("0", "")


def harvest_on_first_call(jitted, name: str):
    """Wrap a jitted callable so its FIRST invocation also harvests the
    cost row (AOT lower+compile on the live operands, then the normal
    call).  Used by the forest/fleet bind points when
    :func:`enabled`; the steady-state path after the first call is the
    raw jitted function (the wrapper uninstalls itself logically via a
    flag — one bool test per call, no device work ever).

    The harvest runs BEFORE the wrapped call (lowering never donates,
    so the operands are still live); for a store-backed executable
    :func:`analyze_jitted` routes through its already-materialized
    compiled object, so the first call pays zero extra compiles."""
    state = {"done": False}

    def wrapper(*args, **kwargs):
        if not state["done"]:
            state["done"] = True
            analyze_jitted(name, jitted, *args, **kwargs)
        return jitted(*args, **kwargs)

    wrapper.__name__ = getattr(jitted, "__name__", name)
    wrapper.__wrapped__ = jitted
    wrapper.lower = getattr(jitted, "lower", None)
    return wrapper


# -- live allocator watermarks ----------------------------------------------

def memory_watermarks() -> Dict[str, dict]:
    """Sample ``device.memory_stats()`` on every local device into
    ``hbm.peak_bytes{device=}`` / ``hbm.bytes_in_use{device=}`` gauges.
    TPU/GPU backends report allocator stats; CPU returns None — both
    counted, never raised.  Returns {device_label: stats_subset}."""
    out: Dict[str, dict] = {}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        _metrics.counter("costs.unavailable", what="devices").inc()
        return out
    for d in devices:
        label = f"{d.platform}:{d.id}"
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            _metrics.counter("costs.unavailable",
                             what="memory_stats").inc()
            continue
        sub = {}
        peak = stats.get("peak_bytes_in_use")
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if peak is not None:
            sub["peak_bytes_in_use"] = int(peak)
            _metrics.gauge("hbm.peak_bytes", device=label).set(float(peak))
        if in_use is not None:
            sub["bytes_in_use"] = int(in_use)
            _metrics.gauge("hbm.bytes_in_use",
                           device=label).set(float(in_use))
        if limit is not None:
            sub["bytes_limit"] = int(limit)
        if sub:
            out[label] = sub
    return out
