"""Zero-dependency HTTP exporter: ``/metrics`` (Prometheus text) +
``/health`` (JSON) — the scrape surface ROADMAP item 1's fleet server
presupposes (ISSUE 9).

Off by default.  ``CUP3D_METRICS_PORT=<port>`` (or an explicit
:func:`ensure_exporter` call) starts one background
``ThreadingHTTPServer`` daemon thread per process; the step loop is
never touched — a scrape renders a registry :func:`snapshot` on the
server thread, and the registry's own lock is the only shared state.

``/metrics`` renders the flat ``obs/metrics.py`` snapshot keys
(``name{k=v,...}[.suffix]``) into Prometheus exposition format 0.0.4:
``cup3d_`` prefix, dots -> underscores, labels quoted/escaped, one
``# TYPE`` line per family.  Round 16: registered histograms render as
REAL histogram families — ``# TYPE ... histogram`` with cumulative
``_bucket{le="..."}`` lines (the pinned ``obs.metrics.BUCKET_BOUNDS``
ladder + ``+Inf``), ``_sum`` and ``_count`` — so ``histogram_quantile``
works on a scrape; everything else stays untyped.  The legacy flat
``.count``/``.sum`` suffix keys remain in ``snapshot()`` for existing
consumers but are excluded from the text rendering for histogram
families (they would collide with the conformant ``_count``/``_sum``).
:func:`parse_prometheus_text` is the matching parser and
:func:`parse_histograms` regroups the bucket series — the round-trip
is a tested contract, not a formatting accident.

``/health`` reports what a supervisor needs before scraping history:
per-flight-recorder arm state + last-known-good step (the weakref
registry in ``obs/flight.py``), recovery/flight counters, trace sink
and capture-window state.  Live fleet servers report through their
own ``health()`` — round 17 adds the ``"admission"`` block (queue
depth, threshold, backpressure flag, tenant quota: the supervisor's
shed-load signal) and the ``"scheduler"`` block (continuous flag,
policy, reseed count, last window's lane occupancy).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from cup3d_tpu.obs import flight as _flight
from cup3d_tpu.obs import metrics as _metrics
from cup3d_tpu.obs import trace as _trace

_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)


# -- Prometheus text rendering ----------------------------------------------


def prometheus_key(flat: str) -> Tuple[str, Dict[str, str]]:
    """One flat snapshot key -> (metric name, labels).

    ``poisson.iters_hist{driver=amr}.count`` ->
    (``cup3d_poisson_iters_hist_count``, {"driver": "amr"}).
    """
    labels: Dict[str, str] = {}
    base = flat
    if "{" in flat:
        head, rest = flat.split("{", 1)
        inner, _, tail = rest.partition("}")
        labels = dict(p.split("=", 1) for p in inner.split(",") if "=" in p)
        base = head + tail
    name = "cup3d_" + _NAME_SANITIZE_RE.sub("_", base.strip("."))
    return name, labels


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape_label(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _fmt_value(v: float) -> str:
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_le(le: float) -> str:
    """A bucket bound as an ``le`` label value (``+Inf`` for overflow;
    ``repr`` otherwise so ``float()`` round-trips exactly)."""
    return "+Inf" if math.isinf(le) else repr(le)


def render_prometheus(snap: Optional[Dict[str, float]] = None,
                      histograms=None) -> str:
    """The registry snapshot as Prometheus exposition text 0.0.4.

    With no arguments (the live scrape path) registered histograms are
    rendered as conformant histogram families (``_bucket``/``_sum``/
    ``_count``) and their legacy flat ``.count``/``.sum`` keys dropped
    from the untyped section.  An explicit ``snap`` without
    ``histograms`` renders the old untyped-only text (back-compat for
    callers formatting an arbitrary flat dict)."""
    if snap is None:
        snap = _metrics.snapshot()
        if histograms is None:
            histograms = _metrics.histograms()
    histograms = list(histograms or ())
    lines = []
    skip = set()
    # histogram families first: group per prometheus base name so each
    # family gets exactly one TYPE line across all label sets
    hist_fams: Dict[str, list] = {}
    for h in histograms:
        name, labels = prometheus_key(h.flat)
        hist_fams.setdefault(name, []).append((labels, h))
        # the conformant _count/_sum replace the legacy suffix gauges
        # (identical sanitized names would otherwise collide); min/max/
        # last keep rendering untyped below — no conformant equivalent
        skip.add(f"{h.flat}.count")
        skip.add(f"{h.flat}.sum")
    for name in sorted(hist_fams):
        lines.append(f"# TYPE {name} histogram")
        for labels, h in hist_fams[name]:
            for le, cum in h.cumulative_buckets():
                blabels = dict(labels)
                blabels["le"] = _fmt_le(le)
                lines.append(f"{name}_bucket{_label_str(blabels)} {cum}")
            lstr = _label_str(labels)
            lines.append(f"{name}_sum{lstr} {_fmt_value(h.sum)}")
            lines.append(f"{name}_count{lstr} {h.count}")
    families: Dict[str, list] = {}
    for flat in sorted(snap):
        if flat in skip:
            continue
        name, labels = prometheus_key(flat)
        families.setdefault(name, []).append((labels, snap[flat]))
    for name, series in families.items():
        lines.append(f"# TYPE {name} untyped")
        for labels, val in series:
            lines.append(f"{name}{_label_str(labels)} {_fmt_value(val)}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[Tuple[str, frozenset], float]:
    """Exposition text -> {(name, frozenset(label items)): value}.
    Raises ValueError on a malformed sample line (the round-trip test's
    teeth); comment/blank lines are skipped per the format."""
    out: Dict[Tuple[str, frozenset], float] = {}
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: not a prometheus sample: {line!r}")
        name, inner, val = m.group(1), m.group(2), m.group(3)
        labels = frozenset(
            (k, _unescape_label(v))
            for k, v in _LABEL_RE.findall(inner or "")
        )
        out[(name, labels)] = float(val)
    return out


def parse_histograms(text: str) -> Dict[Tuple[str, frozenset], dict]:
    """Regroup an exposition's histogram series: ``{(family_name,
    frozenset(labels-without-le)): {"buckets": [(le, cum), ...
    ascending, +Inf last], "sum": float, "count": float}}``.

    The inverse of the histogram half of :func:`render_prometheus`
    (family name still carries the ``cup3d_`` prefix).  Families appear
    only once a ``_bucket`` line is seen; buckets are checked monotone
    non-decreasing in cumulative count (ValueError otherwise — a
    non-cumulative rendering is a bug, not a dialect)."""
    samples = parse_prometheus_text(text)
    fams: Dict[Tuple[str, frozenset], dict] = {}

    def fam(name: str, labels: frozenset) -> dict:
        return fams.setdefault((name, labels),
                               {"buckets": [], "sum": None, "count": None})

    for (name, labels), val in samples.items():
        if name.endswith("_bucket"):
            ldict = dict(labels)
            le = ldict.pop("le", None)
            if le is None:
                continue  # a _bucket-suffixed untyped metric, not ours
            fam(name[:-len("_bucket")],
                frozenset(ldict.items()))["buckets"].append(
                    (float(le), val))
    for (name, labels), val in samples.items():
        for suffix, field in (("_sum", "sum"), ("_count", "count")):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and (base, labels) in fams:
                fams[(base, labels)][field] = val
    for (name, labels), rec in fams.items():
        rec["buckets"].sort(key=lambda b: b[0])
        cums = [c for _, c in rec["buckets"]]
        if cums != sorted(cums):
            raise ValueError(
                f"histogram {name}{dict(labels)}: non-cumulative buckets")
    return fams


# -- /health ----------------------------------------------------------------


def health_payload() -> dict:
    """Supervisor view: flight-recorder arm state + last-known-good
    step per live recorder, recovery counters, trace/profile state."""
    from cup3d_tpu.obs import profile as _profile

    snap = _metrics.snapshot()
    flights = []
    for fr in _flight.live_recorders():
        flights.append({
            "directory": fr.directory,
            "armed": fr.armed,
            "last_known_good_step": fr.last_known_good_step,
            "steps_recorded": len(fr.steps),
            "dumps_written": list(fr.dumps_written),
            "recovery_events": len(fr.recovery_events),
            "job_events": len(fr.job_events),
        })
    counters = {k: v for k, v in snap.items()
                if k.startswith(("flight.", "resilience.", "recovery.",
                                 "fleet.", "aot.", "journal."))}
    # live fleet servers (weakref registry, same pattern as the flight
    # recorders); the lazy import keeps obs importable standalone
    from cup3d_tpu.fleet.server import live_servers as _fleet_live

    fleet = [srv.health() for srv in _fleet_live()]
    from cup3d_tpu.obs import federate as _federate

    # round 21: persistent AOT executable store state (None when
    # CUP3D_AOT_STORE is unset; the lazy import keeps obs import-light)
    from cup3d_tpu.aot import store as _aot_store

    aot_st = _aot_store.active_store()

    return {
        "status": "ok",
        "time": _trace.wall(),
        "flight_recorders": flights,
        "recovery_counters": counters,
        "fleet": fleet,
        "aot": {"store": aot_st.state() if aot_st is not None else None},
        "trace": {"enabled": _trace.TRACE.enabled,
                  "steps_recorded": _trace.TRACE.steps_recorded,
                  "steps_dropped": _trace.TRACE.steps_dropped},
        "profile": {"windows": _profile.CONTROLLER.windows,
                    "capturing": _profile.CONTROLLER.capturing},
        "federation": _federate.FED.state(),
        "stragglers": _federate.STRAGGLER.health(),
    }


# -- the server --------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/health":
                body = json.dumps(health_payload()).encode()
                ctype = "application/json"
            elif path == "/federate":
                # this process's registry snapshot, JSON — what a
                # federation coordinator scrapes off every peer
                from cup3d_tpu.obs import federate as _federate

                body = json.dumps(_federate.FED.local_payload()).encode()
                ctype = "application/json"
            elif path == "/metrics/federated":
                # the coordinator's merged view: counters summed,
                # gauges/histograms per process labeled process=i
                from cup3d_tpu.obs import federate as _federate

                body = _federate.FED.view().render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/health/federated":
                from cup3d_tpu.obs import federate as _federate

                body = json.dumps(_federate.FED.view().health()).encode()
                ctype = "application/json"
            else:
                self.send_error(
                    404, "try /metrics[,/federated], /health[,/federated]"
                    " or /federate")
                return
        except Exception:
            _metrics.counter("export.errors").inc()
            self.send_error(500, "exporter render failed")
            return
        _metrics.counter("export.scrapes", path=path.strip("/")).inc()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsExporter:
    """One background daemon HTTP server; ``port=0`` binds an ephemeral
    port (tests).  ``start()`` returns self; ``stop()`` is idempotent."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="cup3d-metrics",
            daemon=True,
        )
        self._thread.start()
        _metrics.gauge("export.port").set(float(self.port))
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


#: the process singleton (env-gated); drivers call ensure_exporter() at
#: construction — a no-op unless CUP3D_METRICS_PORT is set.
EXPORTER: Optional[MetricsExporter] = None


def ensure_exporter(port: Optional[int] = None) -> Optional[MetricsExporter]:
    """Start (once) the process exporter.  ``port=None`` reads
    ``CUP3D_METRICS_PORT``; unset/empty/0 means off.  Failure to bind is
    counted, not raised — telemetry must never kill a run."""
    global EXPORTER
    if EXPORTER is not None:
        return EXPORTER
    if port is None:
        spec = os.environ.get("CUP3D_METRICS_PORT", "")
        if not spec or spec == "0":
            return None
        try:
            port = int(spec)
        except ValueError:
            _metrics.counter("export.bad_port").inc()
            return None
    try:
        EXPORTER = MetricsExporter(port=port).start()
    except Exception:
        _metrics.counter("export.bind_errors").inc()
        return None
    import atexit

    atexit.register(EXPORTER.stop)
    return EXPORTER
