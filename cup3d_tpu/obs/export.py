"""Zero-dependency HTTP exporter: ``/metrics`` (Prometheus text) +
``/health`` (JSON) — the scrape surface ROADMAP item 1's fleet server
presupposes (ISSUE 9).

Off by default.  ``CUP3D_METRICS_PORT=<port>`` (or an explicit
:func:`ensure_exporter` call) starts one background
``ThreadingHTTPServer`` daemon thread per process; the step loop is
never touched — a scrape renders a registry :func:`snapshot` on the
server thread, and the registry's own lock is the only shared state.

``/metrics`` renders the flat ``obs/metrics.py`` snapshot keys
(``name{k=v,...}[.suffix]``) into Prometheus exposition format 0.0.4:
``cup3d_`` prefix, dots -> underscores, labels quoted/escaped, one
``# TYPE`` line per family (untyped: the flat snapshot does not carry
metric kinds).  :func:`parse_prometheus_text` is the matching parser —
the round-trip is a tested contract, not a formatting accident.

``/health`` reports what a supervisor needs before scraping history:
per-flight-recorder arm state + last-known-good step (the weakref
registry in ``obs/flight.py``), recovery/flight counters, trace sink
and capture-window state.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from cup3d_tpu.obs import flight as _flight
from cup3d_tpu.obs import metrics as _metrics
from cup3d_tpu.obs import trace as _trace

_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)


# -- Prometheus text rendering ----------------------------------------------


def prometheus_key(flat: str) -> Tuple[str, Dict[str, str]]:
    """One flat snapshot key -> (metric name, labels).

    ``poisson.iters_hist{driver=amr}.count`` ->
    (``cup3d_poisson_iters_hist_count``, {"driver": "amr"}).
    """
    labels: Dict[str, str] = {}
    base = flat
    if "{" in flat:
        head, rest = flat.split("{", 1)
        inner, _, tail = rest.partition("}")
        labels = dict(p.split("=", 1) for p in inner.split(",") if "=" in p)
        base = head + tail
    name = "cup3d_" + _NAME_SANITIZE_RE.sub("_", base.strip("."))
    return name, labels


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape_label(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _fmt_value(v: float) -> str:
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def render_prometheus(snap: Optional[Dict[str, float]] = None) -> str:
    """The registry snapshot as Prometheus exposition text 0.0.4."""
    snap = _metrics.snapshot() if snap is None else snap
    families: Dict[str, list] = {}
    for flat in sorted(snap):
        name, labels = prometheus_key(flat)
        families.setdefault(name, []).append((labels, snap[flat]))
    lines = []
    for name, series in families.items():
        lines.append(f"# TYPE {name} untyped")
        for labels, val in series:
            lstr = ""
            if labels:
                inner = ",".join(
                    f'{k}="{_escape_label(str(v))}"'
                    for k, v in sorted(labels.items())
                )
                lstr = "{" + inner + "}"
            lines.append(f"{name}{lstr} {_fmt_value(val)}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[Tuple[str, frozenset], float]:
    """Exposition text -> {(name, frozenset(label items)): value}.
    Raises ValueError on a malformed sample line (the round-trip test's
    teeth); comment/blank lines are skipped per the format."""
    out: Dict[Tuple[str, frozenset], float] = {}
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: not a prometheus sample: {line!r}")
        name, inner, val = m.group(1), m.group(2), m.group(3)
        labels = frozenset(
            (k, _unescape_label(v))
            for k, v in _LABEL_RE.findall(inner or "")
        )
        out[(name, labels)] = float(val)
    return out


# -- /health ----------------------------------------------------------------


def health_payload() -> dict:
    """Supervisor view: flight-recorder arm state + last-known-good
    step per live recorder, recovery counters, trace/profile state."""
    from cup3d_tpu.obs import profile as _profile

    snap = _metrics.snapshot()
    flights = []
    for fr in _flight.live_recorders():
        flights.append({
            "directory": fr.directory,
            "armed": fr.armed,
            "last_known_good_step": fr.last_known_good_step,
            "steps_recorded": len(fr.steps),
            "dumps_written": list(fr.dumps_written),
            "recovery_events": len(fr.recovery_events),
        })
    counters = {k: v for k, v in snap.items()
                if k.startswith(("flight.", "resilience.", "recovery.",
                                 "fleet."))}
    # live fleet servers (weakref registry, same pattern as the flight
    # recorders); the lazy import keeps obs importable standalone
    from cup3d_tpu.fleet.server import live_servers as _fleet_live

    fleet = [srv.health() for srv in _fleet_live()]
    return {
        "status": "ok",
        "time": time.time(),
        "flight_recorders": flights,
        "recovery_counters": counters,
        "fleet": fleet,
        "trace": {"enabled": _trace.TRACE.enabled,
                  "steps_recorded": _trace.TRACE.steps_recorded,
                  "steps_dropped": _trace.TRACE.steps_dropped},
        "profile": {"windows": _profile.CONTROLLER.windows,
                    "capturing": _profile.CONTROLLER.capturing},
    }


# -- the server --------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/health":
                body = json.dumps(health_payload()).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "try /metrics or /health")
                return
        except Exception:
            _metrics.counter("export.errors").inc()
            self.send_error(500, "exporter render failed")
            return
        _metrics.counter("export.scrapes", path=path.strip("/")).inc()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsExporter:
    """One background daemon HTTP server; ``port=0`` binds an ephemeral
    port (tests).  ``start()`` returns self; ``stop()`` is idempotent."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="cup3d-metrics",
            daemon=True,
        )
        self._thread.start()
        _metrics.gauge("export.port").set(float(self.port))
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


#: the process singleton (env-gated); drivers call ensure_exporter() at
#: construction — a no-op unless CUP3D_METRICS_PORT is set.
EXPORTER: Optional[MetricsExporter] = None


def ensure_exporter(port: Optional[int] = None) -> Optional[MetricsExporter]:
    """Start (once) the process exporter.  ``port=None`` reads
    ``CUP3D_METRICS_PORT``; unset/empty/0 means off.  Failure to bind is
    counted, not raised — telemetry must never kill a run."""
    global EXPORTER
    if EXPORTER is not None:
        return EXPORTER
    if port is None:
        spec = os.environ.get("CUP3D_METRICS_PORT", "")
        if not spec or spec == "0":
            return None
        try:
            port = int(spec)
        except ValueError:
            _metrics.counter("export.bad_port").inc()
            return None
    try:
        EXPORTER = MetricsExporter(port=port).start()
    except Exception:
        _metrics.counter("export.bind_errors").inc()
        return None
    import atexit

    atexit.register(EXPORTER.stop)
    return EXPORTER
