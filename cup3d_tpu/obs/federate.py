"""Cross-process metric federation + mesh straggler watch (ISSUE 15,
round 19).

PR 14 made the system multi-process; the observability stack was still
per-process — on a pod the operator saw 1/N of the fleet and no
cross-shard skew signal.  This module is the missing layer, in two
halves:

**Federation.**  Each process serializes its registry
(:func:`local_snapshot`) at K-boundaries (``Federation.on_k_boundary``,
called from the megaloop/fleet dispatch seams when armed) and on
scrape (the ``/federate`` JSON endpoint obs/export.py serves).  The
coordinator (process 0) collects every process's snapshot —
in-process providers first (the socket-free single-host path tests
use), then HTTP peers listed in ``CUP3D_FEDERATE`` (it scrapes each
peer exporter's ``/federate``) — and merges them
(:func:`merge_snapshots`):

- **counters** sum across processes (process-wide totals become
  fleet-wide totals);
- **gauges** keep per-process identity, re-labeled ``process=i`` (a
  queue depth is not summable);
- **histograms** are revived bucket-wise per process, so
  ``metrics.merged_quantile`` over the group is EXACTLY the quantile a
  single fleet-wide registry would have produced (same bucket counts,
  min-of-mins, max-of-maxes) — the federated p99 is exact by
  construction, and the test asserts equality, not approximation.

The merged view renders through the existing Prometheus exposition
(``/metrics/federated``: per-process histogram/gauge families labeled
``process=i``, counters summed) and a federated ``/health`` with
per-process sub-blocks and the coordinator's ``mesh_state()``.

**Straggler watch.**  :class:`StragglerWatch` records per-shard
K-boundary wall-time gauges (``fleet.shard_last_k_wall_s{shard=}``),
computes the skew ratio slowest/median (``fleet.shard_skew_ratio``),
bumps ``fleet.stragglers{shard=}`` when a shard exceeds
``CUP3D_STRAGGLER_RATIO`` x median (default 2.0), emits
``kind="shard"`` aux records + pid-4 Perfetto spans when a trace sink
is armed, and exposes :meth:`StragglerWatch.warnings` as the
early-warning signal ``resilience/elastic.py`` surfaces before a shard
is actually lost.  All timestamps come from :func:`obs.trace.now` —
the one sanctioned monotonic clock (JX008/JX014).

Hot-path rule (PR 9): everything here is host dict/list arithmetic on
scalars the callers already had.  No jax import at module scope, no
device reads anywhere; the armed-idle path is transfer-guard clean and
trace-free (tested with RecompileCounter budget 1).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from statistics import median
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cup3d_tpu.obs import metrics as _metrics
from cup3d_tpu.obs import trace as _trace

SNAPSHOT_SCHEMA = 1

#: default alert threshold: a shard whose last-K wall exceeds this
#: multiple of the median shard wall is flagged a straggler
DEFAULT_STRAGGLER_RATIO = 2.0


def straggler_ratio() -> float:
    """``CUP3D_STRAGGLER_RATIO`` (>1.0) or the default."""
    raw = os.environ.get("CUP3D_STRAGGLER_RATIO", "").strip()
    if not raw:
        return DEFAULT_STRAGGLER_RATIO
    try:
        r = float(raw)
    except ValueError:
        _metrics.counter("federate.bad_knob",
                         knob="CUP3D_STRAGGLER_RATIO").inc()
        return DEFAULT_STRAGGLER_RATIO
    if r > 1.0:
        return r
    _metrics.counter("federate.bad_knob",
                     knob="CUP3D_STRAGGLER_RATIO").inc()
    return DEFAULT_STRAGGLER_RATIO


def _dist_probe() -> dict:
    """``parallel.topology.dist_state()`` when importable (it pulls in
    jax); a rank-0 single-process stub otherwise — federation must work
    in import-light/obs-only contexts."""
    try:
        from cup3d_tpu.parallel import topology as topo

        return topo.dist_state()
    except Exception:
        _metrics.counter("federate.dist_probe_errors").inc()
        return {"mode": "off", "initialized": False, "error": None,
                "processes": 1, "rank": 0}


def mesh_summary() -> dict:
    """JSON-able mesh picture for federated /health and flight
    postmortems: the distributed-init state plus every live fleet
    server's ``mesh_state()``.  Best-effort: probes are guarded and
    counted, a dead subsystem yields an empty block, never a raise."""
    out: dict = {"dist": _dist_probe(), "fleet_meshes": []}
    try:
        from cup3d_tpu.fleet.server import live_servers
        from cup3d_tpu.parallel import topology as topo

        for srv in live_servers():
            out["fleet_meshes"].append(topo.mesh_state(srv.mesh))
    except Exception:
        _metrics.counter("federate.mesh_probe_errors").inc()
    return out


# -- snapshot / revive -------------------------------------------------------

def serialize_histogram(h: _metrics.Histogram) -> dict:
    """One histogram's full merge state: bucket counts + count/sum/
    min/max/last.  JSON round-trips ints and IEEE doubles exactly, so
    reviving on the coordinator loses nothing."""
    return {"name": h.name, "labels": {k: str(v)
                                       for k, v in h.labels.items()},
            "count": int(h.count), "sum": float(h.sum),
            "min": h.min, "max": h.max, "last": h.last,
            "bucket_counts": list(h.bucket_counts)}


def revive_histogram(d: dict,
                     extra_labels: Optional[dict] = None
                     ) -> _metrics.Histogram:
    """Rebuild an (unregistered) Histogram from its serialized state,
    optionally with extra labels (the coordinator adds ``process=i``).
    The revived object is merge-equivalent to the original: same
    buckets, count, sum, min, max."""
    labels = dict(d.get("labels") or {})
    if extra_labels:
        labels.update(extra_labels)
    h = _metrics.Histogram(str(d["name"]), labels)
    h.count = int(d["count"])
    h.sum = float(d["sum"])
    h.min = d.get("min")
    h.max = d.get("max")
    h.last = d.get("last")
    counts = list(d.get("bucket_counts") or [])
    if len(counts) == len(h.bucket_counts):
        h.bucket_counts = [int(c) for c in counts]
    else:
        _metrics.counter("federate.bucket_mismatch").inc()
    return h


def local_snapshot(registry: Optional[_metrics.MetricsRegistry] = None,
                   process: Optional[int] = None) -> dict:
    """This process's registry, serialized for federation.

    Structured per kind (counters/gauges/histograms) so the
    coordinator can apply per-kind merge semantics; collector output
    (stream stats etc., flat-only, counter-like) rides in ``extras``
    and merges by summing.  ``process`` defaults to the distributed
    rank (0 single-process)."""
    reg = registry if registry is not None else _metrics.REGISTRY
    dist = _dist_probe()
    if process is None:
        process = int(dist.get("rank") or 0)
    counters, gauges, hists = [], [], []
    structured_keys = set()
    for m in reg.metrics():
        if isinstance(m, _metrics.Histogram):
            hists.append(serialize_histogram(m))
            structured_keys.update(m.sample().keys())
        elif isinstance(m, _metrics.Counter):
            counters.append({"name": m.name, "labels": dict(m.labels),
                             "value": m.value})
            structured_keys.add(m.flat)
        elif isinstance(m, _metrics.Gauge):
            gauges.append({"name": m.name, "labels": dict(m.labels),
                           "value": m.value})
            structured_keys.add(m.flat)
    extras = {k: v for k, v in reg.snapshot().items()
              if k not in structured_keys
              and isinstance(v, (int, float))}
    return {"schema": SNAPSHOT_SCHEMA, "process": int(process),
            "time": _trace.now(), "dist": dist,
            "counters": counters, "gauges": gauges,
            "histograms": hists, "extras": extras,
            "shard_walls": STRAGGLER.last_walls_jsonable()}


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class FederatedView:
    """The coordinator's merged picture over N process snapshots.

    - ``counters``: flat name -> fleet-wide sum (extras folded in)
    - ``gauges``: flat name WITH ``process=i`` label -> value
    - ``histograms``: every per-process revived Histogram, labeled
      ``process=i`` (what ``/metrics/federated`` renders)
    - ``merged(name, **labels)``: the per-process group for one family
      / label set — feed it to ``metrics.merged_quantile``
    """

    def __init__(self, snapshots: Sequence[dict]):
        self.snapshots = sorted(
            (s for s in snapshots if isinstance(s, dict)),
            key=lambda s: int(s.get("process") or 0))
        self.processes = [int(s.get("process") or 0)
                          for s in self.snapshots]
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: List[_metrics.Histogram] = []
        self._groups: Dict[Tuple[str, Tuple], List[_metrics.Histogram]]
        self._groups = {}
        #: (process, shard) -> last-K wall seconds, fleet-wide
        self.shard_walls: Dict[Tuple[int, int], float] = {}
        for snap in self.snapshots:
            p = int(snap.get("process") or 0)
            for c in snap.get("counters") or []:
                flat = _metrics.flat_name(c["name"], c.get("labels") or {})
                self.counters[flat] = (
                    self.counters.get(flat, 0) + c["value"])
            for k, v in (snap.get("extras") or {}).items():
                self.counters[k] = self.counters.get(k, 0) + v
            for g in snap.get("gauges") or []:
                labels = dict(g.get("labels") or {})
                labels["process"] = str(p)
                self.gauges[_metrics.flat_name(g["name"], labels)] = (
                    g["value"])
            for hd in snap.get("histograms") or []:
                h = revive_histogram(hd, {"process": str(p)})
                self.histograms.append(h)
                key = (str(hd["name"]), _label_key(hd.get("labels") or {}))
                self._groups.setdefault(key, []).append(h)
            for shard, wall in (snap.get("shard_walls") or {}).items():
                try:
                    self.shard_walls[(p, int(shard))] = float(wall)
                except (TypeError, ValueError):
                    _metrics.counter("federate.bad_shard_wall").inc()

    def merged(self, name: str, **labels) -> List[_metrics.Histogram]:
        """The per-process histogram group for one (name, labels)."""
        return list(self._groups.get((name, _label_key(labels)), []))

    def quantile(self, name: str, q: float, **labels) -> Optional[float]:
        """Exact fleet-wide quantile: ``merged_quantile`` over the
        per-process group (bucket sums + min-of-mins / max-of-maxes)."""
        return _metrics.merged_quantile(self.merged(name, **labels), q)

    def phase_quantiles(self, tenant: Optional[str] = None,
                        qs: Sequence[float] = (0.5, 0.99)) -> dict:
        """Fleet-wide per-phase latency quantiles (round 22): exact
        merged quantiles of ``fleet.latency_phase_s`` across every
        process snapshot, keyed by phase — the federated analogue of
        ``FleetServer.phase_quantiles``.  With ``tenant=None`` the
        groups pool across tenants; pooling histogram groups keeps the
        quantiles exact (bucket sums are associative)."""
        pooled: Dict[str, List[_metrics.Histogram]] = {}
        for (name, lkey), group in self._groups.items():
            if name != "fleet.latency_phase_s":
                continue
            labels = dict(lkey)
            if tenant is not None and labels.get("tenant") != tenant:
                continue
            pooled.setdefault(labels.get("phase", ""), []).extend(group)
        return {ph: {f"p{int(round(q * 100))}":
                     _metrics.merged_quantile(group, q) for q in qs}
                for ph, group in sorted(pooled.items())}

    def skew(self, ratio: Optional[float] = None) -> dict:
        """Fleet-wide straggler assessment over every process's
        per-shard walls (the federated analogue of
        ``StragglerWatch.evaluate``)."""
        return _assess_skew(
            {f"{p}/{s}": w for (p, s), w in self.shard_walls.items()},
            straggler_ratio() if ratio is None else ratio)

    def render_prometheus(self) -> str:
        """Prometheus exposition of the merged view: counters summed
        (no process label), gauges + histogram families per process
        with ``process=i`` — so downstream ``sum by (le)`` is exact and
        round-trips through ``obs.export.parse_histograms``."""
        from cup3d_tpu.obs import export as _export

        snap = dict(self.counters)
        snap.update(self.gauges)
        return _export.render_prometheus(snap, self.histograms)

    def health(self) -> dict:
        """Federated /health body: per-process sub-blocks + the
        coordinator's mesh picture + fleet-wide skew."""
        procs = {}
        for snap in self.snapshots:
            p = str(int(snap.get("process") or 0))
            procs[p] = {"time": snap.get("time"),
                        "dist": snap.get("dist"),
                        "counters": len(snap.get("counters") or []),
                        "gauges": len(snap.get("gauges") or []),
                        "histograms": len(snap.get("histograms") or []),
                        "shard_walls": snap.get("shard_walls") or {}}
        return {"schema": SNAPSHOT_SCHEMA,
                "processes": procs,
                "coordinator": {"mesh": mesh_summary(),
                                "stragglers": STRAGGLER.health()},
                "skew": self.skew()}


def merge_snapshots(snapshots: Sequence[dict]) -> FederatedView:
    """Merge per-process snapshots into one :class:`FederatedView`."""
    return FederatedView(snapshots)


# -- transport ---------------------------------------------------------------

def _scrape_peer(url: str, timeout: float = 2.0) -> Optional[dict]:
    """GET one peer exporter's ``/federate`` JSON (stdlib urllib);
    failures are counted per peer, never raised — a dead peer drops
    out of the merged view instead of killing the scrape."""
    import urllib.request

    target = url.rstrip("/") + "/federate"
    try:
        with urllib.request.urlopen(target, timeout=timeout) as resp:
            snap = json.loads(resp.read().decode("utf-8"))
        if isinstance(snap, dict):
            return snap
        _metrics.counter("federate.scrape_errors", peer=url).inc()
    except Exception:
        _metrics.counter("federate.scrape_errors", peer=url).inc()
    return None


def _peers_from_env() -> List[str]:
    """``CUP3D_FEDERATE``: ``0``/empty = off, ``1`` = armed
    self-only, otherwise a comma-separated list of peer exporter base
    URLs the coordinator scrapes."""
    spec = os.environ.get("CUP3D_FEDERATE", "0").strip()
    if spec in ("0", "", "1"):
        return []
    return [p.strip() for p in spec.split(",") if p.strip()]


class Federation:
    """One process's federation endpoint state.

    Every process runs one (the module singleton :data:`FED`): it
    caches a local snapshot at K-boundaries and serves it on scrape.
    The coordinator additionally collects providers (in-process,
    socket-free) and peers (HTTP) and merges.  ``armed`` is read once
    per K-boundary — one bool test when federation is off."""

    def __init__(self,
                 providers: Optional[List[Callable[[], dict]]] = None,
                 peers: Optional[List[str]] = None,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        env = os.environ.get("CUP3D_FEDERATE", "0").strip()
        self.providers = list(providers or [])
        self.peers = list(peers if peers is not None
                          else _peers_from_env())
        self.registry = registry
        self.armed = bool(self.providers or self.peers
                          or env not in ("0", ""))
        self.boundaries = 0
        self._cached: Optional[dict] = None
        self._lock = threading.Lock()

    # -- arming ------------------------------------------------------------

    def arm(self) -> "Federation":
        self.armed = True
        return self

    def disarm(self) -> "Federation":
        self.armed = False
        with self._lock:
            self._cached = None
        return self

    def register_provider(self, fn: Callable[[], dict]) -> None:
        """In-process fallback transport: ``fn()`` returns a snapshot
        dict (another registry's :func:`local_snapshot`).  Single-host
        tests federate N simulated processes this way — no sockets."""
        self.providers.append(fn)
        self.armed = True

    # -- K-boundary hook ---------------------------------------------------

    def on_k_boundary(self) -> None:
        """Refresh the cached local snapshot (host dict work only).
        Called from the megaloop / fleet dispatch K-boundary seams;
        no-op unless armed, so the un-federated hot path pays one bool
        test."""
        if not self.armed:
            return
        snap = local_snapshot(self.registry)
        with self._lock:
            self._cached = snap
        self.boundaries += 1
        _metrics.counter("federate.boundaries").inc()

    def local_payload(self) -> dict:
        """What ``/federate`` serves: the K-boundary cache when armed
        and fresh, else a snapshot taken now (scrape-time fallback —
        the ISSUE's "at K-boundaries AND on scrape")."""
        with self._lock:
            cached = self._cached
        if cached is not None:
            return cached
        return local_snapshot(self.registry)

    # -- coordinator -------------------------------------------------------

    def collect(self) -> List[dict]:
        """Local payload + every provider + every scrapeable peer."""
        snaps = [self.local_payload()]
        for fn in list(self.providers):
            try:
                snap = fn()
                if isinstance(snap, dict):
                    snaps.append(snap)
                else:
                    _metrics.counter("federate.provider_errors").inc()
            except Exception:
                _metrics.counter("federate.provider_errors").inc()
        for url in self.peers:
            snap = _scrape_peer(url)
            if snap is not None:
                snaps.append(snap)
        return snaps

    def view(self) -> FederatedView:
        return merge_snapshots(self.collect())

    def state(self) -> dict:
        """Compact /health block for the plain (un-federated) payload."""
        return {"armed": self.armed, "boundaries": self.boundaries,
                "providers": len(self.providers),
                "peers": list(self.peers)}


#: the process-global federation endpoint (env-armed via CUP3D_FEDERATE)
FED = Federation()


# -- straggler watch ---------------------------------------------------------

def _assess_skew(walls: Dict[object, float], ratio: float) -> dict:
    """Shared skew math: slowest/median over a wall map + the over-
    threshold keys.  Returns {"shards", "median_s", "slowest_s",
    "skew_ratio", "threshold", "stragglers"}."""
    vals = [w for w in walls.values() if w is not None and w >= 0]
    out = {"shards": len(vals), "median_s": None, "slowest_s": None,
           "skew_ratio": None, "threshold": ratio, "stragglers": []}
    if len(vals) < 2:
        return out
    med = median(vals)
    slowest = max(vals)
    out["median_s"] = med
    out["slowest_s"] = slowest
    if med > 0:
        out["skew_ratio"] = slowest / med
        out["stragglers"] = sorted(
            (k for k, w in walls.items()
             if w is not None and w >= ratio * med),
            key=str)
    return out


class StragglerWatch:
    """Per-shard K-boundary wall gauges + skew-ratio alerting.

    The dispatch seams call :meth:`boundary` with the local shard ids;
    the elapsed host wall since the previous boundary (on
    :func:`obs.trace.now`) is recorded for each — in a single process
    all local shards share the dispatch wall (honest: the dispatch IS
    gated on its slowest local shard), and cross-process skew emerges
    in the federated view, where each process contributes its own
    walls.  Tests and multi-wall callers inject per-shard walls
    directly via :meth:`record` then :meth:`evaluate`."""

    def __init__(self, ratio: Optional[float] = None):
        self._ratio = ratio
        self.last_walls: Dict[int, float] = {}
        self.straggler_counts: Dict[int, int] = {}
        self.alerts: deque = deque(maxlen=64)
        self.skew_ratio: Optional[float] = None
        self._mark: Optional[float] = None
        self._warnings: List[int] = []

    @property
    def ratio(self) -> float:
        return self._ratio if self._ratio is not None else straggler_ratio()

    def reset(self) -> None:
        self.last_walls.clear()
        self.straggler_counts.clear()
        self.alerts.clear()
        self.skew_ratio = None
        self._mark = None
        self._warnings = []

    def record(self, shard: int, wall_s: float,
               source: str = "fleet") -> None:
        """One shard's last-K wall (host scalar the caller already
        had, or measured here at the boundary seam)."""
        shard = int(shard)
        self.last_walls[shard] = float(wall_s)
        _metrics.gauge("fleet.shard_last_k_wall_s",
                       shard=str(shard)).set(float(wall_s))
        _metrics.counter("fleet.shard_boundaries",
                         source=source).inc()

    def boundary(self, shards: Sequence[int], source: str = "fleet",
                 sink: Optional[_trace.TraceSink] = None,
                 step: int = 0) -> Optional[dict]:
        """K-boundary tick from a dispatch seam: stamps
        :func:`obs.trace.now`, attributes the elapsed wall since the
        previous boundary to every local shard, and evaluates.  The
        first boundary only sets the mark (no wall yet)."""
        t = _trace.now()
        mark, self._mark = self._mark, t
        if mark is None:
            return None
        wall = t - mark
        for shard in shards:
            self.record(shard, wall, source=source)
        return self.evaluate(source=source, sink=sink, step=step,
                             t0=mark, dur=wall)

    def evaluate(self, source: str = "fleet",
                 sink: Optional[_trace.TraceSink] = None,
                 step: int = 0, t0: Optional[float] = None,
                 dur: Optional[float] = None) -> dict:
        """Skew over the current per-shard walls: sets the
        ``fleet.shard_skew_ratio`` gauge, bumps
        ``fleet.stragglers{shard=}`` + the alert ring for every shard
        over threshold, and (when a sink is armed) emits one
        ``kind="shard"`` aux record and pid-4 span per shard."""
        ratio = self.ratio
        skew = _assess_skew(self.last_walls, ratio)
        if skew["skew_ratio"] is not None:
            self.skew_ratio = skew["skew_ratio"]
            _metrics.gauge("fleet.shard_skew_ratio").set(self.skew_ratio)
        self._warnings = [int(s) for s in skew["stragglers"]]
        for shard in self._warnings:
            self.straggler_counts[shard] = (
                self.straggler_counts.get(shard, 0) + 1)
            _metrics.counter("fleet.stragglers", shard=str(shard)).inc()
            self.alerts.append({
                "shard": shard, "step": int(step), "source": source,
                "wall_s": self.last_walls.get(shard),
                "median_s": skew["median_s"],
                "skew_ratio": self.skew_ratio, "threshold": ratio})
        if sink is not None and sink.enabled:
            sr = self.skew_ratio if self.skew_ratio is not None else 0.0
            straggling = set(self._warnings)
            for shard, wall in sorted(self.last_walls.items()):
                sink.aux(_trace.shard_record(
                    shard, step, wall, sr,
                    straggler=shard in straggling, source=source))
                span_t0 = (t0 if t0 is not None
                           else _trace.now() - wall)
                sink.shard_span(
                    shard, f"K-boundary s{shard}", span_t0,
                    dur if dur is not None else wall,
                    args={"shard": shard, "wall_s": wall,
                          "skew_ratio": sr, "source": source,
                          "straggler": shard in straggling})
        return skew

    def warnings(self) -> List[int]:
        """Shards currently over threshold — the early-warning signal
        ``resilience/elastic.py`` reads before a shard is lost."""
        return list(self._warnings)

    def last_walls_jsonable(self) -> Dict[str, float]:
        return {str(s): float(w) for s, w in self.last_walls.items()}

    def health(self) -> dict:
        """The /health "stragglers" block."""
        return {"threshold": self.ratio,
                "skew_ratio": self.skew_ratio,
                "last_walls": self.last_walls_jsonable(),
                "straggler_counts": {str(s): c for s, c in
                                     self.straggler_counts.items()},
                "warnings": list(self._warnings),
                "alerts": list(self.alerts)[-8:]}


#: the process-global straggler watch (dispatch seams + /health share it)
STRAGGLER = StragglerWatch()
