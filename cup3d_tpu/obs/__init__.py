"""Unified telemetry (ISSUE 4): metrics registry, span traces, flight
recorder — the repo's cross-cutting nervous system.

- :mod:`cup3d_tpu.obs.metrics` — process-global counters / gauges /
  histograms with labels; ``snapshot()``/``delta()``/``reset()``; the
  stream data-plane, the analysis sanitizers, the bucket caches, and
  the solvers all report here.  Host scalars only: the hot path never
  syncs a device value for telemetry.
- :mod:`cup3d_tpu.obs.trace` — nested span timing (the engine behind
  ``io/logging.py``'s Profiler shim), per-step structured JSONL records
  (``CUP3D_TRACE=1`` -> ``trace.jsonl``), Chrome trace-event export
  (``trace.pfto.json``, Perfetto-loadable), optional
  ``jax.profiler.TraceAnnotation`` passthrough (``CUP3D_TRACE_XLA=1``).
- :mod:`cup3d_tpu.obs.flight` — fixed-size ring of recent step records
  + solver residual history; dumps a self-contained postmortem JSON on
  NaN/Inf velocity, dt collapse, or a Poisson solve at its iteration
  cap.

See README "Observability" for the metric catalog and trace schema, and
VALIDATION.md round 9 for the pinned contract.
"""

from cup3d_tpu.obs import flight, metrics, trace  # noqa: F401
