"""Unified telemetry (ISSUE 4): metrics registry, span traces, flight
recorder — the repo's cross-cutting nervous system.

- :mod:`cup3d_tpu.obs.metrics` — process-global counters / gauges /
  histograms with labels; ``snapshot()``/``delta()``/``reset()``; the
  stream data-plane, the analysis sanitizers, the bucket caches, and
  the solvers all report here.  Host scalars only: the hot path never
  syncs a device value for telemetry.
- :mod:`cup3d_tpu.obs.trace` — nested span timing (the engine behind
  ``io/logging.py``'s Profiler shim), per-step structured JSONL records
  (``CUP3D_TRACE=1`` -> ``trace.jsonl``), Chrome trace-event export
  (``trace.pfto.json``, Perfetto-loadable), optional
  ``jax.profiler.TraceAnnotation`` passthrough (``CUP3D_TRACE_XLA=1``).
- :mod:`cup3d_tpu.obs.flight` — fixed-size ring of recent step records
  + solver residual history; dumps a self-contained postmortem JSON on
  NaN/Inf velocity, dt collapse, or a Poisson solve at its iteration
  cap.

Observability v2 (ISSUE 9) — the device half:

- :mod:`cup3d_tpu.obs.profile` — programmatic ``jax.profiler`` capture
  windows (``CUP3D_PROFILE=every:N``) + the trace-event parser that
  attributes device-stream op time to logical sections (fused BiCGSTAB
  stages, ring halos, megaloop body) and merges it into the step-trace
  JSONL and Perfetto export.
- :mod:`cup3d_tpu.obs.export` — zero-dependency background HTTP
  exporter: ``/metrics`` (Prometheus text from the registry snapshot)
  and ``/health`` (flight-recorder arm state, last-known-good step,
  recovery counters).  ``CUP3D_METRICS_PORT`` enables.
- :mod:`cup3d_tpu.obs.history` — append-only JSONL bench-history store
  with rolling-median regression detection (``tools/perfwatch.py``).

See README "Observability" / "Observability v2" for the metric catalog
and trace schema, and VALIDATION.md rounds 9 and 13 for the pinned
contracts.
"""

from cup3d_tpu.obs import (  # noqa: F401
    export,
    flight,
    history,
    metrics,
    profile,
    trace,
)
