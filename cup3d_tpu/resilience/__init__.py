"""Self-healing step loop (ISSUE 5): deterministic fault injection at
named seams (``resilience.faults``), hardened host-data-plane writes
(``resilience.writeguard``), and the rollback/retry RecoveryEngine both
drivers run their ``simulate()`` loop through (``resilience.recovery``).

Env knobs (full catalog in README "Resilience"):

- ``CUP3D_RECOVER``     1 (default) arms recovery inside ``simulate()``;
                        0 keeps the legacy crash-on-fault behavior (the
                        equivalence baseline).
- ``CUP3D_FAULT``       ``site@step[:count]`` (``;``-separated) arms
                        deterministic fault injection, e.g.
                        ``step.nan_velocity@40:1``.
- ``CUP3D_SNAP_EVERY``  rolling in-memory snapshot cadence (steps, 16).
- ``CUP3D_MAX_RETRIES`` rollback attempts before the postmortem +
                        restartable-checkpoint give-up (4).
- ``CUP3D_DT_FLOOR``    lower bound for the retry dt halving (1e-9).
"""

from cup3d_tpu.resilience import elastic  # noqa: F401 (public surface)
from cup3d_tpu.resilience import faults  # noqa: F401 (public surface)
from cup3d_tpu.resilience.recovery import (  # noqa: F401
    RecoveryEngine,
    SimulationFailure,
    recovery_enabled,
)
