"""Hardened host-side writes for the data-plane (ISSUE 5 tentpole b).

Two invariants for every checkpoint/dump byte that reaches disk:

1. **atomicity** — payloads are written to ``<path>.tmp`` and promoted
   with ``os.replace``, so a kill (or an injected ``*.write_fail``) at
   any instant leaves either the previous complete file or none: readers
   never see a truncated pickle / half a raw extent;
2. **bounded retries** — transient write failures (full-but-recovering
   disk, NFS hiccups) are retried with exponential backoff plus jitter
   before the caller's degradation policy (sync fallback for
   checkpoints, drop-and-count for dumps) kicks in.

Every retry is counted in the obs registry
(``resilience.write_retries{site=...}``).  This module deliberately
knows nothing about payload formats — callers pass a ``write_fn`` that
produces the complete tmp file.
"""

from __future__ import annotations

import os
import random
from typing import Callable

from cup3d_tpu.obs import metrics as _metrics


def backoff_sleep(attempt: int, base_delay: float = 0.05,
                  jitter: float = 0.5) -> None:
    """Exponential backoff before retry ``attempt`` (1-based) with a
    multiplicative jitter so concurrent writers decorrelate."""
    import time

    delay = base_delay * (2 ** (attempt - 1))
    time.sleep(delay * (1.0 + jitter * random.random()))


def atomic_write(path: str, write_fn: Callable[[str], None],
                 site: str = "write", retries: int = 2,
                 base_delay: float = 0.05) -> str:
    """Run ``write_fn(tmp_path)`` (which must produce the COMPLETE file
    at ``tmp_path``) then ``os.replace`` it over ``path``; on failure the
    tmp file is removed and the write retried up to ``retries`` times
    with backoff + jitter.  Raises the last failure; on success returns
    ``path``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp"
    last: Exception = RuntimeError("unreachable")
    for attempt in range(retries + 1):
        if attempt:
            _metrics.counter("resilience.write_retries", site=site).inc()
            backoff_sleep(attempt, base_delay)
        try:
            write_fn(tmp)
            os.replace(tmp, path)
            return path
        except Exception as e:
            last = e
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                _metrics.counter("resilience.tmp_unlink_failures").inc()
    raise last
