"""Per-slice elastic recovery for the mesh-sharded fleet (round 18).

A fleet batch sharded over the 2-D ``(lanes, x)`` mesh places a
contiguous block of ``B / nshards`` lanes on each mesh device (the
shard_map batch split in fleet/batch.build_fleet_advance).  When a
shard drops out — a preempted TPU slice, a failed host — ONLY that
block is lost: every surviving lane's carry bits are untouched (the
batch carry is never gathered or rewritten here), and the lost lanes'
jobs go back to the queue to be reseeded onto surviving shards at the
next K-boundary by the continuous scheduler (fleet/server._schedule).

The slice loss itself is injectable like every other failure seam:
the ``fleet.shard_loss`` fault site (resilience/faults.py) is armed
with the SHARD index in the step slot — the fleet.lane_nan idiom one
level up — and consulted per shard at each dispatch boundary.

Engine contract (exercised by tests/test_topology.py):

- the dead shard's lanes join ``batch.dead_lanes`` and are never again
  reseed targets (``FleetBatch.free_lanes`` excludes them);
- each lost RUNNING job is requeued from step 0 (its row buffer and
  step mirrors reset — rollback to the initial snapshot; partial rows
  from the dead slice are not trusted);
- in-flight QoI rows of lost lanes drop on the lane-guard epoch bump,
  so a late stream consume cannot resurrect them;
- counters: ``fleet.shard_losses`` per slice, ``fleet.elastic_requeues``
  per recovered job.
"""

from __future__ import annotations

from typing import List

import numpy as np

from cup3d_tpu.obs import metrics as M

__all__ = [
    "lanes_of_shard",
    "shard_of_lane",
    "fail_shard",
    "straggler_warnings",
]


def straggler_warnings() -> List[int]:
    """Mesh shards currently over the straggler threshold (round 19:
    the observatory's early-warning signal).  A slice that is straggling
    often precedes a slice that is GONE — operators and the scheduler
    can drain or deprioritize its lane block before ``fail_shard`` is
    forced.  Reads the obs-side skew watch; empty when balanced or
    unsharded."""
    from cup3d_tpu.obs import federate as FEDERATE

    return FEDERATE.STRAGGLER.warnings()


def lanes_of_shard(n_lanes: int, nshards: int, shard: int) -> range:
    """The contiguous lane block living on mesh shard ``shard`` —
    shard_map splits the leading batch axis into ``nshards`` equal
    blocks in flat device order, so block ``s`` is lanes
    ``[s * B/nshards, (s+1) * B/nshards)``."""
    if n_lanes % nshards:
        raise ValueError(
            f"{n_lanes} lanes do not split over {nshards} shards")
    bl = n_lanes // nshards
    if not 0 <= shard < nshards:
        raise ValueError(f"shard {shard} outside [0, {nshards})")
    return range(shard * bl, (shard + 1) * bl)


def shard_of_lane(n_lanes: int, nshards: int, lane: int) -> int:
    """Inverse of :func:`lanes_of_shard` (occupancy/SLO shard labels)."""
    return int(lane) // (n_lanes // nshards)


def fail_shard(batch, shard: int) -> List[str]:
    """Fail one mesh slice of a fleet batch: freeze its lane block,
    requeue its RUNNING jobs, leave every other lane untouched.
    Returns the requeued job ids (test hook).

    The batch carry is deliberately NOT rewritten: the dead lanes are
    fenced host-side (``left`` budget zero at the next dispatch via
    ``left_h``, epoch bump for in-flight rows, exclusion from
    ``free_lanes``), which is exactly how padding lanes are already
    kept inert — so the surviving lanes' device bits stay identical to
    a run where the slice never existed."""
    nshards = batch.nshards()
    lanes = lanes_of_shard(batch.B, nshards, shard)
    M.counter("fleet.shard_losses").inc()
    requeued: List[str] = []
    for lane in lanes:
        batch.dead_lanes.add(int(lane))
        batch.left_h[lane] = 0
        batch.guard.epochs[lane] += 1
        job = batch.jobs[lane]
        batch.jobs[lane] = None
        if job is None or job.status != "running":
            continue
        # rollback to the initial snapshot: the job restarts from step
        # 0 on whatever shard the scheduler reseeds it onto
        job.status = "queued"
        job.batch = None
        job.lane = -1
        job.steps_done = 0
        job.time = 0.0
        if job.rows is not None:
            job.rows[:] = 0.0
        job.mark("shard_lost")
        job.mark("queued")
        M.counter("fleet.elastic_requeues").inc()
        requeued.append(job.job_id)
    batch.server.update_lane_gauge()
    return requeued
