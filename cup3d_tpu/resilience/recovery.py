"""Rollback/retry recovery for the step loop (ISSUE 5 tentpole b).

The :class:`RecoveryEngine` turns the flight recorder's terminal
conditions (nan-velocity, runaway-velocity, dt-collapse,
poisson-itercap, poisson-nan-residual) from crashes into bounded
recovery, following the elastic-training pattern (periodic in-memory
snapshots + rollback/retry, as in Orbax-style emergency checkpointing):

- every ``CUP3D_SNAP_EVERY`` steps the engine takes a **rolling
  in-memory snapshot**: ``io.checkpoint.build_payload`` (the exact
  restart payload) with every device field re-staged into a FRESH
  device buffer (``jnp.copy`` — the step jits donate their state, so
  holding live references would hand the engine deleted arrays) and the
  host-mutable obstacle state deep-frozen via a pickle round trip.  The
  snapshot never leaves the device on the hot path — no host sync, no
  retrace (``jnp.copy`` is an eager op, not a jit);
- on a flight-recorder trigger the engine **rolls back** to the last
  snapshot (``driver._resilience_restore``), **halves dt** for the
  re-advance (``0.5**attempt``, floored at ``CUP3D_DT_FLOOR``, reset
  once the run progresses past the failure), and for Poisson failures
  walks the **escalation ladder**: warm-restart (restored pressure) ->
  zero initial guess -> tile-only preconditioner -> 4x iteration
  budget (the last two rebuild the solver — a deliberate, counted
  retrace on the failure path only);
- after ``CUP3D_MAX_RETRIES`` failed attempts it restores the last good
  snapshot, writes the postmortem (interception bypassed) plus a
  restartable on-disk checkpoint, and re-raises — a clean, resumable
  exit instead of a poisoned trajectory.

``CUP3D_RECOVER=0`` (or a sharded ``mesh`` driver, whose topology has no
in-place restore) disables installation entirely: the drivers then
behave exactly as before this subsystem existed — that is the bitwise
equivalence baseline the bench overhead gate compares against.

Every rollback/retry lands in the obs registry
(``resilience.rollbacks``, ``resilience.retries{stage=...}``,
``resilience.snapshots``, ``resilience.giveups``) and in the flight
recorder's ``recovery_events`` ring (part of any later postmortem).
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

from cup3d_tpu.obs import metrics as _metrics
from cup3d_tpu.resilience import faults

#: flight-recorder reasons the engine knows how to recover from
RECOVERABLE = frozenset((
    "nan-velocity",
    "runaway-velocity",
    "dt-collapse",
    "poisson-itercap",
    "poisson-nan-residual",
))

#: reasons that walk the Poisson escalation ladder on retry
_POISSON = frozenset(("poisson-itercap", "poisson-nan-residual"))

#: ladder stage per attempt number for Poisson failures
_LADDER = {1: "warm-restart", 2: "zero-guess", 3: "tile-only"}


def recovery_enabled() -> bool:
    """Default ON; ``CUP3D_RECOVER=0`` keeps the legacy crash-on-fault
    behavior (the equivalence baseline)."""
    return os.environ.get("CUP3D_RECOVER", "1") != "0"


class SimulationFailure(RuntimeError):
    """A detected terminal condition, carrying its flight-recorder
    ``reason`` so the recovery engine can classify it.  Subclasses
    RuntimeError: callers (and tests) that match the legacy abort
    messages keep working unchanged."""

    def __init__(self, reason: str, message: str,
                 extra: Optional[dict] = None):
        super().__init__(message)
        self.reason = reason
        self.extra = dict(extra or {})


class RecoveryEngine:
    """Snapshot / rollback / retry state machine for one driver run.

    The driver contract (implemented by ``sim/simulation.py`` and
    ``sim/amr.py``):

    - ``driver.flight``                        flight recorder
    - ``driver._resilience``                   engine backref (dt scale)
    - ``driver._resilience_restore(payload)``  in-place restore of a
      ``build_payload``-shaped snapshot
    - ``driver._resilience_zero_pressure()``   zero the pressure field
    - ``driver._resilience_rebuild_poisson(two_level=, maxiter_mult=)``
      rebuild the Poisson solve (escalation; retraces by design)
    """

    def __init__(self, driver, snapshot_every: Optional[int] = None,
                 max_retries: Optional[int] = None,
                 dt_floor: Optional[float] = None):
        env = os.environ.get
        self.driver = driver
        self.flight = driver.flight
        self.snapshot_every = int(
            snapshot_every if snapshot_every is not None
            else env("CUP3D_SNAP_EVERY", "16")
        )
        self.max_retries = int(
            max_retries if max_retries is not None
            else env("CUP3D_MAX_RETRIES", "4")
        )
        self.dt_floor = float(
            dt_floor if dt_floor is not None else env("CUP3D_DT_FLOOR", "1e-9")
        )
        self.dt_scale = 1.0
        self.attempts = 0
        self._snap: Optional[dict] = None
        self._snap_step: Optional[int] = None
        self._pending: Optional[tuple] = None
        self._recovering_until = -1
        self._c_snap = _metrics.counter("resilience.snapshots")
        self._c_roll = _metrics.counter("resilience.rollbacks")
        self._c_give = _metrics.counter("resilience.giveups")
        # the one bound-method object installed as the flight hook
        # (bound methods are created per access, so identity checks in
        # uninstall need a stable reference)
        self._hook = self._intercept

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def install(cls, driver, force: bool = False,
                **kw) -> Optional["RecoveryEngine"]:
        """Attach an engine to ``driver`` for the duration of a
        ``simulate()`` loop (None when disabled).  Sharded (mesh) runs
        are excluded: their topology has no in-place restore path."""
        if not (force or recovery_enabled()):
            return None
        if getattr(driver, "mesh", None) is not None:
            return None
        faults.load_env()
        eng = cls(driver, **kw)
        driver._resilience = eng
        eng.flight.recovery_intercept = eng._hook
        return eng

    def uninstall(self) -> None:
        if getattr(self.driver, "_resilience", None) is self:
            self.driver._resilience = None
        if self.flight.recovery_intercept is self._hook:
            self.flight.recovery_intercept = None

    # -- flight-recorder interception --------------------------------------

    def _intercept(self, reason: str, extra: dict) -> bool:
        """Called INSIDE ``flight.trigger``: claim the failure (skip the
        postmortem dump) when it is recoverable and a snapshot exists;
        the actual rollback runs from the simulate loop."""
        if reason not in RECOVERABLE or self._snap is None:
            return False
        self._pending = (reason, dict(extra))
        return True

    # -- simulate-loop hooks -----------------------------------------------

    def _step(self) -> int:
        d = self.driver
        if hasattr(d, "step_idx"):  # AMR driver
            return int(d.step_idx)
        return int(d.sim.step)

    def snapshot_due(self, step: Optional[int] = None) -> bool:
        """True when :meth:`on_loop_top` will take its cadence snapshot.
        Scan-megaloop drivers ask BEFORE the loop top and flush their
        QoI stream first, so the pickled obstacle mirrors match the
        device carry at the K boundary (VALIDATION.md round 11)."""
        if step is None:
            step = self._step()
        return (self._snap is None
                or step - self._snap_step >= self.snapshot_every)

    def on_loop_top(self) -> bool:
        """Top of every simulate iteration.  Handles failures latched by
        the async pack consumption (returns True after a rollback so the
        loop re-enters), retires recovery state once the run progressed
        past the failure, and takes the cadence snapshot."""
        if self._pending is not None:
            reason, extra = self._pending
            self._pending = None
            if not self._recover(reason, extra):
                self._give_up(reason, extra)  # raises
            return True
        step = self._step()
        if self.attempts and step > self._recovering_until:
            self.attempts = 0
            self.dt_scale = 1.0
        if self.snapshot_due(step):
            try:
                self.snapshot()
            except Exception:
                # best-effort: a snapshot that cannot be taken (e.g. an
                # unpicklable monkeypatched obstacle) must never kill a
                # healthy run — the rollback point just stays staler,
                # and the drop is counted
                _metrics.counter("resilience.snapshot_failures").inc()
        return False

    def handle_failure(self, exc: BaseException) -> bool:
        """Exception filter for the simulate loop: True after a
        successful rollback (retry the iteration), False when the
        failure is not ours / not recoverable (re-raise)."""
        self._pending = None  # the raise supersedes any latched trigger
        reason = getattr(exc, "reason", None)
        if reason is None or reason not in RECOVERABLE:
            return False
        if self._snap is None:
            # nothing to roll back to: the trigger already wrote its
            # postmortem (interception declines without a snapshot)
            return False
        if not self._recover(reason, getattr(exc, "extra", {})):
            self._give_up(reason, getattr(exc, "extra", {}), exc)  # raises
        return True

    # -- snapshot / restore ------------------------------------------------

    def snapshot(self) -> None:
        """Rolling in-memory snapshot: the restart payload with every
        device field re-staged into a fresh buffer and obstacles frozen
        to bytes.  Device-staged — the hot path pays eager device copies
        and host pickling of small kinematic state, never a field
        read."""
        import jax.numpy as jnp

        from cup3d_tpu.io.checkpoint import build_payload

        if hasattr(getattr(self.driver, "dt", 0.0), "block_until_ready"):
            # device-dt chain: the payload's float(dt) is a real sync —
            # a designed once-per-cadence read (VALIDATION.md round 10)
            from cup3d_tpu.analysis.runtime import sanctioned_transfer

            with sanctioned_transfer("resilience-snapshot"):
                payload = build_payload(self.driver)
        else:
            payload = build_payload(self.driver)
        payload["obstacles"] = pickle.dumps(
            payload["obstacles"], protocol=pickle.HIGHEST_PROTOCOL
        )
        payload["fields"] = {
            k: (jnp.copy(v) if hasattr(v, "block_until_ready") else v)
            for k, v in payload["fields"].items()
        }
        self._snap = payload
        self._snap_step = int(payload["step"])
        self._c_snap.inc()

    def _restore(self) -> None:
        self.driver._resilience_restore(self._snap)

    # -- rollback / escalation ---------------------------------------------

    def _stage(self, reason: str) -> str:
        if reason in _POISSON:
            return _LADDER.get(self.attempts, "iter-bump")
        return "dt-halve"

    def _recover(self, reason: str, extra: dict) -> bool:
        """One rollback attempt; False when the retry budget is spent."""
        self.attempts += 1
        if self.attempts > self.max_retries:
            return False
        failed_at = int(extra.get("step", self._step()))
        stage = self._stage(reason)
        self._restore()
        self.dt_scale = 0.5 ** self.attempts
        if reason in _POISSON:
            if stage == "zero-guess":
                self.driver._resilience_zero_pressure()
            elif stage == "tile-only":
                self.driver._resilience_zero_pressure()
                self.driver._resilience_rebuild_poisson(two_level=False)
            elif stage == "iter-bump":
                self.driver._resilience_zero_pressure()
                self.driver._resilience_rebuild_poisson(
                    two_level=False, maxiter_mult=4
                )
        # recovery state retires once the run is safely past the failure
        # (a short grace: dt returns to policy quickly, and a recurrence
        # simply re-enters with attempts already counted up)
        self._recovering_until = failed_at + 4
        self._c_roll.inc()
        _metrics.counter("resilience.retries", stage=stage).inc()
        self.flight.note_recovery({
            "reason": reason, "stage": stage, "attempt": self.attempts,
            "failed_at_step": failed_at, "rolled_back_to": self._snap_step,
            "dt_scale": self.dt_scale,
        })
        return True

    def _give_up(self, reason: str, extra: dict,
                 exc: Optional[BaseException] = None) -> None:
        """Retries exhausted: postmortem (interception bypassed) + a
        restartable checkpoint from the last good snapshot, then raise —
        the exit is clean and resumable, never a poisoned trajectory."""
        self._c_give.inc()
        icpt, self.flight.recovery_intercept = (
            self.flight.recovery_intercept, None,
        )
        try:
            self.flight.trigger(reason, extra={
                **extra, "recovery": "exhausted",
                "attempts": self.attempts,
                "rolled_back_to": self._snap_step,
            })
        finally:
            self.flight.recovery_intercept = icpt
        try:
            self._restore()
            from cup3d_tpu.io.checkpoint import save_checkpoint

            path = save_checkpoint(self.driver)
            _metrics.counter("resilience.restart_checkpoints").inc()
            self.flight.note_recovery({
                "reason": reason, "stage": "give-up",
                "restart_checkpoint": path,
            })
        except Exception:
            # the give-up path must reach the raise even when the disk
            # (or an armed ckpt.write_fail) refuses the restart file
            _metrics.counter("resilience.restart_ckpt_failures").inc()
        if exc is not None:
            raise exc
        raise SimulationFailure(
            reason,
            f"recovery exhausted after {self.attempts - 1} retries: "
            f"{reason}", extra,
        )

    # -- dt policy hook ----------------------------------------------------

    def scale_dt(self, dt):
        """Retry dt halving.  Exact identity (same object) at scale 1.0,
        so the armed-but-clean path is bitwise-equivalent to
        CUP3D_RECOVER=0; host floats are floored at ``dt_floor`` (device
        dt chains scale unfloored — a probe-free multiply)."""
        if self.dt_scale == 1.0:
            return dt
        scaled = dt * self.dt_scale
        if isinstance(dt, float):
            return max(scaled, min(dt, self.dt_floor))
        return scaled
