"""Deterministic fault injection at named seams (ISSUE 5 tentpole a).

Every injectable failure the flight recorder already knows how to detect
gets a stable SITE name; arming one makes the existing seam misbehave in
a controlled, reproducible way so the recovery machinery (and its tests)
exercise the REAL detection and rollback paths instead of mocks:

==================== ======================================================
site                 seam (where ``fire`` is consulted)
==================== ======================================================
step.nan_velocity    drivers' ``calc_max_timestep``: poisons the max|u|
                     mirror, tripping the existing NaN-umax abort
dt.collapse          drivers' ``calc_max_timestep``: poisons the computed
                     dt, tripping the existing dt-collapse abort
solver.nan_residual  ``obs.trace.StepObserver.note_solver``: the consumed
                     packed solver residual becomes NaN
solver.itercap       ``obs.trace.StepObserver.note_solver``: the consumed
                     iteration count hits the solver's cap
ckpt.write_fail      ``io.checkpoint.write_payload``: the checkpoint
                     write raises (every retry re-fires while armed)
dump.write_fail      ``stream.dump.AsyncDumper._write``: the dump write
                     raises (retried, then dropped + counted)
stream.stall         ``stream.qoi.QoIStream.emit``: a simulated tunnel
                     stall (sleep) before the pack is queued
==================== ======================================================

Arming is via ``CUP3D_FAULT="site@step[:count]"`` (``;``-separated for
several; ``step`` may be ``*`` for "any step") or the :func:`arm` API.
A site fires at most ``count`` times, once armed-and-reached; every
firing lands in the obs registry as ``faults.injected{site=...}``.  An
empty plan is one tuple iteration per probe — the unarmed hot path pays
nothing measurable.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import List, Optional

from cup3d_tpu.obs import metrics as _metrics

#: the fault-site catalog (README "Resilience" documents each seam)
SITES = (
    "solver.nan_residual",
    "solver.itercap",
    "step.nan_velocity",
    "dt.collapse",
    "ckpt.write_fail",
    "dump.write_fail",
    "stream.stall",
    # lane-addressed fleet seam: armed with the LANE index in the step
    # slot, it poisons exactly one chosen lane's QoI chain at its next
    # consumed row (fleet/isolate.py check_row)
    "fleet.lane_nan",
    # shard-addressed fleet seam (round 18): armed with the SHARD index
    # in the step slot, it drops that mesh slice of every live batch at
    # the next dispatch boundary (resilience/elastic.fail_shard via
    # fleet/server.FleetBatch.dispatch)
    "fleet.shard_loss",
    # round 23 — durability chaos sites:
    # journal segment write raises inside the writeguard seam (one-shot
    # arms are absorbed by the retry; wildcard arms exhaust it and the
    # append is counted-dropped, never raised to the serve loop)
    "journal.write_fail",
    # hard process death (os._exit) at a dispatch K-boundary of
    # fleet/server.FleetBatch.dispatch, armed with the DISPATCH count in
    # the step slot — the crash-restart drill's kill switch
    "server.crash",
    # flips bytes mid-artifact before an aot/store.py load, driving the
    # read down the checksum-reject path (transparent recompile)
    "aot.store_corrupt",
    # kills the background compile worker thread mid-task
    # (aot/compiler.py _run), leaving its build orphaned RUNNING — the
    # death-path serve() must fall back from, not park on
    "compile.service_die",
)

ENV_VAR = "CUP3D_FAULT"

#: simulated tunnel stall for the stream.stall site (seconds)
STALL_S = 0.02


class InjectedFault(IOError):
    """The exception raised at write-path seams when their site fires."""

    def __init__(self, site: str, step):
        super().__init__(f"injected fault {site!r} at step {step}")
        self.site = site
        self.step = step


@dataclass
class _Arm:
    site: str
    step: Optional[int]  # None = any step ('*')
    count: int = 1
    fired: int = 0

    def matches(self, step) -> bool:
        if self.fired >= self.count:
            return False
        if self.step is None:
            return True
        return step is not None and int(step) >= self.step


class FaultPlan:
    """A deterministic, ordered set of armed fault sites."""

    def __init__(self) -> None:
        self.arms: List[_Arm] = []

    def arm(self, site: str, step="*", count: int = 1) -> None:
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; known: {', '.join(SITES)}"
            )
        step_i = None if step in ("*", None) else int(step)
        self.arms.append(_Arm(site, step_i, int(count)))

    def clear(self) -> None:
        self.arms = []

    def parse(self, spec: str) -> None:
        """``site@step[:count]`` entries separated by ``;`` or ``,``."""
        for part in spec.replace(",", ";").split(";"):
            part = part.strip()
            if not part:
                continue
            if "@" not in part:
                raise ValueError(
                    f"bad CUP3D_FAULT entry {part!r}: want site@step[:count]"
                )
            site, rest = part.split("@", 1)
            count = 1
            if ":" in rest:
                rest, cnt = rest.rsplit(":", 1)
                count = int(cnt)
            self.arm(site.strip(), rest.strip(), count)

    def fire(self, site: str, step=None) -> bool:
        """True exactly when an armed entry for ``site`` fires at
        ``step`` (counted, so a ``count``-shot arm exhausts itself)."""
        for a in self.arms:
            if a.site == site and a.matches(step):
                a.fired += 1
                _metrics.counter("faults.injected", site=site).inc()
                return True
        return False

    def snapshot(self) -> List[dict]:
        """Armed-state view for postmortems / tests."""
        return [
            {"site": a.site, "step": a.step, "count": a.count,
             "fired": a.fired}
            for a in self.arms
        ]


#: the process-global plan every seam consults
PLAN = FaultPlan()

_env_src: str = ""


def load_env(force: bool = False) -> FaultPlan:
    """(Re)load ``CUP3D_FAULT`` into the global plan.  Idempotent while
    the env value is unchanged, so drivers call it at every
    ``simulate()`` entry; API-armed entries survive only until the env
    value CHANGES (tests monkeypatching the env get a fresh plan)."""
    global _env_src
    spec = os.environ.get(ENV_VAR, "")
    if not force and spec == _env_src:
        return PLAN
    _env_src = spec
    PLAN.clear()
    if spec:
        PLAN.parse(spec)
    return PLAN


def arm(site: str, step="*", count: int = 1) -> None:
    PLAN.arm(site, step, count)


def clear() -> None:
    """Disarm everything (tests)."""
    global _env_src
    PLAN.clear()
    _env_src = ""


def fire(site: str, step=None) -> bool:
    return PLAN.fire(site, step)


def maybe_raise(site: str, step=None) -> None:
    """Raise :class:`InjectedFault` when ``site`` fires (write seams)."""
    if PLAN.fire(site, step):
        raise InjectedFault(site, step)


def maybe_stall(site: str = "stream.stall", step=None) -> None:
    """Sleep :data:`STALL_S` when ``site`` fires (stream seams)."""
    if PLAN.fire(site, step):
        time.sleep(STALL_S)
