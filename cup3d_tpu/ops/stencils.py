"""Cell-level finite-difference stencils on ghost-padded arrays.

Every function takes an array already padded with ``w`` ghost cells on each
face of the three leading spatial axes (trailing axes, e.g. the vector
component, are untouched) and returns interior-shaped results.  Written as
pure slicing arithmetic so XLA fuses each kernel into one pass over HBM.

Math sources in the reference (not code): 7-point Laplacian and 2nd-order
centered first derivatives throughout (e.g. KernelLHSPoisson main.cpp:9197,
KernelDissipation main.cpp:10347); 5th-order 6-point biased-upwind advection
derivatives (KernelAdvectDiffuse, main.cpp:9474-9548).
"""

from __future__ import annotations

import jax.numpy as jnp


def shift(ap: jnp.ndarray, w: int, ox: int = 0, oy: int = 0, oz: int = 0):
    """Interior view of padded array `ap`, shifted by (ox,oy,oz) cells."""
    nx = ap.shape[0] - 2 * w
    ny = ap.shape[1] - 2 * w
    nz = ap.shape[2] - 2 * w
    return ap[
        w + ox : w + ox + nx,
        w + oy : w + oy + ny,
        w + oz : w + oz + nz,
    ]


def _offsets(axis: int, k: int):
    o = [0, 0, 0]
    o[axis] = k
    return tuple(o)


def d1_central(ap, w, axis, h):
    """2nd-order centered first derivative along `axis`."""
    return (shift(ap, w, *_offsets(axis, 1)) - shift(ap, w, *_offsets(axis, -1))) / (
        2.0 * h
    )


def d1_upwind5(ap, w, axis, vel, h):
    """5th-order 6-point biased-upwind first derivative, selected by the
    sign of `vel` — the reference's advective derivative
    (KernelAdvectDiffuse, main.cpp:9474-9483).

    vel > 0: (-2 q[-3] + 15 q[-2] - 60 q[-1] + 20 q[0] + 30 q[+1] - 3 q[+2]) / 60h
    vel < 0: ( 2 q[+3] - 15 q[+2] + 60 q[+1] - 20 q[0] - 30 q[-1] + 3 q[-2]) / 60h
    Requires w >= 3.
    """
    qm3 = shift(ap, w, *_offsets(axis, -3))
    qm2 = shift(ap, w, *_offsets(axis, -2))
    qm1 = shift(ap, w, *_offsets(axis, -1))
    q0 = shift(ap, w)
    qp1 = shift(ap, w, *_offsets(axis, 1))
    qp2 = shift(ap, w, *_offsets(axis, 2))
    qp3 = shift(ap, w, *_offsets(axis, 3))
    inv60h = 1.0 / (60.0 * h)
    dplus = (
        -2.0 * qm3 + 15.0 * qm2 - 60.0 * qm1 + 20.0 * q0 + 30.0 * qp1 - 3.0 * qp2
    ) * inv60h
    dminus = (
        2.0 * qp3 - 15.0 * qp2 + 60.0 * qp1 - 20.0 * q0 - 30.0 * qm1 + 3.0 * qm2
    ) * inv60h
    return jnp.where(vel > 0, dplus, dminus)


def laplacian(ap, w, h):
    """7-point Laplacian of a padded scalar (w >= 1)."""
    out = -6.0 * shift(ap, w)
    for axis in range(3):
        out = out + shift(ap, w, *_offsets(axis, 1)) + shift(ap, w, *_offsets(axis, -1))
    return out / (h * h)


def grad(ap, w, h):
    """(nx,ny,nz,3) centered gradient of a padded scalar."""
    return jnp.stack([d1_central(ap, w, a, h) for a in range(3)], axis=-1)


def divergence(up, w, h):
    """Centered divergence of a padded (.., 3) vector field."""
    return sum(d1_central(up[..., a], w, a, h) for a in range(3))


def curl(up, w, h):
    """Centered curl (vorticity) of a padded (.., 3) vector field."""
    d = lambda c, a: d1_central(up[..., c], w, a, h)
    wx = d(2, 1) - d(1, 2)
    wy = d(0, 2) - d(2, 0)
    wz = d(1, 0) - d(0, 1)
    return jnp.stack([wx, wy, wz], axis=-1)


def vector_laplacian(up, w, h):
    return jnp.stack([laplacian(up[..., c], w, h) for c in range(3)], axis=-1)


def laplacian_lanes_chunk(t: jnp.ndarray, planes: jnp.ndarray,
                          inv_h2) -> jnp.ndarray:
    """7-point Laplacian on a lane-resident chunk (bs, bs, bs, T) whose
    cross-tile boundary values arrive as 6 explicit face planes
    (6, bs, bs, T), rows [lo0, hi0, lo1, hi1, lo2, hi2]
    (krylov.make_lane_planes).

    With the boundary data externalized, the apply is pure intra-chunk
    slice/concat arithmetic — the form that lowers both in an XLA fusion
    and inside a Pallas kernel body over lane chunks, which is exactly
    how the fused BiCGSTAB iteration uses it (ops/fused_bicgstab.py
    shares this function between its kernel and its jnp twin)."""
    out = -6.0 * t
    out = out + jnp.concatenate([t[1:], planes[1][None]], axis=0)
    out = out + jnp.concatenate([planes[0][None], t[:-1]], axis=0)
    out = out + jnp.concatenate([t[:, 1:], planes[3][:, None]], axis=1)
    out = out + jnp.concatenate([planes[2][:, None], t[:, :-1]], axis=1)
    out = out + jnp.concatenate([t[:, :, 1:], planes[5][:, :, None]], axis=2)
    out = out + jnp.concatenate([planes[4][:, :, None], t[:, :, :-1]], axis=2)
    return out * inv_h2
