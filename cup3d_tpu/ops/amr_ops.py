"""Differential operators on block-structured AMR fields.

Mirrors the uniform-grid kernels (cup3d_tpu.ops.stencils) on
``(nb, bs, bs, bs[, 3])`` block batches: halo'd labs come from the gather
tables (grid/blocks.py), spatial derivatives are batch slices, and each
block scales by its own spacing ``h``.  Conservative operators emit
outward per-unit-area face fluxes for coarse-fine refluxing (grid/flux.py).

Reference counterparts: KernelLHSPoisson (main.cpp:9197-9269),
KernelAdvectDiffuse (9461-9639), KernelPressureRHS (14761-14948),
KernelGradP (14957-15056), ComputeVorticity (8624-8745).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.blocks import BlockGrid, LabTables
from cup3d_tpu.grid.flux import FluxTables


def _sh(lab: jnp.ndarray, w: int, bs: int, ox=0, oy=0, oz=0) -> jnp.ndarray:
    """Interior view of a (nb, L,L,L, ...) lab shifted by (ox,oy,oz)."""
    return lab[
        :,
        w + ox : w + ox + bs,
        w + oy : w + oy + bs,
        w + oz : w + oz + bs,
    ]


def _off(axis, k):
    o = [0, 0, 0]
    o[axis] = k
    return tuple(o)


def _hcol(grid: BlockGrid, dtype=jnp.float32, extra: int = 0) -> jnp.ndarray:
    """(nb, 1, 1, 1[, 1]) per-block spacing."""
    shape = (grid.nb, 1, 1, 1) + (1,) * extra
    return jnp.asarray(grid.h.reshape(shape), dtype)


def face_fluxes(lab: jnp.ndarray, w: int, bs: int, inv_h: jnp.ndarray):
    """Outward per-unit-area gradient fluxes (lab_nb - c)/h on the 6 faces:
    (nb, 6, bs, bs) in the grid/flux.py convention."""
    c = _sh(lab, w, bs)
    ih = inv_h[:, 0, 0, 0][:, None, None]  # (nb,1,1)
    fl = []
    for ax in range(3):
        lo = _sh(lab, w, bs, *_off(ax, -1))
        hi = _sh(lab, w, bs, *_off(ax, 1))
        sel_lo = [slice(None)] * 4
        sel_lo[ax + 1] = 0
        sel_hi = [slice(None)] * 4
        sel_hi[ax + 1] = bs - 1
        fl.append((lo - c)[tuple(sel_lo)] * ih)
        fl.append((hi - c)[tuple(sel_hi)] * ih)
    return jnp.stack(fl, axis=1)


def laplacian_blocks(
    grid: BlockGrid,
    field: jnp.ndarray,
    tab: LabTables,
    flux_tab: Optional[FluxTables] = None,
) -> jnp.ndarray:
    """Refluxed 7-point Laplacian (the AMR ComputeLHS, main.cpp:9196-9328,
    in physical 1/h^2 units)."""
    bs = grid.bs
    w = tab.width
    lab = tab.assemble_scalar(field, bs)
    c = _sh(lab, w, bs)
    s = -6.0 * c
    for ax in range(3):
        s = s + _sh(lab, w, bs, *_off(ax, 1)) + _sh(lab, w, bs, *_off(ax, -1))
    inv_h = 1.0 / _hcol(grid, field.dtype)
    out = s * inv_h * inv_h
    if flux_tab is not None and flux_tab.ncorr:
        fluxes = face_fluxes(lab, w, bs, inv_h)
        out = flux_tab.apply(out, fluxes)
    return out


def grad_blocks(grid: BlockGrid, lab: jnp.ndarray, w: int) -> jnp.ndarray:
    """(nb,bs,bs,bs,3) centered gradient from a scalar lab."""
    bs = grid.bs
    inv2h = 0.5 / _hcol(grid, lab.dtype)
    return jnp.stack(
        [
            (_sh(lab, w, bs, *_off(a, 1)) - _sh(lab, w, bs, *_off(a, -1))) * inv2h
            for a in range(3)
        ],
        axis=-1,
    )


def div_blocks(grid: BlockGrid, vlab: jnp.ndarray, w: int) -> jnp.ndarray:
    """Centered divergence from a vector lab (nb, L,L,L, 3)."""
    bs = grid.bs
    inv2h = 0.5 / _hcol(grid, vlab.dtype)
    out = 0.0
    for a in range(3):
        out = out + (
            _sh(vlab[..., a], w, bs, *_off(a, 1))
            - _sh(vlab[..., a], w, bs, *_off(a, -1))
        )
    return out * inv2h


def curl_blocks(grid: BlockGrid, vlab: jnp.ndarray, w: int) -> jnp.ndarray:
    bs = grid.bs
    inv2h = 0.5 / _hcol(grid, vlab.dtype)

    def d(c, a):
        return (
            _sh(vlab[..., c], w, bs, *_off(a, 1))
            - _sh(vlab[..., c], w, bs, *_off(a, -1))
        ) * inv2h

    return jnp.stack(
        [d(2, 1) - d(1, 2), d(0, 2) - d(2, 0), d(1, 0) - d(0, 1)], axis=-1
    )


# ---------------------------------------------------------------------------
# advection-diffusion (explicit RK3) on blocks
# ---------------------------------------------------------------------------

_UP_W = 3  # 6-point biased upwind needs 3 ghosts


def _upwind_d1(lab_c: jnp.ndarray, w: int, bs: int, axis: int, vel, inv_h):
    """5th-order biased upwind derivative (KernelAdvectDiffuse,
    main.cpp:9474-9483) on a batched lab component."""
    q = [_sh(lab_c, w, bs, *_off(axis, k)) for k in range(-3, 4)]
    inv60h = inv_h / 60.0
    dplus = (
        -2.0 * q[0] + 15.0 * q[1] - 60.0 * q[2] + 20.0 * q[3] + 30.0 * q[4]
        - 3.0 * q[5]
    ) * inv60h
    dminus = (
        2.0 * q[6] - 15.0 * q[5] + 60.0 * q[4] - 20.0 * q[3] - 30.0 * q[2]
        + 3.0 * q[1]
    ) * inv60h
    return jnp.where(vel > 0, dplus, dminus)


def advdiff_rhs_blocks(
    grid: BlockGrid,
    vel: jnp.ndarray,
    tab: LabTables,
    nu: float,
    uinf: jnp.ndarray,
    flux_tab: Optional[FluxTables] = None,
) -> jnp.ndarray:
    """du/dt = -(u+uinf).grad(u) + nu lap(u), refluxing diffusive fluxes
    (reference AdvectionDiffusion, main.cpp:9640-9728)."""
    bs = grid.bs
    w = tab.width
    vlab = tab.assemble_vector(vel, bs)
    inv_h = 1.0 / _hcol(grid, vel.dtype)
    adv_u = _sh(vlab, w, bs) + uinf  # (nb,bs,bs,bs,3)

    rhs = []
    for c in range(3):
        lab_c = vlab[..., c]
        conv = 0.0
        for a in range(3):
            conv = conv + adv_u[..., a] * _upwind_d1(
                lab_c, w, bs, a, adv_u[..., a], inv_h
            )
        s = -6.0 * _sh(lab_c, w, bs)
        for a in range(3):
            s = s + _sh(lab_c, w, bs, *_off(a, 1)) + _sh(lab_c, w, bs, *_off(a, -1))
        diff = nu * s * inv_h * inv_h
        out_c = diff - conv
        if flux_tab is not None and flux_tab.ncorr:
            fluxes = nu * face_fluxes(lab_c, w, bs, inv_h)
            out_c = flux_tab.apply(out_c, fluxes)
        rhs.append(out_c)
    return jnp.stack(rhs, axis=-1)


def rk3_step_blocks(
    grid: BlockGrid,
    vel: jnp.ndarray,
    dt,
    nu: float,
    uinf: jnp.ndarray,
    tab: LabTables,
    flux_tab: Optional[FluxTables] = None,
) -> jnp.ndarray:
    """Low-storage RK3 (Williamson; the reference's AdvectionDiffusion
    coefficients, main.cpp:9640-9655) — identical staging to the uniform
    path (cup3d_tpu.ops.advection.rk3_step)."""
    from cup3d_tpu.ops.advection import RK3_A, RK3_B

    k = jnp.zeros_like(vel)
    u = vel
    for a, b in zip(RK3_A, RK3_B):
        k = a * k + dt * advdiff_rhs_blocks(grid, u, tab, nu, uinf, flux_tab)
        u = u + b * k
    return u


# ---------------------------------------------------------------------------
# AMR Poisson front-end
# ---------------------------------------------------------------------------


def build_amr_poisson_solver(
    grid: BlockGrid,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    precond_iters: int = 24,
    tab: Optional[LabTables] = None,
    flux_tab: Optional[FluxTables] = None,
    vol: Optional[jnp.ndarray] = None,
    pmask: Optional[jnp.ndarray] = None,
    mean_constraint: int = 2,
    two_level: Optional[bool] = None,
):
    """getZ-preconditioned BiCGSTAB on the AMR forest: the direct TPU
    analogue of PoissonSolverAMR (main.cpp:14363-14616).
    ``two_level`` overrides the CUP3D_COARSE env default (None =
    ``krylov.use_coarse_correction``) — the resilience escalation ladder
    drops to tile-only getZ per driver, not per process.

    This STATIC front-end runs the unfused composition regardless of
    CUP3D_FUSED (it exists for direct/legacy use on unpadded forests);
    the bucketed production path goes through
    ``build_amr_poisson_solver_dynamic``, which dispatches the fused
    Pallas iteration (ops/fused_amr_bicgstab.py) under CUP3D_FUSED.
    It still inherits the round-12 precision hygiene — getZ
    tile solves accumulate in >= f32 for any storage dtype
    (ops/tilesolve.py, ops/precision.py) and the bicgstab breakdown
    threshold lives in the accumulation dtype.

    ``mean_constraint`` mirrors the reference's bMeanConstraint
    (ComputeLHS, main.cpp:9273-9327):

    - 0: no nullspace handling (caller guarantees compatibility);
    - 1: the equation row of cell (0,0,0) of the corner block is
      replaced by the volume-weighted mean of the unknown;
    - 2 (default): mean removal — the projection formulation of the
      reference's rank-one "LHS += avg * h^3" shift;
    - 3 (reference: any value > 2): Dirichlet-pin — the corner row is
      replaced by the identity, fixing p at that cell.

    ``tab``/``flux_tab`` may be pre-built (or the sharded forest's
    duck-typed equivalents); ``vol`` overrides the per-block cell volume
    (the forest passes zeros on padding blocks) and ``pmask`` zeroes
    padding blocks after the mean shifts so they never re-enter the
    Krylov iteration."""
    from cup3d_tpu.grid.flux import build_flux_tables
    from cup3d_tpu.ops import krylov

    if tab is None:
        tab = grid.lab_tables(1)
    if flux_tab is None:
        flux_tab = build_flux_tables(grid)
    if vol is None:
        vol = jnp.asarray(
            (grid.h**3).reshape(grid.nb, 1, 1, 1), jnp.float32
        )
    vol_total = jnp.sum(vol) * grid.bs**3
    # square in f32 AFTER the dtype cast: bit-identical to the dynamic
    # builder's h_col * h_col (tests/test_bucketing equivalence)
    h_col = jnp.asarray(grid.h.reshape(grid.nb, 1, 1, 1), jnp.float32)
    h2 = h_col * h_col
    # corner block: the reference pins block .index == (0,0,0); in the
    # Hilbert-ordered forest that is the leaf covering the domain corner
    slot0 = int(
        np.lexsort(
            (grid.ijk[:, 2], grid.ijk[:, 1], grid.ijk[:, 0])
        )[0]
    ) if mean_constraint in (1, 3) else 0

    # AMR two-level preconditioner (the round-5 uniform win extended to
    # the forest): tile getZ at the block's own h plus a coarse
    # correction over the block face graph (krylov.BlockGraph).  Gated
    # exactly like the uniform path: pinned-row modes 1/3 would have
    # their removed nullspace reintroduced by the singular coarse solve
    # (ADVICE r5), and the sharded forest's _PaddedGeom carries no tree
    # (distributed coarse solve is future work — VALIDATION.md).
    use_two = (krylov.use_coarse_correction() if two_level is None
               else bool(two_level))
    graph = None
    if (use_two and mean_constraint not in (1, 3)
            and hasattr(grid, "tree")):
        graph = krylov.block_graph_tables(grid)

    def wmean(x):
        return jnp.sum(x * vol) / vol_total

    def M_of(t, ft):
        if graph is None:
            # per-block getZ with the block's own h^2 (poisson_kernels
            # getZ, main.cpp:14617-14746); blocks are already bs^3 tiles
            return lambda r: krylov.getz_blocks(-h2 * r,
                                                cg_iters=precond_iters)

        def M(r):
            # multiplicative two-level: coarse first, then the tile
            # solve on the coarse-corrected residual (the lanes-layout
            # scheme of make_twolevel_preconditioner_lanes, with the
            # analytic tile-face A zc replaced by one full refluxed
            # Laplacian — correct on any forest topology)
            zc = krylov.coarse_correct_blocks(r, vol, graph)
            zf = jnp.broadcast_to(
                zc[:, None, None, None], r.shape
            ).astype(r.dtype)
            r2 = r - laplacian_blocks(grid, zf, t, ft)
            return krylov.getz_blocks(-h2 * r2,
                                      cg_iters=precond_iters) + zf

        return M

    def A_of(t, ft):
        if mean_constraint == 1:
            return lambda x_: laplacian_blocks(grid, x_, t, ft).at[
                slot0, 0, 0, 0
            ].set(wmean(x_) * vol_total)
        if mean_constraint == 3:
            return lambda x_: laplacian_blocks(grid, x_, t, ft).at[
                slot0, 0, 0, 0
            ].set(x_[slot0, 0, 0, 0])
        return lambda x_: laplacian_blocks(grid, x_, t, ft)

    def solve(rhs, x0=None, tab_arg=None, flux_arg=None, rnorm_ref=None,
              with_stats=False):
        # callers under jit pass the tables as traced ARGUMENTS so they
        # are runtime buffers, not constants embedded in the lowered HLO
        # (see grid/blocks.py pytree registration); the builder's own
        # tables are the fallback for direct use
        t = tab if tab_arg is None else tab_arg
        ft = flux_tab if flux_arg is None else flux_arg
        if mean_constraint == 2:
            b = rhs - wmean(rhs)
        elif mean_constraint in (1, 3):
            # pinned row: its RHS is the pin target (0 = zero mean / p=0)
            b = rhs.at[slot0, 0, 0, 0].set(0.0)
        else:
            b = rhs
        if pmask is not None:
            b = b * pmask
        if rnorm_ref is None:
            # rel tolerance references the system's own RHS; warm-started
            # callers pass the cold RHS norm (see krylov.bicgstab)
            rnorm_ref = jnp.sqrt(jnp.sum(b * b, dtype=jnp.float32))
        x, rnorm, k = krylov.bicgstab(
            A_of(t, ft), b, M=M_of(t, ft), x0=x0,
            tol_abs=tol_abs, tol_rel=tol_rel, maxiter=maxiter,
            rnorm_ref=rnorm_ref,
        )
        if mean_constraint == 2:
            x = x - wmean(x)
        x = x * pmask if pmask is not None else x
        if with_stats:
            return x, krylov.solver_stats(rnorm, k)
        return x

    solve.supports_stats = True
    solve.maxiter = maxiter
    return solve


def build_amr_poisson_solver_dynamic(
    bs: int,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    precond_iters: int = 24,
    mean_constraint: int = 2,
):
    """The bucket-stable variant of build_amr_poisson_solver: EVERY
    topology-dependent quantity travels as a call argument, so one built
    solve function serves every regrid of a capacity bucket without
    retracing (sim/amr.py compiled-step cache).

    Per-call arguments: ``geom`` (a duck-typed grid whose ``h`` is a
    traced (nb,) array — sim/amr._ArgGeom), ``vol``/``pmask`` (padded
    (nb,1,1,1) cell volume / real-block mask, 0 on padding), optional
    ``graph`` (krylov.BlockGraph: enables the two-level preconditioner),
    and ``slot0`` (traced corner-block slot for the pinned-row modes —
    a dynamic index, so pin relocation across regrids never retraces).
    The math is identical to the static builder's.

    Under ``CUP3D_FUSED`` (precision.use_fused) the production pressure
    configuration — mean removal (mode 2) with the exact getZ — routes
    the iteration through the fused Pallas driver
    (ops/fused_amr_bicgstab.py): same A/M composition, intermediates
    fused into per-stage kernels with in-kernel dot partials, Krylov
    storage in ``precision.krylov_dtype()``.  Equivalence to the legacy
    composition is at matched residual targets, not bitwise (the
    reduction trees differ) — tests/test_fused_amr.py pins the bound.
    Pinned-row modes and the CUP3D_GETZ=cg ladder keep the legacy loop.
    """
    from cup3d_tpu.ops import krylov
    from cup3d_tpu.ops import precision as _precision

    # read the env knobs at build time, like build_iterative_solver:
    # tests rebuild the solver to flip paths, production builds once
    fused_on = (_precision.use_fused() and mean_constraint == 2
                and krylov.use_exact_getz())

    def solve(rhs, x0=None, tab_arg=None, flux_arg=None, rnorm_ref=None,
              geom=None, vol=None, pmask=None, graph=None, slot0=None,
              with_stats=False):
        t, ft = tab_arg, flux_arg
        h_col = jnp.reshape(
            jnp.asarray(geom.h, rhs.dtype), (geom.nb, 1, 1, 1)
        )
        h2 = h_col * h_col
        vol_total = jnp.sum(vol) * bs**3

        def wmean(x):
            return jnp.sum(x * vol) / vol_total

        if slot0 is None:
            slot0 = 0

        def A(x_):
            out = laplacian_blocks(geom, x_, t, ft)
            if mean_constraint == 1:
                out = out.at[slot0, 0, 0, 0].set(wmean(x_) * vol_total)
            elif mean_constraint == 3:
                out = out.at[slot0, 0, 0, 0].set(x_[slot0, 0, 0, 0])
            return out

        if graph is not None and mean_constraint not in (1, 3):
            def M(r):
                zc = krylov.coarse_correct_blocks(r, vol, graph)
                zf = jnp.broadcast_to(
                    zc[:, None, None, None], r.shape
                ).astype(r.dtype)
                r2 = r - laplacian_blocks(geom, zf, t, ft)
                return krylov.getz_blocks(-h2 * r2,
                                          cg_iters=precond_iters) + zf
        else:
            def M(r):
                return krylov.getz_blocks(-h2 * r,
                                          cg_iters=precond_iters)

        if mean_constraint == 2:
            b = rhs - wmean(rhs)
        elif mean_constraint in (1, 3):
            b = rhs.at[slot0, 0, 0, 0].set(0.0)
        else:
            b = rhs
        b = b * pmask if pmask is not None else b
        if rnorm_ref is None:
            rnorm_ref = jnp.sqrt(jnp.sum(b * b, dtype=jnp.float32))
        if fused_on:
            from cup3d_tpu.ops import fused_amr_bicgstab as _fused

            x, rnorm, k = _fused.fused_amr_bicgstab(
                geom, b, tab=t, ftab=ft, vol=vol, graph=graph,
                tol_abs=tol_abs, tol_rel=tol_rel, maxiter=maxiter,
                rnorm_ref=rnorm_ref, x0=x0,
                store_dtype=_precision.krylov_dtype(),
            )
        else:
            x, rnorm, k = krylov.bicgstab(
                A, b, M=M, x0=x0, tol_abs=tol_abs, tol_rel=tol_rel,
                maxiter=maxiter, rnorm_ref=rnorm_ref,
            )
        if mean_constraint == 2:
            x = x - wmean(x)
        x = x * pmask if pmask is not None else x
        if with_stats:
            return x, krylov.solver_stats(rnorm, k)
        return x

    solve.supports_stats = True
    solve.maxiter = maxiter
    return solve


# ---------------------------------------------------------------------------
# pressure projection on blocks (reference PressureProjection,
# main.cpp:15061-15160, kernels 14761-15056)
# ---------------------------------------------------------------------------


def div_fluxes(vlab: jnp.ndarray, w: int, bs: int) -> jnp.ndarray:
    """Outward per-unit-area *velocity* fluxes of the centered divergence:
    F(+a) = +(u_c + u_hi)/2 . e_a, F(-a) = -(u_c + u_lo)/2 . e_a, so that
    div = (1/h) sum_f F — the flux form the reflux tables expect."""
    fl = []
    for ax in range(3):
        u = vlab[..., ax]
        c = _sh(u, w, bs)
        lo = _sh(u, w, bs, *_off(ax, -1))
        hi = _sh(u, w, bs, *_off(ax, 1))
        sel_lo = [slice(None)] * 4
        sel_lo[ax + 1] = 0
        sel_hi = [slice(None)] * 4
        sel_hi[ax + 1] = bs - 1
        fl.append((-0.5 * (c + lo))[tuple(sel_lo)])
        fl.append((0.5 * (c + hi))[tuple(sel_hi)])
    return jnp.stack(fl, axis=1)


def pressure_rhs_blocks(
    grid: BlockGrid,
    vel: jnp.ndarray,
    dt,
    tab: LabTables,
    flux_tab: Optional[FluxTables] = None,
    chi: Optional[jnp.ndarray] = None,
    udef: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """rhs = div(u)/dt - chi div(u_def)/dt with conservative refluxing of
    the velocity fluxes (KernelPressureRHS, main.cpp:14761-14948)."""
    bs = grid.bs
    w = tab.width
    vlab = tab.assemble_vector(vel, bs)
    rhs = div_blocks(grid, vlab, w)
    if flux_tab is not None and flux_tab.ncorr:
        rhs = flux_tab.apply(rhs, div_fluxes(vlab, w, bs))
    if chi is not None and udef is not None:
        dlab = tab.assemble_vector(udef, bs)
        rhs = rhs - chi * div_blocks(grid, dlab, w)
    return rhs / dt


def solver_supports_stats(solver) -> bool:
    """True when ``solver`` (or the function under a ``partial``
    binding) advertises the ``with_stats`` return — the AMR front-ends
    built in this module do, the sharded forest's does not yet."""
    if getattr(solver, "supports_stats", False):
        return True
    return bool(getattr(getattr(solver, "func", None),
                        "supports_stats", False))


def project_blocks(
    grid: BlockGrid,
    vel: jnp.ndarray,
    dt,
    solver,
    tab: LabTables,
    flux_tab: Optional[FluxTables] = None,
    chi: Optional[jnp.ndarray] = None,
    udef: Optional[jnp.ndarray] = None,
    p_init: Optional[jnp.ndarray] = None,
    second_order: bool = False,
    with_stats: bool = False,
):
    """Solve lap p = rhs and correct u -= dt grad p.  Returns (u, p).

    ``p_init`` warm-starts the Krylov solve from the previous step's
    pressure.  With ``second_order`` the reference's 2nd-order-in-time form
    (main.cpp:15087-15100) is used instead: subtract lap(p_old) from the
    RHS, solve for the *increment*, and add p_old back — algebraically the
    same warm start, but matching the reference's residual bookkeeping.

    ``with_stats`` returns (u, p, stats) with stats the solver's (2,)
    [residual, iterations] vector (zeros when the solver cannot report —
    the forest path), so driver call signatures stay uniform.
    """
    bs = grid.bs
    rhs = pressure_rhs_blocks(grid, vel, dt, tab, flux_tab, chi, udef)
    # the warm/increment solves stop relative to the COLD system's RHS
    # norm, so a good start can only cut iterations (krylov.bicgstab)
    ref = jnp.sqrt(jnp.sum(rhs * rhs, dtype=jnp.float32))
    stats_kw = (
        {"with_stats": True}
        if with_stats and solver_supports_stats(solver) else {}
    )
    if second_order and p_init is not None:
        rhs = rhs - laplacian_blocks(grid, p_init, tab, flux_tab)
        out = solver(rhs, None, tab_arg=tab, flux_arg=flux_tab,
                     rnorm_ref=ref, **stats_kw)
        p, stats = out if stats_kw else (out, None)
        p = p_init + p
    else:
        out = solver(rhs, p_init, tab_arg=tab, flux_arg=flux_tab,
                     rnorm_ref=ref, **stats_kw)
        p, stats = out if stats_kw else (out, None)
    plab = tab.assemble_scalar(p, bs)
    gp = grad_blocks(grid, plab, tab.width)
    if with_stats:
        if stats is None:
            stats = jnp.zeros(2, jnp.float32)
        return vel - dt * gp, p, stats
    return vel - dt * gp, p


# ---------------------------------------------------------------------------
# refinement scores (ComputeVorticity + GradChiOnTmp tagging,
# main.cpp:8624-8745, 8540-8602)
# ---------------------------------------------------------------------------


def vorticity_score(grid: BlockGrid, vel: jnp.ndarray, tab: LabTables):
    """(nb,) max |curl u| per block — the reference's tag magnitude."""
    vlab = tab.assemble_vector(vel, grid.bs)
    om = curl_blocks(grid, vlab, tab.width)
    mag = jnp.sqrt(jnp.sum(om * om, axis=-1))
    return jnp.max(mag.reshape(grid.nb, -1), axis=-1)


def gradchi_mask(grid: BlockGrid, chi: jnp.ndarray, tab: LabTables):
    """(nb,) bool: block touches the body interface (0 < chi < 1 anywhere
    or grad chi != 0) -> force max refinement (GradChiOnTmp)."""
    clab = tab.assemble_scalar(chi, grid.bs)
    g = grad_blocks(grid, clab, tab.width)
    has_grad = jnp.max(jnp.sum(g * g, axis=-1).reshape(grid.nb, -1), axis=-1) > 0
    return has_grad


# ---------------------------------------------------------------------------
# forces + diagnostics on blocks (ComputeForces main.cpp:12250-12503,
# ComputeDissipation 10347-10447, ComputeDivergence 8789-8919)
# ---------------------------------------------------------------------------


def _vel_gradients(grid: BlockGrid, vlab: jnp.ndarray, w: int):
    """g[c][a] = d u_c / d x_a as (nb,bs,bs,bs) arrays."""
    bs = grid.bs
    inv2h = 0.5 / _hcol(grid, vlab.dtype)
    return [
        [
            (
                _sh(vlab[..., c], w, bs, *_off(a, 1))
                - _sh(vlab[..., c], w, bs, *_off(a, -1))
            )
            * inv2h
            for a in range(3)
        ]
        for c in range(3)
    ]


def force_integrals_blocks(
    grid: BlockGrid,
    tab: LabTables,
    xc: jnp.ndarray,
    chi: jnp.ndarray,
    p: jnp.ndarray,
    vel: jnp.ndarray,
    nu: float,
    cm: jnp.ndarray,
    ubody: jnp.ndarray,
    udef: Optional[jnp.ndarray] = None,
    vel_unit: Optional[jnp.ndarray] = None,
):
    """Surface tractions via the chi-gradient surface measure, per-block h.

    The block-forest counterpart of models.base.force_integrals: with n_hat
    the outward normal, grad(chi) = -n_hat * delta, so pressure and viscous
    tractions become volume reductions against grad(chi) (the dense-band
    formulation replacing the reference's 5h surface probing,
    main.cpp:12250-12494).  xc: (nb,bs,bs,bs,3) cell centers.
    """
    bs = grid.bs
    w = tab.width
    vol = _hcol(grid, vel.dtype) ** 3
    clab = tab.assemble_scalar(chi, bs)
    gchi = grad_blocks(grid, clab, w)  # points into the body
    vlab = tab.assemble_vector(vel, bs)
    g = _vel_gradients(grid, vlab, w)
    fpres = jnp.stack([jnp.sum(p * gchi[..., a] * vol) for a in range(3)])
    visc_tr = jnp.stack(
        [
            sum((g[c][a] + g[a][c]) * gchi[..., c] for c in range(3))
            for a in range(3)
        ],
        axis=-1,
    )
    fvisc = -nu * jnp.stack([jnp.sum(visc_tr[..., a] * vol) for a in range(3)])
    traction = p[..., None] * gchi - nu * visc_tr
    r = xc - cm
    torque = jnp.sum(jnp.cross(r, traction) * vol[..., None], axis=(0, 1, 2, 3))
    power = jnp.sum(traction * ubody * vol[..., None])
    from cup3d_tpu.ops.diagnostics import swim_split

    return {"pres_force": fpres, "visc_force": fvisc, "torque": torque,
            "power": power,
            **swim_split(traction, vol, udef, vel_unit)}


def divergence_norms_blocks(grid: BlockGrid, vel: jnp.ndarray, tab: LabTables):
    """(sum |div u| h^3, max |div u|) over the forest."""
    vlab = tab.assemble_vector(vel, grid.bs)
    d = div_blocks(grid, vlab, tab.width)
    vol = _hcol(grid, vel.dtype) ** 3
    return jnp.sum(jnp.abs(d) * vol), jnp.max(jnp.abs(d))


def dissipation_blocks(grid: BlockGrid, vel: jnp.ndarray, nu: float,
                       tab: LabTables):
    """Energy-budget integrals with per-block cell volume (KernelDissipation
    semantics, main.cpp:10347-10435)."""
    bs = grid.bs
    w = tab.width
    vol = _hcol(grid, vel.dtype) ** 3
    vlab = tab.assemble_vector(vel, bs)
    g = _vel_gradients(grid, vlab, w)
    ss = 0.0
    for c in range(3):
        for a in range(3):
            s = 0.5 * (g[c][a] + g[a][c])
            ss = ss + s * s
    om = curl_blocks(grid, vlab, w)
    return {
        "kinetic_energy": 0.5 * jnp.sum(jnp.sum(vel * vel, axis=-1) * vol),
        "enstrophy": 0.5 * jnp.sum(jnp.sum(om * om, axis=-1) * vol),
        "dissipation_rate": 2.0 * nu * jnp.sum(ss * vol),
    }
