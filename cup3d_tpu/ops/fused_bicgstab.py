"""Fused Pallas BiCGSTAB iteration for the lane-resident Poisson solve.

The legacy composition (krylov.bicgstab over make_laplacian_lanes +
tilesolve getZ) issues each Krylov iteration as ~a dozen separate XLA
ops, and every intermediate (p, y, v, s, z, t) round-trips HBM between
them — BENCH_r05 measured the iteration at 3.6% MFU / 37% of HBM peak
on fish128.  This module replaces the iteration body with five fused
``pallas_call`` stages over the lane-major ``(bs, bs, bs, T)`` layout,
each chaining what the legacy path split:

- ``update``  p/rhat recurrence + breakdown select + coarse tile-sums
- ``getz``    exact DST tile solve (+ the two-level coarse/face terms)
- ``lap``     cross-tile Laplacian apply + the iteration's dot partials
- ``axpy``    s = r - alpha v + coarse tile-sums
- ``finish``  x/r updates + the residual/rho dot partials

Global reductions never materialize a full-size temporary: each stage
emits **per-tile (lane) partials** ``(1, 1, 1, T)`` reduced over the
512 cells of its own tile, and a cheap follow-up ``jnp.sum`` (f32)
combines them into the iteration scalars.  The only full-array data
that crosses stages inside one iteration are the Krylov vectors
themselves (one read + one write each) and the 6 cross-tile neighbor
face planes (1/8 of a vector) assembled between the getz and lap
stages — tile interiors never leave VMEM between the Laplacian, the
preconditioner, and the axpys.

Mixed precision (ops/precision.py): Krylov vectors may be stored bf16;
every kernel loads to f32, accumulates dots / tile-solve matmuls /
tile-sums in f32 (matmuls at ``Precision.HIGHEST`` — a default-precision
bf16 preconditioner stalls the outer solve, ops/tilesolve.py), and
rounds back to the storage dtype only at the final store.  Partials are
computed on the *stored* (rounded) values so the reported residual norm
is the norm of the vector the next iteration actually sees.

Every stage has a pure-jnp twin (`*_math` helpers shared verbatim by
the kernel bodies), which is both the CPU execution path and the
reference the ``interpret=True`` parity tests check against
(tests/test_fused_bicgstab.py; the ``block_cg_tiles_fast`` pattern).
This supersedes ops/getz_pallas.py's standalone CG kernel on the fused
path: the getZ tile solve now runs *inside* the iteration stages (the
legacy module remains the CUP3D_GETZ=cg fallback and keeps the shared
``TILE_T``/``use_pallas`` plumbing).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from cup3d_tpu.ops import precision
from cup3d_tpu.ops.getz_pallas import TILE_T, use_pallas

_HI = jax.lax.Precision.HIGHEST
_F32 = jnp.float32


# ---------------------------------------------------------------------------
# shared stage math: the kernel bodies and the jnp twins run THIS code
# ---------------------------------------------------------------------------


def _azc_from_aux(aux: jnp.ndarray, bs: int) -> jnp.ndarray:
    """(8, T) coarse aux rows -> A zc in lanes layout.

    zc is constant per tile, so A zc is nonzero only on the 6 tile-face
    planes; aux rows 0..5 carry the per-face deltas
    (lo0, hi0, lo1, hi1, lo2, hi2 — krylov.make_face_deltas), row 6 the
    coarse values zc, row 7 padding.  Reconstruction is concatenation
    (face, zeros, face) per axis — no scatter, so it lowers in Mosaic."""
    T = aux.shape[-1]
    total = None
    for ax in range(3):
        shp_face = [bs, bs, bs, T]
        shp_face[ax] = 1
        shp_mid = [bs, bs, bs, T]
        shp_mid[ax] = bs - 2
        lo = jnp.broadcast_to(aux[2 * ax], tuple(shp_face))
        hi = jnp.broadcast_to(aux[2 * ax + 1], tuple(shp_face))
        mid = jnp.zeros(tuple(shp_mid), aux.dtype)
        part = jnp.concatenate([lo, mid, hi], axis=ax)
        total = part if total is None else total + part
    return total


def _cellsum(a: jnp.ndarray) -> jnp.ndarray:
    """Per-tile partial: reduce the 512 cells of each lane, keep lanes.
    The (1,1,1,T) result is what the cheap follow-up combine sums —
    identical per lane whether computed chunked (kernel grid) or whole
    (twin), which is what makes the interpret parity tests tight."""
    return jnp.sum(a.astype(_F32), axis=(0, 1, 2), keepdims=True)


def _update_math(r, p, v, rhat, beta, omega, broke, store):
    """p/rhat recurrence with the breakdown re-seed folded in."""
    r32, p32, v32 = (a.astype(_F32) for a in (r, p, v))
    # on rho breakdown the legacy path zeroes p/v and re-seeds rhat = r
    # (krylov.bicgstab body); the explicit zeroing (not just beta = 0)
    # keeps a non-finite p/v from leaking through 0 * inf
    p_eff = jnp.where(broke > 0.5, 0.0, p32)
    v_eff = jnp.where(broke > 0.5, 0.0, v32)
    p_new = r32 + beta * (p_eff - omega * v_eff)
    rhat_new = jnp.where(broke > 0.5, r32, rhat.astype(_F32))
    p_st = p_new.astype(store)
    rh_st = rhat_new.astype(store)
    return p_st, rh_st, _cellsum(p_st)


def _getz_math(w, aux, S3, lam, h2, bs, two_level, store):
    """Exact-getZ preconditioner application on a lanes chunk:
    y = zc + tilesolve(-h2 (w - A zc)) (two-level) or
    y = tilesolve(-h2 w) (tile-only).  Matmuls are f32 HIGHEST like
    ops/tilesolve.py — the quality floor for the outer iteration."""
    w32 = w.astype(_F32)
    if two_level:
        azc = _azc_from_aux(aux, bs)
        b = -h2 * (w32 - azc)
    else:
        b = -h2 * w32
    T = b.shape[-1]
    b2 = b.reshape(bs ** 3, T)
    t = jnp.dot(S3, b2, precision=_HI, preferred_element_type=_F32)
    t = t / lam  # (512, 1) eigenvalues broadcast over lanes
    z2 = jnp.dot(S3, t, precision=_HI, preferred_element_type=_F32)
    y = z2.reshape(b.shape)
    if two_level:
        y = y + aux[6]
    return y.astype(store)


def _lap_math(w, planes, a, inv_h2, store):
    """Cross-tile Laplacian apply + the iteration's dot partials.

    ``planes`` (6, bs, bs, T): cross-tile neighbor face planes
    (krylov.make_lane_planes), so the apply is pure intra-chunk
    slicing/concat.  Emits Aw plus per-tile partials of a . Aw and
    Aw . Aw (the second is free — Aw is already in registers)."""
    from cup3d_tpu.ops.stencils import laplacian_lanes_chunk

    aw = laplacian_lanes_chunk(
        w.astype(_F32), planes.astype(_F32), inv_h2
    ).astype(store)
    aw32 = aw.astype(_F32)
    d_a = _cellsum(a.astype(_F32) * aw32)
    d_self = _cellsum(aw32 * aw32)
    return aw, d_a, d_self


def _axpy_math(r, v, alpha, store):
    s = (r.astype(_F32) - alpha * v.astype(_F32)).astype(store)
    return s, _cellsum(s)


def _finish_math(x, y, z, s, t, rhat, alpha, omega, store):
    """x/r updates + the residual / next-rho partials.  x stays f32
    (the policy's wide accumulator over the narrow stored directions)."""
    y32, z32, s32, t32 = (a.astype(_F32) for a in (y, z, s, t))
    x_new = x + alpha * y32 + omega * z32
    r_st = (s32 - omega * t32).astype(store)
    r32 = r_st.astype(_F32)
    p_rr = _cellsum(r32 * r32)
    p_rhr = _cellsum(rhat.astype(_F32) * r32)
    return x_new, r_st, p_rr, p_rhr


# ---------------------------------------------------------------------------
# Pallas kernel bodies: load refs, run the shared math, store
# ---------------------------------------------------------------------------


def _k_update(r_ref, p_ref, v_ref, rhat_ref, sc_ref,
              pn_ref, rh_ref, ts_ref):
    beta, omega, broke = sc_ref[0, 0], sc_ref[0, 1], sc_ref[0, 2]
    p_new, rhat_new, ts = _update_math(
        r_ref[...], p_ref[...], v_ref[...], rhat_ref[...],
        beta, omega, broke, pn_ref.dtype,
    )
    pn_ref[...] = p_new
    rh_ref[...] = rhat_new
    ts_ref[...] = ts


def _k_getz_two(w_ref, S3_ref, lam_ref, aux_ref, y_ref, *, h2, bs):
    y_ref[...] = _getz_math(w_ref[...], aux_ref[...], S3_ref[...],
                            lam_ref[...], h2, bs, True, y_ref.dtype)


def _k_getz_tile(w_ref, S3_ref, lam_ref, y_ref, *, h2, bs):
    y_ref[...] = _getz_math(w_ref[...], None, S3_ref[...], lam_ref[...],
                            h2, bs, False, y_ref.dtype)


def _k_lap(w_ref, pl_ref, a_ref, aw_ref, da_ref, ds_ref, *, inv_h2):
    aw, d_a, d_self = _lap_math(w_ref[...], pl_ref[...], a_ref[...],
                                inv_h2, aw_ref.dtype)
    aw_ref[...] = aw
    da_ref[...] = d_a
    ds_ref[...] = d_self


def _k_axpy(r_ref, v_ref, sc_ref, s_ref, ts_ref):
    s, ts = _axpy_math(r_ref[...], v_ref[...], sc_ref[0, 0], s_ref.dtype)
    s_ref[...] = s
    ts_ref[...] = ts


def _k_finish(x_ref, y_ref, z_ref, s_ref, t_ref, rhat_ref, sc_ref,
              xo_ref, ro_ref, prr_ref, prh_ref):
    x_new, r_new, p_rr, p_rhr = _finish_math(
        x_ref[...], y_ref[...], z_ref[...], s_ref[...], t_ref[...],
        rhat_ref[...], sc_ref[0, 0], sc_ref[0, 1], ro_ref.dtype,
    )
    xo_ref[...] = x_new
    ro_ref[...] = r_new
    prr_ref[...] = p_rr
    prh_ref[...] = p_rhr


# ---------------------------------------------------------------------------
# stage dispatch: pallas_call (native or interpret) or the jnp twin
# ---------------------------------------------------------------------------


class _Stages(NamedTuple):
    """Static per-solve stage configuration (shapes, dtypes, dispatch)."""

    bs: int
    Tpad: int
    C: int
    store: object        # storage dtype for Krylov vectors
    h2: float
    inv_h2: float
    kernels: bool        # run pallas_call (native TPU or interpret)
    interpret: bool

    def _specs(self):
        from jax.experimental import pallas as pl

        bs, C = self.bs, self.C
        vec = pl.BlockSpec((bs, bs, bs, C), lambda i: (0, 0, 0, i))
        part = pl.BlockSpec((1, 1, 1, C), lambda i: (0, 0, 0, i))
        planes = pl.BlockSpec((6, bs, bs, C), lambda i: (0, 0, 0, i))
        aux = pl.BlockSpec((8, C), lambda i: (0, i))
        mat = pl.BlockSpec((bs ** 3, bs ** 3), lambda i: (0, 0))
        lam = pl.BlockSpec((bs ** 3, 1), lambda i: (0, 0))
        scal = pl.BlockSpec((1, 8), lambda i: (0, 0))
        return vec, part, planes, aux, mat, lam, scal

    @property
    def grid(self):
        return (self.Tpad // self.C,)

    def _shape(self, kind):
        bs, T = self.bs, self.Tpad
        if kind == "vec":
            return jax.ShapeDtypeStruct((bs, bs, bs, T), self.store)
        if kind == "vec32":
            return jax.ShapeDtypeStruct((bs, bs, bs, T), _F32)
        return jax.ShapeDtypeStruct((1, 1, 1, T), _F32)

    # -- stages -----------------------------------------------------------

    def update(self, r, p, v, rhat, scal):
        if not self.kernels:
            beta, omega, broke = scal[0, 0], scal[0, 1], scal[0, 2]
            return _update_math(r, p, v, rhat, beta, omega, broke,
                                self.store)
        from jax.experimental import pallas as pl

        vec, part, _, _, _, _, scs = self._specs()
        return pl.pallas_call(
            _k_update,
            grid=self.grid,
            in_specs=[vec, vec, vec, vec, scs],
            out_specs=[vec, vec, part],
            out_shape=[self._shape("vec"), self._shape("vec"),
                       self._shape("part")],
            # donate the carried p/rhat buffers into their updates
            input_output_aliases={1: 0, 3: 1},
            interpret=self.interpret,
        )(r, p, v, rhat, scal)

    def getz(self, w, aux, S3, lam):
        two = aux is not None
        if not self.kernels:
            return _getz_math(w, aux, S3, lam, self.h2, self.bs, two,
                              self.store)
        from jax.experimental import pallas as pl

        vec, _, _, auxs, mat, lams, _ = self._specs()
        if two:
            return pl.pallas_call(
                partial(_k_getz_two, h2=self.h2, bs=self.bs),
                grid=self.grid,
                in_specs=[vec, mat, lams, auxs],
                out_specs=vec,
                out_shape=self._shape("vec"),
                interpret=self.interpret,
            )(w, S3, lam, aux)
        return pl.pallas_call(
            partial(_k_getz_tile, h2=self.h2, bs=self.bs),
            grid=self.grid,
            in_specs=[vec, mat, lams],
            out_specs=vec,
            out_shape=self._shape("vec"),
            interpret=self.interpret,
        )(w, S3, lam)

    def lap(self, w, planes, a):
        if not self.kernels:
            return _lap_math(w, planes, a, self.inv_h2, self.store)
        from jax.experimental import pallas as pl

        vec, part, pls, _, _, _, _ = self._specs()
        return pl.pallas_call(
            partial(_k_lap, inv_h2=self.inv_h2),
            grid=self.grid,
            in_specs=[vec, pls, vec],
            out_specs=[vec, part, part],
            out_shape=[self._shape("vec"), self._shape("part"),
                       self._shape("part")],
            interpret=self.interpret,
        )(w, planes, a)

    def axpy(self, r, v, scal):
        if not self.kernels:
            return _axpy_math(r, v, scal[0, 0], self.store)
        from jax.experimental import pallas as pl

        vec, part, _, _, _, _, scs = self._specs()
        return pl.pallas_call(
            _k_axpy,
            grid=self.grid,
            in_specs=[vec, vec, scs],
            out_specs=[vec, part],
            out_shape=[self._shape("vec"), self._shape("part")],
            interpret=self.interpret,
        )(r, v, scal)

    def finish(self, x, y, z, s, t, rhat, scal):
        if not self.kernels:
            return _finish_math(x, y, z, s, t, rhat, scal[0, 0],
                                scal[0, 1], self.store)
        from jax.experimental import pallas as pl

        vec, part, _, _, _, _, scs = self._specs()
        return pl.pallas_call(
            _k_finish,
            grid=self.grid,
            in_specs=[vec, vec, vec, vec, vec, vec, scs],
            out_specs=[vec, vec, part, part],
            out_shape=[self._shape("vec32"), self._shape("vec"),
                       self._shape("part"), self._shape("part")],
            # donate x into x_new and the s buffer into r_new
            input_output_aliases={0: 0, 3: 1},
            interpret=self.interpret,
        )(x, y, z, s, t, rhat, scal)


def _scalars(*vals):
    """Pack iteration scalars into the (1, 8) f32 row the kernels read."""
    row = jnp.zeros((8,), _F32)
    row = row.at[: len(vals)].set(jnp.stack(
        [jnp.asarray(v, _F32) for v in vals]))
    return row.reshape(1, 8)


def _combine(part: jnp.ndarray) -> jnp.ndarray:
    """Per-tile partials -> global scalar (the cheap follow-up op)."""
    return jnp.sum(part, dtype=_F32)


# ---------------------------------------------------------------------------
# the fused solver driver
# ---------------------------------------------------------------------------


class _FusedState(NamedTuple):
    k: jnp.ndarray
    x: jnp.ndarray        # f32 accumulator
    r: jnp.ndarray        # storage dtype from here down
    rhat: jnp.ndarray
    p: jnp.ndarray
    v: jnp.ndarray
    rho: jnp.ndarray      # f32 scalars
    alpha: jnp.ndarray
    omega: jnp.ndarray
    rnorm: jnp.ndarray
    rho_dot: jnp.ndarray  # rhat . r, carried from the finish partials
    x_best: jnp.ndarray
    rnorm_best: jnp.ndarray


def fused_bicgstab(
    grid,
    b: jnp.ndarray,
    *,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    rnorm_ref=None,
    x0: Optional[jnp.ndarray] = None,
    bs: int = 8,
    two_level: bool = True,
    store_dtype=None,
    kernels: Optional[bool] = None,
    interpret: bool = False,
):
    """Fused-iteration preconditioned BiCGSTAB on the lanes layout.

    Same contract as ``krylov.bicgstab`` specialized to the production
    pressure system: A = the grid's 7-point Laplacian, M = the exact
    getZ tile solve (+ the exact Galerkin coarse level when
    ``two_level``).  ``b`` is the mean-removed rhs in lanes layout
    (f32); returns ``(x_best (f32 lanes), rnorm_best, iterations)``.

    ``kernels=None`` auto-selects pallas on TPU (getz_pallas.use_pallas)
    and the jnp twins elsewhere; ``interpret=True`` forces the kernels
    through the Pallas interpreter for the CPU parity tests.
    """
    from cup3d_tpu.ops import krylov, tilesolve

    store = precision.krylov_dtype() if store_dtype is None else store_dtype
    if kernels is None:
        kernels = use_pallas()
    if interpret:
        kernels = True

    T = b.shape[-1]
    C = min(TILE_T, T)
    Tpad = -(-T // C) * C
    h2 = float(grid.h * grid.h)
    st = _Stages(bs=bs, Tpad=Tpad, C=C, store=store, h2=h2,
                 inv_h2=1.0 / h2, kernels=kernels, interpret=interpret)

    S3, lam3, _ = tilesolve._basis(bs, "float32")
    lam = lam3.reshape(bs ** 3, 1)
    planes_fn = krylov.make_lane_planes(grid, bs)
    coarse_core = krylov._make_coarse_core(grid, bs) if two_level else None
    deltas_fn = krylov.make_face_deltas(grid, bs) if two_level else None

    def padT(a):
        if a.shape[-1] == Tpad:
            return a
        pad = [(0, 0)] * (a.ndim - 1) + [(0, Tpad - a.shape[-1])]
        return jnp.pad(a, pad)

    def planes(w):
        # rolls must see the REAL lane extent: build on [:T], re-pad.
        # Padded lanes keep zero planes, so they stay exactly zero
        # through every stage (their rhs/x0 are zero-padded).
        return padT(planes_fn(w[..., :T]))

    def coarse_aux(tsum):
        rc = tsum[0, 0, 0, :T]
        zc = coarse_core(rc)
        aux = jnp.concatenate(
            [deltas_fn(zc), zc[None, :], jnp.zeros((1, T), _F32)], axis=0
        )
        return padT(aux)

    b32 = padT(b.astype(_F32))
    x0_ = jnp.zeros_like(b32) if x0 is None else padT(x0.astype(_F32))
    A_init = krylov.make_laplacian_lanes(grid, bs)
    if x0 is None:
        r0 = b32  # A(0) == 0 exactly; skip the apply
    else:
        r0 = b32 - padT(A_init(x0.astype(_F32)))
    rr0 = krylov._dot(r0, r0)
    rnorm0 = jnp.sqrt(rr0)
    ref = rnorm0 if rnorm_ref is None else rnorm_ref
    target = jnp.maximum(tol_abs, tol_rel * ref)
    # eps in the ACCUMULATION dtype: 1e-30 underflows to 0 in bf16,
    # which would silently disable the breakdown re-seed (JX005 audit)
    eps = jnp.asarray(1e-30, _F32)
    one = jnp.asarray(1.0, _F32)

    r_st = r0.astype(store)
    init = _FusedState(
        k=jnp.asarray(0, jnp.int32),
        x=x0_,
        r=r_st,
        rhat=r_st,
        p=jnp.zeros_like(r_st),
        v=jnp.zeros_like(r_st),
        rho=one,
        alpha=one,
        omega=one,
        rnorm=rnorm0,
        rho_dot=rr0,
        x_best=x0_,
        rnorm_best=rnorm0,
    )

    def cond(s: _FusedState):
        return jnp.logical_and(s.k < maxiter, s.rnorm > target)

    def body(s: _FusedState):
        safe = krylov._safe
        rn2 = s.rnorm * s.rnorm
        broke = jnp.abs(s.rho_dot) < eps * jnp.maximum(rn2, 1.0)
        rho_new = jnp.where(broke, rn2, s.rho_dot)
        beta = (rho_new / safe(s.rho)) * (s.alpha / safe(s.omega))
        beta = jnp.where(broke, 0.0, beta)

        p, rhat, ts_p = st.update(
            s.r, s.p, s.v, s.rhat,
            _scalars(beta, s.omega, broke.astype(_F32)),
        )
        aux_p = coarse_aux(ts_p) if two_level else None
        y = st.getz(p, aux_p, S3, lam)
        v, d_rhv, _ = st.lap(y, planes(y), rhat)
        alpha = rho_new / safe(_combine(d_rhv))

        svec, ts_s = st.axpy(s.r, v, _scalars(alpha))
        aux_s = coarse_aux(ts_s) if two_level else None
        z = st.getz(svec, aux_s, S3, lam)
        t, d_ts, d_tt = st.lap(z, planes(z), svec)
        omega = _combine(d_ts) / safe(_combine(d_tt))

        x, r, p_rr, p_rhr = st.finish(s.x, y, z, svec, t, rhat,
                                      _scalars(alpha, omega))
        rnorm = jnp.sqrt(_combine(p_rr))
        better = rnorm < s.rnorm_best
        return _FusedState(
            k=s.k + 1, x=x, r=r, rhat=rhat, p=p, v=v,
            rho=rho_new, alpha=alpha, omega=omega, rnorm=rnorm,
            rho_dot=_combine(p_rhr),
            x_best=jnp.where(better, x, s.x_best),
            rnorm_best=jnp.minimum(rnorm, s.rnorm_best),
        )

    out = jax.lax.while_loop(cond, body, init)
    return out.x_best[..., :T], out.rnorm_best, out.k


# ---------------------------------------------------------------------------
# analytic traffic model + smoke test
# ---------------------------------------------------------------------------


def bytes_model(store_dtype=None, two_level: bool = True) -> dict:
    """Analytic HBM bytes per cell per fused iteration (reads + writes),
    by stage — the model bench.py reports next to the measured rate.

    e = storage bytes/cell (4 f32, 2 bf16); x stays 4 B.  Face planes
    count 6 * bs^2 / bs^3 = 0.75 e per pass.  Partials/aux are O(T) and
    ignored."""
    store = precision.krylov_dtype() if store_dtype is None else store_dtype
    e = jnp.dtype(store).itemsize
    per = {
        # r, p, v, rhat in; p, rhat out
        "update": 6 * e,
        # 2x (w in, y out)
        "getz": 2 * (2 * e),
        # 2x (planes glue: read 6 faces, write planes array)
        "planes": 2 * (2 * 0.75 * e),
        # 2x (w + planes + partner in, Aw out)
        "lap": 2 * ((2 + 0.75) * e + e),
        # r, v in; s out
        "axpy": 3 * e,
        # y, z, s, t, rhat in + x f32 in; x f32 + r out
        "finish": 5 * e + 4 + 4 + e,
        # best-x select: x_new, x_best in, x_best out (f32)
        "best_x": 12,
    }
    per["total"] = round(sum(per.values()), 2)
    return per


def legacy_bytes_model() -> float:
    """The unfused composition's per-cell-iteration bytes under the same
    counting rules: every intermediate round-trips HBM between ops.
    2 Laplacians (r+w each), 2 getZ (r+w), ~10 vector ops (2 passes),
    4 dots (1 read), all f32."""
    return 2 * 8.0 + 2 * 8.0 + 10 * 8.0 + 4 * 4.0


def harvest_costs(grid, b: jnp.ndarray, maxiter: int = 1,
                  name: str = "fused_bicgstab", **kwargs):
    """Compiler-counted cost row of one fixed-k fused-solve executable
    (round 19): AOT lower+compile a ``maxiter``-capped fused solve on
    ``b`` and harvest ``cost_analysis``/``memory_analysis`` through
    obs/costs.py.  XLA counts the while body once regardless of the
    cap, so the k=1 row IS setup + one iteration body — the compiler
    ground truth next to :func:`bytes_model`'s analytic count.
    Executes nothing; returns the row, or None where the backend
    cannot lower (counted, never raised)."""
    import jax

    from cup3d_tpu.obs import costs as obs_costs

    kw = dict(kwargs, tol_abs=0.0, tol_rel=0.0, maxiter=int(maxiter))
    jitted = jax.jit(lambda bb: fused_bicgstab(grid, bb, **kw)[0])
    return obs_costs.analyze_jitted(f"{name}_k{int(maxiter)}", jitted, b)


def selftest() -> None:
    """Interpret-mode kernel smoke: a 16^3 Poisson solve through the
    fused driver with interpret kernels must match the jnp-twin driver.
    Wired into tools/lint.sh so CI exercises the kernels without a TPU."""
    import numpy as np

    from cup3d_tpu.grid.uniform import BC, UniformGrid
    from cup3d_tpu.ops import krylov

    n = 16
    g = UniformGrid((n, n, n), (1.0,) * 3, (BC.periodic,) * 3)
    rng = np.random.default_rng(0)
    rhs = jnp.asarray(rng.standard_normal((n, n, n)), _F32)
    bt = krylov.to_lanes(rhs - jnp.mean(rhs))
    kw = dict(tol_abs=1e-6, tol_rel=1e-5, maxiter=40, two_level=True,
              store_dtype=_F32)
    x_twin, rn_twin, k_twin = fused_bicgstab(g, bt, kernels=False, **kw)
    x_kern, rn_kern, k_kern = fused_bicgstab(g, bt, interpret=True, **kw)
    assert int(k_twin) == int(k_kern), (int(k_twin), int(k_kern))
    scale = float(jnp.max(jnp.abs(x_twin))) or 1.0
    err = float(jnp.max(jnp.abs(x_twin - x_kern))) / scale
    assert err < 1e-5, err
    print(f"fused_bicgstab selftest: OK (iters={int(k_twin)}, "
          f"interpret-vs-twin rel err {err:.2e})")


if __name__ == "__main__":
    selftest()
