"""Fused Pallas BiCGSTAB iteration on the bucket-padded AMR block forest.

The AMR Poisson iteration was the slowest path in the repo (BENCH_r05
amr_tgv roofline: 0.2% MFU, 2.7% of HBM peak, ~17x worse per iteration
than the uniform path ops/fused_bicgstab.py fused): the legacy
composition (krylov.bicgstab over amr_ops.laplacian_blocks +
getz_blocks) issues each iteration as ~a dozen XLA ops and every
intermediate (p, y, v, s, z, t) round-trips HBM between them.  The
bucket-padded layout (PR 3) made the forest fixed-shape by
construction, so the fused-iteration design applies directly; this
module is its block-forest twin, with stages over ``(capacity, bs, bs,
bs)`` padded blocks:

- ``update``  p/rhat recurrence + breakdown select + the volume-
              weighted coarse restriction (per-block partials)
- ``getz``    exact DST tile solve at the block's own h^2 (+ the
              two-level coarse injection)
- ``lap``     7-point lab stencil x per-block 1/h^2 + the dense
              coarse-fine reflux increment + the iteration's dot
              partials
- ``axpy``    s = r - alpha v + coarse restriction partials
- ``finish``  x/r updates + the residual/rho dot partials

Global dots never materialize a full-size temporary: every stage emits
**per-block f32 partials** ``(capacity, 1)`` reduced over the bs^3
cells of its own block, and a cheap follow-up ``jnp.sum`` combines
them into the iteration scalars.

What stays OUTSIDE the kernels, by design: the halo gather (the
face-table lab assembly is data-dependent indexing — grid/faces.py
keeps it as jnp gathers) and the coarse-fine flux scatter, which is
precomputed per application as a DENSE per-cell increment
(``flux_tab.apply`` on a zero field) so the kernel's Laplacian stage
consumes only fixed-shape inputs.  The two-level coarse solve (a
(capacity,)-sized graph CG, krylov._cg_graph) also runs between
stages; its restriction input comes from the update/axpy stage
partials, so no extra full-field reduction pass exists.

Padding-block invariants (the ``inv_hc = 0`` contract from PR 3): the
padded face tables gather zeros into padding labs, padded flux rows
carry ``inv_hc = 0`` and scatter exactly 0.0 into the dump cell,
``vol = 0`` keeps padding rows out of every restriction/dot partial,
and the graph's padding rows have ``deg = 0`` so the coarse deflation
masks them.  Zero fields on padding blocks therefore stay exactly zero
through every stage — the selftest and tests/test_fused_amr.py assert
this.

Mixed precision follows ops/precision.py verbatim: Krylov vectors may
be stored bf16, every kernel loads to f32, dots/tile-solve matmuls
(``Precision.HIGHEST``) accumulate in f32, x stays f32.  Every stage
has a pure-jnp twin (the ``*_math`` helpers are shared verbatim by the
kernel bodies), which is the CPU execution path and the reference the
``interpret=True`` parity tests check against.

Dispatch: ``amr_ops.build_amr_poisson_solver_dynamic`` routes through
this driver under ``CUP3D_FUSED`` (precision.use_fused) for the
mean-removal constraint (mode 2) with the exact getZ — the production
pressure configuration; pinned-row modes and the CUP3D_GETZ=cg ladder
keep the legacy composition.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from cup3d_tpu.ops import precision
from cup3d_tpu.ops.fused_bicgstab import _combine, _scalars
from cup3d_tpu.ops.getz_pallas import use_pallas

_HI = jax.lax.Precision.HIGHEST
_F32 = jnp.float32

#: leading-axis chunk: padded blocks per kernel invocation.  64 blocks
#: of 8^3 f32 keep every stage's working set well under the ~16 MB VMEM
#: budget (the heaviest stage, getz, holds ~5 chunk-sized f32 arrays
#: plus the 512x512 basis: ~7.5 MB).
BLOCK_CHUNK = 64


# ---------------------------------------------------------------------------
# shared stage math: the kernel bodies and the jnp twins run THIS code
# ---------------------------------------------------------------------------


def _blocksum(a: jnp.ndarray) -> jnp.ndarray:
    """Per-block partial: reduce the bs^3 cells of each block, keep the
    block axis as (n, 1) f32.  Identical per block whether computed
    chunked (kernel grid) or whole (twin), which is what makes the
    interpret parity tests tight."""
    return jnp.sum(a.astype(_F32), axis=(1, 2, 3)).reshape(a.shape[0], 1)


def _update_math(r, p, v, rhat, vol, beta, omega, broke, store):
    """p/rhat recurrence with the breakdown re-seed folded in, plus the
    volume-weighted coarse restriction of the new search direction
    (coarse_correct_blocks' ``sum(r * vol)`` computed in-stage)."""
    r32, p32, v32 = (a.astype(_F32) for a in (r, p, v))
    p_eff = jnp.where(broke > 0.5, 0.0, p32)
    v_eff = jnp.where(broke > 0.5, 0.0, v32)
    p_new = r32 + beta * (p_eff - omega * v_eff)
    rhat_new = jnp.where(broke > 0.5, r32, rhat.astype(_F32))
    p_st = p_new.astype(store)
    rh_st = rhat_new.astype(store)
    return p_st, rh_st, _blocksum(p_st.astype(_F32) * vol)


def _getz_math(w, azf, zc, S3, lam, h2, bs, two_level, store):
    """Exact-getZ application on a block chunk at the block's own h^2:
    y = zc + tilesolve(-h2 (w - A zf)) (two-level; ``azf`` is the full
    refluxed Laplacian of the injected coarse correction, computed
    between stages — the analytic face-delta shortcut of the uniform
    kernel is not correct on a general forest, see
    amr_ops.build_amr_poisson_solver) or y = tilesolve(-h2 w)
    (tile-only).  Matmuls are f32 HIGHEST like ops/tilesolve.py — the
    quality floor for the outer iteration."""
    w32 = w.astype(_F32)
    if two_level:
        b = -h2 * (w32 - azf)
    else:
        b = -h2 * w32
    n = b.shape[0]
    b2 = b.reshape(n, bs ** 3)
    t = jnp.dot(b2, S3, precision=_HI, preferred_element_type=_F32)
    t = t / lam  # (1, 512) eigenvalue row broadcast over blocks
    z2 = jnp.dot(t, S3, precision=_HI, preferred_element_type=_F32)
    y = z2.reshape(b.shape)
    if two_level:
        y = y + zc  # constant coarse injection, (n, 1, 1, 1)
    return y.astype(store)


def _lap_math(lab, corr, a, inv_h2, bs, store):
    """Refluxed 7-point Laplacian on assembled labs + dot partials.

    ``lab`` (n, bs+2, bs+2, bs+2): the width-1 halo lab from the padded
    face tables (assembled between stages); ``corr`` the dense
    coarse-fine flux increment (0.0 everywhere the flux tables are
    inert, incl. every padding row by ``inv_hc = 0``).  Emits Aw plus
    per-block partials of a . Aw and Aw . Aw (the second is free — Aw
    is already in registers)."""
    lab32 = lab.astype(_F32)
    c = lab32[:, 1:bs + 1, 1:bs + 1, 1:bs + 1]
    s = -6.0 * c
    s = s + lab32[:, 2:bs + 2, 1:bs + 1, 1:bs + 1]
    s = s + lab32[:, 0:bs, 1:bs + 1, 1:bs + 1]
    s = s + lab32[:, 1:bs + 1, 2:bs + 2, 1:bs + 1]
    s = s + lab32[:, 1:bs + 1, 0:bs, 1:bs + 1]
    s = s + lab32[:, 1:bs + 1, 1:bs + 1, 2:bs + 2]
    s = s + lab32[:, 1:bs + 1, 1:bs + 1, 0:bs]
    aw = (s * inv_h2 + corr).astype(store)
    aw32 = aw.astype(_F32)
    d_a = _blocksum(a.astype(_F32) * aw32)
    d_self = _blocksum(aw32 * aw32)
    return aw, d_a, d_self


def _axpy_math(r, v, vol, alpha, store):
    s = (r.astype(_F32) - alpha * v.astype(_F32)).astype(store)
    return s, _blocksum(s.astype(_F32) * vol)


def _finish_math(x, y, z, s, t, rhat, alpha, omega, store):
    """x/r updates + the residual / next-rho partials.  x stays f32
    (the policy's wide accumulator over the narrow stored directions)."""
    y32, z32, s32, t32 = (a.astype(_F32) for a in (y, z, s, t))
    x_new = x + alpha * y32 + omega * z32
    r_st = (s32 - omega * t32).astype(store)
    r32 = r_st.astype(_F32)
    p_rr = _blocksum(r32 * r32)
    p_rhr = _blocksum(rhat.astype(_F32) * r32)
    return x_new, r_st, p_rr, p_rhr


# ---------------------------------------------------------------------------
# Pallas kernel bodies: load refs, run the shared math, store
# ---------------------------------------------------------------------------


def _k_update(r_ref, p_ref, v_ref, rhat_ref, vol_ref, sc_ref,
              pn_ref, rh_ref, ts_ref):
    beta, omega, broke = sc_ref[0, 0], sc_ref[0, 1], sc_ref[0, 2]
    p_new, rhat_new, ts = _update_math(
        r_ref[...], p_ref[...], v_ref[...], rhat_ref[...], vol_ref[...],
        beta, omega, broke, pn_ref.dtype,
    )
    pn_ref[...] = p_new
    rh_ref[...] = rhat_new
    ts_ref[...] = ts


def _k_getz_two(w_ref, azf_ref, zc_ref, h2_ref, S3_ref, lam_ref,
                y_ref, *, bs):
    y_ref[...] = _getz_math(w_ref[...], azf_ref[...], zc_ref[...],
                            S3_ref[...], lam_ref[...], h2_ref[...],
                            bs, True, y_ref.dtype)


def _k_getz_tile(w_ref, h2_ref, S3_ref, lam_ref, y_ref, *, bs):
    y_ref[...] = _getz_math(w_ref[...], None, None, S3_ref[...],
                            lam_ref[...], h2_ref[...], bs, False,
                            y_ref.dtype)


def _k_lap(lab_ref, corr_ref, a_ref, ih2_ref, aw_ref, da_ref, ds_ref,
           *, bs):
    aw, d_a, d_self = _lap_math(lab_ref[...], corr_ref[...], a_ref[...],
                                ih2_ref[...], bs, aw_ref.dtype)
    aw_ref[...] = aw
    da_ref[...] = d_a
    ds_ref[...] = d_self


def _k_axpy(r_ref, v_ref, vol_ref, sc_ref, s_ref, ts_ref):
    s, ts = _axpy_math(r_ref[...], v_ref[...], vol_ref[...],
                       sc_ref[0, 0], s_ref.dtype)
    s_ref[...] = s
    ts_ref[...] = ts


def _k_finish(x_ref, y_ref, z_ref, s_ref, t_ref, rhat_ref, sc_ref,
              xo_ref, ro_ref, prr_ref, prh_ref):
    x_new, r_new, p_rr, p_rhr = _finish_math(
        x_ref[...], y_ref[...], z_ref[...], s_ref[...], t_ref[...],
        rhat_ref[...], sc_ref[0, 0], sc_ref[0, 1], ro_ref.dtype,
    )
    xo_ref[...] = x_new
    ro_ref[...] = r_new
    prr_ref[...] = p_rr
    prh_ref[...] = p_rhr


# ---------------------------------------------------------------------------
# stage dispatch: pallas_call (native or interpret) or the jnp twin
# ---------------------------------------------------------------------------


class _Stages(NamedTuple):
    """Static per-solve stage configuration (shapes, dtypes, dispatch).

    Per-block geometry (h^2, 1/h^2, cell volume) rides as TRACED
    (npad, 1, 1, 1) column inputs — unlike the uniform _Stages' static
    floats — so one lowered stage serves every regrid of a capacity
    bucket (the sim/amr.py compiled-step cache contract)."""

    bs: int
    npad: int
    C: int
    store: object        # storage dtype for Krylov vectors
    kernels: bool        # run pallas_call (native TPU or interpret)
    interpret: bool

    def _specs(self):
        from jax.experimental import pallas as pl

        bs, C = self.bs, self.C
        L = bs + 2
        vec = pl.BlockSpec((C, bs, bs, bs), lambda i: (i, 0, 0, 0))
        col = pl.BlockSpec((C, 1, 1, 1), lambda i: (i, 0, 0, 0))
        labs = pl.BlockSpec((C, L, L, L), lambda i: (i, 0, 0, 0))
        part = pl.BlockSpec((C, 1), lambda i: (i, 0))
        mat = pl.BlockSpec((bs ** 3, bs ** 3), lambda i: (0, 0))
        lam = pl.BlockSpec((1, bs ** 3), lambda i: (0, 0))
        scal = pl.BlockSpec((1, 8), lambda i: (0, 0))
        return vec, col, labs, part, mat, lam, scal

    @property
    def grid(self):
        return (self.npad // self.C,)

    def _shape(self, kind):
        bs, n = self.bs, self.npad
        if kind == "vec":
            return jax.ShapeDtypeStruct((n, bs, bs, bs), self.store)
        if kind == "vec32":
            return jax.ShapeDtypeStruct((n, bs, bs, bs), _F32)
        return jax.ShapeDtypeStruct((n, 1), _F32)

    # -- stages -----------------------------------------------------------

    def update(self, r, p, v, rhat, vol, scal):
        if not self.kernels:
            beta, omega, broke = scal[0, 0], scal[0, 1], scal[0, 2]
            return _update_math(r, p, v, rhat, vol, beta, omega, broke,
                                self.store)
        from jax.experimental import pallas as pl

        vec, col, _, part, _, _, scs = self._specs()
        return pl.pallas_call(
            _k_update,
            grid=self.grid,
            in_specs=[vec, vec, vec, vec, col, scs],
            out_specs=[vec, vec, part],
            out_shape=[self._shape("vec"), self._shape("vec"),
                       self._shape("part")],
            # donate the carried p/rhat buffers into their updates
            input_output_aliases={1: 0, 3: 1},
            interpret=self.interpret,
        )(r, p, v, rhat, vol, scal)

    def getz(self, w, azf, zc, h2, S3, lam):
        two = azf is not None
        if not self.kernels:
            return _getz_math(w, azf, zc, S3, lam, h2, self.bs, two,
                              self.store)
        from jax.experimental import pallas as pl

        vec, col, _, _, mat, lams, _ = self._specs()
        if two:
            return pl.pallas_call(
                partial(_k_getz_two, bs=self.bs),
                grid=self.grid,
                in_specs=[vec, vec, col, col, mat, lams],
                out_specs=vec,
                out_shape=self._shape("vec"),
                interpret=self.interpret,
            )(w, azf, zc, h2, S3, lam)
        return pl.pallas_call(
            partial(_k_getz_tile, bs=self.bs),
            grid=self.grid,
            in_specs=[vec, col, mat, lams],
            out_specs=vec,
            out_shape=self._shape("vec"),
            interpret=self.interpret,
        )(w, h2, S3, lam)

    def lap(self, lab, corr, a, inv_h2):
        if not self.kernels:
            return _lap_math(lab, corr, a, inv_h2, self.bs, self.store)
        from jax.experimental import pallas as pl

        vec, col, labs, part, _, _, _ = self._specs()
        return pl.pallas_call(
            partial(_k_lap, bs=self.bs),
            grid=self.grid,
            in_specs=[labs, vec, vec, col],
            out_specs=[vec, part, part],
            out_shape=[self._shape("vec"), self._shape("part"),
                       self._shape("part")],
            interpret=self.interpret,
        )(lab, corr, a, inv_h2)

    def axpy(self, r, v, vol, scal):
        if not self.kernels:
            return _axpy_math(r, v, vol, scal[0, 0], self.store)
        from jax.experimental import pallas as pl

        vec, col, _, part, _, _, scs = self._specs()
        return pl.pallas_call(
            _k_axpy,
            grid=self.grid,
            in_specs=[vec, vec, col, scs],
            out_specs=[vec, part],
            out_shape=[self._shape("vec"), self._shape("part")],
            interpret=self.interpret,
        )(r, v, vol, scal)

    def finish(self, x, y, z, s, t, rhat, scal):
        if not self.kernels:
            return _finish_math(x, y, z, s, t, rhat, scal[0, 0],
                                scal[0, 1], self.store)
        from jax.experimental import pallas as pl

        vec, _, _, part, _, _, scs = self._specs()
        return pl.pallas_call(
            _k_finish,
            grid=self.grid,
            in_specs=[vec, vec, vec, vec, vec, vec, scs],
            out_specs=[vec, vec, part, part],
            out_shape=[self._shape("vec32"), self._shape("vec"),
                       self._shape("part"), self._shape("part")],
            # donate x into x_new and the s buffer into r_new
            input_output_aliases={0: 0, 3: 1},
            interpret=self.interpret,
        )(x, y, z, s, t, rhat, scal)


# ---------------------------------------------------------------------------
# the fused solver driver
# ---------------------------------------------------------------------------


class _FusedState(NamedTuple):
    k: jnp.ndarray
    x: jnp.ndarray        # f32 accumulator
    r: jnp.ndarray        # storage dtype from here down
    rhat: jnp.ndarray
    p: jnp.ndarray
    v: jnp.ndarray
    rho: jnp.ndarray      # f32 scalars
    alpha: jnp.ndarray
    omega: jnp.ndarray
    rnorm: jnp.ndarray
    rho_dot: jnp.ndarray  # rhat . r, carried from the finish partials
    x_best: jnp.ndarray
    rnorm_best: jnp.ndarray


def fused_amr_bicgstab(
    geom,
    b: jnp.ndarray,
    *,
    tab,
    ftab=None,
    vol: jnp.ndarray,
    graph=None,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    rnorm_ref=None,
    x0: Optional[jnp.ndarray] = None,
    store_dtype=None,
    kernels: Optional[bool] = None,
    interpret: bool = False,
):
    """Fused-iteration preconditioned BiCGSTAB on the padded forest.

    Same contract as the ``krylov.bicgstab`` call inside
    ``amr_ops.build_amr_poisson_solver_dynamic`` specialized to the
    production pressure system: A = the refluxed 7-point forest
    Laplacian (``tab``/``ftab``, PR 3 padded tables), M = the exact
    getZ tile solve at each block's own h (+ the block-graph coarse
    level when ``graph`` is given).  ``b`` is the mean-removed, masked
    rhs in blocks layout ``(geom.nb, bs, bs, bs)`` f32; ``vol`` the
    per-cell volume column (0 on padding blocks).  Returns
    ``(x (f32 blocks), rnorm_best, iterations)``.

    ``kernels=None`` auto-selects pallas on TPU (getz_pallas.use_pallas)
    and the jnp twins elsewhere; ``interpret=True`` forces the kernels
    through the Pallas interpreter for the CPU parity tests.
    """
    from cup3d_tpu.ops import amr_ops, krylov, tilesolve

    bs = int(geom.bs)
    nb = int(geom.nb)
    store = precision.krylov_dtype() if store_dtype is None else store_dtype
    if kernels is None:
        kernels = use_pallas()
    if interpret:
        kernels = True
    two_level = graph is not None
    if tab.width != 1:
        raise ValueError("fused AMR Laplacian needs width-1 lab tables")

    C = min(BLOCK_CHUNK, nb)
    npad = -(-nb // C) * C
    st = _Stages(bs=bs, npad=npad, C=C, store=store, kernels=kernels,
                 interpret=interpret)

    def padN(a):
        if a.shape[0] == npad:
            return a
        pad = [(0, npad - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, pad)

    # per-block geometry columns (traced; padding blocks carry h = 1 by
    # the bucket invariant, and the chunk-alignment rows added here are
    # zero — their fields stay identically zero through every stage)
    h_col = jnp.reshape(jnp.asarray(geom.h, _F32), (nb, 1, 1, 1))
    inv_h = 1.0 / h_col
    h2_col = padN(h_col * h_col)
    inv_h2_col = padN(inv_h * inv_h)
    vol_col = padN(jnp.asarray(vol, _F32))

    S3, lam3, _ = tilesolve._basis(bs, "float32")
    lam = lam3.reshape(1, bs ** 3)

    if two_level:
        # the coarse solve of coarse_correct_blocks with the restriction
        # already computed by the update/axpy stage partials
        m = (graph.deg > 0).astype(graph.w.dtype)
        nreal = jnp.maximum(jnp.sum(m), 1.0)

        def _deflate(vv):
            return (vv - jnp.sum(vv * m) / nreal) * m

        def _C(z):
            return graph.deg * z - jnp.sum(z[graph.idx] * graph.w,
                                           axis=-1)

        def coarse_aux(tsum):
            rc = tsum[:nb, 0].astype(graph.w.dtype)
            zc = (-_deflate(krylov._cg_graph(_C, _deflate(rc), 32))
                  ).astype(_F32)
            zf = jnp.broadcast_to(zc[:, None, None, None],
                                  (nb, bs, bs, bs))
            # full refluxed A zf between stages: correct on any forest
            # topology (amr_ops.build_amr_poisson_solver docstring)
            azf = amr_ops.laplacian_blocks(geom, zf, tab, ftab)
            return padN(azf.astype(_F32)), padN(zc.reshape(nb, 1, 1, 1))
    else:
        def coarse_aux(tsum):
            return None, None

    def lab_corr(w_st):
        """Assemble the width-1 halo lab of a Krylov direction and the
        dense coarse-fine reflux increment — the two data-dependent-
        indexing pieces of A the kernels consume as fixed-shape inputs."""
        w32 = w_st[:nb].astype(_F32)
        lab = tab.assemble_scalar(w32, bs)
        if ftab is not None and ftab.ncorr:
            fl = amr_ops.face_fluxes(lab, tab.width, bs, inv_h)
            corr = ftab.apply(jnp.zeros((nb, bs, bs, bs), _F32), fl)
        else:
            corr = jnp.zeros((nb, bs, bs, bs), _F32)
        return padN(lab.astype(_F32)), padN(corr)

    b32 = padN(jnp.asarray(b, _F32))
    if x0 is None:
        x0_ = jnp.zeros_like(b32)
        r0 = b32  # A(0) == 0 exactly; skip the apply
    else:
        x0_ = padN(jnp.asarray(x0, _F32))
        r0 = b32 - padN(amr_ops.laplacian_blocks(
            geom, jnp.asarray(x0, _F32), tab, ftab))
    rr0 = krylov._dot(r0, r0)
    rnorm0 = jnp.sqrt(rr0)
    ref = rnorm0 if rnorm_ref is None else rnorm_ref
    target = jnp.maximum(tol_abs, tol_rel * ref)
    # eps in the ACCUMULATION dtype (see ops/fused_bicgstab.py)
    eps = jnp.asarray(1e-30, _F32)
    one = jnp.asarray(1.0, _F32)

    r_st = r0.astype(store)
    init = _FusedState(
        k=jnp.asarray(0, jnp.int32),
        x=x0_,
        r=r_st,
        rhat=r_st,
        p=jnp.zeros_like(r_st),
        v=jnp.zeros_like(r_st),
        rho=one,
        alpha=one,
        omega=one,
        rnorm=rnorm0,
        rho_dot=rr0,
        x_best=x0_,
        rnorm_best=rnorm0,
    )

    def cond(s: _FusedState):
        return jnp.logical_and(s.k < maxiter, s.rnorm > target)

    def body(s: _FusedState):
        safe = krylov._safe
        rn2 = s.rnorm * s.rnorm
        broke = jnp.abs(s.rho_dot) < eps * jnp.maximum(rn2, 1.0)
        rho_new = jnp.where(broke, rn2, s.rho_dot)
        beta = (rho_new / safe(s.rho)) * (s.alpha / safe(s.omega))
        beta = jnp.where(broke, 0.0, beta)

        p, rhat, ts_p = st.update(
            s.r, s.p, s.v, s.rhat, vol_col,
            _scalars(beta, s.omega, broke.astype(_F32)),
        )
        azf_p, zc_p = coarse_aux(ts_p)
        y = st.getz(p, azf_p, zc_p, h2_col, S3, lam)
        lab_y, corr_y = lab_corr(y)
        v, d_rhv, _ = st.lap(lab_y, corr_y, rhat, inv_h2_col)
        alpha = rho_new / safe(_combine(d_rhv))

        svec, ts_s = st.axpy(s.r, v, vol_col, _scalars(alpha))
        azf_s, zc_s = coarse_aux(ts_s)
        z = st.getz(svec, azf_s, zc_s, h2_col, S3, lam)
        lab_z, corr_z = lab_corr(z)
        t, d_ts, d_tt = st.lap(lab_z, corr_z, svec, inv_h2_col)
        omega = _combine(d_ts) / safe(_combine(d_tt))

        x, r, p_rr, p_rhr = st.finish(s.x, y, z, svec, t, rhat,
                                      _scalars(alpha, omega))
        rnorm = jnp.sqrt(_combine(p_rr))
        better = rnorm < s.rnorm_best
        return _FusedState(
            k=s.k + 1, x=x, r=r, rhat=rhat, p=p, v=v,
            rho=rho_new, alpha=alpha, omega=omega, rnorm=rnorm,
            rho_dot=_combine(p_rhr),
            x_best=jnp.where(better, x, s.x_best),
            rnorm_best=jnp.minimum(rnorm, s.rnorm_best),
        )

    out = jax.lax.while_loop(cond, body, init)
    return out.x_best[:nb], out.rnorm_best, out.k


# ---------------------------------------------------------------------------
# analytic traffic model + smoke test
# ---------------------------------------------------------------------------


def bytes_model(store_dtype=None, two_level: bool = True) -> dict:
    """Analytic HBM bytes per cell per fused AMR iteration (reads +
    writes), by stage — the model bench.py reports next to the measured
    rate.  e = storage bytes/cell; labs cost (bs+2)^3/bs^3 ~ 1.95 f32
    reads per cell per apply and the dense reflux increment one more;
    per-block columns/partials are O(nb) and ignored."""
    store = precision.krylov_dtype() if store_dtype is None else store_dtype
    e = jnp.dtype(store).itemsize
    lab = float((8 + 2) ** 3) / 8 ** 3  # width-1 halo amplification
    per = {
        # r, p, v, rhat in; p, rhat out
        "update": 6 * e,
        # 2x (w + azf in, y out)
        "getz": 2 * (e + 4 + e),
        # 2x (lab assemble: read w, write lab; corr: read lab, write)
        "assemble": 2 * ((e + lab * 4) + (lab * 4 + 4)),
        # 2x (lab + corr + partner in, Aw out)
        "lap": 2 * ((lab * 4 + 4 + e) + e),
        # coarse zf Laplacian between stages: lab round trip again
        "coarse_azf": 2 * (4 + lab * 4 + 4) if two_level else 0.0,
        # r, v in; s out
        "axpy": 3 * e,
        # y, z, s, t, rhat in + x f32 in; x f32 + r out
        "finish": 5 * e + 4 + 4 + e,
        # best-x select: x_new, x_best in, x_best out (f32)
        "best_x": 12,
    }
    per["total"] = round(sum(per.values()), 2)
    return per


def legacy_bytes_model(two_level: bool = True) -> float:
    """The unfused AMR composition under the same counting rules: every
    intermediate round-trips HBM between ops — 2 refluxed Laplacians
    (lab assemble + stencil + corr), 2 getZ tile solves, the two-level
    r2 Laplacians, ~10 vector ops, 4 dots, all f32."""
    lab = float((8 + 2) ** 3) / 8 ** 3
    lap = (4 + lab * 4 + 4) + (lab * 4 + 4 + 4)  # assemble + apply
    n_lap = 4 if two_level else 2
    return n_lap * lap + 2 * 8.0 + 10 * 8.0 + 4 * 4.0


def selftest() -> None:
    """Interpret-mode kernel smoke on a PADDED two-level forest: the
    fused driver with interpret kernels must match the jnp-twin driver
    iteration-for-iteration, and padding blocks must stay exactly zero.
    Wired into tools/lint.sh so CI exercises the kernels without a TPU."""
    import numpy as np

    from cup3d_tpu.grid import bucket as bk
    from cup3d_tpu.grid.blocks import BlockGrid
    from cup3d_tpu.grid.faces import pad_face_tables
    from cup3d_tpu.grid.flux import build_flux_tables, pad_flux_tables
    from cup3d_tpu.grid.octree import Octree, TreeConfig
    from cup3d_tpu.grid.uniform import BC
    from cup3d_tpu.ops import krylov

    tree = Octree(TreeConfig((2, 2, 2), 2, (True,) * 3), 0)
    tree.refine(sorted(tree.leaves)[0])
    g = BlockGrid(tree, (1.0,) * 3, (BC.periodic,) * 3, 8)
    cap = bk.capacity(g.nb)
    tab = pad_face_tables(g.face_tables(1), g, cap)
    ftab = pad_flux_tables(build_flux_tables(g), g.bs, cap)
    graph = krylov.block_graph_tables(g, cap=cap)
    h = np.ones(cap)
    h[: g.nb] = g.h
    vol = np.zeros((cap, 1, 1, 1), np.float32)
    vol[: g.nb, 0, 0, 0] = g.h ** 3

    class _Geom:
        pass

    geom = _Geom()
    geom.bs, geom.nb, geom.extent = g.bs, cap, g.extent
    geom.h = jnp.asarray(h, jnp.float32)
    jvol = jnp.asarray(vol)

    rng = np.random.default_rng(0)
    rhs = np.zeros((cap, 8, 8, 8), np.float32)
    rhs[: g.nb] = rng.standard_normal((g.nb, 8, 8, 8))
    rhs = jnp.asarray(rhs)
    b = rhs - jnp.sum(rhs * jvol) / (jnp.sum(jvol) * g.bs ** 3)
    mask = jnp.asarray((vol > 0).astype(np.float32))
    b = b * mask
    kw = dict(tab=tab, ftab=ftab, vol=jvol, graph=graph, tol_abs=1e-8,
              tol_rel=1e-5, maxiter=60, store_dtype=_F32,
              rnorm_ref=jnp.sqrt(jnp.sum(b * b)))
    x_twin, rn_twin, k_twin = fused_amr_bicgstab(geom, b, kernels=False,
                                                 **kw)
    x_kern, rn_kern, k_kern = fused_amr_bicgstab(geom, b,
                                                 interpret=True, **kw)
    assert int(k_twin) == int(k_kern), (int(k_twin), int(k_kern))
    scale = float(jnp.max(jnp.abs(x_twin))) or 1.0
    err = float(jnp.max(jnp.abs(x_twin - x_kern))) / scale
    assert err < 1e-5, err
    pad_max = float(jnp.max(jnp.abs(x_twin[g.nb:])))
    assert pad_max == 0.0, pad_max
    # bf16 storage smoke through the same twin: the narrow-storage
    # iteration has a quality floor well above the f32 target (the
    # uniform driver gates it the same way) — require 3 digits relative
    bnorm = float(jnp.sqrt(jnp.sum(b * b)))
    xb, rnb, kb = fused_amr_bicgstab(geom, b, kernels=False,
                                     **{**kw, "store_dtype": jnp.bfloat16})
    assert float(rnb) <= 1e-3 * bnorm, (float(rnb), bnorm)
    print(f"fused_amr_bicgstab selftest: OK (iters={int(k_twin)}, "
          f"interpret-vs-twin rel err {err:.2e}, padding max 0.0, "
          f"bf16 iters={int(kb)})")


if __name__ == "__main__":
    selftest()
