"""Mixed-precision policy for the Krylov hot loop (round 12).

The pressure-Poisson BiCGSTAB iteration is bandwidth-bound (BENCH_r05:
37% of HBM peak at 128^3, 19% at 256^3), so halving the bytes of the
Krylov *storage* is worth more than any further flop work.  The policy
split is storage-vs-accumulation, not a blanket dtype:

- **Krylov vectors** (r, rhat, p, v and the per-iteration y, z, s, t)
  may be stored bf16: they only feed short-recurrence updates whose
  error the outer iteration contracts away.
- **All accumulations stay f32**: global dot products / residual norms
  (a bf16 sum over 2M cells loses ~3 digits and corrupts alpha/omega),
  the getZ tile-solve matmuls (a default-precision bf16 preconditioner
  measurably stalls the outer solve: 133+ vs 50 iterations,
  ops/tilesolve.py), and the coarse-level einsums.
- **rhs and solution stay f32**: x accumulates alpha*y + omega*z over
  O(10) iterations; keeping the accumulator wide is what lets the
  stored directions be narrow.

``CUP3D_KRYLOV_DTYPE`` selects the storage dtype (``f32`` default —
bitwise-identical to the pre-round-12 solver — or ``bf16``).  bf16
storage runs through the fused iteration driver
(ops/fused_bicgstab.py), which is where the cast discipline lives;
``CUP3D_FUSED`` controls that driver independently (``auto`` = fused
iff bf16, ``1`` = fused even at f32, ``0`` = legacy-only, which makes
a bf16 request a loud build-time error instead of a silent downgrade).

Lint rule JX011 (analysis/rules.py) machine-checks the accumulation
half of this contract across ``cup3d_tpu/ops``: a reduction over bf16
operands without an explicit f32 accumulator is a finding.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

#: env knob -> storage dtype for Krylov vectors
_DTYPES = {
    "": jnp.float32,
    "f32": jnp.float32,
    "float32": jnp.float32,
    "bf16": jnp.bfloat16,
    "bfloat16": jnp.bfloat16,
}


def krylov_dtype():
    """Storage dtype for Krylov vectors (CUP3D_KRYLOV_DTYPE; f32 default).

    Read per call like the other env knobs (use_exact_getz,
    use_coarse_correction) so tests and the resilience ladder can flip
    it without touching process-global state.
    """
    key = os.environ.get("CUP3D_KRYLOV_DTYPE", "").strip().lower()
    try:
        return _DTYPES[key]
    except KeyError:
        raise ValueError(
            f"CUP3D_KRYLOV_DTYPE={key!r}: expected one of "
            f"{sorted(k for k in _DTYPES if k)}"
        ) from None


def accum_dtype(dtype):
    """Accumulation dtype for reductions over ``dtype`` values: at least
    f32 (bf16 -> f32; f32/f64 pass through, keeping f64 solves exact)."""
    return jnp.promote_types(dtype, jnp.float32)


def use_fused() -> bool:
    """Whether build_iterative_solver routes through the fused
    per-iteration driver (ops/fused_bicgstab.py).

    CUP3D_FUSED: ``auto`` (default) = fused iff the storage dtype is
    bf16, so the stock f32 config stays bitwise-identical to the
    pre-round-12 solver; ``1`` forces the fused driver at f32 (for the
    bench side-by-side); ``0`` forces the legacy composition.
    """
    v = os.environ.get("CUP3D_FUSED", "auto").strip().lower()
    if v in ("1", "true", "yes"):
        return True
    if v in ("0", "false", "no"):
        return False
    return krylov_dtype() == jnp.bfloat16


def check_policy(mean_constraint: int = 2) -> None:
    """Build-time validation of the knob combination: a bf16 request the
    configuration cannot honor raises instead of silently downgrading."""
    if krylov_dtype() == jnp.bfloat16 and not use_fused():
        raise ValueError(
            "CUP3D_KRYLOV_DTYPE=bf16 requires the fused iteration driver "
            "(its cast discipline keeps accumulations f32); unset "
            "CUP3D_FUSED=0 or use f32 storage"
        )
