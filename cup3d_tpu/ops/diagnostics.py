"""Diagnostic kernels: vorticity, Q-criterion, divergence, dissipation,
max-velocity — the reference's diagnostics operators (ComputeVorticity
main.cpp:8624-8745, ComputeQcriterion 8746-8788, ComputeDivergence
8789-8919, KernelDissipation 10347-10435, findMaxU 8603-8623) as fused
dense reductions.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops import stencils as st


def vorticity(grid: UniformGrid, u: jnp.ndarray) -> jnp.ndarray:
    return st.curl(grid.pad_vector(u, 1), 1, grid.h)


def q_criterion(grid: UniformGrid, u: jnp.ndarray) -> jnp.ndarray:
    """Q = 0.5 (|Omega|^2 - |S|^2), positive in vortex cores."""
    up = grid.pad_vector(u, 1)
    h = grid.h
    g = [[st.d1_central(up[..., c], 1, a, h) for a in range(3)] for c in range(3)]
    omega2 = jnp.zeros_like(g[0][0])
    s2 = jnp.zeros_like(g[0][0])
    for c in range(3):
        for a in range(3):
            s = 0.5 * (g[c][a] + g[a][c])
            o = 0.5 * (g[c][a] - g[a][c])
            s2 = s2 + s * s
            omega2 = omega2 + o * o
    return 0.5 * (omega2 - s2)


def divergence_field(grid: UniformGrid, u: jnp.ndarray) -> jnp.ndarray:
    return st.divergence(grid.pad_vector(u, 1), 1, grid.h)


def divergence_norms(grid: UniformGrid, u: jnp.ndarray):
    """(sum |div u| h^3, max |div u|) — the reference appends the former to
    div.txt every call (main.cpp:8911-8917)."""
    d = divergence_field(grid, u)
    vol = grid.h ** 3
    return jnp.sum(jnp.abs(d)) * vol, jnp.max(jnp.abs(d))


def fluid_divergence_max(grid: UniformGrid, u: jnp.ndarray,
                         chi: jnp.ndarray, halo: int = 3) -> jnp.ndarray:
    """max |div u| over cells at least ``halo`` cells away from the
    mollified chi band.  Inside the band the Brinkman forcing is a
    momentum source, so the projected field is legitimately not
    divergence-free there (the reference behaves the same); this is the
    meaningful incompressibility gate for flows with immersed bodies.

    "Away" is Chebyshev distance: the mask is dilated per axis in sequence
    (box dilation), wrapping only across periodic boundaries."""
    from cup3d_tpu.grid.uniform import BC

    def shift(m, sh, ax):
        if grid.bc[ax] == BC.periodic:
            return jnp.roll(m, sh, axis=ax)
        z = jnp.zeros_like(m)
        if sh > 0:
            src = jax.lax.slice_in_dim(m, 0, m.shape[ax] - sh, axis=ax)
            return jax.lax.dynamic_update_slice_in_dim(z, src, sh, axis=ax)
        src = jax.lax.slice_in_dim(m, -sh, m.shape[ax], axis=ax)
        return jax.lax.dynamic_update_slice_in_dim(z, src, 0, axis=ax)

    grow = chi > 1e-6
    for ax in range(3):  # sequential per-axis dilation = full box dilation
        g = grow
        for sh in range(1, halo + 1):
            g = g | shift(grow, sh, ax) | shift(grow, -sh, ax)
        grow = g
    d = divergence_field(grid, u)
    return jnp.max(jnp.abs(jnp.where(grow, 0.0, d)))


def fluid_divergence_max_blocks(grid, vel, chi, tab):
    """Block-forest twin of fluid_divergence_max: max |div u| over blocks
    whose chi halo'd lab vanishes everywhere — block granularity plus the
    ghost halo gives at least a stencil-width separation from the band."""
    from cup3d_tpu.ops import amr_ops

    vlab = tab.assemble_vector(vel, grid.bs)
    d = amr_ops.div_blocks(grid, vlab, tab.width)
    clab = tab.assemble_scalar(chi, grid.bs)
    fluid = jnp.max(clab.reshape(grid.nb, -1), axis=1) < 1e-6
    return jnp.max(
        jnp.where(fluid[:, None, None, None], jnp.abs(d), 0.0)
    )


def max_velocity(u: jnp.ndarray, uinf: jnp.ndarray) -> jnp.ndarray:
    """max over cells of max-norm of lab-frame velocity (findMaxU)."""
    return jnp.max(jnp.abs(u + uinf))


def dissipation(grid: UniformGrid, u: jnp.ndarray, nu: float) -> Dict[str, jnp.ndarray]:
    """Energy-budget integrals (KernelDissipation semantics):

    kinetic energy  0.5 |u|^2, enstrophy 0.5 |omega|^2, viscous dissipation
    rate 2 nu S:S — each integrated over the domain with cell volume h^3.
    """
    up = grid.pad_vector(u, 1)
    h = grid.h
    g = [[st.d1_central(up[..., c], 1, a, h) for a in range(3)] for c in range(3)]
    ss = jnp.zeros_like(g[0][0])
    for c in range(3):
        for a in range(3):
            s = 0.5 * (g[c][a] + g[a][c])
            ss = ss + s * s
    w = st.curl(up, 1, h)
    vol = h ** 3
    return {
        "kinetic_energy": 0.5 * jnp.sum(jnp.sum(u * u, axis=-1)) * vol,
        "enstrophy": 0.5 * jnp.sum(jnp.sum(w * w, axis=-1)) * vol,
        "dissipation_rate": 2.0 * nu * jnp.sum(ss) * vol,
    }


def swim_split(traction, vol, udef, vel_unit):
    """thrust/drag/def_power from a per-cell traction band (reference
    per-surface-point split, main.cpp:12476-12485): forcePar is the
    traction component along the swimming direction; thrust sums its
    positive part, drag its negative part, def_power is traction . u_def.
    Layout-agnostic (dense uniform or block batch); vol broadcasts."""
    if vel_unit is None:
        z = jnp.zeros((), traction.dtype)
        return {"thrust": z, "drag": z, "def_power": z}
    force_par = jnp.einsum("...c,c->...", traction, vel_unit)
    thrust = jnp.sum(jnp.maximum(force_par, 0.0) * vol)
    drag = -jnp.sum(jnp.minimum(force_par, 0.0) * vol)
    if udef is None:
        def_power = jnp.zeros((), traction.dtype)
    else:
        def_power = jnp.sum(jnp.sum(traction * udef, axis=-1) * vol)
    return {"thrust": thrust, "drag": drag, "def_power": def_power}
