"""Signed-distance -> characteristic function chi and surface measure.

The reference converts each obstacle's SDF into a mollified Heaviside chi and
extracts surface points with gradients and delta weights
(KernelCharacteristicFunction, main.cpp:13291-13404, Towers-style).  The TPU
formulation works on dense fields: chi is a C^1 smoothed Heaviside of the SDF
over a 2h mollification band, and the surface delta is |grad chi| — every
surface integral becomes a fused masked reduction instead of ragged
per-block point lists.

Convention: sdf > 0 inside the body (matching the reference's rasterizer).
"""

from __future__ import annotations

import jax.numpy as jnp

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops import stencils as st


def heaviside(sdf: jnp.ndarray, h: float) -> jnp.ndarray:
    """C^1 mollified Heaviside over the band |sdf| <= 2h:
    chi = (1 + t + sin(pi t)/pi) / 2 with t = clip(sdf/2h, -1, 1).

    Fallback used where no SDF neighbor values are available (the
    sharded-forest create path); the production chi is towers_chi below
    — its band is half as wide (+-1h), which measurably shrinks the
    effective body radius bias in drag (VALIDATION.md)."""
    t = jnp.clip(sdf / (2.0 * h), -1.0, 1.0)
    return 0.5 * (1.0 + t + jnp.sin(jnp.pi * t) / jnp.pi)


def towers_chi(sdf_lab: jnp.ndarray, h) -> jnp.ndarray:
    """The reference's discrete Heaviside (Towers construction;
    KernelCharacteristicFunction, main.cpp:13312-13346): outside the
    +-1h band chi is the sharp indicator; inside it

        chi = (grad I+ . grad phi) / |grad phi|^2,   I+ = max(0, phi)

    with centered differences.  ``sdf_lab``: a 1-ghost halo'd SDF lab
    (..., n+2, n+2, n+2), phi > 0 inside; ``h`` broadcastable to the
    interior.  Undivided differences — the scaling cancels in the ratio.
    """
    c = sdf_lab[..., 1:-1, 1:-1, 1:-1]
    gU2 = 0.0
    num = 0.0
    for a in range(3):
        lo = [slice(1, -1)] * 3
        hi = [slice(1, -1)] * 3
        lo[a] = slice(0, -2)
        hi[a] = slice(2, None)
        p = sdf_lab[(Ellipsis,) + tuple(hi)]
        m = sdf_lab[(Ellipsis,) + tuple(lo)]
        gU = p - m
        gI = jnp.maximum(p, 0.0) - jnp.maximum(m, 0.0)
        gU2 = gU2 + gU * gU
        num = num + gI * gU
    band = num / (gU2 + 1e-30)
    return jnp.where(c > h, 1.0, jnp.where(c < -h, 0.0, band))


def surface_delta(grid: UniformGrid, chi: jnp.ndarray) -> jnp.ndarray:
    """|grad chi| — the surface delta-function weight per cell.

    grad chi points INTO the body (chi rises inward), i.e. -n_hat * delta
    with n_hat the outward normal.
    """
    g = st.grad(grid.pad_scalar(chi, 1), 1, grid.h)
    return jnp.sqrt(jnp.sum(g * g, axis=-1))


def grad_chi(grid: UniformGrid, chi: jnp.ndarray) -> jnp.ndarray:
    return st.grad(grid.pad_scalar(chi, 1), 1, grid.h)
