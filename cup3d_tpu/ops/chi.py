"""Signed-distance -> characteristic function chi and surface measure.

The reference converts each obstacle's SDF into a mollified Heaviside chi and
extracts surface points with gradients and delta weights
(KernelCharacteristicFunction, main.cpp:13291-13404, Towers-style).  The TPU
formulation works on dense fields: chi is a C^1 smoothed Heaviside of the SDF
over a 2h mollification band, and the surface delta is |grad chi| — every
surface integral becomes a fused masked reduction instead of ragged
per-block point lists.

Convention: sdf > 0 inside the body (matching the reference's rasterizer).
"""

from __future__ import annotations

import jax.numpy as jnp

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops import stencils as st


def heaviside(sdf: jnp.ndarray, h: float) -> jnp.ndarray:
    """C^1 mollified Heaviside over the band |sdf| <= 2h:
    chi = (1 + t + sin(pi t)/pi) / 2 with t = clip(sdf/2h, -1, 1)."""
    t = jnp.clip(sdf / (2.0 * h), -1.0, 1.0)
    return 0.5 * (1.0 + t + jnp.sin(jnp.pi * t) / jnp.pi)


def surface_delta(grid: UniformGrid, chi: jnp.ndarray) -> jnp.ndarray:
    """|grad chi| — the surface delta-function weight per cell.

    grad chi points INTO the body (chi rises inward), i.e. -n_hat * delta
    with n_hat the outward normal.
    """
    g = st.grad(grid.pad_scalar(chi, 1), 1, grid.h)
    return jnp.sqrt(jnp.sum(g * g, axis=-1))


def grad_chi(grid: UniformGrid, chi: jnp.ndarray) -> jnp.ndarray:
    return st.grad(grid.pad_scalar(chi, 1), 1, grid.h)
