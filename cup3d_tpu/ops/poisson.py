"""Pressure Poisson solvers.

The reference solves ``lap p = rhs`` with a pipelined BiCGSTAB + per-block CG
preconditioner (PoissonSolverAMR, main.cpp:14363-14616).  On a *uniform* TPU
grid we can do strictly better: the discrete Laplacian with periodic /
zero-gradient boundaries is diagonalized exactly by per-axis orthonormal
transforms — the real Fourier basis (periodic axes) and the DCT-II basis
(Neumann axes).  Both are applied as dense basis matmuls: an N x N orthogonal
matrix per axis, inverse = transpose.  This maps the entire solve onto the
MXU (6 large matmuls + one elementwise scale), works identically under SPMD
sharding (no FFT layout constraints), is exact to machine precision, and
costs O(N) flops/cell that the systolic array absorbs.

Discrete eigenvalues per axis with grid angle theta_k:

- periodic: theta_k = 2 pi k / N    (real Fourier rows: DC, cos/sin pairs,
                                     Nyquist)
- Neumann:  theta_k =   pi k / N    (DCT-II rows; copy-edge ghosts)

operator="compact":    7-point Laplacian        -> (2 cos theta - 2) / h^2
operator="consistent": div(grad) of 2h-centered -> -sin(theta)^2 / h^2

The consistent form makes pressure projection remove the centered divergence
*exactly* (up to the periodic Nyquist mode, invisible to centered
differencing).  The Krylov path for non-diagonalizable operators (AMR octree)
lives in ``cup3d_tpu.ops.krylov``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.uniform import BC, UniformGrid


def dct2_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """Orthonormal DCT-II basis matrix C with X = C @ x, x = C.T @ X."""
    k = np.arange(n)[:, None]
    j = np.arange(n)[None, :]
    c = np.cos(np.pi * k * (2 * j + 1) / (2 * n)) * np.sqrt(2.0 / n)
    c[0] *= np.sqrt(0.5)
    return c.astype(dtype)


def rfourier_matrix(n: int, dtype=np.float64):
    """Orthonormal *real* Fourier basis R (n x n) and per-row frequencies.

    Rows: DC; then (cos, sin) pairs for k = 1..ceil(n/2)-1; plus the Nyquist
    alternating row when n is even.  R @ R.T = I, so the inverse transform is
    the transpose — the same matmul-only structure as the DCT path.
    """
    j = np.arange(n)
    rows = [np.full(n, 1.0 / np.sqrt(n))]
    freqs = [0]
    for k in range(1, (n + 1) // 2):
        rows.append(np.sqrt(2.0 / n) * np.cos(2 * np.pi * k * j / n))
        freqs.append(k)
        rows.append(np.sqrt(2.0 / n) * np.sin(2 * np.pi * k * j / n))
        freqs.append(k)
    if n % 2 == 0:
        rows.append(((-1.0) ** j) / np.sqrt(n))
        freqs.append(n // 2)
    return np.stack(rows).astype(dtype), np.asarray(freqs)


def _axis_spectrum(n: int, periodic: bool, operator: str):
    """(basis matrix, eigenvalues*h^2) for one axis; f64 construction."""
    if periodic:
        mat, freqs = rfourier_matrix(n)
        theta = 2.0 * np.pi * freqs / n
    else:
        mat = dct2_matrix(n)
        theta = np.pi * np.arange(n) / n
    if operator == "compact":
        lam = 2.0 * np.cos(theta) - 2.0
    elif operator == "consistent":
        lam = -np.sin(theta) ** 2
    else:
        raise ValueError(operator)
    return mat, lam


def _apply_mat(mat, f, axis):
    # HIGHEST: default matmul precision is bf16-grade on TPU; the inverse
    # eigenvalues span ~N^2 orders so the transform must be true f32.
    out = jnp.tensordot(mat, f, axes=([1], [axis]), precision=jax.lax.Precision.HIGHEST)
    return jnp.moveaxis(out, 0, axis)


def make_poisson_solver(grid: UniformGrid, kind: str = "spectral",
                        dtype=jnp.float32, tol_abs: float = 1e-6,
                        tol_rel: float = 1e-4, maxiter: int = 1000,
                        mean_constraint: int = 2,
                        two_level=None) -> Callable:
    """Factory mirroring the reference's makePoissonSolver
    (main.cpp:14747-14758): "spectral" = exact uniform-grid diagonalization
    (this module); "iterative" = getZ-preconditioned BiCGSTAB
    (cup3d_tpu.ops.krylov), the path that generalizes to AMR.
    ``mean_constraint`` = the reference's bMeanConstraint for the
    iterative path; the spectral solve is mean-free by construction.
    ``two_level``/``maxiter`` parameterize the iterative path for the
    resilience escalation ladder (resilience/recovery.py); the spectral
    solver is direct and ignores both.

    Round 12: the iterative path additionally honors the
    CUP3D_KRYLOV_DTYPE / CUP3D_FUSED knobs (ops/precision.py) — bf16
    Krylov storage routes through the fused per-iteration Pallas driver
    (ops/fused_bicgstab.py) while keeping this factory's contract
    (``with_stats``, ``maxiter``, the escalation ladder) unchanged; the
    default f32 config stays bitwise-identical to the unfused solver."""
    if kind == "spectral":
        return build_spectral_solver(grid, dtype)
    if kind == "iterative":
        from cup3d_tpu.ops.krylov import build_iterative_solver

        return build_iterative_solver(
            grid, tol_abs=tol_abs, tol_rel=tol_rel, maxiter=maxiter,
            mean_constraint=mean_constraint, two_level=two_level,
        )
    raise ValueError(f"unknown poissonSolver {kind!r}")


def build_spectral_solver(grid: UniformGrid, dtype=jnp.float32,
                          operator: str = "consistent") -> Callable:
    """Returns jittable solve(rhs) -> p with mean(p) = 0.

    Wall/freespace faces impose zero-gradient (Neumann) pressure ghosts,
    identical to the reference's BlockLabNeumann treatment of p.  Use
    operator="consistent" (default) for pressure projection and "compact"
    to solve the literal 7-point system.
    """
    periodic = [b == BC.periodic for b in grid.bc]
    h = grid.h

    mats = []
    lams = []
    for n, p in zip(grid.shape, periodic):
        mat, lam = _axis_spectrum(n, p, operator)
        mats.append(jnp.asarray(mat, dtype=dtype))
        lams.append(lam)

    lam = (
        lams[0][:, None, None] + lams[1][None, :, None] + lams[2][None, None, :]
    ) / (h * h)
    lam_flat = lam.reshape(-1)
    inv = np.zeros_like(lam_flat)
    nz = np.abs(lam_flat) > 1e-12 * np.max(np.abs(lam_flat))
    inv[nz] = 1.0 / lam_flat[nz]
    inv = jnp.asarray(inv.reshape(lam.shape), dtype=dtype)

    def solve(rhs: jnp.ndarray, x0=None) -> jnp.ndarray:
        # x0 accepted for interface parity with the iterative solver
        # (warm starts are meaningless for an exact direct solve)
        f = rhs.astype(dtype)
        for a in range(3):
            f = _apply_mat(mats[a], f, a)
        f = f * inv
        for a in range(3):
            f = _apply_mat(mats[a].T, f, a)
        p = f.astype(rhs.dtype)
        return p - jnp.mean(p)

    return solve
