"""Pressure Poisson solvers.

The reference solves ``lap p = rhs`` with a pipelined BiCGSTAB + per-block CG
preconditioner (PoissonSolverAMR, main.cpp:14363-14616).  On a *uniform* TPU
grid we can do strictly better: the 7-point Laplacian with
periodic/zero-gradient boundaries is diagonalized exactly by FFTs (periodic
axes) and DCT-II transforms (Neumann axes).  The DCT is applied as a dense
cosine-basis matmul — an orthogonal transform whose inverse is its transpose
— which maps straight onto the MXU and is exact to machine precision, with
O(N) extra flops per cell that the systolic array absorbs.

Discrete eigenvalues per axis (cell-centered, copy-edge ghosts):

- periodic: 2 cos(2 pi k / N) - 2
- Neumann:  2 cos(pi k / N) - 2      (DCT-II basis)

The Krylov path for non-diagonalizable operators (AMR octree) lives in
``cup3d_tpu.ops.krylov``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.uniform import BC, UniformGrid


def dct2_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """Orthonormal DCT-II basis matrix C with X = C @ x, x = C.T @ X."""
    k = np.arange(n)[:, None]
    j = np.arange(n)[None, :]
    c = np.cos(np.pi * k * (2 * j + 1) / (2 * n)) * np.sqrt(2.0 / n)
    c[0] *= np.sqrt(0.5)
    return c.astype(dtype)


def _axis_eigenvalues(n: int, periodic: bool, operator: str) -> np.ndarray:
    """Per-axis eigenvalues (times h^2) of the chosen discrete Laplacian.

    operator="compact":    7-point Laplacian  -> 2 cos(theta) - 2
    operator="consistent": div(grad(.)) built from 2h-centered first
                           differences        -> -sin(theta)^2
    The consistent form makes the pressure projection remove the centered
    divergence *exactly* (up to the periodic Nyquist mode, which centered
    differencing cannot see).
    """
    k = np.arange(n)
    theta = (2.0 * np.pi * k / n) if periodic else (np.pi * k / n)
    if operator == "compact":
        return 2.0 * np.cos(theta) - 2.0
    if operator == "consistent":
        return -np.sin(theta) ** 2
    raise ValueError(operator)


def _apply_mat(mat, f, axis):
    # HIGHEST: default matmul precision is bf16-grade on TPU; the inverse
    # eigenvalues span ~N^2 orders so the transform must be true f32.
    out = jnp.tensordot(mat, f, axes=([1], [axis]), precision=jax.lax.Precision.HIGHEST)
    return jnp.moveaxis(out, 0, axis)


def build_spectral_solver(grid: UniformGrid, dtype=jnp.float32,
                          operator: str = "consistent") -> Callable:
    """Returns jittable solve(rhs) -> p with mean(p) = 0.

    Wall/freespace faces impose zero-gradient (Neumann) pressure ghosts,
    identical to the reference's BlockLabNeumann treatment of p.  Use
    operator="consistent" (default) for pressure projection and "compact"
    to solve the literal 7-point system.
    """
    periodic = [b == BC.periodic for b in grid.bc]
    h = grid.h

    lams = [
        _axis_eigenvalues(n, p, operator) for n, p in zip(grid.shape, periodic)
    ]
    lam = (
        lams[0][:, None, None] + lams[1][None, :, None] + lams[2][None, None, :]
    ) / (h * h)
    lam_flat = lam.reshape(-1)
    inv = np.zeros_like(lam_flat)
    nz = np.abs(lam_flat) > 1e-12 * np.max(np.abs(lam_flat))
    inv[nz] = 1.0 / lam_flat[nz]
    inv = jnp.asarray(inv.reshape(lam.shape), dtype=dtype)

    dct_mats = {
        a: jnp.asarray(dct2_matrix(grid.shape[a]), dtype=dtype)
    # transform matrices only for Neumann axes; FFT handles periodic axes
        for a in range(3)
        if not periodic[a]
    }
    fft_axes = tuple(a for a in range(3) if periodic[a])

    def solve(rhs: jnp.ndarray) -> jnp.ndarray:
        f = rhs.astype(dtype)
        for a, mat in dct_mats.items():
            f = _apply_mat(mat, f, a)
        if fft_axes:
            f = jnp.fft.fftn(f, axes=fft_axes)
        f = f * inv
        if fft_axes:
            f = jnp.fft.ifftn(f, axes=fft_axes)
            f = jnp.real(f)
        for a, mat in dct_mats.items():
            f = _apply_mat(mat.T, f, a)
        p = f.astype(rhs.dtype)
        return p - jnp.mean(p)

    return solve
