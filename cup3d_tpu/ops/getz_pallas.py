"""Pallas TPU kernel for the getZ block preconditioner.

``krylov.block_cg_tiles`` runs a fixed-iteration CG on every 8^3 tile
independently (the reference's poisson/diffusion getZ kernels,
main.cpp:14617-14746, 10448-10580).  Expressed in jnp, every CG iteration
materializes several full-size temporaries to HBM — ~24 HBM passes per
preconditioner application, measured at ~3% of HBM peak on a v5e.

This kernel keeps the whole CG in VMEM: HBM traffic is read b once, write
z once.  Layout: tiles are transposed to ``(8, 8, 8, T)`` so the *batch*
of tiles rides the 128-wide lane dimension — every (i, j, k) cell is a
T-vector processed fully vectorized, and the zero-Dirichlet 7-point
Laplacian becomes shifted adds over the three leading (sublane) axes.
Per-tile CG scalars (alpha, beta, residual norms) are (1,1,1,T) lane
vectors.

``krylov.block_cg_tiles`` is the public entry and dispatches here on TPU
(via ``use_pallas``); tests call ``block_cg_tiles_fast(interpret=True)``
for bit-level parity with the jnp reference on CPU.

Round 12: on the production hot path (exact getZ + mean-removal) the
standalone preconditioner kernel is SUPERSEDED by the fused per-iteration
stages of ops/fused_bicgstab.py, which run the tile solve inside the same
kernel program as the Laplacian apply and the iteration's dot partials —
the per-application HBM round-trip this kernel saved now disappears
entirely.  This module remains the CUP3D_GETZ=cg fallback and the home of
the shared ``TILE_T`` / ``use_pallas`` plumbing the fused path imports.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

TILE_T = 256  # tiles per kernel instance: ~6 VMEM arrays * 512 KB


def _cg_kernel(b_ref, shift_ref, z_ref, *, iters: int):
    b = b_ref[...]
    shift = shift_ref[...]  # (1, 1, 1, T), broadcasts over cells
    zero_plane = jnp.zeros_like(b[:1])

    def lap(p):
        out = -6.0 * p
        # zero-Dirichlet neighbor sums along the three leading axes
        out += jnp.concatenate([p[1:], zero_plane], axis=0)
        out += jnp.concatenate([zero_plane, p[:-1]], axis=0)
        zy = jnp.zeros_like(p[:, :1])
        out += jnp.concatenate([p[:, 1:], zy], axis=1)
        out += jnp.concatenate([zy, p[:, :-1]], axis=1)
        zz = jnp.zeros_like(p[:, :, :1])
        out += jnp.concatenate([p[:, :, 1:], zz], axis=2)
        out += jnp.concatenate([zz, p[:, :, :-1]], axis=2)
        return out

    def dot(a, c):
        return jnp.sum(a * c, axis=(0, 1, 2), keepdims=True)

    z0 = jnp.zeros_like(b)
    rs0 = dot(b, b)

    def body(_, carry):
        z, res, p, rs = carry
        ap = -lap(p) + shift * p
        denom = dot(p, ap)
        ok = jnp.abs(denom) > 1e-30
        alpha = jnp.where(ok, rs / jnp.where(ok, denom, 1.0), 0.0)
        z = z + alpha * p
        res = res - alpha * ap
        rs_new = dot(res, res)
        okr = rs > 1e-30
        beta = jnp.where(okr, rs_new / jnp.where(okr, rs, 1.0), 0.0)
        p = res + beta * p
        return z, res, p, rs_new

    z, _, _, _ = jax.lax.fori_loop(0, iters, body, (z0, b, b, rs0))
    z_ref[...] = z


@partial(jax.jit, static_argnames=("iters", "interpret"))
def _cg_tiles_pallas(bt: jnp.ndarray, shift_t: jnp.ndarray, iters: int,
                     interpret: bool = False) -> jnp.ndarray:
    """bt: (bs, bs, bs, n_pad) batch-last tiles; shift_t: (1, 1, 1, n_pad)."""
    from jax.experimental import pallas as pl

    bs = bt.shape[0]
    n = bt.shape[-1]
    T = min(TILE_T, n)
    grid = (n // T,)
    spec = pl.BlockSpec((bs, bs, bs, T), lambda i: (0, 0, 0, i))
    sspec = pl.BlockSpec((1, 1, 1, T), lambda i: (0, 0, 0, i))
    return pl.pallas_call(
        partial(_cg_kernel, iters=iters),
        out_shape=jax.ShapeDtypeStruct(bt.shape, bt.dtype),
        grid=grid,
        in_specs=[spec, sspec],
        out_specs=spec,
        interpret=interpret,
    )(bt, shift_t)


def use_pallas() -> bool:
    if os.environ.get("CUP3D_NO_PALLAS"):
        return False
    return jax.default_backend() == "tpu"


def block_cg_tiles_fast(b: jnp.ndarray, iters: int, shift=0.0,
                        interpret: bool = False) -> jnp.ndarray:
    """Solve (-block_lap + shift) z = b per trailing-8^3 tile, forcing the
    Pallas path (interpret=True runs it on CPU for parity tests)."""
    if not (use_pallas() or interpret):
        from cup3d_tpu.ops.krylov import block_cg_tiles_reference

        return block_cg_tiles_reference(b, iters, shift)
    return block_cg_tiles_pallas(b, iters, shift, interpret)


def cg_tiles_lanes(bt: jnp.ndarray, iters: int, shift=0.0) -> jnp.ndarray:
    """getZ on batch-last tiles (bs, bs, bs, T) — the kernel's native
    layout.  The lane-resident Krylov solve (krylov.make_laplacian_lanes)
    keeps every field in this layout, so the per-application
    (nb,8,8,8) <-> (8,8,8,nb) transposes of ``block_cg_tiles_pallas``
    vanish (measured: they were ~55% of the BiCGSTAB iteration on v5e).
    Off-TPU it falls back to the jnp reference (with the transposes)."""
    n = bt.shape[-1]
    if not use_pallas():
        from cup3d_tpu.ops.krylov import block_cg_tiles_reference

        b = jnp.moveaxis(bt, -1, 0)
        z = block_cg_tiles_reference(b, iters, shift)
        return jnp.moveaxis(z, 0, -1)
    shift_vec = jnp.broadcast_to(
        jnp.asarray(shift, bt.dtype), (1, 1, 1, n)
    )
    T = min(TILE_T, n)
    n_pad = -(-n // T) * T
    if n_pad != n:
        bt = jnp.concatenate(
            [bt, jnp.zeros(bt.shape[:-1] + (n_pad - n,), bt.dtype)], axis=-1
        )
        shift_vec = jnp.concatenate(
            [shift_vec, jnp.zeros((1, 1, 1, n_pad - n), bt.dtype)], axis=-1
        )
    return _cg_tiles_pallas(bt, shift_vec, iters)[..., :n]


def block_cg_tiles_pallas(b: jnp.ndarray, iters: int, shift=0.0,
                          interpret: bool = False) -> jnp.ndarray:
    bs = b.shape[-1]
    lead = b.shape[:-3]
    n = int(np.prod(lead)) if lead else 1
    bt = jnp.moveaxis(b.reshape((n,) + b.shape[-3:]), 0, -1)  # (bs,bs,bs,n)

    shift_arr = jnp.asarray(shift, b.dtype)
    if shift_arr.ndim == 0:
        shift_vec = jnp.full((1, 1, 1, n), shift_arr, b.dtype)
    else:
        # per-tile scalar (e.g. (nb,1,1,1) block h^2): one value per tile
        sv = jnp.broadcast_to(shift_arr, lead + (1, 1, 1)).reshape(n)
        shift_vec = sv.reshape(1, 1, 1, n)

    T = min(TILE_T, max(n, 1))
    n_pad = -(-n // T) * T
    if n_pad != n:
        bt = jnp.concatenate(
            [bt, jnp.zeros(b.shape[-3:] + (n_pad - n,), b.dtype)], axis=-1
        )
        shift_vec = jnp.concatenate(
            [shift_vec, jnp.zeros((1, 1, 1, n_pad - n), b.dtype)], axis=-1
        )
    zt = _cg_tiles_pallas(bt, shift_vec, iters, interpret)
    z = jnp.moveaxis(zt[..., :n], -1, 0).reshape(b.shape)
    return z
