"""Pressure projection: the reference's ``PressureProjection`` operator
(main.cpp:15061-15160) on the uniform dense grid.

rhs = (div u - chi * div u_def) / dt            (KernelPressureRHS semantics)
solve lap p = rhs
u  -= dt * grad p                                (KernelGradP semantics)

The obstacle term subtracts the deformation-velocity divergence inside the
body so that the penalized region does not source pressure.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops import stencils as st


def pressure_rhs(grid: UniformGrid, u: jnp.ndarray, dt,
                 chi: Optional[jnp.ndarray] = None,
                 udef: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    w = 1
    div_u = st.divergence(grid.pad_vector(u, w), w, grid.h)
    if chi is not None and udef is not None:
        div_udef = st.divergence(grid.pad_vector(udef, w), w, grid.h)
        div_u = div_u - chi * div_udef
    return div_u / dt


def project(grid: UniformGrid, u: jnp.ndarray, dt, solver: Callable,
            chi: Optional[jnp.ndarray] = None,
            udef: Optional[jnp.ndarray] = None,
            p_init: Optional[jnp.ndarray] = None,
            with_stats: bool = False):
    """Returns (projected velocity, pressure).  ``p_init`` warm-starts an
    iterative solver from the previous step's pressure (ignored by the
    exact spectral solver).

    ``with_stats`` (solvers advertising ``supports_stats``, i.e. the
    iterative front-ends) additionally returns the (2,) [residual,
    iterations] device vector — packed telemetry for the obs layer, no
    host sync here."""
    rhs = pressure_rhs(grid, u, dt, chi, udef)
    if with_stats and getattr(solver, "supports_stats", False):
        p, stats = solver(rhs, p_init, with_stats=True)
    else:
        p = solver(rhs, p_init)
        stats = None
    gradp = st.grad(grid.pad_scalar(p, 1), 1, grid.h)
    if with_stats:
        return u - dt * gradp, p, stats
    return u - dt * gradp, p
