"""Matrix-free Krylov machinery: the TPU analogue of the reference's
``PoissonSolverAMR`` (pipelined BiCGSTAB, main.cpp:14363-14616) and its
per-block CG "getZ" preconditioner (poisson_kernels, main.cpp:14617-14746).

Design notes (TPU-first, not a port):

- The reference overlaps ``MPI_Iallreduce`` with preconditioner work to hide
  reduction latency across ranks.  Under ``jit`` + SPMD sharding, XLA already
  schedules the ``psum`` behind independent compute, so we use the *plain*
  preconditioned BiCGSTAB recurrence — fewer fused reductions beat manual
  pipelining on ICI (SURVEY.md section 7, hard part (c)).
- The getZ preconditioner is kept, because its structure is ideal for TPU:
  an independent fixed-iteration CG on every 8^3 tile, batched over the tile
  axis — a dense, static-shape, embarrassingly parallel kernel.  The
  reference iterates each block CG to a tolerance (<=100 its,
  main.cpp:14739); we use a *fixed* iteration count so the compiled graph is
  static and every tile takes the same time (no block-imbalance).  The
  default is 24 inner iterations: measured on a 128^3 TGV pressure system
  in float32, 12 inner iterations let the outer BiCGSTAB stagnate just
  above the 1e-4 relative target and burn the full 1000-iteration cap,
  while 24 converges in ~50 outer iterations (12x wall-clock) — with the
  VMEM-resident Pallas kernel (ops/getz_pallas.py) the extra inner
  iterations are nearly free.
- Breakdown handling: the reference restarts up to 100 times and keeps the
  best-residual ``x_opt`` (main.cpp:14374, 14452).  We do the same inside
  one ``lax.while_loop``: on rho/omega breakdown the recurrence re-seeds
  ``rhat = r, p = v = 0``, and a running best-x is carried in the state.

All reductions are ``jnp`` dots: under ``pjit`` they lower to ``psum`` over
the device mesh, which is the ICI-native replacement for the reference's
``MPI_Iallreduce``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.uniform import UniformGrid

_HI = jax.lax.Precision.HIGHEST


def _dot(a, b):
    # accumulate in at least f32 (precision.accum_dtype): bf16-stored
    # Krylov vectors still reduce in f32, and f64 solves stay f64.  The
    # promote_types form cannot silently produce f64 from f32/bf16
    # inputs (JX005 audit, round 12).
    acc = jnp.promote_types(a.dtype, jnp.float32)
    return jnp.sum(a * b, dtype=acc)


def make_laplacian(grid: UniformGrid) -> Callable:
    """Matrix-free 7-point Laplacian  (lap x)_i = (sum_nb x - 6 x_i)/h^2
    with the grid's scalar BCs (periodic wrap / zero-gradient), the same
    operator ``ComputeLHS`` applies (main.cpp:9197-9269, without the h^3
    scaling — we keep physical 1/h^2 units so rhs is the physical rhs).
    """
    inv_h2 = 1.0 / (grid.h * grid.h)

    def apply(x: jnp.ndarray) -> jnp.ndarray:
        xp = grid.pad_scalar(x, 1)
        c = xp[1:-1, 1:-1, 1:-1]
        out = (
            xp[2:, 1:-1, 1:-1]
            + xp[:-2, 1:-1, 1:-1]
            + xp[1:-1, 2:, 1:-1]
            + xp[1:-1, :-2, 1:-1]
            + xp[1:-1, 1:-1, 2:]
            + xp[1:-1, 1:-1, :-2]
            - 6.0 * c
        )
        return out * inv_h2

    return apply


# ---------------------------------------------------------------------------
# lane-resident layout: (bs, bs, bs, T) with the tile batch on the 128-wide
# lane axis.  The whole Krylov solve runs in this layout (one transpose in,
# one out) because per-iteration tile/untile transposes around the Pallas
# getZ kernel measured ~55% of the BiCGSTAB iteration on a v5e.
# ---------------------------------------------------------------------------


def to_lanes(x: jnp.ndarray, bs: int = 8) -> jnp.ndarray:
    """(nx,ny,nz) -> (bs,bs,bs,T), T = (nx/bs)(ny/bs)(nz/bs), lane index
    t = (tx*NBy + ty)*NBz + tz."""
    nx, ny, nz = x.shape
    t = x.reshape(nx // bs, bs, ny // bs, bs, nz // bs, bs)
    return t.transpose(1, 3, 5, 0, 2, 4).reshape(bs, bs, bs, -1)


def from_lanes(t: jnp.ndarray, shape) -> jnp.ndarray:
    bs = t.shape[0]
    nbx, nby, nbz = (s // bs for s in shape)
    t = t.reshape(bs, bs, bs, nbx, nby, nbz)
    return t.transpose(3, 0, 4, 1, 5, 2).reshape(shape)


def make_laplacian_lanes(grid: UniformGrid, bs: int = 8) -> Callable:
    """The same operator as make_laplacian, acting on the lane-resident
    layout.  Intra-tile neighbors are sublane shifts; cross-tile neighbor
    planes are lane-axis rolls by the tile stride (periodic wrap is exactly
    the roll; zero-gradient clamps the domain-edge plane to itself)."""
    from cup3d_tpu.grid.uniform import BC

    nb = tuple(s // bs for s in grid.shape)
    strides = (nb[1] * nb[2], nb[2], 1)
    T = nb[0] * nb[1] * nb[2]
    lanes = np.arange(T)
    tco = (lanes // strides[0] % nb[0],
           lanes // strides[1] % nb[1],
           lanes % nb[2])
    inv_h2 = 1.0 / (grid.h * grid.h)

    def edge_src(t, axis, idx):
        return jax.lax.slice_in_dim(t, idx, idx + 1, axis=axis)

    def neighbor(t, axis, sign):
        """Value of each cell's +/-1 neighbor along ``axis``.

        A lane roll by the tile stride reaches the next tile along the
        axis — except for domain-edge tiles on non-outermost axes, where
        the flat roll crosses into the adjacent outer tile, so edge lanes
        get an explicit wrap roll (periodic) or a zero-gradient clamp."""
        periodic = grid.bc[axis] == BC.periodic
        n = t.shape[axis]
        st, nba = strides[axis], nb[axis]
        if sign > 0:
            inner = jax.lax.slice_in_dim(t, 1, n, axis=axis)
            edge = jax.lax.slice_in_dim(t, n - 1, n, axis=axis)
            src = edge_src(t, axis, 0)  # next tile's low plane
            plane = jnp.roll(src, -st, axis=-1)
            mask = jnp.asarray(tco[axis] == nba - 1)
            wrap = jnp.roll(src, (nba - 1) * st, axis=-1)
        else:
            inner = jax.lax.slice_in_dim(t, 0, n - 1, axis=axis)
            edge = jax.lax.slice_in_dim(t, 0, 1, axis=axis)
            src = edge_src(t, axis, n - 1)  # previous tile's high plane
            plane = jnp.roll(src, st, axis=-1)
            mask = jnp.asarray(tco[axis] == 0)
            wrap = jnp.roll(src, -(nba - 1) * st, axis=-1)
        plane = jnp.where(mask, wrap if periodic else edge, plane)
        parts = (inner, plane) if sign > 0 else (plane, inner)
        return jnp.concatenate(parts, axis=axis)

    def apply(t: jnp.ndarray) -> jnp.ndarray:
        out = -6.0 * t
        for ax in range(3):
            out = out + neighbor(t, ax, +1) + neighbor(t, ax, -1)
        return out * inv_h2

    return apply


def make_lane_planes(grid: UniformGrid, bs: int = 8) -> Callable:
    """w (bs,bs,bs,T) -> (6,bs,bs,T) cross-tile neighbor face planes,
    rows [lo0, hi0, lo1, hi1, lo2, hi2]: row 2*ax+1 holds the +1
    neighbor of each tile's cells at local index bs-1 along ``ax``, row
    2*ax the -1 neighbor of the cells at index 0 — exactly the boundary
    planes make_laplacian_lanes's ``neighbor()`` concatenates in, with
    the same lane-roll / periodic-wrap / zero-gradient-clamp selection.

    Factored out so the fused iteration (ops/fused_bicgstab.py) can
    pass the planes as a kernel input and keep the Laplacian apply
    itself pure intra-chunk slicing — this boundary fetch touches
    6*bs^2/bs^3 = 3/4 of a plane's bytes per tile and is the only part
    of the apply with cross-lane data flow (on the sharded path it is
    also the natural seam for the ring-DMA halo, parallel/ring.py)."""
    from cup3d_tpu.grid.uniform import BC

    nb = tuple(s // bs for s in grid.shape)
    strides = (nb[1] * nb[2], nb[2], 1)
    T = nb[0] * nb[1] * nb[2]
    lanes = np.arange(T)
    tco = (lanes // strides[0] % nb[0],
           lanes // strides[1] % nb[1],
           lanes % nb[2])

    def planes(t: jnp.ndarray) -> jnp.ndarray:
        rows = []
        for ax in range(3):
            periodic = grid.bc[ax] == BC.periodic
            st, nba = strides[ax], nb[ax]
            p0 = jax.lax.slice_in_dim(t, 0, 1, axis=ax)       # own low plane
            p1 = jax.lax.slice_in_dim(t, bs - 1, bs, axis=ax)  # own high
            hi = jnp.roll(p0, -st, axis=-1)  # next tile's low plane
            hi = jnp.where(jnp.asarray(tco[ax] == nba - 1),
                           jnp.roll(p0, (nba - 1) * st, axis=-1)
                           if periodic else p1, hi)
            lo = jnp.roll(p1, st, axis=-1)   # previous tile's high plane
            lo = jnp.where(jnp.asarray(tco[ax] == 0),
                           jnp.roll(p1, -(nba - 1) * st, axis=-1)
                           if periodic else p0, lo)
            rows.append(jnp.squeeze(lo, axis=ax))
            rows.append(jnp.squeeze(hi, axis=ax))
        return jnp.stack(rows, axis=0)

    return planes


# ---------------------------------------------------------------------------
# getZ block preconditioner: fixed-iteration CG on every bs^3 tile
# ---------------------------------------------------------------------------


def _tile(x: jnp.ndarray, bs: int) -> jnp.ndarray:
    """(nx,ny,nz) -> (NBx,NBy,NBz,bs,bs,bs) tile view."""
    nx, ny, nz = x.shape
    x = x.reshape(nx // bs, bs, ny // bs, bs, nz // bs, bs)
    return x.transpose(0, 2, 4, 1, 3, 5)


def _untile(t: jnp.ndarray) -> jnp.ndarray:
    nbx, nby, nbz, bs, _, _ = t.shape
    return t.transpose(0, 3, 1, 4, 2, 5).reshape(nbx * bs, nby * bs, nbz * bs)


def _block_lap(t: jnp.ndarray) -> jnp.ndarray:
    """Per-tile 7-pt Laplacian (h^2-scaled out) with implicit zero-Dirichlet
    halo — exactly the preconditioner operator of kernelPoissonGetZInner
    (main.cpp:14651-14702)."""
    z = jnp.pad(t, [(0, 0)] * (t.ndim - 3) + [(1, 1)] * 3)
    c = z[..., 1:-1, 1:-1, 1:-1]
    return (
        z[..., 2:, 1:-1, 1:-1]
        + z[..., :-2, 1:-1, 1:-1]
        + z[..., 1:-1, 2:, 1:-1]
        + z[..., 1:-1, :-2, 1:-1]
        + z[..., 1:-1, 1:-1, 2:]
        + z[..., 1:-1, 1:-1, :-2]
        - 6.0 * c
    )


def use_exact_getz() -> bool:
    """Round-4 default: the exact fast-diagonalization tile solve
    (ops/tilesolve.py) replaces the fixed-sweep CG getZ.  CUP3D_GETZ=cg
    restores the round-3 Pallas/jnp CG path."""
    import os

    return os.environ.get("CUP3D_GETZ", "") != "cg"


def getz_blocks(b_scaled: jnp.ndarray, shift=None,
                cg_iters: int = 24) -> jnp.ndarray:
    """getZ preconditioner application in the (..., bs, bs, bs) blocks
    layout: solve (-lap_tile + shift) z = b_scaled per tile.  Dispatches to
    the exact tile solve (default) or the legacy fixed-iteration CG."""
    from cup3d_tpu.ops import tilesolve

    if use_exact_getz():
        return tilesolve.tile_solve_blocks(b_scaled, shift)
    return block_cg_tiles(b_scaled, cg_iters,
                          shift=0.0 if shift is None else shift)


def getz_lanes(bt_scaled: jnp.ndarray, shift=None,
               cg_iters: int = 24) -> jnp.ndarray:
    """getZ in the lane-resident (bs, bs, bs, T) layout (see getz_blocks)."""
    from cup3d_tpu.ops import getz_pallas, tilesolve

    if use_exact_getz():
        return tilesolve.tile_solve_lanes(bt_scaled, shift)
    return getz_pallas.cg_tiles_lanes(bt_scaled, cg_iters,
                                      shift=0.0 if shift is None else shift)


def block_cg_tiles(b: jnp.ndarray, iters: int, shift=0.0) -> jnp.ndarray:
    """Solve (-block_lap + shift*I) z = b independently on every
    trailing-bs^3 tile of ``b`` (shape (..., bs, bs, bs)) with `iters` CG
    steps — the batched getZ kernel (kernelPoissonGetZInner,
    main.cpp:14651-14702; the shifted variant is the diffusion getZ with
    coefficient -6 - h^2/(nu dt), main.cpp:10571).

    On TPU this dispatches to the VMEM-resident Pallas kernel
    (ops/getz_pallas.py, ~3x per application); elsewhere (and in tests)
    it runs the jnp reference below."""
    from cup3d_tpu.ops import getz_pallas

    if getz_pallas.use_pallas():
        return getz_pallas.block_cg_tiles_pallas(b, iters, shift)
    return block_cg_tiles_reference(b, iters, shift)


def block_cg_tiles_reference(b: jnp.ndarray, iters: int, shift=0.0) -> jnp.ndarray:
    """Pure-jnp getZ (the ground truth the Pallas kernel is tested
    against — the reference's own optimized-vs-reference kernel pattern,
    main.cpp:9186-9190).  The tile operator with its implicit
    zero-Dirichlet halo is SPD for shift >= 0, so plain CG applies; the
    fixed iteration count keeps the graph static and every tile equally
    expensive (no block imbalance).  ``shift`` may be a traced scalar or
    an array broadcastable to ``b`` (per-block h^2)."""
    acc = jnp.promote_types(b.dtype, jnp.float32)
    bdot = lambda a, c: jnp.sum(
        a * c, axis=(-1, -2, -3), keepdims=True, dtype=acc
    )

    z0 = jnp.zeros_like(b)
    rs0 = bdot(b, b)

    def body(_, carry):
        z, res, p, rs = carry
        ap = -_block_lap(p) + shift * p
        denom = bdot(p, ap)
        alpha = rs / jnp.where(jnp.abs(denom) > 1e-30, denom, 1.0)
        alpha = jnp.where(jnp.abs(denom) > 1e-30, alpha, 0.0)
        z = z + alpha * p
        res = res - alpha * ap
        rs_new = bdot(res, res)
        beta = rs_new / jnp.where(rs > 1e-30, rs, 1.0)
        beta = jnp.where(rs > 1e-30, beta, 0.0)
        p = res + beta * p
        return z, res, p, rs_new

    z, _, _, _ = jax.lax.fori_loop(0, iters, body, (z0, b, b, rs0))
    return z


def make_block_cg_preconditioner(bs: int = 8, iters: int = 24,
                                 h: float = 1.0) -> Callable:
    """z ~ A^{-1} r block-locally for A = lap/h^2 on a *dense* grid:
    tile the grid into bs^3 blocks and run block_cg_tiles.  The h^2 scaling
    of A is folded into the per-tile rhs so M is a genuine approximate
    inverse of A (not just a Krylov-equivalent rescaling)."""
    h2 = h * h

    def precond(r: jnp.ndarray) -> jnp.ndarray:
        z = getz_blocks(-h2 * _tile(r, bs), cg_iters=iters)
        return _untile(z)

    return precond


# ---------------------------------------------------------------------------
# coarse-grid correction: the round-5 second preconditioner level
# ---------------------------------------------------------------------------


def make_coarse_correction_lanes(grid: UniformGrid, bs: int = 8) -> Callable:
    """Galerkin coarse correction T = P (P^T A P)^{-1} P^T on the tile-mean
    grid, for A = the 7-point Laplacian/h^2 with the grid's BCs.

    P is piecewise-constant prolongation over each bs^3 tile.  A is
    separable, so the coarse operator is exactly
    P^T A P = (bs^2/h^2) (L_x (+) L_y (+) L_z) with L_* the 1D coarse
    graph Laplacians (periodic wrap or Neumann path per BC) — solved
    EXACTLY by per-axis eigendecomposition: three (NB,NB) matmuls on an
    (NBx,NBy,NBz) array, negligible next to the fine-grid work.

    Why: the exact tile solve (ops/tilesolve.py) is block-Jacobi — no
    global coupling — so outer BiCGSTAB iterations grow with resolution
    (48 at 128^3, more at 256^3; BENCH_r04).  Adding this coarse level
    (additive two-level Schwarz) carries the smooth modes globally and
    makes the iteration count roughly resolution-independent.  The
    reference has no counterpart (its getZ is block-local too,
    main.cpp:14617-14746) — this is a TPU-side algorithmic win, not a
    port.
    """
    solve_vec = _make_coarse_solve_vec(grid, bs)

    def correct(rt: jnp.ndarray) -> jnp.ndarray:
        """rt: residual in lanes layout (bs,bs,bs,T) -> coarse correction
        in the same layout (constant per tile)."""
        zc = solve_vec(rt)
        return jnp.broadcast_to(zc[None, None, None, :], rt.shape)

    return correct


def make_twolevel_preconditioner_lanes(grid: UniformGrid, h2: float,
                                       bs: int = 8,
                                       precond_iters: int = 24) -> Callable:
    """Multiplicative two-level preconditioner in the lanes layout:

        zc = P (P^T A P)^{-1} P^T r        (exact Galerkin coarse solve)
        z  = zc + getZ(r - A zc)           (exact tile solve on the rest)

    Measured on the 128^3 pressure system this converges in 12 outer
    BiCGSTAB iterations vs 51 for the tile solve alone, and the count is
    resolution-independent (11-12 at 64^3/128^3/256^3) — the coarse level
    carries the smooth modes the block-local getZ cannot see.

    Coarse-first ordering makes the multiplicative coupling nearly free:
    zc is CONSTANT per tile, so A zc is nonzero only on the 6 tile-face
    sublane planes and is assembled analytically from coarse neighbor
    differences — no fine-grid stencil application.
    """
    coarse_vec = _make_coarse_solve_vec(grid, bs)
    nb = tuple(s // bs for s in grid.shape)
    T = nb[0] * nb[1] * nb[2]
    deltas_fn = make_face_deltas(grid, bs)

    def lap_tileconst(zc: jnp.ndarray) -> jnp.ndarray:
        """(T,) coarse values -> A zc in lanes layout (bs,bs,bs,T)."""
        d = deltas_fn(zc)
        out = jnp.zeros((bs, bs, bs, T), zc.dtype)
        for ax in range(3):
            idx_hi = [slice(None)] * 4
            idx_hi[ax] = bs - 1
            idx_lo = [slice(None)] * 4
            idx_lo[ax] = 0
            out = out.at[tuple(idx_hi)].add(d[2 * ax + 1])
            out = out.at[tuple(idx_lo)].add(d[2 * ax])
        return out

    def M(r: jnp.ndarray) -> jnp.ndarray:
        zc = coarse_vec(r)
        z = getz_lanes(-h2 * (r - lap_tileconst(zc)),
                       cg_iters=precond_iters)
        return z + zc[None, None, None, :]

    return M


def make_face_deltas(grid: UniformGrid, bs: int = 8) -> Callable:
    """zc (T,) coarse tile values -> (6, T) face deltas of A zc, rows
    [lo0, hi0, lo1, hi1, lo2, hi2].

    For tile-constant zc, A zc is nonzero only on the 6 tile-face
    planes: row 2*ax+1 is the value added on the face at local index
    bs-1 along ``ax`` ((next - self)/h^2 with the BC's wrap/clamp), row
    2*ax the face at index 0.  make_twolevel_preconditioner_lanes
    scatters these into the lanes layout; the fused iteration
    (ops/fused_bicgstab.py) ships them to its getZ kernel as coarse aux
    rows and reconstructs A zc in-kernel by face concatenation."""
    from cup3d_tpu.grid.uniform import BC

    nb = tuple(s // bs for s in grid.shape)
    strides = (nb[1] * nb[2], nb[2], 1)
    T = nb[0] * nb[1] * nb[2]
    lanes = np.arange(T)
    tco = (lanes // strides[0] % nb[0],
           lanes // strides[1] % nb[1],
           lanes % nb[2])
    inv_h2 = 1.0 / (grid.h * grid.h)
    periodic = [grid.bc[ax] == BC.periodic for ax in range(3)]
    masks_hi = [jnp.asarray(tco[ax] == nb[ax] - 1) for ax in range(3)]
    masks_lo = [jnp.asarray(tco[ax] == 0) for ax in range(3)]

    def deltas(zc: jnp.ndarray) -> jnp.ndarray:
        rows = []
        for ax in range(3):
            st, nba = strides[ax], nb[ax]
            nxt = jnp.roll(zc, -st)
            wrap_hi = jnp.roll(zc, (nba - 1) * st)
            # Neumann wall: neighbor = self -> zero face difference
            nxt = jnp.where(masks_hi[ax],
                            wrap_hi if periodic[ax] else zc, nxt)
            prv = jnp.roll(zc, st)
            wrap_lo = jnp.roll(zc, -(nba - 1) * st)
            prv = jnp.where(masks_lo[ax],
                            wrap_lo if periodic[ax] else zc, prv)
            rows.append((prv - zc) * inv_h2)
            rows.append((nxt - zc) * inv_h2)
        return jnp.stack(rows, axis=0)

    return deltas


def _make_coarse_solve_vec(grid: UniformGrid, bs: int = 8) -> Callable:
    """(bs,bs,bs,T) residual -> (T,) coarse correction values (the shared
    core of make_coarse_correction_lanes / make_twolevel_preconditioner)."""
    core = _make_coarse_core(grid, bs)

    def solve_vec(rt: jnp.ndarray) -> jnp.ndarray:
        return core(jnp.sum(rt, axis=(0, 1, 2)).reshape(-1))

    return solve_vec


def _make_coarse_core(grid: UniformGrid, bs: int = 8) -> Callable:
    """(T,) tile sums (R = P^T r) -> (T,) coarse correction values: the
    eigendecomposition einsum core of _make_coarse_solve_vec, split out
    so the fused iteration can feed it the per-tile partial sums its
    kernels already emit instead of re-reducing the fine grid."""
    from cup3d_tpu.grid.uniform import BC

    nb = tuple(s // bs for s in grid.shape)
    Vs, lams = [], []
    for ax in range(3):
        n = nb[ax]
        if n == 1:
            # degenerate axis: a single tile has no coarse neighbor in
            # either BC family (the periodic wrap is itself, the Neumann
            # wall is zero-gradient), so the exact Galerkin P^T A P row is
            # 0 — an isolated node whose constant mode the pseudo-inverse
            # below projects out (ADVICE r5: the wall branch's diagonal 1
            # added a spurious bs^2/h^2 eigenvalue shift here)
            L = np.zeros((1, 1))
        else:
            L = 2.0 * np.eye(n) - np.diag(np.ones(n - 1), 1) - np.diag(
                np.ones(n - 1), -1
            )
            if grid.bc[ax] == BC.periodic:
                L[0, -1] -= 1.0
                L[-1, 0] -= 1.0
            else:  # zero-gradient: no coupling through the wall
                L[0, 0] = 1.0
                L[-1, -1] = 1.0
        w, V = np.linalg.eigh(L)
        Vs.append(V)
        lams.append(w)
    scale = bs * bs / (grid.h * grid.h)
    lam3 = scale * (
        lams[0][:, None, None] + lams[1][None, :, None]
        + lams[2][None, None, :]
    )
    inv3 = np.where(lam3 > 1e-8 * scale, 1.0 / np.maximum(lam3, 1e-300), 0.0)
    dt = np.float32
    Vx, Vy, Vz = (jnp.asarray(V.astype(dt)) for V in Vs)
    inv3 = jnp.asarray(inv3.astype(dt))
    T = nb[0] * nb[1] * nb[2]

    def core(rc_flat: jnp.ndarray) -> jnp.ndarray:
        rc = rc_flat.reshape(nb)
        t = jnp.einsum("ia,abc->ibc", Vx.T, rc, precision=_HI)
        t = jnp.einsum("jb,ibc->ijc", Vy.T, t, precision=_HI)
        t = jnp.einsum("kc,ijc->ijk", Vz.T, t, precision=_HI)
        t = -t * inv3  # A is the negative of the positive graph form
        t = jnp.einsum("ai,ijk->ajk", Vx, t, precision=_HI)
        t = jnp.einsum("bj,ajk->abk", Vy, t, precision=_HI)
        zc = jnp.einsum("ck,abk->abc", Vz, t, precision=_HI)
        return zc.reshape(T)

    return core


def use_coarse_correction() -> bool:
    """Round-5 default: two-level (tile + coarse) preconditioner.
    CUP3D_COARSE=0 restores the pure block-Jacobi tile solve."""
    import os

    return os.environ.get("CUP3D_COARSE", "1") != "0"


# ---------------------------------------------------------------------------
# AMR coarse level: one DOF per block over the forest's face graph
# ---------------------------------------------------------------------------

#: max face-neighbor entries per block under 26-neighbor 2:1 balance:
#: 6 faces x up to 4 finer blocks per face
GRAPH_K = 24


class BlockGraph(NamedTuple):
    """Face-adjacency graph of one forest topology, the coarse space of
    the AMR two-level preconditioner (the multi-level counterpart of
    make_coarse_correction_lanes' tile-mean grid).

    ``idx``/``w``: (nb[, pad], K) neighbor slots and couplings (w = 0 on
    padding entries and padding blocks); ``deg``: (nb[, pad],) row sums.
    The coarse operator is the SPSD graph Laplacian C z = deg*z - W z,
    whose nullspace is the constant — consistent with the mean-removed
    pressure system, exactly like the uniform path's pseudo-inverse.

    NamedTuple => pytree: travels as a traced jit ARGUMENT, so bucketed
    drivers (sim/amr.py) reuse compiled executables across regrids."""

    idx: jnp.ndarray
    w: jnp.ndarray
    deg: jnp.ndarray


def block_graph_tables(grid, cap: Optional[int] = None,
                       dtype=jnp.float32) -> BlockGraph:
    """Host-build the face graph of ``grid`` (a BlockGrid).

    Couplings are the physical finite-volume face conductances A/d in
    the convention that makes the graph Laplacian the exact Galerkin
    P^T A P of the refluxed 7-pt Laplacian for SAME-LEVEL faces (the
    verified uniform limit: w = bs^2 h with volume-weighted restriction
    reproduces make_coarse_correction_lanes' bs^2/h^2 operator exactly).
    Coarse-fine faces use the same A/d rule — shared area (bs h_f)^2
    over the 1.5 h_f center distance — which is an APPROXIMATION of the
    interpolated-ghost Galerkin rows there; a preconditioner-grade one
    (symmetric, positive semidefinite, constant nullspace), documented
    in VALIDATION.md.  ``cap``: optional bucket capacity to pad to."""
    tree = grid.tree
    bs = grid.bs
    nb = grid.nb
    idx = np.zeros((nb, GRAPH_K), np.int64)
    w = np.zeros((nb, GRAPH_K), np.float64)
    fill = np.zeros(nb, np.int64)

    def add(i, j, wij):
        k = fill[i]
        idx[i, k] = j
        w[i, k] = wij
        fill[i] = k + 1

    offs2 = [(0, 0), (0, 1), (1, 0), (1, 1)]
    for s, (l, bi, bj, bk) in enumerate(grid.keys):
        h = float(grid.h[s])
        for ax in range(3):
            t1, t2 = [a for a in range(3) if a != ax]
            for side in (-1, 1):
                npos = [bi, bj, bk]
                npos[ax] += side
                wp = tree.wrap(l, npos)
                if wp is None:
                    continue  # closed face: no coupling (zero-gradient)
                own = tree.owner_level(l, wp)
                if own == l:
                    add(s, grid.slot[(l, *wp)], bs * bs * h)
                elif own == l - 1:
                    parent = (l - 1, wp[0] // 2, wp[1] // 2, wp[2] // 2)
                    # fine side of a coarse-fine face: A = (bs h)^2,
                    # d = (h + 2h)/2 -> w = bs^2 h / 1.5
                    add(s, grid.slot[parent], bs * bs * h / 1.5)
                else:  # own == l + 1: 4 finer blocks, h_f = h/2
                    hf = 0.5 * h
                    for o1, o2 in offs2:
                        fpos = [0, 0, 0]
                        fpos[ax] = 2 * wp[ax] + (1 if side < 0 else 0)
                        fpos[t1] = 2 * wp[t1] + o1
                        fpos[t2] = 2 * wp[t2] + o2
                        fslot = grid._slot_maps[l + 1][tuple(fpos)]
                        if fslot < 0:
                            raise KeyError("fine neighbor missing: "
                                           "unbalanced tree")
                        add(s, int(fslot), bs * bs * hf / 1.5)
    deg = w.sum(axis=1)
    if cap is not None:
        from cup3d_tpu.grid import bucket as bk_

        idx = bk_.pad_rows(idx, cap)
        w = bk_.pad_rows(w, cap)
        deg = bk_.pad_rows(deg, cap)
    return BlockGraph(
        idx=jnp.asarray(idx, jnp.int32),
        w=jnp.asarray(w, dtype),
        deg=jnp.asarray(deg, dtype),
    )


def _cg_graph(Cfun: Callable, b: jnp.ndarray, iters: int,
              rtol: float = 1e-6) -> jnp.ndarray:
    """Fixed-iteration CG on the (tiny) coarse system — fixed so the
    preconditioner is a FIXED linear operator (BiCGSTAB requirement) and
    the graph stays static.

    Two gates make the fixed sweep safe in f32 on the SINGULAR
    (constant-nullspace) coarse system: updates freeze once the
    relative residual drops below ``rtol`` (CG iterating past
    convergence on roundoff noise diverges — measured NaN on a 22-node
    graph at 32 sweeps), and non-positive curvature directions (noise /
    nullspace: C is PSD) are skipped."""
    acc = jnp.promote_types(b.dtype, jnp.float32)
    dot = lambda a, c: jnp.sum(a * c, dtype=acc)
    rs0 = dot(b, b)

    def body(_, carry):
        z, r, p, rs = carry
        live = rs > (rtol * rtol) * rs0
        ap = Cfun(p)
        denom = dot(p, ap)
        ok = jnp.logical_and(live, denom > 0.0)
        alpha = jnp.where(ok, rs / jnp.where(ok, denom, 1.0), 0.0)
        z = z + alpha * p
        r = r - alpha * ap
        rs_new = dot(r, r)
        beta = jnp.where(ok, rs_new / jnp.where(rs > 0, rs, 1.0), 0.0)
        return z, r, r + beta * p, rs_new

    z0 = jnp.zeros_like(b)
    z, _, _, _ = jax.lax.fori_loop(0, iters, body, (z0, b, b, rs0))
    return z


def coarse_correct_blocks(r: jnp.ndarray, vol: jnp.ndarray,
                          graph: BlockGraph, iters: int = 32) -> jnp.ndarray:
    """Coarse correction over the block graph: volume-weighted restrict
    the residual to one value per block, solve the graph Laplacian with
    fixed-iteration CG, return the (nb,) per-block correction (prolonged
    by constant injection at the caller).

    ``vol`` is the per-cell volume column ((nb,1,1,1); 0 on padding
    blocks, which keeps their rows exactly 0 through the CG).  The
    restriction R r = h^3 sum_cells r makes the graph weights of
    block_graph_tables the exact uniform-limit Galerkin scaling (see
    there).  CG on the singular-consistent system stays in range(C):
    conservation of the refluxed Laplacian puts zero volume-weighted
    mean on every Krylov residual of the mean-removed solve."""
    rc = jnp.sum(r * vol, axis=(1, 2, 3)).astype(graph.w.dtype)
    # project the constant nullspace out of the restricted residual (the
    # uniform path's pseudo-inverse does this spectrally): the outer
    # residual is mean-free only to f32 roundoff, and CG amplifies an
    # inconsistent nullspace component through near-zero curvature
    # directions (measured: NaN without this).  Real blocks carry
    # deg > 0; padding rows are isolated zero rows and stay untouched.
    m = (graph.deg > 0).astype(rc.dtype)
    nreal = jnp.maximum(jnp.sum(m), 1.0)

    def deflate(v):
        return (v - jnp.sum(v * m) / nreal) * m

    def C(z):
        return graph.deg * z - jnp.sum(z[graph.idx] * graph.w, axis=-1)

    zc = _cg_graph(C, deflate(rc), iters)
    # the fine A is the NEGATIVE of the positive graph form (lap x =
    # sum(nb - c)/h^2), same sign flip as the uniform path's
    # `t = -t * inv3` (_make_coarse_solve_vec)
    return -deflate(zc).astype(r.dtype)


# ---------------------------------------------------------------------------
# restarted preconditioned BiCGSTAB
# ---------------------------------------------------------------------------


class _BiCGState(NamedTuple):
    k: jnp.ndarray
    x: jnp.ndarray
    r: jnp.ndarray
    rhat: jnp.ndarray
    p: jnp.ndarray
    v: jnp.ndarray
    rho: jnp.ndarray
    alpha: jnp.ndarray
    omega: jnp.ndarray
    rnorm: jnp.ndarray
    x_best: jnp.ndarray
    rnorm_best: jnp.ndarray


def bicgstab(
    apply_A: Callable,
    b: jnp.ndarray,
    M: Optional[Callable] = None,
    x0: Optional[jnp.ndarray] = None,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    rnorm_ref=None,
):
    """Preconditioned BiCGSTAB with breakdown re-seeding and best-x tracking
    (the reference's solve loop, main.cpp:14449-14604).  Returns
    (x_best, final residual norm, iterations used).

    Stopping matches the reference: ||r|| <= max(tol_abs, tol_rel*||r0||)
    (PoissonErrorTol/PoissonErrorTolRel, main.cpp:15364-15365).

    ``rnorm_ref`` overrides the relative-tolerance reference norm.  A warm
    start (x0 != 0, or the 2nd-order increment form) SHRINKS ||r0||, which
    would tighten the target exactly when the start is good and make warm
    solves cost MORE iterations than cold ones (measured 54 vs 44,
    VERDICT r2 item 4).  Callers with a warm start pass the cold system's
    RHS norm so the solve targets the same absolute quality as a cold
    solve and a good start can only reduce iterations.
    """
    if M is None:
        M = lambda r: r
    if x0 is None:
        x0 = jnp.zeros_like(b)

    # breakdown threshold in the ACCUMULATION dtype, not b.dtype: 1e-30
    # underflows to 0 in bf16/f16 storage, which would silently disable
    # the rho re-seed below (round-12 mixed-precision audit)
    eps = jnp.asarray(1e-30, jnp.promote_types(b.dtype, jnp.float32))

    r0 = b - apply_A(x0)
    rnorm0 = jnp.sqrt(_dot(r0, r0))
    ref = rnorm0 if rnorm_ref is None else rnorm_ref
    target = jnp.maximum(tol_abs, tol_rel * ref)
    one = jnp.asarray(1.0, b.dtype)

    init = _BiCGState(
        k=jnp.asarray(0, jnp.int32),
        x=x0,
        r=r0,
        rhat=r0,
        p=jnp.zeros_like(b),
        v=jnp.zeros_like(b),
        rho=one,
        alpha=one,
        omega=one,
        rnorm=rnorm0,
        x_best=x0,
        rnorm_best=rnorm0,
    )

    def cond(s: _BiCGState):
        return jnp.logical_and(s.k < maxiter, s.rnorm > target)

    def body(s: _BiCGState):
        rho_new = _dot(s.rhat, s.r)
        # rho breakdown -> re-seed shadow residual (reference restart,
        # main.cpp:14452-14479)
        broke = jnp.abs(rho_new) < eps * jnp.maximum(s.rnorm * s.rnorm, 1.0)
        rhat = jnp.where(broke, s.r, s.rhat)
        rho_new = jnp.where(broke, s.rnorm * s.rnorm, rho_new)
        p_prev = jnp.where(broke, 0.0, s.p)
        v_prev = jnp.where(broke, 0.0, s.v)

        beta = (rho_new / _safe(s.rho)) * (s.alpha / _safe(s.omega))
        beta = jnp.where(broke, 0.0, beta)
        p = s.r + beta * (p_prev - s.omega * v_prev)
        y = M(p)
        v = apply_A(y)
        rhat_v = _dot(rhat, v)
        alpha = rho_new / _safe(rhat_v)
        svec = s.r - alpha * v
        z = M(svec)
        t = apply_A(z)
        tt = _dot(t, t)
        omega = _dot(t, svec) / _safe(tt)
        x = s.x + alpha * y + omega * z
        r = svec - omega * t
        rnorm = jnp.sqrt(_dot(r, r))

        better = rnorm < s.rnorm_best
        return _BiCGState(
            k=s.k + 1,
            x=x,
            r=r,
            rhat=rhat,
            p=p,
            v=v,
            rho=rho_new,
            alpha=alpha,
            omega=omega,
            rnorm=rnorm,
            x_best=jnp.where(better, x, s.x_best),
            rnorm_best=jnp.minimum(rnorm, s.rnorm_best),
        )

    out = jax.lax.while_loop(cond, body, init)
    return out.x_best, out.rnorm_best, out.k


def _safe(d):
    # ``d`` is always an accumulated scalar (f32+, never bf16 — see
    # _dot), so the 1e-30 floor is representable; the dtype-matched
    # asarray cannot promote an f32 pipeline to f64 (JX005 audit).
    return jnp.where(jnp.abs(d) > 1e-30, d, jnp.asarray(1e-30, d.dtype))


# ---------------------------------------------------------------------------
# Poisson front-end (iterative; see poisson.build_spectral_solver for the
# uniform-grid spectral fast path)
# ---------------------------------------------------------------------------


def build_iterative_solver(
    grid: UniformGrid,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    precond_bs: int = 8,
    precond_iters: int = 24,
    mean_constraint: int = 2,
    two_level: Optional[bool] = None,
) -> Callable:
    """solve(rhs) -> p via getZ-preconditioned BiCGSTAB.

    ``mean_constraint`` mirrors the reference's bMeanConstraint
    (ComputeLHS, main.cpp:9273-9327): 0 = none, 1 = the equation row of
    cell (0,0,0) becomes the volume-weighted mean of the unknown, 2 =
    nullspace projection (mean removal; default), 3 = Dirichlet-pin of
    cell (0,0,0).  The pinned-row RHS is zeroed like the reference's
    solve loop (main.cpp:14404-14407).

    The solve runs in the lane-resident tile layout (to_lanes /
    make_laplacian_lanes): one transpose in, one out, none per iteration.

    ``two_level`` overrides the CUP3D_COARSE env default for the
    preconditioner choice (None = :func:`use_coarse_correction`): the
    resilience escalation ladder drops to the tile-only getZ without
    touching process-global state (resilience/recovery.py).
    """
    if any(s % precond_bs for s in grid.shape):
        return _build_iterative_solver_dense(
            grid, tol_abs, tol_rel, maxiter, precond_bs, precond_iters,
            mean_constraint,
        )
    A0 = make_laplacian_lanes(grid, precond_bs)
    h2 = grid.h * grid.h
    h3 = grid.h ** 3

    # lanes layout: dense cell (0,0,0) lives at [0,0,0, lane 0].
    # The replaced row is rescaled to the Laplacian's diagonal magnitude
    # (6/h^2): its RHS entry is zeroed below, so row scaling leaves the
    # solution unchanged, but an O(1) (pin) or O(h^3) (mean) row next to
    # O(1/h^2) rows wrecks the conditioning and stalls float32 BiCGSTAB
    # (ADVICE r5 regression test: test_mean_constraint_pinned_paths)
    pin = 6.0 / h2
    if mean_constraint == 1:
        A = lambda t: A0(t).at[0, 0, 0, 0].set(jnp.sum(t) * h3 * pin)
    elif mean_constraint == 3:
        A = lambda t: A0(t).at[0, 0, 0, 0].set(t[0, 0, 0, 0] * pin)
    else:
        A = A0

    use_two = (use_coarse_correction() if two_level is None
               else bool(two_level))
    if use_two and mean_constraint not in (1, 3):
        # multiplicative two-level: 12 outer iterations vs 51 tile-only at
        # 128^3, resolution-independent (make_twolevel_preconditioner_lanes)
        M = make_twolevel_preconditioner_lanes(grid, h2, precond_bs,
                                               precond_iters)
    else:
        # mean_constraint 1/3 pin one equation row, making A nonsingular —
        # but the two-level M's exact Galerkin coarse solve is built from
        # the UNMODIFIED singular Laplacian, so its pseudo-inverse projects
        # the constant mode back out and the preconditioned operator
        # reintroduces the nullspace the pin removed (ADVICE r5).  The
        # tile-local getZ has no global coupling, so it is unaffected by
        # the single-row modification.

        def M(r):
            return getz_lanes(-h2 * r, cg_iters=precond_iters)

    from cup3d_tpu.ops import precision as _precision

    # round 12: loud build-time error for knob combinations that cannot
    # honor a bf16 request (no silent downgrade)
    _precision.check_policy(mean_constraint)
    # The fused per-iteration driver covers the production hot path
    # only: mean-removal constraint + exact getZ.  The pinned-row modes
    # (1/3) and the legacy CG getZ keep the unfused composition at f32
    # storage — they are off the hot path and the single-row A
    # modification doesn't fit the fused stencil kernel.
    if (_precision.use_fused() and mean_constraint == 2
            and use_exact_getz()):
        from cup3d_tpu.ops import fused_bicgstab as _fused

        store = _precision.krylov_dtype()

        def solve(rhs: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
                  with_stats: bool = False):
            b = rhs - jnp.mean(rhs)
            bt = to_lanes(b, precond_bs)
            x0t = None if x0 is None else to_lanes(x0, precond_bs)
            xt, rnorm, k = _fused.fused_bicgstab(
                grid, bt, tol_abs=tol_abs, tol_rel=tol_rel,
                maxiter=maxiter, rnorm_ref=jnp.sqrt(_dot(bt, bt)),
                x0=x0t, bs=precond_bs, two_level=use_two,
                store_dtype=store,
            )
            x = from_lanes(xt, rhs.shape)
            x = x - jnp.mean(x)
            if with_stats:
                return x, solver_stats(rnorm, k)
            return x

        solve.supports_stats = True
        solve.maxiter = maxiter
        return solve

    def solve(rhs: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
              with_stats: bool = False):
        if mean_constraint == 2:
            b = rhs - jnp.mean(rhs)
        else:
            b = rhs
        bt = to_lanes(b, precond_bs)
        if mean_constraint in (1, 3):
            bt = bt.at[0, 0, 0, 0].set(0.0)
        x0t = None if x0 is None else to_lanes(x0, precond_bs)
        # rel tolerance always references the cold system's RHS norm so a
        # warm start can only reduce iterations (see bicgstab docstring)
        xt, rnorm, k = bicgstab(
            A, bt, M=M, x0=x0t, tol_abs=tol_abs, tol_rel=tol_rel,
            maxiter=maxiter, rnorm_ref=jnp.sqrt(_dot(bt, bt)),
        )
        x = from_lanes(xt, rhs.shape)
        x = x - jnp.mean(x) if mean_constraint == 2 else x
        if with_stats:
            # (final residual norm, iterations) as one device vector —
            # drivers pack it onto the async QoI read so per-step solver
            # telemetry costs ZERO extra syncs (obs/trace.py)
            return x, solver_stats(rnorm, k)
        return x

    solve.supports_stats = True
    solve.maxiter = maxiter
    return solve


def solver_stats(rnorm, k) -> jnp.ndarray:
    """(2,) f32 device vector [residual norm, iterations] — the packed
    per-solve telemetry the obs layer consumes (shared by the uniform
    and AMR solver front-ends)."""
    return jnp.stack([jnp.asarray(rnorm, jnp.float32),
                      jnp.asarray(k, jnp.float32)])


def _build_iterative_solver_dense(
    grid: UniformGrid,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    precond_bs: int = 8,
    precond_iters: int = 24,
    mean_constraint: int = 2,
) -> Callable:
    """Dense-layout fallback (grids not divisible by the tile size)."""
    A0 = make_laplacian(grid)
    M = make_block_cg_preconditioner(precond_bs, precond_iters, h=grid.h)
    h3 = grid.h ** 3
    # pin-row rescale: same conditioning fix as the lanes path above
    pin = 6.0 / (grid.h * grid.h)
    if mean_constraint == 1:
        A = lambda x: A0(x).at[0, 0, 0].set(jnp.sum(x) * h3 * pin)
    elif mean_constraint == 3:
        A = lambda x: A0(x).at[0, 0, 0].set(x[0, 0, 0] * pin)
    else:
        A = A0

    def solve(rhs: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
              with_stats: bool = False):
        b = rhs - jnp.mean(rhs) if mean_constraint == 2 else rhs
        if mean_constraint in (1, 3):
            b = b.at[0, 0, 0].set(0.0)
        x, rnorm, k = bicgstab(
            A, b, M=M, x0=x0, tol_abs=tol_abs, tol_rel=tol_rel,
            maxiter=maxiter, rnorm_ref=jnp.sqrt(_dot(b, b)),
        )
        x = x - jnp.mean(x) if mean_constraint == 2 else x
        if with_stats:
            return x, solver_stats(rnorm, k)
        return x

    solve.supports_stats = True
    solve.maxiter = maxiter
    return solve
