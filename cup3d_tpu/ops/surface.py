"""Surface-point force probing: the reference's KernelComputeForces
(main.cpp:12250-12494) + surface extraction (main.cpp:13291-13404) as a
dense TPU kernel.

The reference walks per-block ragged surface-point lists; each point
probes the velocity field up to 4 cells OUTSIDE the body along the
outward normal with one-sided 5th-order stencils and Taylor-corrects the
gradient back to the surface cell.  That machinery is what makes its drag
measure converge — the dense chi-band substitute under-reads pressure
inside the penalized band by a flat ~28% on the sphere (VALIDATION.md,
VERDICT r2 missing #1).

TPU formulation: obstacle surfaces live on finest-level blocks (grad-chi
tagging forces max refinement), so the band's neighborhood is locally
UNIFORM at hmin.  The driver gathers the obstacle's holding blocks into a
dense local window (block-granular gathers); every step of the reference
algorithm is then a static-shape dense computation over the window:

- surface measure: delta = (grad H . grad phi)/|grad phi|^2 per cell
  (Towers; reference Delta with the h factors made physical), surface
  cells = cells with delta > 0; outward normal n = -grad phi/|grad phi|
  (phi > 0 inside);
- probe point: first cell along round(k*n), k = 0..4, with chi < 0.01
  (else the last in-window candidate) — reference marching loop;
- velocity gradient at the probe point: 6-point one-sided 5th-order
  per axis in the sign(n) direction, falling back to 3-point/2-point
  when the window (reference: the lab) runs out; second + mixed
  derivatives Taylor-correct the gradient back to the surface cell;
- tractions: f = -P(surface cell) n dS + (nu/h) (grad_u . n dS) with
  UNDIVIDED derivatives (the reference's bookkeeping), and the same
  reductions: force/torque split, thrust/drag along velUnit, Pout,
  defPower, pLocom.

Everything is masked dense math + in-window gathers; no ragged lists.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-21
_C6 = (-137.0 / 60.0, 5.0, -5.0, 10.0 / 3.0, -5.0 / 4.0, 1.0 / 5.0)


def _shift(f, ox, oy, oz):
    """Zero-padded static shift: out[i] = f[i + o]."""
    pad = [(max(-ox, 0), max(ox, 0)), (max(-oy, 0), max(oy, 0)),
           (max(-oz, 0), max(oz, 0))] + [(0, 0)] * (f.ndim - 3)
    g = jnp.pad(f, pad)
    sl = tuple(
        slice(p[0] + o, p[0] + o + n)
        for p, o, n in zip(pad[:3], (ox, oy, oz), f.shape[:3])
    ) + (slice(None),) * (f.ndim - 3)
    return g[sl]


def _central(f, axis):
    """Undivided centered difference along axis (zero-padded edges)."""
    o = [0, 0, 0]
    o[axis] = 1
    hi = _shift(f, *o)
    o[axis] = -1
    lo = _shift(f, *o)
    return 0.5 * (hi - lo)


def _flat_index(ix, iy, iz, shape):
    return (ix * shape[1] + iy) * shape[2] + iz


def _gather(fflat, ix, iy, iz, shape):
    """Window gather with clamped indices (callers mask validity)."""
    ix = jnp.clip(ix, 0, shape[0] - 1)
    iy = jnp.clip(iy, 0, shape[1] - 1)
    iz = jnp.clip(iz, 0, shape[2] - 1)
    return fflat[_flat_index(ix, iy, iz, shape)]


def surface_force_window(
    vel: jnp.ndarray,  # (Wx, Wy, Wz, 3) window velocity
    p: jnp.ndarray,  # (Wx, Wy, Wz)
    chi: jnp.ndarray,
    sdf: jnp.ndarray,  # phi > 0 inside
    udef: jnp.ndarray,  # (Wx, Wy, Wz, 3)
    valid: jnp.ndarray,  # (Wx, Wy, Wz) bool: cell carries real field data
    xc: jnp.ndarray,  # (Wx, Wy, Wz, 3) physical cell centers
    h,  # window spacing (finest level)
    nu: float,
    cm: jnp.ndarray,  # (3,)
    u_trans: jnp.ndarray,  # (3,)
    omega: jnp.ndarray,  # (3,)
    per_point: bool = False,
    max_points: int | None = None,
) -> Dict[str, jnp.ndarray]:
    """Reference KernelComputeForces on a dense uniform window.  Returns
    the force-integral dict of models.base.force_integrals (pres/visc
    force, torque, power, thrust/drag/def_power) measured at probed
    surface points.

    ``max_points`` (static) compacts the surface band to at most that many
    points before the probe math runs.  The band is SPARSE — measured 2674
    surface cells in an 88^3-cell (~680k) window for the 128^3 fish —
    while the marching/one-sided/mixed stencils cost ~60 gathered samples
    per evaluation point; run dense over the window they made ComputeForces
    0.41 s/step of device time (the whole step is ~0.06 s without it,
    profiled r4).  ``jnp.nonzero(size=K)`` is the static-shape compaction
    (the TPU analogue of the reference's ragged per-block surface lists,
    main.cpp:7256-7478); overflow is detectable via the returned
    ``n_surf`` (callers size K generously from probe_max_points)."""
    shape = vel.shape[:3]
    dtype = vel.dtype

    # -- surface measure + outward normal (KernelCharacteristicFunction) --
    # dense over the window, but all static shifts — cheap VPU passes
    gphi = jnp.stack([_central(sdf, a) for a in range(3)], -1)  # undivided*h
    gH = jnp.stack([_central(chi, a) for a in range(3)], -1)
    gphi2 = jnp.sum(gphi * gphi, -1) + _EPS
    # (gH.gphi)/|gphi|^2 with BOTH gradients undivided equals the physical
    # Towers surface density delta(x) [1/length]; dS = delta * h^3
    # (reference Delta = fac1*numD/gradUSq with its 2h/inv2h bookkeeping)
    dS_w = jnp.sum(gH * gphi, -1) / gphi2 * (h * h * h)
    nhat_w = -gphi / jnp.sqrt(gphi2)[..., None]  # outward unit normal
    surf_w = (dS_w > 1e-12) & valid

    # -- compact the band to K static slots --------------------------------
    # top-K by dS (not first-K): if the band exceeds the budget, the
    # dropped cells are the SMALLEST-measure tail (graceful truncation
    # bounded by the tail's dS sum), not a spatially-biased trailing set
    ncells = int(np.prod(shape))
    K = ncells if max_points is None else min(int(max_points), ncells)
    surf_flat = surf_w.reshape(-1)
    n_surf = jnp.sum(surf_flat.astype(jnp.int32))
    dS_flat = jnp.where(surf_flat, dS_w.reshape(-1), 0.0)
    top_dS, iflat0 = jax.lax.top_k(dS_flat, K)
    pt_ok = top_dS > 0

    def take_s(fw):
        return fw.reshape(-1)[iflat0]

    def take_v(fw):
        return fw.reshape((-1,) + fw.shape[3:])[iflat0]

    dS = jnp.where(pt_ok, take_s(dS_w), 0.0)
    surf = pt_ok & (dS > 0)
    nhat = take_v(nhat_w)
    xc = take_v(xc)
    P = take_s(p)
    v_base = take_v(vel)
    u_base = take_v(udef)
    base = (
        (iflat0 // (shape[1] * shape[2])).astype(jnp.int32),
        ((iflat0 // shape[2]) % shape[1]).astype(jnp.int32),
        (iflat0 % shape[2]).astype(jnp.int32),
    )
    chif = chi.reshape(-1)
    validf = valid.reshape(-1)

    def inwin(ix, iy, iz):
        geo = (
            (ix >= 0) & (ix < shape[0]) & (iy >= 0) & (iy < shape[1])
            & (iz >= 0) & (iz < shape[2])
        )
        return geo & _gather(validf, ix, iy, iz, shape)

    def nbhd_ok(ix, iy, iz):
        """Probe-candidate acceptance: the cell AND its +-1 neighborhood
        must be in-window — the reference rejects marching candidates
        unless ix+dxi+-1 is inside the lab (guarding the centered second
        derivatives); with slot=-1 holes in the AMR window the clamped
        gathers would otherwise silently duplicate edge values (ADVICE r3)."""
        ok = inwin(ix, iy, iz)
        for a in range(3):
            o = [ix, iy, iz]
            for s in (-1, 1):
                o[a] = (ix, iy, iz)[a] + s
                ok = ok & inwin(*o)
        return ok

    # -- probe point: march outward to the first chi < 0.01 cell ----------
    px, py, pz = base
    found = jnp.zeros_like(pt_ok)
    for k in range(5):
        cx = base[0] + jnp.round(k * nhat[..., 0]).astype(jnp.int32)
        cy = base[1] + jnp.round(k * nhat[..., 1]).astype(jnp.int32)
        cz = base[2] + jnp.round(k * nhat[..., 2]).astype(jnp.int32)
        ok = nbhd_ok(cx, cy, cz) & ~found
        px = jnp.where(ok, cx, px)
        py = jnp.where(ok, cy, py)
        pz = jnp.where(ok, cz, pz)
        found = found | (ok & (_gather(chif, cx, cy, cz, shape) < 0.01))

    sx = jnp.where(nhat[..., 0] > 0, 1, -1).astype(jnp.int32)
    sy = jnp.where(nhat[..., 1] > 0, 1, -1).astype(jnp.int32)
    sz = jnp.where(nhat[..., 2] > 0, 1, -1).astype(jnp.int32)

    velf = vel.reshape(-1, 3)

    def vat(ix, iy, iz):
        return _gather(velf, ix, iy, iz, shape)

    def axis_pts(axis, s):
        """Probe-relative sample positions k*s along one axis."""
        def at(k):
            o = [px, py, pz]
            o[axis] = o[axis] + k * s
            return o
        return at

    def one_sided(axis, s):
        """Undivided one-sided first derivative at the probe point:
        6-pt 5th order -> 3-pt 2nd order -> 2-pt 1st order, by range
        (reference inrange cascade)."""
        at = axis_pts(axis, s)
        v = [vat(*at(k)) for k in range(6)]
        d6 = s[..., None] * sum(c * vk for c, vk in zip(_C6, v))
        d3 = s[..., None] * (-1.5 * v[0] + 2.0 * v[1] - 0.5 * v[2])
        d2 = s[..., None] * (v[1] - v[0])
        # every intermediate sample must be valid, not just the endpoint:
        # an AMR-window hole (slot=-1) between probe and endpoint would be
        # zero-filled while the endpoint check passes (ADVICE r3)
        oks = [inwin(*at(k)) for k in range(6)]
        ok5 = (oks[1] & oks[2] & oks[3] & oks[4] & oks[5])[..., None]
        ok2 = (oks[1] & oks[2])[..., None]
        # final 2-pt fallback still reads at(1): zero the derivative when
        # even that neighbor is a hole (code-review r4)
        d2 = jnp.where(oks[1][..., None], d2, 0.0)
        return jnp.where(ok5, d6, jnp.where(ok2, d3, d2))

    dvdx = one_sided(0, sx)
    dvdy = one_sided(1, sy)
    dvdz = one_sided(2, sz)

    # when no marching candidate passed nbhd_ok the probe stays at base
    # with NO neighborhood guarantee: gate every centered/compact stencil
    # below so holes demote to a zero (lower-order) contribution instead of
    # reading clamped/zero-filled cells (code-review r4)
    probe_ok = nbhd_ok(px, py, pz)

    def second(axis):
        o = [px, py, pz]
        o2 = [px, py, pz]
        o = list(o)
        o[axis] = o[axis] + 1
        o2[axis] = o2[axis] - 1
        d2 = vat(*o) - 2.0 * vat(px, py, pz) + vat(*o2)
        return jnp.where(probe_ok[..., None], d2, 0.0)

    d2x, d2y, d2z = second(0), second(1), second(2)

    def mixed(a1, s1, a2, s2):
        """Nested one-sided mixed derivative (reference dveldxdy form),
        falling back to the compact 2x2 form when out of range."""
        def at(k1, k2):
            o = [px, py, pz]
            o[a1] = o[a1] + k1 * s1
            o[a2] = o[a2] + k2 * s2
            return o

        def row(k1):  # 3-pt one-sided along a2 at offset k1 along a1
            return (-1.5 * vat(*at(k1, 0)) + 2.0 * vat(*at(k1, 1))
                    - 0.5 * vat(*at(k1, 2)))

        full = (s1 * s2)[..., None] * (
            -0.5 * row(2) + 2.0 * row(1) - 1.5 * row(0)
        )
        # deliberate divergence: the reference's compact fallback applies
        # the sign product to only the first difference
        # (main.cpp:12399-12401), inverting one term whenever the two
        # normal signs differ; we use the mathematically consistent form
        compact = (s1 * s2)[..., None] * (
            (vat(*at(1, 1)) - vat(*at(1, 0)))
            - (vat(*at(0, 1)) - vat(*at(0, 0)))
        )
        # all nine samples of the nested form must be valid (ADVICE r3:
        # intermediate AMR-window holes must demote to the compact form);
        # the compact 2x2 form's own samples (incl. the diagonal, which
        # nbhd_ok never covers) must be valid too, else the mixed term
        # drops to zero (code-review r4)
        ok = jnp.ones_like(pt_ok)
        for k1 in range(3):
            for k2 in range(3):
                ok = ok & inwin(*at(k1, k2))
        okc = (inwin(*at(0, 0)) & inwin(*at(0, 1)) & inwin(*at(1, 0))
               & inwin(*at(1, 1)))
        compact = jnp.where(okc[..., None], compact, 0.0)
        return jnp.where(ok[..., None], full, compact)

    dxy = mixed(0, sx, 1, sy)
    dxz = mixed(0, sx, 2, sz)
    dyz = mixed(1, sy, 2, sz)

    # Taylor-correct the gradient from the probe point back to the
    # surface cell (integer offsets; undivided derivatives throughout)
    ox = (base[0] - px)[..., None].astype(dtype)
    oy = (base[1] - py)[..., None].astype(dtype)
    oz = (base[2] - pz)[..., None].astype(dtype)
    gx = dvdx + d2x * ox + dxy * oy + dxz * oz  # (..., 3): du/dx, dv/dx, dw/dx
    gy = dvdy + d2y * oy + dyz * oz + dxy * ox
    gz = dvdz + d2z * oz + dxz * ox + dyz * oy

    # -- tractions ---------------------------------------------------------
    n_meas = nhat * dS[..., None]  # outward normal * dS
    inv_h = nu / h
    fV = inv_h * (
        gx * n_meas[..., 0:1] + gy * n_meas[..., 1:2] + gz * n_meas[..., 2:3]
    )
    fP = -P[..., None] * n_meas
    fT = fV + fP

    vel_norm = jnp.linalg.norm(u_trans)
    vel_unit = jnp.where(vel_norm > 1e-9, u_trans / jnp.where(
        vel_norm > 0, vel_norm, 1.0), 0.0)

    r = xc - cm
    pres_force = jnp.sum(fP, axis=0)
    visc_force = jnp.sum(fV, axis=0)
    torque = jnp.sum(jnp.cross(r, fT), axis=0)
    force_par = jnp.sum(fT * vel_unit, -1)
    thrust = jnp.sum(0.5 * (force_par + jnp.abs(force_par)))
    drag = -jnp.sum(0.5 * (force_par - jnp.abs(force_par)))
    # power = traction . FLUID velocity at the surface cell — the
    # reference's Pout (main.cpp:12461); the old band measure used
    # u_body here, a divergence this kernel removes.  p_locom is the
    # reference's traction . u_solid work (main.cpp:12470-2476).  The
    # *Bnd variants clip each point's power to its negative part before
    # summing (reference PoutBnd/defPowerBnd, main.cpp:12483-12485) —
    # the "useful work only" bound the swimming-efficiency outputs use.
    pow_pt = jnp.sum(fT * v_base, -1)
    defp_pt = jnp.sum(fT * u_base, -1)
    pow_out = jnp.sum(pow_pt)
    pout_bnd = jnp.sum(jnp.minimum(pow_pt, 0.0))
    def_power = jnp.sum(defp_pt)
    def_power_bnd = jnp.sum(jnp.minimum(defp_pt, 0.0))
    u_solid = u_trans + jnp.cross(jnp.broadcast_to(omega, r.shape), r)
    p_locom = jnp.sum(fT * u_solid)
    out = {
        "pres_force": pres_force,
        "visc_force": visc_force,
        "torque": torque,
        "power": pow_out,
        "pout_bnd": pout_bnd,
        "thrust": thrust,
        "drag": drag,
        "def_power": def_power,
        "def_power_bnd": def_power_bnd,
        "p_locom": p_locom,
        # diagnostics: real surface-cell count vs the K slots (overflow
        # check for max_points; tests/bench assert n_surf <= K)
        "n_surf": n_surf,
    }
    if per_point:
        # per-surface-point record (the reference's ObstacleBlock
        # per-point arrays pX..pZ / P / fxP..fzV / vX..vzDef,
        # main.cpp:12300-12330 fill): (K, ...) slot arrays — host
        # consumers compact on the surf mask (compact_surface_points)
        out["points"] = {
            "surf": surf,
            "x": xc,
            "n_dS": n_meas,
            "dS": dS,
            "p": P,
            "fP": fP,
            "fV": fV,
            "v": v_base,
            "vdef": u_base,
        }
    return out


# ---------------------------------------------------------------------------
# window extraction: dense local neighborhoods around one obstacle
# ---------------------------------------------------------------------------


def probe_margin(length: float, h: float) -> float:
    """Half-extent of an obstacle's working AABB: body half-length plus an
    8h band.  THE single source for the rasterizer's candidate search
    (stefanfish._rasterize_blocks) and both probe windows — these must
    stay mutually consistent or surface cells silently fall outside the
    window.  8h also covers the pipelined host-mirror staleness (~8 steps
    x CFL*h <= 3.2h of position drift, sim/pack.py)."""
    return 0.625 * length + 8.0 * h


def window_size_cells(length: float, h: float, bs: int = 8) -> int:
    """Static window edge (cells): 2x probe_margin, rounded up to whole
    blocks so AMR gathers stay block-granular and jit retraces only on
    bucket changes."""
    half = probe_margin(length, h)
    return int(-(-2.0 * half / h // bs) * bs)


def probe_max_points(length: float, h) -> int:
    """Static surface-point slot budget for the compacted probe, with no
    prior measurement.  The Towers band holds ~(L/h)^2 cells for a fish
    (measured 1.02x at 128^3) and ~pi (L/h)^2 for a sphere of diameter L,
    but the wide sine-mollifier chi (ops/chi.heaviside, tests/diagnostics)
    carries ~18 (L/h)^2 — 20x covers every construction.  Rounded to 1024
    so jit retraces only on resolution buckets.  Steady-state consumers
    tighten this to ~4x the MEASURED band via obstacle_probe_budget
    (n_surf rides the packed force QoI)."""
    n = 20.0 * (float(length) / float(h)) ** 2
    return int(max(4096, -(-n // 1024) * 1024))


def obstacle_probe_budget(ob, h) -> int:
    """Per-obstacle slot budget: once a measured band size is available
    (ob.n_surf_points, refreshed by every packed force read), budget 4x
    the measurement; hysteresis keeps the previous budget while it stays
    within [2x, 8x] measured, so steady swimming never retraces.  Safe
    either way: surface_force_window truncates top-K by dS (smallest-
    measure tail dropped first) and n_surf keeps reporting the true
    count."""
    n = float(getattr(ob, "n_surf_points", 0) or 0)
    prev = int(getattr(ob, "_probe_budget", 0) or 0)
    if n > 0 and np.isfinite(n):
        if prev and 2.0 * n <= prev <= 8.0 * n:
            return prev
        b = int(max(4096, -(-4.0 * n // 1024) * 1024))
    elif prev:
        return prev
    else:
        b = probe_max_points(ob.length, h)
    ob._probe_budget = b
    return b


@partial(jax.jit, static_argnames=("wcells", "per_point", "max_points"))
def _uniform_window_probe(vel, p, chi, sdf, udef, idx0, h, origin0, nu,
                          cm, u_trans, omega, wcells, per_point=False,
                          max_points=None):
    sl3 = (wcells,) * 3
    wv = jax.lax.dynamic_slice(vel, (idx0[0], idx0[1], idx0[2], 0),
                               sl3 + (3,))
    wu = jax.lax.dynamic_slice(udef, (idx0[0], idx0[1], idx0[2], 0),
                               sl3 + (3,))
    wp = jax.lax.dynamic_slice(p, tuple(idx0), sl3)
    wc = jax.lax.dynamic_slice(chi, tuple(idx0), sl3)
    ws = jax.lax.dynamic_slice(sdf, tuple(idx0), sl3)
    loc = jnp.stack(
        jnp.meshgrid(*[jnp.arange(wcells, dtype=vel.dtype) + 0.5] * 3,
                     indexing="ij"),
        axis=-1,
    )
    xc = origin0 + (idx0.astype(vel.dtype) + loc) * h
    valid = jnp.ones(sl3, bool)
    return surface_force_window(
        wv, wp, wc, ws, wu, valid, xc, h, nu, cm, u_trans, omega,
        per_point=per_point, max_points=max_points,
    )


def force_integrals_probe_uniform(grid, ob, vel, p, chi, sdf, udef, nu,
                                  cm, u_trans, omega,
                                  per_point: bool = False,
                                  max_points: int | None = None):
    """Uniform-grid driver entry: AABB window around the obstacle."""
    n = np.asarray(grid.shape)
    w = window_size_cells(ob.length, grid.h)
    w = int(min(w, n.min()))
    half = 0.5 * w * grid.h
    pos = np.asarray(ob.position)
    idx0 = np.clip(
        np.floor((pos - half) / grid.h).astype(np.int64), 0, n - w
    )
    if max_points is None:
        max_points = obstacle_probe_budget(ob, grid.h)
    return _uniform_window_probe(
        vel, p, chi, sdf, udef, jnp.asarray(idx0, jnp.int32),
        jnp.asarray(grid.h, vel.dtype), jnp.zeros(3, vel.dtype), nu,
        jnp.asarray(cm, vel.dtype), jnp.asarray(u_trans, vel.dtype),
        jnp.asarray(omega, vel.dtype), wcells=w, per_point=per_point,
        max_points=max_points,
    )


def block_window_slots(grid, position: np.ndarray, length: float):
    """Host: finest-level block slots covering the obstacle AABB.
    Returns (slots (nbx,nby,nbz) int32 with -1 for positions not owned at
    the finest level, window block origin (3,) ints, h_fine).

    The window SIZE depends only on (length, h, domain) — never on the
    position — so jitted consumers (the pipelined megastep) retrace only
    on re-layouts, not when the body crosses a block boundary."""
    lmax = len(grid._slot_maps) - 1
    h = grid.h0 / (1 << lmax)
    bs = grid.bs
    nbd = np.asarray(grid.tree.blocks_per_dim(lmax))
    half = probe_margin(length, h)
    nwin = np.minimum(int(np.ceil(2.0 * half / (bs * h))) + 1, nbd)
    b0 = np.floor((position - half) / (bs * h)).astype(np.int64)
    b0 = np.clip(b0, 0, nbd - nwin)
    rng = [np.arange(b0[a], b0[a] + nwin[a]) for a in range(3)]
    slots = grid._slot_maps[lmax][np.ix_(*rng)].astype(np.int32)
    return slots, b0, h


@jax.jit
def _gather_block_window(field, slots):
    """(nb, bs, bs, bs[,C]) + (nbx,nby,nbz) slots -> dense window; rows
    with slot -1 fill with zeros."""
    nbx, nby, nbz = slots.shape
    bs = field.shape[1]
    flat = jnp.take(field, slots.reshape(-1), axis=0, mode="fill",
                    fill_value=0)
    trail = field.shape[4:]
    wi = flat.reshape((nbx, nby, nbz, bs, bs, bs) + trail)
    wi = jnp.moveaxis(wi, 3, 1)  # (nbx, bs, nby, nbz, bs, bs, ...)
    wi = jnp.moveaxis(wi, 4, 3)
    return wi.reshape((nbx * bs, nby * bs, nbz * bs) + trail)


def probe_blocks_core(vel, p, ob_chi, ob_sdf, ob_udef, slots, b0, h, nu,
                      cm, u_trans, omega, per_point: bool = False,
                      max_points: int | None = None):
    """Traceable AMR probe core: gather the finest-level holding blocks
    into a dense window (block-granular takes) and run the surface probe.
    ``slots``: (nbx,nby,nbz) int32 block slots, -1 where the position is
    not owned at the finest level — those window cells are invalid and
    probes fall back to shorter stencils there, mirroring the reference's
    lab-range cascade.  ``b0``: (3,) window origin in finest-block units.
    Callable inside jit (the pipelined megastep) or via the jitted
    wrapper below."""
    wv = _gather_block_window(vel, slots)
    wp = _gather_block_window(p, slots)
    wc = _gather_block_window(ob_chi, slots)
    ws = _gather_block_window(ob_sdf, slots)
    wu = _gather_block_window(ob_udef, slots)
    bs = vel.shape[1]
    valid = jnp.repeat(
        jnp.repeat(jnp.repeat(slots >= 0, bs, 0), bs, 1), bs, 2
    )
    shape = wv.shape[:3]
    dtype = wv.dtype
    loc = jnp.stack(
        jnp.meshgrid(*[jnp.arange(s, dtype=dtype) + 0.5 for s in shape],
                     indexing="ij"),
        axis=-1,
    )
    xc = (b0.astype(dtype) * bs + loc) * h
    return surface_force_window(
        wv, wp, wc, ws, wu, valid, xc, h, nu, cm, u_trans, omega,
        per_point=per_point, max_points=max_points,
    )


_probe_blocks_jit = jax.jit(
    probe_blocks_core, static_argnames=("nu", "per_point", "max_points")
)
_probe_blocks_pts_jit = partial(_probe_blocks_jit, per_point=True)


def force_integrals_probe_blocks(grid, state_fields, ob_chi, ob_sdf,
                                 ob_udef, nu, position, length, cm,
                                 u_trans, omega, per_point: bool = False,
                                 max_points: int | None = None):
    """Host-calling AMR entry: host computes the window slots, the jitted
    core does the rest."""
    slots, b0, h = block_window_slots(grid, np.asarray(position), length)
    vel, p = state_fields["vel"], state_fields["p"]
    dtype = vel.dtype
    if max_points is None:
        max_points = probe_max_points(length, h)
    fn = _probe_blocks_pts_jit if per_point else _probe_blocks_jit
    return fn(
        vel, p, ob_chi, ob_sdf, ob_udef, jnp.asarray(slots),
        jnp.asarray(b0, jnp.int32), jnp.asarray(h, dtype), float(nu),
        jnp.asarray(cm, dtype), jnp.asarray(u_trans, dtype),
        jnp.asarray(omega, dtype), max_points=max_points,
    )


# ---------------------------------------------------------------------------
# per-surface-point export (reference per-point arrays, main.cpp:12300-12330)
# ---------------------------------------------------------------------------

SURFACE_POINT_COLUMNS = (
    "x", "y", "z",              # surface-cell center
    "nx_dS", "ny_dS", "nz_dS",  # outward normal * dS
    "dS",
    "p",                        # surface-cell pressure
    "fxP", "fyP", "fzP",        # pressure traction * dS
    "fxV", "fyV", "fzV",        # viscous traction * dS
    "vx", "vy", "vz",           # fluid velocity at the surface cell
    "vxDef", "vyDef", "vzDef",  # body deformation velocity
)


def compact_surface_points(pts: Dict[str, jnp.ndarray]) -> np.ndarray:
    """Masked-dense window per-point record -> compact (n_pts, 20) host
    array, columns as SURFACE_POINT_COLUMNS.  One device fetch of the
    dense stack; the ragged compaction happens host-side (the TPU keeps
    static shapes, the reference's ragged surface_data lists are a host
    format)."""
    dense = jnp.concatenate(
        [pts["x"], pts["n_dS"], pts["dS"][..., None], pts["p"][..., None],
         pts["fP"], pts["fV"], pts["v"], pts["vdef"]],
        axis=-1,
    )
    mask = np.asarray(pts["surf"]).reshape(-1)
    flat = np.asarray(dense, np.float64).reshape(-1, dense.shape[-1])
    return flat[mask]


def dump_surface_points(path: str, grid, state_fields, ob, nu) -> int:
    """Write one obstacle's compacted surface-point record (positions,
    measures, tractions, velocities) to ``path`` (.npy via np.save).
    Returns the number of surface points written.  RL/logging parity with
    the reference's per-point ObstacleBlock arrays.  Dispatches on the
    grid type: AMR block forest or dense uniform grid."""
    if hasattr(grid, "_slot_maps"):  # BlockGrid
        out = force_integrals_probe_blocks(
            grid, state_fields, ob.chi, ob.sdf, ob.udef, nu, ob.position,
            ob.length, ob.centerOfMass, ob.transVel, ob.angVel,
            per_point=True,
        )
    else:
        out = force_integrals_probe_uniform(
            grid, ob, state_fields["vel"], state_fields["p"], ob.chi,
            ob.sdf, ob.udef, nu, ob.centerOfMass, ob.transVel, ob.angVel,
            per_point=True,
        )
    rows = compact_surface_points(out["points"])
    np.save(path, rows)
    return rows.shape[0]
