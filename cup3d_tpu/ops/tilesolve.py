"""Exact 8^3-tile Poisson/Helmholtz solve by fast diagonalization — the
round-4 getZ preconditioner.

The reference's getZ preconditioner (poisson_kernels, main.cpp:14617-14746)
approximately solves (-lap_tile + shift) z = b on every 8^3 block with the
tile's implicit zero-Dirichlet halo, via CG iterated to a tolerance.  Round
2/3 ran a fixed-24-sweep CG in a Pallas VMEM kernel (ops/getz_pallas.py),
~0.96 ms per application at 128^3 on a v5e — all VPU work.

TPU-first observation: the zero-Dirichlet 7-point Laplacian on a fixed 8^3
tile is diagonalized by the 8-point discrete sine transform (DST-I), so the
EXACT tile inverse is the fixed 512x512 matrix

    W = S3 diag(1/lam) S3^T,   S3 = S (x) S (x) S,
    S[k,i] = sqrt(2/9) sin(pi (i+1)(k+1)/9),
    lam[i,j,k] = 4 [sin^2(pi(i+1)/18) + sin^2(pi(j+1)/18) + sin^2(pi(k+1)/18)]

and one application is ONE (512,512)@(512,T) matmul — MXU work in any
layout, ~7x the Pallas CG kernel at 128^3 and exact (= infinitely many CG
sweeps, so the outer Krylov solve sees a strictly stronger preconditioner).
The shifted variant (diffusion getZ, coefficient -6 - h^2/(nu dt),
main.cpp:10571) keeps the split form S3 [ (S3^T b) / (lam + shift) ] so a
traced, per-block shift stays a cheap row-wise divide between the two
matmuls.

Matmul precision is HIGHEST (3-pass bf16 ~ f32): measured at 128^3, a
DEFAULT-precision (single-pass bf16) preconditioner makes the outer
BiCGSTAB stagnate (133+ iterations vs 50) — the ~4e-3 rounding noise acts
as a nonlinear perturbation the short recurrence cannot absorb.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

_HI = jax.lax.Precision.HIGHEST


@lru_cache(maxsize=None)
def _basis_np(bs: int, np_dtype: str):
    """(S3, lam512, W) for the bs^3 zero-Dirichlet tile, built in f64.
    Cached as NUMPY arrays — jnp conversion happens at each call site so a
    trace-time first call cannot leak tracers into the cache."""
    i = np.arange(1, bs + 1)
    S1 = np.sqrt(2.0 / (bs + 1)) * np.sin(np.pi * np.outer(i, i) / (bs + 1))
    lam1 = 4.0 * np.sin(np.pi * i / (2 * (bs + 1))) ** 2  # eig of -[1,-2,1]
    lam3 = (lam1[:, None, None] + lam1[None, :, None]
            + lam1[None, None, :]).reshape(bs ** 3)
    S3 = np.einsum("ai,bj,ck->abcijk", S1, S1, S1).reshape(bs ** 3, bs ** 3)
    W = (S3 * (1.0 / lam3)) @ S3.T
    dt = np.dtype(np_dtype)
    return (S3.astype(dt), lam3.astype(dt), W.astype(dt))


def _basis(bs: int, np_dtype: str):
    S3, lam3, W = _basis_np(bs, np_dtype)
    return jnp.asarray(S3), jnp.asarray(lam3), jnp.asarray(W)


def tile_solve_blocks(b: jnp.ndarray, shift=None) -> jnp.ndarray:
    """Solve (-lap_tile + shift) z = b on every trailing-bs^3 tile of ``b``
    (shape (..., bs, bs, bs)), exactly.

    ``shift`` may be None (pure Poisson getZ), a scalar, or an array
    broadcastable over the leading dims (e.g. the per-block h^2/(nu dt) of
    the AMR diffusion getZ) — traced values are fine.
    """
    bs = b.shape[-1]
    lead = b.shape[:-3]
    n = int(np.prod(lead)) if lead else 1
    # basis + matmuls in the ACCUMULATION dtype (>= f32): a bf16 basis
    # degrades the preconditioner enough to stall the outer BiCGSTAB
    # (see module docstring) — sub-f32 inputs are solved in f32 and
    # rounded on the way out (ops/precision.py policy, round 12)
    acc = jnp.promote_types(b.dtype, jnp.float32)
    S3, lam3, W = _basis(bs, jnp.dtype(acc).name)
    b2 = b.reshape(n, bs ** 3).astype(acc)
    # always the split form: measured in-loop on the axon TPU, ONE
    # (n,512)x(512,512) HIGHEST matmul costs ~320us while the TWO split
    # matmuls cost ~23us total (validation/prof_xla_prims.py) — the
    # single-pass W form is never worth it
    if shift is None:
        sh = jnp.zeros((n, 1), acc)
    else:
        sh = jnp.broadcast_to(jnp.asarray(shift, acc),
                              lead + (1, 1, 1)).reshape(n, 1)
    t = jax.lax.dot(b2, S3, precision=_HI)  # S3 symmetric: rows @ S3
    t = t / (lam3[None, :] + sh)
    z = jax.lax.dot(t, S3, precision=_HI)
    return z.reshape(b.shape).astype(b.dtype)


def tile_solve_lanes(bt: jnp.ndarray, shift=None) -> jnp.ndarray:
    """Same solve in the lane-resident (bs, bs, bs, T) layout the uniform
    Krylov path keeps every field in (krylov.make_laplacian_lanes).

    ``shift``: None, scalar, or a (T,)-broadcastable lane vector.
    """
    bs = bt.shape[0]
    T = bt.shape[-1]
    # accumulate in >= f32 regardless of storage dtype (see
    # tile_solve_blocks / ops/precision.py)
    acc = jnp.promote_types(bt.dtype, jnp.float32)
    S3, lam3, W = _basis(bs, jnp.dtype(acc).name)
    b2 = bt.reshape(bs ** 3, T).astype(acc)
    # split form always — see tile_solve_blocks
    if shift is None:
        sh = jnp.zeros((1, T), acc)
    else:
        sh = jnp.broadcast_to(jnp.asarray(shift, acc), (1, T))
    t = jax.lax.dot(S3, b2, precision=_HI)
    t = t / (lam3[:, None] + sh)
    z = jax.lax.dot(S3, t, precision=_HI)
    return z.reshape(bt.shape).astype(bt.dtype)
