"""Explicit advection-diffusion: the reference's ``AdvectionDiffusion``
operator (main.cpp:9461-9728) rebuilt as fused dense stencils.

RHS(u) = -((u + uinf) . grad) u + nu lap(u), with the reference's 5th-order
6-point biased-upwind advective derivatives and a 2nd-order 7-point viscous
Laplacian, advanced by low-storage RK3 (main.cpp:9640-9728).
"""

from __future__ import annotations

import jax.numpy as jnp

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops import stencils as st

GHOSTS = 3  # 5th-order upwind needs 3 ghost cells

# Low-storage RK3 (Williamson) — same scheme as the reference's
# coefficients {1/3, 15/16, 8/15} / {0, -5/9, -153/128}.
RK3_A = (0.0, -5.0 / 9.0, -153.0 / 128.0)
RK3_B = (1.0 / 3.0, 15.0 / 16.0, 8.0 / 15.0)


def advection_diffusion_rhs(grid: UniformGrid, u: jnp.ndarray, nu: float,
                            uinf: jnp.ndarray, pad=None) -> jnp.ndarray:
    """du/dt from advection + diffusion on the uniform grid.

    u: (nx, ny, nz, 3) velocity in the body/lab frame.
    uinf: (3,) frame velocity added to the advecting field only.
    pad: optional ``(u, width) -> padded`` ghost supplier replacing
    ``grid.pad_vector`` — the x-slab decomposition injects the
    ring-halo pad (parallel/ring.pad_slab_vector) here so the stencil
    body itself stays layout-agnostic.
    """
    h = grid.h
    up = grid.pad_vector(u, GHOSTS) if pad is None else pad(u, GHOSTS)
    uadv = [u[..., c] + uinf[c] for c in range(3)]
    out = []
    for c in range(3):
        comp = up[..., c]
        adv = sum(
            uadv[a] * st.d1_upwind5(comp, GHOSTS, a, uadv[a], h) for a in range(3)
        )
        dif = st.laplacian(comp, GHOSTS, h) * nu
        out.append(dif - adv)
    return jnp.stack(out, axis=-1)


def rk3_step(grid: UniformGrid, u: jnp.ndarray, dt, nu: float,
             uinf: jnp.ndarray, pad=None) -> jnp.ndarray:
    """One explicit low-storage RK3 advection-diffusion step."""
    k = jnp.zeros_like(u)
    for a, b in zip(RK3_A, RK3_B):
        k = a * k + dt * advection_diffusion_rhs(grid, u, nu, uinf,
                                                 pad=pad)
        u = u + b * k
    return u
