"""Implicit diffusion: the TPU rebuild of the reference's DiffusionSolver +
AdvectionDiffusionImplicit (main.cpp:6719-7147, 9849-10118, 10448-10580).

The reference advances advection with an explicit Euler kernel
(``KernelAdvect``) and then solves, per velocity component, the Helmholtz
system

    (I - nu dt lap) u = u*            (u* = post-advection velocity)

with the same pipelined BiCGSTAB it uses for pressure, preconditioned by a
shifted per-block CG ("getZ" with coefficient -6 - h^2/(nu dt),
main.cpp:10571), and with per-component velocity boundary labs
(``BlockLabBC<direction>``, main.cpp:6851-6862).

TPU design:

- **Uniform grid — exact diagonalization.**  The 7-point Helmholtz operator
  with periodic / copy-edge / sign-flip ghosts is diagonalized exactly by
  per-axis orthonormal bases: real Fourier (periodic), DCT-II (copy-edge,
  i.e. zero-gradient ghosts), and DST-II (sign-flip ghosts: the
  antisymmetric ghost = -edge convention of wall/freespace faces).  The
  whole solve is 6 dense matmuls on the MXU plus one elementwise scale —
  exact, unconditionally stable, and compile-friendly (no data-dependent
  iteration count).  The basis choice per (axis, component) mirrors
  ``uniform._pad``: flip when wall, or freespace on the face-normal
  component.
- **AMR forest — shifted getZ + BiCGSTAB.**  Reuses the Poisson Krylov
  machinery (ops/krylov.py) with the Helmholtz operator on per-component
  block labs (sign-correct ghosts) and the shifted block-CG preconditioner:
  solving (-block_lap + h^2/(nu dt)) z = (h^2/(nu dt)) r per 8^3 tile is
  exactly the reference's diffusion getZ.  The previous velocity is the
  warm start (the solution is an O(nu dt) perturbation of the rhs).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.blocks import BlockGrid, LabTables
from cup3d_tpu.grid.flux import FluxTables
from cup3d_tpu.grid.uniform import BC, UniformGrid
from cup3d_tpu.ops import stencils as st
from cup3d_tpu.ops.amr_ops import _sh
from cup3d_tpu.ops.poisson import dct2_matrix, rfourier_matrix

_HI = jax.lax.Precision.HIGHEST


# ---------------------------------------------------------------------------
# uniform grid: exact spectral Helmholtz
# ---------------------------------------------------------------------------


def dst2_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """Orthonormal DST-II basis S with X = S @ x, x = S.T @ X.

    Rows sin(theta_k (j + 1/2)), theta_k = pi (k+1) / n: the eigenbasis of
    the 1-D Laplacian with antisymmetric copy-edge ghosts (ghost = -edge),
    which is the discrete no-penetration / no-slip convention of
    ``uniform._pad``.  Eigenvalues are 2 cos(theta_k) - 2.
    """
    k = np.arange(1, n + 1)[:, None]
    j = np.arange(n)[None, :]
    s = np.sin(np.pi * k * (2 * j + 1) / (2 * n)) * np.sqrt(2.0 / n)
    s[-1] *= np.sqrt(0.5)  # k = n row is the alternating +-1 row
    return s.astype(dtype)


def _axis_basis(n: int, bc: BC, comp: int, axis: int):
    """(basis, eigenvalue array [2 cos theta - 2]) for one axis of one
    velocity component, matching the ghost convention of uniform._pad."""
    if bc == BC.periodic:
        mat, freqs = rfourier_matrix(n)
        theta = 2.0 * np.pi * freqs / n
    else:
        flip = bc == BC.wall or comp == axis
        if flip:
            mat = dst2_matrix(n)
            theta = np.pi * np.arange(1, n + 1) / n
        else:
            mat = dct2_matrix(n)
            theta = np.pi * np.arange(n) / n
    return mat, 2.0 * np.cos(theta) - 2.0


def build_spectral_helmholtz(grid: UniformGrid, dtype=jnp.float32) -> Callable:
    """Returns solve(u, nudt) -> (I - nudt lap)^{-1} u for a (nx,ny,nz,3)
    velocity field — exact per-component diagonalization (see module doc).

    ``nudt`` may be a traced scalar: the eigenvalue scale is recomputed
    elementwise per call, so per-step dt changes never retrace.
    """
    h2 = grid.h * grid.h
    per_comp = []
    for c in range(3):
        mats, lam3 = [], 0.0
        shape = [1, 1, 1]
        for a, (n, bc) in enumerate(zip(grid.shape, grid.bc)):
            mat, lam = _axis_basis(n, bc, c, a)
            mats.append(jnp.asarray(mat, dtype))
            sh = shape.copy()
            sh[a] = n
            lam3 = lam3 + lam.reshape(sh)
        per_comp.append((mats, jnp.asarray(lam3 / h2, dtype)))

    def solve(u: jnp.ndarray, nudt) -> jnp.ndarray:
        outs = []
        for c in range(3):
            mats, lam = per_comp[c]
            f = u[..., c].astype(dtype)
            for a in range(3):
                f = _apply(mats[a], f, a)
            f = f / (1.0 - nudt * lam)
            for a in range(3):
                f = _apply(mats[a].T, f, a)
            outs.append(f.astype(u.dtype))
        return jnp.stack(outs, axis=-1)

    return solve


def _apply(mat, f, axis):
    out = jnp.tensordot(mat, f, axes=([1], [axis]), precision=_HI)
    return jnp.moveaxis(out, 0, axis)


def advect_euler(grid: UniformGrid, u: jnp.ndarray, dt, uinf: jnp.ndarray):
    """Explicit advection-only Euler stage (reference KernelAdvect,
    main.cpp:9849-10029): u* = u - dt (u + uinf) . grad u, upwind5."""
    from cup3d_tpu.ops.advection import GHOSTS

    h = grid.h
    up = grid.pad_vector(u, GHOSTS)
    uadv = [u[..., c] + uinf[c] for c in range(3)]
    out = []
    for c in range(3):
        comp = up[..., c]
        adv = sum(
            uadv[a] * st.d1_upwind5(comp, GHOSTS, a, uadv[a], h)
            for a in range(3)
        )
        out.append(u[..., c] - dt * adv)
    return jnp.stack(out, axis=-1)


def implicit_step(grid: UniformGrid, u: jnp.ndarray, dt, nu: float,
                  uinf: jnp.ndarray, helmholtz: Callable) -> jnp.ndarray:
    """One AdvectionDiffusionImplicit Euler step (main.cpp:10030-10118):
    explicit advection, then the exact implicit diffusion solve."""
    ustar = advect_euler(grid, u, dt, uinf)
    return helmholtz(ustar, nu * dt)


# ---------------------------------------------------------------------------
# AMR forest: Helmholtz BiCGSTAB with shifted getZ
# ---------------------------------------------------------------------------


def helmholtz_comp_blocks(
    grid: BlockGrid,
    x: jnp.ndarray,
    tab: LabTables,
    nudt,
    comp: int,
    flux_tab: Optional[FluxTables] = None,
    inv_h=None,
) -> jnp.ndarray:
    """(I - nudt lap) x on one velocity component of the forest, with the
    component's BC sign ghosts and diffusive-flux refluxing — the AMR
    Helmholtz operator (reference DiffusionSolver LHS, main.cpp:6726-6801)."""
    from cup3d_tpu.ops.amr_ops import face_fluxes

    bs = grid.bs
    w = tab.width
    if inv_h is None:
        inv_h = 1.0 / jnp.asarray(grid.h.reshape(grid.nb, 1, 1, 1), x.dtype)
    lab = tab.assemble_component(x, bs, comp)
    c = _sh(lab, w, bs)
    s = -6.0 * c
    for ax in range(3):
        o = [0, 0, 0]
        o[ax] = 1
        s = s + _sh(lab, w, bs, *o)
        o[ax] = -1
        s = s + _sh(lab, w, bs, *o)
    lap = s * inv_h * inv_h
    if flux_tab is not None and flux_tab.ncorr:
        fluxes = face_fluxes(lab, w, bs, inv_h)
        lap = flux_tab.apply(lap, fluxes)
    return x - nudt * lap


def build_amr_helmholtz_solver(
    grid: BlockGrid,
    tol_abs: float = 1e-6,
    tol_rel: float = 1e-4,
    maxiter: int = 1000,
    precond_iters: int = 24,
    tab: Optional[LabTables] = None,
    flux_tab: Optional[FluxTables] = None,
) -> Callable:
    """solve(u, nudt) -> (I - nudt lap)^{-1} u per component on the forest:
    the reference DiffusionSolver (main.cpp:6896-7146) with the shifted
    getZ preconditioner (diffusion_kernels, main.cpp:10448-10580).
    ``tab``/``flux_tab`` may be pre-built or the sharded forest's
    duck-typed equivalents."""
    from cup3d_tpu.grid.flux import build_flux_tables
    from cup3d_tpu.ops import krylov

    if tab is None:
        tab = grid.lab_tables(1)
    if flux_tab is None:
        flux_tab = build_flux_tables(grid)
    h2 = jnp.asarray((grid.h**2).reshape(grid.nb, 1, 1, 1), jnp.float32)
    inv_h = 1.0 / jnp.sqrt(h2)

    def solve(u: jnp.ndarray, nudt, tab_arg=None, flux_arg=None,
              geom=None) -> jnp.ndarray:
        # like the Poisson front-end, jitted callers pass the tables as
        # traced ARGUMENTS so they are runtime buffers, not HLO constants
        # (compile-payload rule; ADVICE r2).  ``geom`` (a bucketed
        # duck-grid with a TRACED h — sim/amr._ArgGeom) makes the
        # per-block scale a runtime value too, so one built solve serves
        # every regrid of a capacity bucket without retracing.
        t = tab if tab_arg is None else tab_arg
        ft = flux_tab if flux_arg is None else flux_arg
        if geom is None:
            g_, h2_, inv_h_ = grid, h2, inv_h
        else:
            g_ = geom
            h2_ = jnp.reshape(
                jnp.asarray(g_.h, u.dtype), (g_.nb, 1, 1, 1)
            ) ** 2
            inv_h_ = 1.0 / jnp.sqrt(h2_)
        shift = h2_ / nudt  # per-block; reference coeff -6 - h^2/(nu dt)
        outs = []
        for c in range(3):
            b = u[..., c]

            def A(x, _c=c):
                return helmholtz_comp_blocks(
                    g_, x, t, nudt, _c, ft, inv_h_
                )

            def M(r):
                return krylov.getz_blocks(shift * r, shift=shift,
                                          cg_iters=precond_iters)

            # x0=b is a warm start: rel tolerance must reference the cold
            # RHS norm or the good start tightens the target and costs
            # iterations (krylov.bicgstab rnorm_ref)
            x, _, _ = krylov.bicgstab(
                A, b, M=M, x0=b, tol_abs=tol_abs, tol_rel=tol_rel,
                maxiter=maxiter,
                rnorm_ref=jnp.sqrt(jnp.sum(b * b, dtype=jnp.float32)),
            )
            outs.append(x)
        return jnp.stack(outs, axis=-1)

    return solve


def advect_euler_blocks(
    grid: BlockGrid,
    vel: jnp.ndarray,
    dt,
    uinf: jnp.ndarray,
    tab: LabTables,
) -> jnp.ndarray:
    """Explicit advection-only Euler stage on the forest (KernelAdvect)."""
    from cup3d_tpu.ops.amr_ops import _hcol, _upwind_d1

    bs = grid.bs
    w = tab.width
    vlab = tab.assemble_vector(vel, bs)
    inv_h = 1.0 / _hcol(grid, vel.dtype)
    adv_u = _sh(vlab, w, bs) + uinf
    out = []
    for c in range(3):
        lab_c = vlab[..., c]
        conv = 0.0
        for a in range(3):
            conv = conv + adv_u[..., a] * _upwind_d1(
                lab_c, w, bs, a, adv_u[..., a], inv_h
            )
        out.append(vel[..., c] - dt * conv)
    return jnp.stack(out, axis=-1)


def implicit_step_blocks(
    grid: BlockGrid,
    vel: jnp.ndarray,
    dt,
    nu: float,
    uinf: jnp.ndarray,
    tab: LabTables,
    solver: Callable,
) -> jnp.ndarray:
    """AdvectionDiffusionImplicit on the forest (main.cpp:10030-10118)."""
    ustar = advect_euler_blocks(grid, vel, dt, uinf, tab)
    return solver(ustar, nu * dt)
