"""Brinkman penalization (reference Penalization kernel,
main.cpp:13841-13912).

Implicit form: u^{n+1} = u + (lambda chi dt / (1 + lambda chi dt)) (u_body - u),
where u_body = u_trans + omega x r + u_def is the obstacle's local solid-body
+ deformation velocity.  Operating on the dense chi/ubody fields makes this a
single fused elementwise kernel over the whole domain.
"""

from __future__ import annotations

import jax.numpy as jnp


def penalize(vel: jnp.ndarray, chi: jnp.ndarray, ubody: jnp.ndarray,
             lam, dt) -> jnp.ndarray:
    """vel, ubody: (...,3); chi in [0,1]; lam, dt scalars."""
    x = lam * dt * chi
    fac = (x / (1.0 + x))[..., None]
    return vel + fac * (ubody - vel)


def penalization_force(vel_new: jnp.ndarray, vel_old: jnp.ndarray, dt,
                       h: float) -> jnp.ndarray:
    """Instantaneous penalization force density integrand
    F = (u^{n+1} - u^n)/dt * h^3 (reference force reduction, main.cpp:13913-13938)."""
    return (vel_new - vel_old) * (h ** 3 / dt)


def per_obstacle_penalization_force(
    vel_new: jnp.ndarray,
    vel_old: jnp.ndarray,
    chis,
    dt,
    vol: jnp.ndarray,
    xc: jnp.ndarray,
    cms: jnp.ndarray,
    eps: float = 1e-6,
) -> jnp.ndarray:
    """Per-obstacle momentum-balance force/torque from the penalization
    update (the reference's kernelFinalizePenalizationForce,
    main.cpp:13913-13938: obst->force/torque come from the per-cell
    (u^{n+1}-u^n)/dt sums inside each obstacle's blocks).

    chis: tuple of per-obstacle chi fields; overlap cells are attributed
    by chi fraction.  vol broadcasts per cell ((nb,1,1,1) or scalar h^3).
    Returns a stacked (n_obs, 6) array [force(3), torque(3)] — one host
    read for all obstacles."""
    df = (vel_new - vel_old) / dt  # force density / cell volume
    chi_sum = sum(chis)
    den = jnp.maximum(chi_sum, eps)
    out = []
    for i, chi in enumerate(chis):
        w = chi / den  # overlap-fractional weight
        wv = (w * vol)[..., None]
        f = jnp.sum(df * wv, axis=tuple(range(df.ndim - 1)))
        r = xc - cms[i]
        t = jnp.sum(jnp.cross(r, df) * wv, axis=tuple(range(df.ndim - 1)))
        out.append(jnp.concatenate([f, t]))
    return jnp.stack(out)
