"""Brinkman penalization (reference Penalization kernel,
main.cpp:13841-13912).

Implicit form: u^{n+1} = u + (lambda chi dt / (1 + lambda chi dt)) (u_body - u),
where u_body = u_trans + omega x r + u_def is the obstacle's local solid-body
+ deformation velocity.  Operating on the dense chi/ubody fields makes this a
single fused elementwise kernel over the whole domain.
"""

from __future__ import annotations

import jax.numpy as jnp


def penalize(vel: jnp.ndarray, chi: jnp.ndarray, ubody: jnp.ndarray,
             lam, dt) -> jnp.ndarray:
    """vel, ubody: (...,3); chi in [0,1]; lam, dt scalars."""
    x = lam * dt * chi
    fac = (x / (1.0 + x))[..., None]
    return vel + fac * (ubody - vel)


def penalization_force(vel_new: jnp.ndarray, vel_old: jnp.ndarray, dt,
                       h: float) -> jnp.ndarray:
    """Instantaneous penalization force density integrand
    F = (u^{n+1} - u^n)/dt * h^3 (reference force reduction, main.cpp:13913-13938)."""
    return (vel_new - vel_old) * (h ** 3 / dt)
