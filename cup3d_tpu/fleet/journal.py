"""Write-ahead job journal: the fleet's durability layer (round 23).

A :class:`JobJournal` records every job lifecycle transition — submit,
placement into a batch lane, terminal — plus periodic host-serialized
lane-carry snapshots at K-boundaries, so a killed-and-restarted
``FleetServer`` (``FleetServer.recover()``) finishes every accepted job
with QoI bytes identical to a never-crashed run: terminal jobs are
remembered (their recorded rows ARE the bytes), queued jobs re-admitted,
and RUNNING jobs resumed from their latest carry snapshot through the
jitted ``fleet/batch.reseed_lane_carry`` upload.

Storage is one self-contained checksummed segment file per record,
``<seq>.jrec`` under the journal root, written through
``resilience/writeguard.atomic_write`` (tmp + fsync-free ``os.replace``
promotion with counted retries) — append-only in the sense that a
promoted segment is never rewritten, and a torn write can only ever
leave a tmp file behind, never a half-promoted segment.  Each segment is
``MAGIC + blake2s(payload).hexdigest() + "\\n" + pickle(payload)`` —
the aot/store.py artifact frame, applied to lifecycle records.

Defect taxonomy (the AOT-store discipline): a segment that fails to
load is counted ``journal.rejects{reason}`` — ``io`` / ``magic`` /
``truncated`` / ``checksum`` / ``unpickle`` / ``schema`` — and SKIPPED;
replay continues with every healthy segment.  A corrupt journal can
cost at most the re-execution between a job's last healthy snapshot and
the crash; it can never crash recovery or corrupt a result (resumed
lanes recompute from a validated carry, and ``FleetJob.record`` is
keyed by step, so re-applied rows are byte-idempotent).

Appends are best-effort by design: the serve loop must never die
because the journal disk did.  A write failure (after writeguard's
retries — the ``journal.write_fail`` chaos site fires inside the write
seam, so a transient fault is absorbed by the retry with a counted
``resilience.write_retries{site=fleet-journal}``) is counted
``journal.append_failures`` and dropped; durability degrades to the
previous healthy record, correctness is untouched.

Record types (``schema`` 1):

``submit``    job_id, tenant, spec, nsteps — admission happened.
``place``     job_id, batch_uid, lane, cap, K, kind — the job became
              RUNNING in a lane (first assembly or a reseed splice).
``snapshot``  job_id, batch_uid, cap, K, kind, lane, step, left,
              steps_done, time, rows[:steps_done], carry (host copies
              of the lane's carry leaves) — taken at the same settled
              K-boundary as the rollback snapshot, so it is always a
              validated state.
``terminal``  job_id, status, error, steps_done, time, nsteps, rows —
              done/failed/cancelled/migrated; the recorded rows make
              the job's QoI bytes reconstructible without re-running.

Replay folds records seq-ascending with latest-wins per job, so
replaying the same journal twice — or a journal extended by a recovered
server's own appends — is a no-op for already-known jobs.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from collections import OrderedDict
from typing import Dict, List, Optional

from cup3d_tpu.obs import metrics as M
from cup3d_tpu.obs import trace as OT
from cup3d_tpu.resilience import faults, writeguard

#: bump when record keys/meaning change; recovery rejects (reason
#: "schema") rather than misreads segments from another journal era
SCHEMA = 1

MAGIC = b"CUP3DJRN1\n"

#: record types a healthy journal may carry
RECORD_TYPES = ("submit", "place", "snapshot", "terminal")

#: statuses replay treats as terminal (mirrors fleet/server.py — kept
#: as literals so the journal never imports the server)
TERMINAL_STATUSES = ("done", "failed", "cancelled", "migrated")


class JournalReject(Exception):
    """One segment failed to load; ``reason`` matches the
    ``journal.rejects`` counter label."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class JobJournal:
    """Append-only checksummed segment store for job lifecycle records."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # continue numbering after the largest existing segment so a
        # recovered server appends AFTER the journal it replayed
        self._seq = 1 + max(
            (self._seq_of(name) for name in os.listdir(root)), default=-1)

    @staticmethod
    def _seq_of(name: str) -> int:
        if not name.endswith(".jrec"):
            return -1
        try:
            return int(name[:-5])
        # jax-lint: allow(JX009, a foreign file in the journal dir is
        # not a segment; replay counts it as a reject, not a crash)
        except ValueError:
            return -1

    def path_for(self, seq: int) -> str:
        return os.path.join(self.root, f"{seq:010d}.jrec")

    # -- append ------------------------------------------------------------

    def append(self, rtype: str, **fields) -> Optional[str]:
        """Write one record as a fresh segment; returns its path, or
        None when the write failed (counted, never raised — the serve
        loop outlives the journal disk)."""
        seq = self._seq
        rec = dict(fields)
        rec.update(schema=SCHEMA, seq=seq, type=str(rtype),
                   wall=OT.wall())
        inner = pickle.dumps(rec, protocol=4)
        blob = (MAGIC + hashlib.blake2s(inner).hexdigest().encode()
                + b"\n" + inner)

        def write(tmp: str, blob=blob, seq=seq) -> None:
            # the chaos site fires INSIDE the write seam: a 1-shot arm
            # is absorbed by writeguard's retry (counted
            # resilience.write_retries{site=fleet-journal}); a
            # wildcard arm exhausts the retries and surfaces below
            faults.maybe_raise("journal.write_fail", step=seq)
            with open(tmp, "wb") as f:
                f.write(blob)

        try:
            writeguard.atomic_write(self.path_for(seq), write,
                                    site="fleet-journal")
        except (OSError, faults.InjectedFault):
            M.counter("journal.append_failures", type=str(rtype)).inc()
            return None
        self._seq = seq + 1
        M.counter("journal.appends", type=str(rtype)).inc()
        return self.path_for(seq)

    # -- read / replay -----------------------------------------------------

    def _read_segment(self, path: str) -> dict:
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            raise JournalReject("io") from exc
        if not blob.startswith(MAGIC):
            raise JournalReject("magic")
        rest = blob[len(MAGIC):]
        nl = rest.find(b"\n")
        if nl < 0 or not rest[nl + 1:]:
            raise JournalReject("truncated")
        digest, inner = rest[:nl], rest[nl + 1:]
        if hashlib.blake2s(inner).hexdigest().encode() != digest:
            raise JournalReject("checksum")
        try:
            rec = pickle.loads(inner)
        except Exception as exc:
            raise JournalReject("unpickle") from exc
        if (not isinstance(rec, dict) or rec.get("schema") != SCHEMA
                or rec.get("type") not in RECORD_TYPES
                or not isinstance(rec.get("seq"), int)):
            raise JournalReject("schema")
        return rec

    def records(self) -> List[dict]:
        """Every healthy record, seq-ascending; defective segments are
        counted ``journal.rejects{reason}`` and skipped."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            M.counter("journal.rejects", reason="io").inc()
            return []
        out: List[dict] = []
        for name in names:
            if not name.endswith(".jrec"):
                continue
            try:
                out.append(self._read_segment(
                    os.path.join(self.root, name)))
            except JournalReject as rej:
                M.counter("journal.rejects", reason=rej.reason).inc()
        out.sort(key=lambda r: r["seq"])
        return out

    def replay(self) -> "OrderedDict[str, dict]":
        """Fold the journal into one view per job (submission order,
        latest record wins): ``{job_id: {tenant, spec, nsteps, status,
        error, batch_uid, cap, K, snapshot, rows, steps_done, time}}``.
        ``status`` is "queued" until a place record, "running" after,
        and the terminal status after a terminal record; ``snapshot``
        is the latest snapshot record (or None)."""
        jobs: "OrderedDict[str, dict]" = OrderedDict()
        for rec in self.records():
            rtype = rec["type"]
            jid = rec.get("job_id")
            if not isinstance(jid, str):
                M.counter("journal.rejects", reason="schema").inc()
                continue
            if rtype == "submit":
                jobs.setdefault(jid, {
                    "tenant": rec.get("tenant", "unknown"),
                    "spec": rec.get("spec", {}),
                    "nsteps": int(rec.get("nsteps", 0)),
                    "status": "queued", "error": None,
                    "batch_uid": None, "cap": None, "K": None,
                    "snapshot": None, "rows": None,
                    "steps_done": 0, "time": 0.0,
                })
                continue
            view = jobs.get(jid)
            if view is None:
                # a place/snapshot/terminal with no submit: the submit
                # segment was rejected — remember what we can
                M.counter("journal.orphan_records", type=rtype).inc()
                view = jobs.setdefault(jid, {
                    "tenant": rec.get("tenant", "unknown"),
                    "spec": rec.get("spec", {}),
                    "nsteps": int(rec.get("nsteps", 0)),
                    "status": "queued", "error": None,
                    "batch_uid": None, "cap": None, "K": None,
                    "snapshot": None, "rows": None,
                    "steps_done": 0, "time": 0.0,
                })
            if rtype == "place":
                if view["status"] not in TERMINAL_STATUSES:
                    view["status"] = "running"
                view["batch_uid"] = rec.get("batch_uid")
                view["cap"] = rec.get("cap")
                view["K"] = rec.get("K")
            elif rtype == "snapshot":
                view["snapshot"] = rec
                view["batch_uid"] = rec.get("batch_uid")
                view["cap"] = rec.get("cap")
                view["K"] = rec.get("K")
            elif rtype == "terminal":
                view["status"] = rec.get("status", "failed")
                view["error"] = rec.get("error")
                view["rows"] = rec.get("rows")
                view["steps_done"] = int(rec.get("steps_done", 0))
                view["time"] = float(rec.get("time", 0.0))
                if rec.get("nsteps"):
                    view["nsteps"] = int(rec["nsteps"])
        return jobs

    # -- observability -----------------------------------------------------

    def state(self) -> dict:
        """Segment count + byte total for ``health()["durability"]``."""
        segments = 0
        nbytes = 0
        try:
            for name in os.listdir(self.root):
                if name.endswith(".jrec"):
                    segments += 1
                    try:
                        nbytes += os.path.getsize(
                            os.path.join(self.root, name))
                    except OSError:
                        M.counter("journal.rejects", reason="io").inc()
        except OSError:
            M.counter("journal.rejects", reason="io").inc()
        return {"root": self.root, "segments": segments,
                "bytes": nbytes, "next_seq": self._seq}
