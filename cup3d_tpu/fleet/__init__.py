"""cup3d_tpu.fleet: vmapped many-simulation batching + multi-tenant
job serving.

- :mod:`fleet.batch` — the megaloop scan body vmapped over a leading
  ``lane`` (scenario) axis, with optional device sharding of the lane
  axis (CUP3D_FLEET_MESH).
- :mod:`fleet.server` — job queue, capacity-bucketed batch assembly,
  the continuous-batching serve loop (work-conserving lane reseeding
  at K-boundaries, admission control), and per-tenant QoI fan-out.
- :mod:`fleet.isolate` — per-lane fault isolation: lane-scoped
  rollback with dt-halving; healthy lanes bitwise untouched.
"""

from cup3d_tpu.fleet.batch import (  # noqa: F401
    build_fleet_advance,
    fleet_mesh,
    reseed_lane_carry,
    reseed_lane_gaits,
    stack_carries,
    stack_gaits,
)
from cup3d_tpu.fleet.server import (  # noqa: F401
    CANCELLED,
    DONE,
    FAILED,
    QUEUED,
    RUNNING,
    FleetAdmissionError,
    FleetJob,
    FleetServer,
    live_servers,
)
