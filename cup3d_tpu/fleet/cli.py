"""Headless fleet entrypoint: ``python -m cup3d_tpu fleet --scenarios
spec.json``.

The spec file is either a JSON list of scenario dicts or an object
``{"scenarios": [...], "lanes": N, "buckets": N}``.  Each scenario is a
fleet/server.py job spec (kind, nsteps, n, cfl, L/T/xpos, ...) plus an
optional ``tenant`` name.  The process drains the whole queue and
prints the per-tenant summary JSON on stdout.

``python -m cup3d_tpu fleet slo --scenarios spec.json`` drains the same
way but prints the SLO report instead: per-tenant p50/p95/p99 job
latency (from the obs/metrics.py bucketed histograms), breach counts
against the target p99, and the burn rate over the 1% error budget.
``--slo-p99``/``--slo-window`` override the CUP3D_FLEET_SLO_* knobs.

Round 17: ``--policy fifo|srb`` picks the continuous-batching reseed
order, ``--queue-depth``/``--tenant-quota`` set the admission-control
knobs, and ``--no-continuous`` falls back to the legacy
generation-drain (the occupancy baseline).

Round 18: ``--mesh`` prints the resolved 2-D (lanes, x) device-mesh
state JSON (parallel/topology.py mesh_state: axes, shape, per-device
placement, fallback count) before the drain — the operator's one-look
answer to "did the fleet actually shard, and across what".

Round 22: ``python -m cup3d_tpu fleet why [job-id] --scenarios ...``
drains the same way but prints the latency-provenance report: the
per-phase p50/p99 breakdown (admission / capacity_wait / compile_wait /
assembly / reseed_wait / dispatch / rollback_retry / retire) and, per
tenant, the burn attribution — the dominant phase of the current
window and which phase's share of end-to-end grew against the rolling
baseline.  With a job id it prints that one job's exact phase
decomposition (sums to its e2e by construction) instead — the
operator's answer to "WHY was this job slow".

Round 23: ``python -m cup3d_tpu fleet recover --workdir DIR`` boots a
server on an existing workdir, replays its write-ahead journal
(``FleetServer.recover()``), drains every surviving job, and prints a
probe-style JSON report: the recovery stats (remembered / requeued /
resumed), ``recover_restart_s`` (CLI entry -> first dispatch on the
restarted server, the bench.py durability metric), the RecompileCounter
advance-compile count (zero with a warm AOT store), and the
``rows_blake2s`` digest over every job's QoI bytes — the crash drill
(tools/chaosdrill.py) compares this digest bitwise against an
unfaulted control run.
"""

from __future__ import annotations

import argparse
import hashlib
import json
from typing import List, Optional

from cup3d_tpu.fleet.server import FleetServer, summary_json
from cup3d_tpu.obs import trace as OT


def _build_parser(mode: Optional[str]) -> argparse.ArgumentParser:
    slo = mode == "slo"
    why = mode == "why"
    prog = "python -m cup3d_tpu fleet" + (f" {mode}" if mode else "")
    desc = ("drain a fleet scenario spec and print the "
            + ("latency-provenance report JSON (per-phase p50/p99, "
               "burn attribution; with a job id, that job's exact "
               "phase decomposition)" if why else
               "per-tenant SLO report JSON" if slo else
               "per-tenant summary JSON"))
    ap = argparse.ArgumentParser(prog=prog, description=desc)
    ap.add_argument("--scenarios", required=True,
                    help="JSON spec: a list of scenarios or "
                         '{"scenarios": [...], "lanes": N, "buckets": N}')
    ap.add_argument("--lanes", type=int, default=None,
                    help="max lanes per batch (CUP3D_FLEET_LANES)")
    ap.add_argument("--buckets", type=int, default=None,
                    help="executable cache cap (CUP3D_FLEET_BUCKETS)")
    ap.add_argument("--workdir", default=None,
                    help="serialization dir (default: fresh tempdir)")
    ap.add_argument("--policy", choices=("fifo", "srb"), default=None,
                    help="scheduler policy: fifo (default) or srb = "
                         "shortest-remaining-budget "
                         "(CUP3D_FLEET_POLICY)")
    ap.add_argument("--queue-depth", type=int, default=None,
                    help="admission backpressure threshold "
                         "(CUP3D_FLEET_QUEUE_DEPTH)")
    ap.add_argument("--tenant-quota", type=int, default=None,
                    help="live jobs per tenant, 0 = unlimited "
                         "(CUP3D_FLEET_TENANT_QUOTA)")
    ap.add_argument("--no-continuous", action="store_true",
                    help="legacy generation-drain instead of "
                         "continuous batching "
                         "(CUP3D_FLEET_CONTINUOUS=0)")
    ap.add_argument("--mesh", action="store_true",
                    help="print the resolved 2-D device-mesh state "
                         "JSON on stderr before draining "
                         "(CUP3D_FLEET_MESH)")
    if slo or why:
        ap.add_argument("--slo-p99", type=float, default=None,
                        help="target p99 end-to-end seconds "
                             "(CUP3D_FLEET_SLO_P99)")
        ap.add_argument("--slo-window", type=int, default=None,
                        help="rolling breach window in jobs "
                             "(CUP3D_FLEET_SLO_WINDOW)")
    if why:
        ap.add_argument("job_id", nargs="?", default=None,
                        help="report one job's exact phase "
                             "decomposition instead of the fleet view")
    return ap


def _why_report(server: FleetServer, job_id: Optional[str]) -> dict:
    """The ``fleet why`` payload: fleet-wide (or one job's) latency
    provenance.  Per tenant: the per-phase p50/p99 breakdown and the
    burn attribution (dominant phase of the current window + which
    phase's e2e share grew vs the rolling baseline)."""
    if job_id is not None:
        job = server._jobs[job_id]
        return {
            "job_id": job.job_id,
            "tenant": job.tenant,
            "status": job.status,
            "phases": {k: round(v, 6)
                       for k, v in job.phases().items()},
            "durations": {k: round(v, 6)
                          for k, v in job.durations().items()},
            "events": [[n, round(t, 6)] for n, t in job.events],
        }
    tenants = {}
    for tenant in sorted(server._phase_share_history):
        tenants[tenant] = server.phase_attribution(tenant)
    return {
        "phase_quantiles": server.phase_quantiles(),
        "tenants": tenants,
        "jobs": server.jobs_by_status(),
    }


def cmd_recover(argv: List[str], t0: float) -> int:
    """``fleet recover``: journal replay -> drain -> probe report."""
    from cup3d_tpu.analysis.runtime import RecompileCounter

    ap = argparse.ArgumentParser(
        prog="python -m cup3d_tpu fleet recover",
        description="replay a crashed server's write-ahead journal, "
                    "drain every surviving job, and print the "
                    "recovery report JSON")
    ap.add_argument("--workdir", required=True,
                    help="the crashed server's workdir (holds the "
                         "journal/ directory)")
    ap.add_argument("--lanes", type=int, default=None,
                    help="max lanes per batch (CUP3D_FLEET_LANES)")
    ap.add_argument("--buckets", type=int, default=None,
                    help="executable cache cap (CUP3D_FLEET_BUCKETS)")
    args = ap.parse_args(argv)

    with RecompileCounter() as rc:
        server = FleetServer(max_lanes=args.lanes,
                             max_buckets=args.buckets,
                             workdir=args.workdir)
        recovery = server.recover()
        summary = server.drain()
    dispatched = [t for t in (
        j.event_time("dispatched") for j in server._jobs.values())
        if t is not None]
    digest = hashlib.blake2s()
    for jid in sorted(server._jobs):
        digest.update(jid.encode())
        digest.update(server._jobs[jid].qoi_bytes())
    from cup3d_tpu.obs import metrics as M

    # count compiles of the fleet advance on either path: live jit
    # tracing (RecompileCounter) or AOT lower().compile() (the
    # aot.compile_s histograms) — a warm store serves without either
    advance_compiles = sum(
        n for name, n in rc.compiles.items() if "advance" in name)
    advance_compiles += int(sum(
        v for k, v in M.snapshot().items()
        if k.startswith("aot.compile_s{")
        and "advance" in k and k.endswith(".count")))
    report = {
        "recovery": recovery,
        "recover_restart_s": (min(dispatched) - t0 if dispatched
                              else None),
        "total_s": OT.now() - t0,
        "advance_compiles": advance_compiles,
        "total_compiles": rc.total_compiles,
        "rows_blake2s": digest.hexdigest(),
        "jobs": {jid: server._jobs[jid].status
                 for jid in sorted(server._jobs)},
        "durability": server.health()["durability"],
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    bad = sum(st.get("failed", 0) for st in
              (t["statuses"] for t in summary.values()))
    return 1 if bad else 0


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    # the recovery clock starts at CLI entry: recover_restart_s
    # includes every import + journal replay + driver re-init between
    # exec and the restarted server's first dispatch
    t0 = OT.now()
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "recover":
        return cmd_recover(argv[1:], t0)
    mode = argv[0] if argv and argv[0] in ("slo", "why") else None
    if mode is not None:
        argv = argv[1:]
    slo = mode == "slo"
    why = mode == "why"
    args = _build_parser(mode).parse_args(argv)

    with open(args.scenarios) as f:
        spec = json.load(f)
    if isinstance(spec, dict):
        scenarios = spec.get("scenarios", [])
        lanes = args.lanes if args.lanes is not None else spec.get("lanes")
        buckets = (args.buckets if args.buckets is not None
                   else spec.get("buckets"))
    else:
        scenarios, lanes, buckets = spec, args.lanes, args.buckets
    if not scenarios:
        raise SystemExit("no scenarios in spec")

    server = FleetServer(max_lanes=lanes, max_buckets=buckets,
                         workdir=args.workdir,
                         slo_p99_s=getattr(args, "slo_p99", None),
                         slo_window=getattr(args, "slo_window", None),
                         continuous=(False if args.no_continuous
                                     else None),
                         policy=args.policy,
                         max_queue_depth=args.queue_depth,
                         tenant_quota=args.tenant_quota)
    if args.mesh:
        from cup3d_tpu.obs import metrics as M
        from cup3d_tpu.parallel import topology as topo

        # stderr so the stdout summary/SLO JSON stays machine-parseable
        print(json.dumps(topo.mesh_state(
            server.mesh,
            fallbacks=int(M.counter("fleet.mesh_fallbacks").value)),
            sort_keys=True), file=sys.stderr)
    for i, sc in enumerate(scenarios):
        server.submit(sc.get("tenant", f"tenant-{i}"), sc)
    summary = server.drain()
    if slo:
        report = {"slo": server.slo_status(),
                  "quantiles": server.latency_quantiles(),
                  "jobs": server.jobs_by_status()}
        print(json.dumps(report, indent=2, sort_keys=True))
    elif why:
        print(json.dumps(_why_report(server, args.job_id),
                         indent=2, sort_keys=True))
    else:
        print(summary_json(summary))
    bad = sum(
        st.get("failed", 0) for st in
        (t["statuses"] for t in summary.values()))
    return 1 if bad else 0
