"""Headless fleet entrypoint: ``python -m cup3d_tpu fleet --scenarios
spec.json``.

The spec file is either a JSON list of scenario dicts or an object
``{"scenarios": [...], "lanes": N, "buckets": N}``.  Each scenario is a
fleet/server.py job spec (kind, nsteps, n, cfl, L/T/xpos, ...) plus an
optional ``tenant`` name.  The process drains the whole queue and
prints the per-tenant summary JSON on stdout.
"""

from __future__ import annotations

import argparse
import json
from typing import List, Optional

from cup3d_tpu.fleet.server import FleetServer, summary_json


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cup3d_tpu fleet",
        description="drain a fleet scenario spec and print the "
                    "per-tenant summary JSON")
    ap.add_argument("--scenarios", required=True,
                    help="JSON spec: a list of scenarios or "
                         '{"scenarios": [...], "lanes": N, "buckets": N}')
    ap.add_argument("--lanes", type=int, default=None,
                    help="max lanes per batch (CUP3D_FLEET_LANES)")
    ap.add_argument("--buckets", type=int, default=None,
                    help="executable cache cap (CUP3D_FLEET_BUCKETS)")
    ap.add_argument("--workdir", default=None,
                    help="serialization dir (default: fresh tempdir)")
    args = ap.parse_args(argv)

    with open(args.scenarios) as f:
        spec = json.load(f)
    if isinstance(spec, dict):
        scenarios = spec.get("scenarios", [])
        lanes = args.lanes if args.lanes is not None else spec.get("lanes")
        buckets = (args.buckets if args.buckets is not None
                   else spec.get("buckets"))
    else:
        scenarios, lanes, buckets = spec, args.lanes, args.buckets
    if not scenarios:
        raise SystemExit("no scenarios in spec")

    server = FleetServer(max_lanes=lanes, max_buckets=buckets,
                         workdir=args.workdir)
    for i, sc in enumerate(scenarios):
        server.submit(sc.get("tenant", f"tenant-{i}"), sc)
    summary = server.drain()
    print(summary_json(summary))
    bad = sum(
        st.get("failed", 0) for st in
        (t["statuses"] for t in summary.values()))
    return 1 if bad else 0
