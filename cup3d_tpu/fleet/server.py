"""Multi-tenant fleet server: job queue -> bucketed batches -> dispatch.

The serving pipeline, one layer per concern:

1. **Queue** — ``submit(tenant, spec)`` validates a scenario spec and
   enqueues a :class:`FleetJob`; ``poll``/``cancel`` give tenants the
   usual lifecycle, ``drain`` runs the dispatch loop to completion.
2. **Capacity-bucketed assembly** — queued jobs group by their *static
   signature* (grid shape, dtype, solver, fish geometry: everything
   that changes the compiled executable) plus a ×1.25 ladder rung of
   their step budget (grid/bucket.py's ladder idea, re-applied to the
   lane and step axes), and each group is padded up the lane ladder —
   so mixed workloads share a small, bounded set of executables and the
   RecompileCounter budget is #buckets, not #jobs.
3. **Dispatch loop** — each batch advances all its lanes K steps per
   dispatch through the vmapped advance (fleet/batch.py), emitting one
   (B, K, ROW) QoI block per dispatch into a stream/qoi.py
   :class:`QoIStream` (async copy, bounded in-flight window).
4. **Fan-out** — the stream consumer splits rows per lane, runs the
   per-lane failure detection (fleet/isolate.py), and appends each
   tenant's rows in (step) order into that job's QoI buffer — a
   deterministic, byte-stable ordering per tenant.

Round 16 — the job-lifecycle observatory: every job carries a
monotonic-clock span timeline (``obs.trace.now()`` marks at the
lifecycle seams: submitted -> queued -> bucketed -> running ->
dispatched -> fanout -> rollback*/retire -> done/failed/cancelled).
Timestamps are host clock reads at seam transitions ONLY — the dispatch
loop itself never takes one per step, and nothing here reads a device
value.  At a job's terminal transition the server (1) observes
queue-wait / execution / end-to-end durations into per-tenant,
per-bucket ``fleet.job_*_s`` histograms (obs/metrics.py log buckets ->
p50/p95/p99), (2) tracks the per-tenant SLO window (target p99 +
rolling breach window -> burn-rate counters in ``health()``), and
(3) when tracing is on, emits one ``kind="job"`` aux record plus a
pid-3 lane-occupancy span into the Perfetto export (obs/trace.py).

Round 17 — continuous batching: with ``CUP3D_FLEET_CONTINUOUS`` on
(the default) the server is work-conserving at K-boundaries.  A lane
that retires (done, cancelled, or gave up) is immediately reseeded
with a compatible queued job — same static signature, so the cached
executable is reused with zero recompiles; the reseed is a per-lane
carry upload (fleet/batch.reseed_lane_carry, the same scan-carry
upload shape as the rollback path) plus a gait-row swap, leaving every
other lane bitwise untouched.  ``serve(feed)`` accepts ``submit()``
in-flight with admission control (per-tenant quota + max-queue-depth
backpressure, surfaced in ``health()["admission"]``), the scheduler
policy hook picks the reseed order (FIFO default, "srb" =
shortest-remaining-budget), and lane occupancy (busy-lane-steps /
total-lane-steps per drain window) lands in the
``fleet.lane_occupancy`` gauge plus idle spans on the pid-3 Perfetto
lane tracks.  ``CUP3D_FLEET_CONTINUOUS=0`` keeps the legacy
generation-drain path bitwise-unchanged.

Round 23 — durability: with ``CUP3D_FLEET_JOURNAL`` on (the default)
every job lifecycle transition (submit, lane placement, terminal) plus
a periodic settled K-boundary carry snapshot per lane lands in a
write-ahead journal (fleet/journal.py) under the server workdir; a
killed-and-restarted server replays it via :meth:`FleetServer.recover`
— terminal jobs remembered, queued jobs re-admitted, RUNNING jobs
resumed from their latest snapshot through the jitted reseed upload
INTO a batch rebuilt at the RECORDED (cap, K) so the same compiled
executable reproduces the never-crashed bytes.  fleet/migrate.py rides
the same checkpoint/resume seams for live migration and graceful
drains.  ``CUP3D_FLEET_JOURNAL=0`` keeps the serve loop bitwise-legacy
(no journal instance at all).

Env knobs: ``CUP3D_FLEET_LANES`` caps lanes per batch (default 64),
``CUP3D_FLEET_BUCKETS`` caps the executable cache (default 8, LRU),
``CUP3D_FLEET_MESH=1`` shards the lane axis over visible devices,
``CUP3D_FLEET_SLO_P99``/``CUP3D_FLEET_SLO_WINDOW`` set the completion
SLO (target p99 seconds, rolling job window), and ``CUP3D_SNAP_EVERY``/
``CUP3D_MAX_RETRIES`` carry their resilience meanings per lane.
Round 17 adds ``CUP3D_FLEET_CONTINUOUS`` (default 1),
``CUP3D_FLEET_POLICY`` (``fifo``/``srb``), ``CUP3D_FLEET_QUEUE_DEPTH``
(admission backpressure threshold, default 1024) and
``CUP3D_FLEET_TENANT_QUOTA`` (live jobs per tenant, 0 = unlimited).
Live servers surface in the obs /health payload (obs/export.py)
through the same weakref registry pattern as the flight recorders.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import weakref
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from cup3d_tpu.config import SimulationConfig
from cup3d_tpu.fleet import batch as FB
from cup3d_tpu.fleet import isolate as ISO
from cup3d_tpu.fleet.journal import JobJournal
from cup3d_tpu.grid.bucket import count_capacity
from cup3d_tpu.obs import federate as FEDERATE
from cup3d_tpu.obs import flight as _flight
from cup3d_tpu.obs import metrics as M
from cup3d_tpu.obs import trace as OT
from cup3d_tpu.parallel import topology as topo
from cup3d_tpu.resilience import faults
from cup3d_tpu.sim.dtpolicy import ramped_cfl
from cup3d_tpu.sim.megaloop import (
    DEFAULT_SCAN_K,
    FISH_ROW,
    TGV_ROW,
    resolve_scan_k,
)
from cup3d_tpu.stream.qoi import QoIStream

# job lifecycle states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
#: round 23 — checkpointed off this server by fleet/migrate.py; the
#: receiving server finishes the job under the same id
MIGRATED = "migrated"

#: terminal statuses (the journal replays these verbatim; mirrored as
#: literals in fleet/journal.py TERMINAL_STATUSES)
TERMINALS = (DONE, FAILED, CANCELLED, MIGRATED)

#: lane-count ladder base: fleet batches start amortizing at 2 lanes
LANE_LADDER_BASE = 2

#: scheduler policies: FIFO (submit order) and shortest-remaining-budget
#: (smallest nsteps first, cutting p99 under skewed job lengths)
POLICIES = ("fifo", "srb")

#: sentinel so FleetServer(mesh=None) means "explicitly unsharded"
#: while omitting it means "resolve via fleet_mesh()/CUP3D_FLEET_MESH"
_MESH_DEFAULT = object()


class FleetAdmissionError(RuntimeError):
    """submit() rejected by admission control: the queue is at its
    backpressure depth, or the tenant is at its live-job quota.  The
    ``reason`` ("queue-full" / "quota") matches the
    ``fleet.admission_rejects`` counter label and the backpressure
    field in ``health()["admission"]``."""

    def __init__(self, reason: str, detail: str):
        super().__init__(detail)
        self.reason = reason


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    # jax-lint: allow(JX009, malformed env knob falls back to the
    # default; the effective value is visible in health()["knobs"])
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    # jax-lint: allow(JX009, malformed env knob falls back to the
    # default; the effective value is visible in health()["slo"])
    except ValueError:
        return default


@dataclass
class FleetJob:
    """One tenant scenario: spec in, per-step QoI rows + final lane
    state out."""

    job_id: str
    tenant: str
    spec: dict
    status: str = QUEUED
    nsteps: int = 0
    steps_done: int = 0
    time: float = 0.0
    error: Optional[str] = None
    rows: Optional[np.ndarray] = None  # (nsteps, ROW) float64, step order
    lane: int = -1
    batch: Optional["FleetBatch"] = None
    cfg: Optional[SimulationConfig] = None
    #: bucket-signature label for the SLO histograms (set at assembly)
    sig_label: str = ""
    #: the monotonic span timeline: (event, obs.trace.now()) appends at
    #: lifecycle seams — never inside the per-step hot loop
    events: List[Tuple[str, float]] = field(default_factory=list)
    _seen: Set[str] = field(default_factory=set, repr=False)
    #: round 23 — _job_terminal ran for this job (idempotence guard:
    #: cancel of a mid-migration or journal-replayed job must resolve
    #: to exactly one terminal, never a double fold into the SLO state)
    _terminal_done: bool = field(default=False, repr=False)

    def mark(self, event: str, once: bool = False,
             collapse: bool = False) -> None:
        """Append one lifecycle event at the current monotonic time.
        ``once`` drops repeats (dispatched/fanout fire per dispatch
        otherwise); ``collapse`` drops a repeat only when it would
        IMMEDIATELY follow itself (compile_wait/reseed_wait re-fire
        every scheduling pass while the job stays parked — one event
        per parked stretch is the provenance-correct timeline).  The
        timestamp is clamped non-decreasing: marks may arrive from the
        dispatch thread and the QoI consumer thread, and the timeline
        is validated monotone (obs/trace.py)."""
        if once and event in self._seen:
            return
        if collapse and self.events and self.events[-1][0] == event:
            return
        self._seen.add(event)
        t = OT.now()
        if self.events and t < self.events[-1][1]:
            t = self.events[-1][1]
        self.events.append((event, t))

    def event_time(self, event: str) -> Optional[float]:
        """First occurrence time of ``event`` (None when absent)."""
        for n, t in self.events:
            if n == event:
                return t
        return None

    def durations(self) -> Dict[str, float]:
        """The SLO-relevant durations derivable from the timeline:
        queue-wait (queued -> running), execution (running -> terminal)
        and end-to-end (submitted -> terminal) — all on the monotonic
        clock, present only when both endpoints were marked.

        CAVEAT (round 22): ``queue_wait_s`` is kept for schema
        compatibility but since the round-21 AOT path it CONFLATES two
        remediable-by-different-means waits — capacity wait (fix:
        scale out) and background compile wait (fix: warm the store).
        The split rides alongside as ``capacity_wait_s`` +
        ``compile_wait_s`` (from :meth:`phases`); prefer those and the
        full :meth:`phases` decomposition for attribution."""
        out: Dict[str, float] = {}
        if not self.events:
            return out
        t_end = self.events[-1][1]
        t_q = self.event_time("queued")
        t_run = self.event_time("running")
        t_sub = self.event_time("submitted")
        if t_q is not None and t_run is not None:
            out["queue_wait_s"] = t_run - t_q
            ph = self.phases()
            out["capacity_wait_s"] = ph.get("capacity_wait", 0.0)
            out["compile_wait_s"] = ph.get("compile_wait", 0.0)
        if t_run is not None:
            out["exec_s"] = t_end - t_run
        if t_sub is not None:
            out["e2e_s"] = t_end - t_sub
        return out

    def phases(self) -> Dict[str, float]:
        """Exact latency-provenance decomposition of the timeline
        (:func:`cup3d_tpu.obs.trace.phase_decomposition`): exclusive
        per-phase seconds that sum to end-to-end by construction."""
        return OT.phase_decomposition(self.events)

    def record(self, step: int, row: np.ndarray, t: float) -> None:
        """Append (or re-apply, after a lane rollback replay) the QoI
        row for ``step``; keyed by step index, so the final buffer is a
        clean, gap-free, byte-stable sequence per tenant."""
        if 0 <= step < self.nsteps:
            self.rows[step] = row
            self.steps_done = max(self.steps_done, step + 1)
            self.time = t

    def summary(self) -> dict:
        return {
            "job_id": self.job_id,
            "tenant": self.tenant,
            "status": self.status,
            "steps_done": int(self.steps_done),
            "nsteps": int(self.nsteps),
            "time": float(self.time),
            "error": self.error,
        }

    def qoi_bytes(self) -> bytes:
        """The tenant's QoI block as bytes (ordering-stability tests)."""
        return b"" if self.rows is None else self.rows.tobytes()


def _job_config(spec: dict, workdir: str) -> Tuple[str, SimulationConfig]:
    """Scenario spec -> (kind, SimulationConfig) for one pipelined
    lane.  Only scan-eligible configs are expressible: free dt,
    step-budget termination, <= 1 frozen-gait obstacle; "amr_tgv"
    lanes run the bucketed block-forest body on a topology frozen
    after init (see _AMRLaneDriver)."""
    kind = str(spec.get("kind", "fish"))
    nsteps = int(spec.get("nsteps", 0))
    if nsteps <= 0:
        raise ValueError("fleet scenario needs nsteps > 0")
    n = int(spec.get("n", 32))
    common = dict(
        nsteps=nsteps, tend=0.0,
        CFL=float(spec.get("cfl", 0.3)),
        rampup=int(spec.get("rampup", 0)),
        dtype=str(spec.get("dtype", "float32")),
        pipelined=True, verbose=False, freqDiagnostics=0,
        path4serialization=workdir,
    )
    uniform = dict(
        bpdx=1, bpdy=1, bpdz=1, block_size=n,
        levelMax=1, levelStart=0,
    )
    if kind == "tgv":
        cfg = SimulationConfig(
            extent=float(spec.get("extent", 2.0 * np.pi)),
            nu=float(spec.get("nu", 0.02)),
            initCond=str(spec.get("initCond", "taylorGreen")),
            **uniform, **common,
        )
    elif kind == "amr_tgv":
        bpd = int(spec.get("bpd", 2))
        lm = int(spec.get("levelMax", 2))
        cfg = SimulationConfig(
            bpdx=bpd, bpdy=bpd, bpdz=bpd,
            levelMax=lm, levelStart=int(spec.get("levelStart", lm - 1)),
            Rtol=float(spec.get("rtol", 1e9)),
            Ctol=float(spec.get("ctol", -1.0)),
            extent=float(spec.get("extent", 2.0 * np.pi)),
            nu=float(spec.get("nu", 0.02)),
            initCond=str(spec.get("initCond", "taylorGreen")),
            step_2nd_start=int(spec.get("step_2nd_start", 0)),
            **common,
        )
    elif kind == "fish":
        L = float(spec.get("L", 0.3))
        T = float(spec.get("T", 1.0))
        xpos = float(spec.get("xpos", 0.5))
        factory = f"stefanfish L={L} T={T} xpos={xpos}"
        for k in ("ypos", "zpos"):
            if k in spec:
                factory += f" {k}={float(spec[k])}"
        cfg = SimulationConfig(
            extent=float(spec.get("extent", 1.0)),
            nu=float(spec.get("nu", 1e-4)),
            factory_content=factory,
            **uniform, **common,
        )
    else:
        raise ValueError(f"unknown fleet scenario kind {kind!r}")
    return kind, cfg


class _AMRLaneDriver:
    """Adapter giving an obstacle-free AMRSimulation the driver surface
    assemble()/FleetBatch expect (.sim/.cfg/init/_megaloop_eligible).
    init runs the usual 3*levelMax adaptation rounds, then FREEZES the
    topology: the fleet scan body never regrids, so every lane keeps
    the (capacity, topology-signature) it bucketed on for the whole
    drain — the zero-retrace contract inside a bucket."""

    def __init__(self, sim):
        self.sim = sim
        self.cfg = sim.cfg

    def init(self):
        self.sim.init()
        self.sim.adapt_enabled = False

    def _megaloop_eligible(self) -> bool:
        s, cfg = self.sim, self.cfg
        return (not s.obstacles and s.forest is None and s._bucketing
                and not cfg.implicitDiffusion and not cfg.bFixMassFlux
                and cfg.uMax_forced <= 0)


def _static_signature(drv, kind: str) -> tuple:
    """Everything that changes the compiled lane body: jobs sharing a
    signature (and a lane/step rung) share one executable.  Adaptive
    tenants key on (capacity, octree topology-signature): equal keys
    <=> the vmapped bucketed step's compiled shapes AND its frozen
    padded tables match, so lanes can share the closure-captured
    geometry bundle without retracing."""
    s = drv.sim
    if kind == "amr_tgv":
        return (
            kind,
            int(s.grid.bs),
            int(s._cap),
            s.grid.signature,
            str(np.dtype(s.dtype)),
            float(s.nu),
            tuple(float(v) for v in s.grid.extent),
            int(drv.cfg.step_2nd_start),
        )
    sig = (
        kind,
        tuple(int(v) for v in np.asarray(s.grid.shape)),
        str(np.dtype(s.dtype)),
        float(s.grid.h),
        float(s.nu),
        type(s.poisson_solver).__name__,
    )
    if s.obstacles:
        ob = s.obstacles[0]
        sig += (
            float(ob.length),
            bool(ob.bFixFrameOfRef),
            tuple(int(v) for v in ob._window_shape),
            tuple(np.asarray(ob.forced_mask_dev()).astype(float).tolist()),
            tuple(np.asarray(ob.block_mask_dev()).astype(float).tolist()),
            float(drv.cfg.DLM),
            float(drv.cfg.lambda_penalization),
        )
    return sig


def _lane_payload(kind: str, drv, label: str):
    """One lane's device payload from an initialized driver: the solo
    carry plus the frozen gait (fish only) — shared by first assembly
    (stacked into the batched carry) and reseeding (per-lane upload)."""
    if kind == "fish":
        ob = drv.sim.obstacles[0]
        from cup3d_tpu.models.fish.device_midline import freeze_gait

        gait = freeze_gait(ob, drv.sim.time, drv.sim.dtype)
        if gait is None:
            raise ValueError(f"{label}: gait not freezable for fleet")
        return FB.init_fish_carry(drv.sim, ob), gait
    if kind == "amr_tgv":
        return FB.init_amr_carry(drv.sim), None
    return FB.init_tgv_carry(drv.sim), None


class FleetBatch:
    """B lanes sharing one compiled executable: the batched carry, the
    host step/budget mirrors, the lane guard, and the QoI stream."""

    def __init__(self, server: "FleetServer", batch_id: int, kind: str,
                 jobs: List[FleetJob], drivers: list, K: int, cap: int):
        self.server = server
        self.batch_id = batch_id
        #: cross-restart-unique batch id for journal place/snapshot
        #: records — a restarted server reuses small batch_ids, and a
        #: replayed record must never alias a live batch's lanes
        self.uid = f"{os.getpid():x}.{batch_id}"
        self.kind = kind
        self.K = int(K)
        self.B = int(cap)
        self.row_w = FISH_ROW if kind == "fish" else TGV_ROW
        # row offsets of the per-lane (umax, dt, time) chain
        self.off_umax = self.row_w - 3
        self.off_dt = self.row_w - 2
        self.off_time = self.row_w - 1

        template = drivers[0]
        self.template = template
        s = template.sim
        self.np_dtype = np.dtype(s.dtype)

        # lane assembly: per-job solo carries + frozen gaits, padded up
        # the lane ladder with inert clones of lane 0 (left = 0 from
        # step 0, so the gated body freezes them; they are never
        # consumed because jobs[lane] is None there)
        carries, gaits, targets = [], [], []
        for job, drv in zip(jobs, drivers):
            carry, gait = _lane_payload(kind, drv, job.job_id)
            carries.append(carry)
            if gait is not None:
                gaits.append(gait)
            targets.append(job.nsteps)
        while len(carries) < self.B:
            carries.append(carries[0])
            targets.append(0)
            if gaits:
                gaits.append(gaits[0])
        self.jobs: List[Optional[FleetJob]] = list(jobs) + [None] * (
            self.B - len(jobs))
        for lane, job in enumerate(jobs):
            job.lane = lane
            job.batch = self
            job.status = RUNNING
            job.mark("running")
            job.rows = np.zeros((job.nsteps, self.row_w), np.float64)
            # jax-lint: allow(JX013, journal append is host-side file
            # I/O — no device dispatch per lane; the place record is
            # inherently per-lane)
            server._journal(
                "place", job_id=job.job_id, batch_uid=self.uid,
                lane=lane, cap=self.B, K=self.K, kind=kind)
        #: lanes whose job has not had its first dispatch marked yet —
        #: steady-state dispatch() pays one empty-set truth test
        self._undispatched: Set[int] = {
            lane for lane, j in enumerate(self.jobs) if j is not None}

        self.carry = FB.stack_carries(carries, targets)
        self.gaits = FB.stack_gaits(gaits, s.dtype) if gaits else None
        ob = s.obstacles[0] if kind == "fish" else None
        # the batch's actual mesh: the server's, downgraded loudly to
        # None when B does not divide over it (fleet.mesh_fallbacks) —
        # health()/the CLI report THIS, the shard state really running
        self.mesh = FB.resolve_fleet_mesh(self.B, server.mesh)
        #: lanes on failed mesh slices (resilience/elastic.fail_shard):
        #: never reseed targets again, frozen at zero budget
        self.dead_lanes: Set[int] = set()
        #: the static bucket signature — reseed compatibility is THIS
        #: (the step-budget rung only shapes first assembly; it does
        #: not enter the executable key, so cross-rung reseeds still
        #: hit the compiled-advance cache)
        self.sig = _static_signature(template, kind)
        self.advance = server.executable(
            self.sig, s, ob, self.B, self.K, kind=kind, mesh=self.mesh)

        self.step_h = np.zeros(self.B, np.int64)
        self.left_h = np.asarray(targets, np.int64)
        self.snap_dispatches = max(1, server.snap_steps // self.K)
        self.guard = ISO.LaneGuard(self.B, server.max_retries)
        self.guard.snapshot(self.carry, self.step_h, self.left_h)
        self._since_snap = 0
        self.dispatches = 0
        # lane-occupancy accounting: busy = budget-gated lane-steps
        # actually advanced, total = B*K per dispatch (frozen and
        # padding lanes count against the denominator — that is the
        # waste continuous batching reclaims)
        self.busy_steps = 0
        self.total_steps = 0
        #: monotonic time each idle lane last went free (padding lanes
        #: at construction, retired lanes at their terminal mark) —
        #: the start of the pid-3 idle span the next reseed closes
        self._lane_free_since: Dict[int, float] = {
            lane: OT.now() for lane in range(self.B)
            if self.jobs[lane] is None}
        self.stream = QoIStream(
            self._consume, read_every=1, max_inflight=2,
            name=f"fleet-b{batch_id}")
        M.counter("fleet.batches").inc()
        M.counter("fleet.lanes", kind=kind).inc(len(jobs))

    # -- dispatch ----------------------------------------------------------

    def active(self) -> bool:
        return bool(
            (self.left_h > 0).any()
            or self.stream
            or any(j is not None and j.status == RUNNING for j in self.jobs)
        )

    def _cfl_block(self) -> np.ndarray:
        """Host-precomputed per-lane CFL ramp for the next K steps —
        the same dtpolicy.ramped_cfl chain the solo megaloop feeds, per
        lane (host fan-out loop: no device work here)."""
        cfl = np.empty((self.B, self.K), self.np_dtype)
        for lane in range(self.B):
            job = self.jobs[lane]
            base = float(job.cfg.CFL) if job is not None else 0.1
            ramp = int(job.cfg.rampup) if job is not None else 0
            step0 = int(self.step_h[lane])
            for k in range(self.K):
                cfl[lane, k] = ramped_cfl(base, step0 + k, ramp)
        return cfl

    def nshards(self) -> int:
        """Mesh slices this batch spans (1 when unsharded)."""
        return (int(self.mesh.devices.size)
                if self.mesh is not None else 1)

    def lane_shard(self, lane: int) -> int:
        """The mesh slice owning ``lane`` (occupancy/SLO shard labels;
        0 when unsharded)."""
        from cup3d_tpu.resilience import elastic as EL

        return EL.shard_of_lane(self.B, self.nshards(), lane)

    def fail_shard(self, shard: int) -> List[str]:
        """Drop one mesh slice: freeze its lane block, requeue its
        running jobs onto the queue for surviving shards (per-slice
        elastic recovery, resilience/elastic.py)."""
        from cup3d_tpu.resilience import elastic as EL

        return EL.fail_shard(self, shard)

    def dispatch(self) -> None:
        """One batched advance: every live lane moves K steps, one QoI
        block goes onto the stream."""
        # the shard-loss seam fires per mesh slice at the K-boundary
        # (shard index in the step slot, the fleet.lane_nan idiom one
        # level up); the dead slice's lanes drop out of this dispatch
        for shard in range(self.nshards()):
            if shard in {self.lane_shard(d) for d in self.dead_lanes}:
                continue
            if faults.fire("fleet.shard_loss", step=shard):
                self.fail_shard(shard)
        valid = np.minimum(self.left_h, self.K).astype(np.int64)
        if self._undispatched:
            for lane in sorted(self._undispatched):
                if valid[lane] > 0:
                    job = self.jobs[lane]
                    if job is not None:
                        job.mark("dispatched", once=True)
                    self._undispatched.discard(lane)
        carry, rows = self.advance(self.carry, self._cfl_block(), self.gaits)
        self.carry = carry
        entry = self.stream.pack_parts(
            [("scan", rows.reshape(self.B * self.K * self.row_w))],
            self.template.sim.dtype,
            step0=self.step_h.copy(), valid=valid,
            epochs=self.guard.epochs.copy(),
            step=int(self.dispatches),
        )
        self.stream.emit(entry)
        self.step_h += valid
        self.left_h -= valid
        self.dispatches += 1
        self._since_snap += 1
        busy = int(valid.sum())
        self.busy_steps += busy
        self.total_steps += self.B * self.K
        M.counter("fleet.dispatches").inc()
        M.counter("fleet.busy_lane_steps").inc(busy)
        M.counter("fleet.total_lane_steps").inc(self.B * self.K)
        ns = self.nshards()
        if ns > 1:
            # shard-labeled occupancy (round 18): which mesh slice the
            # busy lane-steps ran on, additive next to the totals
            bl = self.B // ns
            for shard in range(ns):
                sb = int(valid[shard * bl:(shard + 1) * bl].sum())
                M.counter("fleet.shard_busy_lane_steps",
                          shard=str(shard)).inc(sb)
                M.counter("fleet.shard_total_lane_steps",
                          shard=str(shard)).inc(bl * self.K)
        # round-19 observatory seam: per-shard K-boundary walls + skew
        # detection + the federation snapshot refresh.  Host scalars
        # only (the mark is obs.trace.now()); both calls collapse to
        # one bool/len test when nothing is armed or the batch is
        # unsharded, so the solo-lane hot path pays nothing.
        if ns > 1:
            FEDERATE.STRAGGLER.boundary(
                range(ns), source="fleet", sink=OT.TRACE,
                step=int(self.dispatches))
        FEDERATE.FED.on_k_boundary()
        if self._since_snap >= self.snap_dispatches:
            self.settle()
            self.guard.snapshot(self.carry, self.step_h, self.left_h)
            self.journal_snapshots()
            self._since_snap = 0
        # the crash drill's kill switch (round 23): hard process death
        # at a K-boundary, armed with the dispatch count in the step
        # slot — recovery may lose at most the work since the last
        # journaled snapshot, never a job
        if faults.fire("server.crash", step=int(self.dispatches)):
            os._exit(23)

    def settle(self) -> None:
        """Drain the stream: every emitted row is consumed (and every
        lane fault handled) before the caller proceeds.  Required
        before snapshots — only a validated state may become a rollback
        target."""
        self.stream.flush()

    def tick(self) -> None:
        """One dispatch-loop turn: advance if any lane has budget, else
        drain the stream (which may resurrect budget via rollback)."""
        if (self.left_h > 0).any():
            self.dispatch()
        else:
            self.settle()

    # -- fan-out + isolation ----------------------------------------------

    def _consume(self, entry: dict) -> None:
        vals = entry.get("vals")
        if vals is None:
            vals = np.asarray(entry["pack"], np.float64)
        rows = np.asarray(vals, np.float64).reshape(
            self.B, self.K, self.row_w)
        step0, valid = entry["step0"], entry["valid"]
        epochs = entry["epochs"]
        for lane in range(self.B):
            job = self.jobs[lane]
            if job is None or job.status != RUNNING:
                continue
            if epochs[lane] != self.guard.epochs[lane]:
                continue  # stale rows from an abandoned lane trajectory
            if valid[lane] > 0:
                job.mark("fanout", once=True)
            for k in range(int(valid[lane])):
                step = int(step0[lane]) + k
                row = rows[lane, k]
                reason = self.guard.check_row(
                    lane, step, float(row[self.off_umax]),
                    float(row[self.off_dt]))
                if reason is not None:
                    self.lane_fault(lane, step, reason)
                    break
                self.guard.note_progress(lane, step)
                job.record(step, row, float(row[self.off_time]))
                if job.steps_done >= job.nsteps:
                    self.retire(lane, DONE, "done")
                    break

    def lane_fault(self, lane: int, step: int, reason: str) -> None:
        """Contain one lane's failure: rollback with dt-halving while
        the retry budget lasts, retire the lane after."""
        M.counter("fleet.lane_faults", reason=reason).inc()
        if self.guard.exhausted(lane):
            self.carry = self.guard.give_up(self.carry, lane, reason)
            self.left_h[lane] = 0
            job = self.jobs[lane]
            job.error = reason
            self.retire(lane, FAILED, "failed")
            return
        job = self.jobs[lane]
        if job is not None:
            job.mark("rollback")
        self.carry, snap_step, snap_left = self.guard.rollback(
            self.carry, lane, step, reason)
        self.step_h[lane] = snap_step
        self.left_h[lane] = snap_left

    def retire(self, lane: int, status: str, reason: str) -> None:
        job = self.jobs[lane]
        if job is None or job.status not in (RUNNING,):
            return
        job.status = status
        job.mark("retire")
        job.mark(status)
        M.counter("fleet.lane_retires", reason=reason).inc()
        self.server.update_lane_gauge()
        # the lane goes idle exactly where the job's occupancy span
        # ends (the terminal mark), so the idle span a later reseed
        # emits touches it without overlapping
        self._lane_free_since[lane] = job.events[-1][1]
        self.server._job_terminal(job, batch=self, lane=lane)

    def cancel_lane(self, lane: int) -> None:
        """Freeze the lane (bits of every other lane untouched) and
        drop its in-flight rows."""
        self.carry = ISO.retire_lanes(
            self.carry, np.arange(self.B) == lane)
        self.left_h[lane] = 0
        self.guard.epochs[lane] += 1
        self.retire(lane, CANCELLED, "cancelled")

    def free_lanes(self) -> List[int]:
        """Lanes holding no RUNNING job — padding or retired — i.e.
        reseed targets for the continuous scheduler.  Callers settle
        the stream first so pending retirements are visible.  Lanes on
        a lost mesh slice (``dead_lanes``) are never reseed targets."""
        return [lane for lane in range(self.B)
                if lane not in self.dead_lanes
                and (self.jobs[lane] is None
                     or self.jobs[lane].status != RUNNING)]

    def reseed_lane(self, lane: int, job: FleetJob, drv) -> None:
        """Splice a queued job into a freed lane at a K-boundary: a
        per-lane carry upload + gait-row swap (fleet/batch.py), fresh
        host mirrors, and a guard reset (epoch bump + full retry budget
        + snapshot-row refresh, fleet/isolate.py).  Every other lane's
        carry bits are untouched, and the previous occupant's in-flight
        rows drop on the epoch bump."""
        solo, gait = _lane_payload(self.kind, drv, job.job_id)
        self.carry = FB.reseed_lane_carry(
            self.carry, lane, solo, job.nsteps, mesh=self.mesh)
        if self.gaits is not None:
            self.gaits = FB.reseed_lane_gaits(
                self.gaits, lane, gait, mesh=self.mesh)
        self.step_h[lane] = 0
        self.left_h[lane] = job.nsteps
        self.guard.reseed(self.carry, lane, job.nsteps)
        self.jobs[lane] = job
        job.lane = lane
        job.batch = self
        job.status = RUNNING
        job.mark("reseeded")
        job.mark("running")
        job.rows = np.zeros((job.nsteps, self.row_w), np.float64)
        self._undispatched.add(lane)
        self.server._journal(
            "place", job_id=job.job_id, batch_uid=self.uid,
            lane=lane, cap=self.B, K=self.K, kind=self.kind)
        M.counter("fleet.reseeds", kind=self.kind).inc()
        M.counter("fleet.lanes", kind=self.kind).inc()
        self.server.update_lane_gauge()
        t_free = self._lane_free_since.pop(lane, None)
        sink = OT.TRACE
        if sink.enabled and t_free is not None:
            t_run = job.event_time("running")
            if t_run is not None and t_run > t_free:
                sink.lane_span(
                    FB.lane_track_id(self.batch_id, lane), "idle",
                    t_free, t_run - t_free, args={"job_id": "<idle>"})

    # -- durability (round 23) ---------------------------------------------

    def journal_snapshots(self) -> None:
        """Journal one carry snapshot per RUNNING lane.  Called at the
        same settled K-boundary as the rollback snapshot, so the
        recorded state is always validated: every row up to it consumed
        clean, ``steps_done == step_h`` per lane.  The recorded (cap,
        K) let recovery rebuild the SAME compiled executable, which is
        what makes a resumed trajectory bitwise."""
        if self.server.journal is None:
            return
        for lane in range(self.B):
            job = self.jobs[lane]
            if job is None or job.status != RUNNING:
                continue
            steps = int(job.steps_done)
            self.server._journal(
                "snapshot", job_id=job.job_id, batch_uid=self.uid,
                cap=self.B, K=self.K, kind=self.kind, lane=lane,
                step=int(self.step_h[lane]),
                left=int(self.left_h[lane]),
                steps_done=steps, time=float(job.time),
                rows=job.rows[:steps].copy(),
                carry=FB.lane_carry_host(self.carry, lane))

    def resume_lane(self, lane: int, job: FleetJob, snap: dict) -> None:
        """Upload one journaled/migrated lane checkpoint into ``lane``:
        the round-23 resume splice.  The batch was just built with the
        checkpoint's recorded (cap, K) and ``job`` occupies ``lane``
        from first assembly (RUNNING, zeroed rows); this re-enters the
        checkpointed carry through the same jitted per-lane upload as a
        reseed, restores the recorded rows, and points the guard's host
        mirrors at the resumed position."""
        solo = {k: np.asarray(v) for k, v in snap["carry"].items()}
        step, left = int(snap["step"]), int(snap["left"])
        self.carry = FB.reseed_lane_carry(
            self.carry, lane, solo, left, mesh=self.mesh)
        self.step_h[lane] = step
        self.left_h[lane] = left
        self.guard.resume(self.carry, lane, step, left)
        rows = snap.get("rows")
        if rows is not None and len(rows):
            rows = np.asarray(rows, np.float64)
            job.rows[:rows.shape[0]] = rows
        job.steps_done = int(snap.get("steps_done", step))
        job.time = float(snap.get("time", 0.0))
        M.counter("fleet.lane_resumes", kind=self.kind).inc()

    def release_for_migration(self, lane: int) -> dict:
        """Checkpoint one RUNNING lane off this batch for live
        migration (fleet/migrate.py): settle so the lane state is
        validated, host-serialize the carry + rows, then freeze the
        lane and retire its job MIGRATED.  Every other lane's bits are
        untouched (the same lane-wise selects as a cancel).  The
        returned payload is exactly a journal snapshot view, so the
        receiving server resumes it through ``resume_lane``."""
        self.settle()
        job = self.jobs[lane]
        if job is None or job.status != RUNNING:
            raise ValueError(f"lane {lane} holds no RUNNING job")
        steps = int(job.steps_done)
        ckpt = {
            "job_id": job.job_id, "tenant": job.tenant,
            "spec": dict(job.spec), "nsteps": int(job.nsteps),
            "kind": self.kind, "cap": self.B, "K": self.K,
            "step": int(self.step_h[lane]),
            "left": int(self.left_h[lane]),
            "steps_done": steps, "time": float(job.time),
            "rows": job.rows[:steps].copy(),
            "carry": FB.lane_carry_host(self.carry, lane),
        }
        self.carry = ISO.retire_lanes(
            self.carry, np.arange(self.B) == lane)
        self.left_h[lane] = 0
        self.guard.epochs[lane] += 1
        self.retire(lane, MIGRATED, "migrated")
        return ckpt

    def lane_state(self, lane: int) -> Dict[str, np.ndarray]:
        """Host copies of one lane's carry leaves (tests, summaries)."""
        return {k: np.asarray(v[lane]) for k, v in self.carry.items()}

    def running_lanes(self) -> int:
        return sum(
            1 for j in self.jobs if j is not None and j.status == RUNNING)


#: weakrefs of live servers, for the obs /health payload
_LIVE: List["weakref.ReferenceType[FleetServer]"] = []


def live_servers() -> List["FleetServer"]:
    out = []
    for ref in list(_LIVE):
        srv = ref()
        if srv is None:
            _LIVE.remove(ref)
        else:
            out.append(srv)
    return out


class FleetServer:
    """The multi-tenant front door: queue, assembly, dispatch, fan-out."""

    #: SLO error budget matching a p99 target: 1% of jobs may breach
    SLO_ERROR_BUDGET = 0.01

    def __init__(self, max_lanes: Optional[int] = None,
                 max_buckets: Optional[int] = None,
                 snap_every: Optional[int] = None,
                 max_retries: Optional[int] = None,
                 workdir: Optional[str] = None,
                 slo_p99_s: Optional[float] = None,
                 slo_window: Optional[int] = None,
                 continuous: Optional[bool] = None,
                 policy: Optional[str] = None,
                 max_queue_depth: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 provenance: Optional[bool] = None,
                 journal: Optional[bool] = None,
                 mesh=_MESH_DEFAULT):
        # the chaos sites (server.crash, journal.write_fail, ...) are
        # armable from the environment in drill subprocesses
        # (tools/chaosdrill.py); the solo path loads CUP3D_FAULT at
        # RecoveryEngine.install, the fleet path loads it here
        faults.load_env()
        self.max_lanes = int(
            max_lanes if max_lanes is not None
            else _env_int("CUP3D_FLEET_LANES", 64))
        self.max_buckets = int(
            max_buckets if max_buckets is not None
            else _env_int("CUP3D_FLEET_BUCKETS", 8))
        snap_steps = (
            snap_every if snap_every is not None
            else _env_int("CUP3D_SNAP_EVERY", 16))
        self.snap_steps = max(1, int(snap_steps))
        self.max_retries = max_retries
        self.workdir = workdir or tempfile.mkdtemp(prefix="cup3d-fleet-")
        self._jobs: "OrderedDict[str, FleetJob]" = OrderedDict()
        self._execs: "OrderedDict[tuple, object]" = OrderedDict()
        self.batches: List[FleetBatch] = []
        self._next_job = 0
        self._next_batch = 0
        self.mesh = FB.fleet_mesh() if mesh is _MESH_DEFAULT else mesh
        # completion SLO: target p99 end-to-end seconds + rolling
        # per-tenant breach window (health()["slo"], fleet slo CLI)
        self.slo_p99_s = float(
            slo_p99_s if slo_p99_s is not None
            else _env_float("CUP3D_FLEET_SLO_P99", 60.0))
        self.slo_window = max(1, int(
            slo_window if slo_window is not None
            else _env_int("CUP3D_FLEET_SLO_WINDOW", 100)))
        self._slo_windows: Dict[str, deque] = {}
        # round 17 — continuous-batching knobs + scheduler state
        self.continuous = bool(
            continuous if continuous is not None
            else _env_int("CUP3D_FLEET_CONTINUOUS", 1))
        self.policy = str(
            policy if policy is not None
            else os.environ.get("CUP3D_FLEET_POLICY", "fifo"))
        if self.policy not in POLICIES:
            raise ValueError(
                f"unknown scheduler policy {self.policy!r} "
                f"(expected one of {POLICIES})")
        self.max_queue_depth = max(1, int(
            max_queue_depth if max_queue_depth is not None
            else _env_int("CUP3D_FLEET_QUEUE_DEPTH", 1024)))
        self.tenant_quota = int(
            tenant_quota if tenant_quota is not None
            else _env_int("CUP3D_FLEET_TENANT_QUOTA", 0))
        self.reseeds = 0
        self.last_occupancy: Optional[float] = None
        #: prepared-but-waiting queued jobs: job_id -> (kind, driver,
        #: sig, bucket key) — a job waiting for a compatible lane is
        #: not re-inited at every K-boundary
        self._prepared: Dict[str, tuple] = {}
        #: round 21 — background compile service (aot/compiler.py),
        #: created lazily iff the persistent store is active; with
        #: CUP3D_AOT_STORE unset the whole AOT path is inert
        self._aot_service = None
        # round 22 — latency provenance: per-job phase decomposition,
        # fleet.latency_phase_s histograms, flow events, and SLO burn
        # attribution.  CUP3D_FLEET_PROVENANCE=0 reverts _job_terminal
        # to the round-16 aggregate-only bookkeeping (the bench.py
        # _provenance_overhead gate measures exactly this delta).
        self.provenance = bool(
            provenance if provenance is not None
            else _env_int("CUP3D_FLEET_PROVENANCE", 1))
        #: per-tenant rolling history of per-job phase SHARES (phase
        #: seconds / e2e), newest last — the burn-attribution baseline
        self._phase_share_history: Dict[str, deque] = {}
        # round 23 — write-ahead durability.  CUP3D_FLEET_JOURNAL=0
        # keeps the serve loop bitwise-legacy: no journal instance, no
        # appends, no recovery — every _journal call is one None test.
        use_journal = bool(
            journal if journal is not None
            else _env_int("CUP3D_FLEET_JOURNAL", 1))
        self.journal = (
            JobJournal(os.path.join(self.workdir, "journal"))
            if use_journal else None)
        #: admission closed for drain_for_shutdown (fleet/migrate.py)
        self.draining = False
        #: the last recover() outcome (health()["durability"])
        self.last_recovery: Optional[dict] = None
        self.migrations = 0
        _LIVE.append(weakref.ref(self))

    # -- AOT store / background compile (round 21) -------------------------

    def _aot(self):
        """(store, service) when ``CUP3D_AOT_STORE`` is set, else
        (None, None): the whole zero-cold-start machinery keys off the
        active store."""
        from cup3d_tpu.aot import store as aot_store

        st = aot_store.active_store()
        if st is None:
            return None, None
        if self._aot_service is None:
            from cup3d_tpu.aot.compiler import CompileService

            self._aot_service = CompileService()
        return st, self._aot_service

    @staticmethod
    def _mesh_key(mesh):
        return tuple(mesh.shape.items()) if mesh is not None else None

    @staticmethod
    def _store_sig(sig: tuple, cap: int, K: int, mesh_key) -> tuple:
        """The cross-process store key for one fleet advance: the
        content-addressed static signature plus the shapes that enter
        the compiled executable (lane rung, scan K, mesh layout)."""
        return ("fleet.advance", sig, int(cap), int(K), mesh_key)

    def _bind_advance(self, s, ob, cap: int, K: int, kind, mesh,
                      sig: tuple, store):
        """Build the vmapped advance and, with a store active, wrap it
        store-backed: first use loads the serialized executable (zero
        compiles) or AOT-compiles and writes back."""
        fn = FB.build_fleet_advance(s, ob, mesh=mesh, kind=kind)
        if store is not None:
            from cup3d_tpu.aot import store as aot_store

            skey = self._store_sig(sig, cap, K, self._mesh_key(mesh))
            fn = aot_store.StoreBackedExecutable(
                fn, skey,
                name=f"fleet.advance-{aot_store.sig_label(skey)}",
                store=store)
        return fn

    def _background_key(self, sig: tuple, cap: int, K: int, mesh):
        return (sig, int(cap), int(K), self._mesh_key(mesh))

    def _batch_shape(self, members) -> Tuple[int, int, object]:
        """(cap, K, mesh) the assembly of ``members`` will use — must
        mirror _build_batches so background-compiled executables land
        on the exact LRU key assembly asks for."""
        cap = self.lane_capacity(len(members))
        K = resolve_scan_k(members[0][2].cfg)
        if K <= 1:
            K = DEFAULT_SCAN_K
        mesh = FB.resolve_fleet_mesh(cap, self.mesh)
        return cap, K, mesh

    def _maybe_background_compile(self, leftovers):
        """Split fresh-assembly groups into assemble-now vs wait-for-
        compile.  With the service active, a group whose executable is
        neither LRU-cached nor in the store is submitted as a
        background build and its jobs stay QUEUED (preps cached) —
        the dispatch thread keeps serving warm signatures meanwhile.
        Returns the groups to assemble on this pass."""
        st, svc = self._aot()
        if svc is None:
            return leftovers
        ready: "OrderedDict[tuple, list]" = OrderedDict()
        for key, members in leftovers.items():
            sig = key[0]
            kind, job, drv = members[0]
            cap, K, mesh = self._batch_shape(members)
            ekey = self._background_key(sig, cap, K, mesh)
            if ekey in self._execs:
                self._mark_compile_ready(members)
                ready[key] = members
                continue
            status = svc.status(ekey)
            if status == "done":
                fn = svc.take(ekey)
                if fn is not None:
                    self._execs[ekey] = fn
                    M.counter("aot.background_installs").inc()
                self._mark_compile_ready(members)
                ready[key] = members
                continue
            if status in ("pending", "running"):
                svc.attach(ekey, [job_m.job_id for _, job_m, _ in members])
                for kind_m, job_m, drv_m in members:
                    job_m.mark("compile_wait", collapse=True)
                    self._prepared[job_m.job_id] = (
                        kind_m, drv_m, sig, key)
                continue
            if status == "failed" or st.contains(
                    self._store_sig(sig, cap, K, self._mesh_key(mesh))):
                # failed background build -> synchronous fallback;
                # store present -> assembling now is a disk read
                self._mark_compile_ready(members)
                ready[key] = members
                continue
            self._submit_background(svc, st, sig, cap, K, kind, mesh,
                                    drv, job, members, ekey, key)
        return ready

    @staticmethod
    def _mark_compile_ready(members) -> None:
        """Close the compile_wait interval on every member that opened
        one: the group's executable is now installable, so from here the
        timeline is back in "assembly" (round-22 provenance).  Members
        that never waited (warm signature) are untouched."""
        for _kind, job_m, _drv in members:
            if job_m.event_time("compile_wait") is not None:
                job_m.mark("compile_ready", collapse=True)

    def _submit_background(self, svc, st, sig, cap, K, kind, mesh,
                           drv, job, members, ekey, bucket_key) -> None:
        """Queue one demand build (plus the speculative ±1 ladder
        rungs) and park the group's jobs as prepared-but-waiting."""
        from cup3d_tpu.aot import compiler as aot_compiler

        s = drv.sim
        ob = s.obstacles[0] if kind == "fish" else None
        carry, gait = _lane_payload(kind, drv, job.job_id)
        label = "fleet.advance-" + hashlib.blake2s(
            repr(sig).encode()).hexdigest()[:8]

        def demand_build(cap=cap, K=K, mesh=mesh):
            fn = self._bind_advance(s, ob, cap, K, kind, mesh, sig, st)
            avals = FB.abstract_advance_args(
                carry, gait, cap, K, s.dtype)
            warm = getattr(fn, "warm", None)
            if warm is not None:
                warm(*avals)
            return fn

        # the demand build is causally linked to the jobs that wait on
        # it (round 22): their ids ride the compile task into the pid-5
        # Perfetto span + flow events, and each job's timeline opens a
        # compile_wait interval here
        svc.submit(ekey, demand_build, name=label,
                   priority=aot_compiler.PRIORITY_DEMAND,
                   jobs=[job_m.job_id for _, job_m, _ in members])
        for kind_m, job_m, drv_m in members:
            job_m.mark("compile_wait", collapse=True)
            self._prepared[job_m.job_id] = (kind_m, drv_m, sig,
                                            bucket_key)
        if not aot_compiler.speculate_enabled():
            return
        for rung in self._neighbor_rungs(cap):
            rkey = self._background_key(sig, rung, K, mesh)
            if rkey in self._execs or svc.status(rkey) is not None:
                continue

            def spec_build(rung=rung, K=K, mesh=mesh):
                fn = self._bind_advance(s, ob, rung, K, kind, mesh,
                                        sig, st)
                avals = FB.abstract_advance_args(
                    carry, gait, rung, K, s.dtype)
                warm = getattr(fn, "warm", None)
                if warm is not None:
                    warm(*avals)
                return fn

            if svc.submit(rkey, spec_build, name=label,
                          priority=aot_compiler.PRIORITY_SPECULATIVE):
                M.counter("aot.speculative_compiles").inc()

    def _neighbor_rungs(self, cap: int) -> List[int]:
        """The ±1 rungs of the ×1.25 lane ladder around ``cap``
        (mesh-rounded, max-lanes-clamped, deduplicated)."""
        rungs = []
        down = None
        c = LANE_LADDER_BASE
        while c < cap:
            down = c
            c = max(c + 1, int(np.ceil(c * 1.25)))
        if down is not None:
            down = self.lane_capacity(down)
            if 0 < down != cap:
                rungs.append(down)
        up = self.lane_capacity(cap + 1)
        if cap < up <= self.max_lanes and up not in rungs:
            rungs.append(up)
        return rungs

    # -- tenant lifecycle --------------------------------------------------

    def submit(self, tenant: str, spec: dict) -> str:
        """Validate + enqueue one scenario; returns the job id.
        Admission control (round 17): a queue at its backpressure
        depth, or a tenant at its live-job quota, raises
        :class:`FleetAdmissionError` instead of enqueueing — both
        rejection counts and the backpressure flag surface in
        ``health()["admission"]``."""
        kind = str(spec.get("kind", "fish"))
        if kind not in ("fish", "tgv", "amr_tgv"):
            raise ValueError(f"unknown fleet scenario kind {kind!r}")
        if int(spec.get("nsteps", 0)) <= 0:
            raise ValueError("fleet scenario needs nsteps > 0")
        if self.draining:
            M.counter("fleet.admission_rejects", reason="draining").inc()
            raise FleetAdmissionError(
                "draining", "server is draining for shutdown")
        depth = self.queue_depth()
        if depth >= self.max_queue_depth:
            M.counter("fleet.admission_rejects", reason="queue-full").inc()
            raise FleetAdmissionError(
                "queue-full",
                f"queue depth {depth} at backpressure threshold "
                f"{self.max_queue_depth}")
        if self.tenant_quota > 0:
            live = sum(
                1 for j in self._jobs.values()
                if j.tenant == str(tenant)
                and j.status in (QUEUED, RUNNING))
            if live >= self.tenant_quota:
                M.counter("fleet.admission_rejects", reason="quota").inc()
                raise FleetAdmissionError(
                    "quota",
                    f"tenant {tenant!r} at live-job quota "
                    f"{self.tenant_quota}")
        job_id = f"job-{self._next_job:04d}"
        self._next_job += 1
        job = FleetJob(job_id=job_id, tenant=str(tenant), spec=dict(spec),
                       nsteps=int(spec["nsteps"]))
        job.mark("submitted")
        job.mark("queued")
        self._jobs[job_id] = job
        self._journal("submit", job_id=job_id, tenant=job.tenant,
                      spec=dict(spec), nsteps=job.nsteps)
        M.counter("fleet.submits").inc()
        return job_id

    def poll(self, job_id: str) -> dict:
        return self._jobs[job_id].summary()

    def cancel(self, job_id: str) -> bool:
        """Cancel a queued or running job; terminal jobs are left
        alone.  Returns True when the job state changed."""
        job = self._jobs[job_id]
        if job.status == QUEUED:
            job.status = CANCELLED
            job.mark("cancelled")
            self._prepared.pop(job_id, None)
            M.counter("fleet.lane_retires", reason="cancelled").inc()
            self._job_terminal(job)
            return True
        if job.status == RUNNING and job.batch is not None:
            job.batch.cancel_lane(job.lane)
            # cancel_lane retires through the batch's guarded retire()
            # — a lane already swapped or terminal in the batch is a
            # no-op there, so verify the state actually changed rather
            # than reporting success unconditionally
            return job.status == CANCELLED
        return False

    def drain(self) -> Dict[str, dict]:
        """Run everything queued to completion and return the per-
        tenant summary.  Continuous mode (the default) runs the work-
        conserving serve() loop with admission closed;
        ``CUP3D_FLEET_CONTINUOUS=0`` keeps the legacy generation-drain
        (assemble the queue once, run every batch to completion)
        bitwise-unchanged as the occupancy baseline."""
        if self.continuous:
            return self.serve()
        busy0, total0 = self._occupancy_totals()
        self.assemble()
        while True:
            live = [b for b in self.batches if b.active()]
            if not live:
                break
            for b in live:
                b.tick()
        for b in self.batches:
            b.settle()
        self._aot_quiesce()
        self._close_occupancy_window(busy0, total0)
        self.update_lane_gauge()
        return self.tenant_summary()

    def serve(self, feed=None) -> Dict[str, dict]:
        """The continuous-batching dispatch loop: one scheduling pass
        (reseed freed lanes, assemble what cannot wait) plus one round-
        robin tick per K-boundary.  ``feed(server, tick)``, when given,
        is called at each boundary and may ``submit()`` in-flight
        (admission control applies); it returns False to close
        admission.  The loop ends when admission is closed and every
        admitted job is terminal.  Returns the tenant summary."""
        busy0, total0 = self._occupancy_totals()
        admitting = feed is not None
        tick = 0
        while True:
            if admitting:
                # settle first so pending retirements are visible to
                # the feed's poll()-driven admission decisions; with no
                # feed there is nothing to decide and the dispatch
                # pipeline keeps its full in-flight overlap
                for b in self.batches:
                    if b.active():
                        b.settle()
                admitting = bool(feed(self, tick))
            self._schedule()
            live = [b for b in self.batches if b.active()]
            for b in live:
                b.tick()
            tick += 1
            queued = any(
                j.status == QUEUED for j in self._jobs.values())
            if (not live and queued and self._aot_service is not None
                    and self._aot_service.depth() > 0):
                # death-path (round 23): a dead compile worker can
                # never finish its orphaned builds — reap them FAILED
                # (aot.service_fallbacks) so the next scheduling pass
                # compiles inline, instead of parking forever below
                if self._aot_service.fail_orphans():
                    continue
                # every queued job waits on a background compile and
                # nothing is dispatchable: park on the service instead
                # of busy-spinning the scheduler
                self._aot_service.wait(0.05)
            if not admitting and not live and not queued:
                break
        for b in self.batches:
            b.settle()
        self._aot_quiesce()
        self._close_occupancy_window(busy0, total0)
        self.update_lane_gauge()
        return self.tenant_summary()

    def _aot_quiesce(self) -> None:
        """Let in-flight background builds finish before the serve/drain
        window closes: speculative executables land in the store (warm
        for the next boot), and the process never exits mid-XLA-compile
        (a daemon thread inside the compiler at interpreter teardown
        aborts the process)."""
        if self._aot_service is not None:
            self._aot_service.drain(timeout=600.0)

    def queue_depth(self) -> int:
        return sum(1 for j in self._jobs.values() if j.status == QUEUED)

    # -- durability (round 23) ---------------------------------------------

    def _journal(self, rtype: str, **fields) -> None:
        """Best-effort journal append (no-op with the journal off)."""
        if self.journal is not None:
            self.journal.append(rtype, **fields)

    def close_admission(self) -> None:
        """Stop accepting new jobs (drain-for-shutdown seam,
        fleet/migrate.py): submit() rejects with reason "draining"."""
        self.draining = True

    def _note_job_id(self, job_id: str) -> None:
        """Keep the job-id counter ahead of a replayed id so a
        recovered server never mints a colliding fresh id."""
        try:
            n = int(job_id.rsplit("-", 1)[-1])
        # jax-lint: allow(JX009, foreign-format replayed ids cannot
        # collide with the server's job-%04d mint, so there is nothing
        # to advance past; journal.orphan_records covers the taxonomy)
        except ValueError:
            return
        self._next_job = max(self._next_job, n + 1)

    def recover(self) -> dict:
        """Replay the write-ahead journal into this server (boot-time;
        idempotent — job ids already known are skipped, so replaying
        twice, or a journal extended by this server's own appends, is a
        no-op).  Terminal jobs are remembered with their recorded rows
        (QoI bytes intact, nothing re-runs); queued jobs re-enter the
        queue; RUNNING jobs with a snapshot resume mid-flight in a
        batch rebuilt at the recorded (cap, K) — same executable, same
        bytes; RUNNING jobs that never reached a snapshot restart from
        step 0, which recomputes the identical trajectory.  Returns
        ``{replayed, remembered, requeued, resumed}``."""
        stats = {"replayed": 0, "remembered": 0, "requeued": 0,
                 "resumed": 0}
        if self.journal is None:
            self.last_recovery = dict(stats)
            return self.last_recovery
        pending: List[Tuple[FleetJob, dict]] = []
        for job_id, view in self.journal.replay().items():
            if job_id in self._jobs:
                continue
            stats["replayed"] += 1
            job = FleetJob(
                job_id=job_id, tenant=str(view["tenant"]),
                spec=dict(view["spec"]), nsteps=int(view["nsteps"]))
            self._note_job_id(job_id)
            self._jobs[job_id] = job
            snap = self._install_replayed_job(job, view)
            if job.status in TERMINALS:
                stats["remembered"] += 1
            elif snap is not None:
                pending.append((job, snap))
                stats["resumed"] += 1
            else:
                stats["requeued"] += 1
        if pending:
            self._resume_batches(pending)
        self.update_lane_gauge()
        self.last_recovery = dict(stats)
        return self.last_recovery

    def _install_replayed_job(self, job: FleetJob,
                              view: dict) -> Optional[dict]:
        """Install one replayed journal view onto a fresh FleetJob.
        Returns the snapshot record to resume from (RUNNING jobs with a
        journaled snapshot), else None.  Terminal replays keep their
        recorded rows/steps and set the ``_terminal_done`` guard — the
        crashed server already folded them into its SLO bookkeeping, so
        this server only REMEMBERS them (poll/summaries/QoI bytes),
        it does not re-observe them."""
        status = view["status"]
        if status in TERMINALS:
            job.status = status
            job.error = view.get("error")
            job.steps_done = int(view.get("steps_done", 0))
            job.time = float(view.get("time", 0.0))
            rows = view.get("rows")
            if rows is not None:
                job.rows = np.asarray(rows, np.float64).copy()
            job.mark(status)
            job._terminal_done = True
            M.counter("fleet.recovered_jobs", outcome="remembered").inc()
            return None
        job.status = QUEUED
        job.mark("submitted")
        job.mark("queued")
        job.mark("recovered")
        snap = view.get("snapshot") if status == RUNNING else None
        M.counter("fleet.recovered_jobs",
                  outcome="resumed" if snap is not None
                  else "requeued").inc()
        return snap

    def _resume_batches(self, pending) -> int:
        """Rebuild one batch per crashed batch_uid at its RECORDED
        (cap, K) and splice every resumed job back in at its journaled
        position.  Forcing the recorded shape — rather than re-deriving
        the rung from the (smaller) survivor count — is what keeps
        recovery bitwise: the lane count enters the compiled
        executable, and only the crashed server's own executable
        reproduces the control bytes (with a warm AOT store it loads
        from disk, zero recompiles)."""
        groups: "OrderedDict[object, list]" = OrderedDict()
        for job, snap in pending:
            prep = self._prepare(job)
            if prep is None:
                continue
            kind, drv, _sig, _key = prep
            groups.setdefault(snap.get("batch_uid"), []).append(
                (kind, job, drv, snap))
        resumed = 0
        for members in groups.values():
            kind = members[0][0]
            snap0 = members[0][3]
            cap, K = int(snap0["cap"]), int(snap0["K"])
            jobs = [job for _, job, _, _ in members]
            drivers = [drv for _, _, drv, _ in members]
            b = FleetBatch(self, self._next_batch, kind, jobs,
                           drivers, K, cap)
            self._next_batch += 1
            self.batches.append(b)
            for lane, (_, job, _, snap) in enumerate(members):
                b.resume_lane(lane, job, snap)
                resumed += 1
        return resumed

    # -- assembly ----------------------------------------------------------

    def lane_capacity(self, njobs: int) -> int:
        """Lane-count ladder rung for a batch of ``njobs``, clamped to
        the max-lanes knob and rounded to the mesh multiple."""
        cap = min(
            count_capacity(njobs, base=LANE_LADDER_BASE), self.max_lanes)
        cap = max(cap, njobs)
        mult = FB.mesh_lane_multiple(self.mesh)
        if cap % mult:
            cap += mult - cap % mult
        return cap

    def _prepare(self, job: FleetJob) -> Optional[tuple]:
        """Build + init one queued job's lane driver and bucket key,
        consuming the prepared-job cache when the scheduler already did
        the work on an earlier pass.  Returns (kind, driver, sig,
        bucket_key), or None after failing an ineligible job."""
        prep = self._prepared.pop(job.job_id, None)
        if prep is not None:
            return prep
        kind, cfg = _job_config(job.spec, self.workdir)
        job.cfg = cfg
        if kind == "amr_tgv":
            from cup3d_tpu.sim.amr import AMRSimulation

            drv = _AMRLaneDriver(AMRSimulation(cfg))
        else:
            from cup3d_tpu.sim.simulation import Simulation

            drv = Simulation(cfg)
        drv.init()
        if not drv._megaloop_eligible():
            job.status = FAILED
            job.error = "scenario not scan-eligible"
            job.mark("failed")
            M.counter("fleet.lane_retires", reason="ineligible").inc()
            self._job_terminal(job)
            return None
        sig = _static_signature(drv, kind)
        key = (sig, count_capacity(job.nsteps, base=1))
        # deterministic bucket-signature label for the SLO
        # histograms (hash(), being per-process salted, would split
        # one bucket's series across restarts)
        job.sig_label = "{}-{}".format(
            kind,
            hashlib.blake2s(repr(key).encode()).hexdigest()[:8])
        job.mark("bucketed")
        return kind, drv, sig, key

    def _build_batches(self, buckets) -> List[FleetBatch]:
        """Bucketed (kind, job, driver) groups -> FleetBatches: each
        bucket splits into chunks of <= max_lanes and pads up the lane
        ladder."""
        built = []
        for (sig, _rung), members in buckets.items():
            for i in range(0, len(members), self.max_lanes):
                chunk = members[i:i + self.max_lanes]
                kind = chunk[0][0]
                jobs = [job for _, job, _ in chunk]
                drivers = [drv for _, _, drv in chunk]
                K = resolve_scan_k(drivers[0].cfg)
                if K <= 1:
                    K = DEFAULT_SCAN_K
                b = FleetBatch(self, self._next_batch, kind, jobs,
                               drivers, K, self.lane_capacity(len(jobs)))
                self._next_batch += 1
                self.batches.append(b)
                built.append(b)
        return built

    def assemble(self) -> List[FleetBatch]:
        """Queued jobs -> bucketed batches.  Buckets key on the static
        signature plus the ×1.25 step-budget rung; each bucket splits
        into chunks of <= max_lanes and pads up the lane ladder."""
        queued = [j for j in self._jobs.values() if j.status == QUEUED]
        if not queued:
            return []
        buckets: "OrderedDict[tuple, list]" = OrderedDict()
        for job in queued:
            prep = self._prepare(job)
            if prep is None:
                continue
            kind, drv, _sig, key = prep
            buckets.setdefault(key, []).append((kind, job, drv))
        built = self._build_batches(buckets)
        self.update_lane_gauge()
        return built

    def _schedule(self) -> int:
        """One K-boundary scheduling pass (continuous batching): settle
        the live batches so pending retirements are visible, reseed
        freed lanes with compatible queued jobs (same static signature
        -> the cached executable is reused with zero recompiles), and
        assemble fresh batches only for jobs with no compatible live
        batch to wait on.  Returns the number of lanes reseeded."""
        queued = [j for j in self._jobs.values() if j.status == QUEUED]
        if not queued:
            return 0
        for b in self.batches:
            if b.active():
                b.settle()
        if self.policy == "srb":
            # shortest-remaining-budget: stable sort, FIFO within ties
            queued.sort(key=lambda j: j.nsteps)
        reseeded = 0
        leftovers: "OrderedDict[tuple, list]" = OrderedDict()
        waiting: "OrderedDict[tuple, list]" = OrderedDict()
        for job in queued:
            prep = self._prepare(job)
            if prep is None:
                continue
            kind, drv, sig, key = prep
            placed = blocked = False
            for b in self.batches:
                # only LIVE batches are reseed targets: once a batch
                # fully drains, fresh assembly (which serves the same
                # executable from the LRU cache) is just as work-
                # conserving and keeps the generation semantics of an
                # idle server unchanged
                if b.kind != kind or b.sig != sig or not b.active():
                    continue
                free = b.free_lanes()
                if free:
                    b.reseed_lane(free[0], job, drv)
                    self.reseeds += 1
                    reseeded += 1
                    placed = True
                    break
                blocked = True
            if placed:
                continue
            if blocked:
                # a live compatible batch will free a lane at a coming
                # K-boundary; waiting beats padding out a fresh batch.
                # The wait is a distinct provenance phase (reseed_wait):
                # neither capacity (lanes exist) nor compile (executable
                # is warm) — collapse keeps one event per parked stretch
                job.mark("reseed_wait", collapse=True)
                self._prepared[job.job_id] = prep
                waiting.setdefault(key, []).append((kind, job, drv))
                continue
            leftovers.setdefault(key, []).append((kind, job, drv))
        for key, members in waiting.items():
            # enough blocked same-rung jobs to FILL a batch beats
            # waiting: zero padding lanes, so assembling now is a
            # strict occupancy win over a reseed slot later
            if (len(members) > 1
                    and self.lane_capacity(len(members)) == len(members)):
                for _, job, _ in members:
                    self._prepared.pop(job.job_id, None)
                leftovers.setdefault(key, []).extend(members)
        if leftovers:
            # round 21: cold signatures may compile off-thread — the
            # service keeps their jobs queued and this pass assembles
            # only what is warm (LRU, store, or finished build)
            leftovers = self._maybe_background_compile(leftovers)
        if leftovers:
            self._build_batches(leftovers)
        if reseeded or leftovers:
            self.update_lane_gauge()
        return reseeded

    def executable(self, sig: tuple, s, ob, cap: int, K: int,
                   kind: Optional[str] = None, mesh=None):
        """The compiled-advance cache, LRU-capped by the buckets knob:
        one vmapped executable per (signature, lane rung, K, mesh).
        Round 21: with ``CUP3D_AOT_STORE`` set a miss first consults
        the background compile service, then binds a store-backed
        executable — a previously-seen signature loads its serialized
        XLA executable instead of compiling (zero-cold-start boot)."""
        key = (sig, int(cap), int(K), self._mesh_key(mesh))
        hit = self._execs.pop(key, None)
        if hit is not None:
            self._execs[key] = hit
            M.counter("fleet.executable_hits").inc()
            return hit
        st, svc = self._aot()
        fn = svc.take(key) if svc is not None else None
        if fn is not None:
            M.counter("aot.background_installs").inc()
        else:
            fn = self._bind_advance(s, ob, cap, K, kind, mesh, sig, st)
        self._execs[key] = fn
        M.counter("fleet.executable_builds").inc()
        while len(self._execs) > self.max_buckets:
            self._execs.popitem(last=False)
            M.counter("fleet.executable_evictions").inc()
        return fn

    # -- observability -----------------------------------------------------

    def _occupancy_totals(self) -> Tuple[int, int]:
        return (sum(b.busy_steps for b in self.batches),
                sum(b.total_steps for b in self.batches))

    def _close_occupancy_window(self, busy0: int,
                                total0: int) -> Optional[float]:
        """Fold one drain/serve window into the ``fleet.lane_occupancy``
        gauge: busy-lane-steps / total-lane-steps over the window's
        dispatches.  Frozen and padding lanes count against the
        denominator — that is exactly the waste continuous batching
        reclaims, so the gauge is the bench gate's metric
        (bench.py fleet_skew, gates.fleet_occupancy)."""
        busy, total = self._occupancy_totals()
        dbusy, dtotal = busy - busy0, total - total0
        if dtotal <= 0:
            return None
        occ = dbusy / dtotal
        self.last_occupancy = occ
        M.gauge("fleet.lane_occupancy").set(occ)
        return occ

    def _job_terminal(self, job: FleetJob, batch: Optional[FleetBatch]
                      = None, lane: Optional[int] = None) -> None:
        """One job reached done/failed/cancelled: fold its timeline into
        the SLO histograms + breach window, notify the flight recorders,
        and (tracing on) emit the kind="job" aux record and the pid-3
        lane-occupancy span.  Called exactly once per job — every
        terminal transition funnels through here, and the
        ``_terminal_done`` guard (round 23) makes a second arrival — a
        cancel racing a migration, or a replayed-from-journal terminal
        — a counted no-op instead of a double SLO fold."""
        if job._terminal_done:
            M.counter("fleet.duplicate_terminals").inc()
            return
        job._terminal_done = True
        self._journal(
            "terminal", job_id=job.job_id, status=job.status,
            error=job.error, steps_done=int(job.steps_done),
            time=float(job.time), nsteps=int(job.nsteps),
            rows=None if job.rows is None else job.rows.copy())
        durs = job.durations()
        bucket = job.sig_label or "unbucketed"
        if "queue_wait_s" in durs:
            M.histogram("fleet.job_queue_wait_s", tenant=job.tenant,
                        bucket=bucket).observe(durs["queue_wait_s"])
        if "exec_s" in durs:
            M.histogram("fleet.job_exec_s", tenant=job.tenant,
                        bucket=bucket).observe(durs["exec_s"])
        # round 22 — latency provenance: the exact phase decomposition
        # (sums to e2e by construction) feeds the federation-mergeable
        # per-phase histograms and the burn-attribution share history.
        # CUP3D_FLEET_PROVENANCE=0 skips all of it (overhead gate).
        phases = job.phases() if self.provenance else None
        if phases:
            for ph, v in phases.items():
                M.histogram("fleet.latency_phase_s", phase=ph,
                            tenant=job.tenant).observe(v)
            total = sum(phases.values())
            if total > 0:
                self._phase_share_history.setdefault(
                    job.tenant, deque(maxlen=64)).append(
                        {ph: v / total for ph, v in phases.items()})
        e2e = durs.get("e2e_s")
        if e2e is not None:
            M.histogram("fleet.job_e2e_s", tenant=job.tenant,
                        bucket=bucket).observe(e2e)
            # shard-labeled companion (round 18): which mesh slice the
            # job finished on — a separate family so the existing
            # tenant/bucket label sets (and their quantile merges) are
            # untouched by sharding
            if batch is not None and lane is not None \
                    and batch.nshards() > 1:
                M.histogram(
                    "fleet.shard_job_e2e_s",
                    shard=str(batch.lane_shard(lane))).observe(e2e)
            wnd = self._slo_windows.setdefault(
                job.tenant, deque(maxlen=self.slo_window))
            breached = e2e > self.slo_p99_s
            wnd.append(bool(breached))
            if breached:
                M.counter("fleet.slo_breaches", tenant=job.tenant).inc()
        for fr in _flight.live_recorders():
            fr.note_job({"job_id": job.job_id, "tenant": job.tenant,
                         "status": job.status,
                         "steps_done": int(job.steps_done),
                         **{k: round(v, 6) for k, v in durs.items()}})
        sink = OT.TRACE
        if not sink.enabled:
            return
        rec = OT.job_record(
            job.job_id, job.tenant, job.status, job.steps_done,
            job.events, bucket=bucket,
            durations={k: round(v, 6) for k, v in durs.items()})
        if phases:
            # unrounded: trace_check asserts the partition invariant to
            # float eps against the event-timeline span
            rec["phases"] = phases
        if batch is not None and lane is not None:
            rec["batch"] = int(batch.batch_id)
            rec["lane"] = int(lane)
        sink.aux(rec)
        t_run = job.event_time("running")
        if batch is not None and lane is not None and t_run is not None:
            tid = FB.lane_track_id(batch.batch_id, lane)
            t_end = job.events[-1][1]
            sink.lane_span(
                tid, job.job_id, t_run, t_end - t_run,
                args={"job_id": job.job_id, "tenant": job.tenant,
                      "status": job.status, "bucket": bucket,
                      "steps_done": int(job.steps_done)})
            for name, t in job.events:
                if name == "rollback":
                    sink.lane_instant(tid, "rollback", t,
                                      args={"job_id": job.job_id})
            if (self.provenance
                    and job.event_time("compile_wait") is not None):
                # terminate the flow arrow the compile service opened:
                # the arrow lands inside this job's lane-occupancy span,
                # tying cold-start wait to its build in the trace UI
                sink.flow_finish(job.job_id, "compile->lane", t_run,
                                 OT.LANE_PID, tid)

    def latency_quantiles(self, name: str = "fleet.job_e2e_s",
                          tenant: Optional[str] = None,
                          qs: Tuple[float, ...] = (0.5, 0.95, 0.99)
                          ) -> Dict[str, Optional[float]]:
        """Aggregate quantiles over one job-latency histogram family
        (optionally one tenant's slice), merging bucket counts across
        label sets — the PromQL ``histogram_quantile(sum by (le))``
        computed in-process.  Values are None until a first job lands.
        Note the registry is process-global: the family aggregates over
        every server in the process, exactly like a scrape would."""
        hists = [h for h in M.histograms(name)
                 if tenant is None or h.labels.get("tenant") == tenant]
        return {f"p{int(round(q * 100))}": M.merged_quantile(hists, q)
                for q in qs}

    def phase_quantiles(self, tenant: Optional[str] = None,
                        qs: Tuple[float, ...] = (0.5, 0.99)
                        ) -> Dict[str, Dict[str, Optional[float]]]:
        """Per-phase latency quantiles over the round-22
        ``fleet.latency_phase_s`` family (optionally one tenant's
        slice), bucket counts merged across label sets exactly like
        :meth:`latency_quantiles`.  Only phases that observed at least
        one job appear."""
        out: Dict[str, Dict[str, Optional[float]]] = {}
        fam = M.histograms("fleet.latency_phase_s")
        for ph in OT.JOB_PHASES:
            hists = [h for h in fam
                     if h.labels.get("phase") == ph
                     and (tenant is None
                          or h.labels.get("tenant") == tenant)]
            if hists:
                out[ph] = {
                    f"p{int(round(q * 100))}": M.merged_quantile(
                        hists, q)
                    for q in qs}
        return out

    def phase_attribution(self, tenant: str) -> Optional[dict]:
        """SLO burn attribution for one tenant: which phase dominates
        the current latency window, and which phase's SHARE of
        end-to-end grew against the rolling baseline (the
        obs/history.py median machinery).  Shares are per-job
        phase-seconds / e2e, so they are scale-free: a fleet that got
        uniformly slower shows zero deltas, while a compile storm shows
        compile_wait's share growing.  None until a first job retires
        (or with provenance off)."""
        from cup3d_tpu.obs import history as obs_history

        shares = self._phase_share_history.get(tenant)
        if not shares:
            return None
        recent = list(shares)[-8:]
        quantiles = self.phase_quantiles(tenant=tenant, qs=(0.99,))
        phases: Dict[str, dict] = {}
        dominant = grew = None
        dom_share = grew_delta = 0.0
        for ph in OT.JOB_PHASES:
            series = [s.get(ph, 0.0) for s in shares]
            share = sum(s.get(ph, 0.0) for s in recent) / len(recent)
            base = obs_history.rolling_baseline(series, window=32)
            delta = share - base
            phases[ph] = {
                "p99_s": quantiles.get(ph, {}).get("p99"),
                "share": round(share, 4),
                "baseline_share": round(base, 4),
                "delta": round(delta, 4),
            }
            if dominant is None or share > dom_share:
                dominant, dom_share = ph, share
            if grew is None or delta > grew_delta:
                grew, grew_delta = ph, delta
        return {"dominant_phase": dominant, "grew_phase": grew,
                "phases": phases}

    def slo_status(self) -> dict:
        """The per-tenant SLO view (health()["slo"], fleet slo CLI):
        target, rolling-window breach fraction, and the burn rate —
        breach fraction over the 1% error budget a p99 target implies
        (burn 1.0 = exactly on budget, >1 = burning ahead of it)."""
        tenants = {}
        for tenant, wnd in sorted(self._slo_windows.items()):
            n = len(wnd)
            b = int(sum(wnd))
            frac = (b / n) if n else 0.0
            tenants[tenant] = {
                "jobs": n,
                "breaches": b,
                "breach_fraction": round(frac, 4),
                "burn_rate": round(frac / self.SLO_ERROR_BUDGET, 2),
                "quantiles": self.latency_quantiles(tenant=tenant),
            }
            if frac > self.SLO_ERROR_BUDGET and self.provenance:
                # the budget is burning ahead of plan: attach the
                # round-22 phase attribution so /health names the
                # phase to remediate (capacity vs compile vs reseed)
                tenants[tenant]["attribution"] = \
                    self.phase_attribution(tenant)
        return {
            "target_p99_s": self.slo_p99_s,
            "window": self.slo_window,
            "error_budget": self.SLO_ERROR_BUDGET,
            "tenants": tenants,
        }

    def shard_loss(self, shard: int) -> List[str]:
        """Per-slice elastic recovery entry point: drop mesh slice
        ``shard`` of every live sharded batch.  The lost lanes' RUNNING
        jobs go back to the queue (from step 0) and land on surviving
        shards at the next K-boundary; every surviving lane's carry
        bits are untouched (resilience/elastic.py).  Returns the
        requeued job ids."""
        requeued: List[str] = []
        for b in self.batches:
            if b.nshards() > 1 and shard < b.nshards():
                requeued.extend(b.fail_shard(shard))
        # the jobs are back in the implicit queue (status == QUEUED in
        # self._jobs); the next _schedule() pass reseeds them onto
        # surviving-shard lanes
        return requeued

    def update_lane_gauge(self) -> None:
        M.gauge("fleet.lanes_active").set(
            float(sum(b.running_lanes() for b in self.batches)))

    def jobs_by_status(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for job in self._jobs.values():
            out[job.status] = out.get(job.status, 0) + 1
        return out

    def tenant_summary(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for job in self._jobs.values():
            t = out.setdefault(
                job.tenant, {"jobs": [], "steps_done": 0, "statuses": {}})
            t["jobs"].append(job.summary())
            t["steps_done"] += int(job.steps_done)
            st = t["statuses"]
            st[job.status] = st.get(job.status, 0) + 1
        return out

    def lane_state(self, job_id: str) -> Dict[str, np.ndarray]:
        job = self._jobs[job_id]
        if job.batch is None:
            raise ValueError(f"{job_id} was never assembled into a batch")
        return job.batch.lane_state(job.lane)

    def _aot_health(self) -> Optional[dict]:
        """Store + compile-service state, or None when inert."""
        from cup3d_tpu.aot import store as aot_store

        st = aot_store.active_store()
        if st is None and self._aot_service is None:
            return None
        return {
            "store": st.state() if st is not None else None,
            "service": (self._aot_service.state()
                        if self._aot_service is not None else None),
        }

    def health(self) -> dict:
        """Fleet state for the obs /health endpoint."""
        depth = self.queue_depth()
        return {
            "jobs": self.jobs_by_status(),
            "lanes_active": int(
                sum(b.running_lanes() for b in self.batches)),
            "batches": len(self.batches),
            "dispatches": int(sum(b.dispatches for b in self.batches)),
            "rollbacks": int(sum(b.guard.rollbacks for b in self.batches)),
            "executables": len(self._execs),
            "aot": self._aot_health(),
            "slo": self.slo_status(),
            "admission": {
                "queue_depth": depth,
                "max_queue_depth": self.max_queue_depth,
                "backpressure": depth >= self.max_queue_depth,
                "tenant_quota": self.tenant_quota,
            },
            "scheduler": {
                "continuous": self.continuous,
                "policy": self.policy,
                "reseeds": int(self.reseeds),
                "lane_occupancy": self.last_occupancy,
            },
            "mesh": {
                **topo.mesh_state(
                    self.mesh,
                    fallbacks=int(
                        M.counter("fleet.mesh_fallbacks").value)),
                "dead_lanes": sorted(
                    int(lane) for b in self.batches
                    for lane in b.dead_lanes),
                "shard_losses": int(
                    M.counter("fleet.shard_losses").value),
            },
            "durability": {
                "journal": (None if self.journal is None
                            else self.journal.state()),
                "draining": bool(self.draining),
                "recovered": self.last_recovery,
                "migrations": int(self.migrations),
            },
            "knobs": {
                "max_lanes": self.max_lanes,
                "max_buckets": self.max_buckets,
                "snap_steps": self.snap_steps,
                "mesh": (int(self.mesh.devices.size)
                         if self.mesh is not None else 0),
            },
        }


def summary_json(summary: Dict[str, dict]) -> str:
    return json.dumps(summary, indent=2, sort_keys=True)
