"""Live lane migration + graceful drain (round 23).

A running lane's carry is host-serializable (the journal snapshot
path) and re-enterable through the jitted per-lane reseed upload
(fleet/batch.reseed_lane_carry) — so a RUNNING job can be checkpointed
off server A and finished on server B with bitwise-identical QoI
bytes, PROVIDED B resumes it in a batch of the same recorded (cap, K):
the lane count enters the compiled executable, and only the same
executable reproduces the same bits.  :func:`migrate_job` does exactly
that — ``FleetBatch.release_for_migration`` on the source (settle,
host-copy, freeze the lane, retire MIGRATED) then
``FleetServer._resume_batches`` on the destination (rebuild at the
recorded shape, splice the carry back in, restore the recorded rows).

:func:`drain_for_shutdown` is the graceful-exit mode ROADMAP item 1's
scale-in needs: close admission, move every RUNNING job to the target
server (or, with no target, journal a final settled snapshot per lane
so a later ``recover()`` resumes them), quiesce the background compile
service, and report what went where.  Queued jobs are already durable
(their submit records are in the journal) — nothing to do.

The checkpoint payload is deliberately the journal snapshot view
(fleet/journal.py record schema), so migration and crash recovery
share one resume path and one bitwise contract (VALIDATION.md
"Round 23").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from cup3d_tpu.fleet.server import (
    QUEUED,
    RUNNING,
    FleetJob,
    FleetServer,
)
from cup3d_tpu.obs import metrics as M


def checkpoint_job(server: FleetServer, job_id: str) -> dict:
    """Checkpoint one RUNNING job off ``server``: the lane settles,
    its carry + rows are host-serialized, the lane freezes, and the
    job retires MIGRATED (terminal on the source; the journal terminal
    record remembers the handoff).  Returns the resume payload."""
    job = server._jobs[job_id]
    if job.status != RUNNING or job.batch is None:
        raise ValueError(
            f"{job_id} is {job.status!r}, not a running lane")
    return job.batch.release_for_migration(job.lane)


def admit_checkpoint(server: FleetServer, ckpt: dict) -> str:
    """Install a migrated checkpoint on ``server`` and resume it
    mid-flight under its original job id.  The destination journals
    the admission like a fresh submit, so a crash AFTER migration
    recovers the job here, not on the (drained) source."""
    job_id = str(ckpt["job_id"])
    if job_id in server._jobs:
        raise ValueError(f"{job_id} already exists on the target server")
    job = FleetJob(
        job_id=job_id, tenant=str(ckpt["tenant"]),
        spec=dict(ckpt["spec"]), nsteps=int(ckpt["nsteps"]))
    job.mark("submitted")
    job.mark("queued")
    job.mark("recovered")
    server._note_job_id(job_id)
    server._jobs[job_id] = job
    server._journal("submit", job_id=job_id, tenant=job.tenant,
                    spec=dict(ckpt["spec"]), nsteps=job.nsteps)
    server._resume_batches([(job, ckpt)])
    server.migrations += 1
    M.counter("fleet.migrations").inc()
    return job_id


def migrate_job(src: FleetServer, dst: FleetServer, job_id: str) -> str:
    """Move one RUNNING job from ``src`` to ``dst`` live: checkpoint
    off A, reseed onto B, bitwise (the round-23 contract).  The source
    keeps a MIGRATED terminal under the id; the destination runs the
    job to completion under the same id."""
    return admit_checkpoint(dst, checkpoint_job(src, job_id))


def drain_for_shutdown(src: FleetServer,
                       target: Optional[FleetServer] = None
                       ) -> Dict[str, List[str]]:
    """Graceful exit: stop admission, migrate every RUNNING job to
    ``target`` (or journal a final settled snapshot per lane when no
    target is given, so a restart's ``recover()`` resumes them), and
    quiesce the compile service.  Returns ``{"migrated": [...],
    "journaled": [...], "queued": [...]}`` job-id lists."""
    src.close_admission()
    for b in src.batches:
        if b.active():
            b.settle()
    migrated: List[str] = []
    journaled: List[str] = []
    if target is not None:
        running = [j.job_id for j in src._jobs.values()
                   if j.status == RUNNING and j.batch is not None]
        for job_id in running:
            migrate_job(src, target, job_id)
            migrated.append(job_id)
    else:
        for b in src.batches:
            b.settle()
            b.journal_snapshots()
        journaled = [j.job_id for j in src._jobs.values()
                     if j.status == RUNNING]
    src._aot_quiesce()
    queued = [j.job_id for j in src._jobs.values()
              if j.status == QUEUED]
    M.counter("fleet.drains").inc()
    return {"migrated": migrated, "journaled": journaled,
            "queued": queued}
