"""Per-lane fault isolation: one lane's NaN never touches its neighbors.

The solo resilience layer (resilience/recovery.py) rolls the WHOLE
simulation back to a snapshot; at fleet scale that would punish B-1
healthy tenants for one lane's blow-up.  This module scopes recovery to
the lane:

- detection runs on the consumed QoI rows (per-lane umax/dt chain), so
  it rides the stream's async cadence with zero extra device traffic;
- a faulted lane is rolled back to the rolling batch snapshot through a
  lane-wise ``jnp.where`` select — an elementwise copy for the masked
  lane and a bit-exact passthrough for every other lane (the vmapped
  scan body has no cross-lane op, so healthy lanes are bitwise
  unaffected end to end: VALIDATION.md "Round 14");
- the restored lane's carried dt is halved per attempt (the same
  geometric backoff as RecoveryEngine.scale_dt), which the in-scan
  1.03x growth limiter then recovers from gradually;
- a lane that keeps faulting past ``max_retries`` is retired (its
  ``left`` budget is zeroed, so the gated scan body freezes its carry)
  and flagged to the server, which fails only that tenant's job.

Fault seams (resilience/faults.py): ``step.nan_velocity`` fires on the
per-lane step chain exactly as in the solo consumer, and the
lane-addressed ``fleet.lane_nan`` site (armed with the LANE index in
the step slot) poisons one chosen lane for the isolation tests.
"""

from __future__ import annotations

import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.fleet.batch import LEFT
from cup3d_tpu.obs import metrics as M
from cup3d_tpu.resilience import faults

#: lane lifecycle states (host-side; the device only sees ``left``)
LANE_RUNNING = "running"
LANE_DONE = "done"
LANE_FAILED = "failed"
LANE_CANCELLED = "cancelled"
LANE_PADDING = "padding"

DEFAULT_MAX_RETRIES = 4


def _max_retries() -> int:
    try:
        return int(os.environ.get("CUP3D_MAX_RETRIES", DEFAULT_MAX_RETRIES))
    # jax-lint: allow(JX009, malformed env knob falls back to the
    # default retry budget; the effective value is reported in the
    # server's health payload)
    except ValueError:
        return DEFAULT_MAX_RETRIES


@jax.jit
def _select_lanes(mask, a, b):
    """Lane-wise select over a carry pytree: ``a`` where ``mask`` (B,),
    else ``b``.  jnp.where is an elementwise select, so unselected lanes
    come through with their bits untouched."""
    def sel(x, y):
        m = mask.reshape(mask.shape + (1,) * (x.ndim - 1))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(sel, a, b)


@jax.jit
def _scale_lane_dt(carry, mask, scale):
    out = dict(carry)
    out["dt"] = jnp.where(mask, carry["dt"] * scale, carry["dt"])
    return out


@jax.jit
def _zero_lane_left(carry, mask):
    out = dict(carry)
    out[LEFT] = jnp.where(mask, 0, carry[LEFT])
    return out


def restore_lanes(carry, snap, mask_np, dt_scale):
    """Roll the masked lanes back to ``snap`` with their carried dt
    scaled by ``dt_scale``; every unmasked lane keeps its exact bits."""
    mask = jnp.asarray(np.asarray(mask_np, bool))
    out = _select_lanes(mask, snap, carry)
    return _scale_lane_dt(out, mask, jnp.asarray(dt_scale, out["dt"].dtype))


def retire_lanes(carry, mask_np):
    """Zero the masked lanes' ``left`` budget so the gated scan body
    freezes them; unmasked lanes keep their exact bits."""
    mask = jnp.asarray(np.asarray(mask_np, bool))
    return _zero_lane_left(carry, mask)


class LaneGuard:
    """Per-batch isolation state: the rolling snapshot, per-lane epochs
    (stale-row filtering across rollbacks), and per-lane retry budgets.

    The guard owns no device dispatch loop — the server calls
    ``snapshot()`` at validated boundaries and ``check_row()`` from its
    stream consumer; ``rollback()``/``give_up()`` return the corrected
    batched carry."""

    def __init__(self, nlanes: int, max_retries: Optional[int] = None):
        self.B = int(nlanes)
        self.max_retries = (
            _max_retries() if max_retries is None else int(max_retries))
        self.epochs = np.zeros(self.B, np.int64)
        self.attempts = np.zeros(self.B, np.int64)
        self.fail_step = np.full(self.B, -1, np.int64)
        self.rollbacks = 0
        self.snap = None
        self.snap_step = np.zeros(self.B, np.int64)
        self.snap_left = np.zeros(self.B, np.int64)

    # -- rolling snapshot --------------------------------------------------

    def snapshot(self, carry, step_h, left_h) -> None:
        """Copy the batched carry (and the host step/budget mirrors) as
        the per-lane rollback target.  Callers must only snapshot a
        VALIDATED state: every emitted row up to it consumed clean."""
        self.snap = jax.tree_util.tree_map(jnp.copy, carry)
        self.snap_step = np.asarray(step_h, np.int64).copy()
        self.snap_left = np.asarray(left_h, np.int64).copy()

    # -- detection ---------------------------------------------------------

    def check_row(self, lane: int, step: int, umax: float,
                  dt: float) -> Optional[str]:
        """Classify one consumed lane row; None when healthy.  The
        injection seams run first so a test fault poisons the chain at
        exactly the armed (lane, step)."""
        if faults.fire("fleet.lane_nan", lane):
            return "nan-velocity"
        if faults.fire("step.nan_velocity", step):
            return "nan-velocity"
        if not (math.isfinite(umax) and math.isfinite(dt)):
            return "nan-velocity"
        if dt <= 0.0:
            return "dt-collapse"
        return None

    def note_progress(self, lane: int, step: int) -> None:
        """A clean row past the last failure point closes the incident:
        the retry budget re-arms (RecoveryEngine's retire semantics)."""
        if self.fail_step[lane] >= 0 and step > self.fail_step[lane]:
            self.fail_step[lane] = -1
            self.attempts[lane] = 0

    # -- recovery ----------------------------------------------------------

    def exhausted(self, lane: int) -> bool:
        return bool(self.attempts[lane] >= self.max_retries)

    def rollback(self, carry, lane: int, step: int, reason: str):
        """Roll ONE lane back to the rolling snapshot with dt halved per
        attempt.  Returns (carry', snap_step, snap_left) for the host
        mirrors; the lane's epoch bump invalidates every in-flight row
        it emitted on the abandoned trajectory."""
        if self.snap is None:
            raise RuntimeError("lane rollback requested before any snapshot")
        self.attempts[lane] += 1
        self.fail_step[lane] = max(self.fail_step[lane], int(step))
        self.epochs[lane] += 1
        self.rollbacks += 1
        mask = np.zeros(self.B, bool)
        mask[lane] = True
        scale = 0.5 ** int(self.attempts[lane])
        M.counter("fleet.lane_rollbacks", reason=reason).inc()
        out = restore_lanes(carry, self.snap, mask, scale)
        return out, int(self.snap_step[lane]), int(self.snap_left[lane])

    def reseed(self, carry, lane: int, nsteps: int) -> None:
        """A retired lane was respliced with a fresh job (the caller
        already uploaded the new solo state via fleet/batch.
        reseed_lane_carry; ``carry`` is the post-upload batched carry).
        Reseed-vs-rollback contract (VALIDATION.md "Round 17"):

        - the epoch bump drops every in-flight row the previous
          occupant emitted, exactly like a rollback does;
        - the retry budget resets — a reseeded lane starts with the
          full ``max_retries``, not the previous tenant's remainder;
        - the lane's rows of the rolling snapshot are refreshed to the
          NEW job's initial state, so a post-reseed rollback restores
          the new tenant, never a ghost of the old one.  Other lanes'
          snapshot rows keep their exact bits (lane-wise select)."""
        self.epochs[lane] += 1
        self.attempts[lane] = 0
        self.fail_step[lane] = -1
        if self.snap is not None:
            mask = np.zeros(self.B, bool)
            mask[lane] = True
            self.snap = _select_lanes(
                jnp.asarray(mask), carry, self.snap)
        self.snap_step[lane] = 0
        self.snap_left[lane] = int(nsteps)

    def resume(self, carry, lane: int, step: int, left: int) -> None:
        """A recovered/migrated job was respliced mid-flight (round 23):
        identical to :meth:`reseed` — epoch bump, fresh retry budget,
        lane-wise snapshot refresh to the uploaded carry — except the
        host mirrors record the RESUMED position, not step 0, so a
        post-resume rollback restores the journaled snapshot state."""
        self.reseed(carry, lane, left)
        self.snap_step[lane] = int(step)
        self.snap_left[lane] = int(left)

    def give_up(self, carry, lane: int, reason: str):
        """Retire a lane that exhausted its retries: freeze its carry
        (left = 0) and bump its epoch so stale rows drop."""
        self.epochs[lane] += 1
        mask = np.zeros(self.B, bool)
        mask[lane] = True
        M.counter("fleet.lane_giveups", reason=reason).inc()
        return retire_lanes(carry, mask)
