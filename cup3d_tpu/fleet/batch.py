"""Vmapped many-simulation batching: one dispatch advances B lanes.

BENCH_r04/r05 put every config's floor at ~0.03 s/step of host overhead.
The megaloop (PR 6) amortizes that over K steps of ONE simulation; this
module amortizes it over *scenarios* by laying a leading ``lane`` axis
over the megaloop scan body (sim/megaloop.make_tgv_step /
make_fish_step) with ``jax.vmap``:

- the batched carry stacks vel/p/chi/udef + the 6-DOF rigid vector and
  internal quaternion per lane, so every lane owns its own state;
- the (umax, time, dt) chain is per-lane carry state, so each lane runs
  its own dt policy (stale-umax CFL bound + 1.03x growth limiter) with
  no cross-lane coupling;
- per-lane frozen-gait parameters (models/fish/device_midline.
  freeze_gait) are stacked into a batched pytree and passed as traced
  arguments, so lanes in one executable swim different gaits;
- a per-lane integer ``left`` budget gates the scan body: a lane with
  ``left == 0`` (finished, retired, or padding) has its carry passed
  through a lane-wise ``jnp.where`` select, which reproduces the frozen
  bits exactly — the foundation of the isolation contract
  (fleet/isolate.py, VALIDATION.md "Round 14").

Every operation in the scan body is elementwise over the lane axis under
vmap (per-lane FFTs, per-lane reductions, per-lane while_loops), so lane
trajectories are mutually independent: NaNs cannot cross lanes, and a
frozen or rolled-back lane never perturbs another lane's bits.

Optionally the lane axis is sharded over devices through the
parallel/compat.py shard_map wrapper (CUP3D_FLEET_MESH=1): the body has
no cross-lane collective, so the per-device program is the unmodified
vmapped advance over the local lane shard.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.sim.megaloop import (  # noqa: F401  (rows re-exported)
    FISH_ROW,
    TGV_ROW,
    init_fish_carry,
    init_tgv_carry,
    make_fish_step,
    make_tgv_step,
)

#: carry key holding the per-lane remaining-step budget (int32, (B,))
LEFT = "left"


def init_amr_carry(s):
    """Obstacle-free bucketed-AMR lane carry: the (capacity, 8, 8, 8)
    padded vel/p forest plus the (umax, time, dt) chain — same keys as
    init_tgv_carry, so stack_carries/the gated body treat adaptive and
    uniform lanes identically.  umax is measured with the mega_free
    convention (max |vel + uinf| over the padded forest; padding rows
    are zero, so they never win the max)."""
    dtype = s.dtype
    uinf = s.uinf_device()
    vel = s.state["vel"]
    return {
        "vel": vel,
        "p": s.state["p"],
        "umax": jnp.max(jnp.abs(vel + uinf)),
        "time": jnp.asarray(s.time, dtype),
        "dt": jnp.asarray(s.dt, dtype),
    }


def stack_gaits(gaits, dtype):
    """Per-lane frozen-gait dicts -> one batched pytree (leading lane
    axis).  Python-float leaves become (B,) device scalars so vmap can
    batch them (the solo megaloop bakes them in as constants instead);
    array leaves must share shape across lanes — mixed midline
    discretizations belong in different buckets (fleet/server.py keys
    assembly on the static signature)."""
    keys = sorted(gaits[0])
    for g in gaits:
        if sorted(g) != keys:
            raise ValueError("lane gaits disagree on parameter set")
    out = {}
    for k in keys:
        leaves = [jnp.asarray(g[k], dtype) for g in gaits]
        shapes = {leaf.shape for leaf in leaves}
        if len(shapes) != 1:
            raise ValueError(
                f"gait leaf {k!r} varies in shape across lanes: {shapes}"
            )
        out[k] = jnp.stack(leaves)
    return out


def stack_carries(carries, targets):
    """Stack per-lane solo carries (init_tgv_carry / init_fish_carry
    outputs) into one batched carry, attaching the per-lane ``left``
    budget.  ``targets[b] <= 0`` marks lane b as padding: its state is a
    clone that the gated body freezes from step 0."""
    keys = sorted(carries[0])
    for c in carries:
        if sorted(c) != keys:
            raise ValueError("lane carries disagree on state set")
    out = {k: jnp.stack([c[k] for c in carries]) for k in keys}
    out[LEFT] = jnp.asarray(np.asarray(targets, np.int32))
    return out


def abstract_advance_args(carry, gait, B, K, dtype):
    """The ``jax.ShapeDtypeStruct`` avals of one batched advance call
    — exactly the shapes stack_carries / _cfl_block / stack_gaits
    produce for ``B`` lanes and ``K`` steps — from a SINGLE lane's
    solo (carry, gait) payload.  This is what the background compile
    service (aot/compiler.py) lowers against: no batched arrays are
    materialized, no device memory is touched, and the resulting AOT
    executable is bit-for-bit the one a live dispatch would build.
    Returns ``(carry_avals, cfl_aval, gaits_avals_or_None)``."""
    sds = jax.ShapeDtypeStruct

    def batched(v):
        leaf = jnp.asarray(v) if not hasattr(v, "shape") else v
        return sds((int(B),) + tuple(leaf.shape), leaf.dtype)

    carry_avals = {k: batched(v) for k, v in carry.items()}
    carry_avals[LEFT] = sds((int(B),), jnp.int32)
    cfl_aval = sds((int(B), int(K)), np.dtype(dtype))
    gaits_avals = None
    if gait is not None:
        # mirror stack_gaits: every leaf is cast to the sim dtype and
        # stacked along a new lane axis (floats become (B,) scalars)
        gaits_avals = {
            k: sds((int(B),) + tuple(np.shape(v)), np.dtype(dtype))
            for k, v in gait.items()
        }
    return carry_avals, cfl_aval, gaits_avals


def _gated(core, has_gait):
    """Wrap a solo scan body with the per-lane freeze gate.  Inside vmap
    each lane sees scalar ``left``; a finished/retired/padding lane
    (left == 0) recomputes the step but keeps its old carry through an
    elementwise select — bit-exact freezing, no shape change, and the
    rows it produces are replays the consumer drops by budget."""
    if has_gait:
        def body(gait, carry, cfl_eff):
            left = carry[LEFT]
            act = left > 0
            inner = {k: v for k, v in carry.items() if k != LEFT}
            new, row = core(gait, inner, cfl_eff)
            merged = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new, inner)
            merged[LEFT] = left - act.astype(left.dtype)
            return merged, row
    else:
        def body(gait, carry, cfl_eff):
            del gait
            left = carry[LEFT]
            act = left > 0
            inner = {k: v for k, v in carry.items() if k != LEFT}
            new, row = core(inner, cfl_eff)
            merged = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new, inner)
            merged[LEFT] = left - act.astype(left.dtype)
            return merged, row
    return body


@jax.jit
def _upload_lane_carry(carry, lane, solo, nsteps):
    """One lane's rows of the batched carry <- a solo carry, with the
    lane's ``left`` budget set to ``nsteps``.  ``lane`` is a traced
    int32 scalar, so ``.at[lane].set`` lowers to a dynamic_update_slice
    and every lane index shares ONE compiled specialization — the
    zero-recompile half of the reseed contract.  jnp's scatter-update
    writes only the addressed rows: every other lane's bits come
    through untouched (the round-14 isolation contract extended to
    reseeding, VALIDATION.md "Round 17")."""
    out = {}
    for k, v in carry.items():
        if k == LEFT:
            out[k] = v.at[lane].set(nsteps.astype(v.dtype))
        else:
            out[k] = v.at[lane].set(solo[k].astype(v.dtype))
    return out


@jax.jit
def _upload_lane_gait(gaits, lane, gait):
    return {k: gaits[k].at[lane].set(gait[k]) for k in gaits}


def reseed_lane_carry(carry, lane, solo, nsteps, mesh=None):
    """Splice a fresh job's solo carry into lane ``lane`` of a batched
    carry (per-lane upload, NOT a host restack): the continuous-batching
    reseed primitive.  ``solo`` is an init_*_carry output for the same
    bucket signature; ``nsteps`` becomes the lane's ``left`` budget.
    Like the rollback selects (fleet/isolate.py) the result is a new
    carry — the input is not donated, so in-flight consumers of the old
    buffers stay valid.  With a ``mesh`` the update runs shard-local
    (:func:`_sharded_lane_upload`) so reseeding a mesh-resident carry
    never gathers it to one device.

    Provenance (round 22): this upload is the K-boundary reseed splice
    — on the waiting job's timeline it sits inside the
    ``reseed_wait -> reseeded`` interval (``obs.trace.now()`` clock),
    which the phase decomposition attributes to ``reseed_wait``.  The
    ``fleet.reseed_uploads`` counter gives the per-scrape rate without
    waiting for job terminals."""
    from cup3d_tpu.obs import metrics as M

    M.counter("fleet.reseed_uploads").inc()
    solo = {k: jnp.asarray(solo[k]) for k in carry if k != LEFT}
    up = (_sharded_lane_upload(mesh) if mesh is not None
          else _upload_lane_carry)
    return up(carry, jnp.asarray(lane, jnp.int32), solo,
              jnp.asarray(nsteps, jnp.int32))


def reseed_lane_gaits(gaits, lane, gait, mesh=None):
    """Swap one lane's row of the stacked frozen-gait pytree (fish
    bucket reseed); None passes through for gait-free bodies.  The new
    gait must share the batch's parameter set and leaf shapes — reseeds
    are same-signature by construction (fleet/server.py matches on the
    static signature before calling this).  ``mesh`` routes the update
    through the shard-local upload like :func:`reseed_lane_carry`."""
    if gaits is None:
        return None
    if sorted(gait) != sorted(gaits):
        raise ValueError("reseed gait disagrees with the batch gait set")
    solo = {k: jnp.asarray(gait[k], gaits[k].dtype) for k in gaits}
    if mesh is not None:
        # gait rows ride the same shard-local update as carry rows (the
        # gait pytree has no LEFT key, so nsteps is inert)
        return _sharded_lane_upload(mesh)(
            gaits, jnp.asarray(lane, jnp.int32), solo,
            jnp.asarray(0, jnp.int32))
    return _upload_lane_gait(gaits, jnp.asarray(lane, jnp.int32), solo)


def lane_carry_host(carry, lane):
    """One lane's rows of a batched carry as host numpy copies — the
    serialization half of the round-23 durability contract (the upload
    half is :func:`reseed_lane_carry`).  ``np.asarray`` round-trips the
    f32 bits exactly, so journal snapshot -> ``recover()`` reseed -> the
    SAME compiled advance reproduces the never-crashed trajectory
    bitwise.  The LEFT budget row is dropped: placement decides the
    resumed lane's budget (``nsteps`` arg of the reseed upload), exactly
    as it does for a fresh splice."""
    return {k: np.asarray(v[lane]) for k, v in carry.items() if k != LEFT}


#: lane-track tid stride: lane tids are ``batch_id * LANE_TID_STRIDE +
#: lane`` so concurrent batches never share a Perfetto thread track
#: (the pid-3 job-occupancy export, obs/trace.LANE_PID)
LANE_TID_STRIDE = 4096


def lane_track_id(batch_id: int, lane: int) -> int:
    """Stable Perfetto tid of one (batch, lane) occupancy track —
    shared by fleet/server.py (emission) and tools/trace_check.py
    (validation: spans on one tid must not overlap)."""
    return int(batch_id) * LANE_TID_STRIDE + int(lane)


def fleet_mesh() -> Optional["jax.sharding.Mesh"]:
    """The optional fleet mesh behind CUP3D_FLEET_MESH: now the 2-D
    ``(lanes, x)`` factory (parallel/topology.fleet_mesh2d), whose
    ``CUP3D_MESH`` auto default of ``(ndevices, 1)`` reproduces the old
    1-D lanes mesh bit-for-bit as the L-by-1 special case.  None keeps
    the pure-vmap single-device fleet."""
    from cup3d_tpu.parallel import topology as topo

    return topo.fleet_mesh2d()


def mesh_lane_multiple(mesh) -> int:
    """Lane counts must divide evenly over the mesh; 1 when unsharded.
    On the 2-D mesh the batch axis shards over EVERY mesh device (the
    lane axis flattens across ``lanes`` and ``x``), so the multiple is
    the full device count."""
    return int(mesh.devices.size) if mesh is not None else 1


def resolve_fleet_mesh(n_lanes: int, mesh) -> Optional[
        "jax.sharding.Mesh"]:
    """The loud mesh gate: the mesh the fleet will actually use for a
    batch of ``n_lanes``.  A lane count that does not divide over the
    mesh devices cannot shard evenly — the fleet then falls back to the
    unsharded vmap advance, visibly: a warning, the
    ``fleet.mesh_fallbacks`` counter, and a None that callers store in
    place of the mesh (so /health and the CLI report the shard state
    that is really running, not the one that was asked for)."""
    if mesh is None:
        return None
    mult = mesh_lane_multiple(mesh)
    if n_lanes % mult == 0:
        return mesh
    import warnings

    from cup3d_tpu.obs import metrics as M

    warnings.warn(
        f"{n_lanes} lanes do not divide over the {mult}-device fleet "
        f"mesh {dict(mesh.shape)}: batch runs unsharded", stacklevel=2)
    M.counter("fleet.mesh_fallbacks").inc()
    return None


#: per-mesh memo of the shard_map'd lane-upload executables (one entry
#: per live mesh; jit's own cache keys the shapes under it)
_SHARDED_UPLOADS: dict = {}


def _sharded_lane_upload(mesh):
    """The round-17 reseed upload for a mesh-sharded carry: a
    shard_map'd dynamic-update-slice in LOCAL lane coordinates.  A
    plain ``.at[lane].set`` on a sharded carry would make the SPMD
    partitioner materialize cross-device gathers around the update;
    here every shard computes its flat shard id, rebases ``lane`` into
    its own block, and applies a where-masked one-row update — the
    owning shard writes, every other shard reproduces its bits
    untouched.  Memoized per mesh so steady-state reseeding never
    retraces."""
    fn = _SHARDED_UPLOADS.get(mesh)
    if fn is not None:
        return fn
    from jax.sharding import PartitionSpec as P

    from cup3d_tpu.parallel.compat import shard_map

    axes = tuple(mesh.axis_names)
    minor = int(mesh.shape[axes[1]]) if len(axes) > 1 else 1

    def upload(carry, lane, solo, nsteps):
        sid = jax.lax.axis_index(axes[0])
        if len(axes) > 1:
            sid = sid * minor + jax.lax.axis_index(axes[1])
        some = next(iter(carry.values()))
        bl = some.shape[0]  # local lanes per shard (B // nshards)
        loc = lane - sid * bl
        ok = (loc >= 0) & (loc < bl)
        locc = jnp.clip(loc, 0, bl - 1)

        def upd(v, row):
            cur = jax.lax.dynamic_slice_in_dim(v, locc, 1, axis=0)
            new = jnp.where(ok, row[None].astype(v.dtype), cur)
            return jax.lax.dynamic_update_slice_in_dim(
                v, new, locc, axis=0)

        out = {}
        for k, v in carry.items():
            if k == LEFT:
                out[k] = upd(v, nsteps)
            else:
                out[k] = upd(v, solo[k])
        return out

    def specs(tree):
        return jax.tree_util.tree_map(lambda _: P(axes), tree)

    def wrapped(carry, lane, solo, nsteps):
        sm = shard_map(
            upload, mesh,
            in_specs=(specs(carry), P(),
                      jax.tree_util.tree_map(lambda _: P(), solo), P()),
            out_specs=specs(carry),
            check_vma=False)
        return sm(carry, lane, solo, nsteps)

    fn = jax.jit(wrapped)
    _SHARDED_UPLOADS[mesh] = fn
    return fn


def build_fleet_advance(s, ob=None, mesh=None, kind=None):
    """jitted ``(carry_B, cfl (B, K), gaits_B) -> (carry_B', rows
    (B, K, ROW))``: B independent lanes, K steps each, one dispatch.

    ``s`` is the bucket's template Simulation (grid, solver, statics);
    ``ob`` its template obstacle for the fish pipeline (None selects an
    obstacle-free body, where ``gaits`` is passed as None).  ``kind``
    picks the scan body explicitly — "fish", "tgv", or "amr_tgv" (the
    bucketed block-forest body from sim/amr.make_amr_tgv_step, whose
    frozen padded-topology closure is what fleet/server.py's
    (capacity, topology-signature) bucket key guarantees is shared) —
    defaulting to fish/tgv by ``ob`` for older callers.  With a
    ``mesh`` the lane axis is sharded across devices via the
    parallel/compat.py shard_map wrapper — the body is collective-free,
    so each device runs the vmapped advance over its lane shard.

    The carry is deliberately NOT donated: the batched advance's result
    feeds lane-wise where-selects against the previous carry on the
    rollback path (fleet/isolate.py), so the pre-dispatch buffers must
    stay valid until the isolation layer releases them."""
    if kind is None:
        kind = "fish" if ob is not None else "tgv"
    has_gait = kind == "fish"
    if kind == "fish":
        core = make_fish_step(s, ob)
    elif kind == "amr_tgv":
        from cup3d_tpu.sim.amr import make_amr_tgv_step

        core = make_amr_tgv_step(s)
    else:
        core = make_tgv_step(s)
    body = _gated(core, has_gait)

    def lane_scan(gait, carry, cfl_eff):
        return jax.lax.scan(
            lambda c, x: body(gait, c, x), carry, cfl_eff)

    gait_axes = 0 if has_gait else None

    def advance(carry, cfl_eff, gaits):
        return jax.vmap(lane_scan, in_axes=(gait_axes, 0, 0))(
            gaits, carry, cfl_eff)

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from cup3d_tpu.parallel.compat import shard_map

        # the batch axis shards over the FLATTENED mesh (2-D (lanes, x)
        # or the legacy 1-D (lanes,)): the body is collective-free, so
        # each device runs the vmapped advance over its lane block
        lanes = P(tuple(mesh.axis_names))
        advance = shard_map(
            advance, mesh,
            in_specs=(lanes, lanes, lanes),
            out_specs=(lanes, lanes),
        )
    return jax.jit(advance)
