"""Triangle geometry helpers (reference main.cpp:8341-8463: Vector3,
Moller-Trumbore rayIntersectsTriangle, pointTriangleSqrDistance).

The reference carries these for externally-meshed obstacles; its condensed
factory builds only StefanFish, so they are utility parity.  Here they are
vectorized jnp kernels (batch of rays/points vs batch of triangles) so a
future mesh-SDF rasterizer can run them as one gather-free device pass.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def ray_intersects_triangle(origin, direction, v0, v1, v2):
    """Moller-Trumbore: returns (hit mask, t) for rays against triangles.

    All arguments broadcast: origin/direction (..., 3), v0/v1/v2 (..., 3).
    t is the ray parameter (inf where no hit)."""
    e1 = v1 - v0
    e2 = v2 - v0
    h = jnp.cross(direction, e2)
    a = jnp.sum(e1 * h, axis=-1)
    parallel = jnp.abs(a) < _EPS
    f = 1.0 / jnp.where(parallel, 1.0, a)
    s = origin - v0
    u = f * jnp.sum(s * h, axis=-1)
    q = jnp.cross(s, e1)
    v = f * jnp.sum(direction * q, axis=-1)
    t = f * jnp.sum(e2 * q, axis=-1)
    hit = (
        (~parallel)
        & (u >= 0.0)
        & (u <= 1.0)
        & (v >= 0.0)
        & (u + v <= 1.0)
        & (t > _EPS)
    )
    return hit, jnp.where(hit, t, jnp.inf)


def point_triangle_sqr_distance(p, v0, v1, v2):
    """Squared distance from points p (..., 3) to triangles (v0, v1, v2)
    (..., 3) — the region-based closest-point classification
    (main.cpp:8395-8463)."""
    e0 = v1 - v0
    e1 = v2 - v0
    d = v0 - p
    a = jnp.sum(e0 * e0, axis=-1)
    b = jnp.sum(e0 * e1, axis=-1)
    c = jnp.sum(e1 * e1, axis=-1)
    dd = jnp.sum(e0 * d, axis=-1)
    e = jnp.sum(e1 * d, axis=-1)
    det = jnp.maximum(a * c - b * b, _EPS)
    s = b * e - c * dd
    t = b * dd - a * e

    # barycentric clamping: project onto edges/vertices outside the face
    inside = (s + t <= det) & (s >= 0) & (t >= 0)
    s_in = s / det
    t_in = t / det

    # edge v0-v1 (t = 0)
    s01 = jnp.clip(jnp.where(a > _EPS, -dd / jnp.maximum(a, _EPS), 0.0), 0, 1)
    # edge v0-v2 (s = 0)
    t02 = jnp.clip(jnp.where(c > _EPS, -e / jnp.maximum(c, _EPS), 0.0), 0, 1)
    # edge v1-v2 (s + t = 1): parameterize q = v1 + w (v2 - v1)
    e12 = v2 - v1
    w12 = jnp.clip(
        jnp.sum((p - v1) * e12, axis=-1)
        / jnp.maximum(jnp.sum(e12 * e12, axis=-1), _EPS),
        0,
        1,
    )

    def dist2(ss, tt):
        q = v0 + ss[..., None] * e0 + tt[..., None] * e1
        r = p - q
        return jnp.sum(r * r, axis=-1)

    d_face = dist2(s_in, t_in)
    d01 = dist2(s01, jnp.zeros_like(s01))
    d02 = dist2(jnp.zeros_like(t02), t02)
    q12 = v1 + w12[..., None] * e12
    d12 = jnp.sum((p - q12) ** 2, axis=-1)
    d_border = jnp.minimum(jnp.minimum(d01, d02), d12)
    return jnp.where(inside, d_face, d_border)
