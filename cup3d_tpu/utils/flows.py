"""Canonical analytic flow fields shared by ICs, tests, and benchmarks."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.uniform import UniformGrid


def taylor_green_2d(grid: UniformGrid, t: float = 0.0, nu: float = 0.0,
                    dtype=jnp.float32) -> jnp.ndarray:
    """z-invariant Taylor-Green vortex — an *exact* unsteady NS solution
    (velocity decays as exp(-2 nu k^2 t)); the correctness anchor."""
    x = grid.cell_centers(dtype)
    k = 2.0 * np.pi / grid.extent[0]
    decay = float(np.exp(-2.0 * nu * k * k * t))
    u = jnp.sin(k * x[..., 0]) * jnp.cos(k * x[..., 1]) * decay
    v = -jnp.cos(k * x[..., 0]) * jnp.sin(k * x[..., 1]) * decay
    return jnp.stack([u, v, jnp.zeros_like(u)], axis=-1)


def taylor_green_3d(grid: UniformGrid, dtype=jnp.float32) -> jnp.ndarray:
    """Classic 3-D Taylor-Green initial condition (transitions to
    turbulence) — the reference's `-initCond taylorGreen`
    (main.cpp:12722)."""
    x = grid.cell_centers(dtype)
    k = 2.0 * np.pi / grid.extent[0]
    u = jnp.sin(k * x[..., 0]) * jnp.cos(k * x[..., 1]) * jnp.cos(k * x[..., 2])
    v = -jnp.cos(k * x[..., 0]) * jnp.sin(k * x[..., 1]) * jnp.cos(k * x[..., 2])
    return jnp.stack([u, v, jnp.zeros_like(u)], axis=-1)


def coil_vorticity(xc: jnp.ndarray) -> jnp.ndarray:
    """The reference's coiled-vorticity field (IC_vorticity,
    main.cpp:12537-12614): a 90-point coil at radius R(phi) =
    0.05 sin(2 phi) centered on (1,1,1); each cell takes the unit tangent
    of the NEAREST coil point scaled by 1/(r^2+1)^2.  xc: (..., 3) cell
    centers; returns omega (..., 3).  The absolute constants are the
    reference's (meant for a domain enclosing (1,1,1))."""
    ncoil, m = 90, 2
    phi = np.arange(ncoil) * (2.0 * np.pi / ncoil)
    R = 0.05 * np.sin(m * phi)
    pts = np.stack(
        [R * np.cos(phi) + 1.0, R * np.sin(phi) + 1.0,
         R * np.cos(m * phi) + 1.0], axis=-1
    )
    dR = 0.05 * m * np.cos(m * phi)
    tang = np.stack(
        [dR * np.cos(phi) - R * np.sin(phi),
         dR * np.sin(phi) + R * np.cos(phi),
         dR * np.cos(m * phi) - m * R * np.sin(m * phi)], axis=-1
    )
    tang /= np.sqrt((tang**2).sum(-1) + 1e-21)[:, None]
    p = jnp.asarray(pts, xc.dtype)
    t = jnp.asarray(tang, xc.dtype)
    d2 = jnp.sum((xc[..., None, :] - p) ** 2, axis=-1)  # (..., ncoil)
    idx = jnp.argmin(d2, axis=-1)
    r2 = jnp.take_along_axis(d2, idx[..., None], axis=-1)[..., 0]
    mag = 1.0 / (r2 + 1.0) ** 2
    return mag[..., None] * t[idx]


def coil_velocity_uniform(grid: UniformGrid, dtype=jnp.float32):
    """Velocity recovered from the coiled vorticity: u_d = lap^-1 of
    -(curl omega)_d component-wise (the reference solves the same three
    Poisson problems with its pressure solver, main.cpp:12614-12668).
    Uses the exact spectral inverse on the uniform grid."""
    from cup3d_tpu.ops import stencils as st
    from cup3d_tpu.ops.poisson import build_spectral_solver

    om = coil_vorticity(grid.cell_centers(dtype))
    curl = st.curl(grid.pad_vector(om, 1), 1, grid.h)
    solver = build_spectral_solver(grid, dtype)
    comps = [solver(-curl[..., d]) for d in range(3)]
    return jnp.stack(comps, axis=-1)
