"""Canonical analytic flow fields shared by ICs, tests, and benchmarks."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.uniform import UniformGrid


def taylor_green_2d(grid: UniformGrid, t: float = 0.0, nu: float = 0.0,
                    dtype=jnp.float32) -> jnp.ndarray:
    """z-invariant Taylor-Green vortex — an *exact* unsteady NS solution
    (velocity decays as exp(-2 nu k^2 t)); the correctness anchor."""
    x = grid.cell_centers(dtype)
    k = 2.0 * np.pi / grid.extent[0]
    decay = float(np.exp(-2.0 * nu * k * k * t))
    u = jnp.sin(k * x[..., 0]) * jnp.cos(k * x[..., 1]) * decay
    v = -jnp.cos(k * x[..., 0]) * jnp.sin(k * x[..., 1]) * decay
    return jnp.stack([u, v, jnp.zeros_like(u)], axis=-1)


def taylor_green_3d(grid: UniformGrid, dtype=jnp.float32) -> jnp.ndarray:
    """Classic 3-D Taylor-Green initial condition (transitions to
    turbulence) — the reference's `-initCond taylorGreen`
    (main.cpp:12722)."""
    x = grid.cell_centers(dtype)
    k = 2.0 * np.pi / grid.extent[0]
    u = jnp.sin(k * x[..., 0]) * jnp.cos(k * x[..., 1]) * jnp.cos(k * x[..., 2])
    v = -jnp.cos(k * x[..., 0]) * jnp.sin(k * x[..., 1]) * jnp.cos(k * x[..., 2])
    return jnp.stack([u, v, jnp.zeros_like(u)], axis=-1)
