from cup3d_tpu.parallel.collectives import (  # noqa: F401
    all_gather_tiled,
    pmax_axis,
    psum_axis,
)
from cup3d_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    field_sharding,
    scalar_sharding,
    shard_field,
)
