"""Block-axis sharding of the AMR forest over a device mesh.

This is the TPU-native rebuild of the reference's entire L0 layer:
GridMPI's block partition (main.cpp:2960-2988), the SynchronizerMPI_AMR
halo engine (pack / Isend / Irecv / unpack, main.cpp:1515-2545),
FluxCorrectionMPI's cross-rank face exchange (main.cpp:2546-2946) and the
LoadBalancer's Z-sorted contiguous partition (main.cpp:4906-5021).

Design
------
Blocks are laid out in cross-level Hilbert order (grid/sfc.py) and cut
into ``D`` contiguous chunks, one per device — Hilbert contiguity *is* the
balanced, locality-preserving partition the reference's LoadBalancer
maintains by migrating blocks.  Every field pads the block axis to a
multiple of ``D`` and shards it over a 1-D ``Mesh((D,), ("b",))``.

For each (topology, stencil width) pair the host computes once exactly
which remote cells each shard's halo gathers touch (the analogue of
``SynchronizerMPI_AMR::_Setup``).  Per lab assembly the device then runs,
inside ``shard_map``:

    local gather (pack) -> one all_to_all over ICI -> local gather (unpack)

The all_to_all payload is the union of cross-shard halo rows — the same
wire bytes the reference's nonblocking sends move, batched into a single
static collective, which is the shape ICI wants.  2:1 restriction weights,
coarse-scratch interpolation and BC signs ride in the same tables as the
single-device path; the operators in ops/amr_ops.py and ops/diffusion.py
run unchanged because ShardedLabTables / ShardedFluxTables duck-type the
LabTables / FluxTables assembly protocol.

Global reductions (Krylov dots, force integrals) stay ordinary ``jnp``
sums over the sharded arrays: under jit XLA lowers them to ``psum`` over
the mesh — the reference's MPI_Iallreduce (main.cpp:14486-14550).

Adaptation (a new topology) simply builds a new ShardedForest: re-setup of
all synchronizers (main.cpp:5153-5157) becomes re-deriving gather tables,
and the contiguous cut of the *new* Hilbert order is the rebalanced
partition (no diffusion balancing needed).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cup3d_tpu.grid.blocks import BlockGrid, LabTables
from cup3d_tpu.grid.flux import FluxTables, build_flux_tables
from cup3d_tpu.parallel.compat import shard_map

_HI = jax.lax.Precision.HIGHEST


def make_block_mesh(devices=None, axis: str = "b") -> Mesh:
    """1-D mesh over the block axis.  jax.devices() order follows the
    physical torus, so contiguous Hilbert chunks land on ICI neighbors."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis,))


#: (octree signature, mesh device ids, axis) -> ShardedForest, LRU
_FOREST_MEMO: "OrderedDict[tuple, ShardedForest]" = OrderedDict()
_FOREST_MEMO_MAX = 4


def cached_forest(grid: BlockGrid, mesh: Optional[Mesh] = None
                  ) -> "ShardedForest":
    """Signature-keyed ShardedForest memo (the sharded twin of
    sim/amr.py's _table_memo discipline): a regrid that returns to a
    previously-seen topology — the dominant ping-pong pattern of
    adaptive runs — reuses the forest's host-derived gather/exchange
    tables AND, through sim/amr.py's executable memo keyed on the same
    signature, every compiled sharded step.  Two topologies with equal
    signatures have bitwise-equal tables, so the reuse is exact; a
    genuinely new topology still pays one setup + trace (its tables
    are closure constants by design, see module doc)."""
    if mesh is None:
        mesh = make_block_mesh()
    key = (
        grid.signature,
        tuple(int(d.id) for d in np.asarray(mesh.devices).flat),
        tuple(mesh.axis_names),
    )
    forest = _FOREST_MEMO.pop(key, None)
    from cup3d_tpu.obs import metrics as obs_metrics

    obs_metrics.counter(
        "forest.memo_hits" if forest is not None else "forest.memo_misses"
    ).inc()
    if forest is None:
        forest = ShardedForest(grid, mesh)
    _FOREST_MEMO[key] = forest
    while len(_FOREST_MEMO) > _FOREST_MEMO_MAX:
        _FOREST_MEMO.popitem(last=False)
    return forest


class ExecutableMemo:
    """Signature-keyed LRU of compiled step-executable bundles — the
    round-18 port of PR 3's capacity-bucketing discipline to the forest
    path.  The sharded forest's duck-typed tables are not pytrees, so
    its jits close over them and are only reusable for an IDENTICAL
    topology; equal octree signatures guarantee bitwise-equal tables,
    so a regrid that returns to a seen topology (the refine->coarsen
    ping-pong) swaps the whole bundle back in with zero retraces.
    Hits/misses surface as ``<name>_hits`` / ``<name>_misses``."""

    def __init__(self, max_entries: int = 4,
                 name: str = "forest.exec_memo"):
        self.max_entries = int(max_entries)
        self.name = name
        self._memo: "OrderedDict[object, dict]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._memo)

    def get(self, sig) -> Optional[dict]:
        """The bundle compiled for ``sig``, refreshed in LRU order, or
        None on a genuinely new topology (counted either way)."""
        from cup3d_tpu.obs import metrics as obs_metrics

        bundle = self._memo.pop(sig, None)
        obs_metrics.counter(
            f"{self.name}_hits" if bundle is not None
            else f"{self.name}_misses"
        ).inc()
        if bundle is not None:
            self._memo[sig] = bundle
        return bundle

    def put(self, sig, bundle: dict) -> None:
        self._memo[sig] = bundle
        while len(self._memo) > self.max_entries:
            self._memo.popitem(last=False)


def bind_step_executable(fn, *bound, donate=(), name=None,
                         store_sig=None):
    """One compiled step executable with the forest's (non-pytree)
    tables closed over as trailing constants: ``fn(*args, *bound)``
    jitted with ``donate`` naming the caller-facing state argnums.

    This is THE jit-construction site for the forest path — callers on
    the adaptation path (sim/amr.py ``_rebuild``) bind here and memoize
    the result by octree signature (:class:`ExecutableMemo`), so a
    fresh jit object is only ever built once per NEW topology, never
    per regrid pass (the JX007 hazard class this helper burns down).

    Round 19: it is therefore also THE cost-accounting seam — under
    ``CUP3D_COSTS=1`` (obs/costs.enabled) the jitted object's first
    invocation additionally AOT-harvests the executable's compiler-
    counted FLOPs/bytes/HBM footprint into the obs registry under
    ``name`` (default: the wrapped fn's name).  One extra lowering per
    bound executable, a single cached bool test per call after that —
    the steady-state hot path is untouched.

    Round 21: and THE persistence seam — ``store_sig`` (the octree
    signature plus the config content the closure captures; equal sigs
    guarantee bitwise-equal bound tables) keys the executable into the
    persistent AOT store when ``CUP3D_AOT_STORE`` is active, so a
    restarted process loads the serialized executable instead of
    retracing.  With the store inactive or ``store_sig=None`` the
    returned object is the plain jitted callable, unchanged."""
    jitted = jax.jit(lambda *a: fn(*a, *bound), donate_argnums=donate)
    label = name or getattr(fn, "__name__", None) or "forest.step"
    if store_sig is not None:
        from cup3d_tpu.aot import store as aot_store

        jitted = aot_store.store_backed(
            jitted, ("forest", label, tuple(donate), store_sig),
            name=f"forest.{label}", donated=bool(donate))
    from cup3d_tpu.obs import costs as obs_costs

    if obs_costs.enabled():
        jitted = obs_costs.harvest_on_first_call(
            jitted, f"forest.{label}")
    return jitted


def bind_order_executables(fn, tabs, donate=(), store_sig=None) -> tuple:
    """(first_order, second_order) compiled executables for a pressure-
    order-switched step body: ``fn(*args, *tabs, second_order=...)``
    bound per order through :func:`bind_step_executable`.  The caller
    picks by step index at call time — the order switch is two cached
    executables, not a retrace."""
    return tuple(
        bind_step_executable(partial(fn, second_order=so), *tabs,
                             donate=donate,
                             name=f"{getattr(fn, '__name__', 'step')}"
                                  f"_o{2 if so else 1}",
                             store_sig=store_sig)
        for so in (False, True)
    )


class _Exchange:
    """Host-built routing for one (flat-array layout, reference set).

    ``unit``: flat entries per block.  Remaps global flat indices (with
    sentinel ``nb*unit``) into each destination shard's local address
    space: [0, nbs*unit) local, [nbs*unit, nbs*unit + D*M) received rows,
    nbs*unit + D*M the zero sentinel."""

    def __init__(self, forest: "ShardedForest", unit: int,
                 ref_lists: Dict[int, np.ndarray]):
        D, nbs = forest.D, forest.nbs
        self.unit = unit
        local_n = nbs * unit
        sent = forest.grid.nb * unit  # global sentinel

        def shard_of(f):
            return np.minimum(f // unit // nbs, D)  # sentinel -> D

        # per destination shard: remote refs grouped by source shard
        groups = []  # groups[s][t] = sorted unique global indices
        for s in range(D):
            refs = ref_lists.get(s)
            if refs is None or refs.size == 0:
                groups.append([np.zeros(0, np.int64)] * D)
                continue
            refs = refs[refs < sent]
            own = shard_of(refs)
            groups.append(
                [np.unique(refs[own == t]) if t != s else np.zeros(0, np.int64)
                 for t in range(D)]
            )
        # keep M >= 1 so the all_to_all payload shape never degenerates
        M = max([g.size for gs in groups for g in gs] + [1])
        self.M = M

        # send table: send_idx[t, s, :] = local flat indices (on t) of the
        # cells shard s needs from t; padded rows re-read cell 0
        send_idx = np.zeros((D, D, M), np.int64)
        for s in range(D):
            for t in range(D):
                g = groups[s][t]
                send_idx[t, s, : g.size] = g - t * local_n
        self.send_idx = jnp.asarray(send_idx, jnp.int32)
        self.groups = groups
        self.local_n = local_n
        self.zero_idx = local_n + D * M
        self._shard_of = shard_of
        self._sent = sent

    def remap(self, idx: np.ndarray, dst_shard: int) -> np.ndarray:
        """Global flat indices -> dst shard's local address space."""
        D = len(self.groups)
        out = np.full(idx.shape, self.zero_idx, np.int64)
        own = self._shard_of(idx)
        mine = own == dst_shard
        out[mine] = idx[mine] - dst_shard * self.local_n
        for t in range(D):
            if t == dst_shard:
                continue
            g = self.groups[dst_shard][t]
            sel = (own == t) & (idx < self._sent)
            if not np.any(sel) or g.size == 0:
                continue
            pos = np.searchsorted(g, idx[sel])
            out[sel] = self.local_n + t * self.M + pos
        return out


def _exchange_gather(flat: jnp.ndarray, send_idx: jnp.ndarray, axis: str):
    """flat: (local_n, C) shard-local values.  Returns (local_n + D*M + 1, C)
    extended array: local rows, received rows, zero sentinel."""
    send = flat[send_idx]  # (D, M, C)
    recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0)
    zero = jnp.zeros((1,) + flat.shape[1:], flat.dtype)
    return jnp.concatenate([flat, recv.reshape(-1, *flat.shape[1:]), zero])


@dataclass
class ShardedLabTables:
    """Duck-typed LabTables whose assembly runs under shard_map with a
    cross-shard halo exchange (see module docstring)."""

    width: int
    forest: "ShardedForest"
    ghost_xyz: Tuple[np.ndarray, np.ndarray, np.ndarray]
    g_idx: jnp.ndarray  # (nb_pad, ng, 8) shard-local addresses
    g_w: jnp.ndarray
    g_sign: jnp.ndarray
    mask_coarse: jnp.ndarray
    s_idx: jnp.ndarray
    s_w: jnp.ndarray
    s_sign: jnp.ndarray
    interp_w: jnp.ndarray
    any_coarse: bool
    send_idx: jnp.ndarray  # (D, D, M)

    def _assemble(self, field: jnp.ndarray, bs: int, signed: bool):
        """field: (nb_pad, bs,bs,bs, C) sharded on axis 0 -> labs
        (nb_pad, L,L,L, C)."""
        f = self.forest
        w = self.width
        L = bs + 2 * w
        S = self.interp_w.shape[1]
        gx, gy, gz = self.ghost_xyz
        axis = f.axis
        any_coarse = self.any_coarse
        interp_w = np.asarray(self.interp_w)  # replicated closure constant

        def kernel(field, g_idx, g_w, g_sign, mask, s_idx, s_w, s_sign,
                   send_idx):
            nbs = field.shape[0]
            C = field.shape[-1]
            flat = field.reshape(-1, C)
            ext = _exchange_gather(flat, send_idx[0], axis)
            vals = ext[g_idx]  # (nbs, ng, 8, C)
            ghosts = jnp.sum(vals * g_w[..., None], axis=2)
            if signed:
                ghosts = ghosts * g_sign
            if any_coarse:
                sv = jnp.sum(ext[s_idx] * s_w[..., None], axis=2)
                if signed:
                    sv = sv * s_sign
                scratch = sv.reshape(nbs, S, S, S, C)
                interp = scratch
                for ax in (1, 2, 3):
                    interp = jnp.moveaxis(
                        jnp.tensordot(interp, interp_w,
                                      axes=([ax], [1]), precision=_HI),
                        -1, ax,
                    )
                ghosts = jnp.where(
                    mask[..., None], interp[:, gx, gy, gz], ghosts
                )
            lab = jnp.zeros((nbs, L, L, L, C), field.dtype)
            lab = lab.at[:, w : w + bs, w : w + bs, w : w + bs].set(field)
            return lab.at[:, gx, gy, gz].set(ghosts.astype(field.dtype))

        pb = P(f.axis)
        return shard_map(
            kernel,
            mesh=f.mesh,
            in_specs=(pb,) * 9,
            out_specs=pb,
            check_vma=False,
        )(field, self.g_idx, self.g_w, self.g_sign, self.mask_coarse,
          self.s_idx, self.s_w, self.s_sign, self.send_idx)

    def assemble_scalar(self, field: jnp.ndarray, bs: int) -> jnp.ndarray:
        return self._assemble(field[..., None], bs, signed=False)[..., 0]

    def assemble_vector(self, field: jnp.ndarray, bs: int) -> jnp.ndarray:
        return self._assemble(field, bs, signed=True)

    def assemble_component(self, field, bs: int, comp: int) -> jnp.ndarray:
        lab = self._assemble_signed_comp(field[..., None], bs, comp)
        return lab[..., 0]

    def _assemble_signed_comp(self, field, bs: int, comp: int):
        # per-component sign labs: reuse the vector path with the component's
        # sign column broadcast over the single channel
        sub = ShardedLabTables(
            width=self.width, forest=self.forest, ghost_xyz=self.ghost_xyz,
            g_idx=self.g_idx, g_w=self.g_w,
            g_sign=self.g_sign[..., comp : comp + 1],
            mask_coarse=self.mask_coarse, s_idx=self.s_idx, s_w=self.s_w,
            s_sign=self.s_sign[..., comp : comp + 1],
            interp_w=self.interp_w, any_coarse=self.any_coarse,
            send_idx=self.send_idx,
        )
        return sub._assemble(field, bs, signed=True)


@dataclass
class ShardedFluxTables:
    """Duck-typed FluxTables: coarse-side corrections applied shard-locally
    after an all_to_all fetch of remote fine-face flux rows
    (FluxCorrectionMPI, main.cpp:2546-2946)."""

    forest: "ShardedForest"
    tgt_cell: jnp.ndarray  # (D*ncmax,) local cell addresses, sharded
    tgt_flux: jnp.ndarray  # (D*ncmax,) local flux addresses
    src_flux: jnp.ndarray  # (D*ncmax, 4) extended flux addresses
    inv_hc: jnp.ndarray  # (D*ncmax,) 0 on padding rows
    send_idx: jnp.ndarray  # (D, D, Mf)
    ncorr: int

    def apply(self, out: jnp.ndarray, fluxes: jnp.ndarray) -> jnp.ndarray:
        if self.ncorr == 0:
            return out
        f = self.forest
        axis = f.axis

        def kernel(out, fluxes, tgt_cell, tgt_flux, src_flux, inv_hc,
                   send_idx):
            fflat = fluxes.reshape(-1, 1)
            ext = _exchange_gather(fflat, send_idx[0], axis)[..., 0]
            fine_mean = jnp.mean(ext[src_flux], axis=-1)
            corr = (-fine_mean - ext[tgt_flux]) * inv_hc
            flat = out.reshape(-1)
            flat = flat.at[tgt_cell].add(corr.astype(flat.dtype))
            return flat.reshape(out.shape)

        pb = P(f.axis)
        return shard_map(
            kernel,
            mesh=f.mesh,
            in_specs=(pb,) * 7,
            out_specs=pb,
            check_vma=False,
        )(out, fluxes, self.tgt_cell, self.tgt_flux, self.src_flux,
          self.inv_hc, self.send_idx)


class _PaddedGeom:
    """Duck-typed BlockGrid view over the padded block axis: exactly the
    attributes ops/amr_ops.py touches (nb, bs, h).  Padding blocks get
    h=1 — their fields are zero, so every operator output on them is 0."""

    def __init__(self, grid: BlockGrid, nb_pad: int):
        self.bs = grid.bs
        self.nb = nb_pad
        self.h = np.concatenate(
            [grid.h, np.ones(nb_pad - grid.nb, grid.h.dtype)]
        )
        self.extent = grid.extent


class ShardedForest:
    """One AMR topology sharded over a 1-D device mesh (see module doc)."""

    def __init__(self, grid: BlockGrid, mesh: Optional[Mesh] = None):
        if mesh is None:
            mesh = make_block_mesh()
        if len(mesh.axis_names) != 1:
            raise ValueError("ShardedForest wants a 1-D mesh over blocks")
        self.grid = grid
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.D = mesh.devices.size
        # per-shard block count rounds up the capacity ladder
        # (grid/bucket.py, base 1: small shards stay exact): regrids
        # whose per-shard count stays within a rung keep every sharded
        # array shape, bounding allocator churn across re-layouts (the
        # forest still re-traces — its tables are closures by design)
        from cup3d_tpu.grid import bucket as bk

        self.nbs = bk.count_capacity(-(-grid.nb // self.D), base=1)
        self.nb_pad = self.nbs * self.D
        self.geom = _PaddedGeom(grid, self.nb_pad)
        self.block_sharding = NamedSharding(mesh, P(self.axis))
        self._lab_cache: Dict[int, ShardedLabTables] = {}
        self._flux_cache: Optional[ShardedFluxTables] = None
        # (nb_pad,1,1,1) cell volume, 0 on padding: reductions weighted by
        # vol automatically ignore the pad blocks
        vol = np.zeros((self.nb_pad, 1, 1, 1), np.float64)
        vol[: grid.nb, 0, 0, 0] = grid.h**3
        self.vol = self.pad_aux(jnp.asarray(vol, jnp.float32))
        pmask = np.zeros((self.nb_pad, 1, 1, 1), np.float32)
        pmask[: grid.nb] = 1.0
        self.pmask = self.pad_aux(jnp.asarray(pmask))

    # -- field layout ------------------------------------------------------

    def pad(self, field: jnp.ndarray) -> jnp.ndarray:
        """(nb, ...) -> (nb_pad, ...) zero-padded, sharded on the mesh."""
        extra = self.nb_pad - field.shape[0]
        if extra:
            field = jnp.concatenate(
                [field, jnp.zeros((extra,) + field.shape[1:], field.dtype)]
            )
        return jax.device_put(field, self.block_sharding)

    def pad_aux(self, arr: jnp.ndarray) -> jnp.ndarray:
        """Already nb_pad-long auxiliary array -> sharded."""
        return jax.device_put(arr, self.block_sharding)

    def unpad(self, field: jnp.ndarray) -> jnp.ndarray:
        return field[: self.grid.nb]

    # -- synchronizer setup (host) ----------------------------------------

    def lab_tables(self, width: int) -> ShardedLabTables:
        if width not in self._lab_cache:
            self._lab_cache[width] = self._build_lab(width)
        return self._lab_cache[width]

    def face_tables(self, width: int):
        """Sharded face-slab fast path (parallel/faces.py) — the round-3
        FaceTables design under shard_map.  Falls back to the per-ghost
        lab tables when the topology has degenerate closed-boundary blocks
        (empty on periodic domains)."""
        key = ("face", width)
        if key not in self._lab_cache:
            from cup3d_tpu.parallel.faces import build_sharded_face_tables

            try:
                self._lab_cache[key] = build_sharded_face_tables(self, width)
            except ValueError:
                self._lab_cache[key] = self.lab_tables(width)
        return self._lab_cache[key]

    def _build_lab(self, width: int) -> ShardedLabTables:
        g = self.grid
        t = g.lab_tables(width)
        D, nbs = self.D, self.nbs
        bs = g.bs
        unit = bs**3

        g_idx = np.asarray(t.g_idx, np.int64)  # (nb, ng, 8)
        s_idx = np.asarray(t.s_idx, np.int64)
        ref_lists = {}
        for s in range(D):
            lo, hi = s * nbs, min((s + 1) * nbs, g.nb)
            if lo >= g.nb:
                ref_lists[s] = np.zeros(0, np.int64)
                continue
            ref_lists[s] = np.concatenate(
                [g_idx[lo:hi].ravel(), s_idx[lo:hi].ravel()]
            )
        ex = _Exchange(self, unit, ref_lists)

        ng, ns = g_idx.shape[1], s_idx.shape[1]
        g_re = np.full((self.nb_pad, ng, 8), ex.zero_idx, np.int64)
        s_re = np.full((self.nb_pad, ns, 8), ex.zero_idx, np.int64)
        for s in range(D):
            lo, hi = s * nbs, min((s + 1) * nbs, g.nb)
            if lo >= g.nb:
                continue
            g_re[lo:hi] = ex.remap(g_idx[lo:hi], s)
            s_re[lo:hi] = ex.remap(s_idx[lo:hi], s)

        def padb(a, fill=0.0):
            pad = np.full((self.nb_pad - g.nb,) + a.shape[1:], fill, a.dtype)
            return jnp.asarray(np.concatenate([np.asarray(a), pad]))

        return ShardedLabTables(
            width=width,
            forest=self,
            ghost_xyz=t.ghost_xyz,
            g_idx=self.pad_aux(jnp.asarray(g_re, jnp.int32)),
            g_w=self.pad_aux(padb(t.g_w)),
            g_sign=self.pad_aux(padb(t.g_sign, 1.0)),
            mask_coarse=self.pad_aux(padb(t.mask_coarse, False)),
            s_idx=self.pad_aux(jnp.asarray(s_re, jnp.int32)),
            s_w=self.pad_aux(padb(t.s_w)),
            s_sign=self.pad_aux(padb(t.s_sign, 1.0)),
            interp_w=t.interp_w,
            any_coarse=t.any_coarse,
            send_idx=self.pad_aux(ex.send_idx),
        )

    @property
    def flux_tables(self) -> ShardedFluxTables:
        if self._flux_cache is None:
            self._flux_cache = self._build_flux()
        return self._flux_cache

    def _build_flux(self) -> ShardedFluxTables:
        g = self.grid
        t: FluxTables = build_flux_tables(g)
        D, nbs = self.D, self.nbs
        bs = g.bs
        funit = 6 * bs * bs
        cunit = bs**3

        if t.ncorr == 0:
            z = jnp.zeros(0, jnp.int32)
            return ShardedFluxTables(
                self, z, z, jnp.zeros((0, 4), jnp.int32),
                jnp.zeros(0, jnp.float32), jnp.zeros((D, D, 0), jnp.int32), 0
            )

        tgt_cell = np.asarray(t.tgt_cell, np.int64)
        tgt_flux = np.asarray(t.tgt_flux, np.int64)
        src_flux = np.asarray(t.src_flux, np.int64)
        inv_hc = np.asarray(t.inv_hc, np.float64)
        owner = tgt_cell // cunit // nbs  # shard of the corrected block

        ref_lists = {
            s: src_flux[owner == s].ravel() for s in range(D)
        }
        ex = _Exchange(self, funit, ref_lists)

        ncmax = max(int(np.sum(owner == s)) for s in range(D))
        TC = np.zeros((D, ncmax), np.int64)
        TF = np.zeros((D, ncmax), np.int64)
        SF = np.full((D, ncmax, 4), ex.zero_idx, np.int64)
        IH = np.zeros((D, ncmax), np.float64)
        for s in range(D):
            sel = owner == s
            n = int(np.sum(sel))
            if n == 0:
                continue
            TC[s, :n] = tgt_cell[sel] - s * nbs * cunit
            TF[s, :n] = tgt_flux[sel] - s * nbs * funit
            SF[s, :n] = ex.remap(src_flux[sel], s)
            IH[s, :n] = inv_hc[sel]

        return ShardedFluxTables(
            forest=self,
            tgt_cell=self.pad_aux(jnp.asarray(TC.reshape(-1), jnp.int32)),
            tgt_flux=self.pad_aux(jnp.asarray(TF.reshape(-1), jnp.int32)),
            src_flux=self.pad_aux(jnp.asarray(SF.reshape(D * ncmax, 4),
                                              jnp.int32)),
            inv_hc=self.pad_aux(jnp.asarray(IH.reshape(-1), jnp.float32)),
            send_idx=self.pad_aux(ex.send_idx),
            ncorr=t.ncorr,
        )

    # -- solvers -----------------------------------------------------------

    def build_poisson_solver(self, **kw):
        """Sharded getZ-preconditioned BiCGSTAB: the single-device builder
        with the forest's duck-typed tables, padded-aware volume weights,
        and a padding mask; halo exchange + refluxing ride the forest's
        collectives and the Krylov dots lower to psum over the mesh (the
        reference's overlapped MPI_Iallreduce, main.cpp:14486-14550).
        Round 4: the halo assembly inside the Krylov loop runs on the
        sharded face-slab fast path (parallel/faces.py)."""
        from cup3d_tpu.ops import amr_ops

        return amr_ops.build_amr_poisson_solver(
            self.geom, tab=self.face_tables(1), flux_tab=self.flux_tables,
            vol=self.vol, pmask=self.pmask, **kw,
        )

    def build_helmholtz_solver(self, **kw):
        """Sharded implicit-diffusion Helmholtz solve (the distributed
        DiffusionSolver, main.cpp:6896-7146)."""
        from cup3d_tpu.ops.diffusion import build_amr_helmholtz_solver

        return build_amr_helmholtz_solver(
            self.geom, tab=self.face_tables(1), flux_tab=self.flux_tables,
            **kw,
        )
