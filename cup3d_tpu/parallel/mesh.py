"""Device-mesh spatial domain decomposition.

The reference partitions the octree across MPI ranks by space-filling-curve
order and hand-codes halo exchange (SynchronizerMPI_AMR, main.cpp:1515-2545)
plus diffusion/global load balancing (main.cpp:4660-5022).  The TPU design
replaces all of that machinery for the uniform path with *sharding
annotations*: fields are laid out ``(x, y, z[, c])`` and sharded over a 2-D
``Mesh("x", "y")``; XLA's SPMD partitioner turns the pad+slice stencils into
neighbor collectives riding the ICI torus, and overlap of compute with halo
communication falls out of the compiler's latency hiding instead of
hand-written ``avail_next()`` polling (main.cpp:2329-2355).

The z axis is kept unsharded so each shard's innermost (lane-aligned)
dimension stays dense — the layout the VPU wants.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _factor2(n: int) -> Tuple[int, int]:
    """n -> (a, b), a*b = n, as square as possible, a >= b."""
    b = int(np.floor(np.sqrt(n)))
    while n % b:
        b -= 1
    return n // b, b


def make_mesh(devices: Optional[Sequence] = None,
              shape: Optional[Tuple[int, int]] = None,
              axis_names: Tuple[str, str] = ("x", "y")) -> Mesh:
    """2-D mesh over the given (default: all) devices.

    On real hardware the device order produced by jax.devices() follows the
    physical torus, so a near-square factorization keeps both mesh axes on
    ICI neighbors.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = _factor2(len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def field_sharding(mesh: Mesh) -> NamedSharding:
    """(nx, ny, nz, 3) vector field: shard x and y, replicate z and c."""
    return NamedSharding(mesh, P(*mesh.axis_names, None, None))


def scalar_sharding(mesh: Mesh) -> NamedSharding:
    """(nx, ny, nz) scalar field: shard x and y."""
    return NamedSharding(mesh, P(*mesh.axis_names, None))


def shard_field(arr, mesh: Mesh):
    sh = field_sharding(mesh) if arr.ndim == 4 else scalar_sharding(mesh)
    return jax.device_put(arr, sh)
