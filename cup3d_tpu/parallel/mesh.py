"""Device-mesh spatial domain decomposition.

The reference partitions the octree across MPI ranks by space-filling-curve
order and hand-codes halo exchange (SynchronizerMPI_AMR, main.cpp:1515-2545)
plus diffusion/global load balancing (main.cpp:4660-5022).  The TPU design
replaces all of that machinery for the uniform path with *sharding
annotations*: fields are laid out ``(x, y, z[, c])`` and sharded over a 2-D
``Mesh("x", "y")``; XLA's SPMD partitioner turns the pad+slice stencils into
neighbor collectives riding the ICI torus, and overlap of compute with halo
communication falls out of the compiler's latency hiding instead of
hand-written ``avail_next()`` polling (main.cpp:2329-2355).

The z axis is kept unsharded so each shard's innermost (lane-aligned)
dimension stays dense — the layout the VPU wants.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _factor2(n: int,
             divide: Optional[Tuple[int, int]] = None) -> Tuple[int, int]:
    """n -> (a, b), a*b = n, as square as possible, a >= b.

    With ``divide`` = (nx, ny) block counts, only factorizations whose
    axes evenly divide them qualify (either orientation; squarest
    wins).  Device counts with no valid split raise — previously e.g. 6
    devices over a 64-block axis silently produced a (3, 2) mesh whose
    x axis cannot shard the grid at all, and every downstream sharding
    constraint quietly replicated (round-12 non-power-of-two fix)."""
    if n <= 0:
        raise ValueError(f"cannot factor a mesh over {n} devices")
    pairs = []
    for b in range(int(np.floor(np.sqrt(n))), 0, -1):
        if n % b == 0:
            pairs.append((n // b, b))
    if divide is None:
        return pairs[0]
    for a, b in pairs:
        if divide[0] % a == 0 and divide[1] % b == 0:
            return a, b
        if divide[0] % b == 0 and divide[1] % a == 0:
            return b, a
    raise ValueError(
        f"{n} devices admit no 2-D mesh whose axes divide the "
        f"(x, y) block counts {divide}: factor pairs "
        f"{pairs} all leave a ragged axis"
    )


def make_mesh(devices: Optional[Sequence] = None,
              shape: Optional[Tuple[int, int]] = None,
              axis_names: Tuple[str, str] = ("x", "y"),
              divide: Optional[Tuple[int, int]] = None) -> Mesh:
    """2-D mesh over the given (default: all) devices.

    On real hardware the device order produced by jax.devices() follows the
    physical torus, so a near-square factorization keeps both mesh axes on
    ICI neighbors.

    ``divide`` = (nx, ny) grid extents (cells or blocks) the mesh axes
    must divide evenly; non-power-of-two device counts then get a valid
    (possibly non-square) shape, or a loud error when none exists,
    instead of a silently unshardable mesh.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = _factor2(len(devices), divide)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def field_sharding(mesh: Mesh) -> NamedSharding:
    """(nx, ny, nz, 3) vector field: shard x and y, replicate z and c."""
    return NamedSharding(mesh, P(*mesh.axis_names, None, None))


def scalar_sharding(mesh: Mesh) -> NamedSharding:
    """(nx, ny, nz) scalar field: shard x and y."""
    return NamedSharding(mesh, P(*mesh.axis_names, None))


def shard_field(arr, mesh: Mesh):
    sh = field_sharding(mesh) if arr.ndim == 4 else scalar_sharding(mesh)
    return jax.device_put(arr, sh)
