"""Ring-permute halo exchange for the sharded Krylov hot path.

The sharded layers move neighbor data two ways today: the face-table
assembly (parallel/faces.py) issues blocking ``lax.all_to_all``
collectives inside every Krylov iteration, and the uniform lanes
Laplacian simply isn't sharded at all.  On a TPU torus both patterns
leave ICI bandwidth on the table: halo traffic is *neighbor* traffic, so
the natural transport is a ring permute per direction — which Pallas can
issue as an **async remote copy** (``pltpu.make_async_remote_copy``,
SNIPPETS.md [1] / the distributed-Pallas ring idiom) that flies while
the interior stencil computes, and is awaited only where boundary tiles
consume it.

Three layers, each with a CPU-exact fallback so tier-1 stays green
without a TPU:

- :func:`ring_shift` — one ring permute step.  TPU (CUP3D_RING_DMA
  auto/on): a Pallas kernel that starts the send-sided DMA and returns;
  elsewhere: ``lax.ppermute`` (same dataflow, collective transport).
- :func:`ring_all_to_all` — drop-in for the halo-exchange
  ``lax.all_to_all(split_axis=0, concat_axis=0)`` built from D-1 ring
  steps, chunks landing as they arrive.  faces.py dispatches here under
  CUP3D_RING_HALO=1.
- :func:`make_laplacian_lanes_sharded` — the lanes Laplacian under
  shard_map with the x-slab halo exchanged by ring permutes that are
  issued BEFORE the interior-tile compute and consumed only in the
  final edge-plane concatenation, so XLA/Mosaic can overlap the ICI
  transfer with the intra-shard stencil.

Lane order is x-major (krylov.to_lanes: t = (tx*NBy + ty)*NBz + tz), so
sharding the lane axis evenly IS an x-slab decomposition and each
shard's boundary is one contiguous run of NBy*NBz lanes — the ring
messages are single dense slices, no gather.
"""

from __future__ import annotations

import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.parallel.compat import shard_map

__all__ = [
    "use_ring_dma",
    "use_ring_halo",
    "ring_shift",
    "ring_all_to_all",
    "pad_slab_scalar",
    "pad_slab_vector",
    "make_laplacian_lanes_sharded",
]


def use_ring_dma() -> bool:
    """Whether ring_shift lowers to the Pallas async-remote-copy kernel.

    CUP3D_RING_DMA: ``auto`` (default) = on for the TPU backend only;
    ``1`` forces it (TPU expected — the kernel targets ICI); ``0``
    forces the ppermute transport everywhere."""
    v = os.environ.get("CUP3D_RING_DMA", "auto").strip().lower()
    if v in ("0", "false", "no"):
        return False
    if v in ("1", "true", "yes"):
        return True
    return jax.default_backend() == "tpu"


def use_ring_halo() -> bool:
    """Whether faces.py's entry exchange rides ring permutes instead of
    the blocking all_to_all (CUP3D_RING_HALO=1; default off — the
    all_to_all path remains the validated baseline)."""
    return os.environ.get("CUP3D_RING_HALO", "0") in ("1", "true", "yes")


def _ring_shift_pallas(x: jnp.ndarray, axis_name: str, shift: int,
                       axis_size: int) -> jnp.ndarray:
    """One ring step as a Pallas async remote copy (send-sided DMA to
    the (me + shift) mod D neighbor over ICI; SNIPPETS.md [1] idiom).
    Must run inside shard_map over ``axis_name`` on TPU."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        me = jax.lax.axis_index(axis_name)
        dst = jax.lax.rem(me + shift + axis_size, axis_size)
        copy = pltpu.make_async_remote_copy(
            src_ref=in_ref,
            dst_ref=out_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(dst,),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        copy.start()
        copy.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid_spec=grid_spec,
    )(x)


def ring_shift(x: jnp.ndarray, axis_name: str, shift: int = 1):
    """Rotate ``x`` by ``shift`` positions around the mesh axis: each
    shard receives the chunk of shard (me - shift) mod D.  Must be
    called inside shard_map over ``axis_name``."""
    D = jax.lax.psum(1, axis_name)  # static axis size
    if use_ring_dma():
        return _ring_shift_pallas(x, axis_name, shift, D)
    perm = [(i, (i + shift) % D) for i in range(D)]
    return jax.lax.ppermute(x, axis_name, perm)


def ring_all_to_all(send: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Drop-in for ``lax.all_to_all(send, axis, split_axis=0,
    concat_axis=0)`` with ``send`` shaped (D, M, ...): D-1 ring permute
    steps, each carrying one shard-to-shard chunk.  On TPU every step is
    an async remote copy, so chunks stream around the ring instead of
    rendezvousing in one blocking collective; the diagonal (own) chunk
    never leaves the shard."""
    D = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    own = jax.lax.dynamic_slice_in_dim(send, me, 1, axis=0)
    out = jax.lax.dynamic_update_slice_in_dim(
        jnp.zeros_like(send), own, me, axis=0
    )
    for k in range(1, D):
        # send my chunk for shard (me+k) this round; the matching chunk
        # from shard (me-k) arrives and lands at its source row
        chunk = jax.lax.dynamic_slice_in_dim(
            send, jax.lax.rem(me + k, D), 1, axis=0
        )
        got = ring_shift(chunk, axis_name, shift=k)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, got, jax.lax.rem(me - k + D, D), axis=0
        )
    return out


def _pad_slab_x(grid: UniformGrid, f: jnp.ndarray, width: int,
                axis_name: str, comp):
    """x-ghosts of one (sx, ny, nz) slab: the cross-shard halo by ring
    permute (issued FIRST, so on TPU the async remote copy flies while
    the caller's y/z padding computes), with the GLOBAL x boundary
    reproduced bit-for-bit from grid/uniform._pad — periodic is the
    natural ring wrap; edge-copy (and the wall/normal-component sign
    flip) applies only on shard 0 / D-1."""
    from cup3d_tpu.grid.uniform import BC

    D = jax.lax.psum(1, axis_name)
    lo_own = jax.lax.slice_in_dim(f, 0, width, axis=0)
    hi_own = jax.lax.slice_in_dim(f, f.shape[0] - width, f.shape[0],
                                  axis=0)
    recv_lo = ring_shift(hi_own, axis_name, shift=+1)
    recv_hi = ring_shift(lo_own, axis_name, shift=-1)
    bc = grid.bc[0]
    if bc == BC.periodic:
        lo, hi = recv_lo, recv_hi
    else:
        me = jax.lax.axis_index(axis_name)
        edge_lo = jnp.repeat(jax.lax.slice_in_dim(f, 0, 1, axis=0),
                             width, axis=0)
        edge_hi = jnp.repeat(
            jax.lax.slice_in_dim(f, f.shape[0] - 1, f.shape[0], axis=0),
            width, axis=0)
        if comp is not None and (bc == BC.wall or comp == 0):
            edge_lo, edge_hi = -edge_lo, -edge_hi
        lo = jnp.where(me == 0, edge_lo, recv_lo)
        hi = jnp.where(me == D - 1, edge_hi, recv_hi)
    return jnp.concatenate([lo, f, hi], axis=0)


def _pad_slab_yz(grid: UniformGrid, f: jnp.ndarray, width: int, comp):
    """y/z ghosts of an x-padded slab — the unsharded axes, padded with
    the same sequential per-axis logic as grid/uniform._pad (so the
    ghost corners match the solo path exactly)."""
    from cup3d_tpu.grid import uniform as _u

    for axis in (1, 2):
        bc = grid.bc[axis]
        if bc == _u.BC.periodic:
            f = _u._pad_axis(f, axis, width, mode="wrap")
        else:
            f = _u._pad_axis(f, axis, width, mode="edge")
            if comp is not None and (bc == _u.BC.wall or comp == axis):
                f = _u._negate_ghosts(f, axis, width)
    return f


def pad_slab_scalar(grid: UniformGrid, f: jnp.ndarray, width: int,
                    axis_name: str) -> jnp.ndarray:
    """grid.pad_scalar for one x-slab inside shard_map over
    ``axis_name``: x-ghosts come from the ring halo (plus the global
    BC at shard 0 / D-1), y/z ghosts from the grid BCs.  Elementwise
    identical to slicing the solo padded array — the slab stencils
    built on top inherit bitwise equivalence."""
    return _pad_slab_yz(grid,
                        _pad_slab_x(grid, f, width, axis_name, None),
                        width, None)


def pad_slab_vector(grid: UniformGrid, u: jnp.ndarray, width: int,
                    axis_name: str) -> jnp.ndarray:
    """grid.pad_vector for one (sx, ny, nz, 3) x-slab inside shard_map:
    per-component ghosts with the solo path's BC sign flips.  The two
    ring messages per component are issued before the y/z padding and
    consumed only in the x-ghost concatenation, preserving the
    halos-before-interior overlap of make_laplacian_lanes_sharded."""
    comps = []
    for c in range(3):
        comps.append(_pad_slab_yz(
            grid, _pad_slab_x(grid, u[..., c], width, axis_name, c),
            width, c))
    return jnp.stack(comps, axis=-1)


def make_laplacian_lanes_sharded(grid: UniformGrid, mesh: Mesh,
                                 bs: int = 8) -> Callable:
    """The lanes-layout 7-point Laplacian (krylov.make_laplacian_lanes)
    sharded over the lane axis as x-slabs, with the cross-shard halo
    exchanged by ring permutes.

    Per shard, the two boundary messages (my lowest slab's low planes to
    the left neighbor, my highest slab's high planes to the right) are
    issued FIRST; the intra-shard stencil (the -6 diagonal, both y/z
    axes, and interior-x planes) computes while they fly; the received
    planes are consumed only in the final edge concatenation.  Global x
    BCs fall out of the ring: periodic is the natural wrap, zero-gradient
    clamps shard 0 / D-1 edges to their own planes.

    Requires a 1-D device mesh whose size divides the x tile count —
    anything else raises (the silently-degenerate sharding this replaces
    is exactly what parallel/mesh._factor2's divide= guard now rejects).
    """
    from cup3d_tpu.grid.uniform import BC

    axis = mesh.axis_names[0]
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    D = mesh_shape[axis]
    if int(np.prod(mesh.devices.shape)) != D:
        raise ValueError(
            f"make_laplacian_lanes_sharded needs a 1-D mesh; got "
            f"{mesh_shape}"
        )
    nb = tuple(s // bs for s in grid.shape)
    if any(s % bs for s in grid.shape):
        raise ValueError(f"grid {grid.shape} not divisible by bs={bs}")
    if nb[0] % D:
        raise ValueError(
            f"{D} devices cannot x-slab {nb[0]} tile columns "
            f"(grid {grid.shape}, bs={bs}): choose a mesh size dividing "
            f"nx/bs — see parallel.mesh.make_mesh(divide=...)"
        )
    nbx_loc = nb[0] // D
    nbyz = nb[1] * nb[2]
    T_loc = nbx_loc * nbyz
    strides = (nbyz, nb[2], 1)
    lanes = np.arange(T_loc)
    tco = (lanes // nbyz, lanes // nb[2] % nb[1], lanes % nb[2])
    inv_h2 = 1.0 / (grid.h * grid.h)
    periodic0 = grid.bc[0] == BC.periodic

    def neighbor_local(t, ax, sign):
        # axes 1/2 are unsharded: identical mask/wrap logic to
        # krylov.make_laplacian_lanes.neighbor on the local lane set
        periodic = grid.bc[ax] == BC.periodic
        n = t.shape[ax]
        st, nba = strides[ax], nb[ax]
        if sign > 0:
            inner = jax.lax.slice_in_dim(t, 1, n, axis=ax)
            edge = jax.lax.slice_in_dim(t, n - 1, n, axis=ax)
            src = jax.lax.slice_in_dim(t, 0, 1, axis=ax)
            plane = jnp.roll(src, -st, axis=-1)
            mask = jnp.asarray(tco[ax] == nba - 1)
            wrap = jnp.roll(src, (nba - 1) * st, axis=-1)
        else:
            inner = jax.lax.slice_in_dim(t, 0, n - 1, axis=ax)
            edge = jax.lax.slice_in_dim(t, 0, 1, axis=ax)
            src = jax.lax.slice_in_dim(t, n - 1, n, axis=ax)
            plane = jnp.roll(src, st, axis=-1)
            mask = jnp.asarray(tco[ax] == 0)
            wrap = jnp.roll(src, -(nba - 1) * st, axis=-1)
        plane = jnp.where(mask, wrap if periodic else edge, plane)
        parts = (inner, plane) if sign > 0 else (plane, inner)
        return jnp.concatenate(parts, axis=ax)

    def local_apply(t: jnp.ndarray) -> jnp.ndarray:
        # -- issue the halo ring transfers first (async DMA on TPU) ----
        p0 = jax.lax.slice_in_dim(t, 0, 1, axis=0)       # own low planes
        p1 = jax.lax.slice_in_dim(t, bs - 1, bs, axis=0)  # own high
        recv_lo = ring_shift(p1[..., -nbyz:], axis, shift=+1)
        recv_hi = ring_shift(p0[..., :nbyz], axis, shift=-1)
        # -- interior compute while the halo flies ---------------------
        out = -6.0 * t
        for ax in (1, 2):
            out = out + neighbor_local(t, ax, +1) + neighbor_local(t, ax, -1)
        # -- boundary tiles: consume the received planes ---------------
        if periodic0:
            edge_lo, edge_hi = recv_lo, recv_hi
        else:
            me = jax.lax.axis_index(axis)
            edge_lo = jnp.where(me == 0, p0[..., :nbyz], recv_lo)
            edge_hi = jnp.where(me == D - 1, p1[..., -nbyz:], recv_hi)
        hi = jnp.concatenate([p0[..., nbyz:], edge_hi], axis=-1)
        lo = jnp.concatenate([edge_lo, p1[..., :-nbyz]], axis=-1)
        out = out + jnp.concatenate([t[1:], hi], axis=0)
        out = out + jnp.concatenate([lo, t[:-1]], axis=0)
        return out * inv_h2

    spec = P(None, None, None, axis)
    return shard_map(local_apply, mesh=mesh, in_specs=(spec,),
                     out_specs=spec, check_vma=False)
