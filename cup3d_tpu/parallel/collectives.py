"""Thin named wrappers over jax's communicating collectives.

This module (with ``ring.py``'s permute transport and ``compat.py``'s
shard_map shim) is the ONE sanctioned seam for device<->device
collectives — the AST linter (JX018) fails any raw ``lax.psum`` /
``lax.all_gather`` / ... call site outside ``cup3d_tpu/parallel/``, and
the IR audit (analysis/ir.py JP002/JP003) proves axis-name and
permutation invariants against the jaxprs these wrappers produce.  The
wrappers add no behavior: each is exactly the underlying primitive, so
rerouting a call site through here leaves the traced jaxpr (and every
bitwise-equivalence test downstream) unchanged.

Why a seam at all: the reference C++ routes every exchange through one
MPI communicator object, which is what makes its runtime assertions
possible.  Keeping the JAX collectives behind one module gives the
same property to static analysis — a mesh-axis rename or a topology
change edits one file, and the audit has a finite surface to reason
about.
"""

from __future__ import annotations

import jax


def all_gather_tiled(x, axis_name, *, axis=0):
    """``lax.all_gather(..., tiled=True)``: concatenate the per-shard
    blocks of ``x`` along ``axis`` across the mesh axis ``axis_name``
    (the sharded megaloop's replicated-solve assembly).  Tiled form
    only — the untiled (stacking) variant has no call site in the
    tree, so the seam stays minimal."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def pmax_axis(x, axis_name):
    """``lax.pmax``: elementwise max across the mesh axis ``axis_name``
    (the megaloop's global umax reduction; fp max is exactly
    associative, so the sharded result is bitwise equal to the solo
    one)."""
    return jax.lax.pmax(x, axis_name)


def psum_axis(x, axis_name):
    """``lax.psum``: elementwise sum across the mesh axis ``axis_name``.
    Mind the round-12 precision policy at call sites: sum-reductions
    over bf16-stored values must accumulate in f32 BEFORE the psum
    (JX011/JP004) — the collective itself reduces in the operand
    dtype."""
    return jax.lax.psum(x, axis_name)
