"""2-D (lane x space) device-mesh topology layer (round 18).

The reference scales the 512^3 fish case over 64 MPI ranks; our stack
stopped at one host's devices, with two *independent* 1-D shardings
bolted on ad hoc: ``fleet/batch.fleet_mesh()`` (a lanes-only mesh) and
``parallel/mesh.make_mesh`` (an x/y field mesh the fleet never sees).
This module subsumes both behind one factory:

- :func:`dist_init` — optional multi-process ``jax.distributed``
  bring-up.  ``CUP3D_DIST=auto`` initializes from the cluster env
  (TPU pods auto-detect), ``coordinator:port`` is the explicit form
  (with ``CUP3D_DIST_NPROCS`` / ``CUP3D_DIST_RANK``), ``0`` (default)
  is a no-op.  Single-process runs never pay anything: the call is
  idempotent and failure-tolerant (state is reported, not raised).
- :func:`make_mesh2d` — the canonical 2-D ``Mesh(("lanes", "x"))``
  over a DETERMINISTIC device order (sorted by ``(process_index,
  id)``), shaped by ``CUP3D_MESH=LxX`` or explicit arguments; the
  default ``(ndevices, 1)`` is exactly the old 1-D lanes mesh, so
  every existing fleet path is the L-by-1 special case.
- :func:`placement_map` — the lane-shard/x-shard -> device/host map,
  row-major over the mesh array; deterministic by construction
  because the device order is.  This is what replaces the
  reference's rank-to-subtree bookkeeping (SynchronizerMPI_AMR):
  placement is a pure function of the sorted device list, never of
  arrival order.
- :func:`fleet_mesh2d` / :func:`megaloop_mesh` — the two consumers'
  entry points: the fleet's batch mesh (``CUP3D_FLEET_MESH`` gate,
  lanes-major) and the solo megaloop's slab mesh (``CUP3D_MESH_X``
  gate, x-major with a unit lanes axis).

Everything here is exercised on CPU via
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the tests'
conftest) — the mesh factory does not care what backs the devices.
"""

from __future__ import annotations

import os
import warnings
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cup3d_tpu.obs import metrics as M

__all__ = [
    "dist_init",
    "dist_state",
    "device_order",
    "make_mesh2d",
    "mesh_axis_size",
    "placement_map",
    "mesh_state",
    "fleet_mesh2d",
    "megaloop_mesh",
    "shard_carry",
]

#: mesh axis names, in array order: leading = scenario lanes, trailing =
#: the x slab axis of the spatial domain decomposition
LANE_AXIS = "lanes"
X_AXIS = "x"

#: module-level distributed-init state (idempotence + health reporting)
_DIST = {"mode": "off", "initialized": False, "error": None,
         "processes": 1, "rank": 0}


def dist_state() -> dict:
    """A copy of the last :func:`dist_init` outcome (health payloads)."""
    return dict(_DIST)


def dist_init(spec: Optional[str] = None) -> dict:
    """Bring up ``jax.distributed`` per ``CUP3D_DIST`` and return the
    resulting state dict (also kept for :func:`dist_state`).

    ``spec`` (default: the ``CUP3D_DIST`` env var, default ``"0"``):

    - ``"0"`` / ``"off"`` / empty — no-op (single-process, the normal
      CPU/test path).
    - ``"auto"`` — ``jax.distributed.initialize()`` with cluster
      auto-detection, but ONLY when ``CUP3D_DIST_NPROCS`` declares
      more than one process; a single process stays a no-op so local
      runs with ``CUP3D_DIST=auto`` in the environment never hang on
      a coordinator that does not exist.
    - ``"host:port"`` — explicit coordinator; ``CUP3D_DIST_NPROCS``
      and ``CUP3D_DIST_RANK`` supply the process count and this
      process's id.

    Idempotent: a second call (or an interpreter where somebody else
    already initialized) records ``initialized`` and returns.  Failures
    are recorded in ``state["error"]`` and counted
    (``topology.dist_init_errors``), never raised — a megaloop run must
    not die because the topology layer could not find its peers."""
    if spec is None:
        spec = os.environ.get("CUP3D_DIST", "0")
    spec = spec.strip().lower()
    if spec in ("", "0", "off", "false", "no"):
        _DIST.update(mode="off", initialized=False, error=None,
                     processes=1, rank=0)
        return dist_state()
    nprocs = int(os.environ.get("CUP3D_DIST_NPROCS", "1"))
    rank = int(os.environ.get("CUP3D_DIST_RANK", "0"))
    if _DIST["initialized"]:
        return dist_state()
    if spec == "auto" and nprocs <= 1:
        # single process asked for auto: nothing to coordinate
        _DIST.update(mode="single", initialized=False, error=None,
                     processes=1, rank=0)
        return dist_state()
    try:
        if spec == "auto":
            jax.distributed.initialize()
        else:
            jax.distributed.initialize(
                coordinator_address=spec,
                num_processes=nprocs,
                process_id=rank,
            )
        _DIST.update(mode=spec, initialized=True, error=None,
                     processes=jax.process_count(),
                     rank=jax.process_index())
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            _DIST.update(mode=spec, initialized=True, error=None,
                         processes=jax.process_count(),
                         rank=jax.process_index())
        else:
            _DIST.update(mode=spec, initialized=False, error=str(e))
            M.counter("topology.dist_init_errors").inc()
    except Exception as e:  # noqa: BLE001 — report, never crash the run
        _DIST.update(mode=spec, initialized=False, error=str(e))
        M.counter("topology.dist_init_errors").inc()
    return dist_state()


def device_order(devices: Optional[Sequence] = None) -> List:
    """The canonical device order every mesh here is built from:
    sorted by ``(process_index, id)``.  ``jax.devices()`` is usually
    already in this order, but sorting makes the lane<->host placement
    a deterministic function of the device set rather than of
    enumeration order."""
    if devices is None:
        devices = jax.devices()
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def _parse_mesh_env() -> Optional[Tuple[int, int]]:
    """``CUP3D_MESH="LxX"`` -> (lanes, x); None for unset/auto."""
    v = os.environ.get("CUP3D_MESH", "").strip().lower()
    if not v or v == "auto":
        return None
    try:
        lanes_s, x_s = v.split("x", 1)
        return max(1, int(lanes_s)), max(1, int(x_s))
    # jax-lint: allow(JX009, malformed CUP3D_MESH falls back to the
    # auto shape; the resolved mesh is surfaced by mesh_state() in the
    # fleet /health payload and the CLI --mesh flag)
    except ValueError:
        return None


def make_mesh2d(lanes: Optional[int] = None, x: Optional[int] = None,
                devices: Optional[Sequence] = None) -> Mesh:
    """The 2-D ``Mesh(("lanes", "x"))`` over the canonical device order.

    Shape resolution, in priority order: explicit ``(lanes, x)``
    arguments, then ``CUP3D_MESH="LxX"``, then the auto default
    ``(ndevices, 1)`` — which is bit-for-bit the old 1-D lanes mesh
    with a unit x axis, so the factory *subsumes* ``fleet_mesh()``.
    Giving only one axis derives the other (``ndevices`` must divide
    evenly); a shape that does not multiply out to the device count
    raises — the silently-replicating degenerate meshes are exactly
    what round 12's ``_factor2(divide=)`` guard rejects on the field
    mesh, and the topology layer holds the same line."""
    devs = device_order(devices)
    nd = len(devs)
    if lanes is None and x is None:
        env = _parse_mesh_env()
        if env is not None:
            lanes, x = env
    if lanes is None and x is None:
        lanes, x = nd, 1
    elif lanes is None:
        if nd % x:
            raise ValueError(
                f"{nd} devices do not factor over x={x}: pick an x "
                f"axis dividing the device count")
        lanes = nd // x
    elif x is None:
        if nd % lanes:
            raise ValueError(
                f"{nd} devices do not factor over lanes={lanes}")
        x = nd // lanes
    if lanes * x != nd:
        raise ValueError(
            f"mesh shape ({lanes} lanes x {x}) needs {lanes * x} "
            f"devices, {nd} visible: fix CUP3D_MESH or the device set")
    arr = np.asarray(devs, dtype=object).reshape(lanes, x)
    return Mesh(arr, (LANE_AXIS, X_AXIS))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    """Size of one named mesh axis (1 for a name the mesh lacks, so
    1-D legacy meshes read as x=1)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(sizes.get(axis, 1))


def placement_map(mesh: Mesh) -> List[dict]:
    """The deterministic lane-shard/x-shard -> device/host table,
    row-major over the mesh array.  Because :func:`make_mesh2d` builds
    from the sorted device order, two processes constructing the same
    mesh agree on every entry — the property the per-slice recovery
    layer (resilience/elastic.py) relies on to name a lost shard."""
    shape = mesh.devices.shape
    out = []
    for flat, dev in enumerate(mesh.devices.flat):
        coords = np.unravel_index(flat, shape)
        out.append({
            "lane_shard": int(coords[0]),
            "x_shard": int(coords[-1]) if len(shape) > 1 else 0,
            "device_id": int(dev.id),
            "process": int(dev.process_index),
            "platform": str(dev.platform),
        })
    return out


def mesh_state(mesh: Optional[Mesh], fallbacks: int = 0) -> dict:
    """JSON-able mesh/shard state for ``/health`` and the fleet CLI."""
    if mesh is None:
        return {"active": False, "axes": [], "shape": [],
                "devices": 0, "fallbacks": int(fallbacks),
                "dist": dist_state()}
    return {
        "active": True,
        "axes": list(mesh.axis_names),
        "shape": [int(v) for v in mesh.devices.shape],
        "devices": int(mesh.devices.size),
        "fallbacks": int(fallbacks),
        "placement": placement_map(mesh),
        "dist": dist_state(),
    }


def fleet_mesh2d() -> Optional[Mesh]:
    """The fleet's batch mesh: the 2-D factory behind the legacy
    ``CUP3D_FLEET_MESH`` gate.  None when the gate is off or only one
    device is visible (pure vmap); otherwise ``(lanes, x)`` from
    ``CUP3D_MESH`` with the ``(ndevices, 1)`` auto default — the old
    1-D lanes mesh as the L-by-1 special case."""
    if os.environ.get("CUP3D_FLEET_MESH", "0").lower() not in (
            "1", "true", "on"):
        return None
    dist_init()
    if len(jax.devices()) < 2:
        return None
    return make_mesh2d()


def megaloop_mesh() -> Optional[Mesh]:
    """The solo megaloop's slab mesh: ``CUP3D_MESH_X=D`` asks for a
    ``(1, D)`` mesh (unit lane axis, D x-slabs).  None when unset,
    <2, or more slabs than devices are requested — the caller falls
    back to the unsharded megaloop, loudly
    (``topology.megaloop_mesh_fallbacks``)."""
    v = os.environ.get("CUP3D_MESH_X", "").strip()
    if not v:
        return None
    try:
        want = int(v)
    # jax-lint: allow(JX009, malformed CUP3D_MESH_X disables the slab
    # mesh; the fallback is counted below so it is observable)
    except ValueError:
        want = 0
    if want < 2:
        return None
    dist_init()
    if len(jax.devices()) < want:
        warnings.warn(
            f"CUP3D_MESH_X={want} exceeds the {len(jax.devices())} "
            f"visible devices: megaloop runs unsharded", stacklevel=2)
        M.counter("topology.megaloop_mesh_fallbacks").inc()
        return None
    return make_mesh2d(lanes=1, x=want,
                       devices=device_order()[:want])


#: megaloop carry keys laid out (nx, ny, nz[, 3]) and slab-sharded on
#: the x axis; every other key (umax/time/dt/rigid/qint/left) replicates
FIELD_KEYS = frozenset({"vel", "p", "chi", "udef"})


def shard_carry(carry: dict, mesh: Mesh, axis: str = X_AXIS) -> dict:
    """Place a megaloop carry on the mesh: field leaves slab-sharded
    over ``axis``, scalar chain replicated.  Callers use this before
    the first sharded-megaloop dispatch so donation lines up (a carry
    living on one device would be resharded, not donated)."""
    out = {}
    for k, v in carry.items():
        spec = P(axis) if k in FIELD_KEYS else P()
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out
