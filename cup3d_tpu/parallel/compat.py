"""jax version compatibility for the sharded-forest layer.

``jax.shard_map`` (with ``check_vma``) became a top-level API after the
experimental ``jax.experimental.shard_map.shard_map`` (with
``check_rep``) stabilized.  The TPU image runs the new API; CPU test
environments may carry an older jax where only the experimental path
exists.  The wrapper keeps one call surface (the new API's) for the
forest/faces kernels and maps the replication-check flag across.
"""

from __future__ import annotations

import jax


def _has_new_api() -> bool:
    try:
        return callable(jax.shard_map)
    except AttributeError:
        return False


if _has_new_api():

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
