"""Sharded face-structured halo assembly: the round-3 FaceTables fast path
(grid/faces.py) on the block-sharded forest (parallel/forest.py).

Round 3 left mesh mode on the per-ghost-cell gather tables — measured
10-80x slower than the face-slab design (VERDICT r3 weak item 3).  This
module ports the restriction-pyramid / face-slab assembly to shard_map:

- Entries (leaves + shadow nodes) are owned by shards: leaves by the
  Hilbert cut, a shadow by the owner of its first child.  Hilbert
  contiguity makes a node's children nearly always co-resident, so the
  cross-shard pyramid traffic is a handful of boundary entries.
- The pyramid runs bottom-up exactly as on one device, with one
  entry-granular ``all_to_all`` BEFORE each level group carrying the few
  remote children that group needs (full (C, bs^3) entries — the fine-side
  AverageDownAndFill messages of the reference, main.cpp:1832-1905,
  batched into a static collective).
- One final ``all_to_all`` fetches the remote face-source entries (same-
  level/shadow neighbors and coarse-window members), then the dense
  face-slab / separable-quadratic math of grid/faces.py runs shard-locally
  on the remapped tables.

Degenerate blocks (coarse windows crossing a CLOSED boundary) keep the
per-cell fallback only on the single-device path; topologies that need it
under a mesh raise — every periodic production config has none.

Address space per shard (entry granularity):
    [0, nbs)                      local leaves
    [nbs, nbs + ns_max)           local shadows (padded)
    [recv_g ... )                 received rows, one region per exchange
    zero sentinel                 (always-zero entry)
    scratch                       (padding writes land here)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cup3d_tpu.grid.faces import FaceTables, _place, _restrict8, _slab
from cup3d_tpu.parallel.compat import shard_map

__all__ = ["ShardedFaceTables", "build_sharded_face_tables"]


@dataclass
class _ExchangePlan:
    """One all_to_all at entry granularity: send_idx[t, s, :] = rows (in
    t's local address space) that shard s needs from t; recv region offset
    in the destination address space.  send_idx None = nothing crosses
    shards for this exchange; the kernel skips the collective."""

    send_idx: Optional[jnp.ndarray]  # (D, D, M) int32, sharded on axis 0
    M: int
    recv_off: int


class _EntrySpace:
    """Per-shard entry address bookkeeping for the host builder."""

    def __init__(self, D: int, nbs: int, ns_max: int):
        self.D = D
        self.nbs = nbs
        self.ns_max = ns_max
        self.recv_regions: List[int] = []  # sizes D*M per exchange
        # owner[global_entry] and local slot of each global entry
        self.owner: Dict[int, int] = {}
        self.slot: Dict[int, int] = {}
        # per-shard, per-exchange: global entry -> recv row index
        self.recv_maps: List[List[Dict[int, int]]] = []

    @property
    def n_recv(self) -> int:
        return sum(self.recv_regions)

    def local_size(self) -> int:
        # + zero sentinel + scratch
        return self.nbs + self.ns_max + self.n_recv + 2

    def zero_row(self) -> int:
        return self.nbs + self.ns_max + self.n_recv

    def scratch_row(self) -> int:
        return self.zero_row() + 1

    def resolve(self, e: int, shard: int, sentinel: int) -> int:
        """Global entry -> shard-local row (owned or received)."""
        if e == sentinel:
            return self.zero_row()
        if self.owner[e] == shard:
            return self.slot[e]
        off = self.nbs + self.ns_max
        for x, (size, maps) in enumerate(
            zip(self.recv_regions, self.recv_maps)
        ):
            row = maps[shard].get(e)
            if row is not None:
                return off + row
            off += size
        raise KeyError(f"entry {e} not routed to shard {shard}")


def _plan_exchange(
    space: _EntrySpace, needed: List[set], D: int
) -> Tuple[Optional[np.ndarray], int]:
    """needed[s] = set of global entries shard s must receive.  Returns
    (send_idx (D, D, M), M) and registers the recv region + maps.  When NO
    shard needs anything remote, returns (None, 0) and registers an empty
    region — the kernel skips the all_to_all entirely (Hilbert contiguity
    makes most pyramid groups fully shard-local, and one needless
    collective per group per assembly lands inside every Krylov
    iteration; code-review r4)."""
    groups = []
    for s in range(D):
        by_src: List[List[int]] = [[] for _ in range(D)]
        for e in sorted(needed[s]):
            t = space.owner[e]
            if t != s:
                by_src[t].append(e)
        groups.append(by_src)
    if not any(g for gs in groups for g in gs):
        space.recv_regions.append(0)
        space.recv_maps.append([dict() for _ in range(D)])
        return None, 0
    M = max([len(g) for gs in groups for g in gs] + [1])
    send_idx = np.zeros((D, D, M), np.int64)
    recv_maps: List[Dict[int, int]] = [dict() for _ in range(D)]
    for s in range(D):
        for t in range(D):
            g = groups[s][t]
            for j, e in enumerate(g):
                send_idx[t, s, j] = space.slot[e]
                # recv layout after all_to_all(split 0, concat 0):
                # rows arrive ordered by source shard t, then j
                recv_maps[s][e] = t * M + j
    space.recv_regions.append(D * M)
    space.recv_maps.append(recv_maps)
    return send_idx, M


def _exchange_entries(ext, send_idx, axis, region_off, M):
    """Send full entries (rows of ext) and write them into the recv
    region starting at region_off.  ext: (n_local, C, bs, bs, bs).

    CUP3D_RING_HALO=1 swaps the blocking all_to_all for the ring-permute
    transport (parallel/ring.py): same chunk routing, but on TPU each
    shard-to-shard chunk is an async remote copy streaming over ICI."""
    from cup3d_tpu.parallel import ring

    send = ext[send_idx]  # (D, M, C, bs, bs, bs)
    if ring.use_ring_halo():
        recv = ring.ring_all_to_all(send, axis)
    else:
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0)
    recv = recv.reshape((-1,) + ext.shape[1:])
    return jax.lax.dynamic_update_slice(
        ext, recv.astype(ext.dtype), (region_off, 0, 0, 0, 0)
    )


@dataclass
class ShardedFaceTables:
    """Duck-typed FaceTables running under shard_map (see module doc)."""

    width: int
    forest: object  # ShardedForest
    tab: FaceTables  # single-device tables of the SAME width (host ref)
    # static layout
    nbs: int
    ns_max: int
    n_local: int
    zero_row: int
    scratch_row: int
    # pyramid: per group (dst_rows (D, nsg_max), child (D, nsg_max, 8),
    # exchange plan)
    groups: Tuple[Tuple[jnp.ndarray, jnp.ndarray, _ExchangePlan], ...]
    final_plan: _ExchangePlan
    src: jnp.ndarray  # (D, 6, nbs) int32 remapped
    bmask: jnp.ndarray  # (D, 6, nbs) bool
    bsign: Tuple[Tuple[float, float, float], ...]
    cf_rows: Tuple[jnp.ndarray, ...]  # 6 x (D, ncf_max) local block rows
    cf_src: Tuple[jnp.ndarray, ...]  # 6 x (D, ncf_max, 8) remapped entries
    cf_toff: Tuple[jnp.ndarray, ...]  # 6 x (D, ncf_max, 2)
    interp_t: jnp.ndarray
    interp_n_lo: jnp.ndarray
    interp_n_hi: jnp.ndarray

    # -- protocol ----------------------------------------------------------

    def assemble_scalar(self, field: jnp.ndarray, bs: int) -> jnp.ndarray:
        return self._assemble(field[..., None], None)[..., 0]

    def assemble_vector(self, field: jnp.ndarray, bs: int) -> jnp.ndarray:
        return self._assemble(field, (0, 1, 2))

    def assemble_component(self, field, bs: int, comp: int) -> jnp.ndarray:
        return self._assemble(field[..., None], (comp,))[..., 0]

    def _assemble(self, fields: jnp.ndarray,
                  sign_comps: Optional[Tuple[int, ...]]) -> jnp.ndarray:
        f = self.forest
        t = self.tab
        bs, w = t.bs, self.width
        L = bs + 2 * w
        C = fields.shape[-1]
        nbs = self.nbs
        axis = f.axis
        self_t = self

        def kernel(fields, src, bmask, grp_tabs, final_send, cf_tabs):
            fm = jnp.moveaxis(fields, -1, 1)  # (nbs, C, bs,bs,bs)
            ext = jnp.zeros(
                (self_t.n_local, C, bs, bs, bs), fields.dtype
            )
            ext = jax.lax.dynamic_update_slice(ext, fm, (0, 0, 0, 0, 0))
            # -- pyramid (deepest group first) ------------------------------
            for (dst, child, plan), (dst_a, child_a, send_a) in zip(
                self_t.groups, grp_tabs
            ):
                if send_a is not None:  # else: fully shard-local group
                    ext = _exchange_entries(
                        ext, send_a[0], axis, plan.recv_off, plan.M
                    )
                ch = jnp.take(ext, child_a[0], axis=0)  # (nsg,8,C,bs^3)
                sh = _restrict8(ch, bs)
                ext = ext.at[dst_a[0]].set(sh.astype(ext.dtype))
            # -- final exchange: face sources + coarse windows --------------
            if final_send is not None:
                ext = _exchange_entries(
                    ext, final_send[0], axis, self_t.final_plan.recv_off,
                    self_t.final_plan.M,
                )
            # -- dense face assembly (grid/faces.py math) -------------------
            lab = jnp.zeros((nbs, C) + (L,) * 3, fields.dtype)
            lab = lab.at[:, :, w:w + bs, w:w + bs, w:w + bs].set(fm)
            for a in range(3):
                for hi in (0, 1):
                    fc = 2 * a + hi
                    sl = (
                        _slab(ext, a, 0, w) if hi
                        else _slab(ext, a, bs - w, w)
                    )
                    slab = jnp.take(sl, src[0, fc], axis=0)
                    own = (
                        _slab(ext[:nbs], a, bs - 1, 1) if hi
                        else _slab(ext[:nbs], a, 0, 1)
                    )
                    own = jnp.broadcast_to(own, slab.shape)
                    if sign_comps is not None:
                        sgn = np.array(
                            [t.bsign[fc][c] for c in sign_comps],
                            np.float32,
                        ).reshape(1, C, 1, 1, 1)
                        own = own * sgn
                    bm = bmask[0, fc][:, None, None, None, None]
                    slab = jnp.where(bm, own.astype(slab.dtype), slab)
                    rows_a, src8_a, toff_a = cf_tabs[fc]
                    if rows_a.shape[1]:
                        halo = self_t._coarse_halo_shard(
                            ext, fc, src8_a[0], toff_a[0], C
                        )
                        # scratch row absorbs padded cf rows
                        slab = jnp.concatenate(
                            [slab, jnp.zeros_like(slab[:1])]
                        )
                        slab = slab.at[rows_a[0]].set(
                            halo.astype(slab.dtype)
                        )[:nbs]
                    lab = _place(lab, slab, a, hi, w, bs)
            return jnp.moveaxis(lab, 1, -1)

        pb = P(f.axis)
        grp_tabs = tuple(
            (dst, child, plan.send_idx) for dst, child, plan in self.groups
        )
        cf_tabs = tuple(
            (self.cf_rows[fc], self.cf_src[fc], self.cf_toff[fc])
            for fc in range(6)
        )
        return shard_map(
            kernel,
            mesh=f.mesh,
            in_specs=(pb, pb, pb, jax.tree_util.tree_map(
                lambda _: pb, grp_tabs), pb,
                jax.tree_util.tree_map(lambda _: pb, cf_tabs)),
            out_specs=pb,
            check_vma=False,
        )(fields, self.src, self.bmask, grp_tabs,
          self.final_plan.send_idx, cf_tabs)

    def _coarse_halo_shard(self, ext, fc, src8, toff, C):
        """grid/faces.py _coarse_halo with explicit (remapped) tables."""
        t = self.tab
        a, hi = fc // 2, fc % 2
        bs, w = t.bs, self.width
        cw = t.interp_n_lo.shape[1] - 1
        S = t.interp_t.shape[1]
        if hi:
            pp = _slab(ext, a, bs - 1, 1)
            npl = _slab(ext, a, 0, cw)
        else:
            pp = _slab(ext, a, 0, 1)
            npl = _slab(ext, a, bs - cw, cw)
        Pp = jnp.take(pp, src8[:, 0:4], axis=0)
        N = jnp.take(npl, src8[:, 4:8], axis=0)

        def arrange(x):
            n, _, _, d = x.shape[:4]
            y = x.reshape(n, 2, 2, C, d, bs, bs)
            y = y.transpose(0, 3, 4, 1, 5, 2, 6)
            return y.reshape(n, C, d, 2 * bs, 2 * bs)

        P16, N16 = arrange(Pp), arrange(N)
        slab16 = (
            jnp.concatenate([P16, N16], axis=2)
            if hi else jnp.concatenate([N16, P16], axis=2)
        )

        def tslice(s, off):
            return jax.lax.dynamic_slice(
                s, (0, 0, off[0], off[1]), (C, cw + 1, S, S)
            )

        win = jax.vmap(tslice)(slab16, toff)
        Tn = t.interp_n_hi if hi else t.interp_n_lo
        Tt = t.interp_t
        out = jnp.tensordot(win, Tn.astype(win.dtype), axes=[[2], [1]])
        out = jnp.tensordot(out, Tt.astype(win.dtype), axes=[[2], [1]])
        out = jnp.tensordot(out, Tt.astype(win.dtype), axes=[[2], [1]])
        return out


def build_sharded_face_tables(forest, width: int) -> ShardedFaceTables:
    """Host builder: shard the global FaceTables of ``forest.grid``."""
    g = forest.grid
    t: FaceTables = g.face_tables(width)
    if t.fb_rows is not None:
        raise ValueError(
            "sharded face tables: topology has degenerate (closed-boundary "
            "deep-coarsening) blocks — use the per-cell lab tables"
        )
    D, nbs = forest.D, forest.nbs
    nb = g.nb
    sentinel = t.n_entries

    # -- ownership ---------------------------------------------------------
    # leaves: Hilbert cut.  shadows: owner of first child (bottom-up).
    child_groups = [np.asarray(c) for c in t.child_idx]
    starts = list(t.shadow_starts)
    owner = {}
    for e in range(nb):
        owner[e] = min(e // nbs, D - 1)
    for ci, start in zip(child_groups, starts):  # deepest first
        for r in range(ci.shape[0]):
            owner[start + r] = owner[int(ci[r, 0])]

    # per-shard shadow slots (padded to ns_max)
    shadows_of: List[List[int]] = [[] for _ in range(D)]
    for ci, start in zip(child_groups, starts):
        for r in range(ci.shape[0]):
            e = start + r
            shadows_of[owner[e]].append(e)
    ns_max = max([len(sh) for sh in shadows_of] + [1])
    space = _EntrySpace(D, nbs, ns_max)
    space.owner = owner
    for e in range(nb):
        space.slot[e] = e - owner[e] * nbs
    for s in range(D):
        for j, e in enumerate(shadows_of[s]):
            space.slot[e] = nbs + j

    # -- pyramid exchange plans (deepest group first) ----------------------
    plans: List[Tuple[np.ndarray, np.ndarray, np.ndarray, int]] = []
    for ci, start in zip(child_groups, starts):
        nsg = ci.shape[0]
        # which remote children does each shard need for THIS group
        needed = [set() for _ in range(D)]
        rows_of: List[List[int]] = [[] for _ in range(D)]
        for r in range(nsg):
            s = owner[start + r]
            rows_of[s].append(r)
            for c in ci[r]:
                c = int(c)
                if owner[c] != s:
                    needed[s].add(c)
        send_idx, M = _plan_exchange(space, needed, D)
        nsg_max = max([len(r) for r in rows_of] + [1])
        plans.append((ci, start, send_idx, M, rows_of, nsg_max))

    # -- final exchange: face srcs + coarse windows ------------------------
    src = np.asarray(t.src, np.int64)  # (6, nb)
    needed_final = [set() for _ in range(D)]
    for fcb in range(6):
        for b in range(nb):
            s = owner[b]
            e = int(src[fcb, b])
            if e != sentinel and owner[e] != s:
                needed_final[s].add(e)
    cf_lists = []
    for fc in range(6):
        rows = np.asarray(t.cf_rows[fc], np.int64)
        src8 = np.asarray(t.cf_src[fc], np.int64)
        toff = np.asarray(t.cf_toff[fc], np.int64)
        cf_lists.append((rows, src8, toff))
        for i, b in enumerate(rows):
            s = owner[int(b)]
            for e in src8[i]:
                e = int(e)
                if owner[e] != s:
                    needed_final[s].add(e)
    final_send, final_M = _plan_exchange(space, needed_final, D)

    # region offsets now that ALL exchanges are planned
    region_offs = []
    off = nbs + ns_max
    for size in space.recv_regions:
        region_offs.append(off)
        off += size
    n_local = space.local_size()

    # -- remap pyramid tables ---------------------------------------------
    groups = []
    for x, (ci, start, send_idx, M, rows_of, nsg_max) in enumerate(plans):
        dst = np.full((D, nsg_max), space.scratch_row(), np.int64)
        child = np.full((D, nsg_max, 8), space.zero_row(), np.int64)
        for s in range(D):
            for j, r in enumerate(rows_of[s]):
                dst[s, j] = space.slot[start + r]
                for c8 in range(8):
                    child[s, j, c8] = space.resolve(
                        int(ci[r, c8]), s, sentinel
                    )
        groups.append((
            jnp.asarray(dst, jnp.int32),
            jnp.asarray(child, jnp.int32),
            _ExchangePlan(
                send_idx=(None if send_idx is None
                          else jnp.asarray(send_idx, jnp.int32)),
                M=M,
                recv_off=region_offs[x],
            ),
        ))

    # -- remap face tables -------------------------------------------------
    src_sh = np.full((D, 6, nbs), space.zero_row(), np.int64)
    bmask_sh = np.zeros((D, 6, nbs), bool)
    bmask_g = np.asarray(t.bmask)
    for b in range(nb):
        s = owner[b]
        ls = space.slot[b]
        for fc in range(6):
            bmask_sh[s, fc, ls] = bmask_g[fc, b]
            e = int(src[fc, b])
            src_sh[s, fc, ls] = space.resolve(e, s, sentinel)

    cf_rows_sh, cf_src_sh, cf_toff_sh = [], [], []
    for fc in range(6):
        rows, src8, toff = cf_lists[fc]
        per = [[] for _ in range(D)]
        for i, b in enumerate(rows):
            per[owner[int(b)]].append(i)
        ncf_max = max([len(p) for p in per] + [0])
        R = np.full((D, ncf_max), nbs, np.int64)  # nbs = scratch slab row
        S8 = np.full((D, ncf_max, 8), space.zero_row(), np.int64)
        TO = np.zeros((D, ncf_max, 2), np.int64)
        for s in range(D):
            for j, i in enumerate(per[s]):
                R[s, j] = space.slot[int(rows[i])]
                TO[s, j] = toff[i]
                for c8 in range(8):
                    S8[s, j, c8] = space.resolve(int(src8[i, c8]), s,
                                                 sentinel)
        cf_rows_sh.append(jnp.asarray(R, jnp.int32))
        cf_src_sh.append(jnp.asarray(S8, jnp.int32))
        cf_toff_sh.append(jnp.asarray(TO, jnp.int32))

    pad = forest.pad_aux
    return ShardedFaceTables(
        width=width,
        forest=forest,
        tab=t,
        nbs=nbs,
        ns_max=ns_max,
        n_local=n_local,
        zero_row=space.zero_row(),
        scratch_row=space.scratch_row(),
        groups=tuple(
            (pad(dst), pad(child),
             _ExchangePlan(
                 None if plan.send_idx is None else pad(plan.send_idx),
                 plan.M, plan.recv_off))
            for dst, child, plan in groups
        ),
        final_plan=_ExchangePlan(
            (None if final_send is None
             else pad(jnp.asarray(final_send, jnp.int32))),
            final_M,
            region_offs[-1],
        ),
        src=pad(jnp.asarray(src_sh, jnp.int32)),
        bmask=pad(jnp.asarray(bmask_sh)),
        bsign=t.bsign,
        cf_rows=tuple(pad(x) for x in cf_rows_sh),
        cf_src=tuple(pad(x) for x in cf_src_sh),
        cf_toff=tuple(pad(x) for x in cf_toff_sh),
        interp_t=t.interp_t,
        interp_n_lo=t.interp_n_lo,
        interp_n_hi=t.interp_n_hi,
    )
