"""K-step scan megaloop: the whole obstacle pipeline inside one dispatch.

BENCH_r05 showed the uniform step loop host-bound: ~28-43 ms/step of fish
midline re-evaluation + SDF re-staging (CreateObstacles) and a regressed
pack read (SyncQoI), against ~0.5 ms of device BiCGSTAB at 128^3.  This
module wraps K full timesteps — dt policy, midline kinematics, SDF/chi
rasterization, advection-diffusion, the 6-DOF rigid update, penalization,
projection, and the surface force probe — in a single jitted ``lax.scan``,
so the host dispatches once per K steps and reads one (K, ROW) QoI block
through the existing stream/qoi.py path.

Step semantics reproduce the host pipelined chain exactly:

- dt comes from the CARRIED umax (one step stale — the same staleness as
  the host chain's freshly-consumed pack, so no 1.5x staleness margin),
  capped by the combined advection-diffusion bound and the 1.03x growth
  limiter (sim/dtpolicy.py).
- The midline is evaluated by the frozen-gait device port
  (models/fish/device_midline.py) at the carried time; rasterization snaps
  the same static window as StefanFish.rasterize from the PRE-update rigid
  state (the host rasterizes before UpdateObstacles runs).
- umax is measured with the PRE-update uinf, matching the host emit point
  (Simulation._emit_step_pack reads s._uinf_dev set from the previous
  rigid state).
- The QoI row layout (FISH_ROW) carries everything _consume_pack needs to
  refresh the host mirrors per step k: the rigid pack, penalization
  force/torque (already negated, models.base.update_penalization_forces
  convention), the force probe pack, solver stats, the internal
  quaternion, and the (umax, dt, time) chain for failure detection.

The carry is donated: callers must rebind every field from the returned
carry and never touch the passed-in arrays again (JX002 discipline).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.models.base import (
    RIGID_STATE,
    momentum_integrals_core,
    pack_forces,
    pack_moments,
    quat_to_rot_dev,
    rigid_update_device,
)
from cup3d_tpu.ops.advection import GHOSTS, rk3_step
from cup3d_tpu.ops.chi import towers_chi
from cup3d_tpu.ops.diagnostics import max_velocity
from cup3d_tpu.ops.penalization import (
    penalize,
    per_obstacle_penalization_force,
)
from cup3d_tpu.ops.projection import project

# QoI row layouts.  Fish: rigid pack 0:29 | penal force/torque 29:35 |
# force probe pack 35:52 | [residual, iterations] 52:54 | internal
# quaternion 54:58 | umax 58 | dt 59 | time 60.
FISH_ROW = 61
# TGV (obstacle-free): [residual, iterations] 0:2 | umax 2 | dt 3 | time 4.
TGV_ROW = 5

DEFAULT_SCAN_K = 8


def resolve_scan_k(cfg) -> int:
    """Effective K: the CUP3D_SCAN_K env knob overrides cfg.scan_k.
    K <= 1 disables the megaloop (per-step host loop, the seed behavior)."""
    env = os.environ.get("CUP3D_SCAN_K")
    if env is not None:
        try:
            return max(0, int(env))
        # jax-lint: allow(JX009, malformed env knob falls back to the
        # config value; the resolved K is printed by the verbose driver
        # banner, so the fallback is observable)
        except ValueError:
            pass
    return max(0, int(cfg.scan_k))


def _solver_stats(dtype):
    """Placeholder stats for non-iterative solvers: the host packs nothing
    there; the row keeps a fixed layout with iterations = -1 (ignored by
    the consumer)."""
    return jnp.asarray([0.0, -1.0], dtype)


def init_tgv_carry(s):
    """Obstacle-free carry from the current host/device state.  The umax
    seed is measured on device (no host read); dt/time seed from the host
    scalars so the first in-scan dt chains off the last host dt."""
    dtype = s.dtype
    uinf = s.uinf_device()
    vel = s.state["vel"]
    return {
        "vel": vel,
        "p": s.state["p"],
        "umax": max_velocity(vel, uinf),
        "time": jnp.asarray(s.time, dtype),
        "dt": jnp.asarray(s.dt, dtype),
    }


def init_fish_carry(s, ob):
    """Single-fish carry: field state + 6-DOF rigid vector + internal
    quaternion, all device-resident.  chi/udef ride the carry so dumps and
    resilience restores see a consistent set (the scan body overwrites
    them every step).  The umax seed is floored by the host's fresh
    max_body_speed bound — the cold-start case where the fields are still
    at rest but the gait is about to accelerate them (see
    Obstacle.max_body_speed)."""
    dtype = s.dtype
    vel, udef = s.state["vel"], s.state["udef"]
    rigid = jnp.asarray(ob.rigid_state_vec(), dtype)
    uinf = -rigid[0:3] if ob.bFixFrameOfRef else s.uinf_device()
    umax = jnp.maximum(max_velocity(vel, uinf), jnp.max(jnp.abs(udef)))
    umax = jnp.maximum(umax, jnp.asarray(ob.max_body_speed(s.uinf), dtype))
    return {
        "vel": vel,
        "p": s.state["p"],
        "chi": s.state["chi"],
        "udef": udef,
        "rigid": rigid,
        "qint": jnp.asarray(ob.myFish.quaternion_internal, dtype),
        "umax": umax,
        "time": jnp.asarray(s.time, dtype),
        "dt": jnp.asarray(s.dt, dtype),
    }


def make_tgv_step(s):
    """The obstacle-free scan body as a pure function
    ``one_step(carry, cfl_eff) -> (carry', row (TGV_ROW,))``.  All grid /
    solver / uinf statics are frozen in the closure; the function has no
    leading batch axis, so fleet/batch.py can ``vmap`` it over a scenario
    axis unchanged (the lane independence the fleet isolation contract
    relies on: no cross-lane reduction anywhere in the body)."""
    grid, nu, dtype = s.grid, s.nu, s.dtype
    h = float(grid.h)
    solver = s.poisson_solver
    with_stats = bool(getattr(solver, "supports_stats", False))
    uinf = s.uinf_device()

    def one_step(carry, cfl_eff):
        vel, p = carry["vel"], carry["p"]
        umax, time, dtprev = carry["umax"], carry["time"], carry["dt"]
        cap = (h * h / 6.0) / (nu + (h / 6.0) * umax)
        dt = jnp.minimum(cfl_eff * h / (umax + 1e-8), cap)
        dt = jnp.where(dtprev > 0, jnp.minimum(dt, 1.03 * dtprev), dt)
        vel = rk3_step(grid, vel, dt, nu, uinf)
        if with_stats:
            vel, p, stats = project(grid, vel, dt, solver, p_init=p,
                                    with_stats=True)
            stats = jnp.asarray(stats, dtype)
        else:
            vel, p = project(grid, vel, dt, solver, p_init=p)
            stats = _solver_stats(dtype)
        umax_new = max_velocity(vel, uinf)
        time_new = time + dt
        out = {"vel": vel, "p": p, "umax": umax_new, "time": time_new,
               "dt": dt}
        row = jnp.concatenate([stats, umax_new[None], dt[None],
                               time_new[None]])
        return out, row

    return one_step


def build_tgv_megaloop(s):
    """jitted (carry, cfl_eff (K,)) -> (carry', rows (K, TGV_ROW)) for the
    obstacle-free uniform pipeline.  The carry is DONATED."""
    one_step = make_tgv_step(s)

    def megaloop(carry, cfl_eff):
        return jax.lax.scan(one_step, carry, cfl_eff)

    return jax.jit(megaloop, donate_argnums=(0,))


def make_fish_step(s, ob):
    """The single-StefanFish scan body as a pure function
    ``one_step(gait, carry, cfl_eff) -> (carry', row (FISH_ROW,))``.

    Everything geometric is frozen static at build time: the rasterization
    window, the probe window + slot budget (obstacle_probe_budget
    hysteresis is deliberately frozen for the megaloop's lifetime so
    steady swimming never retraces), and the forced/blocked masks.  The
    frozen-gait parameters are an ARGUMENT pytree rather than a closure,
    so the solo megaloop can bake one gait in as trace-time constants
    while fleet/batch.py stacks per-lane gaits and vmaps over them."""
    from cup3d_tpu.models.fish.rasterize import rasterize_midline
    from cup3d_tpu.ops.surface import (
        _uniform_window_probe,
        obstacle_probe_budget,
        window_size_cells,
    )

    grid, nu, dtype = s.grid, s.nu, s.dtype
    cfg = s.cfg
    h = float(grid.h)
    solver = s.poisson_solver
    with_stats = bool(getattr(solver, "supports_stats", False))

    n = np.asarray(grid.shape)
    grid_shape = tuple(int(v) for v in n)
    window_shape = tuple(ob._window_shape)
    half_win = jnp.asarray(0.5 * np.asarray(window_shape) * h, dtype)
    lim_win = jnp.asarray(n - np.asarray(window_shape), jnp.int32)
    wp = int(min(window_size_cells(ob.length, h), n.min()))
    half_probe = jnp.asarray(0.5 * wp * h, dtype)
    lim_probe = jnp.asarray(n - wp, jnp.int32)
    budget = obstacle_probe_budget(ob, h)
    forced_mask = ob.forced_mask_dev()
    block_mask = ob.block_mask_dev()
    fix_frame = bool(ob.bFixFrameOfRef)
    uinf_const = None if fix_frame else s.uinf_device()
    xc = s.xc
    h3 = h ** 3
    hd = jnp.asarray(h, dtype)
    zero3 = jnp.zeros(3, dtype)
    dlm = float(cfg.DLM)
    lam_static = jnp.asarray(cfg.lambda_penalization, dtype)

    from cup3d_tpu.models.fish.device_midline import midline_state_device

    def one_step(gait, carry, cfl_eff):
        vel, p = carry["vel"], carry["p"]
        rigid, qint = carry["rigid"], carry["qint"]
        umax, time, dtprev = carry["umax"], carry["time"], carry["dt"]
        # dt from the carried umax (one step stale, like the host chain)
        cap = (h * h / 6.0) / (nu + (h / 6.0) * umax)
        dt = jnp.minimum(cfl_eff * h / (umax + 1e-8), cap)
        dt = jnp.where(dtprev > 0, jnp.minimum(dt, 1.03 * dtprev), dt)
        uinf = -rigid[0:3] if fix_frame else uinf_const
        # shape kinematics + rasterization from the PRE-update rigid state
        # (host order: CreateObstacles runs before UpdateObstacles)
        mid, qint_new = midline_state_device(gait, time, dt, qint)
        pos = rigid[6:9]
        rot = quat_to_rot_dev(rigid[15:19])
        idx0 = jnp.clip(jnp.floor((pos - half_win) / hd).astype(jnp.int32),
                        0, lim_win)
        origin = idx0.astype(dtype) * hd
        sdf_w, udef_w = rasterize_midline(origin, hd, window_shape, mid,
                                          pos, rot)
        sdf = jnp.full(grid_shape, -1.0, dtype)
        sdf = jax.lax.dynamic_update_slice(
            sdf, sdf_w, (idx0[0], idx0[1], idx0[2]))
        udef = jnp.zeros(grid_shape + (3,), dtype)
        udef = jax.lax.dynamic_update_slice(
            udef, udef_w, (idx0[0], idx0[1], idx0[2], 0))
        chi = towers_chi(grid.pad_scalar(sdf, 1), grid.h)
        udef = udef * (chi > 0)[..., None]
        # advection-diffusion
        vel = rk3_step(grid, vel, dt, nu, uinf)
        # chi-weighted fluid momenta -> 6-DOF rigid update, on device
        mom = pack_moments(
            momentum_integrals_core(xc, h3, chi, vel, rigid[12:15]))
        out = rigid_update_device(mom, rigid, forced_mask, block_mask,
                                  uinf, dt)
        rigid_new = out[:RIGID_STATE]
        ut, om, cm = out[0:3], out[3:6], out[12:15]
        # penalization toward the updated body velocity field
        ubody = ut + jnp.cross(jnp.broadcast_to(om, xc.shape), xc - cm) \
            + udef
        lam = dlm / dt if dlm > 0 else lam_static
        vel_old = vel
        vel = penalize(vel, chi, ubody, lam, dt)
        PF = -per_obstacle_penalization_force(
            vel, vel_old, (chi,), dt, h3, xc, cm[None])[0]
        # projection, warm-started from the carried pressure
        if with_stats:
            vel, p, stats = project(grid, vel, dt, solver, chi, udef,
                                    p_init=p, with_stats=True)
            stats = jnp.asarray(stats, dtype)
        else:
            vel, p = project(grid, vel, dt, solver, chi, udef, p_init=p)
            stats = _solver_stats(dtype)
        # surface-probe force QoI around the updated position
        idx0f = jnp.clip(
            jnp.floor((out[6:9] - half_probe) / hd).astype(jnp.int32),
            0, lim_probe)
        F = pack_forces(_uniform_window_probe(
            vel, p, chi, sdf, udef, idx0f, hd, zero3, nu, cm, ut, om,
            wcells=wp, max_points=budget))
        # umax with the PRE-update uinf: the host emit point reads the
        # previous step's frame velocity (Simulation._emit_step_pack)
        umax_new = jnp.maximum(max_velocity(vel, uinf),
                               jnp.max(jnp.abs(udef)))
        time_new = time + dt
        carry_new = {
            "vel": vel, "p": p, "chi": chi, "udef": udef,
            "rigid": rigid_new, "qint": qint_new,
            "umax": umax_new, "time": time_new, "dt": dt,
        }
        row = jnp.concatenate([out, PF, F, stats, qint_new,
                               umax_new[None], dt[None], time_new[None]])
        return carry_new, row

    return one_step


def build_fish_megaloop(s, ob):
    """jitted (carry, cfl_eff (K,)) -> (carry', rows (K, FISH_ROW)) for the
    single-StefanFish uniform pipeline.  Returns None when the gait is not
    freezable (models/fish/device_midline.freeze_gait).  The carry is
    DONATED.  The frozen gait is bound here as trace-time constants (the
    same leaves the closure used to capture), so the compiled artifact is
    unchanged by the make_fish_step refactor."""
    from cup3d_tpu.models.fish.device_midline import freeze_gait

    gait = freeze_gait(ob, s.time, s.dtype)
    if gait is None:
        return None
    one_step = make_fish_step(s, ob)

    def megaloop(carry, cfl_eff):
        return jax.lax.scan(
            lambda c, x: one_step(gait, c, x), carry, cfl_eff)

    return jax.jit(megaloop, donate_argnums=(0,))


# -- x-slab sharded megaloop (round 18) ---------------------------------
#
# The whole K-step scan body runs under shard_map on the topology
# layer's "x" axis: advection-diffusion consumes ring-halo-padded slabs
# (parallel/ring.pad_slab_vector — the two boundary messages per
# component are issued BEFORE the interior stencil, async remote copies
# on TPU), while the global phases (the spectral Poisson solve, the
# body integrals, the force probe) compute REPLICATED on
# ``lax.all_gather(..., tiled=True)`` results.  Replication instead of
# host staging keeps the collective on-device (the JX016 line) and buys
# bitwise equivalence with the solo megaloop for free: every sharded
# element sees the identical arithmetic, max-reductions cross shards
# through ``pmax`` (fp max is exactly associative), and sum-reductions
# run on full gathered arrays in the solo reduction order.


def _slab_specs(keys, axis):
    """shard_map carry specs: field leaves (vel/p/chi/udef) slab-shard
    dim 0 over ``axis``; the scalar chain replicates."""
    from jax.sharding import PartitionSpec as P

    from cup3d_tpu.parallel.topology import FIELD_KEYS

    return {k: (P(axis) if k in FIELD_KEYS else P()) for k in keys}


def make_tgv_step_sharded(s, axis="x"):
    """The obstacle-free scan body on one x-slab, to run INSIDE
    shard_map over mesh axis ``axis``.  Same carry keys and row layout
    as make_tgv_step; vel/p arrive as the local (nx/D, ny, nz[, 3])
    slabs.  RK3 and the divergence read ring-padded slabs; the Poisson
    solve runs replicated on the gathered rhs and each shard slices its
    own pressure slab (and its sx+2 gradient window) back out."""
    from cup3d_tpu.ops import stencils as st
    from cup3d_tpu.parallel import collectives as coll
    from cup3d_tpu.parallel import ring as _ring

    grid, nu, dtype = s.grid, s.nu, s.dtype
    h = float(grid.h)
    solver = s.poisson_solver
    uinf = s.uinf_device()

    def pad_vec(u, w):
        return _ring.pad_slab_vector(grid, u, w, axis)

    def one_step(carry, cfl_eff):
        vel, p = carry["vel"], carry["p"]
        umax, time, dtprev = carry["umax"], carry["time"], carry["dt"]
        cap = (h * h / 6.0) / (nu + (h / 6.0) * umax)
        dt = jnp.minimum(cfl_eff * h / (umax + 1e-8), cap)
        dt = jnp.where(dtprev > 0, jnp.minimum(dt, 1.03 * dtprev), dt)
        vel = rk3_step(grid, vel, dt, nu, uinf, pad=pad_vec)
        # projection: slab divergence, replicated global solve
        # (ops/projection.pressure_rhs semantics on the slab)
        rhs_l = st.divergence(pad_vec(vel, 1), 1, grid.h) / dt
        rhs = coll.all_gather_tiled(rhs_l, axis)
        p_full = solver(rhs, coll.all_gather_tiled(p, axis))
        sx = vel.shape[0]
        me = jax.lax.axis_index(axis)
        p_new = jax.lax.dynamic_slice_in_dim(p_full, me * sx, sx, axis=0)
        win = jax.lax.dynamic_slice_in_dim(
            grid.pad_scalar(p_full, 1), me * sx, sx + 2, axis=0)
        vel = vel - dt * st.grad(win, 1, grid.h)
        umax_new = coll.pmax_axis(max_velocity(vel, uinf), axis)
        time_new = time + dt
        out = {"vel": vel, "p": p_new, "umax": umax_new,
               "time": time_new, "dt": dt}
        row = jnp.concatenate([_solver_stats(dtype), umax_new[None],
                               dt[None], time_new[None]])
        return out, row

    return one_step


def build_tgv_megaloop_sharded(s, mesh, axis="x"):
    """jitted (carry, cfl_eff (K,)) -> (carry', rows (K, TGV_ROW)) with
    the scan body shard_mapped over the mesh's ``axis`` slabs.  Global
    shapes in and out match the solo megaloop exactly.  Returns None
    when unbuildable: an iterative (stats-advertising) solver keeps the
    solo path, and a mesh axis that does not divide nx cannot slab."""
    import warnings

    from jax.sharding import PartitionSpec as P

    from cup3d_tpu.obs import metrics as M
    from cup3d_tpu.parallel import topology as topo
    from cup3d_tpu.parallel.compat import shard_map

    if getattr(s.poisson_solver, "supports_stats", False):
        return None
    D = topo.mesh_axis_size(mesh, axis)
    if s.grid.shape[0] % D or s.grid.shape[0] // D < GHOSTS:
        warnings.warn(
            f"{D} x-shards cannot slab nx={s.grid.shape[0]} (need even "
            f"slabs of >= {GHOSTS} planes for the one-hop ring halo): "
            f"megaloop runs unsharded", stacklevel=2)
        M.counter("topology.megaloop_mesh_fallbacks").inc()
        return None
    one_step = make_tgv_step_sharded(s, axis)

    def megaloop(carry, cfl_eff):
        return jax.lax.scan(one_step, carry, cfl_eff)

    specs = _slab_specs(("vel", "p", "umax", "time", "dt"), axis)
    sm = shard_map(megaloop, mesh, in_specs=(specs, P()),
                   out_specs=(specs, P()))
    return jax.jit(sm, donate_argnums=(0,))


def make_fish_step_sharded(s, ob, axis="x"):
    """The single-StefanFish scan body on one x-slab (inside shard_map
    over ``axis``).  The stencil-heavy advection-diffusion runs sharded
    on ring-padded slabs; the body phases (rasterization, chi, the
    momentum integrals, penalization, projection, probe) compute
    replicated — rasterization from replicated rigid scalars is already
    identical everywhere, and the rest works on the gathered velocity,
    so every reduction keeps the solo order and the step stays bitwise
    against make_fish_step."""
    from cup3d_tpu.models.fish.rasterize import rasterize_midline
    from cup3d_tpu.ops.surface import (
        _uniform_window_probe,
        obstacle_probe_budget,
        window_size_cells,
    )
    from cup3d_tpu.parallel import collectives as coll
    from cup3d_tpu.parallel import ring as _ring

    grid, nu, dtype = s.grid, s.nu, s.dtype
    cfg = s.cfg
    h = float(grid.h)
    solver = s.poisson_solver

    n = np.asarray(grid.shape)
    grid_shape = tuple(int(v) for v in n)
    window_shape = tuple(ob._window_shape)
    half_win = jnp.asarray(0.5 * np.asarray(window_shape) * h, dtype)
    lim_win = jnp.asarray(n - np.asarray(window_shape), jnp.int32)
    wp = int(min(window_size_cells(ob.length, h), n.min()))
    half_probe = jnp.asarray(0.5 * wp * h, dtype)
    lim_probe = jnp.asarray(n - wp, jnp.int32)
    budget = obstacle_probe_budget(ob, h)
    forced_mask = ob.forced_mask_dev()
    block_mask = ob.block_mask_dev()
    fix_frame = bool(ob.bFixFrameOfRef)
    uinf_const = None if fix_frame else s.uinf_device()
    xc = s.xc
    h3 = h ** 3
    hd = jnp.asarray(h, dtype)
    zero3 = jnp.zeros(3, dtype)
    dlm = float(cfg.DLM)
    lam_static = jnp.asarray(cfg.lambda_penalization, dtype)

    from cup3d_tpu.models.fish.device_midline import midline_state_device

    def pad_vec(u, w):
        return _ring.pad_slab_vector(grid, u, w, axis)

    def one_step(gait, carry, cfl_eff):
        vel, p = carry["vel"], carry["p"]
        rigid, qint = carry["rigid"], carry["qint"]
        umax, time, dtprev = carry["umax"], carry["time"], carry["dt"]
        cap = (h * h / 6.0) / (nu + (h / 6.0) * umax)
        dt = jnp.minimum(cfl_eff * h / (umax + 1e-8), cap)
        dt = jnp.where(dtprev > 0, jnp.minimum(dt, 1.03 * dtprev), dt)
        uinf = -rigid[0:3] if fix_frame else uinf_const
        # shape kinematics + rasterization: replicated (pure functions
        # of the replicated rigid/gait scalars)
        mid, qint_new = midline_state_device(gait, time, dt, qint)
        pos = rigid[6:9]
        rot = quat_to_rot_dev(rigid[15:19])
        idx0 = jnp.clip(jnp.floor((pos - half_win) / hd).astype(jnp.int32),
                        0, lim_win)
        origin = idx0.astype(dtype) * hd
        sdf_w, udef_w = rasterize_midline(origin, hd, window_shape, mid,
                                          pos, rot)
        sdf = jnp.full(grid_shape, -1.0, dtype)
        sdf = jax.lax.dynamic_update_slice(
            sdf, sdf_w, (idx0[0], idx0[1], idx0[2]))
        udef = jnp.zeros(grid_shape + (3,), dtype)
        udef = jax.lax.dynamic_update_slice(
            udef, udef_w, (idx0[0], idx0[1], idx0[2], 0))
        chi = towers_chi(grid.pad_scalar(sdf, 1), grid.h)
        udef = udef * (chi > 0)[..., None]
        # advection-diffusion on the slab, halos by ring permute
        vel = rk3_step(grid, vel, dt, nu, uinf, pad=pad_vec)
        vel_full = coll.all_gather_tiled(vel, axis)
        mom = pack_moments(
            momentum_integrals_core(xc, h3, chi, vel_full, rigid[12:15]))
        out = rigid_update_device(mom, rigid, forced_mask, block_mask,
                                  uinf, dt)
        rigid_new = out[:RIGID_STATE]
        ut, om, cm = out[0:3], out[3:6], out[12:15]
        ubody = ut + jnp.cross(jnp.broadcast_to(om, xc.shape), xc - cm) \
            + udef
        lam = dlm / dt if dlm > 0 else lam_static
        vel_pen = penalize(vel_full, chi, ubody, lam, dt)
        PF = -per_obstacle_penalization_force(
            vel_pen, vel_full, (chi,), dt, h3, xc, cm[None])[0]
        p_prev = coll.all_gather_tiled(p, axis)
        vel_proj, p_full = project(grid, vel_pen, dt, solver, chi, udef,
                                   p_init=p_prev)
        stats = _solver_stats(dtype)
        idx0f = jnp.clip(
            jnp.floor((out[6:9] - half_probe) / hd).astype(jnp.int32),
            0, lim_probe)
        F = pack_forces(_uniform_window_probe(
            vel_proj, p_full, chi, sdf, udef, idx0f, hd, zero3, nu, cm,
            ut, om, wcells=wp, max_points=budget))
        umax_new = jnp.maximum(max_velocity(vel_proj, uinf),
                               jnp.max(jnp.abs(udef)))
        time_new = time + dt
        sx = vel.shape[0]
        me = jax.lax.axis_index(axis)

        def sl(a):
            return jax.lax.dynamic_slice_in_dim(a, me * sx, sx, axis=0)

        carry_new = {
            "vel": sl(vel_proj), "p": sl(p_full), "chi": sl(chi),
            "udef": sl(udef), "rigid": rigid_new, "qint": qint_new,
            "umax": umax_new, "time": time_new, "dt": dt,
        }
        row = jnp.concatenate([out, PF, F, stats, qint_new,
                               umax_new[None], dt[None], time_new[None]])
        return carry_new, row

    return one_step


def build_fish_megaloop_sharded(s, ob, mesh, axis="x"):
    """jitted (carry, cfl_eff (K,)) -> (carry', rows (K, FISH_ROW)) with
    the fish scan body shard_mapped over ``axis`` slabs.  Returns None
    when the gait is not freezable, the solver advertises stats (the
    iterative front-ends keep the solo path — their [residual, iter]
    telemetry has no replicated form yet), or nx does not slab."""
    import warnings

    from jax.sharding import PartitionSpec as P

    from cup3d_tpu.models.fish.device_midline import freeze_gait
    from cup3d_tpu.obs import metrics as M
    from cup3d_tpu.parallel import topology as topo
    from cup3d_tpu.parallel.compat import shard_map

    gait = freeze_gait(ob, s.time, s.dtype)
    if gait is None:
        return None
    if getattr(s.poisson_solver, "supports_stats", False):
        return None
    D = topo.mesh_axis_size(mesh, axis)
    if s.grid.shape[0] % D or s.grid.shape[0] // D < GHOSTS:
        warnings.warn(
            f"{D} x-shards cannot slab nx={s.grid.shape[0]} (need even "
            f"slabs of >= {GHOSTS} planes for the one-hop ring halo): "
            f"megaloop runs unsharded", stacklevel=2)
        M.counter("topology.megaloop_mesh_fallbacks").inc()
        return None
    one_step = make_fish_step_sharded(s, ob, axis)

    def megaloop(carry, cfl_eff):
        return jax.lax.scan(
            lambda c, x: one_step(gait, c, x), carry, cfl_eff)

    specs = _slab_specs(("vel", "p", "chi", "udef", "rigid", "qint",
                         "umax", "time", "dt"), axis)
    sm = shard_map(megaloop, mesh, in_specs=(specs, P()),
                   out_specs=(specs, P()))
    return jax.jit(sm, donate_argnums=(0,))
