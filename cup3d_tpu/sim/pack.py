"""Grouped, deferred device->host QoI reads for pipelined drivers.

One device->host round trip costs ~100-200 ms over the tunneled TPU, reads
sporadically stall for seconds regardless of cadence, and concurrent reads
serialize — so reading one QoI pack per step caps throughput at one
latency per step.  Both drivers instead emit per-step packs into this
reader, which every ``read_every`` steps concatenates them ON DEVICE into
one vector and fetches it on a worker thread.  Entries are applied
strictly FIFO via the driver's consume callback, ON THE MAIN THREAD, as
their reads complete.

Round-4 change (VERDICT r3 item 4): ``emit`` never blocks on an in-flight
read.  The old scheme joined the previous group's fetch before starting
the next one, so every ``read_every`` steps the main thread stalled for a
full tunnel latency (and any sporadic multi-second transport stall landed
on the critical path).  Now completed reads are *polled* opportunistically
at each emit and only ``max_inflight`` groups may be outstanding before
emit applies blocking backpressure — a stalled read overlaps stepping
instead of gating it.

Host-mirror staleness is bounded by ~(1 + max_inflight) * read_every
steps; the drivers' device-resident dt chain (or, on the host-dt path,
their dt-growth bound and runaway abort) guards stability against the
stale max|u| (sim/simulation.py calc_max_timestep, sim/amr.py ditto).
"""

from __future__ import annotations

import threading
from typing import Callable, List

import numpy as np


class GroupedPackReader:
    """entries: dicts with a ``pack`` device vector and a ``layout`` of
    (name, size) pairs; ``consume(entry)`` is called with ``entry['vals']``
    filled, in emission order."""

    def __init__(self, consume: Callable[[dict], None], read_every: int = 4,
                 max_inflight: int = 2):
        self.consume = consume
        self.read_every = read_every
        self.max_inflight = max_inflight
        self.queue: List[dict] = []
        self._readers: List = []

    def __bool__(self):
        return bool(self.queue or self._readers)

    def emit(self, entry: dict) -> None:
        self.queue.append(entry)
        self.poll()
        if len(self.queue) >= self.read_every:
            while len(self._readers) >= self.max_inflight:
                self._join_one()  # backpressure: bounded staleness/backlog
            self.kick()

    def kick(self) -> None:
        """Start a worker-thread read of everything queued NOW, without
        waiting for it.  Called by emit() at the regular cadence, and by
        drivers that need fresher mirrors than the cadence provides (e.g.
        the collision pre-check when obstacles approach contact).  An
        opportunistic kick at the max_inflight limit is skipped — emit()'s
        blocking backpressure is the only place allowed to wait, so the
        reader count (and the retained device batches) stay bounded even
        when a driver kicks every step through a transport stall."""
        import jax.numpy as jnp

        if not self.queue or len(self._readers) >= self.max_inflight:
            return
        group, self.queue = self.queue, []
        batch = jnp.concatenate([e["pack"] for e in group])
        try:
            batch.copy_to_host_async()
        except Exception:
            pass
        holder = {"batch": batch, "group": group}
        th = threading.Thread(target=self._fetch, args=(holder,))
        th.start()
        self._readers.append((th, holder))

    @staticmethod
    def _fetch(holder: dict) -> None:
        try:
            holder["vals"] = np.asarray(holder["batch"], np.float64)
        except BaseException as e:  # re-raised on the main thread at join
            holder["err"] = e

    def _consume_holder(self, holder: dict) -> None:
        if "err" in holder:
            raise holder["err"]
        vals = holder["vals"]
        off = 0
        for entry in holder["group"]:
            size = sum(s for _, s in entry["layout"])
            entry["vals"] = vals[off:off + size]
            off += size
            self.consume(entry)

    def _join_one(self) -> None:
        th, holder = self._readers.pop(0)
        th.join()
        self._consume_holder(holder)

    def poll(self) -> None:
        """Consume completed reads without blocking (strictly FIFO: stop at
        the first still-running fetch)."""
        while self._readers and not self._readers[0][0].is_alive():
            self._join_one()

    def join(self) -> None:
        """Join ALL in-flight group reads and consume their entries."""
        while self._readers:
            self._join_one()

    def flush(self) -> None:
        """Drain everything: in-flight reads, then still-queued packs."""
        self.join()
        while self.queue:
            self.consume(self.queue.pop(0))
