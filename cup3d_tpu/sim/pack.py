"""Back-compat shim: the grouped QoI pack reader was promoted to the
async host data-plane subsystem as :class:`cup3d_tpu.stream.qoi.QoIStream`
(round 6; see stream/qoi.py for the full design history and the
staleness/backpressure contract).  Existing imports keep working."""

from cup3d_tpu.stream.qoi import PackPolicy, QoIStream

GroupedPackReader = QoIStream

__all__ = ["GroupedPackReader", "QoIStream", "PackPolicy"]
