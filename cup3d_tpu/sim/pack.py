"""Grouped, deferred device->host QoI reads for pipelined drivers.

One device->host round trip costs ~100-200 ms over the tunneled TPU, reads
sporadically stall for seconds regardless of cadence, and concurrent reads
serialize — so reading one QoI pack per step caps throughput at one
latency per step.  Both drivers instead emit per-step packs into this
reader, which every ``read_every`` steps concatenates them ON DEVICE into
one vector, fetches it on a worker thread (at most one read in flight),
and applies the entries strictly FIFO via the driver's consume callback.

Host-mirror staleness is bounded by ~2*read_every steps; the drivers'
dt-growth bound and runaway abort guard stability against the stale
max|u| (sim/simulation.py calc_max_timestep, sim/amr.py ditto).
"""

from __future__ import annotations

import threading
from typing import Callable, List

import numpy as np


class GroupedPackReader:
    """entries: dicts with a ``pack`` device vector and a ``layout`` of
    (name, size) pairs; ``consume(entry)`` is called with ``entry['vals']``
    filled, in emission order."""

    def __init__(self, consume: Callable[[dict], None], read_every: int = 4):
        self.consume = consume
        self.read_every = read_every
        self.queue: List[dict] = []
        self._readers: List = []

    def __bool__(self):
        return bool(self.queue or self._readers)

    def emit(self, entry: dict) -> None:
        import jax.numpy as jnp

        self.queue.append(entry)
        if len(self.queue) >= self.read_every:
            group, self.queue = self.queue, []
            batch = jnp.concatenate([e["pack"] for e in group])
            try:
                batch.copy_to_host_async()
            except Exception:
                pass
            self.join()  # at most one group read in flight
            holder = {"batch": batch, "group": group}
            th = threading.Thread(target=self._fetch, args=(holder,))
            th.start()
            self._readers.append((th, holder))

    @staticmethod
    def _fetch(holder: dict) -> None:
        try:
            holder["vals"] = np.asarray(holder["batch"], np.float64)
        except BaseException as e:  # re-raised on the main thread at join
            holder["err"] = e

    def join(self) -> None:
        """Join in-flight group reads and consume their entries."""
        while self._readers:
            th, holder = self._readers.pop(0)
            th.join()
            if "err" in holder:
                raise holder["err"]
            vals = holder["vals"]
            off = 0
            for entry in holder["group"]:
                size = sum(s for _, s in entry["layout"])
                entry["vals"] = vals[off:off + size]
                off += size
                self.consume(entry)

    def flush(self) -> None:
        """Drain everything: in-flight reads, then still-queued packs."""
        self.join()
        while self.queue:
            self.consume(self.queue.pop(0))
