"""Grouped, deferred device->host QoI reads for pipelined drivers.

One device->host round trip costs ~100-200 ms over the tunneled TPU and
blocking reads serialize with the dispatch stream — so reading one QoI
pack per step caps throughput at one latency per step.  Both drivers
instead emit per-step packs into this reader, which every ``read_every``
steps concatenates them ON DEVICE into one vector, starts an ASYNC host
copy, and consumes completed groups opportunistically.  Entries are
applied strictly FIFO via the driver's consume callback, on the main
thread.

Round-4 redesign (VERDICT r3 item 4): the reader is THREADLESS.  The old
scheme fetched each group on a worker thread whose blocking ``np.asarray``
was starved by the main thread's dispatch loop (GIL) and serialized with
tunnel traffic — measured 1.5-4 s per group read while stepping, i.e. the
"non-blocking" read gated the whole step (BENCH r3/r4-early: SyncQoI
0.22-0.40 s/step).  Measured on the same tunnel: ``copy_to_host_async``
prefetches the value to host (a later ``np.asarray`` costs ~0.1 ms) and
``x.is_ready()`` is a local ~0.03 ms poll.  So the reader now keeps a FIFO
of in-flight async-copied batches and drains the completed prefix at each
emit; nothing blocks until ``max_inflight`` groups are outstanding, and
the only blocking wait is genuine backpressure (the device has fallen
``max_inflight * read_every`` steps behind the host).

Host-mirror staleness is bounded by ~(1 + max_inflight) * read_every
steps; the drivers' device-resident dt chain (or, on the host-dt path,
their dt-growth bound and runaway abort) guards stability against the
stale max|u| (sim/simulation.py calc_max_timestep, sim/amr.py ditto).
"""

from __future__ import annotations

from typing import Callable, List

import numpy as np


class GroupedPackReader:
    """entries: dicts with a ``pack`` device vector and a ``layout`` of
    (name, size) pairs; ``consume(entry)`` is called with ``entry['vals']``
    filled, in emission order."""

    def __init__(self, consume: Callable[[dict], None], read_every: int = 4,
                 max_inflight: int = 2):
        self.consume = consume
        self.read_every = read_every
        self.max_inflight = max_inflight
        self.queue: List[dict] = []
        self._inflight: List[dict] = []  # {batch, group} FIFO

    def __bool__(self):
        return bool(self.queue or self._inflight)

    def emit(self, entry: dict) -> None:
        self.queue.append(entry)
        self.poll()
        if len(self.queue) >= self.read_every:
            while len(self._inflight) >= self.max_inflight:
                self._consume_one()  # backpressure: bounded staleness
            self.kick()

    def kick(self) -> None:
        """Group everything queued NOW into one device batch and start its
        async host copy.  Called by emit() at the regular cadence, and by
        drivers that need fresher mirrors than the cadence provides (e.g.
        the collision pre-check when obstacles approach contact).  A kick
        at the max_inflight limit is skipped — emit()'s backpressure is
        the only place allowed to wait, so the retained device batches
        stay bounded even when a driver kicks every step."""
        import jax.numpy as jnp

        if not self.queue or len(self._inflight) >= self.max_inflight:
            return
        group, self.queue = self.queue, []
        batch = jnp.concatenate([e["pack"] for e in group])
        try:
            batch.copy_to_host_async()
        except Exception:
            pass  # platforms without async copies: asarray below blocks
        self._inflight.append({"batch": batch, "group": group})

    def _consume_one(self) -> None:
        """Read the oldest in-flight batch (blocking only if its compute /
        transfer has not landed yet) and apply its entries FIFO."""
        holder = self._inflight.pop(0)
        vals = np.asarray(holder["batch"], np.float64)
        off = 0
        for entry in holder["group"]:
            size = sum(s for _, s in entry["layout"])
            entry["vals"] = vals[off:off + size]
            off += size
            self.consume(entry)

    @staticmethod
    def _ready(batch) -> bool:
        try:
            return bool(batch.is_ready())
        except Exception:
            return True  # no readiness probe: treat as ready (read blocks)

    def poll(self) -> None:
        """Consume completed reads without blocking (strictly FIFO: stop at
        the first batch whose computation hasn't landed)."""
        while self._inflight and self._ready(self._inflight[0]["batch"]):
            self._consume_one()

    def join(self) -> None:
        """Consume ALL in-flight group reads (blocking)."""
        while self._inflight:
            self._consume_one()

    def flush(self) -> None:
        """Drain everything: in-flight reads, then still-queued packs."""
        self.join()
        while self.queue:
            self.consume(self.queue.pop(0))
