"""SimulationData: all runtime state of a run (reference main.cpp:6600-6677).

The reference keeps five parallel AMR grids (chi, pres, lhs scalar; vel, tmpV
vector).  Here the uniform-grid path keeps one dict of dense device arrays;
``lhs``/``tmpV`` scratch fields are unnecessary because XLA materializes
temporaries inside fused kernels.  The AMR path swaps these for block-batched
arrays with identical keys (``cup3d_tpu.grid.blocks``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from cup3d_tpu.config import SimulationConfig
from cup3d_tpu.grid.uniform import BC, UniformGrid
from cup3d_tpu.io.logging import BufferedLogger, Profiler
from cup3d_tpu.ops.poisson import make_poisson_solver


class SimulationData:
    def __init__(self, cfg: SimulationConfig):
        self.cfg = cfg
        shape = cfg.uniform_shape()
        self.grid = UniformGrid(shape, cfg.extents, tuple(BC(b) for b in cfg.bc))
        self.dtype = jnp.dtype(cfg.dtype)

        n3 = shape + (3,)
        self.state: Dict[str, jnp.ndarray] = {
            "vel": jnp.zeros(n3, self.dtype),
            "chi": jnp.zeros(shape, self.dtype),
            "p": jnp.zeros(shape, self.dtype),
            "udef": jnp.zeros(n3, self.dtype),
        }

        self.poisson_solver: Callable = make_poisson_solver(
            self.grid,
            cfg.poissonSolver,
            self.dtype,
            tol_abs=cfg.poissonTol,
            tol_rel=cfg.poissonTolRel,
            mean_constraint=cfg.bMeanConstraint,
        )
        # round 12: record which Krylov path this run compiled (storage
        # dtype + fused-iteration driver) so a bench/telemetry dump can
        # tell the configurations apart without re-deriving env state
        from cup3d_tpu.obs import metrics as obs_metrics
        from cup3d_tpu.ops import precision as _precision

        obs_metrics.gauge("poisson.krylov_bf16").set(
            float(_precision.krylov_dtype() == jnp.bfloat16))
        obs_metrics.gauge("poisson.fused_iteration").set(
            float(_precision.use_fused()))

        # scalars (host side, mirroring main.cpp:15348-15387 defaults)
        self.time: float = 0.0
        self.step: int = 0
        self.dt: float = 0.0
        self.uinf = np.asarray(cfg.uinf, dtype=np.float64)
        self.nu = cfg.nu
        self.lambda_penal = cfg.lambda_penalization

        self.obstacles: List = []  # filled by the obstacle factory
        self.MeshChanged = True
        # device fast path: (name, device array) QoI produced during the
        # step, concatenated and fetched in ONE host read at the end of
        # advance() (the tunneled TPU costs ~75 ms per blocking read);
        # pipelined mode defers that read one step so the transfer overlaps
        # the next step's device work
        self.pending_parts: List = []
        self._uinf_dev = None
        self._uinf_host_src = None    # identity key of the cached upload
        self._uinf_host_cache = None  # device mirror of self.uinf

        self.logger = BufferedLogger(cfg.path4serialization)
        self.profiler = Profiler()
        from cup3d_tpu.io.dump import OutputCadence

        self.cadence = OutputCadence(cfg.tdump, cfg.fdump, cfg.saveFreq)

        # device-resident cell centers + jitted rigid-body velocity field:
        # obstacle code calls body_velocity_field every step (penalization,
        # forces); rebuilding centers on host and dispatching eagerly costs
        # seconds/step at 128^3 (measured on TPU).  Built lazily so
        # obstacle-free runs never hold the (nx,ny,nz,3) array on device.
        self._xc_cache = None
        self._ubody_cache_fn = None
        # cached device lambda mirrors (lambda_device): the DLM constant
        # uploads once and lambda = DLM/dt is computed ON DEVICE from the
        # step's already-uploaded dt scalar; a static lambda uploads once
        # per value.  The old per-step jnp.asarray(self.lambda_penal)
        # re-staged a fresh host float every step (lint rule JX010).
        self._dlm_dev_cache = None
        self._lambda_dev_cache = None
        self._lambda_dev_val = None

    @property
    def xc(self) -> jnp.ndarray:
        if self._xc_cache is None:
            self._xc_cache = jnp.asarray(self.grid.cell_centers(self.dtype))
        return self._xc_cache

    @property
    def _ubody_fn(self):
        if self._ubody_cache_fn is None:
            import jax

            xc = self.xc
            self._ubody_cache_fn = jax.jit(
                lambda udef, cm, ut, om: ut
                + jnp.cross(jnp.broadcast_to(om, xc.shape), xc - cm)
                + udef
            )
        return self._ubody_cache_fn

    @property
    def vel(self) -> jnp.ndarray:
        return self.state["vel"]

    @property
    def chi(self) -> jnp.ndarray:
        return self.state["chi"]

    def lambda_device(self, dt_dev) -> jnp.ndarray:
        """Device-resident penalization lambda for this step.

        DLM > 0 configurations recompute lambda = DLM/dt every step
        (main.cpp:15302-15303): the division runs ON DEVICE against the
        step's dt scalar (already uploaded by advance()), with the DLM
        constant cached after one sanctioned upload — zero steady-state
        host->device traffic.  Static-lambda configurations upload once
        per value.  The host ``lambda_penal`` mirror keeps feeding logs
        and checkpoints unchanged."""
        from cup3d_tpu.analysis.runtime import sanctioned_transfer

        if self.cfg.DLM > 0:
            if self._dlm_dev_cache is None:
                with sanctioned_transfer("scalar-upload"):
                    self._dlm_dev_cache = jnp.asarray(
                        self.cfg.DLM, self.dtype
                    )
            return self._dlm_dev_cache / dt_dev
        if self._lambda_dev_val != self.lambda_penal:
            with sanctioned_transfer("scalar-upload"):
                self._lambda_dev_cache = jnp.asarray(
                    self.lambda_penal, self.dtype
                )
            self._lambda_dev_val = self.lambda_penal
        return self._lambda_dev_cache

    def uinf_device(self) -> jnp.ndarray:
        # pipelined mode keeps uinf device-resident (CreateObstacles sets
        # it from the device transVel); the host self.uinf then only feeds
        # logs and checkpoints
        if self._uinf_dev is not None:
            return self._uinf_dev
        # cache the upload keyed on identity: frame-velocity updates
        # REASSIGN self.uinf (models/pipeline.py, io/checkpoint.py), so
        # `is` tracks staleness without a per-step compare and a constant
        # uinf costs the steady-state loop zero host->device traffic
        # (caught by jax.transfer_guard in tests/test_analysis.py)
        if self._uinf_host_src is not self.uinf:
            from cup3d_tpu.analysis.runtime import sanctioned_transfer

            with sanctioned_transfer("uinf-upload"):
                self._uinf_host_cache = jnp.asarray(
                    self.uinf, dtype=self.dtype
                )
            self._uinf_host_src = self.uinf
        return self._uinf_host_cache
