"""Simulation driver: init + timestep loop (reference Simulation,
main.cpp:15161-15326).

``simulate()`` = loop { calcMaxTimestep; advance }, with the reference's
CFL advective/diffusive dt policy, 100-step logarithmic ramp-up, runaway-
velocity abort, and heartbeat print (main.cpp:15247-15305).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import numpy as np

from cup3d_tpu.config import SimulationConfig, parse_factory
from cup3d_tpu.ops import diagnostics as diag
from cup3d_tpu.sim import operators as ops
from cup3d_tpu.sim.data import SimulationData


class Simulation:
    def __init__(self, cfg: SimulationConfig):
        self.cfg = cfg
        self.sim = SimulationData(cfg)
        self.pipeline: List[ops.Operator] = []
        self._max_u = jax.jit(diag.max_velocity)
        # max|u| fetched in the previous step's packed read (fast path):
        # saves the blocking read at the top of calc_max_timestep
        self._umax_next: float | None = None

    # -- setup (reference init(), main.cpp:15163-15178) --------------------

    def init(self) -> None:
        self._setup_operators()
        self._add_obstacles()
        ops.initial_conditions(self.sim)

    def _setup_operators(self) -> None:
        """Pipeline order is the reference's (main.cpp:15229-15246)."""
        s = self.sim
        cfg = self.cfg
        with_bodies = bool(s.obstacles or cfg.factory_content)
        if with_bodies:
            from cup3d_tpu.models import pipeline as body_ops

        pipe: List[ops.Operator] = []
        if with_bodies:
            pipe.append(body_ops.CreateObstacles(s))
        if cfg.implicitDiffusion:
            pipe.append(ops.AdvectionDiffusionImplicit(s))
        else:
            pipe.append(ops.AdvectionDiffusion(s))
        if cfg.uMax_forced > 0 and not cfg.bFixMassFlux:
            pipe.append(ops.ExternalForcing(s))
        if cfg.bFixMassFlux:
            pipe.append(ops.FixMassFlux(s))
        if with_bodies:
            pipe.append(body_ops.UpdateObstacles(s))
            pipe.append(body_ops.Penalization(s))
        pipe.append(ops.PressureProjection(s))
        if with_bodies:
            pipe.append(body_ops.ComputeForces(s))
        pipe.append(ops.ComputeDissipation(s))
        pipe.append(ops.ComputeDivergence(s))
        self.pipeline = pipe

    def _add_obstacles(self) -> None:
        content = self.cfg.resolved_factory_content()
        if not content:
            return
        from cup3d_tpu.models.factory import make_obstacles

        self.sim.obstacles = make_obstacles(self.sim, parse_factory(content))

    # -- time stepping -----------------------------------------------------

    def calc_max_timestep(self) -> float:
        """CFL dt with diffusive cap and log ramp-up (main.cpp:15254-15305)."""
        s, cfg = self.sim, self.cfg
        h = s.grid.h
        if self._umax_next is not None:
            umax, self._umax_next = self._umax_next, None
        else:
            umax = float(self._max_u(s.state["vel"], s.uinf_device()))
        if umax > cfg.uMax_allowed:
            s.logger.flush()
            raise RuntimeError(
                f"runaway velocity: max|u|={umax:.3g} > uMax_allowed={cfg.uMax_allowed}"
            )
        if cfg.dt > 0:
            s.dt = cfg.dt
        else:
            cfl = cfg.CFL
            if s.step < cfg.rampup:  # logarithmic ramp 1e-2*CFL -> CFL
                cfl = cfg.CFL * 10.0 ** (-2.0 * (1.0 - s.step / cfg.rampup))
            dt_adv = cfl * h / max(umax, 1e-12)
            if cfg.implicitDiffusion:
                # a from-rest flow is diffusion-dominated: keep the explicit
                # cap until any velocity scale exists, else dt_adv blows up
                umax_eff = max(umax, cfg.uMax_forced, float(np.abs(s.uinf).max()))
                dt_dif = np.inf if umax_eff > 1e-8 else 0.25 * h * h / s.nu
            else:
                dt_dif = 0.25 * h * h / s.nu
            s.dt = float(min(dt_adv, dt_dif))
            if cfg.tend > 0:
                s.dt = min(s.dt, cfg.tend - s.time)
        # lambda = DLM/dt each step (main.cpp:15302-15303)
        if cfg.DLM > 0:
            s.lambda_penal = cfg.DLM / s.dt
        return s.dt

    # -- output ------------------------------------------------------------

    def _maybe_dump_save(self) -> None:
        s = self.sim
        if s.cadence.dump_due(s.time, s.step):
            self.dump_fields()
        if s.cadence.save_due(s.step):
            from cup3d_tpu.io.checkpoint import save_checkpoint

            with s.profiler("Checkpoint"):
                save_checkpoint(self)

    def dump_fields(self) -> None:
        import os

        from cup3d_tpu.io import dump as dmp

        s, cfg = self.sim, self.cfg

        def omega_mag(vel):
            om = np.asarray(diag.vorticity(s.grid, vel))
            return np.sqrt(np.sum(om**2, axis=-1))

        fields = dmp.collect_dump_fields(cfg, s.state, omega_mag)
        if fields:
            prefix = os.path.join(cfg.path4serialization, f"dump_{s.step:07d}")
            with s.profiler("Dump"):
                dmp.dump_fields(prefix, s.time, s.grid, fields)

    def advance(self, dt: float) -> None:
        s = self.sim
        self._maybe_dump_save()
        for op in self.pipeline:
            with s.profiler(op.name):
                op(dt)
        if s.pending_parts:
            with s.profiler("SyncQoI"):
                self._consume_step_pack()
        s.step += 1
        s.time += dt

    def _consume_step_pack(self) -> None:
        """Fetch every device QoI the step produced (rigid state, forces,
        penalization forces) plus max|u| for the next dt in ONE packed
        host read — the step's only blocking device sync (fast path;
        see models/base.rigid_update_device)."""
        import jax.numpy as jnp

        from cup3d_tpu.models.base import (
            log_forces, store_force_qoi, unpack_forces,
        )

        s = self.sim
        parts = s.pending_parts
        s.pending_parts = []
        parts.append(
            ("umax",
             self._max_u(s.state["vel"], s.uinf_device()).reshape(1))
        )
        # pack in the solver dtype: a forced f32 cast would silently
        # truncate the rigid trajectory in a float64 configuration
        pack = jnp.concatenate([p[1].astype(s.dtype) for p in parts])
        vals = np.asarray(pack, np.float64)  # the single blocking read
        ob = s.obstacles[0] if s.obstacles else None
        off = 0
        for name, arr in parts:
            seg = vals[off:off + arr.shape[0]]
            off += arr.shape[0]
            if name == "rigid":
                ob.apply_rigid_pack(seg)
            elif name == "penal":
                ob.penal_force = seg[:3]
                ob.penal_torque = seg[3:]
            elif name == "forces":
                store_force_qoi(ob, unpack_forces(seg))
                log_forces(s.logger, 0, s.time, ob)
            elif name == "umax":
                self._umax_next = float(seg[0])

    def simulate(self) -> None:
        s, cfg = self.sim, self.cfg
        while True:
            dt = self.calc_max_timestep()
            if cfg.verbose:
                print(f"cup3d_tpu: step: {s.step}, time: {s.time:f}, dt: {dt:.3e}")
            self.advance(dt)
            done_t = cfg.tend > 0 and s.time >= cfg.tend - 1e-12
            done_n = cfg.nsteps > 0 and s.step >= cfg.nsteps
            if done_t or done_n:
                break
        s.logger.flush()
