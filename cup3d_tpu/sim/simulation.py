"""Simulation driver: init + timestep loop (reference Simulation,
main.cpp:15161-15326).

``simulate()`` = loop { calcMaxTimestep; advance }, with the reference's
CFL advective/diffusive dt policy, 100-step logarithmic ramp-up, runaway-
velocity abort, and heartbeat print (main.cpp:15247-15305).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import numpy as np

from cup3d_tpu.analysis.runtime import device_scalar, sanctioned_transfer
from cup3d_tpu.config import SimulationConfig, parse_factory
from cup3d_tpu.obs import trace as obs_trace
from cup3d_tpu.obs.flight import FlightRecorder
from cup3d_tpu.ops import diagnostics as diag
from cup3d_tpu.resilience import faults
from cup3d_tpu.resilience.recovery import SimulationFailure
from cup3d_tpu.sim import operators as ops
from cup3d_tpu.sim.data import SimulationData


class Simulation:
    def __init__(self, cfg: SimulationConfig):
        self.cfg = cfg
        self.sim = SimulationData(cfg)
        self.pipeline: List[ops.Operator] = []
        self._max_u = jax.jit(diag.max_velocity)
        # max|u| fetched in the previous step's packed read (fast path):
        # saves the blocking read at the top of calc_max_timestep
        self._umax_next: float | None = None
        # pipelined mode: grouped deferred reads through the async host
        # data-plane (stream/qoi.py) — K packs concatenate on device into
        # ONE async fetch, amortizing the tunnel's per-read latency;
        # non-pipelined runs consume each pack at the end of its own step.
        # The pack policy slims 256^3-class configs to scalars-only.
        from cup3d_tpu.stream.qoi import PackPolicy, QoIStream

        ncells = int(np.prod(self.sim.grid.shape))
        self._pack_reader = QoIStream(
            self._consume_pack, policy=PackPolicy.for_cells(ncells),
            profiler=self.sim.profiler,
        )
        # off-critical-path output (stream/dump.py, stream/checkpoint.py)
        from cup3d_tpu.stream.checkpoint import AsyncCheckpointer
        from cup3d_tpu.stream.dump import AsyncDumper

        self._dumper = AsyncDumper()
        self._checkpointer = AsyncCheckpointer()
        # round-9 observability (cup3d_tpu/obs/): the flight recorder's
        # ring runs ALWAYS (O(1) host appends — postmortems need history
        # from before the failure); step traces only under CUP3D_TRACE=1.
        # Solver iteration counts ride the packed QoI read (see
        # PressureProjection), never a dedicated sync.
        obs_trace.TRACE.default_directory(cfg.path4serialization)
        self.flight = FlightRecorder(
            directory=cfg.path4serialization, run_config=cfg,
            state_probe=self._flight_state,
        )
        self._obs = obs_trace.StepObserver(
            self.sim.profiler, flight=self.flight,
            stream=self._pack_reader, kind="uniform",
        )
        # round-13 observability v2 (obs/profile.py, obs/export.py):
        # device-time capture windows at loop/K boundaries under
        # CUP3D_PROFILE=every:N, and the env-gated /metrics//health
        # exporter (CUP3D_METRICS_PORT) — both no-ops when disarmed,
        # neither ever touches a device value on the step loop.
        from cup3d_tpu.obs import export as obs_export
        from cup3d_tpu.obs import profile as obs_profile

        obs_profile.CONTROLLER.default_directory(cfg.path4serialization)
        self._obs_profile = obs_profile.CONTROLLER
        obs_export.ensure_exporter()
        self._last_umax: Optional[float] = None
        # round-10 resilience: simulate() installs a RecoveryEngine here
        # (CUP3D_RECOVER=1, the default); None = legacy crash-on-fault
        self._resilience = None
        # round-11 scan megaloop (sim/megaloop.py): K whole steps per
        # jitted lax.scan dispatch.  _scan_k resolves at init() (0 =
        # off, the seed per-step loop); the compiled loop and its
        # device carry build lazily on first eligible iteration.
        self._scan_k = 0
        self._megaloop = None  # (jitted scan fn, row width) once built
        self._scan_carry = None  # device carry dict between megaloops
        self._scan_mesh = None  # round-18 x-slab mesh when sharded

    # -- setup (reference init(), main.cpp:15163-15178) --------------------

    def init(self) -> None:
        self._setup_operators()
        self._add_obstacles()
        if self.cfg.pipelined:
            if len(self.sim.obstacles) > 1:
                raise ValueError(
                    "pipelined mode requires a single obstacle (the device "
                    "rigid chain has no multi-body collision path) — run "
                    "without -pipelined"
                )
            for ob in self.sim.obstacles:
                # stale-PID: position/depth controllers read host mirrors
                # that lag ~2x the grouped-read cadence; they are gentle,
                # clipped controllers and tolerate the lag (tested in
                # tests/test_amr_pipelined.py).  Roll correction instead
                # MUTATES angVel right after the 6x6 solve on host and
                # cannot ride the device rigid chain.
                if getattr(ob, "bCorrectRoll", False):
                    raise ValueError(
                        "pipelined mode cannot run roll-corrected "
                        "obstacles (host-side angVel mutation) — run "
                        "without -pipelined"
                    )
        ops.initial_conditions(self.sim)
        from cup3d_tpu.sim.megaloop import resolve_scan_k

        k = resolve_scan_k(self.cfg)
        self._scan_k = k if (k >= 1 and self._megaloop_eligible()) else 0

    def _megaloop_eligible(self) -> bool:
        """Static gate for the K-step scan megaloop (config + obstacle
        shape); the dynamic parts — gait freezability, the step budget
        tail, a recovery retreat in progress — are re-checked each
        iteration by :meth:`_scan_ready`."""
        cfg, s = self.cfg, self.sim
        if not cfg.pipelined or cfg.dt > 0 or cfg.implicitDiffusion:
            return False
        if cfg.tend > 0 or cfg.nsteps <= 0:
            # done-by-time needs a fresh s.time every step; inside the
            # scan the host time mirror lags by up to the stream window
            return False
        if cfg.uMax_forced > 0 or cfg.bFixMassFlux or cfg.freqDiagnostics:
            return False  # forcing/diagnostics operators are per-step
        if not s.obstacles:
            return True
        if len(s.obstacles) != 1:
            return False
        from cup3d_tpu.models.fish.device_midline import (
            device_midline_eligible,
        )

        return device_midline_eligible(s.obstacles[0])

    def _scan_ready(self) -> bool:
        """True when the next simulate iteration should run as one
        K-step megaloop: scan enabled, the compiled loop buildable
        (fish gait freezable), a full K inside the step budget, and no
        recovery retreat in progress (the per-step path owns the
        halved-dt re-advance; the scan resumes once the engine retires
        the attempt)."""
        K = self._scan_k
        if K < 1:
            return False
        s = self.sim
        if s.step + K > self.cfg.nsteps:
            return False  # per-step tail keeps nsteps exact
        if (self._resilience is not None
                and self._resilience.dt_scale != 1.0):
            return False
        if self._megaloop is None:
            from cup3d_tpu.parallel import topology as topo
            from cup3d_tpu.sim import megaloop as ml

            # CUP3D_MESH_X asks for the x-slab sharded scan body
            # (round 18); builders return None when the run cannot
            # slab (solver stats, nx % D, thin slabs) and the solo
            # loop stays the loud fallback
            mesh = topo.megaloop_mesh()
            fn = None
            if s.obstacles:
                if mesh is not None:
                    fn = ml.build_fish_megaloop_sharded(
                        s, s.obstacles[0], mesh)
                self._scan_mesh = mesh if fn is not None else None
                if fn is None:
                    fn = ml.build_fish_megaloop(s, s.obstacles[0])
                row_w = ml.FISH_ROW
            else:
                if mesh is not None:
                    fn = ml.build_tgv_megaloop_sharded(s, mesh)
                self._scan_mesh = mesh if fn is not None else None
                if fn is None:
                    fn = ml.build_tgv_megaloop(s)
                row_w = ml.TGV_ROW
            if fn is None:
                # gait not freezable after all: scan off for the run
                self._scan_k = 0
                return False
            self._megaloop = (fn, row_w)
        return True

    def _setup_operators(self) -> None:
        """Pipeline order is the reference's (main.cpp:15229-15246)."""
        s = self.sim
        cfg = self.cfg
        with_bodies = bool(s.obstacles or cfg.factory_content)
        if with_bodies:
            from cup3d_tpu.models import pipeline as body_ops

        pipe: List[ops.Operator] = []
        if with_bodies:
            pipe.append(body_ops.CreateObstacles(s))
        if cfg.implicitDiffusion:
            pipe.append(ops.AdvectionDiffusionImplicit(s))
        else:
            pipe.append(ops.AdvectionDiffusion(s))
        if cfg.uMax_forced > 0 and not cfg.bFixMassFlux:
            pipe.append(ops.ExternalForcing(s))
        if cfg.bFixMassFlux:
            pipe.append(ops.FixMassFlux(s))
        if with_bodies:
            pipe.append(body_ops.UpdateObstacles(s))
            pipe.append(body_ops.Penalization(s))
        pipe.append(ops.PressureProjection(s))
        if with_bodies:
            pipe.append(body_ops.ComputeForces(s))
        pipe.append(ops.ComputeDissipation(s))
        pipe.append(ops.ComputeDivergence(s))
        self.pipeline = pipe

    def _add_obstacles(self) -> None:
        content = self.cfg.resolved_factory_content()
        if not content:
            return
        from cup3d_tpu.models.factory import make_obstacles

        self.sim.obstacles = make_obstacles(self.sim, parse_factory(content))

    # -- observability -----------------------------------------------------

    def _flight_state(self) -> dict:
        """Driver state for a flight-recorder postmortem (called only at
        dump time, so the host reads here are free to be thorough)."""
        s = self.sim
        return {
            "driver": "uniform",
            "shape": list(s.grid.shape),
            "step": s.step,
            "time": s.time,
            "dt": s.dt,
            "uinf": [float(v) for v in s.uinf],
            "obstacles": [type(ob).__name__ for ob in s.obstacles],
            "stream": self._pack_reader.snapshot(),
            # round 10: the async writers' health rides in postmortems
            # (latched background failures, drop counts)
            "checkpointer": self._checkpointer.health(),
            "dumper": self._dumper.health(),
        }

    # -- time stepping -----------------------------------------------------

    def calc_max_timestep(self) -> float:
        """CFL dt with diffusive cap and log ramp-up (main.cpp:15254-15305)."""
        s, cfg = self.sim, self.cfg
        h = s.grid.h
        if faults.fire("step.nan_velocity", s.step):
            # injected fault (resilience/faults.py): poison the max|u|
            # mirror so the EXISTING NaN-umax abort below detects it
            self._umax_next = float("nan")
        if self._umax_next is not None:
            umax = self._umax_next
            if not self.cfg.pipelined:
                self._umax_next = None
            # pipelined: keep the latest consumed max|u| — staleness is
            # bounded by ~2x the grouped-read cadence (sim/pack.py) — and
            # FLOOR it with the fresh host-side body speed: a gait
            # spin-up outruns the stale mirror while dt sits at the
            # diffusive cap (measured blow-up at 256^3; see
            # Obstacle.max_body_speed)
            if self.cfg.pipelined and s.obstacles:
                umax = max(
                    umax,
                    max(ob.max_body_speed(s.uinf) for ob in s.obstacles),
                )
        else:
            # the designed once-per-step dt sync of the non-pipelined
            # path (the ONLY device->host read its steady-state step pays)
            with sanctioned_transfer("umax-read"):
                umax = float(
                    self._max_u(s.state["vel"], s.uinf_device())
                )
                if s.obstacles:
                    # the CFL scale must see the BODY kinematics
                    # immediately: at full gait amplitude the tail's
                    # deformation velocity reaches the advective limit one
                    # step before it imprints on the measured fluid field
                    # (blow-up observed at the diffusive-cap dt otherwise)
                    import jax.numpy as _jnp

                    umax = max(
                        umax, float(_jnp.max(_jnp.abs(s.state["udef"])))
                    )
        self._last_umax = umax  # host float already (both branches)
        if not np.isfinite(umax) or umax > cfg.uMax_allowed:
            # NaN must trip the abort too (`NaN > x` is False; code-review r4)
            s.logger.flush()
            # postmortem BEFORE the raise: ring contents, residual
            # history, last-known-good step (obs/flight.py)
            reason = ("nan-velocity" if not np.isfinite(umax)
                      else "runaway-velocity")
            extra = {"step": s.step, "umax": umax}
            self.flight.trigger(reason, extra=extra)
            raise SimulationFailure(
                reason,
                f"runaway velocity: max|u|={umax:.3g} > uMax_allowed={cfg.uMax_allowed}",
                extra,
            )
        if cfg.dt > 0:
            s.dt = cfg.dt
        else:
            from cup3d_tpu.sim import dtpolicy

            prev_dt = s.dt
            if cfg.pipelined:
                # max|u| may be (1 + max_inflight) * read_every ~ 12 steps
                # stale with the round-4 non-blocking reader (sim/pack.py):
                # assume it can have grown 1.5x since measured (the dt
                # growth bound below limits it to 1.03^12 ~ 1.43) so the
                # EFFECTIVE CFL never exceeds the configured value — a
                # sharp-chi fish at full gait measurably blows up without
                # this margin while the fresh-umax host path is stable
                umax = 1.5 * umax
            # reference dt = min(combined diffusion cap, ramped CFL * h/umax)
            # (main.cpp:15268-15281 via sim/dtpolicy.py — the combined cap
            # is the upwind3 stability boundary; the pure 0.25 h^2/nu cap
            # blew up the 256^3 fish, see dtpolicy docstring)
            s.dt = dtpolicy.dt_host(h, s.nu, umax, cfg.CFL, s.step,
                                    cfg.rampup, cfg.implicitDiffusion)
            if cfg.pipelined and prev_dt > 0:
                s.dt = min(s.dt, 1.03 * prev_dt)
            if cfg.tend > 0:
                s.dt = min(s.dt, cfg.tend - s.time)
        if self._resilience is not None:
            # retry dt halving (exact no-op at scale 1.0, so the armed
            # clean path stays bitwise-identical to CUP3D_RECOVER=0)
            s.dt = self._resilience.scale_dt(s.dt)
        if faults.fire("dt.collapse", s.step):
            # injected fault: collapse dt so the existing abort trips
            s.dt = float("nan")
        if not np.isfinite(s.dt) or s.dt <= 0:
            # dt policy collapse: a non-finite or non-positive dt would
            # loop forever / poison every field — dump and abort
            extra = {"step": s.step, "dt": s.dt, "umax": umax}
            self.flight.trigger("dt-collapse", extra=extra)
            raise SimulationFailure(
                "dt-collapse", f"dt policy collapse: dt={s.dt:.3g}", extra
            )
        # lambda = DLM/dt each step (main.cpp:15302-15303)
        if cfg.DLM > 0:
            s.lambda_penal = cfg.DLM / s.dt
        return s.dt

    # -- output ------------------------------------------------------------

    def _maybe_dump_save(self) -> None:
        s = self.sim
        if s.cadence.dump_due(s.time, s.step):
            self.flush_packs()  # host mirrors current before output
            self.dump_fields()
        if s.cadence.save_due(s.step):
            self.flush_packs()
            with s.profiler("Checkpoint"):
                # async snapshot: fields stage via copy_to_host_async and
                # serialize on the writer thread (stream/checkpoint.py)
                self._save_checkpoint_guarded()

    def _save_checkpoint_guarded(self) -> None:
        """Async checkpoint with the round-10 degradation policy: under
        recovery, a failed background write (surfaced by the
        AsyncCheckpointer on the NEXT save) falls back to ONE synchronous
        atomic write; if that fails too the checkpoint is dropped +
        counted — output must never kill the step loop.  Without
        recovery the failure propagates (the legacy baseline)."""
        from cup3d_tpu.obs import metrics as obs_metrics

        try:
            self._checkpointer.save(self)
        except Exception:
            if self._resilience is None:
                raise
            obs_metrics.counter("resilience.ckpt_sync_fallbacks").inc()
            try:
                from cup3d_tpu.io.checkpoint import save_checkpoint

                save_checkpoint(self)
            except Exception:
                obs_metrics.counter("resilience.ckpt_dropped").inc()

    def dump_fields(self) -> None:
        import os

        import jax.numpy as jnp

        from cup3d_tpu.io import dump as dmp

        s, cfg = self.sim, self.cfg

        def omega_mag(vel):
            om = diag.vorticity(s.grid, vel)
            return jnp.sqrt(jnp.sum(om * om, axis=-1))

        fields = dmp.collect_dump_fields_device(cfg, s.state, omega_mag)
        if fields:
            prefix = os.path.join(cfg.path4serialization, f"dump_{s.step:07d}")
            with s.profiler("Dump"):
                # async staged handoff: the sharded multi-writer runs off
                # the step loop (stream/dump.py)
                self._dumper.submit(prefix, s.time, s.grid, fields,
                                    step=s.step)

    def drain_streams(self) -> None:
        """Join all off-critical-path output (pending dumps/checkpoints,
        trace writer) — run end, and anything that must observe the files
        on disk."""
        self._dumper.wait()
        try:
            self._checkpointer.wait()
        except Exception:
            # under recovery a failed final checkpoint write must not
            # fail an otherwise-complete run: drop + count
            if self._resilience is None:
                raise
            from cup3d_tpu.obs import metrics as obs_metrics

            obs_metrics.counter("resilience.ckpt_dropped").inc()
        # close + harvest a still-open capture window before the trace
        # flush so its device-attribution record lands in this trace
        self._obs_profile.finish()
        obs_trace.TRACE.flush()

    def advance(self, dt: float) -> None:
        s = self.sim
        # step span + flight ring: wall/sections/solver-iters land in the
        # trace record (CUP3D_TRACE=1) and the postmortem ring (always)
        with self._obs.step(s.step, s.time, dt, umax=self._last_umax):
            self._maybe_dump_save()
            # ONE sanctioned host->device upload per step: every operator
            # receives dt as the same device scalar, so the steady-state
            # loop is provably transfer-clean under
            # jax.transfer_guard("disallow") (analysis/runtime.py; the
            # sanitizer contract in VALIDATION.md)
            dt_dev = device_scalar(dt, s.dtype, tag="dt-upload")
            for op in self.pipeline:
                with s.profiler(op.name):
                    op(dt_dev)
            if s.pending_parts:
                with s.profiler("SyncQoI"):
                    entry = self._emit_step_pack()
                    if self.cfg.pipelined:
                        # grouped deferred read (sim/pack.py): the
                        # transfer of K packs overlaps later steps' device
                        # work; mirrors are applied strictly FIFO on the
                        # main thread
                        self._pack_reader.emit(entry)
                    else:
                        self._consume_pack(entry)
            elif self._pack_reader:
                # a pack-less step (ADVICE r2: unreachable today in
                # pipelined mode, but the coupling is fragile): keep
                # draining so queued reads and the stale-umax chain still
                # make progress
                self._pack_reader.flush()
            s.step += 1
            s.time += dt

    def advance_megaloop(self) -> None:
        """One K-step scan dispatch (sim/megaloop.py): the whole
        per-step pipeline — dt policy, fish midline, rasterization,
        rigid update, penalization, projection, force probe — runs
        inside one jitted ``lax.scan``; the host only precomputes the
        CFL ramp, dispatches, and emits the (K, ROW) QoI block into the
        stream.  Host mirrors, logs, and failure detection are applied
        row by row at consumption (:meth:`_consume_scan_rows`), so the
        step loop's externally visible sequence is the per-step one, K
        steps late."""
        import jax.numpy as jnp

        from cup3d_tpu.sim import dtpolicy
        from cup3d_tpu.sim import megaloop as ml

        s, cfg = self.sim, self.cfg
        K = self._scan_k
        fn, row_w = self._megaloop
        base_step = s.step
        with self._obs.step(base_step, s.time, s.dt,
                            umax=self._last_umax, scan_k=K):
            self._maybe_dump_save()
            if self._scan_carry is None:
                # carry (re)seed from the host mirrors: one sanctioned
                # upload at scan entry (cold start, post-rollback,
                # post-fallback), never per step
                with sanctioned_transfer("scan-carry-upload"):
                    self._scan_carry = (
                        ml.init_fish_carry(s, s.obstacles[0])
                        if s.obstacles else ml.init_tgv_carry(s))
                    if self._scan_mesh is not None:
                        from cup3d_tpu.parallel import topology as topo

                        self._scan_carry = topo.shard_carry(
                            self._scan_carry, self._scan_mesh)
            # the CFL ramp is a pure function of the step index: host
            # precompute, shipped once per megaloop
            # jax-lint: allow(JX016, host list of Python floats in, host
            # ndarray out — no shard-resident array is gathered)
            cfl = np.asarray([
                dtpolicy.ramped_cfl(cfg.CFL, base_step + k, cfg.rampup)
                for k in range(K)
            ], dtype=s.dtype)
            with sanctioned_transfer("scan-carry-upload"):
                cfl_dev = jnp.asarray(cfl)
            with s.profiler("Megaloop"):
                carry, rows = fn(self._scan_carry, cfl_dev)
            self._scan_carry = carry
            # the megaloop donates its carry: rebind the field state to
            # the carried arrays so dumps/snapshots/fallback see live
            # buffers, never donated ones
            s.state["vel"] = carry["vel"]
            s.state["p"] = carry["p"]
            if "chi" in carry:
                s.state["chi"] = carry["chi"]
                s.state["udef"] = carry["udef"]
            with s.profiler("SyncQoI"):
                entry = self._pack_reader.pack_parts(
                    [("scan", rows.reshape(K * row_w))], s.dtype,
                    time=s.time, step=base_step, scan_k=K)
                self._pack_reader.emit(entry)
            s.step += K
        # round-19 observatory seam: attribute the K-boundary wall to
        # every x-slab shard + refresh the federation snapshot.  Host
        # scalars only (the mark is obs.trace.now()); both calls are a
        # bool/None test when unsharded and unfederated.
        from cup3d_tpu.obs import federate as FEDERATE

        if self._scan_mesh is not None:
            FEDERATE.STRAGGLER.boundary(
                range(int(self._scan_mesh.devices.size)),
                source="megaloop", sink=obs_trace.TRACE, step=base_step)
        FEDERATE.FED.on_k_boundary()

    def _emit_step_pack(self) -> dict:
        """Concatenate every device QoI the step produced (rigid state,
        forces, penalization forces) plus max|u| for a later dt into ONE
        device vector (fast path; see models/base.rigid_update_device).
        Non-pipelined runs read the entry back immediately (advance);
        pipelined runs hand it to the grouped reader."""
        import jax.numpy as jnp

        s = self.sim
        parts = s.pending_parts
        s.pending_parts = []
        umax_dev = self._max_u(s.state["vel"], s.uinf_device())
        if s.obstacles:
            # include body kinematics in the CFL scale (see
            # calc_max_timestep)
            umax_dev = jnp.maximum(
                umax_dev, jnp.max(jnp.abs(s.state["udef"]))
            )
        parts.append(("umax", umax_dev.reshape(1)))
        # pack in the solver dtype (a forced f32 cast would silently
        # truncate the rigid trajectory in a float64 configuration); the
        # stream applies its slimming policy before the device concat
        return self._pack_reader.pack_parts(parts, s.dtype, time=s.time,
                                            step=s.step)

    def _consume_pack(self, entry: dict) -> None:
        """Read one emitted pack (or reuse the worker's fetch) and refresh
        host mirrors — always called from the main thread."""
        from cup3d_tpu.models.base import (
            log_forces, store_force_qoi, unpack_forces,
        )

        s = self.sim
        vals = entry.get("vals")
        if vals is None:
            # the designed end-of-step QoI sync of the non-pipelined path
            with sanctioned_transfer("qoi-read"):
                vals = np.asarray(entry["pack"], np.float64)
        ob = s.obstacles[0] if s.obstacles else None
        off = 0
        for name, size in entry["layout"]:
            seg = vals[off:off + size]
            off += size
            if name == "rigid":
                # pipelined mode chains the rigid state on device across
                # steps: the (trailing) mirrors must not clobber it
                ob.apply_rigid_pack(seg, clear_dev=not self.cfg.pipelined)
            elif name == "penal":
                ob.penal_force = seg[:3]
                ob.penal_torque = seg[3:]
            elif name == "forces":
                store_force_qoi(ob, unpack_forces(seg))
                log_forces(s.logger, 0, entry["time"], ob)
            elif name == "umax":
                self._umax_next = float(seg[0])
            elif name == "psolve":
                # [residual, iterations] from PressureProjection — the
                # consumed values feed the obs gauges, the step trace,
                # and the flight recorder's residual history (itercap
                # trips a postmortem there)
                self._obs.note_solver(
                    int(entry.get("step", s.step)), seg[1], seg[0],
                    cap=getattr(s.poisson_solver, "maxiter", None),
                )
            elif name == "scan":
                self._consume_scan_rows(entry, seg)

    def _consume_scan_rows(self, entry: dict, seg: np.ndarray) -> None:
        """Apply one megaloop's (K, ROW) packed QoI block row by row.
        Each row is one full step's QoI — rigid mirrors, penalization
        forces, surface forces, solver stats, umax/dt/t — so the host
        mirrors, force logs, flight ring and failure detection see the
        SAME per-step sequence the per-step path produces, K steps
        late (row layouts: sim/megaloop.py FISH_ROW / TGV_ROW)."""
        from cup3d_tpu.models.base import (
            log_forces, store_force_qoi, unpack_forces,
        )
        from cup3d_tpu.sim import megaloop as ml

        s, cfg = self.sim, self.cfg
        ob = s.obstacles[0] if s.obstacles else None
        row_w = ml.FISH_ROW if ob is not None else ml.TGV_ROW
        rows = seg.reshape(-1, row_w)
        base_step = int(entry.get("step", s.step))
        for k in range(rows.shape[0]):
            row = rows[k]
            step_k = base_step + k
            if ob is not None:
                resid, iters = float(row[52]), float(row[53])
                umax, dt_k, t_k = (float(row[58]), float(row[59]),
                                   float(row[60]))
            else:
                resid, iters = float(row[0]), float(row[1])
                umax, dt_k, t_k = (float(row[2]), float(row[3]),
                                   float(row[4]))
            # fault seams replay PER STEP at consumption: the injected
            # poisons land on the host copies, so the whole detection
            # -> trigger -> rollback chain runs exactly as it does on a
            # real mid-megaloop failure (resilience/faults.py)
            if faults.fire("step.nan_velocity", step_k):
                umax = float("nan")
            if not np.isfinite(umax) or umax > cfg.uMax_allowed:
                s.logger.flush()
                reason = ("nan-velocity" if not np.isfinite(umax)
                          else "runaway-velocity")
                extra = {"step": step_k, "umax": umax,
                         "scan_k": rows.shape[0]}
                self.flight.trigger(reason, extra=extra)
                raise SimulationFailure(
                    reason,
                    f"runaway velocity: max|u|={umax:.3g} > "
                    f"uMax_allowed={cfg.uMax_allowed}", extra)
            if faults.fire("dt.collapse", step_k):
                dt_k = float("nan")
            if not np.isfinite(dt_k) or dt_k <= 0:
                extra = {"step": step_k, "dt": dt_k, "umax": umax,
                         "scan_k": rows.shape[0]}
                self.flight.trigger("dt-collapse", extra=extra)
                raise SimulationFailure(
                    "dt-collapse",
                    f"dt policy collapse: dt={dt_k:.3g}", extra)
            if ob is not None:
                ob.apply_rigid_pack(row[0:29])
                ob.myFish.quaternion_internal = np.asarray(
                    row[54:58], np.float64)
                ob.penal_force = row[29:32]
                ob.penal_torque = row[32:35]
                store_force_qoi(ob, unpack_forces(row[35:52]))
                log_forces(s.logger, 0, t_k, ob)
                if ob.bFixFrameOfRef:
                    # jax-lint: allow(JX010, host-mirror copy: transVel
                    # is the numpy mirror apply_rigid_pack just wrote —
                    # no device value crosses here)
                    s.uinf = -np.asarray(ob.transVel, np.float64)
                    s._uinf_dev = None
            if iters >= 0:  # -1 = the solver packs no stats
                self._obs.note_solver(
                    step_k, iters, resid,
                    cap=getattr(s.poisson_solver, "maxiter", None))
            # per-step flight ring records: the postmortem sees every
            # scan step, not one blurred megaloop
            self.flight.record_step({
                "step": step_k, "t": t_k, "dt": dt_k, "umax": umax,
                "wall_s": 0.0, "scan": True,
            })
            s.time = t_k
            s.dt = dt_k
            if cfg.DLM > 0:
                s.lambda_penal = cfg.DLM / dt_k
            self._umax_next = umax
            self._last_umax = umax

    def flush_packs(self) -> None:
        """Drain pending QoI packs so host mirrors are current — called
        before dumps, checkpoints, and at run end (pipelined mode)."""
        self._pack_reader.flush()

    # -- resilience hooks (resilience/recovery.py driver contract) ---------

    def _resilience_restore(self, payload: dict) -> None:
        """In-place rollback to a ``build_payload``-shaped in-memory
        snapshot (the uniform twin of ``io.checkpoint.load_checkpoint``,
        reusing the live pipeline/jits so the retry costs zero
        retraces).  Fields are re-copied on the way in: the step jits
        donate them, and the engine's snapshot must survive repeated
        restores."""
        import pickle

        import jax.numpy as jnp

        s = self.sim
        s.state = {k: jnp.copy(v) for k, v in payload["fields"].items()}
        s.time = float(payload["time"])
        s.step = int(payload["step"])
        s.dt = float(payload["dt"])
        s.uinf = np.asarray(payload["uinf"], np.float64)
        s.lambda_penal = float(payload["lambda_penal"])
        s.cadence.next_dump = float(payload["next_dump"])
        s.obstacles = pickle.loads(payload["obstacles"])
        for ob in s.obstacles:
            ob.sim = s
        s.pending_parts = []
        s._uinf_dev = None
        self._umax_next = None
        self._last_umax = None
        # the scan carry references the abandoned trajectory (and its
        # donated buffers): reseed from the restored mirrors on the
        # next megaloop entry
        self._scan_carry = None
        # mirrors queued from the abandoned trajectory must never apply
        self._pack_reader.abandon()
        if s.obstacles:
            self.pipeline[0](0.0)  # CreateObstacles: rebuild chi/udef

    def _resilience_zero_pressure(self) -> None:
        """Escalation stage 'zero-guess': the warm start restarts from
        p = 0 (the solvers warm-start from the live pressure field)."""
        import jax.numpy as jnp

        self.sim.state["p"] = jnp.zeros_like(self.sim.state["p"])

    def _resilience_rebuild_poisson(self, two_level=None,
                                    maxiter_mult: int = 1) -> None:
        """Escalation stages 'tile-only' / 'iter-bump': rebuild the
        Poisson solve with the two-level preconditioner dropped and/or a
        bumped iteration budget.  A deliberate one-off retrace on the
        failure path (the spectral solver is direct and ignores both)."""
        from cup3d_tpu.ops.poisson import make_poisson_solver

        s, cfg = self.sim, self.cfg
        s.poisson_solver = make_poisson_solver(
            s.grid, cfg.poissonSolver, s.dtype, tol_abs=cfg.poissonTol,
            tol_rel=cfg.poissonTolRel, maxiter=1000 * int(maxiter_mult),
            mean_constraint=cfg.bMeanConstraint, two_level=two_level,
        )
        for i, op in enumerate(self.pipeline):
            if isinstance(op, ops.PressureProjection):
                self.pipeline[i] = ops.PressureProjection(s)
        # the megaloop closed over the replaced solver: rebuild it too
        # (a second deliberate retrace, failure path only)
        self._megaloop = None
        self._scan_carry = None

    def simulate(self) -> None:
        from cup3d_tpu.resilience.recovery import RecoveryEngine

        s, cfg = self.sim, self.cfg
        eng = RecoveryEngine.install(self)
        try:
            while True:
                # capture-window hook at the loop top: for the megaloop
                # this is a K boundary, so a profiler window brackets
                # whole scan dispatches (disabled: one branch)
                self._obs_profile.on_step(s.step)
                try:
                    scan_now = self._scan_ready()
                    if scan_now:
                        if eng is not None and eng.snapshot_due(s.step):
                            # K-boundary snapshot consistency: the
                            # engine pickles host obstacle mirrors, so
                            # they must be current (equal to the carry)
                            # before the cadence snapshot fires
                            self.flush_packs()
                    elif self._scan_carry is not None:
                        # leaving scan mode (step-budget tail, recovery
                        # retreat): drain the stream so mirrors, time
                        # and dt are current for the per-step path
                        self.flush_packs()
                        self._scan_carry = None
                except Exception as e:
                    # a flush consumes queued scan rows and can surface
                    # a latched in-flight failure — same recovery path
                    if eng is not None and eng.handle_failure(e):
                        continue
                    raise
                if eng is not None and eng.on_loop_top():
                    continue  # rolled back: restart the iteration
                try:
                    if scan_now:
                        if cfg.verbose:
                            print(f"cup3d_tpu: steps {s.step}.."
                                  f"{s.step + self._scan_k - 1} "
                                  f"(scan K={self._scan_k}), "
                                  f"time: {s.time:f}")
                        self.advance_megaloop()
                    else:
                        dt = self.calc_max_timestep()
                        if cfg.verbose:
                            print(f"cup3d_tpu: step: {s.step}, "
                                  f"time: {s.time:f}, dt: {dt:.3e}")
                        self.advance(dt)
                except Exception as e:
                    if eng is not None and eng.handle_failure(e):
                        continue  # rolled back: retry from the snapshot
                    raise
                done_t = cfg.tend > 0 and s.time >= cfg.tend - 1e-12
                done_n = cfg.nsteps > 0 and s.step >= cfg.nsteps
                if done_t or done_n:
                    break
            self.flush_packs()
            self.drain_streams()
            s.logger.flush()
        finally:
            if eng is not None:
                eng.uninstall()
