"""AMR simulation driver: the adaptive counterpart of sim/simulation.py
(reference Simulation + adaptMesh, main.cpp:15161-15326, 15179-15200).

Differences from the uniform driver are exactly the reference's:

- five conceptual fields live on a block forest; here one dict of
  (nb, bs, bs, bs[, 3]) arrays that are *re-laid-out* on adaptation
  (grid/adapt.py) instead of surgically edited;
- the mesh adapts every ``ADAPT_EVERY`` steps (and each of the first 10),
  tagging on max |vorticity| with grad-chi forcing near bodies
  (main.cpp:15314, 8540-8602);
- startup runs 3*levelMax rounds of {adapt; re-create obstacles; re-IC}
  so the initial grid converges onto the body (main.cpp:15172-15177);
- the Poisson solve is the getZ-preconditioned BiCGSTAB (there is no
  spectral shortcut on a multi-level mesh).

Single-device runs are CAPACITY-BUCKETED (grid/bucket.py): every block
array pads up a geometric capacity ladder and all topology data (gather
tables, per-block h, cell volumes/centers, the coarse block graph)
travels as traced jit ARGUMENTS, so a regrid that stays within a bucket
reuses every compiled executable — zero retraces — and only pays the
host table build (itself memoized by octree signature, so ping-pong
regrids A->B->A skip even that).  CUP3D_BUCKET=0 restores the legacy
retrace-per-regrid path (the equivalence baseline in tests); the
sharded-forest path keeps its closure-style rebuild (per-shard scale is
bounded, and its duck-typed tables are not pytrees) — the reference's
"re-_Setup all synchronizers" cost model (main.cpp:5153-5157).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.analysis.runtime import device_scalar, sanctioned_transfer
from cup3d_tpu.config import SimulationConfig, parse_factory
from cup3d_tpu.grid import adapt as ad
from cup3d_tpu.grid.blocks import BlockGrid, assemble_vector_lab
from cup3d_tpu.grid.flux import build_flux_tables
from cup3d_tpu.grid.octree import Octree, TreeConfig
from cup3d_tpu.grid.uniform import BC
from cup3d_tpu.io.logging import BufferedLogger, Profiler
from cup3d_tpu.models.base import (
    FORCE_PACK,
    RIGID_PACK,
    log_forces,
    momentum_integrals_core,
    pack_forces,
    pack_moments,
    store_force_qoi,
    unpack_forces,
    unpack_moments,
    update_penalization_forces,
    vel_unit,
)
from cup3d_tpu.ops import amr_ops
from cup3d_tpu.ops.chi import heaviside
from cup3d_tpu.ops.penalization import (
    penalize,
    per_obstacle_penalization_force,
)
from cup3d_tpu.resilience import faults
from cup3d_tpu.resilience.recovery import SimulationFailure

ADAPT_EVERY = 20  # reference cadence (main.cpp:15314)
_EPS = 1e-6

#: the complete per-topology executable bundle _rebuild assigns on the
#: forest (mesh) path.  Snapshotting these under the octree signature
#: and rebinding on a signature match is what makes within-signature
#: regrids (the refine->coarsen->refine ping-pong) retrace-free: the
#: closure-style sharded jits are only reusable for an IDENTICAL
#: topology, and equal signatures guarantee bitwise-equal tables.
_FOREST_EXEC_ATTRS = (
    "forest", "_tab1", "_tab3", "_ftab", "_solver", "_vol", "_h_col",
    "_xc", "_real_mask", "_geom", "_advdiff", "_project", "_project_2nd",
    "_penalize", "_penal_force", "_ubody", "_divnorms", "_dissipation",
    "_gradchi", "_omega_mag", "_scores", "_moments", "_maxu",
    "_megastep", "_megastep_free", "_fix_flux", "_device_tags",
)


class _ArgGeom:
    """Duck-typed BlockGrid over the bucket-padded block axis whose
    per-block spacing ``h`` is a (possibly traced) device array: the
    geometry object the bucketed executables construct from their traced
    arguments, so ops/amr_ops.py kernels embed NO topology constants in
    their lowered HLO.  ``nb`` is the static bucket capacity; padding
    blocks carry h = 1 (never divides by zero; their fields are zero, so
    every operator output on them is zero)."""

    __slots__ = ("bs", "nb", "h", "extent")

    def __init__(self, bs: int, nb: int, h, extent):
        self.bs = bs
        self.nb = nb
        self.h = h
        self.extent = extent


@jax.jit
def _penalize_j(vel, chi, ubody, lam, dt):
    return penalize(vel, chi, ubody, lam, dt)


@jax.jit
def _maxu_j(vel, uinf):
    return jnp.max(jnp.abs(vel + uinf))


from cup3d_tpu.sim.dtpolicy import (  # noqa: E402 (placed with jit helpers)
    dt_device as _dt_device_update,
    dt_device_implicit as _dt_device_update_implicit,
)


@partial(jax.jit, static_argnames=("combine", "bs"))
def _combine_obstacle_fields(sdfs, udefs, h_raw, combine=True, tab=None,
                             bs=8):
    """(n_obs, nb, ...) sdf/udef stacks -> per-obstacle chi/masked-udef +
    (optionally) the chi-weighted combined fields, in one dispatch.  The
    pipelined megastep recombines on device, so it passes combine=False.

    With ``tab`` (face tables) the chi is the reference's Towers
    construction from the halo'd SDF (ops/chi.py towers_chi, +-1h band);
    without neighbor data (sharded-forest create) the sine Heaviside
    fallback keeps the old +-2h band."""
    if tab is not None:
        from cup3d_tpu.ops.chi import towers_chi

        chis = jnp.stack(
            [
                towers_chi(tab.assemble_scalar(sdfs[i], bs), h_raw)
                for i in range(sdfs.shape[0])
            ]
        )
    else:
        chis = heaviside(sdfs, h_raw[None])
    udefs = udefs * (chis > 0)[..., None]
    if not combine:
        return chis, udefs, None, None
    chi = jnp.max(chis, axis=0)
    den = jnp.maximum(jnp.sum(chis, axis=0), _EPS)[..., None]
    udef = jnp.sum(chis[..., None] * udefs, axis=0) / den
    return chis, udefs, chi, udef


class AMRSimulation:
    """Adaptive driver.  With ``mesh`` (a 1-D jax Mesh) every block-axis
    field lives padded + sharded over the devices and all halo exchange /
    refluxing / Krylov work runs through the ShardedForest
    (parallel/forest.py) — the distributed execution mode of the
    reference's GridMPI.  Without it, single-device gather tables."""

    def __init__(self, cfg: SimulationConfig, tree: Optional[Octree] = None,
                 mesh=None):
        self.cfg = cfg
        self.mesh = mesh
        self.dtype = jnp.dtype(cfg.dtype)
        periodic = tuple(b == "periodic" for b in cfg.bc)
        if tree is None:
            tree = Octree(
                TreeConfig(
                    (cfg.bpdx, cfg.bpdy, cfg.bpdz), cfg.levelMax, periodic
                ),
                cfg.levelStart,
            )
        self.grid = BlockGrid(
            tree, cfg.extents, tuple(BC(b) for b in cfg.bc), cfg.block_size
        )
        self.state: Dict[str, jnp.ndarray] = {}
        self.obstacles: List = []
        self.time = 0.0
        self.step_idx = 0
        self.dt = 0.0
        self.uinf = np.asarray(cfg.uinf, np.float64)
        self._uinf_host_src = None    # identity key of the cached upload
        self._uinf_host_cache = None  # device mirror of self.uinf
        self.nu = cfg.nu
        self.lambda_penal = cfg.lambda_penalization
        # cached device lambda mirrors (_lambda_device): the DLM constant
        # uploads once and lambda = DLM/dt divides ON DEVICE from the
        # step's dt scalar; a static lambda uploads once per value (the
        # old per-step jnp.asarray(self.lambda_penal) was rule JX010)
        self._dlm_dev_cache = None
        self._lambda_dev_cache = None
        self._lambda_dev_val = None
        self.logger = BufferedLogger(cfg.path4serialization)
        self.profiler = Profiler()
        from cup3d_tpu.io.dump import OutputCadence

        self._cadence = OutputCadence(cfg.tdump, cfg.fdump, cfg.saveFreq)
        # end-of-step packed QoI read (forces, penalization forces, max|u|):
        # one blocking transfer instead of one per quantity (~75 ms each on
        # the tunneled TPU; same scheme as sim/simulation.py)
        self._pending_parts: List = []
        self._umax_next = None
        # device-resident max|u| scalar (the dt chain's CFL scale; see
        # _use_device_dt) — sliced from the megastep pack, never fetched
        self._umax_dev = None
        # static-AMR mode: freeze the (converged) mesh — no tagging, no
        # re-layout, no recompiles (BASELINE config #3 is a static 2-level
        # run; dynamic runs leave this True)
        self.adapt_enabled = True
        # pipelined fast path (cfg.pipelined): grouped deferred reads
        # through the async host data-plane (stream/qoi.py; the uniform
        # driver's depth-2 scheme), plus a collision fallback latch that
        # reroutes to the host path while any stale overlap pre-check is
        # non-zero.  The pack policy slims 256^3-class configs to
        # scalars-only; every emitted pack here already is.
        from cup3d_tpu.stream.qoi import PackPolicy, QoIStream

        self._pack_reader = QoIStream(
            self._consume_entry,
            policy=PackPolicy.for_cells(self.grid.nb * self.grid.bs**3),
            profiler=self.profiler,
        )
        # off-critical-path output (stream/dump.py, stream/checkpoint.py)
        from cup3d_tpu.stream.checkpoint import AsyncCheckpointer
        from cup3d_tpu.stream.dump import AsyncDumper

        self._dumper = AsyncDumper()
        self._checkpointer = AsyncCheckpointer()
        # round-9 observability (cup3d_tpu/obs/): postmortem ring always
        # on; step traces under CUP3D_TRACE=1.  Solver stats ride the
        # packed QoI reads of the host path (the megastep pack layout is
        # unchanged — pipelined traces carry mesh/stream fields only).
        from cup3d_tpu.obs import trace as obs_trace
        from cup3d_tpu.obs.flight import FlightRecorder

        obs_trace.TRACE.default_directory(cfg.path4serialization)
        self.flight = FlightRecorder(
            directory=cfg.path4serialization, run_config=cfg,
            state_probe=self._flight_state,
        )
        self._obs = obs_trace.StepObserver(
            self.profiler, flight=self.flight, stream=self._pack_reader,
            kind="amr",
        )
        # round-13 observability v2: capture windows at loop boundaries
        # (CUP3D_PROFILE=every:N) + the env-gated /metrics//health
        # exporter (CUP3D_METRICS_PORT); both disarmed by default
        from cup3d_tpu.obs import export as obs_export
        from cup3d_tpu.obs import profile as obs_profile

        obs_profile.CONTROLLER.default_directory(cfg.path4serialization)
        self._obs_profile = obs_profile.CONTROLLER
        obs_export.ensure_exporter()
        self._last_umax = None
        self._uinf_dev = None
        self._collision_hot = False
        # refinement scores dispatched one step EARLY in pipelined mode so
        # the device compute + transfer overlap the inter-step host work
        self._scores_prefetch = None
        # bucketed path binds the on-device tag decision in
        # _bind_bucket_executables; None = host tagging (forest/legacy)
        self._device_tags = None
        # capacity bucketing (module doc): single-device regrids reuse
        # compiled executables while the padded table shapes stay inside
        # a bucket; CUP3D_BUCKET=0 restores the legacy retrace path
        self._bucketing = (
            mesh is None and os.environ.get("CUP3D_BUCKET", "1") != "0"
        )
        self._table_memo: Dict = {}   # octree signature -> padded bundle
        self._exec_cache: Dict = {}   # bucket key -> jitted executables
        # octree signature -> the forest path's full executable bundle
        # (closure-style jits can only be reused for an IDENTICAL
        # topology, so the memo key is the signature, not the bucket);
        # round 18: the memo discipline lives in parallel/forest.py
        from cup3d_tpu.parallel.forest import ExecutableMemo

        self._forest_memo = ExecutableMemo(
            max_entries=4, name="forest.exec_memo")
        self._solver_core = None
        # round-10 resilience: simulate() installs a RecoveryEngine here
        # (CUP3D_RECOVER=1, the default); the Poisson escalation ladder
        # overrides these per driver (resilience/recovery.py)
        self._resilience = None
        self._poisson_two_level = None  # None = CUP3D_COARSE default
        self._poisson_maxiter = 1000
        self._rebuild()
        self._alloc_fields()

    def _flight_state(self) -> dict:
        """Driver + bucket/capacity state for a flight-recorder
        postmortem (called only at dump time)."""
        g = self.grid
        return {
            "driver": "amr",
            "blocks": int(g.nb),
            "bucket_capacity": int(getattr(self, "_cap", g.nb)),
            "bucketing": bool(self._bucketing),
            "levels": sorted(set(int(l) for l in np.asarray(g.level))),
            "table_memo_entries": len(self._table_memo),
            "exec_cache_entries": len(self._exec_cache),
            "step": self.step_idx,
            "time": self.time,
            "dt": self.dt,
            "collision_hot": bool(self._collision_hot),
            "obstacles": [type(ob).__name__ for ob in self.obstacles],
            "stream": self._pack_reader.snapshot(),
            # round 10: the async writers' health rides in postmortems
            # (latched background failures, drop counts)
            "checkpointer": self._checkpointer.health(),
            "dumper": self._dumper.health(),
        }

    # the obstacle classes address their host as `sim`; provide the same
    # attribute surface as SimulationData where they need it
    @property
    def sim(self):  # pragma: no cover - convenience alias
        return self

    @property
    def step(self) -> int:
        """SimulationData-compatible step counter (obstacle PID etc.)."""
        return self.step_idx

    def _alloc_fields(self):
        g = self.grid
        self.state = {
            "vel": self._pad(g.zeros(3, self.dtype)),
            "chi": self._pad(g.zeros(0, self.dtype)),
            "p": self._pad(g.zeros(0, self.dtype)),
            "udef": self._pad(g.zeros(3, self.dtype)),
        }

    def _pad(self, field):
        """Block-axis pad: shard padding on a device mesh, bucket-capacity
        padding on the single-device path (padding rows stay 0)."""
        if self.forest is not None:
            return self.forest.pad(field)
        if self._bucketing:
            from cup3d_tpu.grid import bucket as bk

            return bk.pad_field(field, self._cap)
        return field

    def _unpad(self, field):
        if self.forest is not None:
            return self.forest.unpad(field)
        if self._bucketing:
            return field[: self.grid.nb]
        return field

    def uinf_device(self):
        # identity-keyed upload cache: uinf is only ever REASSIGNED (the
        # fixed-frame update in advance/_consume_step_pack), so `is`
        # tracks staleness and a constant uinf costs the step loop zero
        # host->device traffic (same contract as sim/data.uinf_device)
        if self._uinf_host_src is not self.uinf:
            with sanctioned_transfer("uinf-upload"):
                self._uinf_host_cache = jnp.asarray(self.uinf, self.dtype)
            self._uinf_host_src = self.uinf
        return self._uinf_host_cache

    def _lambda_device(self, dt_j):
        """Device-resident penalization lambda for this step (same
        contract as sim/data.lambda_device): DLM > 0 divides the cached
        DLM constant by the step's device dt scalar — zero steady-state
        host->device traffic; a static lambda uploads once per value.
        The host ``lambda_penal`` mirror keeps feeding logs/checkpoints."""
        if self.cfg.DLM > 0:
            if self._dlm_dev_cache is None:
                with sanctioned_transfer("scalar-upload"):
                    self._dlm_dev_cache = jnp.asarray(
                        self.cfg.DLM, self.dtype
                    )
            return self._dlm_dev_cache / dt_j
        if self._lambda_dev_val != self.lambda_penal:
            with sanctioned_transfer("scalar-upload"):
                self._lambda_dev_cache = jnp.asarray(
                    self.lambda_penal, self.dtype
                )
            self._lambda_dev_val = self.lambda_penal
        return self._lambda_dev_cache

    # -- jitted kernels (rebuilt per layout) -------------------------------

    def _aot_content_sig(self, octree_sig) -> tuple:
        """The persistent-store content key of this layout's forest
        executables (round 21): the octree signature plus every config
        knob the closures capture (tolerances, nu, dtype, extent, mesh
        layout).  Equal keys guarantee bitwise-equal bound tables (the
        ExecutableMemo contract), so a store hit is exact; anything
        that changes the compiled body changes the key."""
        cfg = self.cfg
        return (
            octree_sig,
            int(self.grid.bs),
            str(np.dtype(self.dtype)),
            float(self.nu),
            tuple(float(v) for v in self.grid.extent),
            float(cfg.poissonTol),
            float(cfg.poissonTolRel),
            bool(cfg.bMeanConstraint),
            bool(cfg.implicitDiffusion),
            float(cfg.diffusionTol),
            float(cfg.diffusionTolRel),
            bool(cfg.bFixMassFlux),
            int(cfg.step_2nd_start),
            (tuple(self.mesh.shape.items())
             if self.mesh is not None else None),
        )

    def _rebuild(self):
        if self.mesh is None and self._bucketing:
            return self._rebuild_bucketed()
        # forest/legacy paths keep the host tagging decision
        self._device_tags = None
        g = self.grid
        cfg = self.cfg
        if self.mesh is not None:
            from cup3d_tpu.parallel.forest import cached_forest

            # within-signature regrids (the ping-pong A->B->A pattern)
            # rebind the memoized executable bundle: zero retraces, zero
            # table rebuilds (parallel/forest.py cached_forest shares
            # the key discipline)
            sig = g.signature
            memo = self._forest_memo.get(sig)
            if memo is not None:
                for k, v in memo.items():
                    setattr(self, k, v)
                return
            self.forest = cached_forest(g, self.mesh)
            geom = self.forest.geom
            # round 4: mesh mode runs the face-slab fast path too
            # (parallel/faces.py; falls back to per-ghost lab tables only
            # on degenerate closed-boundary topologies)
            self._tab1 = self.forest.face_tables(1)
            self._tab3 = self.forest.face_tables(3)
            self._ftab = self.forest.flux_tables
            self._solver = self.forest.build_poisson_solver(
                tol_abs=cfg.poissonTol, tol_rel=cfg.poissonTolRel,
                mean_constraint=cfg.bMeanConstraint,
            )
            # padded geometry arrays; cell volume is 0 on padding blocks so
            # every volume-weighted reduction ignores them, and the padding
            # rows of all fields are kept at 0 (labs of padding blocks
            # assemble to zero, so operators never write garbage there)
            self._vol = jnp.asarray(self.forest.vol, self.dtype)
            self._h_col = self._pad(
                jnp.asarray(g.h.reshape(g.nb, 1, 1, 1), self.dtype)
            )
            self._xc = self._pad(jnp.asarray(g.cell_centers(self.dtype)))
            self._real_mask = jnp.asarray(self.forest.pmask, self.dtype)
        else:
            self.forest = None
            geom = g
            # face-slab fast-path tables (grid/faces.py): every operator in
            # the step is an axis-stencil consumer, and the per-cell gather
            # tables measured ~10-80x slower on TPU (VERDICT r2 item 1)
            self._tab1 = g.face_tables(1)
            self._tab3 = g.face_tables(3)
            self._ftab = build_flux_tables(g)
            self._solver = amr_ops.build_amr_poisson_solver(
                g, tol_abs=cfg.poissonTol, tol_rel=cfg.poissonTolRel,
                maxiter=self._poisson_maxiter,
                tab=self._tab1, flux_tab=self._ftab,
                mean_constraint=cfg.bMeanConstraint,
                two_level=self._poisson_two_level,
            )
            self._h_col = jnp.asarray(
                g.h.reshape(g.nb, 1, 1, 1), self.dtype
            )
            self._vol = self._h_col**3
            self._xc = jnp.asarray(g.cell_centers(self.dtype))
            self._real_mask = None
        self._geom = geom

        # The jitted step functions take the gather tables and cell-center
        # arrays as trailing ARGUMENTS (LabTables/FluxTables are registered
        # pytrees, grid/blocks.py): closure-captured arrays are embedded
        # into the lowered HLO as constants, which at a few thousand blocks
        # made the compile payload exceed the TPU tunnel's request limit
        # (HTTP 413) and re-embedded everything on every adaptation
        # re-layout.  The sharded forest's duck-typed tables are not
        # pytrees, so that path keeps the closure style (its scale is
        # bounded by per-device shards anyway).
        # round 21: forest-bound executables persist in the AOT store
        # under (octree signature + closure-content) keys — equal keys
        # guarantee bitwise-equal bound tables, so a restarted process
        # reloads the serialized executable instead of retracing
        aot_sig = (self._aot_content_sig(g.signature)
                   if self.mesh is not None else None)

        def jit_bound(fn, *bound, donate=(), name=None):
            # donate: positional argnums of the CALLER-facing signature
            # (the bound tables sit after them, so the numbers agree on
            # both paths).  Donated args are the step state buffers the
            # caller rebinds from the return value (JX002 burn-down).
            if self.forest is not None:
                # the jit construction site lives in parallel/forest.py
                # (bind_step_executable), outside the adaptation path:
                # a NEW octree signature binds once and the bundle rides
                # _forest_memo after (zero steady-state retraces across
                # the regrid ping-pong — the JX007 burn-down)
                from cup3d_tpu.parallel.forest import bind_step_executable

                return bind_step_executable(fn, *bound, donate=donate,
                                            name=name, store_sig=aot_sig)
            # jax-lint: allow(JX007, legacy CUP3D_BUCKET=0 path kept as
            # the bucketing equivalence baseline (tests/test_bucketing);
            # production single-device runs use _rebuild_bucketed)
            jf = jax.jit(fn, donate_argnums=donate)
            return lambda *a: jf(*a, *bound)

        if cfg.implicitDiffusion:
            from cup3d_tpu.ops import diffusion as dif

            helm = dif.build_amr_helmholtz_solver(
                geom, tol_abs=cfg.diffusionTol, tol_rel=cfg.diffusionTolRel,
                tab=self._tab1, flux_tab=self._ftab,
            )
            # the Helmholtz tables travel as traced args too (ADVICE r2):
            # the closure-built helm's captured tables stay unused
            self._advdiff = jit_bound(
                lambda vel, dt, uinf, tab3, tab1, ftab:
                dif.implicit_step_blocks(
                    geom, vel, dt, self.nu, uinf, tab3,
                    lambda u, nudt: helm(
                        u, nudt, tab_arg=tab1, flux_arg=ftab
                    ),
                ),
                self._tab3, self._tab1, self._ftab,
                donate=(0,), name="advdiff_imp",
            )
        else:
            self._advdiff = jit_bound(
                lambda vel, dt, uinf, tab3, ftab: amr_ops.rk3_step_blocks(
                    geom, vel, dt, self.nu, uinf, tab3, ftab
                ),
                self._tab3, self._ftab,
                donate=(0,), name="advdiff",
            )
        # with_stats: (vel, p, [resid, iters]) — the stats vector joins
        # the end-of-step packed QoI read (zeros on the stats-less
        # forest solver), so solver telemetry never adds a host sync
        self._project = jit_bound(
            lambda vel, dt, chi, udef, p_old, tab1, ftab:
            amr_ops.project_blocks(
                geom, vel, dt, self._solver, tab1, ftab, chi, udef,
                p_init=p_old, with_stats=True,
            ),
            self._tab1, self._ftab,
            donate=(0, 4), name="project",
        )
        self._project_2nd = jit_bound(
            lambda vel, dt, chi, udef, p_old, tab1, ftab:
            amr_ops.project_blocks(
                geom, vel, dt, self._solver, tab1, ftab, chi, udef,
                p_init=p_old, second_order=True, with_stats=True,
            ),
            self._tab1, self._ftab,
            donate=(0, 4), name="project_2nd",
        )
        self._penalize = _penalize_j
        self._penal_force = jit_bound(
            lambda vn, vo, chis, dt, cms, vol, xc:
            per_obstacle_penalization_force(vn, vo, chis, dt, vol, xc, cms),
            self._vol, self._xc, name="penal_force",
        )
        # ALL obstacles' force QoI in one (n_obs, FORCE_PACK) host read per
        # step
        # per-obstacle rigid+deformation velocity field from the cached
        # device cell centers (avoids Obstacle.body_velocity_field's host
        # rebuild of cell_centers every step)
        self._ubody = jit_bound(
            lambda udef, cm, ut, om, xc: ut
            + jnp.cross(jnp.broadcast_to(om, xc.shape), xc - cm)
            + udef,
            self._xc, name="ubody",
        )
        self._divnorms = jit_bound(
            lambda vel, tab1: amr_ops.divergence_norms_blocks(geom, vel, tab1),
            self._tab1, name="divnorms",
        )
        self._dissipation = jit_bound(
            lambda vel, tab1: amr_ops.dissipation_blocks(
                geom, vel, self.nu, tab1
            ),
            self._tab1, name="dissipation",
        )
        self._gradchi = jit_bound(
            lambda chi, tab1: amr_ops.grad_blocks(
                geom, tab1.assemble_scalar(chi, g.bs), tab1.width
            ),
            self._tab1, name="gradchi",
        )
        self._omega_mag = jit_bound(
            lambda vel, tab1: jnp.sqrt(
                jnp.sum(
                    amr_ops.curl_blocks(
                        geom, tab1.assemble_vector(vel, g.bs), tab1.width
                    )
                    ** 2,
                    axis=-1,
                )
            ),
            self._tab1, name="omega_mag",
        )

        self._scores = jit_bound(
            lambda vel, chi, tab1: (
                amr_ops.vorticity_score(geom, vel, tab1),
                amr_ops.gradchi_mask(geom, chi, tab1),
            ),
            self._tab1, name="scores",
        )

        if cfg.pipelined:
            self._build_megastep(geom)

        self._moments = jit_bound(
            lambda chis, vel, cms, xc, vol: jnp.stack(
                [
                    pack_moments(
                        momentum_integrals_core(xc, vol, c, vel, cms[i])
                    )
                    for i, c in enumerate(chis)
                ]
            ),
            self._xc, self._vol, name="moments",
        )

        self._maxu = _maxu_j

        if cfg.bFixMassFlux:
            # FixMassFlux on the forest (reference avgUx_nonUniform +
            # parabolic add, main.cpp:12199-12249): volume-weighted mean of
            # u+uinf, then u += delta * 6 eta(1-eta) (exact restoration;
            # see sim/operators.py FixMassFlux for the documented
            # divergence from the reference's 6x-amplifying constant)
            vol_total = float(np.sum(g.h**3) * g.bs**3)
            eta = jnp.asarray(
                (self._xc[..., 1] / g.extent[1]), self.dtype
            )
            profile = 6.0 * eta * (1.0 - eta)
            if self._real_mask is not None:
                # (nb_pad,1,1,1) mask broadcasts over the (nb_pad,8,8,8)
                # profile; padding rows stay 0
                profile = profile * self._real_mask

            def fix_flux(vel, uinf_x, u_target):
                u_msr = (
                    jnp.sum((vel[..., 0] + uinf_x) * self._vol) / vol_total
                )
                delta = u_target - u_msr
                return vel.at[..., 0].add(delta * profile), u_msr

            # jit construction via parallel/forest.bind_step_executable
            # (the JX007 burn-down): closes over this layout's profile +
            # vol_total; a NEW forest topology binds once and joins the
            # signature memo below; the legacy single-device path
            # retraces per regrid as the bucketing equivalence baseline
            from cup3d_tpu.parallel.forest import bind_step_executable

            self._fix_flux = bind_step_executable(
                fix_flux, name="fix_flux", store_sig=aot_sig)

        if self.mesh is not None:
            self._forest_memo.put(sig, {
                k: getattr(self, k) for k in _FOREST_EXEC_ATTRS
                if hasattr(self, k)
            })

    # -- capacity-bucketed rebuild (the single-device production path) -----

    def _rebuild_bucketed(self):
        """Bucketed twin of _rebuild (module doc): pad every topology
        artifact to the capacity ladder, memoize the padded bundle by
        octree signature, and bind jitted executables from the
        compiled-step cache keyed on (capacity, table treedef + shapes,
        donation signature) — a regrid inside a bucket reuses them all.
        """
        g, cfg = self.grid, self.cfg
        self.forest = None
        from cup3d_tpu.grid import bucket as bk
        from cup3d_tpu.grid.faces import pad_face_tables
        from cup3d_tpu.grid.flux import pad_flux_tables
        from cup3d_tpu.ops import krylov

        from cup3d_tpu.obs import metrics as obs_metrics

        sig = g.signature
        memo = self._table_memo.pop(sig, None)
        if memo is not None:
            self._table_memo[sig] = memo  # move-to-back (LRU)
        obs_metrics.counter(
            "bucket.table_memo_hits" if memo is not None
            else "bucket.table_memo_misses"
        ).inc()
        if memo is None:
            cap = bk.capacity(g.nb)
            coarse = (krylov.use_coarse_correction()
                      if self._poisson_two_level is None
                      else bool(self._poisson_two_level))
            coarse = coarse and cfg.bMeanConstraint not in (1, 3)
            h = np.ones(cap, np.float64)
            h[: g.nb] = g.h
            vol = np.zeros((cap, 1, 1, 1), np.float64)
            vol[: g.nb, 0, 0, 0] = g.h**3
            mask = np.zeros((cap, 1, 1, 1), np.float32)
            mask[: g.nb] = 1.0
            xc = np.zeros((cap, g.bs, g.bs, g.bs, 3), np.float32)
            xc[: g.nb] = g.cell_centers(np.float32)
            # corner pin slot (mean_constraint 1/3) rides as a DYNAMIC
            # index so pin relocation across regrids never retraces
            slot0 = 0
            if cfg.bMeanConstraint in (1, 3):
                slot0 = int(np.lexsort(
                    (g.ijk[:, 2], g.ijk[:, 1], g.ijk[:, 0])
                )[0])
            # per-slot octree level for the on-device regrid decision
            # (padding slots carry level 0 -> device_tags emits 'L')
            level = np.zeros(cap, np.int32)
            level[: g.nb] = [k[0] for k in g.keys]
            memo = dict(
                cap=cap,
                tab1=pad_face_tables(g.face_tables(1), g, cap),
                tab3=pad_face_tables(g.face_tables(3), g, cap),
                ftab=pad_flux_tables(build_flux_tables(g), g.bs, cap),
                graph=(krylov.block_graph_tables(g, cap=cap)
                       if coarse else None),
                h=jnp.asarray(h, self.dtype),
                vol=jnp.asarray(vol, self.dtype),
                xc=jnp.asarray(xc, self.dtype),
                mask=jnp.asarray(mask, self.dtype),
                slot0=jnp.asarray(slot0, jnp.int32),
                level=jnp.asarray(level),
            )
            self._table_memo[sig] = memo
            while len(self._table_memo) > 4:
                self._table_memo.pop(next(iter(self._table_memo)))
        self._cap = memo["cap"]
        self._tab1, self._tab3 = memo["tab1"], memo["tab3"]
        self._ftab = memo["ftab"]
        self._graph = memo["graph"]
        self._h_arr = memo["h"]
        self._vol = memo["vol"]
        self._xc = memo["xc"]
        self._real_mask = memo["mask"]
        self._slot0_dev = memo["slot0"]
        self._level_arr = memo["level"]
        self._h_col = jnp.reshape(self._h_arr, (self._cap, 1, 1, 1))
        if cfg.bFixMassFlux:
            eta = self._xc[..., 1] / g.extent[1]
            self._profile = (6.0 * eta * (1.0 - eta)) * self._real_mask
        else:
            self._profile = jnp.zeros((), self.dtype)
        self._geom = _ArgGeom(g.bs, self._cap, self._h_arr, g.extent)
        if self._solver_core is None:
            self._solver_core = amr_ops.build_amr_poisson_solver_dynamic(
                g.bs, tol_abs=cfg.poissonTol, tol_rel=cfg.poissonTolRel,
                maxiter=self._poisson_maxiter,
                mean_constraint=cfg.bMeanConstraint,
            )

        def solver(rhs, x0=None, **kw):
            # eager convenience binding (init-time IC solve); the jitted
            # executables bind the traced geometry themselves
            kw.setdefault("geom", self._geom)
            kw.setdefault("vol", self._vol)
            kw.setdefault("pmask", self._real_mask)
            kw.setdefault("graph", self._graph)
            kw.setdefault("slot0", self._slot0_dev)
            return self._solver_core(rhs, x0, **kw)

        solver.supports_stats = True  # forwards with_stats to the core
        solver.maxiter = getattr(self._solver_core, "maxiter", None)
        self._solver = solver
        key = self._bucket_key()
        ex = self._exec_cache.get(key)
        obs_metrics.counter(
            "bucket.exec_cache_hits" if ex is not None
            else "bucket.exec_cache_misses"
        ).inc()
        obs_metrics.gauge("bucket.capacity").set(self._cap)
        obs_metrics.gauge("amr.blocks").set(g.nb)
        if ex is None:
            ex = self._build_bucket_executables()
            self._exec_cache[key] = ex
        self._bind_bucket_executables(ex)
        if cfg.pipelined:
            self._build_megastep(self._geom)

    def _geo_args(self):
        """The canonical traced-geometry bundle every bucketed
        executable takes as trailing args (unused entries are DCE'd by
        XLA): tables, spacing, volumes, centers, mask, coarse graph, pin
        slot, forcing profile."""
        return (self._tab1, self._tab3, self._ftab, self._h_arr,
                self._vol, self._xc, self._real_mask, self._graph,
                self._slot0_dev, self._profile)

    def _bucket_key(self):
        """(capacity, treedef, leaf shapes/dtypes) of the geometry
        bundle: equal keys <=> jax would reuse every compiled
        executable, which is the definition of 'same bucket'."""
        leaves, treedef = jax.tree_util.tree_flatten(self._geo_args())
        shapes = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        return (self._cap, treedef, shapes)

    def _build_bucket_executables(self):
        """jit the step kernels ONCE per bucket.  Every function takes
        the _geo_args bundle as trailing traced arguments and rebuilds
        its geometry view (_ArgGeom) inside the trace — no topology
        constants in the HLO, so the compiled executables serve every
        regrid whose bucket key matches."""
        cfg = self.cfg
        nu = self.nu
        bs = self.grid.bs
        cap = self._cap
        extent = self.grid.extent
        solver_core = self._solver_core

        def geom_of(h):
            return _ArgGeom(bs, cap, h, extent)

        def solver_for(geo):
            _, _, _, h, vol, _, mask, graph, slot0, _ = geo
            return partial(solver_core, geom=geom_of(h), vol=vol,
                           pmask=mask, graph=graph, slot0=slot0)

        helm = None
        if cfg.implicitDiffusion:
            from cup3d_tpu.ops import diffusion as dif

            # closure tables are dead weight: callers pass tab_arg/
            # flux_arg + geom, so the built solve carries no topology
            helm = dif.build_amr_helmholtz_solver(
                self.grid, tol_abs=cfg.diffusionTol,
                tol_rel=cfg.diffusionTolRel, tab=self._tab1,
                flux_tab=self._ftab,
            )

        ex = {}

        def advdiff(vel, dt, uinf, *geo):
            tab1, tab3, ftab, h = geo[0], geo[1], geo[2], geo[3]
            g_ = geom_of(h)
            if cfg.implicitDiffusion:
                from cup3d_tpu.ops import diffusion as dif

                return dif.implicit_step_blocks(
                    g_, vel, dt, nu, uinf, tab3,
                    lambda u, nudt: helm(u, nudt, tab_arg=tab1,
                                         flux_arg=ftab, geom=g_),
                )
            return amr_ops.rk3_step_blocks(g_, vel, dt, nu, uinf, tab3,
                                           ftab)

        ex["advdiff"] = jax.jit(advdiff, donate_argnums=(0,))

        def make_project(so):
            def project(vel, dt, chi, udef, p_old, *geo):
                g_ = geom_of(geo[3])
                return amr_ops.project_blocks(
                    g_, vel, dt, solver_for(geo), geo[0], geo[2], chi,
                    udef, p_init=p_old, second_order=so, with_stats=True,
                )
            project.__name__ = "project_2nd" if so else "project"
            return jax.jit(project, donate_argnums=(0, 4))

        ex["project"] = make_project(False)
        ex["project_2nd"] = make_project(True)

        def penal_force(vn, vo, chis, dt, cms, *geo):
            return per_obstacle_penalization_force(
                vn, vo, chis, dt, geo[4], geo[5], cms
            )

        ex["penal_force"] = jax.jit(penal_force)

        def ubody(udef, cm, ut, om, *geo):
            xc = geo[5]
            return (ut + jnp.cross(jnp.broadcast_to(om, xc.shape),
                                   xc - cm) + udef)

        ex["ubody"] = jax.jit(ubody)

        def divnorms(vel, *geo):
            return amr_ops.divergence_norms_blocks(
                geom_of(geo[3]), vel, geo[0]
            )

        ex["divnorms"] = jax.jit(divnorms)

        def dissipation(vel, *geo):
            return amr_ops.dissipation_blocks(geom_of(geo[3]), vel, nu,
                                              geo[0])

        ex["dissipation"] = jax.jit(dissipation)

        def gradchi(chi, *geo):
            tab1 = geo[0]
            return amr_ops.grad_blocks(
                geom_of(geo[3]), tab1.assemble_scalar(chi, bs), tab1.width
            )

        ex["gradchi"] = jax.jit(gradchi)

        def omega_mag(vel, *geo):
            tab1 = geo[0]
            return jnp.sqrt(jnp.sum(
                amr_ops.curl_blocks(
                    geom_of(geo[3]), tab1.assemble_vector(vel, bs),
                    tab1.width
                ) ** 2,
                axis=-1,
            ))

        # jax-lint: allow(JX002, diagnostic over a persistent field (the
        # name matches the step regex via omega, not megastep))
        ex["omega_mag"] = jax.jit(omega_mag)

        def scores(vel, chi, *geo):
            g_ = geom_of(geo[3])
            return (amr_ops.vorticity_score(g_, vel, geo[0]),
                    amr_ops.gradchi_mask(g_, chi, geo[0]))

        ex["scores"] = jax.jit(scores)

        def tags(vel, chi, level, *geo):
            # on-device regrid DECISION: scores -> per-slot int8 tag in
            # one dispatch, so adapt_mesh downloads (cap,) bytes instead
            # of two full score fields (grid/adapt.py device_tags)
            g_ = geom_of(geo[3])
            vort = amr_ops.vorticity_score(g_, vel, geo[0])
            near = amr_ops.gradchi_mask(g_, chi, geo[0])
            return ad.device_tags(
                vort, near, level, cfg.Rtol, cfg.Ctol,
                cfg.levelMax, cfg.levelMaxVorticity,
                bool(cfg.bAdaptChiGradient),
            )

        ex["tags"] = jax.jit(tags)

        def moments(chis, vel, cms, *geo):
            vol, xc = geo[4], geo[5]
            return jnp.stack([
                pack_moments(
                    momentum_integrals_core(xc, vol, c, vel, cms[i])
                )
                for i, c in enumerate(chis)
            ])

        ex["moments"] = jax.jit(moments)

        if cfg.bFixMassFlux:
            def fix_flux(vel, uinf_x, u_target, *geo):
                vol, profile = geo[4], geo[9]
                vol_total = jnp.sum(vol) * bs**3
                u_msr = (
                    jnp.sum((vel[..., 0] + uinf_x) * vol) / vol_total
                )
                delta = u_target - u_msr
                return vel.at[..., 0].add(delta * profile), u_msr

            ex["fix_flux"] = jax.jit(fix_flux, donate_argnums=(0,))
        return ex

    def _bind_bucket_executables(self, ex):
        geo = self._geo_args
        self._advdiff = (
            lambda vel, dt, uinf: ex["advdiff"](vel, dt, uinf, *geo())
        )
        self._project = (
            lambda vel, dt, chi, udef, p:
            ex["project"](vel, dt, chi, udef, p, *geo())
        )
        self._project_2nd = (
            lambda vel, dt, chi, udef, p:
            ex["project_2nd"](vel, dt, chi, udef, p, *geo())
        )
        self._penalize = _penalize_j
        self._penal_force = (
            lambda vn, vo, chis, dt, cms:
            ex["penal_force"](vn, vo, chis, dt, cms, *geo())
        )
        self._ubody = (
            lambda udef, cm, ut, om:
            ex["ubody"](udef, cm, ut, om, *geo())
        )
        self._divnorms = lambda vel: ex["divnorms"](vel, *geo())
        self._dissipation = lambda vel: ex["dissipation"](vel, *geo())
        self._gradchi = lambda chi: ex["gradchi"](chi, *geo())
        self._omega_mag = lambda vel: ex["omega_mag"](vel, *geo())
        self._scores = lambda vel, chi: ex["scores"](vel, chi, *geo())
        self._device_tags = (
            lambda vel, chi:
            ex["tags"](vel, chi, self._level_arr, *geo())
        )
        self._moments = (
            lambda chis, vel, cms: ex["moments"](chis, vel, cms, *geo())
        )
        self._maxu = _maxu_j
        if self.cfg.bFixMassFlux:
            self._fix_flux = (
                lambda vel, ux, ut: ex["fix_flux"](vel, ux, ut, *geo())
            )

    # -- pipelined megastep (single-device fast path) ----------------------

    def _build_megastep(self, geom):
        """ONE jitted function for the whole obstacle step: advection ->
        vmapped device rigid update -> penalization -> projection -> force
        QoI -> packed read vector.  The AMR twin of the uniform driver's
        device fast path (models/pipeline.py UpdateObstacles +
        models/base.rigid_update_device), generalized to MULTI-obstacle by
        vmapping the rigid update; collision response stays host-side via a
        stale overlap pre-check in the pack (see advance_pipelined).

        Motivation (measured, VERDICT r2 item 5 / r3 profile): each jit
        dispatch costs ~2.5 ms over the TPU tunnel and each blocking read
        75-180 ms; the non-pipelined AMR step pays ~15 dispatches + 2
        blocking reads of pure latency.  This path pays ~1 dispatch and
        reads one pack, one step late, on a worker thread."""
        if self.forest is None and self._bucketing:
            return self._build_megastep_bucketed()
        from cup3d_tpu.models.base import (
            pack_forces, pack_moments, rigid_update_device,
        )
        from cup3d_tpu.models.collisions import overlap_count
        from cup3d_tpu.ops.surface import obstacle_probe_budget

        cfg = self.cfg
        g = self.grid
        nu = self.nu
        # probe slot budgets are STATIC inside the trace: snapshot them at
        # build time and let advance_pipelined trigger a rebuild when the
        # adaptive budget moves (code-review r4 — without this, a
        # static-mesh run freezes the generous pre-measurement prior)
        hf0 = float(g.h0 / (1 << (len(g._slot_maps) - 1)))
        self._megastep_budgets = tuple(
            obstacle_probe_budget(ob, hf0) for ob in self.obstacles
        )
        rigid_vmapped = jax.vmap(
            rigid_update_device, in_axes=(0, 0, 0, 0, None, None)
        )
        if cfg.bFixMassFlux:
            vol_total = float(np.sum(g.h**3) * g.bs**3)
            eta = jnp.asarray((self._xc[..., 1] / g.extent[1]), self.dtype)
            profile_arr = 6.0 * eta * (1.0 - eta)
        else:
            profile_arr = jnp.zeros((), self.dtype)  # unused placeholder
        helm = None
        if cfg.implicitDiffusion:
            from cup3d_tpu.ops import diffusion as dif

            # the captured tables are fallbacks only: the traced tab1/ftab
            # arguments flow through helm's tab_arg/flux_arg at call time
            helm = dif.build_amr_helmholtz_solver(
                geom, tol_abs=cfg.diffusionTol, tol_rel=cfg.diffusionTolRel,
                tab=self._tab1, flux_tab=self._ftab,
            )

        h_fine = float(g.h0 / (1 << (len(g._slot_maps) - 1)))

        def advdiff_stage(vel, uinf, dt, tab1, tab3, ftab):
            """Advection-diffusion honoring cfg.implicitDiffusion — shared
            by the obstacle and obstacle-free megasteps."""
            if cfg.implicitDiffusion:
                from cup3d_tpu.ops import diffusion as dif

                return dif.implicit_step_blocks(
                    geom, vel, dt, nu, uinf, tab3,
                    lambda u, nudt: helm(
                        u, nudt, tab_arg=tab1, flux_arg=ftab
                    ),
                )
            return amr_ops.rk3_step_blocks(geom, vel, dt, nu, uinf, tab3,
                                           ftab)

        def forcing_stage(vel, uinf, dt, vol, profile):
            """FixMassFlux / uMax_forced forcing — shared by both
            megasteps.  Returns (vel, flux_msr (1,))."""
            flux_msr = jnp.zeros(1, self.dtype)
            if cfg.bFixMassFlux:
                u_target = 2.0 / 3.0 * cfg.uMax_forced
                u_msr = jnp.sum((vel[..., 0] + uinf[0]) * vol) / vol_total
                vel = vel.at[..., 0].add((u_target - u_msr) * profile)
                flux_msr = u_msr.reshape(1)
            elif cfg.uMax_forced > 0:
                H = g.extent[1]
                accel = 8.0 * nu * cfg.uMax_forced / (H * H)
                vel = vel.at[..., 0].add(accel * dt)
            return vel, flux_msr

        def mega(vel, p, chis, udefs, sdfs, rigid, forced, blocked,
                 fixmask, slots, b0s, uinf, dt, lam, tab1, tab3, ftab,
                 xc, vol, profile, second_order):
            n_obs = chis.shape[0]
            chi = jnp.max(chis, axis=0)
            den = jnp.maximum(jnp.sum(chis, axis=0), _EPS)[..., None]
            udef = jnp.sum(chis[..., None] * udefs, axis=0) / den

            vel = advdiff_stage(vel, uinf, dt, tab1, tab3, ftab)

            # rigid update on device, all obstacles at once
            cms = rigid[:, 12:15]
            M = jnp.stack(
                [
                    pack_moments(
                        momentum_integrals_core(xc, vol, chis[i], vel, cms[i])
                    )
                    for i in range(n_obs)
                ]
            )
            out = rigid_vmapped(M, rigid, forced, blocked, uinf, dt)
            cm_new = out[:, 12:15]
            ub = (
                out[:, None, None, None, None, 0:3]
                + jnp.cross(
                    jnp.broadcast_to(
                        out[:, None, None, None, None, 3:6], udefs.shape
                    ),
                    xc[None] - out[:, None, None, None, None, 12:15],
                )
                + udefs
            )  # (n_obs, nb, bs,bs,bs, 3)
            ubody = jnp.sum(chis[..., None] * ub, axis=0) / den

            vel_old = vel
            vel = penalize(vel, chi, ubody, lam, dt)
            PF = -per_obstacle_penalization_force(
                vel, vel_old, tuple(chis[i] for i in range(n_obs)),
                dt, vol, xc, cm_new,
            )

            vel, flux_msr = forcing_stage(vel, uinf, dt, vol, profile)

            vel, p = amr_ops.project_blocks(
                geom, vel, dt, self._solver, tab1, ftab, chi, udef,
                p_init=p, second_order=second_order,
            )

            # surface-point probe per obstacle (ops/surface.py): the
            # production force measure, on the obstacle's dense window,
            # compacted to a static per-obstacle point budget
            from cup3d_tpu.ops.surface import (
                obstacle_probe_budget, probe_blocks_core,
            )

            F = jnp.stack(
                [
                    pack_forces(
                        probe_blocks_core(
                            vel, p, chis[i], sdfs[i], udefs[i],
                            slots[i], b0s[i],
                            jnp.asarray(h_fine, vel.dtype), nu,
                            cm_new[i], out[i, 0:3], out[i, 3:6],
                            max_points=self._megastep_budgets[i],
                        )
                    )
                    for i in range(n_obs)
                ]
            )

            pairs = [
                (i, j) for i in range(n_obs) for j in range(i + 1, n_obs)
            ]
            overlaps = (
                jnp.stack(
                    [
                        overlap_count(chis[i], chis[j]).astype(self.dtype)
                        for i, j in pairs
                    ]
                )
                if pairs
                else jnp.zeros(0, self.dtype)
            )

            # next step's frame velocity from the NEW rigid state, so the
            # device chain matches non-pipelined uinf semantics exactly
            nfix = jnp.sum(fixmask)
            mean_tv = jnp.sum(
                out[:, 0:3] * fixmask[:, None], axis=0
            ) / jnp.maximum(nfix, 1.0)
            uinf_next = jnp.where(nfix > 0, -mean_tv, uinf)
            umax = jnp.maximum(
                jnp.max(jnp.abs(vel + uinf_next)),
                jnp.max(jnp.abs(udef)),
            ).reshape(1)
            pack = jnp.concatenate(
                [out.reshape(-1), PF.reshape(-1).astype(self.dtype),
                 F.reshape(-1), overlaps, flux_msr, umax]
            )
            return vel, p, chi, udef, uinf_next, pack

        # tables AND field-sized geometry (cell centers, volumes, forcing
        # profile) travel as jit ARGUMENTS, not closure constants — the
        # compile-payload rule of _rebuild applies here too.  The sharded
        # forest's duck-typed tables are NOT pytrees, so the mesh path
        # keeps the closure style (its per-shard scale is bounded).
        def order_dispatch(fn, tabs, donate=()):
            """jit fn once per pressure order; pick by step index at call
            time.  Forest mode closes over the (non-pytree) tables;
            single-device passes them as traced call args.  ``donate``
            names the caller-facing state argnums (vel/p) the megastep
            rebinds from its outputs (JX002 burn-down)."""
            if self.forest is not None:
                # jit construction delegated to parallel/forest.py
                # (bind_order_executables): once per NEW signature, then
                # _forest_memo — the JX007 burn-down, as in jit_bound
                from cup3d_tpu.parallel.forest import (
                    bind_order_executables,
                )

                jits = bind_order_executables(
                    fn, tabs, donate=donate,
                    store_sig=self._aot_content_sig(self.grid.signature))
                return lambda *a: jits[
                    self.step_idx >= self.cfg.step_2nd_start
                ](*a)
            # jax-lint: allow(JX007, legacy CUP3D_BUCKET=0 equivalence
            # baseline; production single-device megasteps come from the
            # compiled-step cache in _build_megastep_bucketed)
            jits = [jax.jit(partial(fn, second_order=so),
                            donate_argnums=donate)
                    for so in (False, True)]
            return lambda *a: jits[
                self.step_idx >= self.cfg.step_2nd_start
            ](*a, *tabs)

        self._megastep = order_dispatch(
            mega, (self._tab1, self._tab3, self._ftab, self._xc,
                   self._vol, profile_arr),
            donate=(0, 1),  # vel, p -> vel, p
        )

        # obstacle-free fused step (amr_tgv-style runs): advection +
        # forcing + projection + max|u| in one dispatch, same pack scheme
        def mega_free(vel, p, uinf, dt, tab1, tab3, ftab, vol, profile,
                      second_order):
            vel = advdiff_stage(vel, uinf, dt, tab1, tab3, ftab)
            vel, flux_msr = forcing_stage(vel, uinf, dt, vol, profile)
            vel, p = amr_ops.project_blocks(
                geom, vel, dt, self._solver, tab1, ftab,
                p_init=p, second_order=second_order,
            )
            umax = jnp.max(jnp.abs(vel + uinf)).reshape(1)
            pack = jnp.concatenate([flux_msr, umax])
            return vel, p, pack

        self._megastep_free = order_dispatch(
            mega_free, (self._tab1, self._tab3, self._ftab, self._vol,
                        profile_arr),
            donate=(0, 1),  # vel, p -> vel, p
        )

    def _build_megastep_bucketed(self):
        """Bucketed twin of _build_megastep: the megastep jits live in
        the compiled-step cache keyed by (bucket, probe budgets, n_obs),
        with all topology data as traced args — regrids within a bucket
        AND ping-pong probe-budget moves reuse compiled executables."""
        from cup3d_tpu.ops.surface import obstacle_probe_budget

        g = self.grid
        hf0 = float(g.h0 / (1 << (len(g._slot_maps) - 1)))
        self._megastep_budgets = tuple(
            obstacle_probe_budget(ob, hf0) for ob in self.obstacles
        )
        key = ("mega", self._bucket_key(), self._megastep_budgets,
               len(self.obstacles), bool(self.cfg.bFixMassFlux))
        ex = self._exec_cache.get(key)
        if ex is None:
            ex = self._build_megastep_executables(self._megastep_budgets)
            self._exec_cache[key] = ex
        jits, jits_free = ex
        self._megastep = lambda *a: jits[
            int(self.step_idx >= self.cfg.step_2nd_start)
        ](*a, *self._geo_args())
        self._megastep_free = lambda *a: jits_free[
            int(self.step_idx >= self.cfg.step_2nd_start)
        ](*a, *self._geo_args())

    def _build_megastep_executables(self, budgets):
        """The megastep bodies of _build_megastep with every topology
        array drawn from the traced _geo_args bundle (geometry view
        rebuilt inside the trace, solver bound per call)."""
        from cup3d_tpu.models.base import (
            pack_forces, pack_moments, rigid_update_device,
        )
        from cup3d_tpu.models.collisions import overlap_count
        from cup3d_tpu.ops.surface import probe_blocks_core

        cfg = self.cfg
        g = self.grid
        nu = self.nu
        bs = g.bs
        cap = self._cap
        extent = g.extent
        dtype = self.dtype
        solver_core = self._solver_core
        h_fine = float(g.h0 / (1 << (len(g._slot_maps) - 1)))
        rigid_vmapped = jax.vmap(
            rigid_update_device, in_axes=(0, 0, 0, 0, None, None)
        )
        helm = None
        if cfg.implicitDiffusion:
            from cup3d_tpu.ops import diffusion as dif

            helm = dif.build_amr_helmholtz_solver(
                g, tol_abs=cfg.diffusionTol, tol_rel=cfg.diffusionTolRel,
                tab=self._tab1, flux_tab=self._ftab,
            )

        def geom_of(h):
            return _ArgGeom(bs, cap, h, extent)

        def advdiff_stage(g_, vel, uinf, dt, tab1, tab3, ftab):
            if cfg.implicitDiffusion:
                from cup3d_tpu.ops import diffusion as dif

                return dif.implicit_step_blocks(
                    g_, vel, dt, nu, uinf, tab3,
                    lambda u, nudt: helm(u, nudt, tab_arg=tab1,
                                         flux_arg=ftab, geom=g_),
                )
            return amr_ops.rk3_step_blocks(g_, vel, dt, nu, uinf, tab3,
                                           ftab)

        def forcing_stage(vel, uinf, dt, vol, mask, profile):
            """FixMassFlux / uMax_forced forcing; padding rows stay 0
            (profile carries the real-block mask; the constant
            acceleration is masked explicitly)."""
            flux_msr = jnp.zeros(1, dtype)
            if cfg.bFixMassFlux:
                vol_total = jnp.sum(vol) * bs**3
                u_target = 2.0 / 3.0 * cfg.uMax_forced
                u_msr = jnp.sum((vel[..., 0] + uinf[0]) * vol) / vol_total
                vel = vel.at[..., 0].add((u_target - u_msr) * profile)
                flux_msr = u_msr.reshape(1)
            elif cfg.uMax_forced > 0:
                H = extent[1]
                accel = 8.0 * nu * cfg.uMax_forced / (H * H)
                vel = vel.at[..., 0].add(accel * dt * mask)
            return vel, flux_msr

        def make_mega(so):
            def mega(vel, p, chis, udefs, sdfs, rigid, forced, blocked,
                     fixmask, slots, b0s, uinf, dt, lam, *geo):
                (tab1, tab3, ftab, h, vol, xc, mask, graph, slot0,
                 profile) = geo
                g_ = geom_of(h)
                sol = partial(solver_core, geom=g_, vol=vol, pmask=mask,
                              graph=graph, slot0=slot0)
                n_obs = chis.shape[0]
                chi = jnp.max(chis, axis=0)
                den = jnp.maximum(jnp.sum(chis, axis=0), _EPS)[..., None]
                udef = jnp.sum(chis[..., None] * udefs, axis=0) / den

                vel = advdiff_stage(g_, vel, uinf, dt, tab1, tab3, ftab)

                cms = rigid[:, 12:15]
                M = jnp.stack(
                    [
                        pack_moments(
                            momentum_integrals_core(
                                xc, vol, chis[i], vel, cms[i]
                            )
                        )
                        for i in range(n_obs)
                    ]
                )
                out = rigid_vmapped(M, rigid, forced, blocked, uinf, dt)
                cm_new = out[:, 12:15]
                ub = (
                    out[:, None, None, None, None, 0:3]
                    + jnp.cross(
                        jnp.broadcast_to(
                            out[:, None, None, None, None, 3:6],
                            udefs.shape
                        ),
                        xc[None] - out[:, None, None, None, None, 12:15],
                    )
                    + udefs
                )
                ubody = jnp.sum(chis[..., None] * ub, axis=0) / den

                vel_old = vel
                vel = penalize(vel, chi, ubody, lam, dt)
                PF = -per_obstacle_penalization_force(
                    vel, vel_old, tuple(chis[i] for i in range(n_obs)),
                    dt, vol, xc, cm_new,
                )

                vel, flux_msr = forcing_stage(vel, uinf, dt, vol, mask,
                                              profile)

                vel, p = amr_ops.project_blocks(
                    g_, vel, dt, sol, tab1, ftab, chi, udef,
                    p_init=p, second_order=so,
                )

                F = jnp.stack(
                    [
                        pack_forces(
                            probe_blocks_core(
                                vel, p, chis[i], sdfs[i], udefs[i],
                                slots[i], b0s[i],
                                jnp.asarray(h_fine, vel.dtype), nu,
                                cm_new[i], out[i, 0:3], out[i, 3:6],
                                max_points=budgets[i],
                            )
                        )
                        for i in range(n_obs)
                    ]
                )

                pairs = [
                    (i, j)
                    for i in range(n_obs) for j in range(i + 1, n_obs)
                ]
                overlaps = (
                    jnp.stack(
                        [
                            overlap_count(chis[i], chis[j]).astype(dtype)
                            for i, j in pairs
                        ]
                    )
                    if pairs
                    else jnp.zeros(0, dtype)
                )

                nfix = jnp.sum(fixmask)
                mean_tv = jnp.sum(
                    out[:, 0:3] * fixmask[:, None], axis=0
                ) / jnp.maximum(nfix, 1.0)
                uinf_next = jnp.where(nfix > 0, -mean_tv, uinf)
                umax = jnp.maximum(
                    jnp.max(jnp.abs(vel + uinf_next)),
                    jnp.max(jnp.abs(udef)),
                ).reshape(1)
                pack = jnp.concatenate(
                    [out.reshape(-1), PF.reshape(-1).astype(dtype),
                     F.reshape(-1), overlaps, flux_msr, umax]
                )
                return vel, p, chi, udef, uinf_next, pack

            mega.__name__ = "mega_2nd" if so else "mega"
            return jax.jit(mega, donate_argnums=(0, 1))

        def make_mega_free(so):
            def mega_free(vel, p, uinf, dt, *geo):
                (tab1, tab3, ftab, h, vol, xc, mask, graph, slot0,
                 profile) = geo
                g_ = geom_of(h)
                sol = partial(solver_core, geom=g_, vol=vol, pmask=mask,
                              graph=graph, slot0=slot0)
                vel = advdiff_stage(g_, vel, uinf, dt, tab1, tab3, ftab)
                vel, flux_msr = forcing_stage(vel, uinf, dt, vol, mask,
                                              profile)
                vel, p = amr_ops.project_blocks(
                    g_, vel, dt, sol, tab1, ftab,
                    p_init=p, second_order=so,
                )
                umax = jnp.max(jnp.abs(vel + uinf)).reshape(1)
                pack = jnp.concatenate([flux_msr, umax])
                return vel, p, pack

            mega_free.__name__ = "mega_free_2nd" if so else "mega_free"
            return jax.jit(mega_free, donate_argnums=(0, 1))

        return ((make_mega(False), make_mega(True)),
                (make_mega_free(False), make_mega_free(True)))

    # -- obstacles ---------------------------------------------------------

    def _add_obstacles(self):
        content = self.cfg.resolved_factory_content()
        if not content:
            return
        from cup3d_tpu.models.factory import make_obstacles

        self.obstacles = make_obstacles(self, parse_factory(content))

    def create_obstacles(self, dt: float = 0.0, combine: bool = True):
        """Reference CreateObstacles (main.cpp:13589-13621) on blocks.
        Heaviside + masking + the chi-weighted combine run as ONE jitted
        dispatch over all obstacles (eagerly they cost ~10 tunnel round
        trips per step).  advance_pipelined passes combine=False: the
        megastep recombines on device, so the combined-state write here
        would be dead work (every other caller needs it)."""
        if not self.obstacles:
            return
        fixed = [ob for ob in self.obstacles if ob.bFixFrameOfRef]
        if fixed:
            self.uinf = -np.mean([ob.transVel for ob in fixed], axis=0)
        bucketed = self.forest is None and self._bucketing
        h_raw = (
            self._h_col if bucketed
            else jnp.asarray(
                self.grid.h.reshape(self.grid.nb, 1, 1, 1), self.dtype
            )
        )
        sdfs, udefs = [], []
        for ob in self.obstacles:
            ob.update_shape(self.time, dt)
            sdf, udef = ob.rasterize(self.time)  # unpadded (nb, ...)
            if udef is None:
                udef = self.grid.zeros(3, self.dtype)
            if bucketed:
                # bucket-capacity padding BEFORE the combine: the padded
                # tables assemble (cap,...) labs, and the Towers chi is
                # exactly 0 on the all-zero padding SDF (ops/chi.py), so
                # the padding invariants hold without masking
                sdf, udef = self._pad(sdf), self._pad(udef)
            sdfs.append(sdf)
            udefs.append(udef)
        if self.forest is None:
            chis, udefs, chi, udef = _combine_obstacle_fields(
                jnp.stack(sdfs), jnp.stack(udefs), h_raw, combine=combine,
                tab=self._tab1, bs=self.grid.bs,
            )
            for i, ob in enumerate(self.obstacles):
                ob.chi = chis[i]
                ob.udef = udefs[i]
                # kept for the surface-point force probe (ops/surface.py)
                ob.sdf = sdfs[i]
            if combine:
                self.state["chi"] = chi
                self.state["udef"] = udef
            return
        # mesh mode: the Towers chi needs SDF halos, which live behind the
        # sharded forest's exchange — pad first, assemble, then combine
        # (same construction as the single-device path, so sharded-vs-
        # single trajectories stay comparable)
        from cup3d_tpu.ops.chi import towers_chi

        chis_p, udefs_p = [], []
        for ob, sdf, ud in zip(self.obstacles, sdfs, udefs):
            sdf_p = self._pad(sdf)
            lab = self._tab1.assemble_scalar(sdf_p, self.grid.bs)
            chi_p = towers_chi(lab, self._h_col)
            ud_p = self._pad(ud) * (chi_p > 0)[..., None]
            ob.chi, ob.udef, ob.sdf = chi_p, ud_p, sdf_p
            chis_p.append(chi_p)
            udefs_p.append(ud_p)
        if not combine:
            return  # pipelined megastep recombines on device
        stack = jnp.stack(chis_p)
        self.state["chi"] = jnp.max(stack, axis=0)
        den = jnp.maximum(jnp.sum(stack, axis=0), _EPS)[..., None]
        self.state["udef"] = (
            sum(c[..., None] * u for c, u in zip(chis_p, udefs_p)) / den
        )

    def _obstacle_ubody(self, ob):
        # cached per (step, rigid state); penalization and the force pass
        # both consume the same field each step
        tag = (self.step_idx, tuple(ob.transVel), tuple(ob.angVel),
               tuple(ob.centerOfMass))
        cached = getattr(ob, "_ubody_cache", None)
        if cached is not None and cached[0] == tag:
            return cached[1]
        field = self._ubody(
            ob.udef,
            jnp.asarray(ob.centerOfMass, self.dtype),
            jnp.asarray(ob.transVel, self.dtype),
            jnp.asarray(ob.angVel, self.dtype),
        )
        ob._ubody_cache = (tag, field)
        return field

    def _body_velocity(self):
        chis = jnp.stack([ob.chi for ob in self.obstacles])
        num = sum(
            ob.chi[..., None] * self._obstacle_ubody(ob) for ob in self.obstacles
        )
        den = jnp.maximum(jnp.sum(chis, axis=0), _EPS)[..., None]
        return num / den

    # -- adaptation --------------------------------------------------------

    def adapt_mesh(self):
        g = self.grid
        cfg = self.cfg
        pf, self._scores_prefetch = self._scores_prefetch, None
        if pf is not None and pf[1] != g.nb:
            pf = None  # layout changed since dispatch: recompute
        if self._device_tags is not None:
            # on-device decision (grid/adapt.py device_tags): the host
            # downloads only (cap,) tags — or decodes them from the
            # prefetch pack, where they ride as exact small floats
            if pf is not None and pf[2] == "tags":
                tags = np.rint(np.asarray(pf[0], np.float64))
            else:
                tags = np.asarray(self._device_tags(
                    self.state["vel"], self.state["chi"]
                ))
            states = ad.states_from_tags(g, tags[: g.nb])
            return self._apply_states(states)
        if pf is not None and pf[2] == "scores":
            vals = np.asarray(pf[0], np.float64)
            vort, near_body = vals[: vals.shape[0] // 2], (
                vals[vals.shape[0] // 2:] > 0.5
            )
        else:
            vort, near_body = self._scores(
                self.state["vel"], self.state["chi"]
            )
        score = np.asarray(vort, np.float64)[: g.nb]
        near = np.asarray(near_body)[: g.nb]
        if cfg.bAdaptChiGradient and near.any():
            score = np.where(near, np.inf, score)
        # per-block refinement cap: levelMaxVorticity away from bodies
        cap = np.where(near, cfg.levelMax - 1, cfg.levelMaxVorticity - 1)
        states = ad.tag_states(g, score, cfg.Rtol, cfg.Ctol, cap)
        return self._apply_states(states)

    def _apply_states(self, states) -> bool:
        """Adaptation tail (plan -> transfer -> rebuild -> repad), split
        from the tagging so tests can force arbitrary regrid cycles
        (tests/test_bucketing.py drives refine->coarsen->refine through
        here and asserts the compiled-step cache absorbs them)."""
        from cup3d_tpu.obs import metrics as obs_metrics

        g = self.grid
        plan = ad.adapt(g, states)
        if plan is None:
            obs_metrics.counter("amr.regrid_noops").inc()
            return False
        obs_metrics.counter("amr.regrids").inc()
        for k in ("vel", "udef", "chi", "p"):
            self.state[k] = ad.transfer_field(
                g, plan, self._unpad(self.state[k])
            )
        self.grid = plan.new_grid
        self._rebuild()
        for k in self.state:
            self.state[k] = self._pad(self.state[k])
        return True

    # -- initialization ----------------------------------------------------

    def _ic(self):
        if self.cfg.initCond == "taylorGreen":
            from cup3d_tpu.utils.flows import taylor_green_2d

            vel = taylor_green_2d(self.grid, dtype=self.dtype)
        elif self.cfg.initCond == "vorticity":
            # coiled-vorticity IC (reference IC_vorticity,
            # main.cpp:12506-12668): omega from the coil, then
            # u_d = lap^-1(-(curl omega)_d) with the forest solver
            from cup3d_tpu.utils.flows import coil_vorticity

            g = self.grid
            om = coil_vorticity(jnp.asarray(g.cell_centers(self.dtype)))
            om = self._pad(om)
            vlab = self._tab1.assemble_vector(om, g.bs)
            curl = amr_ops.curl_blocks(self._geom, vlab, self._tab1.width)
            comps = [
                self._solver(
                    -curl[..., d], tab_arg=self._tab1, flux_arg=self._ftab
                )
                for d in range(3)
            ]
            self.state["vel"] = jnp.stack(comps, axis=-1)
            self.state["p"] = self._pad(self.grid.zeros(0, self.dtype))
            return
        else:
            vel = self.grid.zeros(3, self.dtype)
        self.state["vel"] = self._pad(vel)
        self.state["p"] = self._pad(self.grid.zeros(0, self.dtype))

    def init(self):
        """Reference init(): obstacles, IC, then 3*levelMax adaptation
        rounds to converge the initial grid (main.cpp:15163-15178)."""
        self._add_obstacles()
        if self.cfg.pipelined:
            for ob in self.obstacles:
                # stale-PID allowed (see sim/simulation.py init); roll
                # correction mutates the host rigid solve and is not
                if getattr(ob, "bCorrectRoll", False):
                    raise ValueError(
                        "pipelined mode cannot run roll-corrected "
                        "obstacles (host-side angVel mutation) — run "
                        "without -pipelined"
                    )
        self.create_obstacles()
        self._ic()
        for _ in range(3 * self.cfg.levelMax):
            changed = self.adapt_mesh()
            self.create_obstacles()
            self._ic()
            if not changed:
                break

    # -- stepping ----------------------------------------------------------

    def _use_device_dt(self) -> bool:
        """Device-resident dt chain (VERDICT r3 item 4): eligible for
        pipelined OBSTACLE-FREE runs (fish midline kinematics consume host
        time each step) terminated by step count, with no time-based dump
        cadence or mass-flux log rows that would force host reads."""
        cfg = self.cfg
        if not (cfg.pipelined and not self.obstacles and self.forest is None):
            return False
        if cfg.dt > 0 or cfg.tend > 0 or cfg.tdump > 0 or cfg.bFixMassFlux:
            return False
        if cfg.dtDevice == 0:
            return False
        return cfg.dtDevice == 1 or jax.default_backend() == "tpu"

    def _calc_dt_device(self):
        """CFL dt from the previous step's ON-DEVICE max|u| — exactly the
        non-pipelined one-step-lag policy (no staleness margin, no growth
        cap), with zero host transfers.  The runaway abort checks the
        freshest host MIRROR (stale by <= ~3*read_every steps — an abort
        tolerates lag; the dt itself never does)."""
        cfg = self.cfg
        if faults.fire("step.nan_velocity", self.step_idx):
            # injected fault: poison the host mirror so the existing
            # runaway/NaN abort below detects it (resilience/faults.py)
            self._umax_next = float("nan")
        um = self._umax_next
        if um is not None and (not np.isfinite(um) or um > cfg.uMax_allowed):
            self.logger.flush()
            reason = ("nan-velocity" if not np.isfinite(um)
                      else "runaway-velocity")
            extra = {"step": self.step_idx, "umax": um}
            # postmortem (or recovery interception) BEFORE the raise,
            # like the host-dt path below
            self.flight.trigger(reason, extra=extra)
            raise SimulationFailure(
                reason, f"runaway velocity: max|u|={um:.3g}", extra
            )
        if self._umax_dev is None:
            self._umax_dev = self._maxu(self.state["vel"], self.uinf_device())
        from cup3d_tpu.sim import dtpolicy

        cfl = dtpolicy.ramped_cfl(cfg.CFL, self.step_idx, cfg.rampup)
        hmin = float(self.grid.h.min())
        with sanctioned_transfer("scalar-upload"):
            if cfg.implicitDiffusion:
                dt = _dt_device_update_implicit(
                    self._umax_dev, jnp.asarray(cfl, self.dtype),
                    jnp.asarray(hmin, self.dtype),
                    jnp.asarray(self.nu, self.dtype),
                    jnp.asarray(self.step_idx > 10),
                )
            else:
                dt = _dt_device_update(
                    self._umax_dev, jnp.asarray(cfl, self.dtype),
                    jnp.asarray(hmin, self.dtype),
                    jnp.asarray(self.nu, self.dtype),
                )
        if self._resilience is not None:
            # retry dt halving: identity at scale 1.0, one eager device
            # multiply while recovering (no host sync either way)
            dt = self._resilience.scale_dt(dt)
        self.dt = dt
        if cfg.DLM > 0:
            self.lambda_penal = cfg.DLM / dt
        return dt

    def calc_max_timestep(self) -> float:
        cfg = self.cfg
        if self._use_device_dt():
            return self._calc_dt_device()
        hmin = float(self.grid.h.min())
        if faults.fire("step.nan_velocity", self.step_idx):
            # injected fault: poison the max|u| mirror so the EXISTING
            # NaN-umax abort below detects it (resilience/faults.py)
            self._umax_next = float("nan")
        if self._umax_next is not None:
            umax = self._umax_next
            if not cfg.pipelined:
                self._umax_next = None
            # pipelined: keep the latest consumed max|u| (the reader may
            # still be in flight), floored by the fresh host-side body
            # speed — a gait spin-up outruns the stale mirror (measured
            # blow-up at 256^3; see Obstacle.max_body_speed)
            if cfg.pipelined and self.obstacles:
                umax = max(
                    umax,
                    max(ob.max_body_speed(self.uinf)
                        for ob in self.obstacles),
                )
        else:
            # the designed once-per-step dt sync of the non-pipelined path
            with sanctioned_transfer("umax-read"):
                umax = float(
                    self._maxu(self.state["vel"], self.uinf_device())
                )
                if self.obstacles:
                    # body kinematics bound the CFL immediately (see
                    # sim/simulation.py calc_max_timestep)
                    umax = max(
                        umax,
                        float(jnp.max(jnp.abs(self.state["udef"]))),
                    )
        self._last_umax = umax  # host float already (both branches)
        if not np.isfinite(umax) or umax > cfg.uMax_allowed:
            # NaN must trip the abort too: `NaN > x` is False, and a NaN
            # umax would otherwise propagate into dt (code-review r4)
            self.logger.flush()
            # postmortem BEFORE the raise (obs/flight.py): ring, residual
            # history, bucket/capacity state, last-known-good step
            reason = ("nan-velocity" if not np.isfinite(umax)
                      else "runaway-velocity")
            extra = {"step": self.step_idx, "umax": umax}
            self.flight.trigger(reason, extra=extra)
            raise SimulationFailure(
                reason, f"runaway velocity: max|u|={umax:.3g}", extra
            )
        if cfg.dt > 0:
            self.dt = cfg.dt
        else:
            from cup3d_tpu.sim import dtpolicy

            prev_dt = self.dt
            if cfg.pipelined:
                # stale-umax margin: see sim/simulation.py calc_max_timestep
                umax = 1.5 * umax
            # reference combined advection-diffusion cap + 1e-3 CFL ramp
            # (main.cpp:15268-15281 via sim/dtpolicy.py)
            self.dt = dtpolicy.dt_host(hmin, self.nu, umax, cfg.CFL,
                                       self.step_idx, cfg.rampup,
                                       cfg.implicitDiffusion)
            if cfg.pipelined and prev_dt > 0:
                self.dt = min(self.dt, 1.03 * prev_dt)
            if cfg.tend > 0:
                self.dt = min(self.dt, cfg.tend - self.time)
        if self._resilience is not None:
            # retry dt halving (exact no-op at scale 1.0, so the armed
            # clean path stays bitwise-identical to CUP3D_RECOVER=0)
            self.dt = self._resilience.scale_dt(self.dt)
        if faults.fire("dt.collapse", self.step_idx):
            # injected fault: collapse dt so the existing abort trips
            self.dt = float("nan")
        if not np.isfinite(self.dt) or self.dt <= 0:
            # dt policy collapse -> postmortem + abort (obs/flight.py)
            extra = {"step": self.step_idx, "dt": self.dt, "umax": umax}
            self.flight.trigger("dt-collapse", extra=extra)
            raise SimulationFailure(
                "dt-collapse", f"dt policy collapse: dt={self.dt:.3g}",
                extra,
            )
        if cfg.DLM > 0:
            self.lambda_penal = cfg.DLM / self.dt
        return self.dt

    # -- output ------------------------------------------------------------

    def _maybe_dump_save(self):
        if self._cadence.dump_due(self.time, self.step_idx):
            self.flush_packs()  # host mirrors current before output
            self.dump_fields()
        if self._cadence.save_due(self.step_idx):
            self.flush_packs()
            with self.profiler("Checkpoint"):
                # async snapshot: fields stage via copy_to_host_async and
                # serialize on the writer thread (stream/checkpoint.py)
                self._save_checkpoint_guarded()

    def _save_checkpoint_guarded(self):
        """Round-10 degradation policy (see sim/simulation.py): under
        recovery a surfaced background-write failure falls back to one
        synchronous atomic write, then drops + counts — output must
        never kill the step loop.  Legacy behavior without recovery."""
        from cup3d_tpu.obs import metrics as obs_metrics

        try:
            self._checkpointer.save(self)
        except Exception:
            if self._resilience is None:
                raise
            obs_metrics.counter("resilience.ckpt_sync_fallbacks").inc()
            try:
                from cup3d_tpu.io.checkpoint import save_checkpoint

                save_checkpoint(self)
            except Exception:
                obs_metrics.counter("resilience.ckpt_dropped").inc()

    def dump_fields(self):
        import os

        from cup3d_tpu.io import dump as dmp

        state_view = {k: self._unpad(v) for k, v in self.state.items()}
        fields = dmp.collect_dump_fields_device(
            self.cfg, state_view,
            lambda _vel: self._unpad(self._omega_mag(self.state["vel"])),
        )
        if fields:
            prefix = os.path.join(
                self.cfg.path4serialization, f"dump_{self.step_idx:07d}"
            )
            with self.profiler("Dump"):
                # async staged handoff: the sharded multi-writer runs off
                # the step loop (stream/dump.py).  The grid object handed
                # over is this step's layout — adaptation replaces, never
                # mutates, the BlockGrid, so the snapshot stays coherent.
                self._dumper.submit(prefix, self.time, self.grid, fields,
                                    step=self.step_idx)

    def drain_streams(self):
        """Join all off-critical-path output (pending dumps/checkpoints,
        trace writer) — run end, and anything that must observe the files
        on disk."""
        from cup3d_tpu.obs import trace as obs_trace

        self._dumper.wait()
        try:
            self._checkpointer.wait()
        except Exception:
            # under recovery a failed final checkpoint write must not
            # fail an otherwise-complete run: drop + count
            if self._resilience is None:
                raise
            from cup3d_tpu.obs import metrics as obs_metrics

            obs_metrics.counter("resilience.ckpt_dropped").inc()
        # close + harvest a still-open capture window before the trace
        # flush so its device-attribution record lands in this trace
        self._obs_profile.finish()
        obs_trace.TRACE.flush()

    def _log_diagnostics(self):
        """div.txt/energy.txt rows every freqDiagnostics steps — shared by
        all three advance paths.  Off the hot path by construction: the
        production configs run freqDiagnostics=0 (bench.py), so the two
        blocking reads here cost their round trips on diagnostic steps
        only."""
        freq = self.cfg.freqDiagnostics
        if freq <= 0 or self.step_idx % freq:
            return
        with self.profiler("Diagnostics"):
            total, peak = self._divnorms(self.state["vel"])
            self.logger.write(
                "div.txt",
                f"{self.step_idx} {self.time:.8e} {float(total):.8e}"
                f" {float(peak):.8e}\n",
            )
            d = self._dissipation(self.state["vel"])
            self.logger.write(
                "energy.txt",
                f"{self.time:.8e} {float(d['kinetic_energy']):.8e} "
                f"{float(d['enstrophy']):.8e}"
                f" {float(d['dissipation_rate']):.8e}\n",
            )

    def advance(self, dt: float):
        # step span + flight ring around whichever stepping path runs:
        # the record carries the pre-step topology (nb/bucket) so regrid
        # and bucket transitions are visible across consecutive records
        extra = {"nb": int(self.grid.nb)}
        if self._bucketing and hasattr(self, "_cap"):
            extra["bucket_capacity"] = int(self._cap)
        if self._last_umax is not None:
            extra["umax"] = float(self._last_umax)
        with self._obs.step(self.step_idx, self.time, dt, **extra) as late:
            try:
                if self.cfg.pipelined and not self._collision_hot:
                    if self.obstacles:
                        return self.advance_pipelined(dt)
                    return self.advance_pipelined_free(dt)
                return self._advance_host(dt)
            finally:
                if int(self.grid.nb) != extra["nb"]:
                    late["regrid"] = True
                    late["nb_post"] = int(self.grid.nb)

    def _advance_host(self, dt: float):
        """Non-pipelined stepping (also the collision fallback path)."""
        if self._pack_reader:
            # entering the host path from pipelined mode (collision
            # fallback or mode switch): mirrors must be current and the
            # device chains dropped
            self.flush_packs()
            for ob in self.obstacles:
                ob._dev_rigid = None
            self._uinf_dev = None
        s = self.state
        dt_j = device_scalar(dt, self.dtype, tag="dt-upload")
        uinf = self.uinf_device()

        self._maybe_dump_save()
        if self.adapt_enabled and (
            self.step_idx < 10 or self.step_idx % ADAPT_EVERY == 0
        ):
            with self.profiler("AdaptMesh"):
                self.adapt_mesh()

        with self.profiler("CreateObstacles"):
            self.create_obstacles(dt)
        with self.profiler("AdvectionDiffusion"):
            s["vel"] = self._advdiff(s["vel"], dt_j, uinf)
        if self.obstacles:
            with self.profiler("UpdateObstacles"):
                n_obs = len(self.obstacles)
                cms = jnp.asarray(
                    np.stack([ob.centerOfMass for ob in self.obstacles]),
                    self.dtype,
                )
                M_dev = self._moments(
                    tuple(ob.chi for ob in self.obstacles), s["vel"], cms
                ).reshape(-1)
                # piggyback the collision pre-check (overlap cell count per
                # pair) on the moments read: one transfer serves both
                pairs = [
                    (i, j) for i in range(n_obs) for j in range(i + 1, n_obs)
                ]
                if pairs:
                    from cup3d_tpu.models.collisions import overlap_count

                    cnts = jnp.stack(
                        [
                            overlap_count(
                                self.obstacles[i].chi, self.obstacles[j].chi
                            ).astype(self.dtype)
                            for i, j in pairs
                        ]
                    )
                    # the designed once-per-step moments sync of the
                    # non-pipelined obstacle path (the pipelined megastep
                    # streams these rows through the QoI pack instead)
                    with sanctioned_transfer("moments-read"):
                        vals = np.asarray(jnp.concatenate([M_dev, cnts]),
                                          np.float64)
                    precheck = {
                        p: float(v)
                        for p, v in zip(pairs, vals[n_obs * 19:])
                    }
                else:
                    with sanctioned_transfer("moments-read"):
                        vals = np.asarray(M_dev, np.float64)
                    precheck = {}
                self._overlap_now = any(v > 0 for v in precheck.values())
                M = vals[: n_obs * 19].reshape(n_obs, 19)
                for ob, row in zip(self.obstacles, M):
                    ob.compute_velocities(unpack_moments(row))
                    ob.update(dt)
            with self.profiler("Penalization"):
                if len(self.obstacles) > 1:
                    from cup3d_tpu.models.collisions import (
                        prevent_colliding_obstacles,
                    )

                    prevent_colliding_obstacles(
                        self.obstacles,
                        [self._obstacle_ubody(ob) for ob in self.obstacles],
                        self._gradchi,
                        self._xc,
                        dt,
                        precheck_counts=precheck,
                    )
                vel_old = s["vel"]
                s["vel"] = self._penalize(
                    vel_old, s["chi"], self._body_velocity(),
                    self._lambda_device(dt_j), dt_j,
                )
                PF = update_penalization_forces(
                    self.obstacles, self._penal_force, s["vel"], vel_old,
                    dt, self.dtype,
                )
                self._pending_parts.append(("penal", PF.reshape(-1)))
        if self.cfg.bFixMassFlux:
            with self.profiler("FixMassFlux"):
                self._fix_mass_flux()
        elif self.cfg.uMax_forced > 0:
            # constant streamwise acceleration (ExternalForcing,
            # main.cpp:10581-10596); padding rows stay 0
            H = self.grid.extent[1]
            accel = 8.0 * self.nu * self.cfg.uMax_forced / (H * H)
            add = accel * dt
            if self._real_mask is not None:
                add = add * self._real_mask
            s["vel"] = s["vel"].at[..., 0].add(
                add if np.ndim(add) else float(add)
            )
        with self.profiler("PressureProjection"):
            # warm-start the Krylov solve from the previous pressure; after
            # step_2nd_start use the reference's increment form
            # (main.cpp:15087-15100)
            proj = (
                self._project_2nd
                if self.step_idx >= self.cfg.step_2nd_start
                else self._project
            )
            s["vel"], s["p"], psolve = proj(
                s["vel"], dt_j, s["chi"], s["udef"], s["p"]
            )
            # [resid, iters] joins the end-of-step packed read: solver
            # telemetry for the obs layer, no extra transfer
            self._pending_parts.append(("psolve", psolve))
        if self.obstacles:
            with self.profiler("ComputeForces"):
                self._compute_forces()
        self._log_diagnostics()
        with self.profiler("SyncQoI"):
            self._consume_step_pack()
        # collision-fallback bookkeeping: the host path just measured fresh
        # overlap counts; resume the pipelined fast path once clear
        if self._collision_hot:
            latched = any(
                ob.collision_counter > 0 for ob in self.obstacles
            )
            if not latched and not getattr(self, "_overlap_now", False):
                self._collision_hot = False
        self.step_idx += 1
        self.time += dt

    # -- pipelined stepping (device megastep + depth-2 packed reads) -------

    def advance_pipelined(self, dt: float):
        """One device dispatch for the whole obstacle step; the packed QoI
        of step N is fetched by a worker thread during step N+1's device
        work (the uniform driver's depth-2 scheme, sim/simulation.py)."""
        s = self.state
        dt_j = device_scalar(dt, self.dtype, tag="dt-upload")
        self._maybe_dump_save()
        if self.adapt_enabled and (
            self.step_idx < 10 or self.step_idx % ADAPT_EVERY == 0
        ):
            with self.profiler("AdaptMesh"):
                # no flush: packs are immutable device vectors (still
                # readable after re-layout) and the rigid chains are pure
                # kinematic state, independent of the field layout; the
                # no-change case (the steady-state common one) costs only
                # the prefetched scores read
                self.adapt_mesh()
        with self.profiler("CreateObstacles"):
            self.create_obstacles(dt, combine=False)
        # the probe slot budgets are baked into the megastep trace; when
        # the adaptive budget moves (first n_surf measurement landing, or
        # band growth past the hysteresis window) retrace once
        from cup3d_tpu.ops.surface import obstacle_probe_budget

        hf = float(self.grid.h0 / (1 << (len(self.grid._slot_maps) - 1)))
        budgets = tuple(
            obstacle_probe_budget(ob, hf) for ob in self.obstacles
        )
        if budgets != self._megastep_budgets:
            self._build_megastep(self._geom)
        with self.profiler("Megastep"):
            n = len(self.obstacles)
            from cup3d_tpu.ops.surface import block_window_slots

            chis = jnp.stack([ob.chi for ob in self.obstacles])
            udefs = jnp.stack([ob.udef for ob in self.obstacles])
            sdfs = jnp.stack([ob.sdf for ob in self.obstacles])
            slots, b0s = [], []
            for ob in self.obstacles:
                s_, b0_, _ = block_window_slots(
                    # jax-lint: allow(JX010, ob.position is the host
                    # numpy mirror — a host-side copy for the window
                    # table math, no device value crosses here)
                    # jax-lint: allow(JX016, same: host numpy mirror in,
                    # host table math out — nothing shard-resident is
                    # gathered)
                    self.grid, np.asarray(ob.position), ob.length
                )
                # jax-lint: allow(JX004, the window slot tables are host-
                # computed from the body position each step; one small
                # upload per obstacle (n_obs <= 2), batching is follow-up)
                slots.append(jnp.asarray(s_))
                # jax-lint: allow(JX004, same as the slots upload above)
                b0s.append(jnp.asarray(b0_, jnp.int32))
            slots, b0s = tuple(slots), tuple(b0s)
            rigid = jnp.stack(
                [ob.rigid_state_dev(self.dtype) for ob in self.obstacles]
            )
            forced = jnp.asarray(
                np.stack([ob.bForcedInSimFrame for ob in self.obstacles])
            )
            blocked = jnp.asarray(
                np.stack([ob.bBlockRotation for ob in self.obstacles])
            )
            fixmask = jnp.asarray(
                [1.0 if ob.bFixFrameOfRef else 0.0 for ob in self.obstacles],
                self.dtype,
            )
            uinf = (
                self._uinf_dev
                if self._uinf_dev is not None
                else self.uinf_device()
            )
            vel, p, chi, udef, uinf_next, pack = self._megastep(
                s["vel"], s["p"], chis, udefs, sdfs, rigid, forced,
                blocked, fixmask, slots, b0s, uinf, dt_j,
                self._lambda_device(dt_j),
            )
            s["vel"], s["p"], s["chi"], s["udef"] = vel, p, chi, udef
            self._uinf_dev = uinf_next
            for i, ob in enumerate(self.obstacles):
                row = pack[i * RIGID_PACK:(i + 1) * RIGID_PACK]
                ob._dev_rigid = {
                    "step": self.step_idx, "pack": row, "trans": row[0:3],
                    "ang": row[3:6], "cm": row[12:15],
                }
                ob._ubody_cache = None
            nxt = self.step_idx + 1
            if self.adapt_enabled and (
                nxt < 10 or nxt % ADAPT_EVERY == 0
            ):
                # dispatch next step's refinement decision now: the
                # compute and transfer overlap this step's pack read +
                # host work (staged through the stream so its bytes are
                # counted).  Bucketed path ships (cap,) device tags;
                # forest/legacy ships the raw score fields.
                if self._device_tags is not None:
                    t = self._device_tags(s["vel"], s["chi"])
                    # -1/0/1 are exact in any float dtype
                    packed = self._pack_reader.stage(t.astype(self.dtype))
                    self._scores_prefetch = (packed, self.grid.nb, "tags")
                else:
                    vort, near = self._scores(s["vel"], s["chi"])
                    packed = self._pack_reader.stage(jnp.concatenate(
                        [vort.astype(self.dtype), near.astype(self.dtype)]
                    ))
                    self._scores_prefetch = (
                        packed, self.grid.nb, "scores"
                    )
        self._log_diagnostics()
        with self.profiler("SyncQoI"):
            npairs = n * (n - 1) // 2
            layout = [("rigid", n * RIGID_PACK), ("penal", n * 6),
                      ("forces", n * FORCE_PACK), ("overlap", npairs),
                      ("flux", 1),
                      ("umax", 1)]
            # grouped deferred read (sim/pack.py): K packs -> one device
            # concat -> one worker-thread fetch, amortizing the tunnel's
            # per-read latency; staleness bounded by ~2K steps
            self._pack_reader.emit(
                {"layout": layout, "pack": pack, "time": self.time,
                 "step": self.step_idx}
            )
            # collision staleness guard (ADVICE r3): the overlap pre-check
            # in the pack is consumed up to ~2*read_every steps late.  When
            # the (stale) host mirrors show two bodies' bounding boxes
            # within a few fine cells of contact, kick an immediate read so
            # _collision_hot latches with ~1-step staleness instead.
            if n > 1 and self._mirrors_near_contact():
                self._pack_reader.kick()
        self.step_idx += 1
        self.time += dt

    def _mirrors_near_contact(self, margin_cells: float = 6.0) -> bool:
        h_fine = float(self.grid.h.min())
        obs = self.obstacles
        for i in range(len(obs)):
            for j in range(i + 1, len(obs)):
                half = 0.5 * (obs[i].length + obs[j].length)
                d = np.abs(
                    np.asarray(obs[i].position) - np.asarray(obs[j].position)
                )
                if np.all(d < half + margin_cells * h_fine):
                    return True
        return False

    def advance_pipelined_free(self, dt: float):
        """Obstacle-free fused stepping (the amr_tgv/TGV regime): one
        dispatch per step, same grouped pack reads and scores prefetch."""
        s = self.state
        dt_j = device_scalar(dt, self.dtype, tag="dt-upload")
        self._maybe_dump_save()
        if self.adapt_enabled and (
            self.step_idx < 10 or self.step_idx % ADAPT_EVERY == 0
        ):
            with self.profiler("AdaptMesh"):
                self.adapt_mesh()
        with self.profiler("Megastep"):
            uinf = (
                self._uinf_dev
                if self._uinf_dev is not None
                else self.uinf_device()
            )
            vel, p, pack = self._megastep_free(s["vel"], s["p"], uinf, dt_j)
            s["vel"], s["p"] = vel, p
            # device dt chain: next step's CFL scale, never read back
            self._umax_dev = pack[-1]
            nxt = self.step_idx + 1
            if self.adapt_enabled and (nxt < 10 or nxt % ADAPT_EVERY == 0):
                if self._device_tags is not None:
                    t = self._device_tags(s["vel"], s["chi"])
                    packed = self._pack_reader.stage(t.astype(self.dtype))
                    self._scores_prefetch = (packed, self.grid.nb, "tags")
                else:
                    vort, near = self._scores(s["vel"], s["chi"])
                    packed = self._pack_reader.stage(jnp.concatenate(
                        [vort.astype(self.dtype), near.astype(self.dtype)]
                    ))
                    self._scores_prefetch = (
                        packed, self.grid.nb, "scores"
                    )
        self._log_diagnostics()
        with self.profiler("SyncQoI"):
            self._pack_reader.emit(
                {"layout": [("flux", 1), ("umax", 1)], "pack": pack,
                 "time": self.time, "step": self.step_idx}
            )
        self.step_idx += 1
        self.time += dt

    def flush_packs(self):
        """Drain in-flight reads + pending packs so host mirrors are
        current (dump/checkpoint/fallback boundaries)."""
        self._pack_reader.flush()

    def _consume_entry(self, entry: dict):
        vals = entry.get("vals")
        if vals is None:
            with sanctioned_transfer("qoi-read"):
                vals = np.asarray(entry["pack"], np.float64)
        off = 0
        for name, size in entry["layout"]:
            seg = vals[off:off + size]
            off += size
            if name == "rigid":
                for i, ob in enumerate(self.obstacles):
                    ob.apply_rigid_pack(
                        seg[RIGID_PACK * i:RIGID_PACK * (i + 1)],
                        clear_dev=False,
                    )
            elif name == "penal":
                for i, ob in enumerate(self.obstacles):
                    ob.penal_force = seg[6 * i:6 * i + 3]
                    ob.penal_torque = seg[6 * i + 3:6 * i + 6]
            elif name == "forces":
                for i, ob in enumerate(self.obstacles):
                    store_force_qoi(ob, unpack_forces(
                        seg[FORCE_PACK * i:FORCE_PACK * (i + 1)]))
                    log_forces(self.logger, i, entry["time"], ob)
            elif name == "overlap":
                if np.any(seg > 0):
                    # stale contact signal: reroute to the host path (fresh
                    # pre-check + collision impulse machinery) until clear;
                    # the fallback step flushes and clears device chains
                    self._collision_hot = True
            elif name == "flux":
                if self.cfg.bFixMassFlux:
                    u_target = 2.0 / 3.0 * self.cfg.uMax_forced
                    # the producing step's index, not the consuming one —
                    # host-path rows are "step time value target" too
                    self.logger.write(
                        "flux.txt",
                        f"{entry['step']} {entry['time']:.8e} "
                        f"{float(seg[0]):.8e} {u_target:.8e}\n",
                    )
            elif name == "umax":
                self._umax_next = float(seg[0])
            elif name == "psolve":
                # consumed up to ~2*read_every steps late: attribute the
                # stats to the PRODUCING step carried in the entry
                self._obs.note_solver(
                    int(entry.get("step", self.step_idx)), seg[1], seg[0],
                    cap=getattr(self._solver, "maxiter", None),
                )
        # host frame velocity from the refreshed mirrors (logs/dumps)
        fixed = [ob for ob in self.obstacles if ob.bFixFrameOfRef]
        if fixed:
            self.uinf = -np.mean([ob.transVel for ob in fixed], axis=0)

    def _consume_step_pack(self):
        """ONE blocking host read for everything the step produced
        (penalization forces, force QoI, next-dt max|u|) — the AMR twin of
        sim/simulation.py's packed read."""
        from cup3d_tpu.models.base import (
            log_forces, store_force_qoi, unpack_forces,
        )

        parts = self._pending_parts
        self._pending_parts = []
        umax_dev = self._maxu(self.state["vel"], self.uinf_device())
        if self.obstacles:
            umax_dev = jnp.maximum(
                umax_dev, jnp.max(jnp.abs(self.state["udef"]))
            )
        parts.append(("umax", umax_dev.reshape(1)))
        pack = jnp.concatenate([p[1].astype(self.dtype) for p in parts])
        # THE designed end-of-step packed QoI read of the host path: one
        # blocking transfer serves every consumer
        with sanctioned_transfer("qoi-read"):
            vals = np.asarray(pack, np.float64)
        off = 0
        for name, arr in parts:
            seg = vals[off:off + arr.shape[0]]
            off += arr.shape[0]
            if name == "penal":
                for i, ob in enumerate(self.obstacles):
                    ob.penal_force = seg[6 * i:6 * i + 3]
                    ob.penal_torque = seg[6 * i + 3:6 * i + 6]
            elif name == "forces":
                for i, ob in enumerate(self.obstacles):
                    store_force_qoi(ob, unpack_forces(
                        seg[FORCE_PACK * i:FORCE_PACK * (i + 1)]))
                    log_forces(self.logger, i, self.time, ob)
            elif name == "umax":
                self._umax_next = float(seg[0])
            elif name == "psolve":
                # [residual, iterations]: obs gauges + step trace +
                # flight residual history (itercap trips a postmortem)
                self._obs.note_solver(
                    self.step_idx, seg[1], seg[0],
                    cap=getattr(self._solver, "maxiter", None),
                )

    def _fix_mass_flux(self):
        u_target = 2.0 / 3.0 * self.cfg.uMax_forced
        vel, u_msr = self._fix_flux(
            self.state["vel"],
            jnp.asarray(self.uinf[0], self.dtype),
            jnp.asarray(u_target, self.dtype),
        )
        self.state["vel"] = vel
        self.logger.write(
            "flux.txt",
            # jax-lint: allow(JX001, designed flux.txt sync on the host
            # path; the pipelined megastep streams this row instead)
            f"{self.step_idx} {self.time:.8e} {float(u_msr):.8e}"
            f" {u_target:.8e}\n",
        )

    def _compute_forces(self):
        """Per-obstacle force/torque/power QoI from the surface-point
        probe (ops/surface.py; reference ComputeForces,
        main.cpp:12250-12503)."""
        from cup3d_tpu.ops.surface import (
            force_integrals_probe_blocks, obstacle_probe_budget,
        )

        s = self.state
        h_fine = float(self.grid.h.min())
        rows = [
            pack_forces(
                force_integrals_probe_blocks(
                    self.grid, {"vel": s["vel"], "p": s["p"]}, ob.chi,
                    ob.sdf, ob.udef, self.nu, ob.position, ob.length,
                    ob.centerOfMass, ob.transVel, ob.angVel,
                    max_points=obstacle_probe_budget(ob, h_fine),
                )
            )
            for ob in self.obstacles
        ]
        # joins the end-of-step packed read (_consume_step_pack)
        self._pending_parts.append(("forces", jnp.stack(rows).reshape(-1)))

    # -- resilience hooks (resilience/recovery.py driver contract) ---------

    def _resilience_restore(self, payload: dict):
        """In-place rollback to a ``build_payload``-shaped in-memory
        snapshot: rebuild the octree/grid from the snapshot's leaf keys
        (exactly ``io.checkpoint.load_checkpoint``'s AMR branch, minus
        the disk), rebind the compiled executables — a topology already
        seen hits the table memo and the bucketed exec cache, so the
        common rollback costs zero retraces — and restore fields/host
        scalars/obstacles."""
        import pickle

        from cup3d_tpu.grid.octree import Octree, TreeConfig

        cfg = self.cfg
        periodic = tuple(b == "periodic" for b in cfg.bc)
        tree = Octree(
            TreeConfig((cfg.bpdx, cfg.bpdy, cfg.bpdz), cfg.levelMax,
                       periodic),
            0,
        )
        tree.leaves.clear()
        for l, i, j, k in payload["leaves"]:
            tree.leaves[(int(l), int(i), int(j), int(k))] = None
        tree.assert_balanced()
        self.grid = BlockGrid(
            tree, cfg.extents, tuple(BC(b) for b in cfg.bc), cfg.block_size
        )
        self._scores_prefetch = None
        self._rebuild()
        # re-copy on the way in: the step jits donate these buffers and
        # the engine's snapshot must survive repeated restores
        self.state = {
            k: self._pad(jnp.copy(v)) for k, v in payload["fields"].items()
        }
        self.time = float(payload["time"])
        self.step_idx = int(payload["step"])
        self.dt = float(payload["dt"])
        self.uinf = np.asarray(payload["uinf"], np.float64)
        self.lambda_penal = float(payload["lambda_penal"])
        self._cadence.next_dump = float(payload["next_dump"])
        self.obstacles = pickle.loads(payload["obstacles"])
        for ob in self.obstacles:
            ob.sim = self
        self._pending_parts = []
        self._umax_next = None
        self._umax_dev = None
        self._uinf_dev = None
        self._last_umax = None
        self._collision_hot = False
        # mirrors queued from the abandoned trajectory must never apply
        self._pack_reader.abandon()
        if self.obstacles:
            self.create_obstacles(0.0)  # rebuild chi/udef/sdf on device

    def _resilience_zero_pressure(self):
        """Escalation stage 'zero-guess': the next solve warm-starts
        from p = 0 (projection warm-starts from the live p field)."""
        self.state["p"] = jnp.zeros_like(self.state["p"])

    def _resilience_rebuild_poisson(self, two_level=None,
                                    maxiter_mult: int = 1):
        """Escalation stages 'tile-only' / 'iter-bump': rebuild every
        solver-bearing executable with the two-level preconditioner
        dropped and/or a bumped iteration budget.  Clears the bucketed
        caches (the solver is baked into them) — a deliberate, counted
        retrace on the failure path only."""
        self._poisson_two_level = two_level
        self._poisson_maxiter = 1000 * int(maxiter_mult)
        self._solver_core = None
        self._exec_cache.clear()
        self._table_memo.clear()  # memo carries the coarse graph
        self._rebuild()

    def simulate(self):
        from cup3d_tpu.resilience.recovery import RecoveryEngine

        cfg = self.cfg
        eng = RecoveryEngine.install(self)
        try:
            while True:
                # capture-window hook at the loop top (disabled: one
                # branch; obs/profile.py)
                self._obs_profile.on_step(self.step_idx)
                if eng is not None and eng.on_loop_top():
                    continue  # rolled back: restart the iteration
                try:
                    dt = self.calc_max_timestep()
                    if cfg.verbose:
                        print(
                            f"cup3d_tpu[amr]: step: {self.step_idx},"
                            f" time: {self.time:f},"
                            f" dt: {dt:.3e}, blocks: {self.grid.nb}"
                        )
                    self.advance(dt)
                except Exception as e:
                    if eng is not None and eng.handle_failure(e):
                        continue  # rolled back: retry from the snapshot
                    raise
                done_t = cfg.tend > 0 and self.time >= cfg.tend - 1e-12
                done_n = cfg.nsteps > 0 and self.step_idx >= cfg.nsteps
                if done_t or done_n:
                    break
            self.flush_packs()
            self.drain_streams()
            self.logger.flush()
        finally:
            if eng is not None:
                eng.uninstall()


def make_amr_tgv_step(sim: "AMRSimulation"):
    """The obstacle-free bucketed-AMR scan body as a pure function
    ``one_step(carry, cfl_eff) -> (carry', row (TGV_ROW,))`` — the
    block-forest twin of sim/megaloop.make_tgv_step, so fleet/batch.py
    can ``vmap`` adaptive lanes exactly like uniform ones.

    The padded topology bundle (_geo_args) is frozen in the closure:
    every lane in a fleet bucket shares the template's (capacity,
    octree-signature) tables, and the body never regrids — fleet AMR
    tenants run on a frozen topology for the drain (fleet/server.py
    keys assembly on the signature, so mixed topologies land in
    different buckets).  The dt chain is the uniform policy on the
    FINEST level's spacing (the binding CFL constraint on a forest);
    no operation reduces across lanes, so the PR 9 isolation contract
    (per-lane NaN containment, bitwise freeze) carries over unchanged.
    """
    geo = sim._geo_args()
    tab1, tab3, ftab, h, vol, _, mask, graph, slot0, _ = geo
    cfg, nu, dtype = sim.cfg, sim.nu, sim.dtype
    g = sim.grid
    g_ = _ArgGeom(g.bs, sim._cap, h, g.extent)
    sol = partial(sim._solver_core, geom=g_, vol=vol, pmask=mask,
                  graph=graph, slot0=slot0)
    so = cfg.step_2nd_start == 0
    h_fine = float(np.min(g.h))
    uinf = sim.uinf_device()

    def one_step(carry, cfl_eff):
        vel, p = carry["vel"], carry["p"]
        umax, time, dtprev = carry["umax"], carry["time"], carry["dt"]
        cap_dt = (h_fine * h_fine / 6.0) / (nu + (h_fine / 6.0) * umax)
        dt = jnp.minimum(cfl_eff * h_fine / (umax + 1e-8), cap_dt)
        dt = jnp.where(dtprev > 0, jnp.minimum(dt, 1.03 * dtprev), dt)
        vel = amr_ops.rk3_step_blocks(g_, vel, dt, nu, uinf, tab3, ftab)
        vel, p, stats = amr_ops.project_blocks(
            g_, vel, dt, sol, tab1, ftab, p_init=p, second_order=so,
            with_stats=True,
        )
        umax_new = jnp.max(jnp.abs(vel + uinf))
        time_new = time + dt
        out = {"vel": vel, "p": p, "umax": umax_new, "time": time_new,
               "dt": dt}
        row = jnp.concatenate([jnp.asarray(stats, dtype), umax_new[None],
                               dt[None], time_new[None]])
        return out, row

    return one_step
