"""The operator pipeline (reference Operator ABC main.cpp:6678-6684; pipeline
order fixed in setupOperators, main.cpp:15229-15246).

Each operator wraps a jitted pure function over the state dict.  Device-side
math lives in ``cup3d_tpu.ops``; operators only orchestrate.  ``dt`` is
passed as a traced scalar so per-step dt changes never retrigger compilation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.ops import diagnostics as diag
from cup3d_tpu.ops.advection import rk3_step
from cup3d_tpu.ops.projection import project
from cup3d_tpu.sim.data import SimulationData


class Operator:
    """Base: stateful wrapper invoked once per step as op(dt)."""

    def __init__(self, sim: SimulationData):
        self.sim = sim

    @property
    def name(self) -> str:
        return type(self).__name__

    def __call__(self, dt: float) -> None:
        raise NotImplementedError


class AdvectionDiffusion(Operator):
    """Explicit RK3 advection-diffusion (main.cpp:9640-9728)."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        # donate the velocity buffer (JX002): the step maps vel -> vel, so
        # XLA aliases the update in place instead of holding two fields
        self._step = jax.jit(partial(rk3_step, sim.grid, nu=sim.nu),
                             donate_argnums=(0,))

    def __call__(self, dt):
        s = self.sim
        s.state["vel"] = self._step(s.state["vel"], dt=dt, uinf=s.uinf_device())


class AdvectionDiffusionImplicit(Operator):
    """Explicit-advection Euler + implicit diffusion solve
    (main.cpp:9849-10118).  On the uniform grid the Helmholtz system
    (I - nu dt lap) u = u* is diagonalized exactly per component
    (ops/diffusion.py), so the step is unconditionally stable with no
    Krylov iteration at all."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        from cup3d_tpu.ops import diffusion as dif

        helm = dif.build_spectral_helmholtz(sim.grid, sim.dtype)
        self._step = jax.jit(
            partial(dif.implicit_step, sim.grid, nu=sim.nu, helmholtz=helm),
            donate_argnums=(0,),  # vel -> vel: alias in place (JX002)
        )

    def __call__(self, dt):
        s = self.sim
        s.state["vel"] = self._step(s.state["vel"], dt=dt, uinf=s.uinf_device())


class ExternalForcing(Operator):
    """Constant streamwise acceleration for forced channel-type flows:
    du = 8 nu uMax / H^2 * dt (main.cpp:10581-10596)."""

    def __call__(self, dt):
        s = self.sim
        H = s.grid.extent[1]
        accel = 8.0 * s.nu * s.cfg.uMax_forced / (H * H)
        s.state["vel"] = s.state["vel"].at[..., 0].add(accel * dt)


class FixMassFlux(Operator):
    """Hold a target bulk flux by adding a parabolic streamwise profile
    (reference FixMassFlux, main.cpp:12199-12249): measure the volume
    average of u+uinf and add delta * 6 eta(1-eta) (mean exactly delta).

    Documented divergence from the reference: its aux = 6*(6*delta)*
    eta(1-eta) restores SIX times the measured deficit per step, which
    amplifies the flux error 5x per application (verified numerically) —
    a latent bug its condensed fork never exercises (the factory builds
    only StefanFish, run.sh never sets -bFixMassFlux).  We restore the
    deficit exactly."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        ny = sim.grid.shape[1]
        eta = (np.arange(ny) + 0.5) / ny  # y / y_max at cell centers
        self._profile = jnp.asarray(6.0 * eta * (1.0 - eta), dtype=sim.dtype)

        @jax.jit
        def apply(vel, uinf_x, u_target):
            u_avg_msr = jnp.mean(vel[..., 0]) + uinf_x
            delta = u_target - u_avg_msr
            aux = delta * self._profile[None, :, None]
            return vel.at[..., 0].add(aux), u_avg_msr

        self._apply = apply

    def __call__(self, dt):
        s = self.sim
        u_target = 2.0 / 3.0 * s.cfg.uMax_forced  # bulk of a parabola
        vel, u_msr = self._apply(
            s.state["vel"],
            jnp.asarray(s.uinf[0], s.dtype),
            jnp.asarray(u_target, s.dtype),
        )
        s.state["vel"] = vel
        s.logger.write(
            "flux.txt",
            # jax-lint: allow(JX001, designed flux.txt sync on the host
            # path; the pipelined AMR driver streams this same row)
            f"{s.step} {s.time:.8e} {float(u_msr):.8e} {u_target:.8e}\n",
        )


class PressureProjection(Operator):
    """RHS -> Poisson solve -> velocity correction (main.cpp:15061-15160).

    Note on the reference's 2nd-order-in-time pressure option
    (``step_2nd_start``, main.cpp:15087-15100): it solves for the pressure
    *increment* about p_old as a warm start for the Krylov solver.  With the
    exact spectral solver used here the increment and full formulations are
    algebraically identical, so the option is meaningful only for the
    iterative AMR solver (cup3d_tpu.ops.krylov), which honors it.
    """

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        grid, solver = sim.grid, sim.poisson_solver
        # iterative solvers surface (residual, iterations) as a device
        # vector that rides the end-of-step QoI pack — per-step solver
        # telemetry with zero extra syncs (obs/trace.py).  The exact
        # spectral solver has no iteration count; its path is unchanged.
        self._with_stats = bool(getattr(solver, "supports_stats", False))
        self.solver_maxiter = getattr(solver, "maxiter", None)

        # vel and p_old are the step state: donated (JX002 burn-down).
        # chi/udef persist across steps and must NOT be donated.
        @partial(jax.jit, donate_argnums=(0, 4))
        def _project(vel, chi, udef, dt, p_old):
            # previous pressure warm-starts the iterative solver
            # (main.cpp:15087-15100); the spectral solver ignores it
            return project(grid, vel, dt, solver, chi, udef, p_init=p_old,
                           with_stats=self._with_stats)

        self._project = _project

    def __call__(self, dt):
        s = self.sim
        out = self._project(
            s.state["vel"], s.state["chi"], s.state["udef"], dt, s.state["p"]
        )
        if self._with_stats:
            vel, p, stats = out
            s.pending_parts.append(("psolve", stats))
        else:
            vel, p = out
        s.state["vel"] = vel
        s.state["p"] = p


class ComputeDissipation(Operator):
    """Energy-budget diagnostics every freqDiagnostics steps
    (main.cpp:10436-10447); appends to energy.txt."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        self._diss = jax.jit(partial(diag.dissipation, sim.grid, nu=sim.nu))

    def __call__(self, dt):
        s = self.sim
        freq = s.cfg.freqDiagnostics
        if freq <= 0 or s.step % freq:
            return
        d = self._diss(s.state["vel"])
        s.logger.write(
            "energy.txt",
            # jax-lint: allow(JX001, freq-gated diagnostic: production
            # configs run freqDiagnostics=0 so this never rides the loop)
            f"{s.time:.8e} {float(d['kinetic_energy']):.8e} "
            # jax-lint: allow(JX001, freq-gated diagnostic (see above))
            f"{float(d['enstrophy']):.8e} {float(d['dissipation_rate']):.8e}\n",
        )


class ComputeDivergence(Operator):
    """Appends (step, time, sum|div u| h^3, max|div u|) to div.txt
    (main.cpp:8789-8919)."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        self._norms = jax.jit(partial(diag.divergence_norms, sim.grid))

    def __call__(self, dt):
        s = self.sim
        freq = s.cfg.freqDiagnostics
        if freq <= 0 or s.step % freq:
            return
        total, peak = self._norms(s.state["vel"])
        s.logger.write(
            "div.txt",
            # jax-lint: allow(JX001, freq-gated diagnostic: production
            # configs run freqDiagnostics=0 so this never rides the loop)
            f"{s.step} {s.time:.8e} {float(total):.8e} {float(peak):.8e}\n",
        )


def initial_conditions(sim: SimulationData) -> None:
    """InitialConditions operator (main.cpp:12506-12748): zero, Taylor-Green,
    or parabolic channel profile."""
    cfg, grid = sim.cfg, sim.grid
    kind = cfg.initCond
    if kind == "zero":
        return
    if kind == "taylorGreen":
        from cup3d_tpu.utils.flows import taylor_green_3d

        sim.state["vel"] = taylor_green_3d(grid, sim.dtype)
        return
    if kind == "vorticity":
        from cup3d_tpu.utils.flows import coil_velocity_uniform

        sim.state["vel"] = coil_velocity_uniform(grid, sim.dtype)
        return
    x = grid.cell_centers(sim.dtype)
    if kind == "channel":
        H = grid.extent[1]
        y = x[..., 1] / H
        u = 4.0 * cfg.uMax_forced * y * (1.0 - y)
        sim.state["vel"] = jnp.stack([u, jnp.zeros_like(u), jnp.zeros_like(u)], -1)
    else:
        raise ValueError(f"unknown initCond {kind!r}")
