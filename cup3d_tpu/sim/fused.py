"""The fused whole-timestep kernel: one jitted function covering the
device-side pipeline advection-diffusion -> penalization -> projection.

This is the TPU answer to the reference's operator-by-operator sweep over
blocks (Simulation::advance, main.cpp:15306-15326): instead of five separate
grid traversals with halo exchanges between them, XLA fuses the elementwise
chains and the SPMD partitioner inserts halo exchanges only where stencils
demand them.  Used by the benchmark, the multi-chip dry run, and the
obstacle-free fast path of the driver.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops.advection import rk3_step
from cup3d_tpu.ops.penalization import penalize
from cup3d_tpu.ops.projection import project


def make_step(grid: UniformGrid, nu: float, solver, with_bodies: bool = False,
              jit: bool = True, donate: bool = True):
    """Returns step(vel, dt, uinf[, chi, ubody, udef, lam]) -> (vel, p).

    All runtime scalars are traced arguments, so dt/lambda changes never
    recompile.  `with_bodies` switches in the penalization + pressure-RHS
    obstacle terms (static switch = two compiled variants at most).
    Pass jit=False to wrap the raw function yourself (e.g. with shardings).

    By default the velocity buffer is DONATED (JX002): vel -> vel aliases
    in place, so callers must rebind (`vel, p = step(vel, ...)`) and never
    touch the passed-in array again.  Pass donate=False to keep the input
    readable (comparison tests that reuse one initial condition).
    """

    if with_bodies:

        def step(vel, dt, uinf, chi, ubody, udef, lam):
            vel = rk3_step(grid, vel, dt, nu, uinf)
            vel = penalize(vel, chi, ubody, lam, dt)
            vel, p = project(grid, vel, dt, solver, chi, udef)
            return vel, p

    else:

        def step(vel, dt, uinf):
            vel = rk3_step(grid, vel, dt, nu, uinf)
            vel, p = project(grid, vel, dt, solver)
            return vel, p

    if not jit:
        return step
    return jax.jit(step, donate_argnums=(0,) if donate else ())
