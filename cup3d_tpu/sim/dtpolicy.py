"""Timestep policy shared by the uniform and AMR drivers.

Matches the reference's calcMaxTimestep (main.cpp:15268-15292) exactly:

  dtDiffusion = (implicitDiffusion && step > 10) ? 0.1
              : (1/6) h^2 / (nu + (1/6) h uMax)
  dtAdvection = h / (uMax + 1e-8)
  CFL_eff     = exp(log(1e-3)(1-x) + log(CFL) x),  x = step/rampup  (ramp)
  dt          = min(dtDiffusion, CFL_eff * dtAdvection)

The diffusive cap is NOT the pure-diffusion von-Neumann limit: the
(1/6) h uMax term in the denominator is the upwind-3 advective
dissipation, so the cap is the COMBINED advection-diffusion stability
boundary of the explicit RK3/upwind3 update.  This is what the round-4
0.25 h^2/nu cap missed — at 256^3 with the sharp Towers chi the
combined limit binds BELOW the advective CFL dt, the explicit update
is linearly unstable at the chi interface, and the run blows up
(BENCH_r04 fish256 max|u|=2.1e5).  With this cap the same config is
stable (VALIDATION.md round 5).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["ramped_cfl", "diffusion_cap", "dt_host", "dt_device",
           "dt_device_implicit"]


def ramped_cfl(cfl: float, step: int, rampup: int) -> float:
    """Log-space CFL ramp from an absolute 1e-3 (main.cpp:15275-15279)."""
    if rampup > 0 and step < rampup:
        x = step / rampup
        return math.exp(math.log(1e-3) * (1.0 - x) + math.log(cfl) * x)
    return cfl


def diffusion_cap(h: float, nu: float, umax: float,
                  implicit: bool, step: int) -> float:
    """Combined advection-diffusion stability cap (main.cpp:15269-15273)."""
    if implicit and step > 10:
        return 0.1
    return (h * h / 6.0) / (nu + (h / 6.0) * umax)


def dt_host(h: float, nu: float, umax: float, cfl: float, step: int,
            rampup: int, implicit: bool) -> float:
    """Full host-side dt = min(dtDiffusion, CFL_eff * dtAdvection)."""
    cfl_eff = ramped_cfl(cfl, step, rampup)
    dt_adv = h / (umax + 1e-8)
    return float(min(diffusion_cap(h, nu, umax, implicit, step),
                     cfl_eff * dt_adv))


@jax.jit
def dt_device(umax, cfl_eff, hmin, nu):
    """Device-resident dt (explicit diffusion): same formula, umax stays
    on device so the pipelined driver never blocks on it."""
    cap = (hmin * hmin / 6.0) / (nu + (hmin / 6.0) * umax)
    return jnp.minimum(cfl_eff * hmin / (umax + 1e-8), cap)


@jax.jit
def dt_device_implicit(umax, cfl_eff, hmin, nu, past_warmup):
    """Device-resident dt, implicit diffusion: absolute 0.1 cap once
    step > 10 (main.cpp:15270-15271), combined cap before that."""
    cap = jnp.where(
        past_warmup,
        jnp.asarray(0.1, umax.dtype),
        (hmin * hmin / 6.0) / (nu + (hmin / 6.0) * umax),
    )
    return jnp.minimum(cfl_eff * hmin / (umax + 1e-8), cap)
