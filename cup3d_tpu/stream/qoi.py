"""Streaming QoI layer: grouped, deferred device->host reads with
counters, backpressure, and per-config pack slimming.

One device->host round trip costs ~100-200 ms over the tunneled TPU and
blocking reads serialize with the dispatch stream — so reading one QoI
pack per step caps throughput at one latency per step.  Both drivers
instead emit per-step packs into a :class:`QoIStream`, which every
``read_every`` steps concatenates them ON DEVICE into one vector, starts
an ASYNC host copy, and consumes completed groups opportunistically.
Entries are applied strictly FIFO via the driver's consume callback, on
the main thread.

The stream is THREADLESS (round-4 redesign, VERDICT r3 item 4): the old
scheme fetched each group on a worker thread whose blocking
``np.asarray`` was starved by the main thread's dispatch loop (GIL) and
serialized with tunnel traffic — measured 1.5-4 s per group read while
stepping.  Measured on the same tunnel: ``copy_to_host_async``
prefetches the value to host (a later ``np.asarray`` costs ~0.1 ms) and
``x.is_ready()`` is a local ~0.03 ms poll.  So the stream keeps a FIFO
of in-flight async-copied batches and drains the completed prefix at
each emit; nothing blocks until ``max_inflight`` groups are outstanding,
and the only blocking wait is genuine backpressure (the device has
fallen ``max_inflight * read_every`` steps behind the host).

Host-mirror staleness is bounded by ~(1 + max_inflight) * read_every
steps; the drivers' device-resident dt chain (or, on the host-dt path,
their dt-growth bound and runaway abort) guards stability against the
stale max|u| (see VALIDATION.md, "stream subsystem contract").

Round-6 additions (the ``stream/`` subsystem, ISSUE 1):

- **counters** — every stream keeps ``stats`` (packs emitted, groups
  started/read, bytes streamed, stall/read seconds, peak groups in
  flight) surfaced in the bench JSON, so host-read cost is attributed
  explicitly instead of hiding inside whichever operator forces a sync;
- **stall attribution** — the backpressure wait (device behind host) is
  timed into ``stats['stall_s']`` and, when the stream is given a
  profiler, into its own ``StreamWait`` section: ``SyncQoI`` then
  measures the actual host work of emitting/consuming packs, not the
  device catch-up time;
- **pack slimming** — a :class:`PackPolicy` filters emitted parts by
  name/size so large host mirrors (full-field score vectors, debug
  mirrors) can be dropped per config while the QoI scalars always ship;
  at 256^3 the pack is scalars-only and nothing else rides the stream.
"""

from __future__ import annotations

import weakref
from contextlib import nullcontext
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from cup3d_tpu.obs import trace as _trace


class PackPolicy:
    """Which parts of a step's QoI pack ride the stream.

    ``max_part_elems`` drops any part larger than that many elements
    (0/None = keep all); ``drop`` drops parts by name.  Parts named in
    ``required`` always ship — the dt chain's ``umax`` and the rigid
    mirrors must never be slimmed away.  Dropped parts simply never
    leave the device: their device arrays are unreferenced and their
    bytes are counted in ``bytes_dropped``.
    """

    REQUIRED = ("umax", "rigid", "scan")

    def __init__(self, max_part_elems: int = 0, drop: Iterable[str] = (),
                 required: Iterable[str] = REQUIRED):
        self.max_part_elems = int(max_part_elems or 0)
        self.drop = frozenset(drop)
        self.required = frozenset(required)

    def admits(self, name: str, size: int) -> bool:
        if name in self.required:
            return True
        if name in self.drop:
            return False
        if self.max_part_elems and size > self.max_part_elems:
            return False
        return True

    @classmethod
    def for_cells(cls, ncells: int, slim_at: int = 2**24) -> "PackPolicy":
        """Per-config slimming: at 256^3-class resolutions (>= ``slim_at``
        cells, default 2^24 = 256^3) ship only QoI scalars and small host
        mirrors — any full-field part (scores, debug mirrors) stays on
        device.  Below that, everything rides (the transfers are cheap
        relative to the step)."""
        if ncells >= slim_at:
            return cls(max_part_elems=4096)
        return cls()


class QoIStream:
    """Grouped async device->host QoI reader (the promoted
    ``sim/pack.GroupedPackReader``).

    entries: dicts with a ``pack`` device vector and a ``layout`` of
    (name, size) pairs; ``consume(entry)`` is called with
    ``entry['vals']`` filled, in emission order.
    """

    def __init__(self, consume: Callable[[dict], None], read_every: int = 4,
                 max_inflight: int = 2,
                 policy: Optional[PackPolicy] = None,
                 profiler=None, name: str = "qoi"):
        self.consume = consume
        self.read_every = read_every
        self.max_inflight = max_inflight
        self.policy = policy or PackPolicy()
        self.profiler = profiler
        self.name = name
        self.queue: List[dict] = []
        self._inflight: List[dict] = []  # {batch, group} FIFO
        self.stats = self._zero_stats()
        # the per-instance stats dict stays the single store (tests pin
        # its exact per-stream counts); the process-global registry sees
        # it through a weakref collector, so `obs.metrics.snapshot()`
        # carries every live stream's counters under stream.*{stream=name}
        # and equal-named streams SUM (obs/metrics.py)
        from cup3d_tpu.obs import metrics as obs_metrics

        def _collect(ref=weakref.ref(self)):
            st = ref()
            if st is None:
                return {}
            return {
                f"stream.{k}{{stream={st.name}}}": v
                for k, v in st.snapshot().items()
            }

        obs_metrics.register_collector(_collect, owner=self)

    @staticmethod
    def _zero_stats() -> dict:
        return {
            "packs_emitted": 0,
            "packs_consumed": 0,
            "packs_abandoned": 0,
            "groups_started": 0,
            "groups_read": 0,
            "parts_dropped": 0,
            "bytes_streamed": 0,
            "bytes_dropped": 0,
            "bytes_staged": 0,
            "stall_s": 0.0,
            "read_s": 0.0,
            "inflight_peak": 0,
            "kicks": 0,
        }

    def reset_stats(self) -> None:
        """Zero the counters (bench timed-window boundaries)."""
        self.stats = self._zero_stats()

    def snapshot(self) -> dict:
        """Counters plus instantaneous queue state, for the bench JSON."""
        out = dict(self.stats)
        out["groups_inflight"] = len(self._inflight)
        out["packs_queued"] = len(self.queue)
        return out

    def __bool__(self):
        return bool(self.queue or self._inflight)

    # -- emission ----------------------------------------------------------

    def pack_parts(self, parts: Sequence[Tuple[str, "object"]], dtype,
                   **meta) -> dict:
        """(name, device vector) parts -> one emitted entry, applying the
        slimming policy BEFORE the device concat so dropped parts never
        leave the device.  Returns the entry (callers on the non-pipelined
        path hand it straight to their consume callback)."""
        import jax.numpy as jnp

        kept = []
        for name, arr in parts:
            if self.policy.admits(name, int(arr.shape[0])):
                kept.append((name, arr))
            else:
                self.stats["parts_dropped"] += 1
                self.stats["bytes_dropped"] += int(
                    arr.shape[0]) * jnp.dtype(dtype).itemsize
        pack = jnp.concatenate([a.astype(dtype) for _, a in kept])
        try:
            pack.copy_to_host_async()
        # jax-lint: allow(JX009, capability probe: platforms without
        # async copies fall back to the blocking read downstream)
        except Exception:
            pass
        entry = {"layout": [(n, int(a.shape[0])) for n, a in kept],
                 "pack": pack}
        entry.update(meta)
        return entry

    def emit(self, entry: dict) -> None:
        from cup3d_tpu.resilience import faults

        # stream.stall injection seam (resilience/faults.py): a
        # simulated tunnel stall lands in the stream's own stall
        # accounting; the unarmed probe is one tuple scan
        faults.maybe_stall(step=entry.get("step"))
        self.queue.append(entry)
        self.stats["packs_emitted"] += 1
        self.poll()
        if len(self.queue) >= self.read_every:
            if len(self._inflight) >= self.max_inflight:
                # backpressure: the device has fallen a full window behind
                # the host.  This wait is device catch-up, not host-read
                # cost — attribute it to its own profiler section (and the
                # stall counter) so SyncQoI stays an honest dispatch cost.
                ctx = (self.profiler("StreamWait")
                       if self.profiler is not None else nullcontext())
                with ctx:
                    while len(self._inflight) >= self.max_inflight:
                        self._consume_one()  # bounded staleness
            self.kick()

    def kick(self) -> None:
        """Group everything queued NOW into one device batch and start its
        async host copy.  Called by emit() at the regular cadence, and by
        drivers that need fresher mirrors than the cadence provides (e.g.
        the collision pre-check when obstacles approach contact).  A kick
        at the max_inflight limit is skipped — emit()'s backpressure is
        the only place allowed to wait, so the retained device batches
        stay bounded even when a driver kicks every step."""
        import jax.numpy as jnp

        if not self.queue or len(self._inflight) >= self.max_inflight:
            return
        group, self.queue = self.queue, []
        batch = jnp.concatenate([e["pack"] for e in group])
        try:
            batch.copy_to_host_async()
        # jax-lint: allow(JX009, capability probe: platforms without
        # async copies fall back to the blocking asarray downstream)
        except Exception:
            pass
        self._inflight.append({"batch": batch, "group": group})
        self.stats["kicks"] += 1
        self.stats["groups_started"] += 1
        self.stats["bytes_streamed"] += int(batch.size) * batch.dtype.itemsize
        self.stats["inflight_peak"] = max(
            self.stats["inflight_peak"], len(self._inflight)
        )

    # -- staging (non-pack device->host traffic) ---------------------------

    def stage(self, x):
        """Start an async host copy of ``x`` and account its bytes to this
        stream (scores prefetch, ad-hoc mirrors).  Returns ``x``; the
        caller reads it later with ``np.asarray`` (~free once landed)."""
        try:
            x.copy_to_host_async()
        # jax-lint: allow(JX009, capability probe: platforms without
        # async copies fall back to the caller's blocking asarray)
        except Exception:
            pass
        try:
            self.stats["bytes_staged"] += int(x.size) * x.dtype.itemsize
        # jax-lint: allow(JX009, best-effort byte accounting on duck-
        # typed staged values; the stage itself already succeeded)
        except Exception:
            pass
        return x

    # -- consumption -------------------------------------------------------

    def _consume_one(self) -> None:
        """Read the oldest in-flight batch (blocking only if its compute /
        transfer has not landed yet) and apply its entries FIFO.  The
        read is timed into ``read_s`` when the batch had landed and
        ``stall_s`` when it had not (or its readiness was unknowable),
        and — when the stream has a profiler — into a ``StreamRead`` /
        ``StreamWait`` section, so a blocking read can never hide
        inside whichever driver section happened to enclose it (the
        BENCH_r05 fish256 SyncQoI regression: unattributed device
        catch-up billed as pack-read host work)."""
        holder = self._inflight.pop(0)
        was_ready = self._ready(holder["batch"]) is True
        ctx = (self.profiler("StreamRead" if was_ready else "StreamWait")
               if self.profiler is not None else nullcontext())
        # jax-lint: allow(JX006, the pre-window calls are host
        # bookkeeping (FIFO pop + readiness poll); the timed np.asarray
        # read IS the sync, and stall_s/read_s split on was_ready)
        # jax-lint: allow(JX008, the stall_s/read_s split is the stream's
        # native counter — it feeds the obs registry via the collector
        # registered in __init__; the StreamWait/StreamRead spans above
        # are exactly the obs attribution the rule asks for)
        t0 = _trace.now()
        with ctx:
            vals = np.asarray(holder["batch"], np.float64)
        elapsed = _trace.now() - t0
        self.stats["stall_s" if not was_ready else "read_s"] += elapsed
        self.stats["groups_read"] += 1
        off = 0
        for entry in holder["group"]:
            size = sum(s for _, s in entry["layout"])
            entry["vals"] = vals[off:off + size]
            off += size
            self.consume(entry)
            self.stats["packs_consumed"] += 1

    @staticmethod
    def _ready(batch):
        """True / False from the platform's readiness probe, or None
        when the probe itself fails.  None means "unknowable", NOT
        "ready": poll() treating a probe failure as ready turned every
        opportunistic drain into a BLOCKING read of an unfinished batch
        — serializing the dispatch loop with device compute once per
        emit cadence (the fish256 SyncQoI regression, BENCH_r05)."""
        try:
            return bool(batch.is_ready())
        # jax-lint: allow(JX009, capability probe: duck-typed batches
        # without is_ready report unknowable readiness; blocking
        # consumers proceed, the opportunistic poll() skips)
        except Exception:
            return None

    def poll(self) -> None:
        """Consume completed reads without blocking (strictly FIFO: stop
        at the first batch whose computation hasn't landed or whose
        readiness cannot be probed)."""
        while self._inflight and self._ready(self._inflight[0]["batch"]) is True:
            self._consume_one()

    def join(self) -> None:
        """Consume ALL in-flight group reads (blocking)."""
        while self._inflight:
            self._consume_one()

    def flush(self) -> None:
        """Drain everything: in-flight reads, then still-queued packs."""
        self.join()
        while self.queue:
            entry = self.queue.pop(0)
            self.consume(entry)
            self.stats["packs_consumed"] += 1

    def abandon(self) -> None:
        """Drop every queued pack and in-flight group WITHOUT consuming
        them — recovery rollback (resilience/recovery.py): mirrors from
        the abandoned trajectory must never apply to the restored
        state.  Counted in ``packs_abandoned``."""
        n = len(self.queue) + sum(len(h["group"]) for h in self._inflight)
        self.queue = []
        self._inflight = []
        self.stats["packs_abandoned"] += n
