"""Sharded multi-writer field dumps with async device->host staging.

The reference writes snapshots through collective MPI-IO: every rank
computes its byte offset with ``MPI_Exscan`` and writes its own extent
with ``MPI_File_write_at_all`` (main.cpp:429-553), so no single rank
funnels the whole field.  ``io/dump.py`` inverted that — one writer
serializes geometry + every attribute.  This module is the single-host
analogue of the reference scheme:

- the cell range is split into contiguous extents, one per shard;
- byte offsets are an exclusive scan (``_exscan``) of the per-shard byte
  counts — precomputed, so every shard writes independently;
- shards write concurrently with ``os.pwrite`` into a preallocated file
  (positional writes need no shared file pointer — the thread-pool twin
  of ``write_at_all``), including their own slice of the 8-vertex
  hexahedron geometry (computed per shard: the full vertex array at
  256^3 is ~1.6 GB, which the single writer materialized at once);
- one XDMF index per attribute is written by the coordinator, exactly
  the single-writer format — output is byte-identical to
  ``io.dump.dump_fields`` (asserted in tests/test_stream.py), so the
  reference-style ``tools/post.py`` reader works unchanged.

:class:`AsyncDumper` puts the whole thing off the critical path: fields
are handed over as DEVICE arrays (immutable in jax, so snapshotting is
reference-capture), ``copy_to_host_async`` starts their transfers, and a
background writer thread materializes + shard-writes them while the step
loop keeps dispatching.  ``dump()`` on the drivers is then a few
microseconds of handoff instead of a blocking field read + serial write.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cup3d_tpu.io.dump import (
    _CORNERS,
    _XDMF,
    _cell_geometry_blocks,
    _cell_geometry_uniform,
)
from cup3d_tpu.obs import trace as _trace


def _auto_shards() -> int:
    n = os.cpu_count() or 1
    return max(1, min(8, n))


def _extents(ncell: int, nshards: int) -> List[Tuple[int, int]]:
    """Split [0, ncell) into <= nshards contiguous, near-equal extents."""
    nshards = max(1, min(nshards, ncell)) if ncell else 1
    bounds = np.linspace(0, ncell, nshards + 1, dtype=np.int64)
    return [(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
            if b > a]


def _exscan(counts: Sequence[int]) -> List[int]:
    """Exclusive scan of byte counts -> per-shard file offsets (the
    single-host MPI_Exscan)."""
    out, acc = [], 0
    for c in counts:
        out.append(acc)
        acc += int(c)
    return out


def _pwrite_extents(path: str, jobs: List[Tuple[int, "object"]],
                    total_bytes: int, pool: Optional[ThreadPoolExecutor]):
    """Preallocate ``path`` to ``total_bytes`` and write each (offset,
    make_bytes) extent, concurrently when a pool is given.  Each shard
    produces ITS OWN bytes inside its worker (callable jobs), so no
    single thread materializes the whole file."""
    with open(path, "wb") as f:
        f.truncate(total_bytes)
    fd = os.open(path, os.O_WRONLY)

    def write_one(job):
        off, make = job
        os.pwrite(fd, make() if callable(make) else make, off)

    try:
        if pool is None:
            for job in jobs:
                write_one(job)
        else:
            list(pool.map(write_one, jobs))
    finally:
        os.close(fd)


def cell_geometry(grid) -> Tuple[np.ndarray, np.ndarray]:
    """grid -> per-cell (low corner (n,3), spacing (n,)), block-major for
    BlockGrid, C-order for UniformGrid (shared with io/dump.py)."""
    if hasattr(grid, "shape"):  # uniform
        return _cell_geometry_uniform(grid)
    return _cell_geometry_blocks(grid)


def dump_fields_sharded(
    prefix: str,
    time_: float,
    grid,
    fields: Dict[str, np.ndarray],
    nshards: int = 0,
) -> dict:
    """Sharded-writer twin of ``io.dump.dump_fields``: identical files
    (same names, same bytes), written as concurrent per-extent
    ``pwrite``s.  Returns {bytes_written, shards, files}."""
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    if nshards <= 0:
        nshards = _auto_shards()
    origin, h = cell_geometry(grid)
    ncell = origin.shape[0]
    extents = _extents(ncell, nshards)
    pool = ThreadPoolExecutor(len(extents)) if len(extents) > 1 else None
    bytes_written = 0
    files = []
    try:
        # atomic promotion (round 10): every file is fully written to
        # <path>.tmp and os.replace'd into place, so a kill mid-dump
        # leaves no truncated raws/indices for tools/post.py to trip on
        # geometry: each shard expands ITS cells to 8 float32 vertices
        # inside its writer (the full vertex array never materializes)
        xyz_path = f"{prefix}.xyz.raw"
        item = 8 * 3 * 4  # bytes per cell
        offs = _exscan([(b - a) * item for a, b in extents])

        def geom_bytes(a, b):
            def make():
                xyz = (
                    origin[a:b, None, :]
                    + _CORNERS[None, :, :] * h[a:b, None, None]
                ).astype(np.float32)
                return xyz.tobytes()
            return make

        jobs = [(off, geom_bytes(a, b))
                for (a, b), off in zip(extents, offs)]
        _pwrite_extents(f"{xyz_path}.tmp", jobs, ncell * item, pool)
        os.replace(f"{xyz_path}.tmp", xyz_path)
        bytes_written += ncell * item
        files.append(xyz_path)

        for name, arr in fields.items():
            a = np.asarray(arr, np.float32).reshape(-1)
            if a.size != ncell:
                raise ValueError(
                    f"field {name}: {a.size} values vs {ncell} cells"
                )
            attr_path = f"{prefix}.{name}.attr.raw"
            offs = _exscan([(hi - lo) * 4 for lo, hi in extents])
            jobs = [(off, a[lo:hi].tobytes())
                    for (lo, hi), off in zip(extents, offs)]
            _pwrite_extents(f"{attr_path}.tmp", jobs, ncell * 4, pool)
            os.replace(f"{attr_path}.tmp", attr_path)
            bytes_written += ncell * 4
            files.append(attr_path)
            xdmf_path = f"{prefix}.{name}.xdmf2"
            with open(f"{xdmf_path}.tmp", "w") as f:
                f.write(
                    _XDMF.format(
                        time=time_,
                        ncell=ncell,
                        nvert=8 * ncell,
                        name=name,
                        xyz=os.path.basename(xyz_path),
                        attr=os.path.basename(attr_path),
                    )
                )
            # the index is promoted LAST: it only ever names complete raws
            os.replace(f"{xdmf_path}.tmp", xdmf_path)
            files.append(xdmf_path)
    finally:
        if pool is not None:
            pool.shutdown()
    return {"bytes_written": bytes_written, "shards": len(extents),
            "files": files}


class AsyncDumper:
    """Off-critical-path snapshot writer.

    ``submit()`` captures DEVICE field references (immutable), starts
    their async host copies, and queues one background write job; the
    step loop continues immediately.  The writer thread materializes the
    fields (``np.asarray`` — nearly free once the async copy lands) and
    runs the sharded multi-writer dump.  ``wait()`` joins all pending
    writes and re-raises the first failure; drivers call it at run end
    and before any operation that must observe the files on disk.

    ``max_pending`` bounds host memory: submitting beyond it blocks on
    the oldest write (a dump burst cannot queue unbounded field copies).
    """

    def __init__(self, nshards: int = 0, max_pending: int = 2,
                 retries: int = 2):
        self.nshards = nshards
        self.max_pending = max_pending
        self.retries = retries
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: List = []
        # round-10 degradation contract: a dump that still fails after
        # the retries is DROPPED and counted — snapshots are lossy
        # telemetry, and output must never crash the step loop.  The
        # last error stays visible through health().
        self._last_error: Optional[BaseException] = None
        self.stats = {"dumps": 0, "bytes_written": 0, "write_s": 0.0,
                      "submit_s": 0.0, "write_failures": 0, "dropped": 0}
        # per-instance stats surfaced process-wide through the obs
        # registry (weakref collector; equal keys from live dumpers sum)
        import weakref

        from cup3d_tpu.obs import metrics as obs_metrics

        def _collect(ref=weakref.ref(self)):
            d = ref()
            if d is None:
                return {}
            return {f"dump.{k}": v for k, v in d.stats.items()}

        obs_metrics.register_collector(_collect, owner=self)

    def health(self) -> dict:
        """Driver-pollable liveness: {ok, pending, dumps, dropped,
        write_failures, error} — ``ok`` is False once a dump has been
        dropped (the run keeps going; the loss is visible here and in
        the ``dump.dropped`` registry counter)."""
        return {
            "ok": self.stats["dropped"] == 0,
            "pending": len(self._pending),
            "dumps": self.stats["dumps"],
            "dropped": self.stats["dropped"],
            "write_failures": self.stats["write_failures"],
            "error": repr(self._last_error) if self._last_error else None,
        }

    def submit(self, prefix: str, time_: float, grid,
               fields: Dict[str, "object"], step=None) -> None:
        # jax-lint: allow(JX008, submit_s is the dumper's native counter,
        # surfaced process-wide through the obs collector in __init__;
        # drivers additionally wrap submit in their Dump profiler span)
        t0 = _trace.now()
        staged = {}
        for name, arr in fields.items():
            try:
                arr.copy_to_host_async()
            # jax-lint: allow(JX009, capability probe: numpy arrays and
            # platforms without async copies fall back to the blocking
            # np.asarray in _write)
            except Exception:
                pass
            staged[name] = arr
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                1, thread_name_prefix="cup3d-dump"
            )
        while len(self._pending) >= self.max_pending:
            self._pending.pop(0).result()
        self._pending.append(
            self._pool.submit(self._write, prefix, time_, grid, staged,
                              step)
        )
        self.stats["dumps"] += 1
        # jax-lint: allow(JX006, submit_s measures the HOST staging cost
        # the step loop pays; the async device copy is intentionally not
        # awaited — the background _write syncs when it lands)
        self.stats["submit_s"] += _trace.now() - t0

    def _write(self, prefix, time_, grid, staged, step=None):
        # jax-lint: allow(JX008, write_s runs on the background writer
        # thread — obs spans are main-thread (SpanTimer stack); the
        # counter reaches the registry via the __init__ collector)
        t0 = _trace.now()
        host = {k: np.asarray(v) for k, v in staged.items()}
        from cup3d_tpu.resilience import faults, writeguard

        out = None
        for attempt in range(self.retries + 1):
            if attempt:
                writeguard.backoff_sleep(attempt)
            try:
                # dump.write_fail injection seam: fires per attempt
                # while armed (persistent failure = multi-count arm)
                faults.maybe_raise("dump.write_fail", step)
                out = dump_fields_sharded(prefix, time_, grid, host,
                                          nshards=self.nshards)
                break
            except Exception as e:
                self.stats["write_failures"] += 1
                self._last_error = e
        if out is None:
            # retries exhausted: drop + count, never crash the step loop
            # (checkpoints are the durable artifact; dumps are lossy)
            self.stats["dropped"] += 1
            from cup3d_tpu.obs import metrics as obs_metrics

            obs_metrics.counter("dump.write_dropped").inc()
            return None
        self.stats["bytes_written"] += out["bytes_written"]
        self.stats["write_s"] += _trace.now() - t0
        return out

    def wait(self) -> None:
        pending, self._pending = self._pending, []
        for fut in pending:
            fut.result()

    def __bool__(self):
        return bool(self._pending)
