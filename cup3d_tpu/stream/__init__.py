"""Async host data-plane: every device->host byte goes through here.

The reference overlaps all host traffic with compute (QoI reductions ride
MPI allreduces, snapshots go out via MPI-IO collectives with
``MPI_Exscan``-computed offsets, main.cpp:429-553) so the solve never
waits on the host.  This package is the port's equivalent, as a
first-class subsystem instead of per-driver ad-hoc code:

- :mod:`cup3d_tpu.stream.qoi` — streaming QoI reads: per-step packs are
  grouped on device, copied with ``copy_to_host_async`` into a bounded
  FIFO, and consumed strictly in order with per-stream counters (bytes,
  groups in flight, stall seconds) and per-config pack slimming;
- :mod:`cup3d_tpu.stream.dump` — sharded multi-writer field dumps (the
  single-host analogue of ``MPI_Exscan`` + ``write_at_all``) with async
  device->host staging so ``dump()`` never blocks the dispatch stream;
- :mod:`cup3d_tpu.stream.checkpoint` — checkpoints snapshot device state
  via async copies and serialize off the step loop, restore-compatible
  with :mod:`cup3d_tpu.io.checkpoint` files.
"""

from cup3d_tpu.stream.qoi import PackPolicy, QoIStream
from cup3d_tpu.stream.dump import AsyncDumper, dump_fields_sharded
from cup3d_tpu.stream.checkpoint import AsyncCheckpointer

__all__ = [
    "QoIStream",
    "PackPolicy",
    "AsyncDumper",
    "dump_fields_sharded",
    "AsyncCheckpointer",
]
