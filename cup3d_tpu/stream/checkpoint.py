"""Off-critical-path checkpoints: async device snapshots, background
serialization, restore-compatible with ``io/checkpoint.py`` files.

The reference parses ``-fsave/saveFreq`` but ships no restart
serialization; ``io/checkpoint.py`` filled that gap with a synchronous
pickle — a full blocking field read plus a serial write on the step
loop.  Here the save splits into:

1. **snapshot** (main thread, non-blocking): ``io.checkpoint
   .build_payload`` captures the device fields and all host scalars;
   each device field is snapshotted into a FRESH buffer (``jnp.copy``,
   an async on-device copy) — the step jits donate their state buffers
   (JX002), so holding the live reference would hand the writer thread
   a deleted array whenever the next step lands before the D2H copy
   (a measured, order-dependent flake).  Obstacles are deep-frozen via
   a pickle round trip because their host-side kinematic state keeps
   mutating; every snapshot then starts a ``copy_to_host_async`` so
   the transfers overlap subsequent steps;
2. **write** (background thread): materialize the landed copies and
   pickle the exact ``io/checkpoint.py`` payload (same FORMAT_VERSION,
   same keys), so ``io.checkpoint.load_checkpoint`` restores these
   files unchanged.

``max_pending`` bounds host memory: a save issued while the previous is
still writing joins it first (checkpoints are rare; two in flight means
the disk, not the solver, is the bottleneck).  ``wait()`` joins all
pending writes and re-raises the first failure — drivers call it at run
end, and anything that must read a checkpoint back calls it first.
"""

from __future__ import annotations

import pickle
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from cup3d_tpu.io.checkpoint import (
    build_payload,
    checkpoint_path,
    materialize_payload,
    write_payload,
)
from cup3d_tpu.obs import trace as _trace


class AsyncCheckpointer:
    def __init__(self, max_pending: int = 1):
        self.max_pending = max_pending
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: List = []
        # round-10 fix (ISSUE 5 satellite): a background write failure
        # used to be silently lost unless someone happened to .result()
        # the future — now the FIRST failure is latched here, propagated
        # by the next save()/wait(), and visible through health()
        self._failed: Optional[BaseException] = None
        self.stats = {"saves": 0, "snapshot_s": 0.0, "write_s": 0.0,
                      "write_failures": 0}
        # per-instance stats surfaced process-wide through the obs
        # registry (weakref collector, like stream/qoi.py)
        import weakref

        from cup3d_tpu.obs import metrics as obs_metrics

        def _collect(ref=weakref.ref(self)):
            c = ref()
            if c is None:
                return {}
            return {f"checkpoint.{k}": v for k, v in c.stats.items()}

        obs_metrics.register_collector(_collect, owner=self)

    def _reap_done(self) -> None:
        """Retire completed write futures, latching the first failure
        (the executor would otherwise swallow it forever)."""
        still = []
        for fut in self._pending:
            if not fut.done():
                still.append(fut)
                continue
            try:
                fut.result()
            except Exception as e:
                if self._failed is None:
                    self._failed = e
        self._pending = still

    def health(self) -> dict:
        """Driver-pollable liveness: {ok, pending, saves,
        write_failures, error}.  ``ok`` is False while an unpropagated
        background failure is latched."""
        self._reap_done()
        return {
            "ok": self._failed is None,
            "pending": len(self._pending),
            "saves": self.stats["saves"],
            "write_failures": self.stats["write_failures"],
            "error": repr(self._failed) if self._failed else None,
        }

    def save(self, driver, path: Optional[str] = None) -> str:
        """Snapshot ``driver`` now; write in the background.  Returns the
        checkpoint path (the file lands when the write job completes).
        A failure from a PREVIOUS background write is re-raised here
        (and cleared) before any new snapshot work: callers learn about
        it at the next save instead of never."""
        self._reap_done()
        if self._failed is not None:
            err, self._failed = self._failed, None
            raise err
        # jax-lint: allow(JX008, snapshot_s is the checkpointer's native
        # counter, surfaced through the obs collector in __init__; the
        # drivers wrap save() in their Checkpoint profiler span)
        # jax-lint: allow(JX006, the pre-window calls are host-side
        # future bookkeeping (_reap_done), not device dispatches)
        t0 = _trace.now()
        payload = build_payload(driver)
        # deep-freeze host-mutable obstacle state (device arrays and the
        # sim backref are dropped by Obstacle.__getstate__ / restored on
        # load, exactly as the synchronous path pickles them)
        payload["obstacles"] = pickle.loads(
            pickle.dumps(payload["obstacles"],
                         protocol=pickle.HIGHEST_PROTOCOL)
        )
        fields = {}
        for k, v in payload["fields"].items():
            if hasattr(v, "copy_to_host_async"):  # a live device array
                import jax.numpy as jnp  # deferred: import-light module

                # donation-proof snapshot: the step jits donate their
                # state buffers, so the writer must own a fresh copy
                v = jnp.copy(v)
                try:
                    v.copy_to_host_async()
                # jax-lint: allow(JX009, capability probe: platforms
                # without async copies fall back to the blocking read
                # in materialize_payload)
                except Exception:
                    pass
            fields[k] = v
        payload["fields"] = fields
        if path is None:
            path = checkpoint_path(
                driver.cfg.path4serialization, payload["step"]
            )
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                1, thread_name_prefix="cup3d-ckpt"
            )
        while len(self._pending) >= self.max_pending:
            self._pending.pop(0).result()
        self._pending.append(self._pool.submit(self._write, payload, path))
        self.stats["saves"] += 1
        # jax-lint: allow(JX006, snapshot_s measures the HOST staging
        # cost the step loop pays; the device copy is intentionally not
        # awaited here — overlapping it is the point of the async path)
        self.stats["snapshot_s"] += _trace.now() - t0
        return path

    def _write(self, payload: dict, path: str) -> str:
        # jax-lint: allow(JX008, write_s runs on the background writer
        # thread — obs spans are main-thread; the counter reaches the
        # registry via the __init__ collector)
        t0 = _trace.now()
        try:
            out = write_payload(materialize_payload(payload), path)
        except Exception:
            self.stats["write_failures"] += 1
            raise  # latched by _reap_done / surfaced by save()/wait()
        # jax-lint: allow(JX006, materialize_payload host-reads every
        # staged field inside the window — a transitive sync the AST
        # cannot see; the wall here is true background-write cost)
        self.stats["write_s"] += _trace.now() - t0
        return out

    def wait(self) -> None:
        """Join all pending writes; re-raises the FIRST failure —
        including one latched from an earlier, already-reaped write."""
        pending, self._pending = self._pending, []
        first: Optional[BaseException] = None
        for fut in pending:
            try:
                fut.result()
            except Exception as e:
                if first is None:
                    first = e
        if first is None and self._failed is not None:
            first, self._failed = self._failed, None
        if first is not None:
            raise first

    def __bool__(self):
        return bool(self._pending)
