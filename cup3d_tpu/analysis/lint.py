"""JAX-aware AST lint: machine-checked hot-path invariants.

Static half of the analysis subsystem (see ``analysis/runtime`` for the
sound runtime checks).  The linter is deliberately PRECISION-first: a
Python AST cannot prove an expression holds a device array, so every
rule fires only on patterns that are device-typed by construction or by
this repo's conventions (``jnp.*`` calls, ``self._name(...)`` jitted
wrappers, values assigned from them).  What the heuristics miss, the
runtime transfer guard catches; what they flag wrongly, an inline
annotation documents:

    x = float(self._maxu(v))  # jax-lint: allow(JX001, designed dt sync)

or a checked-in baseline entry (``analysis/baseline.json``) matched by
(rule, path, enclosing function) so entries survive line drift.  The
CLI (``python -m cup3d_tpu.analysis``) exits nonzero on any violation
that is neither annotated nor baselined.

Rule summary (full rationale in ``analysis/rules.py``):

- JX001  host-sync call (``float``/``int``/``bool``/``.item()``/
         ``np.asarray``/``jax.device_get``) on a device value inside a
         hot-path function (step/solve/advance loops in ``sim/``,
         ``ops/``, ``stream/``).
- JX002  step-shaped ``jax.jit`` without ``donate_argnums``.
- JX003  Python ``if``/``while``/ternary on a traced argument inside a
         jitted body (covers the implicit ``__bool__`` host sync).
- JX004  device-array construction inside a per-step Python loop in a
         hot-path function.
- JX005  float64 dtype literal in device code.
- JX006  ``time.perf_counter()`` timing window with no device sync.
- JX007  ``jax.jit`` construction inside a loop body or an
         adaptation-path function (rebuild/adapt): a fresh jit object
         per pass/regrid defeats the per-object trace cache — the bug
         class the capacity-bucketed compiled-step cache removes.
- JX008  ``time.perf_counter()`` / manual section timing inside the
         package but outside ``cup3d_tpu/obs/``: use obs spans, so the
         measured wall reaches the registry/trace/flight recorder
         instead of a private counter.
- JX009  swallowed exception inside the package (handler body is only
         ``pass``/``continue``/``break``/a bare log call): the failure
         leaves no counter, no state, no re-raise.  ``cup3d_tpu/
         resilience/`` is exempt by path — containing already-counted
         failures is its job.
- JX010  per-step host<->device staging of obstacle state:
         ``np.asarray``/``jnp.asarray`` on a loop-carried attribute
         (``self.X``/``ob.X``/``s.X``) inside a step-loop function in
         ``sim/``, ``ops/``, ``stream/`` or ``models/`` — the residue
         the megaloop work removed (cache the mirror identity-keyed,
         derive it on device, or carry it in the scan state).
- JX011  reduction (``jnp.sum``/``dot``/``vdot``/``matmul``/
         ``tensordot``/``lax.dot``) over bfloat16-tainted operands in
         ``cup3d_tpu/ops/`` without an explicit ``dtype=`` /
         ``preferred_element_type=`` accumulator: the round-12 mixed-
         precision policy (ops/precision.py) stores Krylov vectors in
         bf16 but must ACCUMULATE in f32 — a storage-precision
         reduction silently destroys the stopping test.
- JX012  direct ``jax.profiler`` use (imports or dotted access) inside
         the package but outside ``cup3d_tpu/obs/``: the profiler
         session is process-global, so an ad-hoc capture collides with
         obs profile windows and its trace bypasses the device-time
         attribution parser — use obs.profile capture windows instead.
- JX013  per-lane Python loop over the scenario axis in ``cup3d_tpu/
         fleet/`` that dispatches device work per iteration: the lane
         axis must stay vectorized (one vmapped dispatch advances all
         B lanes — fleet/batch.py); host-only loops over lanes are
         fine in assembly/fan-out code because they touch no device
         value.
- JX014  wall-clock subtraction used as a duration: differencing two
         ``time.time()``/``datetime.now()`` reads inside the package —
         NTP slews/steps the wall clock, so the "duration" can be
         negative or jump by seconds and silently corrupts latency
         histograms and SLO burn rates.  Durations come from the
         monotonic clock (``obs.trace.now()`` / obs spans); bare
         ``time.time()`` TIMESTAMPS (history rows, postmortem
         wall_time) stay legal — only the subtraction fires.
- JX015  per-tick host reassembly of full-batch arrays in
         ``cup3d_tpu/fleet/``: a K-boundary fast-path function
         (tick/reseed/dispatch) that restacks the whole lane axis
         (``jnp.stack``/``np.stack``/``concatenate`` or the assembly
         helpers ``stack_carries``/``stack_gaits``) pays O(B) host
         work and a fresh device upload every boundary — a reseed
         must touch ONE lane through the jitted ``.at[lane].set``
         upload path (``fleet/batch.py reseed_lane_carry``).  Batch
         CONSTRUCTION (assemble/__init__) still stacks legitimately:
         the rule keys on the per-tick function names.
- JX016  full-array materialization in a sharded step path:
         ``jax.device_get``/``np.asarray``/``np.array`` (or a single-
         argument ``jax.device_put``) inside a step/advance/dispatch/
         megaloop function in ``cup3d_tpu/{sim,fleet,parallel}/``
         gathers a (possibly mesh-sharded) array whole onto one host
         or device — the scale-out ceiling the round-18 2-D mesh
         removes.  Slice shard-locally under shard_map, place with an
         explicit ``device_put(x, sharding)``, and stage host reads
         through the designed sync points (sanctioned_transfer).
- JX017  hand-typed hardware peak/bandwidth literal in a roofline or
         bench reporting path: a numeric constant >= 1e9 that is not an
         exact power of ten (``197e12``, ``819e9``) hard-codes one
         device's spec sheet into MFU/HBM math that runs on EVERY
         backend — the round-19 bug class where rooflines silently lie
         on non-v5e hardware.  Peaks live in the ``obs/costs.py``
         device-kind table (the one exempt module); consumers call
         ``device_peaks()``.  Scope: ``bench*.py`` files plus any
         function named like roofline/peak-model in the package.
- JX018  raw collective call site outside ``cup3d_tpu/parallel/``:
         ``lax.ppermute``/``psum``/``pmax``/``all_gather``/... called
         directly anywhere else in the package scatters the SPMD
         communication surface across the tree.  Collectives go
         through the parallel/ layer (``ring.py`` ring_shift/pad_slab,
         ``collectives.py`` all_gather_tiled/pmax_axis) so the IR
         audit (JP002) has ONE seam to prove permutation/axis
         invariants on and a mesh-topology change edits one module.
- JX019  direct AOT compile / jit-warmup call site outside the
         executable-store seam: a chained ``fn.lower(...).compile()``
         or an immediately-invoked ``jit(f)(...)`` warmup produces an
         XLA executable the persistent store (``cup3d_tpu/aot/``)
         never sees — recompiled on every boot, invisible to the
         aot.* telemetry.  Route compiles through ``aot.store_backed``
         / ``StoreBackedExecutable.warm`` so seen signatures
         deserialize instead.  ``cup3d_tpu/aot/`` is the seam itself
         and ``obs/costs.py`` harvests from compiled objects — both
         path-exempt.
- JX020  raw clock read inside ``cup3d_tpu/`` outside the trace
         layer: ``time.monotonic()``/``time.time()``/
         ``time.perf_counter()`` (and ``*_ns`` variants) called
         anywhere but ``obs/trace.py`` splits the package across
         clock domains — the round-22 phase decomposition only
         partitions end-to-end latency because every lifecycle
         timestamp comes off ONE monotonic clock.  Route monotonic
         reads through ``obs.trace.now()`` and wall-time stamps
         through ``obs.trace.wall()``; ``obs/trace.py`` itself is the
         sanctioned seam and is path-exempt.
"""

from __future__ import annotations

import ast
import json
import math
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from cup3d_tpu.analysis.rules import RULES, Violation

# -- scoping ----------------------------------------------------------------

#: modules whose functions can be on the per-step critical path
HOT_MODULE_RE = re.compile(r"cup3d_tpu/(sim|ops|stream)/")

#: function names that run inside (or are) the step loop
HOT_FUNC_RE = re.compile(
    r"^(advance\w*|simulate|solve\w*|calc_max_timestep|_calc_dt\w*|"
    r"_emit\w*|_consume\w*|emit|kick|poll|join|flush\w*|stage|"
    r"_fix_mass_flux|_compute_forces|__call__|\w*step\w*|\w*megastep\w*)$"
)

#: names that mark a jitted function / its target as a steady-state step
STEP_SHAPE_RE = re.compile(r"step|mega", re.IGNORECASE)

#: functions that run once per mesh adaptation (JX007): a jax.jit built
#: here is rebuilt per regrid, defeating jax's per-object trace cache
ADAPT_FUNC_RE = re.compile(r"rebuild|adapt", re.IGNORECASE)

#: loop constructs whose body re-executes (JX004/JX007)
LOOP_NODES = (ast.For, ast.While, ast.ListComp, ast.SetComp,
              ast.DictComp, ast.GeneratorExp)

#: host->device constructors relevant to JX004
JNP_CONSTRUCTORS = frozenset(
    {"asarray", "array", "zeros", "ones", "full", "arange", "linspace",
     "eye"}
)

#: calls that force (or are) a device sync, for JX001/JX006
SYNC_BUILTINS = frozenset({"float", "int", "bool"})

#: JX010 scope: the obstacle pipeline's step-loop modules.  Wider than
#: HOT_MODULE_RE because models/ operator ``__call__``s ARE the per-step
#: obstacle path even though they hold no device kernels of their own.
JX010_MODULE_RE = re.compile(r"cup3d_tpu/(sim|ops|stream|models)/")

#: receiver names whose attributes are loop-carried obstacle/driver
#: state by this repo's conventions (JX010): obstacle mirrors live on
#: ``ob``/``obstacle``/``self``, driver scalars on ``s``/``sim``/``self``
JX010_STATE_ROOTS = frozenset({"self", "s", "sim", "ob", "obstacle"})

#: the staging constructors JX010 watches (both directions: np.asarray
#: is a device->host read when the mirror went device-resident,
#: jnp.asarray a host->device upload of the same bytes every step)
ASARRAY_NAMES = frozenset(
    {"np.asarray", "numpy.asarray", "jnp.asarray", "jax.numpy.asarray"}
)

#: array attributes that live on the HOST side of a jax Array (reading
#: them never syncs), so int(x.size) etc. is not a JX001 hit
HOST_METADATA_ATTRS = frozenset(
    {"size", "ndim", "shape", "dtype", "itemsize", "nbytes", "sharding"}
)

#: JX011 scope: the Krylov/kernel modules where the round-12 mixed-
#: precision policy stores vectors in bf16 — the only place a
#: storage-precision reduction can reach the stopping test
JX011_MODULE_RE = re.compile(r"cup3d_tpu/ops/")

#: JX013 scope: the fleet serving layer, where the lane axis exists
JX013_MODULE_RE = re.compile(r"cup3d_tpu/fleet/")

#: names that mark a loop as walking the lane/scenario axis (matched
#: against the loop target and every Name in the iterable expression)
JX013_AXIS_RE = re.compile(r"(^|_)(lanes?|scenarios?)(_|$|\d)",
                           re.IGNORECASE)

#: reduction-position callables JX011 watches (the accumulator-dtype
#: hazard lives where many elements fold into few)
JX011_REDUCTIONS = frozenset(
    {"sum", "dot", "vdot", "matmul", "tensordot", "einsum", "dot_general"}
)

#: keyword args that name an explicit (>= f32) accumulator
JX011_ACCUM_KWARGS = frozenset({"dtype", "preferred_element_type"})

#: datetime constructors whose reads are wall-clock (JX014); the time
#: module's own names are resolved per file from its imports, since
#: ``from time import time`` leaves a bare ``time()`` call behind
JX014_DATETIME_ATTRS = frozenset({"now", "utcnow", "today"})

#: JX015 scope: the fleet K-boundary fast path — functions named like
#: the per-tick seam (tick/reseed/dispatch), where full-batch
#: reassembly turns an O(1)-lane reseed into O(B) host work per tick
JX015_FUNC_RE = re.compile(r"(^|_)(ticks?|reseeds?|dispatch(es)?)",
                           re.IGNORECASE)

#: callables that rebuild the full lane-stacked batch from per-lane
#: pieces: array stackers (resolved against jnp/np roots) plus this
#: repo's own assembly helpers, which stack by construction
JX015_STACKERS = frozenset({"stack", "concatenate", "vstack", "hstack"})
JX015_ASSEMBLY_HELPERS = frozenset({"stack_carries", "stack_gaits"})

#: JX016 scope: the modules hosting mesh-sharded steady-state paths
#: (solo megaloop slabs in sim/, the lane-sharded fleet advance in
#: fleet/, the forest/topology layer in parallel/)
JX016_MODULE_RE = re.compile(r"cup3d_tpu/(sim|fleet|parallel)/")

#: functions on the sharded fast path: the step bodies and their
#: drivers' per-boundary seams
JX016_FUNC_RE = re.compile(r"step|advance|dispatch|megaloop",
                           re.IGNORECASE)

#: builder factories (make_*/build_*/bind_*) run ONCE per topology to
#: stage trace-time constants — not the steady-state path.  Their inner
#: step closures are visited under their own names and stay covered.
JX016_BUILDER_RE = re.compile(r"^(make_|build_|bind_|_build_)")

#: host-materializing callables JX016 watches: full device->host pulls
#: (device_get / np.asarray / np.array on a device value) plus the
#: single-argument device_put, which re-places the WHOLE array onto
#: jax's default device (a cross-shard gather when the input was
#: sharded); device_put WITH an explicit sharding argument stays legal
JX016_HOST_PULLS = frozenset({"device_get", "asarray", "array"})

#: JX018: the communicating collectives (device<->device exchange under
#: a named axis).  ``axis_index`` is deliberately absent — it is a
#: shard-LOCAL coordinate read with no communication (the fleet's
#: shard-local lane upload uses it legitimately outside parallel/).
JX018_COLLECTIVES = frozenset(
    {"ppermute", "pshuffle", "psum", "psum_scatter", "pmax", "pmin",
     "pmean", "all_gather", "all_to_all", "pbroadcast"}
)

#: JX018 exemption: the parallel/ layer IS the sanctioned collective
#: seam (ring.py, compat.py, collectives.py, topology.py)
JX018_EXEMPT_RE = re.compile(r"cup3d_tpu/parallel/")

#: JX017 scope: the bench entrypoints (any bench*.py) and, anywhere in
#: the tree, functions whose names say they place work on a roofline
#: or model a hardware ceiling
JX017_PATH_RE = re.compile(r"(^|/)bench[^/]*\.py$")
JX017_FUNC_RE = re.compile(r"roofline|peak", re.IGNORECASE)

#: the one sanctioned home for hardware peak literals: the device-kind
#: table in obs/costs.py (provenance-annotated, nominal-flagged)
JX017_EXEMPT_RE = re.compile(r"cup3d_tpu/obs/costs\.py$")

#: spec-sheet magnitudes start at ~1e9 (GB/s bandwidths); exact powers
#: of ten below/at any magnitude are unit conversions (1e9, 1e12), not
#: hardware claims
JX017_MIN_MAGNITUDE = 1e9

#: JX019 exemption: cup3d_tpu/aot/ IS the store seam (its wrapper owns
#: the one sanctioned lower().compile()), and obs/costs.py harvests
#: cost analytics from an already-compiled object
JX019_EXEMPT_RE = re.compile(r"cup3d_tpu/(aot/|obs/costs\.py$)")

#: JX020 exemption: obs/trace.py IS the clock seam — its ``now()`` /
#: ``wall()`` own the package's two sanctioned clock reads
JX020_EXEMPT_RE = re.compile(r"cup3d_tpu/obs/trace\.py$")

#: the ``time``-module attributes JX020 treats as raw clock reads
JX020_CLOCK_ATTRS = ("time", "monotonic", "perf_counter",
                     "time_ns", "monotonic_ns", "perf_counter_ns")

#: JX021 (round 23): the sanctioned fleet job-state seams — the ONLY
#: functions in cup3d_tpu/fleet/ allowed to assign ``<job>.status``.
#: Each either journals the transition itself or sits on a path that
#: funnels into ``_job_terminal``/``mark`` (first assembly, retire,
#: reseed splice, queued-cancel, prepare-failure, journal replay); a
#: status flip anywhere else is a lifecycle transition the write-ahead
#: journal never sees, i.e. a job a crash can silently lose.
JX021_SANCTIONED_RE = re.compile(
    r"^(__init__|retire|reseed_lane|cancel|_prepare|"
    r"_install_replayed_job)$")


def _is_power_of_ten(v: float) -> bool:
    if v <= 0:
        return False
    e = round(math.log10(v))
    return abs(v - 10.0 ** e) <= 1e-6 * (10.0 ** e)


def _is_host_metadata(expr: ast.AST) -> bool:
    """True when ``expr`` only reads host-side array metadata."""
    node = expr
    while isinstance(node, ast.Attribute):
        if node.attr in HOST_METADATA_ATTRS:
            return True
        node = node.value
    return False

# reason may contain one level of nested parens: allow(JX001, freq (gated))
ALLOW_RE = re.compile(
    r"jax-lint:\s*allow\(\s*(JX\d{3})\s*"
    r"(?:,\s*((?:[^()]|\([^()]*\))*?)\s*)?\)"
)


# -- suppressions -----------------------------------------------------------


def parse_suppressions(source: str) -> Dict[int, Dict[str, str]]:
    """line -> {rule: reason}.  An annotation on a pure-comment line (or a
    block of them: a wrapped annotation continues across consecutive
    comment lines) applies to the next CODE line; on a code line, to that
    line."""
    out: Dict[int, Dict[str, str]] = {}
    lines = source.splitlines()
    i = 0
    while i < len(lines):
        text = lines[i]
        if text.lstrip().startswith("#"):
            # join the whole comment block so wrapped annotations parse
            start = i
            while i < len(lines) and lines[i].lstrip().startswith("#"):
                i += 1
            joined = " ".join(
                lines[j].lstrip().lstrip("#").strip()
                for j in range(start, i)
            )
            matches = ALLOW_RE.findall(joined)
            if matches:
                target = i + 1  # 1-based number of the next code line
                slot = out.setdefault(target, {})
                for rule, reason in matches:
                    slot[rule] = (reason or "").strip()
            continue
        # trailing annotation on a code line applies to that line
        if "#" in text:
            matches = ALLOW_RE.findall(text)
            if matches:
                slot = out.setdefault(i + 1, {})
                for rule, reason in matches:
                    slot[rule] = (reason or "").strip()
        i += 1
    return out


# -- AST helpers ------------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a Name/Attribute chain ('' otherwise)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _call_name(call: ast.Call) -> str:
    return _dotted(call.func)


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_jnp_call(call: ast.Call) -> bool:
    name = _call_name(call)
    root = name.split(".", 1)[0].lstrip("_")
    return "." in name and root in ("jnp", "jax")


def _is_jitwrapper_call(call: ast.Call) -> bool:
    """``self._name(...)`` / ``s._name(...)``: the repo convention for
    jitted step pieces held as driver attributes."""
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr.startswith("_")
        and isinstance(f.value, ast.Name)
    )


def _is_device_call(call: ast.Call) -> bool:
    return _is_jnp_call(call) or _is_jitwrapper_call(call)


def _jit_target(call: ast.Call) -> Optional[ast.AST]:
    """For a ``jax.jit(f, ...)`` call, the wrapped function node."""
    if _call_name(call) in ("jax.jit", "jit") and call.args:
        return call.args[0]
    return None


def _is_partial_of_jit(call: ast.Call) -> bool:
    """``partial(jax.jit, ...)`` (any name ending in 'partial')."""
    return (
        _call_name(call).endswith("partial")
        and bool(call.args)
        and _dotted(call.args[0]) in ("jax.jit", "jit")
    )


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return set()
            if isinstance(v, str):
                return {v}
            return set(v)
    return set()


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _walk_shallow(func: ast.AST):
    """Walk a function body WITHOUT descending into nested def/class —
    every def gets its own visit from ``FileLint._functions``, so a deep
    walk would double-count nested findings.  Lambdas stay in scope
    (inline ``jax.jit(lambda ...)`` belongs to the enclosing def)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            stack.extend(ast.iter_child_nodes(node))


def _is_none_check(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` (and `and`/`or`/`not` chains of
    them): identity-vs-None is a structural check, static under trace."""
    if isinstance(test, ast.BoolOp):
        return all(_is_none_check(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_check(test.operand)
    return (
        isinstance(test, ast.Compare)
        and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
        and all(
            isinstance(c, ast.Constant) and c.value is None
            for c in test.comparators
        )
    )


def _inner_name(node: ast.AST) -> str:
    """Name of the function being jitted: Name / Attribute / partial(f,…)
    peeled recursively; lambdas are ''. """
    if isinstance(node, ast.Call) and _call_name(node).endswith("partial"):
        return _inner_name(node.args[0]) if node.args else ""
    name = _dotted(node)
    return name.rsplit(".", 1)[-1] if name else ""


# -- per-function device-taint tracking (JX001) -----------------------------


class _Taint:
    """Names assigned (in source order) from device-producing calls."""

    def __init__(self) -> None:
        self.names: Set[str] = set()

    def feed(self, stmt: ast.stmt) -> None:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
            value = stmt.value
        if value is None:
            return
        tainted = any(
            isinstance(n, ast.Call) and _is_device_call(n)
            for n in ast.walk(value)
        ) or bool(self.names & _names_in(value))
        # a host read LAUNDERS the value: np.asarray(x) yields host data
        for n in ast.walk(value):
            if isinstance(n, ast.Call) and _call_name(n) in (
                "np.asarray", "numpy.asarray", "jax.device_get"
            ):
                tainted = False
        if not tainted:
            return
        # only PLAIN names (incl. tuple/list unpacks) become tainted:
        # `self._x = jit(...)` must not taint `self` itself
        for t in targets:
            stack = [t]
            while stack:
                leaf = stack.pop()
                if isinstance(leaf, ast.Name):
                    self.names.add(leaf.id)
                elif isinstance(leaf, (ast.Tuple, ast.List)):
                    stack.extend(leaf.elts)
                elif isinstance(leaf, ast.Starred):
                    stack.append(leaf.value)

    def covers(self, expr: ast.AST) -> bool:
        if any(
            isinstance(n, ast.Call) and _is_device_call(n)
            for n in ast.walk(expr)
        ):
            return True
        return bool(self.names & _names_in(expr))


# -- the linter -------------------------------------------------------------


@dataclass
class FileLint:
    path: str            # repo-relative posix path
    tree: ast.Module
    suppressions: Dict[int, Dict[str, str]]
    violations: List[Violation] = field(default_factory=list)

    def run(self) -> List[Violation]:
        hot_module = bool(HOT_MODULE_RE.search(self.path))
        jitted = self._collect_jitted_defs()
        for func, qualname in self._functions():
            hot = hot_module and bool(HOT_FUNC_RE.match(func.name))
            if hot:
                self._check_host_sync(func, qualname)       # JX001
                self._check_loop_construction(func, qualname)  # JX004
            self._check_jit_sites(func, qualname)           # JX002
            if hot_module:
                self._check_jit_in_regrid_path(func, qualname)  # JX007
            if id(func) in jitted:
                self._check_traced_control_flow(            # JX003
                    func, qualname, jitted[id(func)]
                )
            self._check_timing_windows(func, qualname)      # JX006
            self._check_manual_timing(func, qualname)       # JX008
            self._check_wallclock_duration(func, qualname)  # JX014
            self._check_profiler_usage(func, qualname)      # JX012
            self._check_swallowed_exceptions(func, qualname)  # JX009
            if JX010_MODULE_RE.search(self.path) and bool(
                HOT_FUNC_RE.match(func.name)
            ):
                self._check_obstacle_staging(func, qualname)  # JX010
            if JX011_MODULE_RE.search(self.path):
                self._check_bf16_reduction(func, qualname)  # JX011
            if JX013_MODULE_RE.search(self.path):
                self._check_lane_device_loop(func, qualname)  # JX013
                self._check_batch_reassembly(func, qualname)  # JX015
                self._check_status_mutation(func, qualname)   # JX021
            if JX016_MODULE_RE.search(self.path):
                self._check_sharded_materialization(func, qualname)  # JX016
            if not JX017_EXEMPT_RE.search(self.path) and (
                JX017_PATH_RE.search(self.path)
                or JX017_FUNC_RE.search(func.name)
            ):
                self._check_hardware_peaks(func, qualname)  # JX017
            if (self.path.startswith("cup3d_tpu/")
                    and not JX018_EXEMPT_RE.search(self.path)):
                self._check_raw_collectives(func, qualname)  # JX018
            if (self.path.startswith("cup3d_tpu/")
                    and not JX019_EXEMPT_RE.search(self.path)):
                self._check_aot_seam(func, qualname)        # JX019
            if (self.path.startswith("cup3d_tpu/")
                    and not JX020_EXEMPT_RE.search(self.path)):
                self._check_raw_clock(func, qualname)       # JX020
        if (self.path.startswith("cup3d_tpu/")
                and not JX018_EXEMPT_RE.search(self.path)):
            self._check_raw_collectives(self.tree, "<module>")  # JX018
        if (self.path.startswith("cup3d_tpu/")
                and not JX019_EXEMPT_RE.search(self.path)):
            self._check_aot_seam(self.tree, "<module>")     # JX019
        if (self.path.startswith("cup3d_tpu/")
                and not JX020_EXEMPT_RE.search(self.path)):
            self._check_raw_clock(self.tree, "<module>")    # JX020
        self._check_dtype_literals()                        # JX005
        self._check_swallowed_exceptions(self.tree, "<module>")  # JX009
        self._check_wallclock_duration(self.tree, "<module>")  # JX014
        self._check_profiler_usage(self.tree, "<module>")   # JX012
        if JX011_MODULE_RE.search(self.path):
            self._check_bf16_reduction(self.tree, "<module>")  # JX011
        if JX013_MODULE_RE.search(self.path):
            self._check_lane_device_loop(self.tree, "<module>")  # JX013
            self._check_status_mutation(self.tree, "<module>")  # JX021
        if JX017_PATH_RE.search(self.path) and not JX017_EXEMPT_RE.search(
            self.path
        ):
            self._check_hardware_peaks(self.tree, "<module>")  # JX017
        return self.violations

    # -- plumbing ----------------------------------------------------------

    def _functions(self):
        """(FunctionDef, qualname) for every def, with class/def nesting."""
        out = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{prefix}{child.name}"
                    out.append((child, q))
                    visit(child, f"{q}.")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return out

    def _emit(self, rule: str, node: ast.AST, func: str, msg: str) -> None:
        v = Violation(
            rule=rule, path=self.path, line=node.lineno,
            col=node.col_offset, func=func, message=msg,
        )
        reason = self.suppressions.get(node.lineno, {}).get(rule)
        if reason is not None:
            v.suppressed = True
            v.suppression_reason = reason or None
        self.violations.append(v)

    def _collect_jitted_defs(self) -> Dict[int, Set[str]]:
        """id(FunctionDef) -> static argnames, for defs that are jitted:
        decorated with jax.jit / partial(jax.jit, ...), or passed by name
        to a jax.jit(...) call anywhere in the module."""
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        jitted: Dict[int, Set[str]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _dotted(dec) in ("jax.jit", "jit"):
                        jitted[id(node)] = set()
                    elif isinstance(dec, ast.Call) and (
                        _dotted(dec.func) in ("jax.jit", "jit")
                        or _is_partial_of_jit(dec)
                    ):
                        jitted[id(node)] = _static_argnames(dec)
            elif isinstance(node, ast.Call):
                target = _jit_target(node)
                if target is not None:
                    name = _dotted(target)
                    if name in defs:
                        jitted[id(defs[name])] = _static_argnames(node)
        return jitted

    # -- JX001 -------------------------------------------------------------

    def _sanction_lookup(self, func: ast.AST):
        """line -> tag for `with sanctioned_transfer("tag"):` spans.

        The sanctioned block IS the designed-sync-point annotation — the
        runtime guard and the lint (JX001/JX010) agree on the same
        marker, so a site is never annotated twice."""
        sanctioned: List[Tuple[int, int, str]] = []
        for node in _walk_shallow(func):
            if isinstance(node, ast.With):
                for item in node.items:
                    c = item.context_expr
                    if isinstance(c, ast.Call) and _call_name(c).endswith(
                        "sanctioned_transfer"
                    ):
                        tag = ""
                        if c.args and isinstance(c.args[0], ast.Constant):
                            tag = str(c.args[0].value)
                        sanctioned.append(
                            (node.lineno, node.end_lineno or node.lineno,
                             tag)
                        )

        def sanction_tag(line: int) -> Optional[str]:
            for lo, hi, tag in sanctioned:
                if lo <= line <= hi:
                    return tag or "sanctioned"
            return None

        return sanction_tag

    def _check_host_sync(self, func: ast.AST, qualname: str) -> None:
        taint = _Taint()
        for stmt in _walk_shallow(func):
            if isinstance(stmt, ast.stmt):
                taint.feed(stmt)
        sanction_tag = self._sanction_lookup(func)
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            tag = sanction_tag(node.lineno)
            if tag is not None:
                n_before = len(self.violations)
                self._try_host_sync_call(node, qualname, taint)
                for v in self.violations[n_before:]:
                    v.suppressed = True
                    v.suppression_reason = (
                        f"sanctioned_transfer({tag!r})"
                    )
                continue
            self._try_host_sync_call(node, qualname, taint)

    def _try_host_sync_call(
        self, node: ast.Call, qualname: str, taint: "_Taint"
    ) -> None:
        name = _call_name(node)
        if name == "jax.device_get":
            self._emit("JX001", node, qualname,
                       "jax.device_get blocks on a device->host read")
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            self._emit("JX001", node, qualname,
                       ".item() blocks on a device->host read")
        elif name in SYNC_BUILTINS and len(node.args) == 1:
            if _is_host_metadata(node.args[0]):
                return
            if taint.covers(node.args[0]):
                self._emit(
                    "JX001", node, qualname,
                    f"{name}() on a device value blocks the dispatch "
                    "stream for a host round trip",
                )
        elif name in ("np.asarray", "numpy.asarray") and node.args:
            if taint.covers(node.args[0]):
                self._emit(
                    "JX001", node, qualname,
                    "np.asarray() of a device value is a blocking "
                    "device->host transfer",
                )

    # -- JX010 -------------------------------------------------------------

    def _check_obstacle_staging(self, func: ast.AST, qualname: str) -> None:
        """{np,jnp}.asarray on a ``self.X``/``ob.X``/``s.X`` attribute
        inside a step-loop function: the same obstacle/driver mirror
        crosses the host boundary again every step.  Precision-first like
        the rest of the linter — only attribute reads off the
        conventional state receivers fire, and ``sanctioned_transfer``
        blocks suppress just as they do for JX001."""
        sanction_tag = self._sanction_lookup(func)
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _call_name(node)
            if name not in ASARRAY_NAMES:
                continue
            arg = node.args[0]
            if not isinstance(arg, ast.Attribute) or _is_host_metadata(arg):
                continue
            root = arg
            while isinstance(root, ast.Attribute):
                root = root.value
            if not (isinstance(root, ast.Name)
                    and root.id in JX010_STATE_ROOTS):
                continue
            direction = (
                "host->device upload"
                if name.split(".", 1)[0].lstrip("_") in ("jnp", "jax")
                else "device->host read"
            )
            n_before = len(self.violations)
            self._emit(
                "JX010", node, qualname,
                f"{name}({_dotted(arg)}) re-stages loop-carried "
                f"obstacle/driver state every step ({direction}); cache "
                "the mirror identity-keyed, derive it on device, or "
                "carry it in the scan state",
            )
            tag = sanction_tag(node.lineno)
            if tag is not None:
                for v in self.violations[n_before:]:
                    if not v.suppressed:
                        v.suppressed = True
                        v.suppression_reason = (
                            f"sanctioned_transfer({tag!r})"
                        )

    # -- JX002 -------------------------------------------------------------

    def _check_jit_sites(self, func: ast.AST, qualname: str) -> None:
        # assignment-target text per jit call, so `self._step = jax.jit(f)`
        # is step-shaped even when f's own name is opaque
        targets: Dict[int, str] = {}
        for stmt in _walk_shallow(func):
            if isinstance(stmt, ast.Assign):
                t = " ".join(_dotted(x) for x in stmt.targets)
                for sub in ast.walk(stmt.value):
                    if isinstance(sub, ast.Call):
                        targets[id(sub)] = t
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            wrapped = _jit_target(node)
            if wrapped is None:
                continue
            step_shaped = (
                STEP_SHAPE_RE.search(_inner_name(wrapped))
                or STEP_SHAPE_RE.search(targets.get(id(node), ""))
                or STEP_SHAPE_RE.search(qualname)
            )
            if step_shaped and not _has_kw(node, "donate_argnums"):
                self._emit(
                    "JX002", node, qualname,
                    "step-shaped jax.jit without donate_argnums: the "
                    "state buffers are copied instead of updated in "
                    "place",
                )

    # -- JX007 -------------------------------------------------------------

    def _check_jit_in_regrid_path(self, func: ast.AST, qualname: str) -> None:
        """jax.jit construction per-regrid or per-loop-pass: the exact
        bug class capacity bucketing removes (sim/amr.py compiled-step
        cache).  Fires on a jit-construction call that is (a) inside a
        loop/comprehension body, or (b) anywhere in a function whose
        qualname marks it as an adaptation-path rebuild."""
        in_adapt = bool(ADAPT_FUNC_RE.search(qualname))

        def is_jit_construction(node: ast.AST) -> bool:
            return (
                isinstance(node, ast.Call)
                and (_jit_target(node) is not None
                     or _is_partial_of_jit(node))
            )

        loop_hits: Set[int] = set()
        for loop in _walk_shallow(func):
            if not isinstance(loop, LOOP_NODES):
                continue
            stack = list(ast.iter_child_nodes(loop))
            while stack:
                node = stack.pop()
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)
                ):
                    continue  # nested defs get their own visit
                if is_jit_construction(node):
                    loop_hits.add(id(node))
                    self._emit(
                        "JX007", node, qualname,
                        "jax.jit built inside a loop body creates a "
                        "fresh (cold-cache) jit object every pass; "
                        "hoist it and reuse, or cache by shape bucket",
                    )
                stack.extend(ast.iter_child_nodes(node))
        if not in_adapt:
            return
        for node in _walk_shallow(func):
            if is_jit_construction(node) and id(node) not in loop_hits:
                self._emit(
                    "JX007", node, qualname,
                    "jax.jit built on the adaptation path recompiles "
                    "every regrid even when shapes match (per-object "
                    "trace cache); build once and cache by bucket "
                    "(sim/amr.py compiled-step cache)",
                )

    # -- JX003 -------------------------------------------------------------

    def _check_traced_control_flow(
        self, func: ast.AST, qualname: str, static: Set[str]
    ) -> None:
        args = func.args
        params = {
            a.arg
            for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
            )
        } - static - {"self"}
        for node in ast.walk(func):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                if _is_none_check(node.test):
                    continue  # `x is (not) None`: static under trace
                traced = params & _names_in(node.test)
                if traced:
                    kind = type(node).__name__.lower()
                    self._emit(
                        "JX003", node, qualname,
                        f"Python {kind} on traced argument(s) "
                        f"{sorted(traced)} inside a jitted body (implicit "
                        "__bool__ host sync or ConcretizationTypeError); "
                        "use lax.cond/lax.while_loop/jnp.where or mark "
                        "the argument static",
                    )

    # -- JX004 -------------------------------------------------------------

    def _check_loop_construction(self, func: ast.AST, qualname: str) -> None:
        for loop in _walk_shallow(func):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if (
                    name.split(".", 1)[0].lstrip("_") in ("jnp", "jax")
                    and "." in name
                    and name.rsplit(".", 1)[-1] in JNP_CONSTRUCTORS
                ):
                    self._emit(
                        "JX004", node, qualname,
                        f"{name}() inside a per-step Python loop "
                        "dispatches one host->device upload per "
                        "iteration; hoist or batch it",
                    )

    # -- JX005 -------------------------------------------------------------

    def _check_dtype_literals(self) -> None:
        if not re.search(r"cup3d_tpu/(sim|ops|grid|stream|models)/",
                         self.path):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                if _dotted(node) in ("jnp.float64", "jax.numpy.float64"):
                    self._emit(
                        "JX005", node, "<module>",
                        "jnp.float64 literal in device code; take the "
                        "dtype from the config (sim.dtype)",
                    )
            elif isinstance(node, ast.Call) and _is_jnp_call(node):
                for kw in node.keywords:
                    if kw.arg == "dtype" and (
                        (isinstance(kw.value, ast.Constant)
                         and kw.value.value == "float64")
                        or _dotted(kw.value) in (
                            "np.float64", "numpy.float64", "jnp.float64"
                        )
                    ):
                        self._emit(
                            "JX005", node, "<module>",
                            "float64 dtype literal in a jnp constructor",
                        )

    # -- JX006 -------------------------------------------------------------

    def _check_timing_windows(self, func: ast.AST, qualname: str) -> None:
        """Between consecutive perf_counter() reads (and from function
        start to the first one) there must be a sync: block_until_ready,
        a host read (float/int/np.asarray/.item), or nothing dispatched
        at all (no calls in the window)."""
        pc_lines: List[int] = []
        sync_lines: List[int] = []
        call_lines: List[int] = []
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            line = node.lineno
            if name.endswith("perf_counter"):
                pc_lines.append(line)
            elif (
                name in SYNC_BUILTINS
                or name in ("np.asarray", "numpy.asarray")
                or name.endswith("block_until_ready")
                or (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item")
            ):
                sync_lines.append(line)
            else:
                call_lines.append(line)
        if len(pc_lines) < 2:
            return
        pc_lines.sort()
        start = func.lineno
        for pc in pc_lines:
            window = (start, pc)
            dispatches = any(window[0] <= l <= window[1]
                             for l in call_lines)
            synced = any(window[0] <= l <= window[1] for l in sync_lines)
            if dispatches and not synced:
                v = Violation(
                    rule="JX006", path=self.path, line=pc, col=0,
                    func=qualname,
                    message=(
                        "perf_counter() read with dispatched device work "
                        "and no block_until_ready/host-read sync since "
                        f"line {window[0]}: the window times dispatch, "
                        "not device execution"
                    ),
                )
                reason = self.suppressions.get(pc, {}).get("JX006")
                if reason is not None:
                    v.suppressed = True
                    v.suppression_reason = reason or None
                self.violations.append(v)
            start = pc

    # -- JX008 -------------------------------------------------------------

    def _check_manual_timing(self, func: ast.AST, qualname: str) -> None:
        """``time.perf_counter()`` inside the package but outside the obs
        layer: a private timing channel the registry/trace/flight layer
        never sees.  One finding per function (the first read in source
        order), so one annotation covers a timed section; the obs layer
        itself is exempt by path, and so are bench.py/validation (they
        ARE timing harnesses, linted only for the other rules)."""
        if not self.path.startswith("cup3d_tpu/"):
            return
        if self.path.startswith("cup3d_tpu/obs/"):
            return
        first = None
        for node in _walk_shallow(func):
            if (isinstance(node, ast.Call)
                    and _call_name(node).endswith("perf_counter")):
                if first is None or node.lineno < first.lineno:
                    first = node
        if first is not None:
            self._emit(
                "JX008", first, qualname,
                "manual section timing outside cup3d_tpu/obs/: use obs "
                "spans (obs.trace.SpanTimer / the driver profiler) or "
                "obs metrics so the measurement reaches the registry "
                "and the step trace",
            )

    # -- JX014 -------------------------------------------------------------

    def _wallclock_call_names(self) -> Set[str]:
        """Dotted call names that read the WALL clock in this file,
        resolved from its imports: ``time.time`` under whatever alias
        the time module was imported as, the bare name ``from time
        import time [as X]`` leaves behind, and the datetime
        now/utcnow/today constructors."""
        cached = getattr(self, "_jx014_names", None)
        if cached is not None:
            return cached
        names: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name
                    if a.name == "time":
                        names.add(f"{alias}.time")
                    elif a.name == "datetime":
                        for attr in JX014_DATETIME_ATTRS:
                            names.add(f"{alias}.datetime.{attr}")
                            names.add(f"{alias}.date.{attr}")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for a in node.names:
                        if a.name == "time":
                            names.add(a.asname or a.name)
                elif node.module == "datetime":
                    for a in node.names:
                        if a.name in ("datetime", "date"):
                            alias = a.asname or a.name
                            for attr in JX014_DATETIME_ATTRS:
                                names.add(f"{alias}.{attr}")
        self._jx014_names = names
        return names

    def _check_wallclock_duration(self, func: ast.AST,
                                  qualname: str) -> None:
        """Subtraction whose operands trace back to wall-clock reads:
        a duration computed from ``time.time()``/``datetime.now()``
        (directly, or through names/attributes assigned from them in
        this function).  Timestamp-only uses never subtract and stay
        silent; subtracting a numeric CONSTANT from a wall-clock read
        is timestamp arithmetic ("an hour ago") and stays silent too."""
        if not self.path.startswith("cup3d_tpu/"):
            return
        wall = self._wallclock_call_names()
        if not wall:
            return

        def is_wall_call(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and _call_name(node) in wall)

        # names/attributes assigned from a wall-clock read, iterated to
        # a fixpoint so t1 = time.time(); t2 = t1 taints t2 as well
        tainted: Set[str] = set()
        stmts = [n for n in _walk_shallow(func)
                 if isinstance(n, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign))]
        for _ in range(3):
            grew = False
            for stmt in stmts:
                value = stmt.value
                if value is None:
                    continue
                hit = any(is_wall_call(n) for n in ast.walk(value)) or any(
                    isinstance(n, (ast.Name, ast.Attribute))
                    and _dotted(n) in tainted
                    for n in ast.walk(value)
                )
                if not hit:
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    for leaf in ast.walk(t):
                        name = _dotted(leaf)
                        if name and name not in tainted:
                            tainted.add(name)
                            grew = True
            if not grew:
                break

        def is_wallish(node: ast.AST) -> bool:
            if is_wall_call(node):
                return True
            return (isinstance(node, (ast.Name, ast.Attribute))
                    and _dotted(node) in tainted)

        for node in _walk_shallow(func):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            l_wall, r_wall = is_wallish(node.left), is_wallish(node.right)
            if not (l_wall or r_wall):
                continue
            other = node.right if l_wall else node.left
            if isinstance(other, ast.Constant):
                continue  # timestamp arithmetic, not a duration
            self._emit(
                "JX014", node, qualname,
                "wall-clock subtraction used as a duration: "
                "time.time()/datetime.now() differences are NTP-"
                "slewed and can go negative — use the monotonic "
                "clock (obs.trace.now() at lifecycle seams, or obs "
                "spans/metrics) for durations",
            )

    # -- JX012 -------------------------------------------------------------

    def _check_profiler_usage(self, func: ast.AST, qualname: str) -> None:
        """Direct ``jax.profiler`` access — ``import``/``from`` imports
        or dotted ``jax.profiler.*`` chains — inside the package but
        outside the obs layer: a second, uncoordinated profiling channel
        (the profiler session is process-global).  Mirrors the JX008
        pattern: one finding per function/module (the first hit in
        source order, so one annotation covers a capture block); the obs
        layer owns the profiler and is exempt by path, and so are
        bench.py/validation harnesses (outside the package)."""
        if not self.path.startswith("cup3d_tpu/"):
            return
        if self.path.startswith("cup3d_tpu/obs/"):
            return
        first = None
        for node in _walk_shallow(func):
            hit = False
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                hit = (mod == "jax.profiler"
                       or mod.startswith("jax.profiler."))
            elif isinstance(node, ast.Import):
                hit = any(
                    a.name == "jax.profiler"
                    or a.name.startswith("jax.profiler.")
                    for a in node.names
                )
            elif isinstance(node, ast.Attribute):
                name = _dotted(node)
                hit = (name == "jax.profiler"
                       or name.startswith("jax.profiler."))
            if hit and (first is None or node.lineno < first.lineno):
                first = node
        if first is not None:
            self._emit(
                "JX012", first, qualname,
                "direct jax.profiler use outside cup3d_tpu/obs/: use obs "
                "profile windows (obs.profile.CONTROLLER / "
                "CaptureController.capture()) and obs spans "
                "(CUP3D_TRACE_XLA=1) so captures coordinate and land on "
                "the merged host+device timeline",
            )

    # -- JX011 -------------------------------------------------------------

    def _dtype_aliases(self) -> Dict[str, str]:
        """Module-level ``_F32 = jnp.float32``-style aliases, so the
        idiomatic local dtype names resolve like the dotted originals."""
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                leaf = _dotted(node.value).rsplit(".", 1)[-1]
                if leaf in ("bfloat16", "float32", "float64"):
                    aliases[node.targets[0].id] = leaf
        return aliases

    def _dtype_leaf(self, node: ast.AST, aliases: Dict[str, str]) -> str:
        """'bfloat16'/'float32'/... for a dtype expression ('' unknown)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        name = _dotted(node)
        if not name:
            return ""
        if "." not in name:
            return aliases.get(name, name)
        return name.rsplit(".", 1)[-1]

    def _cast_dtype(self, call: ast.Call, aliases: Dict[str, str]) -> str:
        """The dtype a call casts/constructs to: ``x.astype(D)`` or a jnp
        constructor/reduction with ``dtype=D`` ('' when neither)."""
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "astype" and call.args):
            return self._dtype_leaf(call.args[0], aliases)
        if _is_jnp_call(call):
            for kw in call.keywords:
                if kw.arg == "dtype":
                    return self._dtype_leaf(kw.value, aliases)
        return ""

    def _check_bf16_reduction(self, func: ast.AST, qualname: str) -> None:
        """Reductions over bf16-tainted operands without an explicit
        accumulator dtype (JX011).  Precision-first: taint starts ONLY at
        an explicit bfloat16 cast/construction (``.astype(jnp.bfloat16)``,
        ``dtype=jnp.bfloat16``, module aliases included) and propagates
        through assignments; an f32/f64 re-cast launders.  A reduction
        call (jnp.sum/dot/vdot/...) whose operand is tainted and that
        names no ``dtype=``/``preferred_element_type=`` accumulator
        fires."""
        if not hasattr(self, "_jx011_aliases"):
            self._jx011_aliases = self._dtype_aliases()
        aliases = self._jx011_aliases

        def value_taint(value: ast.AST, tainted: Set[str]) -> bool:
            top = value
            if (isinstance(top, ast.Call)
                    and self._cast_dtype(top, aliases)
                    in ("float32", "float64")):
                return False  # explicit up-cast launders
            for n in ast.walk(value):
                if (isinstance(n, ast.Call)
                        and self._cast_dtype(n, aliases) == "bfloat16"):
                    return True
            return bool(tainted & _names_in(value))

        tainted: Set[str] = set()
        for stmt in _walk_shallow(func):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            hit = value_taint(value, tainted)
            for t in targets:
                stack = [t]
                while stack:
                    leaf = stack.pop()
                    if isinstance(leaf, ast.Name):
                        (tainted.add if hit
                         else tainted.discard)(leaf.id)
                    elif isinstance(leaf, (ast.Tuple, ast.List)):
                        stack.extend(leaf.elts)
                    elif isinstance(leaf, ast.Starred):
                        stack.append(leaf.value)

        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            root = name.split(".", 1)[0].lstrip("_")
            if (name.rsplit(".", 1)[-1] not in JX011_REDUCTIONS
                    or "." not in name
                    or root not in ("jnp", "jax", "lax", "np", "numpy")):
                continue
            if any(kw.arg in JX011_ACCUM_KWARGS for kw in node.keywords):
                continue
            if any(value_taint(a, tainted) for a in node.args):
                self._emit(
                    "JX011", node, qualname,
                    f"{name}() over bfloat16 operands reduces in storage "
                    "precision; name the f32 accumulator explicitly "
                    "(dtype=/preferred_element_type=) or up-cast the "
                    "operand first (ops/precision.py policy)",
                )

    # -- JX013 -------------------------------------------------------------

    def _check_lane_device_loop(self, func: ast.AST, qualname: str) -> None:
        """Python loop over the lane/scenario axis that dispatches device
        work per iteration (JX013, fleet/ only).  A loop 'walks the lane
        axis' when its target or any name in its iterable matches
        JX013_AXIS_RE (``lane``, ``lanes``, ``scenario``...); it fires
        when the loop body then makes a device call (jnp./jax. dotted
        call or a ``self._name(...)`` jitwrapper) — the B lanes exist to
        be advanced by ONE vmapped dispatch, not B host dispatches.
        Host-only lane loops (assembly, QoI fan-out) never fire."""
        for node in _walk_shallow(func):
            if not isinstance(node, LOOP_NODES) or isinstance(
                    node, ast.While):
                continue  # while has no axis target to classify
            if isinstance(node, ast.For):
                axis_src = [node.target, node.iter]
            else:  # comprehensions: every generator's target + iterable
                axis_src = [p for g in node.generators
                            for p in (g.target, g.iter)]
            names: Set[str] = set()
            for piece in axis_src:
                names |= _names_in(piece)
                names |= {a.attr for a in ast.walk(piece)
                          if isinstance(a, ast.Attribute)}
            if not any(JX013_AXIS_RE.search(n) for n in names):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _is_device_call(sub):
                    self._emit(
                        "JX013", sub, qualname,
                        f"`{_call_name(sub)}()` dispatches device work "
                        "per iteration of a lane/scenario-axis loop; "
                        "vectorize over the batch axis instead "
                        "(fleet/batch.py vmap advance, lane-masked "
                        "jnp.where selects)",
                    )
                    break

    # -- JX015 -------------------------------------------------------------

    def _check_batch_reassembly(self, func: ast.AST, qualname: str) -> None:
        """Full-batch host reassembly on the per-tick fleet fast path
        (JX015, fleet/ only).  Fires inside functions named like the
        K-boundary seam (JX015_FUNC_RE: tick/reseed/dispatch) on calls
        that restack the whole lane axis — ``jnp.stack``/``np.stack``/
        ``concatenate`` (any jnp/np/jax/lax root) or the assembly
        helpers ``stack_carries``/``stack_gaits`` under any dotted
        prefix.  A reseed must replace ONE lane through the jitted
        ``.at[lane].set`` upload (fleet/batch.py reseed_lane_carry /
        reseed_lane_gaits); rebuilding the B-lane pytree host-side
        every boundary is O(B) host work plus a full re-upload, and it
        breaks the bitwise-untouched guarantee for the other B-1
        lanes.  Batch construction (assemble/__init__) stacks
        legitimately and never matches the function-name gate."""
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if not JX015_FUNC_RE.search(func.name):
            return
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            leaf = name.rsplit(".", 1)[-1]
            if leaf in JX015_ASSEMBLY_HELPERS:
                pass  # repo helpers stack by construction, any prefix
            elif leaf in JX015_STACKERS:
                root = name.split(".", 1)[0].lstrip("_")
                if "." not in name or root not in (
                        "jnp", "jax", "lax", "np", "numpy"):
                    continue  # bare/unknown-root stack(): not an array op
            else:
                continue
            self._emit(
                "JX015", node, qualname,
                f"`{name}()` reassembles the full lane-stacked batch "
                "inside a per-tick path; replace one lane via the "
                "jitted `.at[lane].set` upload instead "
                "(fleet/batch.py reseed_lane_carry/reseed_lane_gaits)",
            )

    # -- JX016 -------------------------------------------------------------

    def _check_sharded_materialization(
        self, func: ast.AST, qualname: str
    ) -> None:
        """Full-array materialization inside a sharded step path
        (JX016, sim|fleet|parallel only).  Fires inside functions named
        like the steady-state seam (JX016_FUNC_RE: step/advance/
        dispatch/megaloop) on ``jax.device_get``, ``np.asarray`` /
        ``np.array``, and the single-argument form of
        ``jax.device_put`` — each of which gathers a (possibly mesh-
        sharded) array whole onto one host or one device.
        ``device_put(x, sharding)`` with an explicit placement is the
        sanctioned way to move data and never matches; ``jnp.asarray``
        stays a device-side cast and is JX004/JX010's business.  Calls
        inside a ``with sanctioned_transfer(...)`` block are exempt —
        that context manager IS the designed-sync-point marker the
        runtime transfer guard audits (analysis/runtime.py)."""
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if not JX016_FUNC_RE.search(func.name):
            return
        if JX016_BUILDER_RE.match(func.name):
            return
        sanctioned: Set[int] = set()
        for node in _walk_shallow(func):
            if isinstance(node, ast.With) and any(
                isinstance(it.context_expr, ast.Call)
                and _call_name(it.context_expr).rsplit(".", 1)[-1]
                == "sanctioned_transfer"
                for it in node.items
            ):
                for sub in ast.walk(node):
                    sanctioned.add(id(sub))
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call) or id(node) in sanctioned:
                continue
            name = _call_name(node)
            leaf = name.rsplit(".", 1)[-1]
            root = name.split(".", 1)[0].lstrip("_")
            if leaf == "device_get" and root in ("jax",):
                what = "pulls the full array to the host"
            elif (leaf in ("asarray", "array")
                  and root in ("np", "numpy")):
                what = "materializes the full array host-side"
            elif (leaf == "device_put" and root in ("jax",)
                    and len(node.args) == 1 and not node.keywords):
                what = ("re-places the full array onto the default "
                        "device (no explicit sharding)")
            else:
                continue
            self._emit(
                "JX016", node, qualname,
                f"`{name}()` {what} inside a sharded step path — a "
                "cross-shard gather under the 2-D mesh; slice shard-"
                "locally under shard_map or place with an explicit "
                "`device_put(x, sharding)`",
            )

    # -- JX018 -------------------------------------------------------------

    def _check_raw_collectives(self, func: ast.AST, qualname: str) -> None:
        """Raw communicating-collective call sites outside the
        ``cup3d_tpu/parallel/`` seam (JX018).  Matches ``lax.psum`` /
        ``jax.lax.ppermute`` / bare ``all_gather`` (from-import) style
        calls whose leaf name is one of JX018_COLLECTIVES; dotted
        prefixes other than jax/lax (e.g. a wrapper object's method)
        never fire.  ``axis_index`` is exempt by omission — it reads a
        shard-local coordinate and communicates nothing."""
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in JX018_COLLECTIVES:
                continue
            root = name.split(".", 1)[0]
            if "." in name and root not in ("jax", "lax"):
                continue
            self._emit(
                "JX018", node, qualname,
                f"raw collective `{name}()` outside cup3d_tpu/parallel/ "
                "— route it through the parallel/ seam (ring.ring_shift, "
                "collectives.all_gather_tiled/pmax_axis, ...) so the IR "
                "audit has one place to prove axis/permutation "
                "invariants",
            )

    # -- JX017 -------------------------------------------------------------

    def _check_hardware_peaks(self, func: ast.AST, qualname: str) -> None:
        """Hand-typed hardware peak/bandwidth literal in a roofline or
        bench reporting path (JX017).  A numeric constant >= 1e9 that
        is not an exact power of ten reads like a spec sheet
        (``197e12`` FLOP/s, ``819e9`` B/s) and bakes ONE device kind
        into math that runs on every backend — MFU and HBM fractions
        then silently lie on other hardware.  Exact powers of ten are
        unit conversions (``1e9`` for GB, ``1e12`` for T) and stay
        legal.  The sanctioned home for the literals is the
        provenance-annotated device-kind table in ``obs/costs.py``
        (path-exempt); consumers resolve the LIVE device through
        ``obs.costs.device_peaks()``."""
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Constant):
                continue
            v = node.value
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            v = float(v)
            if v < JX017_MIN_MAGNITUDE or _is_power_of_ten(v):
                continue
            self._emit(
                "JX017", node, qualname,
                f"numeric literal {node.value!r} in a roofline/bench "
                "path looks like a hand-typed hardware peak — resolve "
                "the live device via obs.costs.device_peaks() (the "
                "obs/costs.py table is the one sanctioned home for "
                "spec-sheet numbers)",
            )

    # -- JX019 -------------------------------------------------------------

    def _check_aot_seam(self, func: ast.AST, qualname: str) -> None:
        """Direct AOT compile / jit-warmup call site outside the
        executable-store seam (JX019).  Two shapes fire: a chained
        ``fn.lower(...).compile()`` (Attribute ``compile`` called on a
        Call of Attribute ``lower``) and an immediately-invoked
        ``jit(f)(...)`` / ``jax.jit(f)(...)`` warmup.  Both compile an
        XLA executable the persistent store never sees — paid again
        every boot, invisible to the aot.* counters.  Split lowering
        (``lowered = fn.lower(...)`` then introspection, the
        analysis/audit.py pattern) never fires: IR-only reads are not
        warmups."""
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "compile"
                    and isinstance(f.value, ast.Call)
                    and isinstance(f.value.func, ast.Attribute)
                    and f.value.func.attr == "lower"):
                self._emit(
                    "JX019", node, qualname,
                    "chained `.lower().compile()` outside the "
                    "cup3d_tpu/aot/ store seam — wrap the jitted "
                    "callable with aot.store_backed() and call "
                    ".warm()/.ensure_compiled() so previously-seen "
                    "signatures deserialize instead of recompiling",
                )
                continue
            if isinstance(f, ast.Call):
                name = _call_name(f)
                leaf = name.rsplit(".", 1)[-1]
                root = name.split(".", 1)[0]
                if leaf == "jit" and ("." not in name
                                      or root == "jax"):
                    self._emit(
                        "JX019", node, qualname,
                        f"immediately-invoked `{name}(...)(...)` "
                        "warmup compiles outside the cup3d_tpu/aot/ "
                        "store seam — bind the jit once, wrap it with "
                        "aot.store_backed(), and warm through the "
                        "wrapper",
                    )

    # -- JX020 -------------------------------------------------------------

    def _raw_clock_names(self) -> Set[str]:
        """Call names that read a raw ``time``-module clock in this
        file, resolved from its imports: ``time.monotonic`` (etc.)
        under whatever alias the module was imported as, plus the bare
        names ``from time import monotonic [as X]`` leaves behind."""
        cached = getattr(self, "_jx020_names", None)
        if cached is not None:
            return cached
        names: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        alias = a.asname or a.name
                        for attr in JX020_CLOCK_ATTRS:
                            names.add(f"{alias}.{attr}")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for a in node.names:
                        if a.name in JX020_CLOCK_ATTRS:
                            names.add(a.asname or a.name)
        self._jx020_names = names
        return names

    def _check_raw_clock(self, func: ast.AST, qualname: str) -> None:
        """Raw ``time.monotonic()``/``time.time()``/``perf_counter()``
        (and ``*_ns`` variants) inside the package outside
        ``obs/trace.py``: a second clock domain.  The round-22 phase
        decomposition partitions end-to-end latency only because every
        lifecycle timestamp comes off the ONE monotonic clock behind
        ``obs.trace.now()``; wall stamps go through
        ``obs.trace.wall()``.  One finding per function (first read in
        source order) — one fix usually rewires the whole function."""
        clocks = self._raw_clock_names()
        if not clocks:
            return
        first = None
        for node in _walk_shallow(func):
            if isinstance(node, ast.Call) and _call_name(node) in clocks:
                if first is None or node.lineno < first.lineno:
                    first = node
        if first is not None:
            self._emit(
                "JX020", first, qualname,
                f"raw clock read `{_call_name(first)}()` outside "
                "cup3d_tpu/obs/trace.py splits the package across "
                "clock domains — use obs.trace.now() for monotonic "
                "reads or obs.trace.wall() for wall-time stamps",
            )

    # -- JX021 -------------------------------------------------------------

    def _check_status_mutation(self, func: ast.AST,
                               qualname: str) -> None:
        """Direct ``<job>.status = ...`` assignment outside the
        journal-logging seams (JX021, fleet/ only).  Every fleet job
        state transition must flow through a sanctioned seam
        (JX021_SANCTIONED_RE: first assembly, retire, reseed splice,
        cancel, prepare-failure, journal replay) — those are the
        functions whose transitions the round-23 write-ahead journal
        records, directly or via ``_job_terminal``/``mark``.  A status
        flip anywhere else is a lifecycle edge recovery can never
        replay: the job would be silently lost (or doubled) across a
        crash-restart.  One finding per assignment — each is its own
        unjournaled edge."""
        leaf = qualname.rsplit(".", 1)[-1]
        if JX021_SANCTIONED_RE.match(leaf):
            return
        for node in _walk_shallow(func):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "status":
                    self._emit(
                        "JX021", node, qualname,
                        "fleet job status mutated outside the "
                        "journal-logging seam — route the transition "
                        "through _job_terminal/mark (or a sanctioned "
                        "seam: " + JX021_SANCTIONED_RE.pattern + ") so "
                        "the write-ahead journal records it and "
                        "crash recovery can replay it",
                    )

    # -- JX009 -------------------------------------------------------------

    #: attribute names of log-like drop calls (log-and-drop handlers)
    _LOG_ATTRS = frozenset(
        {"warn", "warning", "error", "info", "debug", "exception"}
    )

    def _is_droppy_stmt(self, stmt: ast.stmt) -> bool:
        """A handler statement that drops the failure on the floor:
        pass/continue/break, a bare constant (docstring), or a pure
        log/print call.  Anything else — assignment, raise, return with
        a value, a counter ``.inc()`` — makes the handler observable."""
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        if isinstance(stmt, ast.Expr):
            v = stmt.value
            if isinstance(v, ast.Constant):
                return True
            if isinstance(v, ast.Call):
                name = _call_name(v)
                if name == "print" or name.endswith("warnings.warn"):
                    return True
                if (isinstance(v.func, ast.Attribute)
                        and v.func.attr in self._LOG_ATTRS):
                    return True
        return False

    def _check_swallowed_exceptions(self, func: ast.AST,
                                    qualname: str) -> None:
        """``except`` handlers whose whole body drops the failure (JX009).
        Package scope only; ``cup3d_tpu/resilience/`` is exempt — its
        handlers ARE the degradation policy and carry their own
        counters."""
        if not self.path.startswith("cup3d_tpu/"):
            return
        if self.path.startswith("cup3d_tpu/resilience/"):
            return
        for node in _walk_shallow(func):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.body and all(self._is_droppy_stmt(s)
                                 for s in node.body):
                self._emit(
                    "JX009", node, qualname,
                    "exception swallowed (pass/log-and-drop): re-raise, "
                    "latch it into state, or bump an obs counter so the "
                    "failure is observable",
                )


# -- baseline ---------------------------------------------------------------


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str]) -> Dict[Tuple[str, str, str], dict]:
    if path is None or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out = {}
    for e in data.get("entries", []):
        out[(e["rule"], e["path"], e["func"])] = {
            "reason": e.get("reason", ""),
            "count": int(e.get("count", 1)),
            "used": 0,
        }
    return out


def apply_baseline(
    violations: List[Violation],
    baseline: Dict[Tuple[str, str, str], dict],
) -> None:
    """Mark violations covered by the baseline (up to each entry's count —
    NEW violations in an already-baselined function still fail)."""
    for v in violations:
        if v.suppressed:
            continue
        entry = baseline.get(v.key())
        if entry is not None and entry["used"] < entry["count"]:
            entry["used"] += 1
            v.baselined = True


def write_baseline(violations: List[Violation], path: str) -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    for v in violations:
        if v.suppressed:
            continue
        counts[v.key()] = counts.get(v.key(), 0) + 1
    entries = [
        {"rule": r, "path": p, "func": f, "count": c,
         "reason": "TODO: justify or fix"}
        for (r, p, f), c in sorted(counts.items())
    ]
    with open(path, "w") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2)
        fh.write("\n")


# -- entry points -----------------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def repo_relative(path: str) -> str:
    """Normalize to a posix path rooted at the repo (the directory that
    contains the ``cup3d_tpu`` package), so baseline entries are stable
    regardless of the CWD the CLI runs from."""
    ap = os.path.abspath(path).replace(os.sep, "/")
    marker = "/cup3d_tpu/"
    idx = ap.rfind(marker)
    if idx >= 0:
        return ap[idx + 1:]
    return os.path.basename(ap)


def lint_source(
    source: str, path: str = "<string>"
) -> List[Violation]:
    """Lint one source string (fixture tests use this directly)."""
    tree = ast.parse(source)
    return FileLint(path, tree, parse_suppressions(source)).run()


def lint_paths(
    paths: Sequence[str],
    baseline_path: Optional[str] = None,
    rules: Optional[Set[str]] = None,
) -> List[Violation]:
    violations: List[Violation] = []
    for fpath in _iter_py_files(paths):
        with open(fpath, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            violations.append(Violation(
                rule="JX000", path=repo_relative(fpath),
                line=e.lineno or 0, col=e.offset or 0, func="<module>",
                message=f"syntax error: {e.msg}",
            ))
            continue
        violations.extend(
            FileLint(repo_relative(fpath), tree,
                     parse_suppressions(source)).run()
        )
    if rules:
        violations = [v for v in violations if v.rule in rules]
    baseline = load_baseline(baseline_path)
    apply_baseline(violations, baseline)
    return violations


def failing(violations: Iterable[Violation]) -> List[Violation]:
    return [v for v in violations if not v.suppressed and not v.baselined]
