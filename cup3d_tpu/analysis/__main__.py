"""CLI: ``python -m cup3d_tpu.analysis [paths] [options]``.

Exit status 0 iff every violation is either inline-annotated
(``# jax-lint: allow(JX00n, reason)``) or covered by the baseline
(``analysis/baseline.json`` by default).  Typical invocations::

    python -m cup3d_tpu.analysis cup3d_tpu/            # the package
    python -m cup3d_tpu.analysis cup3d_tpu/ bench.py   # + the bench
    python -m cup3d_tpu.analysis --write-baseline ...  # start a burn-down
    python -m cup3d_tpu.analysis --no-baseline ...     # the raw picture
"""

from __future__ import annotations

import argparse
import json
import sys

from cup3d_tpu.analysis import lint as lint_mod
from cup3d_tpu.analysis.rules import RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cup3d_tpu.analysis",
        description="JAX-aware AST lint (rules JX001-JX008)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current violations to the baseline file "
                         "(reasons left as TODO for the author)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to check (e.g. "
                         "JX001,JX002)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only failing violations")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  {rule.title}")
            print(f"       {rule.rationale}\n")
        return 0

    paths = args.paths
    if not paths:
        import cup3d_tpu

        paths = [cup3d_tpu.__path__[0]]

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or lint_mod.default_baseline_path()
    rules = (set(r.strip().upper() for r in args.rules.split(","))
             if args.rules else None)

    violations = lint_mod.lint_paths(paths, baseline_path=baseline_path,
                                     rules=rules)
    if args.write_baseline:
        out = args.baseline or lint_mod.default_baseline_path()
        lint_mod.write_baseline(violations, out)
        print(f"baseline written: {out} "
              f"({len(lint_mod.failing(violations))} entries to justify)")
        return 0

    failing = lint_mod.failing(violations)
    if args.format == "json":
        print(json.dumps({
            "violations": [v.__dict__ for v in violations],
            "failing": len(failing),
        }, indent=2))
    else:
        shown = failing if args.quiet else violations
        for v in shown:
            print(v.format())
        n_sup = sum(1 for v in violations if v.suppressed)
        n_base = sum(1 for v in violations if v.baselined)
        print(
            f"jax-lint: {len(violations)} finding(s): {len(failing)} "
            f"failing, {n_sup} annotated, {n_base} baselined"
        )
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
