"""CLI: ``python -m cup3d_tpu.analysis [paths] [options]``.

Exit status 0 iff every violation is either inline-annotated
(``# jax-lint: allow(JX00n, reason)``) or covered by the baseline
(``analysis/baseline.json`` by default).  Typical invocations::

    python -m cup3d_tpu.analysis cup3d_tpu/            # the package
    python -m cup3d_tpu.analysis cup3d_tpu/ bench.py   # + the bench
    python -m cup3d_tpu.analysis --write-baseline ...  # start a burn-down
    python -m cup3d_tpu.analysis --no-baseline ...     # the raw picture

The second tier — the IR audit (rules JP001-JP005, traced jaxprs and
AOT-lowered executables of the canonical entry points) — runs as the
``audit`` subcommand::

    python -m cup3d_tpu.analysis audit                 # whole registry
    python -m cup3d_tpu.analysis audit --format json   # CI one-liner
    python -m cup3d_tpu.analysis audit --entries uniform_tgv_megaloop
    python -m cup3d_tpu.analysis audit --write-baseline
"""

from __future__ import annotations

import argparse
import json
import sys

from cup3d_tpu.analysis import lint as lint_mod
from cup3d_tpu.analysis.rules import RULES


def main_audit(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cup3d_tpu.analysis audit",
        description="IR audit: jaxpr/HLO checks over the canonical "
                    "entry points (rules JP001-JP005)",
    )
    ap.add_argument("--entries", default=None,
                    help="comma-separated registry entry names "
                         "(default: all)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs (e.g. JP001,JP003)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: "
                         "analysis/audit_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-entries", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only failing violations")
    args = ap.parse_args(argv)

    # platform bootstrap must precede the first jax device access —
    # audit.py imports jax lazily for exactly this reason
    from cup3d_tpu.analysis import audit as audit_mod

    audit_mod.bootstrap_platform()

    if args.list_entries:
        for ep in audit_mod.REGISTRY:
            mode = ("no-donation contract" if ep.expect_no_donation
                    else "donation checked")
            extra = "" if ep.compile else " (lowered-only)"
            print(f"{ep.name}  [{mode}{extra}]")
            for rule, reason in sorted(ep.allow.items()):
                print(f"    allow({rule}): {reason}")
        return 0

    entries = None
    if args.entries:
        wanted = {e.strip() for e in args.entries.split(",")}
        by_name = {ep.name: ep for ep in audit_mod.REGISTRY}
        unknown = wanted - set(by_name)
        if unknown:
            ap.error(f"unknown entries: {sorted(unknown)} "
                     f"(have: {sorted(by_name)})")
        entries = [by_name[n] for n in sorted(wanted)]

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or audit_mod.default_baseline_path()
    rules = (set(r.strip().upper() for r in args.rules.split(","))
             if args.rules else None)

    violations, metas = audit_mod.run_audit(
        entries, baseline_path=baseline_path, rules=rules)

    if args.write_baseline:
        out = args.baseline or audit_mod.default_baseline_path()
        lint_mod.write_baseline(violations, out)
        print(f"audit baseline written: {out} "
              f"({len(lint_mod.failing(violations))} entries to justify)")
        return 0

    failing = lint_mod.failing(violations)
    if args.format == "json":
        print(audit_mod.summary_line(violations, metas, baseline_path))
    else:
        shown = failing if args.quiet else violations
        for v in shown:
            print(v.format())
        n_sup = sum(1 for v in violations if v.suppressed)
        n_base = sum(1 for v in violations if v.baselined)
        n_skip = sum(1 for m in metas if m.get("skipped"))
        print(
            f"ir-audit: {len(metas)} entries ({n_skip} skipped), "
            f"{len(violations)} finding(s): {len(failing)} failing, "
            f"{n_sup} annotated, {n_base} baselined"
        )
    return 1 if failing else 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "audit":
        return main_audit(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m cup3d_tpu.analysis",
        description="JAX-aware AST lint (rules JX001-JX008)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current violations to the baseline file "
                         "(reasons left as TODO for the author)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to check (e.g. "
                         "JX001,JX002)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only failing violations")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  {rule.title}")
            print(f"       {rule.rationale}\n")
        return 0

    paths = args.paths
    if not paths:
        import cup3d_tpu

        paths = [cup3d_tpu.__path__[0]]

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or lint_mod.default_baseline_path()
    rules = (set(r.strip().upper() for r in args.rules.split(","))
             if args.rules else None)

    violations = lint_mod.lint_paths(paths, baseline_path=baseline_path,
                                     rules=rules)
    if args.write_baseline:
        out = args.baseline or lint_mod.default_baseline_path()
        lint_mod.write_baseline(violations, out)
        print(f"baseline written: {out} "
              f"({len(lint_mod.failing(violations))} entries to justify)")
        return 0

    failing = lint_mod.failing(violations)
    if args.format == "json":
        print(json.dumps({
            "violations": [v.__dict__ for v in violations],
            "failing": len(failing),
        }, indent=2))
    else:
        shown = failing if args.quiet else violations
        for v in shown:
            print(v.format())
        n_sup = sum(1 for v in violations if v.suppressed)
        n_base = sum(1 for v in violations if v.baselined)
        print(
            f"jax-lint: {len(violations)} finding(s): {len(failing)} "
            f"failing, {n_sup} annotated, {n_base} baselined"
        )
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
