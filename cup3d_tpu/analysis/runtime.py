"""Runtime sanitizers: the sound half of the analysis subsystem.

The AST lint (``analysis/lint``) is a precision-first heuristic; these
context managers check the same invariants at runtime, where device
placement is known exactly:

- :class:`RecompileCounter` — intercepts ``jax.jit`` so every jitted
  function created inside the context reports its compile count.  The
  steady-state contract (VALIDATION.md "Analysis subsystem") is that the
  step compiles EXACTLY ONCE per configuration: dt/lambda ride as traced
  scalars, so a second compile of the same function means a shape or
  dtype is leaking into the trace.
- :func:`no_implicit_transfers` — ``jax.transfer_guard("disallow")``
  scoped to a hot loop.  Every implicit device<->host transfer raises
  unless it happens inside :func:`sanctioned_transfer`, the allowlist
  hook that names the designed sync points (``umax-read``,
  ``qoi-read``, ``scalar-upload``, ...).  Sanctioned sites are recorded
  in :data:`TRANSFER_SITES` so tests can assert the allowlist is closed.
- :func:`debug_nans` / :func:`tracer_leak_checks` — opt-in wrappers over
  the jax debug flags, scoped instead of global.

Typical use (tests/test_analysis.py runs exactly this)::

    with RecompileCounter() as rc:
        sim = Simulation(cfg); sim.init()
        with no_implicit_transfers():
            for _ in range(5):
                sim.advance(sim.calc_max_timestep())
    rc.assert_steady_state()
"""

from __future__ import annotations

import threading
from contextlib import contextmanager, nullcontext
from typing import Callable, Dict, Iterable, Optional, Set

#: every sanctioned transfer site that has EVER fired in this process:
#: tag -> fire count.  The documented allowlist lives in VALIDATION.md;
#: tests assert observed tags are a subset of it.  Mirrored into the obs
#: registry as ``transfers.sanctioned{site=tag}`` counters (round 9) so
#: one metrics snapshot carries the transfer picture too.
TRANSFER_SITES: Dict[str, int] = {}

_local = threading.local()


def _allowed_tags() -> Optional[Set[str]]:
    """None = no restriction (every sanctioned site may open the guard)."""
    return getattr(_local, "allowed_tags", None)


@contextmanager
def no_implicit_transfers(allow: Optional[Iterable[str]] = None):
    """Run the body under ``jax.transfer_guard("disallow")``: any device
    sync or host upload OUTSIDE a :func:`sanctioned_transfer` block
    raises immediately, with a traceback pointing at the hidden sync —
    the runtime teeth behind lint rule JX001.

    ``allow`` restricts which sanctioned tags may open the guard while
    this context is active (the allowlist hook); ``None`` admits every
    sanctioned site.  Unknown tags raise at the offending site, not
    here, so the failure names the call stack that transferred.
    """
    import jax

    prev = _allowed_tags()
    _local.allowed_tags = set(allow) if allow is not None else None
    try:
        with jax.transfer_guard("disallow"):
            yield
    finally:
        _local.allowed_tags = prev


@contextmanager
def sanctioned_transfer(tag: str):
    """Mark a DESIGNED sync point: re-allows transfers for the body and
    records the site under ``tag``.  Outside :func:`no_implicit_transfers`
    this costs one thread-local check and a counter bump (the guard
    context itself is cheap, but we skip it entirely when jax is not
    imported yet so import-light paths stay import-light)."""
    allowed = _allowed_tags()
    if allowed is not None and tag not in allowed:
        raise RuntimeError(
            f"transfer site {tag!r} is not in the active allowlist "
            f"{sorted(allowed)}; either the hot loop grew a new sync "
            "point (fix it) or the allowlist in the caller is stale"
        )
    TRANSFER_SITES[tag] = TRANSFER_SITES.get(tag, 0) + 1
    from cup3d_tpu.obs import metrics as obs_metrics

    obs_metrics.counter("transfers.sanctioned", site=tag).inc()
    import sys

    jax = sys.modules.get("jax")
    ctx = jax.transfer_guard("allow") if jax is not None else nullcontext()
    with ctx:
        yield


class RecompileCounter:
    """Counts XLA compiles per jitted function.

    Entering the context monkeypatches ``jax.jit`` so every jit-wrapped
    function CREATED inside it is instrumented: each call compares the
    pjit cache size before and after, attributing cache growth to that
    function's name.  Functions jitted before the context opened (e.g.
    module-level ``@jax.jit`` decorations bound at import) are not
    counted — drivers construct their jits at __init__ time, so building
    the driver inside the context captures the full step.

    ``compiles`` maps function name -> number of distinct compiled
    specializations observed.  ``assert_steady_state()`` enforces the
    contract: every function compiled at most ``budget`` times (default
    1 — one trace per config, dt as a traced scalar)."""

    def __init__(self) -> None:
        self.compiles: Dict[str, int] = {}
        self.calls: Dict[str, int] = {}
        self._real_jit = None

    # -- counting ----------------------------------------------------------

    def _instrument(self, jitted, name: str):
        counter = self

        def wrapper(*args, **kwargs):
            try:
                before = jitted._cache_size()
            except Exception:
                before = None
            out = jitted(*args, **kwargs)
            counter.calls[name] = counter.calls.get(name, 0) + 1
            if before is not None:
                try:
                    grew = jitted._cache_size() - before
                except Exception:
                    grew = 0
                if grew > 0:
                    counter.compiles[name] = (
                        counter.compiles.get(name, 0) + grew
                    )
                    # compile events are rare by contract: mirror them
                    # into the obs registry (round 9) so a metrics
                    # snapshot answers "did anything retrace?"
                    from cup3d_tpu.obs import metrics as obs_metrics

                    obs_metrics.counter("jit.compiles", fn=name).inc(grew)
            return out

        wrapper.__name__ = f"counted({name})"
        wrapper.__wrapped__ = jitted
        # AOT/introspection passthrough for the odd caller that needs it
        wrapper.lower = getattr(jitted, "lower", None)
        wrapper._cache_size = getattr(jitted, "_cache_size", None)
        return wrapper

    def wrap(self, jitted, name: Optional[str] = None):
        """Instrument an existing jitted function explicitly."""
        return self._instrument(
            jitted, name or getattr(jitted, "__name__", repr(jitted))
        )

    # -- context -----------------------------------------------------------

    def __enter__(self) -> "RecompileCounter":
        import jax

        self._real_jit = jax.jit
        counter = self
        real = self._real_jit

        def counting_jit(fun=None, **kwargs):
            if fun is None:
                return lambda f: counting_jit(f, **kwargs)
            name = getattr(fun, "__name__", None)
            if name in (None, "<lambda>"):
                # partial(f, ...) and lambdas: dig for something stable
                inner = getattr(fun, "func", None)
                name = getattr(inner, "__name__", name) or repr(fun)
            return counter._instrument(real(fun, **kwargs), name)

        jax.jit = counting_jit
        return self

    def __exit__(self, *exc) -> None:
        import jax

        jax.jit = self._real_jit
        self._real_jit = None

    # -- assertions --------------------------------------------------------

    @property
    def total_compiles(self) -> int:
        return sum(self.compiles.values())

    def assert_steady_state(self, budget: int = 1,
                            ignore: Iterable[str] = ()) -> None:
        """Every instrumented function compiled at most ``budget`` times.
        A failure names the offender — the usual cause is a Python scalar
        or shape reaching the trace as a fresh constant each step."""
        skip = set(ignore)
        bad = {
            name: n for name, n in self.compiles.items()
            if n > budget and name not in skip
        }
        if bad:
            raise AssertionError(
                f"steady-state recompile budget ({budget}) exceeded: "
                f"{bad} (calls: { {k: self.calls.get(k) for k in bad} })"
            )


@contextmanager
def debug_nans(enabled: bool = True):
    """Scoped ``jax_debug_nans``: every jitted op re-checks its output
    and raises AT the producing primitive instead of propagating NaNs
    into the abort path N steps later.  Opt-in: it disables fusion-level
    performance, so never leave it on in production loops."""
    import jax

    if not enabled:
        yield
        return
    old = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", old)


@contextmanager
def tracer_leak_checks(enabled: bool = True):
    """Scoped ``jax_check_tracer_leaks``: a traced value escaping its
    transform (stashed on self, closed over by a callback) raises at the
    leak site instead of surfacing later as an opaque
    UnexpectedTracerError."""
    import jax

    if not enabled:
        yield
        return
    old = jax.config.jax_check_tracer_leaks
    jax.config.update("jax_check_tracer_leaks", True)
    try:
        yield
    finally:
        jax.config.update("jax_check_tracer_leaks", old)


def device_scalar(value, dtype, tag: str = "scalar-upload"):
    """Upload one host scalar through a sanctioned site and return the
    device array.  Hot loops use this for the per-step dt so the upload
    is the ONLY host->device traffic the step pays — and the transfer
    guard can prove it."""
    import jax.numpy as jnp

    with sanctioned_transfer(tag):
        return jnp.asarray(value, dtype)
