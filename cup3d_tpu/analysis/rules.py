"""Rule catalog for the JAX-aware lint (``cup3d_tpu.analysis.lint``).

Every hazard class that has actually cost this codebase wall-clock gets a
stable rule ID, so violations can be suppressed individually (inline
``# jax-lint: allow(JX00n, reason)``) or burned down against a checked-in
baseline (``analysis/baseline.json``) without ever turning the whole
checker off.

The catalog is the machine-checked half of the sanitizer contract in
VALIDATION.md ("Analysis subsystem: sanitizer contract"); the runtime
half (recompile counter, transfer guard) lives in ``analysis/runtime``.

Rule IDs are append-only: never renumber, never reuse.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    rationale: str


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            "JX001",
            "host sync in hot-path function",
            "float()/.item()/np.asarray()/jax.device_get() on device values "
            "inside step/solve-loop functions blocks the dispatch stream for "
            "a full device->host round trip (~75-200 ms over the tunneled "
            "TPU).  PR 1 measured SyncQoI at 86% of the 256^3 fish step "
            "before these were hoisted onto the stream/ data-plane.  Every "
            "remaining sync must be a designed, annotated sync point.",
        ),
        Rule(
            "JX002",
            "step-shaped jax.jit without donate_argnums",
            "A steady-state step function that maps state -> state and is "
            "jitted without donating the state buffers doubles the field "
            "working set in HBM and forces XLA to copy instead of aliasing "
            "in-place.  At 256^3 the vel+p fields are ~400 MB; donation "
            "makes the update O(1) extra memory.",
        ),
        Rule(
            "JX003",
            "Python control flow on traced values in a jitted body",
            "`if`/`while` on a traced value inside a jitted function either "
            "raises a ConcretizationTypeError or — when the value is an "
            "argument that jit treats as dynamic — silently forces a "
            "trace-time host sync and a recompile per branch outcome.  Use "
            "lax.cond/lax.while_loop or jnp.where, or mark the argument "
            "static.",
        ),
        Rule(
            "JX004",
            "device array construction inside a per-step Python loop",
            "jnp.asarray/jnp.zeros/... inside a Python loop that runs every "
            "step dispatches one host->device upload per iteration per "
            "step.  Hoist the construction out of the loop, batch the "
            "uploads, or keep the data device-resident across steps.",
        ),
        Rule(
            "JX006",
            "perf_counter timing window without a device sync",
            "Timing a region that dispatches device work without a "
            "block_until_ready()/host-read sync before the perf_counter "
            "reads measures DISPATCH latency, not device execution: on an "
            "async backend the reported time can be off by orders of "
            "magnitude in either direction.  Every timed window must sync "
            "before its start and before its closing read.",
        ),
        Rule(
            "JX007",
            "jax.jit construction inside an adaptation/step loop",
            "Creating a jax.jit wrapper inside a loop, or inside a "
            "function that runs per mesh adaptation (rebuild/adapt "
            "paths), makes a FRESH jit object each pass — jax's trace "
            "cache is per-object, so every regrid recompiles every step "
            "function even when all shapes match.  Measured on amr_tgv: "
            "5.50 s max step against a 0.118 s median (BENCH_r05).  "
            "Build jits once and cache them keyed on the shape bucket "
            "(sim/amr.py compiled-step cache), or pass changing data as "
            "traced arguments.",
        ),
        Rule(
            "JX008",
            "manual section timing outside the obs layer",
            "time.perf_counter() section timing outside cup3d_tpu/obs/ "
            "builds a private, invisible telemetry channel: the wall it "
            "measures never reaches the metrics registry, the step trace, "
            "or the flight recorder, and the window repeats every JX006 "
            "sync-honesty hazard from scratch.  Use obs spans "
            "(obs.trace.SpanTimer / the driver profiler) or obs metrics; "
            "the annotated exceptions are the stream data-plane's "
            "stall/read splits, which ARE the registry's data source.",
        ),
        Rule(
            "JX009",
            "swallowed exception (drop without counter or re-raise)",
            "An `except: pass`/`continue` (or a log-and-drop handler) "
            "erases the only evidence of a failure: the round-10 "
            "resilience work found background checkpoint-write errors "
            "that vanished this way until the run ended with silent data "
            "loss.  A handler must re-raise, return a sentinel the "
            "caller checks, record the error into state, or at minimum "
            "bump an obs-registry counter so the drop is observable; "
            "deliberate capability probes are annotated inline.  The "
            "resilience/ subsystem (whose whole job is containing "
            "failures it has already counted) is exempt by path.",
        ),
        Rule(
            "JX010",
            "per-step host<->device staging of obstacle state",
            "np.asarray/jnp.asarray on a loop-carried obstacle/driver "
            "attribute (self.X / ob.X / s.X) inside a step-loop function "
            "re-stages the same mirror across the host boundary every "
            "step: construction-time constants (bForcedInSimFrame, "
            "bBlockRotation) and per-step scalars (lambda = DLM/dt) each "
            "cost a host->device upload per step, and np.asarray on a "
            "device-resident mirror blocks for the round trip.  BENCH_r05 "
            "measured the residue at ~28-43 ms/step on the fish configs.  "
            "Cache static mirrors identity-keyed on the obstacle "
            "(models/base.forced_mask_dev), derive per-step values on "
            "device from already-uploaded scalars "
            "(sim/data.lambda_device), or carry the state device-resident "
            "across steps (sim/megaloop.py).",
        ),
        Rule(
            "JX005",
            "float64 dtype literal in device code",
            "A bare float64 dtype in device code either doubles bandwidth "
            "and VMEM pressure on TPU or silently promotes downstream "
            "arithmetic.  Device-side dtypes must come from the config "
            "(sim.dtype); float64 is reserved for host-side mirrors and "
            "accumulations.",
        ),
        Rule(
            "JX011",
            "bf16 reduction without an explicit f32 accumulator",
            "jnp.sum/dot/vdot (or lax.dot) over bfloat16 operands without "
            "an explicit dtype=/preferred_element_type= accumulator "
            "reduces in storage precision on some backends: at 128^3 a "
            "bf16-accumulated dot product of the Krylov residual loses "
            "~8 of the ~11 significand bits the stopping test needs, so "
            "the solver reports convergence it does not have.  The round-"
            "12 mixed-precision policy (ops/precision.py) stores Krylov "
            "vectors in bf16 but ACCUMULATES in f32 everywhere — any "
            "reduction touching a bf16-cast value must name its f32 "
            "accumulator explicitly.",
        ),
        Rule(
            "JX013",
            "per-lane Python loop over the fleet scenario axis",
            "A Python loop that walks the lane/scenario axis AND "
            "dispatches device work per iteration inside cup3d_tpu/"
            "fleet/ undoes the entire fleet amortization: B lanes "
            "exist to be advanced by ONE vmapped dispatch "
            "(fleet/batch.py), so a per-lane device loop pays the "
            "~0.03 s/step host overhead B times over — exactly the "
            "floor BENCH_r04/r05 measured and the fleet was built to "
            "amortize.  The batch axis must stay vectorized (vmap / "
            "lane-masked selects); host-only Python loops over lanes "
            "are fine in assembly and fan-out code because they touch "
            "no device value.",
        ),
        Rule(
            "JX014",
            "wall-clock subtraction used as a duration",
            "Subtracting two time.time() (or datetime.now()) reads "
            "measures the WALL clock, which NTP slews and steps: a "
            "duration computed this way can come out negative, jump by "
            "whole seconds, and silently corrupts latency histograms "
            "and SLO burn rates (the round-16 job observatory gates on "
            "p99 completion latency, so a stepped clock is a paged "
            "on-call).  Durations must come from the monotonic clock — "
            "obs.trace.now() (perf_counter on the trace epoch) at "
            "lifecycle seams, or the obs span/metric primitives.  "
            "time.time() stays legitimate for TIMESTAMPS (history "
            "store rows, postmortem wall_time, /health time): the rule "
            "fires only on wall-clock SUBTRACTION.",
        ),
        Rule(
            "JX015",
            "per-tick host reassembly of full-batch arrays in fleet/",
            "A K-boundary fast-path function (tick/reseed/dispatch) in "
            "cup3d_tpu/fleet/ that restacks the whole lane axis — "
            "jnp.stack/np.stack/concatenate or the assembly helpers "
            "stack_carries/stack_gaits — turns an O(1)-lane reseed "
            "into O(B) host work plus a full-batch device upload at "
            "EVERY boundary, and the host-side rebuild breaks the "
            "round-14 bitwise-untouched guarantee for the other B-1 "
            "lanes (fresh ndarray round-trips are not bitwise-stable "
            "across pytrees that were never touched).  The round-17 "
            "continuous-batching contract is that a reseed replaces "
            "ONE lane through the jitted `.at[lane].set` upload path "
            "(fleet/batch.py reseed_lane_carry/reseed_lane_gaits, one "
            "compiled specialization for all lane indices).  Batch "
            "CONSTRUCTION (assemble/FleetBatch.__init__) stacks "
            "legitimately — the rule keys on per-tick function names.",
        ),
        Rule(
            "JX016",
            "full-array materialization in a sharded step path",
            "jax.device_get()/np.asarray()/np.array() — or a single-"
            "argument jax.device_put() — on a device value inside a "
            "step/advance/dispatch/megaloop function in cup3d_tpu/"
            "{sim,fleet,parallel}/ gathers the FULL array to one host "
            "or one device.  Under the round-18 2-D (lanes, x) mesh "
            "those arrays are shard-resident: the gather serializes "
            "every shard through a single host link (the exact "
            "scale-out ceiling the mesh removes), doubles peak memory "
            "on the target, and on multi-host topologies is an error.  "
            "Keep fields sharded: slice shard-locally under shard_map "
            "(lax.dynamic_slice + axis_index), move data with an "
            "explicit NamedSharding device_put(x, sharding), and stage "
            "host reads through the designed sync points "
            "(analysis/runtime.sanctioned_transfer).",
        ),
        Rule(
            "JX012",
            "direct jax.profiler use outside the obs layer",
            "jax.profiler.start_trace/stop_trace/TraceAnnotation called "
            "outside cup3d_tpu/obs/ opens a second, uncoordinated "
            "profiling channel: the profiler session is process-global, "
            "so an ad-hoc capture colliding with an obs window aborts "
            "one of them; ad-hoc annotations bypass the sink's cached "
            "class and fast no-op path; and the resulting trace never "
            "reaches the device-time attribution parser or the merged "
            "host+device timeline.  Use obs profile windows "
            "(obs.profile.CONTROLLER / CaptureController.capture()) and "
            "obs spans under CUP3D_TRACE_XLA=1 instead.",
        ),
        Rule(
            "JX018",
            "raw collective call site outside cup3d_tpu/parallel/",
            "jax.lax.ppermute/psum/pmax/all_gather/all_to_all/... called "
            "directly outside cup3d_tpu/parallel/ scatters the SPMD "
            "communication surface across the tree: the IR audit "
            "(analysis/ir.py JP002) and the pod bring-up work need ONE "
            "seam where axis names, permutation structure, and mesh "
            "shape assumptions live.  Collectives go through the "
            "parallel/ layer (parallel/ring.py ring_shift/pad_slab, "
            "parallel/collectives.py all_gather_tiled/pmax_axis) so a "
            "mesh-axis rename or a topology change edits one module "
            "instead of every call site — the exact MPI-communicator "
            "discipline the reference C++ enforces by construction.",
        ),
        Rule(
            "JX019",
            "direct AOT compile / jit-warmup call site outside the "
            "executable-store seam",
            "A chained `fn.lower(...).compile()` or an immediately-"
            "invoked `jit(f)(...)` warmup compiles an XLA executable "
            "that the persistent store (cup3d_tpu/aot/store.py) never "
            "sees: the result is paid again on every process start — "
            "the exact cold-start tax round 21 eliminates — and the "
            "compile evades the aot.* hit/miss/compile-seconds "
            "telemetry.  Compile-producing call sites go through the "
            "store seam (aot.store_backed / StoreBackedExecutable."
            "warm/ensure_compiled) so previously-seen signatures "
            "deserialize instead of recompiling.  cup3d_tpu/aot/ IS "
            "the seam and obs/costs.py harvests cost analytics from "
            "an already-compiled object — both are path-exempt.",
        ),
        Rule(
            "JX020",
            "raw clock read inside cup3d_tpu/ outside obs/trace.py",
            "time.monotonic()/time.time()/time.perf_counter() (and the "
            "*_ns variants) called anywhere but obs/trace.py splits the "
            "package across clock domains: the round-22 latency "
            "provenance decomposes a job's end-to-end time into "
            "exclusive phases that sum back exactly, and that partition "
            "invariant only holds because every lifecycle timestamp — "
            "fleet marks, compile-service spans, flight-recorder stamps "
            "— comes off the ONE monotonic clock behind "
            "obs.trace.now().  A stray time.monotonic() in a subsystem "
            "is a second epoch: its intervals cannot be subtracted "
            "against trace timestamps without silent skew.  Monotonic "
            "reads route through obs.trace.now(); wall-time stamps "
            "(log/postmortem metadata, never durations — JX014) route "
            "through obs.trace.wall().  obs/trace.py IS the clock seam "
            "and is path-exempt.",
        ),
        Rule(
            "JX021",
            "fleet job status mutated outside the journal-logging seam",
            "A direct `<job>.status = ...` assignment in cup3d_tpu/"
            "fleet/ outside the sanctioned seams (FleetBatch.__init__, "
            "retire, reseed_lane, cancel, _prepare, "
            "_install_replayed_job) is a lifecycle transition the "
            "round-23 write-ahead journal never records: the sanctioned "
            "seams journal their transitions (place/terminal records) "
            "or funnel into _job_terminal, so FleetServer.recover() can "
            "replay every accepted job after a crash — terminal jobs "
            "remembered, queued re-admitted, running resumed from their "
            "snapshots.  An unjournaled status flip breaks that "
            "zero-lost-jobs guarantee silently: the job vanishes (or "
            "doubles) only when a server actually dies.  Route "
            "transitions through the seams, or extend "
            "JX021_SANCTIONED_RE when adding a new seam that itself "
            "journals.",
        ),
        Rule(
            "JP001",
            "donated buffer not aliased in the compiled executable",
            "jit(donate_argnums=...) is a PROMISE, not a guarantee: when "
            "XLA cannot alias a donated input to an output (shape/dtype "
            "mismatch, layout change, or an output that is not a pure "
            "update) it silently copies and the donation evaporates — "
            "the steady-state megaloop then carries 2x the field working "
            "set in HBM, exactly what donation exists to prevent (JX002 "
            "rationale, ~400 MB of vel+p at 256^3).  The audit traces "
            "the canonical executables and requires every donated leaf "
            "to appear in the compiled input_output_aliases (or the "
            "lowered tf.aliasing_output marks); an entry that documents "
            "a no-donation contract (fleet advance: rollback needs the "
            "pre-dispatch buffers) declares it and is checked for the "
            "ABSENCE of donation instead.",
        ),
        Rule(
            "JP002",
            "unsafe collective in a shard_map body",
            "A ppermute whose (src, dst) pairs are not a permutation "
            "(duplicate sources, duplicate destinations, or ids outside "
            "the mesh axis) and any collective naming an axis that does "
            "not exist in the enclosing mesh are exactly the class of "
            "bug that deadlocks or corrupts a multi-host pod at runtime "
            "— jax does NOT validate either at trace time.  The "
            "reference C++ relies on MPI runtime assertions here; the "
            "audit walks every shard_map body in the canonical jaxprs "
            "and proves the permutation/axis invariants before any "
            "jax.distributed run is real.",
        ),
        Rule(
            "JP003",
            "cross-shard materialization in a sharded step jaxpr",
            "An all_gather inside a mesh-sharded steady-state step "
            "reassembles a full axis on every shard, every step — the "
            "compiler-truth complement of AST rule JX016 (which can "
            "only see host-side gathers in source text).  A gather that "
            "is part of the design (the sharded megaloop's replicated "
            "coarse solve) is annotated at the registry entry with a "
            "reason; anything else is a scale-out ceiling hiding in "
            "the IR.",
        ),
        Rule(
            "JP004",
            "precision hazard visible in the jaxpr",
            "float64 avals or bf16-accumulated reductions (reduce_sum / "
            "dot_general producing bfloat16) in a hot jaxpr are the "
            "IR-grounded halves of JX005/JX011: dtype promotion "
            "introduced two helpers away from the call site is "
            "invisible to the AST linter but fully visible in the "
            "traced IR.  f64 doubles bandwidth and VMEM pressure on "
            "TPU; a bf16 accumulator loses ~8 of the ~11 significand "
            "bits the Krylov stopping test needs (the round-12 policy "
            "stores bf16 but accumulates f32 everywhere).",
        ),
        Rule(
            "JP005",
            "host callback op in a hot jaxpr",
            "pure_callback/io_callback/debug_callback inside a "
            "steady-state jaxpr inserts a host round trip into every "
            "step: the dispatch stream blocks on the Python interpreter "
            "(the JX001 hazard, but introduced at trace level where the "
            "AST linter cannot see it), and on a multi-host pod the "
            "callback runs per-process with unsynchronized side "
            "effects.  Debug prints and host-side physics must stay "
            "out of the megaloop; diagnostics ride the scan-stacked "
            "row outputs instead.",
        ),
        Rule(
            "JX017",
            "hand-typed hardware peak literal in a roofline/bench path",
            "A numeric constant >= 1e9 that is not an exact power of "
            "ten inside a bench*.py file or a roofline/peak-model "
            "function reads like a spec sheet (197e12 bf16 FLOP/s, "
            "819e9 HBM B/s) and hard-codes ONE device kind into math "
            "that runs on EVERY backend: the reported MFU and HBM "
            "fractions then silently lie on anything that is not that "
            "device — the round-19 bug class where bench.py divided by "
            "v5e ceilings regardless of hardware.  Hardware peaks live "
            "in the provenance-annotated device-kind table in "
            "obs/costs.py (the one path-exempt module, nominal-flagged "
            "CPU fallback included); consumers resolve the live "
            "backend with obs.costs.device_peaks().  Exact powers of "
            "ten (1e9, 1e12) are unit conversions and never fire.",
        ),
    )
}


@dataclass
class Violation:
    """One lint finding.  ``func`` is the enclosing function's qualname —
    the baseline matches on (rule, path, func) so entries survive line
    drift from unrelated edits."""

    rule: str
    path: str
    line: int
    col: int
    func: str
    message: str
    suppressed: bool = False
    suppression_reason: Optional[str] = None
    baselined: bool = False

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.func)

    def format(self) -> str:
        tag = ""
        if self.suppressed:
            tag = f"  [allowed: {self.suppression_reason or 'no reason'}]"
        elif self.baselined:
            tag = "  [baselined]"
        rule = RULES.get(self.rule)
        title = rule.title if rule else "unknown rule"
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"({title}) in `{self.func}`: {self.message}{tag}"
        )
