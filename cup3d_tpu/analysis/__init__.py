"""JAX-aware lint + runtime sanitizers (ISSUE 2).

Static: ``python -m cup3d_tpu.analysis [paths]`` walks the package AST
and flags JAX hazards (hidden host syncs, undonated step jits, traced
control flow, per-step uploads, float64 literals, unsynced timing) with
stable rule IDs — see ``analysis/rules.py`` for the catalog and
``analysis/lint.py`` for the heuristics and suppression machinery.

Runtime: ``analysis/runtime.py`` provides the recompile counter, the
transfer-guard context with its sanctioned-site allowlist, and scoped
NaN/tracer-leak debug modes.  VALIDATION.md ("Analysis subsystem:
sanitizer contract") specifies which loops must run clean and what the
budgets are; tests/test_analysis.py enforces it.
"""

from cup3d_tpu.analysis.rules import RULES, Rule, Violation  # noqa: F401
from cup3d_tpu.analysis.runtime import (  # noqa: F401
    RecompileCounter,
    debug_nans,
    device_scalar,
    no_implicit_transfers,
    sanctioned_transfer,
    tracer_leak_checks,
)
