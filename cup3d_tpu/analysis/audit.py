"""The IR audit driver: canonical entry points, traced and checked.

``analysis/ir.py`` knows how to walk a jaxpr and read alias maps; this
module knows WHAT to walk — the registry below builds every canonical
executable of the tree on tiny shapes (16^3 slabs, B=2 fleets, a
two-level 8^3-block forest) and runs the JP rules over each:

- ``uniform_tgv_megaloop`` / ``uniform_fish_megaloop`` — the solo
  K-step scan megaloops (sim/megaloop.py), carry donated.
- ``amr_tgv_megastep`` — the bucketed-AMR one_step under its own
  scan+jit with the carry donated (the fleet wraps the same body).
- ``fleet_advance`` / ``fleet_reseed_upload`` — the batched vmap
  advance and the one-lane reseed upload (fleet/batch.py); both
  DOCUMENT a no-donation contract (rollback/in-flight consumers need
  the old buffers), so JP001 checks the absence of aliasing.
- ``sharded_tgv_megaloop`` — the mesh-sharded megaloop on a (1, 4)
  (lanes, x) device mesh (parallel/topology.py), carry donated; its
  replicated coarse solve is an ANNOTATED JP003 gather.
- ``fused_bicgstab`` / ``fused_amr_bicgstab`` — the fused Krylov
  stages (ops/), jnp-twin form on CPU.

Contract mirror of the AST linter: stable IDs (JP001–JP005), an
EMPTY shipped baseline (``analysis/audit_baseline.json``),
``--write-baseline`` to start a burn-down, per-entry ``allow``
annotations with reasons (the IR analogue of inline suppression — IR
findings have no source line to annotate), ``--format json`` for CI.

Run it: ``python -m cup3d_tpu.analysis audit`` (tools/lint.sh stage).
Entries trace in-process; the CLI bootstraps JAX_PLATFORMS=cpu and an
8-device host platform BEFORE jax initializes, same as
tests/conftest.py.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from cup3d_tpu.analysis import ir as IR
from cup3d_tpu.analysis import lint as lint_mod
from cup3d_tpu.analysis.rules import Violation
from cup3d_tpu.obs import trace as OT

#: devices the sharded entry needs (a 1x4 (lanes, x) mesh)
MESH_DEVICES = 4


def bootstrap_platform() -> None:
    """Pin jax to CPU with >= MESH_DEVICES virtual devices.  Must run
    before the first jax device access; a jax that already initialized
    (pytest under conftest.py) keeps whatever it has — entries that
    need more devices than exist skip themselves."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


# -- built entries -----------------------------------------------------------


@dataclass
class Built:
    """One traced entry: the jitted callable, its example args, and the
    donation expectation the rules check against.  ``jaxpr`` overrides
    tracing (fixture tests audit hand-mutated jaxprs for the invariant
    classes jax refuses to trace); with ``fn=None`` the lowered/
    compiled donation checks are skipped."""

    fn: Any
    args: Tuple[Any, ...]
    donate_argnums: Tuple[int, ...] = ()
    jaxpr: Any = None


@dataclass
class EntryPoint:
    name: str
    build: Callable[[], Optional[Built]]   # None -> entry skips itself
    compile: bool = True     # cross-check the compiled HLO alias map
    expect_no_donation: bool = False
    #: rule id -> reason: the registry-level suppression (IR findings
    #: have no source line, so the annotation lives with the entry)
    allow: Dict[str, str] = field(default_factory=dict)


def _tmpdir() -> str:
    import tempfile

    d = os.path.join(tempfile.gettempdir(), "cup3d_audit")
    os.makedirs(d, exist_ok=True)
    return d


def _tgv_cfg(**kw):
    import numpy as np

    from cup3d_tpu.config import SimulationConfig

    base = dict(
        bpdx=1, bpdy=1, bpdz=1, block_size=16, levelMax=1, levelStart=0,
        extent=2 * np.pi, CFL=0.3, nu=0.02, nsteps=2, tend=0.0, rampup=0,
        initCond="taylorGreen", dtype="float32", pipelined=True,
        verbose=False, freqDiagnostics=0, path4serialization=_tmpdir(),
    )
    base.update(kw)
    return SimulationConfig(**base)


def _build_uniform_tgv() -> Built:
    import jax.numpy as jnp

    from cup3d_tpu.sim.megaloop import build_tgv_megaloop, init_tgv_carry
    from cup3d_tpu.sim.simulation import Simulation

    sim = Simulation(_tgv_cfg())
    sim.init()
    fn = build_tgv_megaloop(sim.sim)
    carry = init_tgv_carry(sim.sim)
    cfl = jnp.full((2,), 0.3, sim.sim.dtype)
    return Built(fn, (carry, cfl), donate_argnums=(0,))


def _build_uniform_fish() -> Built:
    import jax.numpy as jnp

    from cup3d_tpu.config import SimulationConfig
    from cup3d_tpu.sim.megaloop import build_fish_megaloop, init_fish_carry
    from cup3d_tpu.sim.simulation import Simulation

    cfg = SimulationConfig(
        bpdx=1, bpdy=1, bpdz=1, block_size=16, levelMax=1, levelStart=0,
        extent=1.0, CFL=0.3, nu=1e-4, nsteps=2, tend=0.0, rampup=0,
        factory_content="stefanfish L=0.3 T=1.0 xpos=0.5",
        dtype="float32", pipelined=True, verbose=False,
        freqDiagnostics=0, path4serialization=_tmpdir(),
    )
    sim = Simulation(cfg)
    sim.init()
    ob = sim.sim.obstacles[0]
    fn = build_fish_megaloop(sim.sim, ob)
    carry = init_fish_carry(sim.sim, ob)
    cfl = jnp.full((2,), 0.3, sim.sim.dtype)
    return Built(fn, (carry, cfl), donate_argnums=(0,))


def _build_amr_megastep() -> Built:
    import jax
    import jax.numpy as jnp

    from cup3d_tpu.fleet.batch import init_amr_carry
    from cup3d_tpu.sim.amr import AMRSimulation, make_amr_tgv_step

    cfg = _tgv_cfg(bpdx=2, bpdy=2, bpdz=2, block_size=8, levelMax=2,
                   levelStart=1, Rtol=1e9, Ctol=-1.0)
    sim = AMRSimulation(cfg)
    sim.init()
    sim.adapt_enabled = False          # frozen topology, one bucket
    step = make_amr_tgv_step(sim)

    def megaloop(carry, cfl_eff):
        return jax.lax.scan(step, carry, cfl_eff)

    fn = jax.jit(megaloop, donate_argnums=(0,))
    carry = init_amr_carry(sim)
    cfl = jnp.full((2,), 0.3, jnp.float32)
    return Built(fn, (carry, cfl), donate_argnums=(0,))


def _fleet_batch():
    import jax.numpy as jnp

    from cup3d_tpu.fleet.batch import stack_carries
    from cup3d_tpu.sim.megaloop import init_tgv_carry
    from cup3d_tpu.sim.simulation import Simulation

    sim = Simulation(_tgv_cfg())
    sim.init()
    solo = init_tgv_carry(sim.sim)
    batch = stack_carries([solo, solo], [8, 8])
    cfl = jnp.full((2, 2), 0.3, sim.sim.dtype)
    return sim, solo, batch, cfl


def _build_fleet_advance() -> Built:
    from cup3d_tpu.fleet.batch import build_fleet_advance

    sim, _solo, batch, cfl = _fleet_batch()
    fn = build_fleet_advance(sim.sim)
    return Built(fn, (batch, cfl, None))


def _build_fleet_reseed() -> Built:
    import jax.numpy as jnp

    from cup3d_tpu.fleet import batch as FB

    _sim, solo, batch, _cfl = _fleet_batch()
    solo = dict(solo)
    return Built(FB._upload_lane_carry,
                 (batch, jnp.asarray(0, jnp.int32), solo,
                  jnp.asarray(8, jnp.int32)))


def _build_sharded_tgv() -> Optional[Built]:
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < MESH_DEVICES:
        return None
    from cup3d_tpu.parallel.topology import make_mesh2d, shard_carry
    from cup3d_tpu.sim.megaloop import (
        build_tgv_megaloop_sharded,
        init_tgv_carry,
    )
    from cup3d_tpu.sim.simulation import Simulation

    mesh = make_mesh2d(lanes=1, x=MESH_DEVICES,
                       devices=jax.devices()[:MESH_DEVICES])
    sim = Simulation(_tgv_cfg())
    sim.init()
    fn = build_tgv_megaloop_sharded(sim.sim, mesh)
    if fn is None:
        return None
    carry = shard_carry(init_tgv_carry(sim.sim), mesh)
    cfl = jnp.full((2,), 0.3, sim.sim.dtype)
    return Built(fn, (carry, cfl), donate_argnums=(0,))


def _build_fused_bicgstab() -> Built:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cup3d_tpu.grid.uniform import BC, UniformGrid
    from cup3d_tpu.ops import krylov
    from cup3d_tpu.ops.fused_bicgstab import fused_bicgstab

    n = 16
    g = UniformGrid((n, n, n), (1.0,) * 3, (BC.periodic,) * 3)
    rng = np.random.default_rng(0)
    rhs = jnp.asarray(rng.standard_normal((n, n, n)), jnp.float32)
    bt = krylov.to_lanes(rhs - jnp.mean(rhs))

    def solve(b):
        return fused_bicgstab(g, b, tol_abs=1e-6, tol_rel=1e-5,
                              maxiter=8, two_level=True,
                              store_dtype=jnp.float32, kernels=False)

    return Built(jax.jit(solve), (bt,))


def _build_fused_amr_bicgstab() -> Built:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cup3d_tpu.grid import bucket as bk
    from cup3d_tpu.grid.blocks import BlockGrid
    from cup3d_tpu.grid.faces import pad_face_tables
    from cup3d_tpu.grid.flux import build_flux_tables, pad_flux_tables
    from cup3d_tpu.grid.octree import Octree, TreeConfig
    from cup3d_tpu.grid.uniform import BC
    from cup3d_tpu.ops import krylov
    from cup3d_tpu.ops.fused_amr_bicgstab import fused_amr_bicgstab

    tree = Octree(TreeConfig((2, 2, 2), 2, (True,) * 3), 0)
    tree.refine(sorted(tree.leaves)[0])
    g = BlockGrid(tree, (1.0,) * 3, (BC.periodic,) * 3, 8)
    cap = bk.capacity(g.nb)
    tab = pad_face_tables(g.face_tables(1), g, cap)
    ftab = pad_flux_tables(build_flux_tables(g), g.bs, cap)
    graph = krylov.block_graph_tables(g, cap=cap)
    h = np.ones(cap)
    h[: g.nb] = g.h
    vol = np.zeros((cap, 1, 1, 1), np.float32)
    vol[: g.nb, 0, 0, 0] = g.h ** 3

    class _Geom:
        pass

    geom = _Geom()
    geom.bs, geom.nb, geom.extent = g.bs, cap, g.extent
    geom.h = jnp.asarray(h, jnp.float32)
    jvol = jnp.asarray(vol)

    rng = np.random.default_rng(0)
    rhs = np.zeros((cap, 8, 8, 8), np.float32)
    rhs[: g.nb] = rng.standard_normal((g.nb, 8, 8, 8))
    b = jnp.asarray(rhs)
    mask = jnp.asarray((vol > 0).astype(np.float32))

    def solve(bb):
        bb = (bb - jnp.sum(bb * jvol) / (jnp.sum(jvol) * g.bs ** 3))
        bb = bb * mask
        return fused_amr_bicgstab(
            geom, bb, tab=tab, ftab=ftab, vol=jvol, graph=graph,
            tol_abs=1e-8, tol_rel=1e-5, maxiter=8,
            store_dtype=jnp.float32,
            rnorm_ref=jnp.sqrt(jnp.sum(bb * bb)), kernels=False)

    return Built(jax.jit(solve), (b,))


#: documented no-donation contract on the fleet paths (fleet/batch.py
#: docstrings): advance keeps the pre-dispatch buffers alive for the
#: isolate.py rollback, the reseed upload for in-flight consumers
_FLEET_CONTRACT = (
    "fleet/batch.py documents the no-donation contract: the rollback/"
    "in-flight-consumer paths need the pre-dispatch buffers"
)

REGISTRY: Tuple[EntryPoint, ...] = (
    EntryPoint("uniform_tgv_megaloop", _build_uniform_tgv),
    EntryPoint("uniform_fish_megaloop", _build_uniform_fish,
               # the fish step compiles ~17 s on the CPU container —
               # JP001 reads the lowered tf.aliasing_output marks
               # instead (where jax records the donation decision)
               compile=False),
    EntryPoint("amr_tgv_megastep", _build_amr_megastep),
    EntryPoint("fleet_advance", _build_fleet_advance,
               expect_no_donation=True),
    EntryPoint("fleet_reseed_upload", _build_fleet_reseed,
               expect_no_donation=True),
    EntryPoint("sharded_tgv_megaloop", _build_sharded_tgv,
               allow={
                   "JP003": (
                       "designed replicated stage: the slab megaloop "
                       "gathers rhs/p for the replicated coarse "
                       "Poisson solve so every shard runs the bitwise-"
                       "identical solver (sim/megaloop.py 'replicated "
                       "global solve'); the distributed-solver rung "
                       "(ROADMAP item 2) retires it"
                   ),
               }),
    EntryPoint("fused_bicgstab", _build_fused_bicgstab),
    EntryPoint("fused_amr_bicgstab", _build_fused_amr_bicgstab),
)


# -- driver ------------------------------------------------------------------


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "audit_baseline.json")


def audit_entry(ep: EntryPoint) -> Tuple[List[Violation], Dict[str, Any]]:
    """Trace (and optionally compile) one entry and run every JP rule.
    Returns (violations, meta); a builder returning None skips the
    entry (meta notes why)."""
    import jax

    # jax-lint: allow(JX008, audit wall budget, not a perf measurement:
    # the 60 s lint.sh stage budget is enforced on trace+lower time)
    t0 = OT.now()
    built = ep.build()
    if built is None:
        return [], {"entry": ep.name, "skipped": True,
                    # jax-lint: allow(JX006, times host-side trace and
                    # lower work only; the audit dispatches no device
                    # execution by design)
                    "wall_s": round(OT.now() - t0, 3)}

    if built.jaxpr is not None:
        closed = built.jaxpr
    else:
        closed = jax.make_jaxpr(built.fn)(*built.args)
    violations = IR.audit_jaxpr(closed, ep.name)

    lowered_text = None
    compiled_text = None
    lower = getattr(built.fn, "lower", None) if built.fn is not None else None
    if lower is not None:
        lowered = lower(*built.args)
        lowered_text = lowered.as_text()
        if ep.compile:
            compiled_text = lowered.compile().as_text()
    donated = IR.donated_leaf_indices(built.args, built.donate_argnums)
    violations += IR.audit_donation(
        ep.name, donated, lowered_text, compiled_text,
        expect_no_donation=ep.expect_no_donation)

    for v in violations:
        reason = ep.allow.get(v.rule)
        if reason is not None:
            v.suppressed = True
            v.suppression_reason = reason
    meta = {
        "entry": ep.name, "skipped": False,
        "compiled": bool(compiled_text is not None),
        "donated_params": donated,
        "wall_s": round(OT.now() - t0, 3),
    }
    return violations, meta


def run_audit(
    entries: Optional[Sequence[EntryPoint]] = None,
    baseline_path: Optional[str] = None,
    rules: Optional[set] = None,
) -> Tuple[List[Violation], List[Dict[str, Any]]]:
    """Audit every registry entry; apply the baseline; return all
    violations (suppressed/baselined flags set) plus per-entry meta."""
    violations: List[Violation] = []
    metas: List[Dict[str, Any]] = []
    for ep in (REGISTRY if entries is None else entries):
        vs, meta = audit_entry(ep)
        violations.extend(vs)
        metas.append(meta)
    if rules:
        violations = [v for v in violations if v.rule in rules]
    baseline = lint_mod.load_baseline(baseline_path)
    lint_mod.apply_baseline(violations, baseline)
    return violations, metas


def summary_line(violations: List[Violation],
                 metas: List[Dict[str, Any]],
                 baseline_path: Optional[str]) -> str:
    """The one-line JSON the CI driver tail greps."""
    failing = lint_mod.failing(violations)
    baseline = lint_mod.load_baseline(baseline_path)
    rules = sorted({v.rule for v in violations})
    return json.dumps({
        "audit": "ir",
        "entries": len(metas),
        "skipped": sum(1 for m in metas if m.get("skipped")),
        "rules_fired": rules,
        "findings": len(violations),
        "failing": len(failing),
        "annotated": sum(1 for v in violations if v.suppressed),
        "baseline_size": len(baseline),
        "wall_s": round(sum(m.get("wall_s", 0.0) for m in metas), 3),
    }, sort_keys=True)
