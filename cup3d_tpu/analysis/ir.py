"""IR-level static analysis: jaxpr and lowered/compiled-HLO walkers.

The AST linter (``analysis/lint.py``, JX001–JX018) reads source text and
therefore cannot see anything that only exists after tracing: a
donation XLA silently turned into a copy, a ``ppermute`` whose pair
list is not a permutation, or a bf16 accumulator introduced by dtype
promotion two helper calls away.  This module is the second tier — it
walks the *traced* artifacts of the canonical entry points
(``analysis/audit.py`` owns the registry and the CLI) with the same
contract as the linter: stable rule IDs, ``Violation`` records,
baselines, suppression with reasons.

Rules (catalog text in ``analysis/rules.py``):

- JP001  donated buffer not aliased in the compiled executable.
         Ground truth is read twice: the lowered StableHLO marks each
         aliased ``@main`` argument with ``tf.aliasing_output`` (where
         jax records the donation decision), and — when the entry is
         compiled — the scheduled HLO header's ``input_output_alias``
         map (what XLA actually does).  Entries that DOCUMENT a
         no-donation contract are checked for the absence of aliasing
         instead (``expect_no_donation``).
- JP002  unsafe collective in a shard_map body: a ppermute whose
         (src, dst) pairs have duplicate sources, duplicate
         destinations, or ids outside the mesh axis; any collective
         naming an axis absent from the enclosing mesh.  jax validates
         NEITHER at trace time — both deadlock or corrupt at pod
         scale.
- JP003  cross-shard materialization: ``all_gather`` inside a
         shard_map body of a steady-state jaxpr (the compiler-truth
         complement of JX016).  Designed gathers (the sharded
         megaloop's replicated coarse solve) are annotated at the
         registry entry.
- JP004  precision hazards: float64 avals anywhere, and reductions
         (reduce_sum / cumsum / dot_general / reduce_window_sum)
         whose OUTPUT dtype is bfloat16 — i.e. a storage-precision
         accumulator (IR-grounded JX005/JX011).
- JP005  host callbacks (pure_callback / io_callback /
         debug_callback) in a hot jaxpr.

Everything here is pure inspection — no tracing, no compilation; the
caller (audit.py) brings the jaxpr / Lowered / Compiled objects.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from cup3d_tpu.analysis.rules import Violation

# -- primitive sets ----------------------------------------------------------

#: communicating collectives whose params name mesh axes (JP002 axis
#: check).  ``psum`` lowers to ``psum2`` inside shard_map bodies on the
#: jax in this tree; both spellings are kept so the walker survives
#: version drift.
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "pshuffle", "psum", "psum2", "pmax", "pmin", "pmean",
    "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "pbroadcast", "axis_index",
})

#: host-callback primitives (JP005)
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "host_callback",
    "outside_call",
})

#: reduction-position primitives whose output dtype names the
#: accumulator (JP004 bf16 check).  Elementwise bf16 ops are storage
#: traffic, not accumulation, and never fire.
REDUCTION_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "dot_general",
    "reduce_window_sum",
})


def _entry_path(entry: str) -> str:
    """Baseline-stable pseudo-path for one registry entry.  The lint
    baseline keys on (rule, path, func); IR findings have no source
    file, so the entry name doubles as both."""
    return f"ir://{entry}"


def _emit(out: List[Violation], rule: str, entry: str, msg: str) -> None:
    out.append(Violation(
        rule=rule, path=_entry_path(entry), line=0, col=0, func=entry,
        message=msg,
    ))


# -- jaxpr walking -----------------------------------------------------------


def _sub_jaxprs(params: Dict[str, Any]) -> Iterable[Any]:
    """Every jaxpr-valued entry of an eqn's params: ``jaxpr`` /
    ``call_jaxpr`` / ``cond_jaxpr`` / ``body_jaxpr`` / ``branches`` /
    ... — discovered structurally (isinstance on Jaxpr/ClosedJaxpr)
    so new higher-order primitives keep walking without a catalog."""
    import jax.core as jcore

    kinds = (jcore.Jaxpr, jcore.ClosedJaxpr)
    for v in params.values():
        if isinstance(v, kinds):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if isinstance(item, kinds):
                    yield item


def _as_jaxpr(j: Any):
    """Unwrap ClosedJaxpr -> Jaxpr (eqns live on the inner object)."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _mesh_axes(mesh: Any) -> Dict[str, int]:
    """axis name -> size for a (concrete or abstract) Mesh."""
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:
        return {}


def iter_eqns(jaxpr: Any, axis_env: Optional[Dict[str, int]] = None,
              in_shard_map: bool = False):
    """Yield ``(eqn, axis_env, in_shard_map)`` for every eqn reachable
    from ``jaxpr``, descending into all sub-jaxprs.  ``axis_env`` maps
    live mesh axis names to sizes; entering a ``shard_map`` eqn swaps
    in that mesh's axes and flips ``in_shard_map`` for its body."""
    axis_env = axis_env or {}
    for eqn in _as_jaxpr(jaxpr).eqns:
        yield eqn, axis_env, in_shard_map
        prim = eqn.primitive.name
        if prim == "shard_map":
            sub_env = _mesh_axes(eqn.params.get("mesh"))
            for sub in _sub_jaxprs(eqn.params):
                yield from iter_eqns(sub, sub_env, True)
        else:
            for sub in _sub_jaxprs(eqn.params):
                yield from iter_eqns(sub, axis_env, in_shard_map)


def _axis_names(params: Dict[str, Any]) -> List[str]:
    """The mesh-axis names a collective eqn binds: ``axis_name`` (str
    or tuple) plus any string entries of ``axes`` (psum2-style; the
    integer entries there are positional array axes, not mesh axes)."""
    names: List[str] = []
    an = params.get("axis_name")
    if isinstance(an, str):
        names.append(an)
    elif isinstance(an, (tuple, list)):
        names.extend(a for a in an if isinstance(a, str))
    axes = params.get("axes")
    if isinstance(axes, (tuple, list, frozenset, set)):
        names.extend(a for a in axes if isinstance(a, str))
    return names


def _check_ppermute(out: List[Violation], entry: str, params: Dict[str, Any],
                    axis_env: Dict[str, int], names: List[str]) -> None:
    """JP002 permutation invariants for one ppermute eqn: unique
    sources, unique destinations, every id inside the axis extent."""
    perm = [(int(a), int(b)) for a, b in params.get("perm", ())]
    size = 1
    for n in names:
        size *= axis_env.get(n, 1)
    srcs = [a for a, _ in perm]
    dsts = [b for _, b in perm]
    if len(set(srcs)) != len(srcs):
        dup = sorted({s for s in srcs if srcs.count(s) > 1})
        _emit(out, "JP002", entry,
              f"ppermute perm has duplicate source id(s) {dup} — two "
              f"pairs send from the same shard (perm={perm})")
    if len(set(dsts)) != len(dsts):
        dup = sorted({d for d in dsts if dsts.count(d) > 1})
        _emit(out, "JP002", entry,
              f"ppermute perm has duplicate destination id(s) {dup} — "
              f"two pairs write the same shard (perm={perm})")
    if all(n in axis_env for n in names) and names:
        bad = sorted({i for i in srcs + dsts if not 0 <= i < size})
        if bad:
            _emit(out, "JP002", entry,
                  f"ppermute perm id(s) {bad} outside axis "
                  f"{'x'.join(names)} of size {size} (perm={perm})")


def audit_jaxpr(closed_jaxpr: Any, entry: str) -> List[Violation]:
    """Walk one entry's jaxpr and emit JP002–JP005 violations.  f64 and
    callback findings are deduplicated per (primitive, dtype) so a
    promoted dtype flowing through a 400-eqn scan body reads as one
    finding, not 400."""
    out: List[Violation] = []
    seen_f64: set = set()
    seen_cb: set = set()
    for eqn, axis_env, in_sm in iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        params = eqn.params

        if prim in COLLECTIVE_PRIMS:
            names = _axis_names(params)
            missing = [n for n in names if n not in axis_env]
            if missing:
                have = sorted(axis_env) or ["<none>"]
                _emit(out, "JP002", entry,
                      f"collective `{prim}` names axis "
                      f"{'/'.join(missing)} but the enclosing mesh "
                      f"declares {have} — a trace-time typo that "
                      "deadlocks a pod at runtime")
            if prim == "ppermute":
                _check_ppermute(out, entry, params, axis_env, names)
            if prim == "all_gather" and in_sm:
                shp = "x".join(str(d) for d in eqn.outvars[0].aval.shape)
                _emit(out, "JP003", entry,
                      f"all_gather over axis "
                      f"{'/'.join(names) or '?'} materializes a full "
                      f"({shp}) array on every shard, every step — a "
                      "scale-out ceiling unless it is a designed "
                      "replicated stage (annotate the registry entry "
                      "with the reason)")

        if prim in CALLBACK_PRIMS and prim not in seen_cb:
            seen_cb.add(prim)
            _emit(out, "JP005", entry,
                  f"host callback `{prim}` inside the hot jaxpr — "
                  "every step blocks the dispatch stream on the Python "
                  "interpreter; route diagnostics through the "
                  "scan-stacked row outputs instead")

        for var in tuple(eqn.invars) + tuple(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and str(dt) == "float64":
                key = (prim, "f64")
                if key not in seen_f64:
                    seen_f64.add(key)
                    _emit(out, "JP004", entry,
                          f"float64 aval on `{prim}` — doubles "
                          "bandwidth/VMEM on TPU; device dtypes come "
                          "from the config (sim.dtype), f64 stays "
                          "host-side (JX005, proven at IR level)")

        if prim in REDUCTION_PRIMS:
            for var in eqn.outvars:
                dt = getattr(getattr(var, "aval", None), "dtype", None)
                if dt is not None and str(dt) == "bfloat16":
                    _emit(out, "JP004", entry,
                          f"`{prim}` accumulates in bfloat16 — the "
                          "round-12 policy stores bf16 but ACCUMULATES "
                          "in f32 (name the accumulator: dtype=/"
                          "preferred_element_type=); a bf16 Krylov "
                          "dot loses ~8 of the ~11 significand bits "
                          "the stopping test needs (JX011, proven at "
                          "IR level)")
                    break
    return out


# -- donation (JP001) --------------------------------------------------------


def donated_leaf_indices(args: Sequence[Any],
                         donate_argnums: Sequence[int]) -> List[int]:
    """Flat ``@main`` parameter indices of every leaf of every donated
    argument, under jit's left-to-right flattening of the positional
    args.  This is the audit's own offset bookkeeping — it must match
    how jax flattens, which tests pin with a known executable."""
    import jax

    donate = set(int(d) for d in donate_argnums)
    flat: List[int] = []
    offset = 0
    for i, a in enumerate(args):
        leaves = jax.tree_util.tree_leaves(a)
        if i in donate:
            flat.extend(range(offset, offset + len(leaves)))
        offset += len(leaves)
    return flat


def aliased_params_from_lowered(mlir_text: str) -> List[int]:
    """``@main`` argument indices whose donation survived lowering:
    ``tf.aliasing_output`` when jax resolved the alias itself, or
    ``jax.buffer_donor`` when the module carries shardings and the
    aliasing decision is deferred to the XLA SPMD partitioner (the
    compiled header is then the ground truth — sharded entries keep
    ``compile=True``).  An unaliasable donated arg gets NEITHER mark,
    plus a UserWarning at lowering time."""
    start = mlir_text.find("@main(")
    if start < 0:
        return []
    i = start + len("@main(")
    depth = 1
    j = i
    while j < len(mlir_text) and depth:
        c = mlir_text[j]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        j += 1
    arglist = mlir_text[i:j - 1]
    out: List[int] = []
    # each chunk starts "%argN: tensor<...> {attrs...}"
    for chunk in arglist.split("%arg")[1:]:
        head = chunk.split(":", 1)[0].strip()
        try:
            idx = int(head)
        # jax-lint: allow(JX009, non-arg %arg-prefixed token in an MLIR
        # attr string is expected; a real parse failure surfaces as a
        # JP001 missing-alias finding, never silently)
        except ValueError:
            continue
        if "tf.aliasing_output" in chunk or "jax.buffer_donor" in chunk:
            out.append(idx)
    return sorted(out)


def aliased_params_from_compiled(hlo_text: str) -> List[int]:
    """Input parameter numbers in the scheduled HLO header's
    ``input_output_alias={ {out}: (param, {}, may-alias), ... }`` map —
    what the compiled executable actually aliases."""
    import re

    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return []
    i = start + len("input_output_alias={")
    depth = 1
    j = i
    while j < len(hlo_text) and depth:
        c = hlo_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        j += 1
    blob = hlo_text[i:j - 1]
    return sorted(int(m) for m in re.findall(r"\(\s*(\d+)\s*,", blob))


def audit_donation(entry: str, donated: Sequence[int],
                   lowered_text: Optional[str],
                   compiled_text: Optional[str],
                   expect_no_donation: bool = False) -> List[Violation]:
    """JP001 for one entry.  ``donated`` are the flat parameter indices
    that SHOULD alias (from :func:`donated_leaf_indices`); the lowered
    marks are always checked when available, the compiled header only
    when the entry was compiled (the expensive cross-check is
    per-entry opt-in, audit.py's ``compile=`` flag)."""
    out: List[Violation] = []
    donated = sorted(int(d) for d in donated)

    if expect_no_donation:
        for src_name, text, parse in (
            ("lowered", lowered_text, aliased_params_from_lowered),
            ("compiled", compiled_text, aliased_params_from_compiled),
        ):
            if text is None:
                continue
            aliased = parse(text)
            if aliased:
                _emit(out, "JP001", entry,
                      f"entry documents a no-donation contract (the "
                      "rollback/reseed path needs the pre-dispatch "
                      f"buffers) but the {src_name} executable aliases "
                      f"parameter(s) {aliased} — the contract and the "
                      "IR disagree")
        return out

    if not donated:
        return out

    if lowered_text is not None:
        aliased = set(aliased_params_from_lowered(lowered_text))
        missing = [d for d in donated if d not in aliased]
        if missing:
            _emit(out, "JP001", entry,
                  f"donated parameter(s) {missing} carry no "
                  "tf.aliasing_output mark in the lowered module — jax "
                  "could not alias them (shape/dtype/layout mismatch "
                  "against every output) and the donation is a silent "
                  "copy")
    if compiled_text is not None:
        aliased = set(aliased_params_from_compiled(compiled_text))
        missing = [d for d in donated if d not in aliased]
        if missing:
            _emit(out, "JP001", entry,
                  f"donated parameter(s) {missing} absent from the "
                  "compiled input_output_alias map — XLA copies "
                  "instead of aliasing; the steady-state carry pays "
                  "2x its working set")
    return out
