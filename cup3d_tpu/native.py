"""ctypes bridge to the native table builder (native/tables.cpp).

The reference's synchronizer setup is C++ (SynchronizerMPI_AMR::_Setup,
main.cpp:1979-2322); ours is too: the per-adaptation gather-table build
runs in native/libcup3d_tables.so when available (built lazily with the
in-tree Makefile on first use), with the vectorized numpy implementation
in grid/blocks.py as the always-available reference — the same
optimized-kernel-vs-reference-kernel pattern the upstream uses for its
SIMD hot loops (main.cpp:9186-9190).

Disable with CUP3D_NO_NATIVE=1.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcup3d_tables.so")

_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("CUP3D_NO_NATIVE"):
        return None
    # always invoke make: its mtime check is a ~ms no-op when the .so is
    # current, and rebuilds when tables.cpp changed (a stale gitignored
    # .so would otherwise be loaded silently)
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception:
        if not os.path.exists(_LIB_PATH):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.cup3d_build_lab_tables.restype = ctypes.c_int
    lib.cup3d_build_lab_tables.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # nb bs w lmax
        i64p, i32p, i32p, i64p,  # bpd bc levels ijk
        i32p, u8p, i64p,  # slot_flat int_flat lvl_off
        ctypes.c_int, i64p,  # ng gxyz
        i64p, f32p, f32p, u8p,  # g_idx g_w g_sign mask
        ctypes.c_int, i64p, f32p, f32p,  # cw s_idx s_w s_sign
        ctypes.POINTER(ctypes.c_int32),  # any_coarse
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def build_lab_tables(grid, w: int, gxyz: np.ndarray, cw: int):
    """Native lab-table build for BlockGrid ``grid`` at stencil width w.

    gxyz: (ng, 3) lab-coordinate ghost list (the same list the numpy
    builder enumerates).  Returns the table arrays or None if the native
    library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    nb, bs = grid.nb, grid.bs
    cfg = grid.tree.cfg
    ng = gxyz.shape[0]
    cbs = bs // 2
    S = cbs + 2 * cw
    ns = S**3

    # flatten the per-level dense maps
    lvl_off = np.zeros(cfg.level_max + 1, np.int64)
    for l in range(cfg.level_max):
        lvl_off[l + 1] = lvl_off[l] + grid._slot_maps[l].size
    slot_flat = np.concatenate(
        [np.ascontiguousarray(m.reshape(-1)) for m in grid._slot_maps]
    ).astype(np.int32)
    int_flat = np.concatenate(
        [np.ascontiguousarray(m.reshape(-1)) for m in grid._int_maps]
    ).astype(np.uint8)

    _bc_code = {"periodic": 0, "wall": 1, "freespace": 2}
    bc_codes = np.array([_bc_code[b.value] for b in grid.bc], np.int32)

    g_idx = np.empty((nb, ng, 8), np.int64)
    g_w = np.empty((nb, ng, 8), np.float32)
    g_sign = np.empty((nb, ng, 3), np.float32)
    mask = np.empty((nb, ng), np.uint8)
    s_idx = np.empty((nb, ns, 8), np.int64)
    s_w = np.empty((nb, ns, 8), np.float32)
    s_sign = np.empty((nb, ns, 3), np.float32)
    any_coarse = ctypes.c_int32(0)

    rc = lib.cup3d_build_lab_tables(
        nb, bs, w, cfg.level_max,
        np.ascontiguousarray(np.asarray(cfg.bpd, np.int64)),
        bc_codes,
        np.ascontiguousarray(grid.level.astype(np.int32)),
        np.ascontiguousarray(grid.ijk.astype(np.int64).reshape(-1)),
        slot_flat, int_flat, lvl_off,
        ng, np.ascontiguousarray(gxyz.astype(np.int64).reshape(-1)),
        g_idx.reshape(-1), g_w.reshape(-1), g_sign.reshape(-1),
        mask.reshape(-1),
        cw, s_idx.reshape(-1), s_w.reshape(-1), s_sign.reshape(-1),
        ctypes.byref(any_coarse),
    )
    if rc != 0:
        raise KeyError("unresolved owner: tree not 2:1 balanced?")
    return {
        "g_idx": g_idx, "g_w": g_w, "g_sign": g_sign,
        "mask_coarse": mask.astype(bool),
        "s_idx": s_idx, "s_w": s_w, "s_sign": s_sign,
        "any_coarse": bool(any_coarse.value),
    }
