"""Configuration: the reference's flag surface as a typed dataclass.

Mirrors the ~45 flags parsed by the reference's ``SimulationData`` ctor
(main.cpp:15330-15387) and ``ArgumentParser`` precedence rules
(main.cpp:10120-10299): command line > config file > default.  ``+key``
append and ``#`` comments are supported by :func:`parse_args`.  Obstacle
specs arrive as one mini-config line per obstacle in ``factory_content``
(FactoryFileLineParser semantics, main.cpp:8947-8958).
"""

from __future__ import annotations

import dataclasses
import shlex
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class SimulationConfig:
    # -- domain / discretization (main.cpp:15331-15347) --
    bpdx: int = 1
    bpdy: int = 1
    bpdz: int = 1
    levelMax: int = 1
    levelStart: int = -1  # default levelMax-1, as in the reference
    Rtol: float = 5.0  # refinement tagging threshold
    Ctol: float = 0.1  # compression tagging threshold
    extent: float = 1.0
    block_size: int = 8
    bAdaptChiGradient: bool = True
    levelMaxVorticity: int = -1  # cap refinement away from bodies (def: levelMax)

    # -- boundary conditions (main.cpp:15378-15380) --
    BC_x: str = "periodic"
    BC_y: str = "periodic"
    BC_z: str = "periodic"

    # -- time stepping (main.cpp:15348-15356) --
    CFL: float = 0.1
    dt: float = 0.0  # fixed dt if > 0
    tend: float = 1.0
    nsteps: int = 0  # 0 = no step cap
    rampup: int = 100  # CFL log-ramp steps
    step_2nd_start: int = 2  # enable 2nd-order pressure after this step
    uMax_allowed: float = 10.0  # runaway-velocity abort
    # depth-2 pipelined stepping (new capability, no reference analogue):
    # the per-step QoI pack is fetched one step late so its device->host
    # transfer overlaps the next step's device work.  dt then derives from
    # max|u| one step older than the reference's policy (CFL slack absorbs
    # it); requires a single obstacle without PID/roll corrections.
    pipelined: bool = False
    # device-resident dt chain (round 4): in pipelined obstacle-free runs
    # the CFL dt is computed ON DEVICE from the previous step's max|u|
    # (exactly the non-pipelined one-step-lag policy, no staleness margin)
    # and never read back — the steady-state step issues zero blocking
    # transfers.  -1 = auto (on for TPU backends when eligible), 0 = off,
    # 1 = force on (tests).  Obstacle runs keep the host dt: fish midline
    # kinematics consume host time each step.
    dtDevice: int = -1
    # K-step scan megaloop (sim/megaloop.py): wrap K whole timesteps —
    # dt policy, fish midline, rasterization, rigid update, penalization,
    # projection, force probe — in one jitted lax.scan, emitting the QoI
    # as one (K, ROW) packed block.  0 = off (the per-step loop, seed
    # behavior); the CUP3D_SCAN_K env var overrides.  Requires pipelined
    # mode, free dt, a step-count stop, and either no obstacles or a
    # single frozen-gait StefanFish (megaloop eligibility in
    # sim/simulation.py).  QoI/log latency grows to K steps.
    scan_k: int = 0

    # -- fluid (main.cpp:15357-15363) --
    nu: float = 1e-3
    uinf: Tuple[float, float, float] = (0.0, 0.0, 0.0)
    lambda_penalization: float = 1e6
    DLM: float = 1.0  # if > 0: lambda = DLM/dt each step
    implicitDiffusion: bool = False
    implicitPenalization: bool = True

    # -- pressure solve (main.cpp:15364-15368) --
    poissonTol: float = 1e-6
    poissonTolRel: float = 1e-4
    # nullspace handling (ops/amr_ops.build_amr_poisson_solver): 0 none,
    # 1 pin-corner-row-to-mean, 2 mean projection, 3 Dirichlet pin.
    # Deliberate divergence: the reference defaults to 1
    # (main.cpp:15366); we default to 2 — identical physics up to the
    # nullspace constant, but the projection keeps the Krylov operator
    # uniform (no special row), which converges slightly faster here.
    bMeanConstraint: int = 2
    poissonSolver: str = "spectral"  # spectral (uniform) | iterative (AMR)

    # -- diffusion solve (main.cpp:15369-15371) --
    diffusionTol: float = 1e-6
    diffusionTolRel: float = 1e-4

    # -- forcing (main.cpp:15372-15377) --
    uMax_forced: float = 0.0
    bFixMassFlux: bool = False
    initCond: str = "zero"  # zero | taylorGreen | channel

    # -- obstacles --
    factory_content: str = ""
    factory: str = ""  # path to a factory file (one obstacle per line)

    # -- output / diagnostics (main.cpp:15381-15387) --
    freqDiagnostics: int = 0
    tdump: float = 0.0
    fdump: int = 0
    path4serialization: str = "./"
    saveFreq: int = 0
    dumpChi: bool = True
    dumpOmega: bool = False
    dumpVelocity: bool = False
    verbose: bool = True

    # -- numerics --
    dtype: str = "float32"

    def __post_init__(self):
        if self.levelStart < 0:
            self.levelStart = self.levelMax - 1
        if self.levelMaxVorticity < 0:
            self.levelMaxVorticity = self.levelMax

    def resolved_factory_content(self) -> str:
        """factory_content plus the lines of the ``factory`` file, if any
        (reference ObstacleFactory reads both, main.cpp:13247-13267)."""
        content = self.factory_content
        if self.factory:
            with open(self.factory) as f:
                lines = f.read()
            content = f"{content}\n{lines}" if content else lines
        return content

    @property
    def bc(self) -> Tuple[str, str, str]:
        return (self.BC_x, self.BC_y, self.BC_z)

    @property
    def extents(self) -> Tuple[float, float, float]:
        """Physical domain size per axis (largest bpd axis spans `extent`,
        matching _preprocessArguments, main.cpp:15388-15420)."""
        bpd = (self.bpdx, self.bpdy, self.bpdz)
        m = max(bpd)
        return tuple(self.extent * b / m for b in bpd)

    def uniform_shape(self, level: Optional[int] = None) -> Tuple[int, int, int]:
        """Cells per axis of the dense grid at `level` (default levelStart)."""
        lvl = self.levelStart if level is None else level
        s = self.block_size * (1 << lvl)
        return (self.bpdx * s, self.bpdy * s, self.bpdz * s)


# reference flag name -> dataclass field
_FLAG_ALIASES = {
    "extentx": "extent",  # run.sh spells the domain size -extentx
    "levelMax": "levelMax",
    "levelStart": "levelStart",
    "lambda": "lambda_penalization",
    "poissonTol": "poissonTol",
    "poissonTolRel": "poissonTolRel",
    "BC_x": "BC_x",
    "BC_y": "BC_y",
    "BC_z": "BC_z",
}


def _is_flag(tok: str) -> bool:
    """A token starts a flag if it begins with -/+ and is not a number
    (so negative numeric values parse as values, as in the reference)."""
    if not tok.startswith(("-", "+")) or len(tok) < 2:
        return False
    try:
        float(tok)
        return False
    except ValueError:
        return True


def parse_args(argv: List[str]) -> SimulationConfig:
    """Parse reference-style ``-key value...`` command lines.

    Reference CommandlineParser semantics (main.cpp:10181-10210):
    - consecutive non-flag tokens are space-joined into one value;
    - a valueless flag means boolean true;
    - the FIRST occurrence of ``-key`` wins, so
      ``parse_args(cli + config_file_tokens)`` gives the CLI priority;
    - ``+key`` appends (string-valued flags only, e.g. factory-content).
    Unknown flags raise, mirroring strict mode.
    """
    fields = {f.name: f for f in dataclasses.fields(SimulationConfig)}
    raw: dict = {}
    i = 0
    while i < len(argv):
        tok = argv[i]
        if not _is_flag(tok):
            raise ValueError(f"expected -key, got {tok!r}")
        append = tok.startswith("+")
        key = tok.lstrip("+-").replace("-", "_")
        key = _FLAG_ALIASES.get(key, key)
        if key not in fields:
            raise ValueError(f"unknown flag {tok!r}")
        i += 1
        vals = []
        while i < len(argv) and not _is_flag(argv[i]):
            vals.append(argv[i])
            i += 1
        value = " ".join(vals) if vals else "true"
        if append:
            if fields[key].type not in ("str", str):
                raise ValueError(f"'+' append is only valid for string flags: {tok!r}")
            # newline-join so '+factory-content' appends form separate
            # obstacle lines (parse_factory also splits on bare type tokens)
            raw[key] = f"{raw[key]}\n{value}" if key in raw else value
        elif key not in raw:
            raw[key] = value
    kwargs = {k: _coerce(fields[k], v) for k, v in raw.items()}
    return SimulationConfig(**kwargs)


def _coerce(f: dataclasses.Field, raw: str):
    t = f.type
    if t in ("int", int):
        return int(raw)
    if t in ("float", float):
        return float(raw)
    if t in ("bool", bool):
        return raw.lower() in ("1", "true", "yes")
    if "Tuple[float" in str(t):
        vals = [float(v) for v in raw.replace(",", " ").split()]
        return tuple(vals)
    return raw


def parse_config_file(text: str) -> List[str]:
    """Config-file lines -> argv tokens; '#' starts a comment
    (ArgumentParser file mode, main.cpp:10243-10287)."""
    argv: List[str] = []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if line:
            argv.extend(shlex.split(line))
    return argv


def parse_factory(content: str) -> List[dict]:
    """factory-content -> one {key: value} dict per obstacle
    (FactoryFileLineParser, main.cpp:8947-8958; ObstacleFactory
    main.cpp:13247-13289).

    Obstacles are separated by newlines; additionally any bare (non
    key=value) token starts a new obstacle, so space-joined multi-obstacle
    strings parse too.
    """
    out: List[dict] = []
    for line in content.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        for tok in shlex.split(line):
            if "=" in tok:
                if not out:
                    raise ValueError(f"factory token {tok!r} before obstacle type")
                k, v = tok.split("=", 1)
                out[-1][k] = v
            elif tok[0].isalpha():
                out.append({"type": tok})
            else:
                raise ValueError(
                    f"factory token {tok!r} is neither key=value nor an obstacle type"
                )
    return out
