"""CLI entry point: ``python -m cup3d_tpu -bpdx ... -factory-content ...``.

The reference's ``main()`` (main.cpp:15982-15994): parse flags, build the
driver, ``init()``, ``simulate()``.  Reference-style flag grammar
(``-key value...``, ``+key`` append, first occurrence wins) is
config.parse_args; ``-conf FILE`` pulls extra flags from a config file
with ``#`` comments (ArgumentParser file mode, main.cpp:10243-10287) at
lower precedence than the command line; ``-factory FILE`` appends obstacle
lines to ``-factory-content`` (ObstacleFactory, main.cpp:13247-13267).

Driver selection is capability-based: ``levelMax > 1`` runs the adaptive
block forest (AMRSimulation), ``levelMax == 1`` the dense uniform-grid
driver with the spectral or iterative Poisson solver per
``-poissonSolver``.  The parsed config is recorded to
``argumentparser.log`` (main.cpp:10226-10240).
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import List, Optional

from cup3d_tpu.config import parse_args, parse_config_file, parse_factory


def _expand_conf(argv: List[str]) -> List[str]:
    """Splice ``-conf FILE`` flags out, appending the file's tokens after
    the command line (CLI tokens keep precedence: first occurrence wins)."""
    out: List[str] = []
    tail: List[str] = []
    i = 0
    while i < len(argv):
        if argv[i] == "-conf":
            if i + 1 >= len(argv):
                raise ValueError("-conf needs a file path")
            with open(argv[i + 1]) as f:
                tail.extend(parse_config_file(f.read()))
            i += 2
        else:
            out.append(argv[i])
            i += 1
    return out + tail


def build_driver(argv: List[str]):
    cfg = parse_args(_expand_conf(argv))
    multi_obstacle = (
        len(parse_factory(cfg.resolved_factory_content() or "")) > 1
    )
    # capability-based: levelMax>1 needs the forest; pipelined
    # multi-obstacle runs also route to the forest driver (its vmapped
    # device megastep handles many bodies; the uniform driver's fast
    # path is single-obstacle) — at levelMax=1 the forest IS the
    # uniform grid, just block-laid-out
    if cfg.levelMax > 1 or (cfg.pipelined and multi_obstacle):
        from cup3d_tpu.sim.amr import AMRSimulation

        return AMRSimulation(cfg)
    from cup3d_tpu.sim.simulation import Simulation

    return Simulation(cfg)


def _log_config(driver) -> None:
    cfg = driver.cfg
    os.makedirs(cfg.path4serialization or ".", exist_ok=True)
    path = os.path.join(cfg.path4serialization, "argumentparser.log")
    with open(path, "w") as f:
        for field in dataclasses.fields(cfg):
            f.write(f"{field.name} {getattr(cfg, field.name)!r}\n")


def main(argv: Optional[List[str]] = None) -> None:
    args = sys.argv[1:] if argv is None else argv
    if args and args[0] == "fleet":
        # many-simulation serving mode: `python -m cup3d_tpu fleet
        # --scenarios spec.json` drains a multi-tenant scenario queue
        # (fleet/server.py) and prints the per-tenant summary JSON
        from cup3d_tpu.fleet.cli import main as fleet_main

        raise SystemExit(fleet_main(args[1:]))
    if args and args[0] == "aot":
        # persistent-executable-store operations: `python -m cup3d_tpu
        # aot warm|list|gc|verify|probe` (aot/cli.py) manage the
        # zero-cold-start store and measure boot-to-first-dispatch
        from cup3d_tpu.aot.cli import main as aot_main

        raise SystemExit(aot_main(args[1:]))
    driver = build_driver(args)
    _log_config(driver)
    driver.init()
    driver.simulate()


if __name__ == "__main__":
    main()
