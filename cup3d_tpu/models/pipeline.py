"""Obstacle operators in the timestep pipeline (reference order,
main.cpp:15229-15246): CreateObstacles -> ... -> UpdateObstacles ->
Penalization -> PressureProjection -> ComputeForces.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.models.base import (
    force_integrals,
    log_forces,
    momentum_integrals,
    pack_forces,
    pack_moments,
    rigid_update_device,
    store_force_qoi,
    unpack_forces,
    unpack_moments,
    update_penalization_forces,
    vel_unit,
    vel_unit_dev,
)
from cup3d_tpu.ops.penalization import (
    penalize,
    per_obstacle_penalization_force,
)
from cup3d_tpu.sim.data import SimulationData
from cup3d_tpu.sim.operators import Operator

_EPS = 1e-6


def _device_step(s) -> bool:
    """True when this step's rigid update ran on device (single obstacle,
    rigid_update_device): QoI join the step's single packed read."""
    return (
        len(s.obstacles) == 1
        and s.obstacles[0]._dev_rigid is not None
        and s.obstacles[0]._dev_rigid["step"] == s.step
    )


class CreateObstacles(Operator):
    """Shape kinematics -> SDF -> chi/udef, then combine obstacle fields
    (reference CreateObstacles, main.cpp:13589-13621)."""

    def __call__(self, dt):
        s = self.sim
        self._update_uinf()
        for ob in s.obstacles:
            ob.update_shape(s.time, dt)
            ob.create(s.time)
        chis = jnp.stack([ob.chi for ob in s.obstacles])
        s.state["chi"] = jnp.max(chis, axis=0)
        num = sum(ob.chi[..., None] * ob.udef for ob in s.obstacles)
        den = jnp.maximum(jnp.sum(chis, axis=0), _EPS)[..., None]
        s.state["udef"] = num / den

    def _update_uinf(self):
        """Frame-fixed swimming: uinf counteracts the tracked obstacle's
        translational velocity (ObstacleVector::updateUinf,
        main.cpp:8507-8519).  In pipelined mode the value stays device-
        resident (the host mirror trails one step, feeding only logs)."""
        s = self.sim
        fixed = [ob for ob in s.obstacles if ob.bFixFrameOfRef]
        if not fixed:
            return
        s.uinf = -np.mean([ob.transVel for ob in fixed], axis=0)
        devs = [ob._dev_rigid for ob in fixed]
        if s.cfg.pipelined and all(d is not None for d in devs):
            s._uinf_dev = -sum(d["trans"] for d in devs) / len(devs)


class UpdateObstacles(Operator):
    """chi-weighted fluid momenta -> 6x6 solve -> rigid-body update
    (reference UpdateObstacles, main.cpp:13812-13837).

    Single-obstacle fast path: when the update has no host-only branch
    (no collision latch, no roll correction) the whole chain — moments,
    6x6 solve, position/quaternion update — runs on device
    (rigid_update_device) and the result joins the step's single packed
    QoI read instead of blocking here (~75 ms/read on the tunneled TPU)."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        # ALL obstacles' moments in one (n_obs, 19) host read per step
        self._moments = jax.jit(
            lambda chis, vel, cms: jnp.stack(
                [
                    pack_moments(momentum_integrals(sim.grid, c, vel, cms[i]))
                    for i, c in enumerate(chis)
                ]
            )
        )
        self._rigid = jax.jit(rigid_update_device)

    def __call__(self, dt):
        s = self.sim

        def cm_of(ob):
            # pipelined chaining: the fresh CM lives on device; the host
            # mirror trails one step and would shift the moment reference
            d = ob._dev_rigid
            if d is not None:
                return d["cm"]
            return jnp.asarray(ob.centerOfMass, s.dtype)

        cms = jnp.stack([cm_of(ob) for ob in s.obstacles])
        M = self._moments(tuple(ob.chi for ob in s.obstacles),
                          s.state["vel"], cms)
        if len(s.obstacles) == 1 and s.obstacles[0].supports_device_update():
            ob = s.obstacles[0]
            out = self._rigid(
                M[0],
                ob.rigid_state_dev(s.dtype),
                # cached static mirrors (models/base.py): the flags are
                # construction-time constants — re-staging them with
                # jnp.asarray every step was pure host->device residue
                # (lint rule JX010)
                ob.forced_mask_dev(),
                ob.block_mask_dev(),
                s.uinf_device(),
                jnp.asarray(dt, s.dtype),
            )
            ob._dev_rigid = {"step": s.step, "trans": out[0:3],
                             "ang": out[3:6], "cm": out[12:15], "pack": out}
            ob._ubody_cache = None
            s.pending_parts.append(("rigid", out))
            return
        # host fallback: pipelined mode must never land here with a live
        # device chain — the host mirrors trail the chain and would feed a
        # stale state into compute_velocities (ADVICE r2)
        assert not s.cfg.pipelined or all(
            ob._dev_rigid is None for ob in s.obstacles
        ), "pipelined host fallback with live device rigid chains"
        M = np.asarray(M)
        for ob, row in zip(s.obstacles, M):
            ob.compute_velocities(unpack_moments(row))
            ob.update(dt)


class Penalization(Operator):
    """Collision handling then Brinkman forcing toward the combined body
    velocity field (reference Penalization, main.cpp:14326-14341:
    preventCollidingObstacles runs first, main.cpp:14330)."""

    def __init__(self, sim: SimulationData):
        super().__init__(sim)
        self._penalize = jax.jit(penalize)
        from cup3d_tpu.ops.chi import grad_chi

        self._gradchi = jax.jit(partial(grad_chi, sim.grid))
        self._xc = sim.xc  # device-cached centers (sim/data.py)
        h3 = sim.grid.h ** 3
        self._penal_force = jax.jit(
            lambda vn, vo, chis, dt, cms: per_obstacle_penalization_force(
                vn, vo, chis, dt, h3, sim.xc, cms
            )
        )

    def __call__(self, dt):
        s = self.sim
        if not s.obstacles:
            return
        ubs = [ob.body_velocity_field() for ob in s.obstacles]
        if len(s.obstacles) > 1:
            from cup3d_tpu.models.collisions import prevent_colliding_obstacles

            if prevent_colliding_obstacles(
                s.obstacles, ubs, self._gradchi, self._xc, float(dt)
            ):
                # collision overrode rigid velocities: rebuild the fields
                ubs = [ob.body_velocity_field() for ob in s.obstacles]
        chis = jnp.stack([ob.chi for ob in s.obstacles])
        num = sum(ob.chi[..., None] * ub for ob, ub in zip(s.obstacles, ubs))
        den = jnp.maximum(jnp.sum(chis, axis=0), _EPS)[..., None]
        ubody = num / den
        vel_old = s.state["vel"]
        dt_dev = jnp.asarray(dt, s.dtype)
        s.state["vel"] = self._penalize(
            # lambda rides the device (sim/data.lambda_device): DLM/dt
            # divides on device from the step's dt scalar instead of
            # re-staging a fresh host float every step (rule JX010)
            vel_old, s.state["chi"], ubody, s.lambda_device(dt_dev), dt_dev,
        )
        PF = update_penalization_forces(
            s.obstacles, self._penal_force, s.state["vel"], vel_old, dt,
            s.dtype,
        )
        if _device_step(s):
            s.pending_parts.append(("penal", PF.reshape(-1)))


class ComputeForces(Operator):
    """Per-obstacle force/torque/power QoI from the surface-point probe
    (ops/surface.py: one-sided tractions probed outside the body on a
    dense window, the reference KernelComputeForces measure), appended to
    forces_<i>.txt (reference ComputeForces, main.cpp:12496-12503,
    reduction 13079-13115).  The dense chi-band integral
    (models.base.force_integrals) stays available for diagnostics but the
    probe is the production measure — the band under-reads pressure by a
    flat ~28% on the sphere (VALIDATION.md)."""

    def __call__(self, dt):
        from cup3d_tpu.ops.surface import force_integrals_probe_uniform

        s = self.sim

        def probe(ob, cm, ut, om):
            return pack_forces(
                force_integrals_probe_uniform(
                    s.grid, ob, s.state["vel"], s.state["p"], ob.chi,
                    ob.sdf, ob.udef, s.nu, cm, ut, om,
                )
            )

        if _device_step(s):
            ob = s.obstacles[0]
            d = ob._dev_rigid
            F = probe(ob, d["cm"], d["trans"], d["ang"])
            s.pending_parts.append(("forces", F.reshape(-1)))
            return
        # host fallback: one batched (n_obs, 3, 3) kinematics upload per
        # step instead of three per obstacle (rule JX010); the mirrors
        # here are fresh host values by construction (no device chain)
        kin = jnp.asarray(
            np.stack(
                [
                    np.stack([ob.centerOfMass, ob.transVel, ob.angVel])
                    for ob in s.obstacles
                ]
            ),
            s.dtype,
        )
        F = np.asarray(
            jnp.stack(
                [
                    probe(ob, kin[i, 0], kin[i, 1], kin[i, 2])
                    for i, ob in enumerate(s.obstacles)
                ]
            )
        )
        for i, (ob, row) in enumerate(zip(s.obstacles, F)):
            store_force_qoi(ob, unpack_forces(row))
            log_forces(s.logger, i, s.time, ob)

